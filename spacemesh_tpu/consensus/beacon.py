"""Beacon: per-epoch shared randomness via proposals + weighted voting.

Mirrors the reference beacon protocol (reference beacon/beacon.go:854
runProposalPhase, :934 runConsensusPhase; grading in handlers.go; weak
coin beacon/weakcoin/weak_coin.go; weighted majority votes_calc.go;
fallback beacon.go:239 UpdateBeacon):

  1. PROPOSAL phase: VRF-threshold-eligible smeshers gossip a VRF proof;
     receivers grade arrivals — on time (valid) or slightly late
     (potentially valid).
  2. FIRST VOTING round: participants vote FOR their valid set and
     AGAINST their potentially-valid set, signed, weighted by ATX weight.
  3. FOLLOW-UP rounds (rounds_number): each round tallies the previous
     round's weighted votes per proposal; the next own vote is FOR when
     margin > +theta*W, AGAINST when < -theta*W, and the round's WEAK
     COIN (lowest VRF output's last bit among participants) when the
     margin is inside the theta band.
  4. The final FOR-set hashes to the 4-byte epoch beacon.

Rounds end at their wall-clock deadline or as soon as every active
weight has voted (early completion keeps tests and small nets fast; the
deadline bounds adversarial stalling).

Fallback (bootstrap value) happens ONLY on explicit timeout/empty result
and is recorded with a reason; a protocol-decided beacon is final while
fallbacks may be superseded by a synced majority (storage.misc source).

Genesis epochs 0 and 1 use hash(genesis_id || epoch), as the reference
does (bootstrap beacon).
"""

from __future__ import annotations

import asyncio
import dataclasses
import struct
import time

from ..core import codec
from ..core.codec import fixed, u8, u32, vec
from ..core.hashing import sum256
from ..core.signing import Domain, EdVerifier, VrfVerifier, vrf_output
from ..p2p.pubsub import (
    TOPIC_BEACON_FIRST,
    TOPIC_BEACON_FOLLOW,
    TOPIC_BEACON_PROPOSAL,
    TOPIC_BEACON_WEAK_COIN,
    PubSub,
)
from ..storage import misc as miscstore
from ..storage.db import Database
from ..utils.logging import get as get_logger
from ..core.fixedpoint import ONE as FIXED, frac_from_bytes
from .eligibility import Oracle

BEACON_SIZE = 4

log = get_logger("beacon")


def proposal_alpha(epoch: int) -> bytes:
    return b"BEACON" + struct.pack("<I", epoch)


def weak_coin_alpha(epoch: int, round_: int) -> bytes:
    return b"BWC" + struct.pack("<IH", epoch, round_)


def proposal_id(vrf_proof: bytes) -> bytes:
    return sum256(vrf_output(vrf_proof))


@codec.register
class BeaconProposal:
    epoch: int
    atx_id: bytes
    node_id: bytes
    vrf_proof: bytes

    FIELDS = [("epoch", u32), ("atx_id", fixed(32)), ("node_id", fixed(32)),
              ("vrf_proof", fixed(80))]


@codec.register
class FirstVotes:
    epoch: int
    valid: list[bytes]           # proposal ids graded on-time
    late: list[bytes]            # potentially valid (graded late)
    atx_id: bytes
    node_id: bytes
    signature: bytes

    FIELDS = [("epoch", u32), ("valid", vec(fixed(32), 1 << 10)),
              ("late", vec(fixed(32), 1 << 10)), ("atx_id", fixed(32)),
              ("node_id", fixed(32)), ("signature", fixed(64))]

    def signed_bytes(self) -> bytes:
        return dataclasses.replace(self, signature=bytes(64)).to_bytes()


@codec.register
class FollowVotes:
    epoch: int
    round: int
    votes_for: list[bytes]       # current FOR-set; everything else AGAINST
    atx_id: bytes
    node_id: bytes
    signature: bytes

    FIELDS = [("epoch", u32), ("round", u8),
              ("votes_for", vec(fixed(32), 1 << 10)), ("atx_id", fixed(32)),
              ("node_id", fixed(32)), ("signature", fixed(64))]

    def signed_bytes(self) -> bytes:
        return dataclasses.replace(self, signature=bytes(64)).to_bytes()


@codec.register
class WeakCoinMsg:
    epoch: int
    round: int
    atx_id: bytes
    node_id: bytes
    vrf_proof: bytes

    FIELDS = [("epoch", u32), ("round", u8), ("atx_id", fixed(32)),
              ("node_id", fixed(32)), ("vrf_proof", fixed(80))]


@dataclasses.dataclass
class _EpochState:
    started: float | None = None            # proposal phase start (local)
    # node_id -> (pid, grade) — grade 1 on-time, 0 potentially-valid
    proposals: dict = dataclasses.field(default_factory=dict)
    # node_id -> FirstVotes
    first_votes: dict = dataclasses.field(default_factory=dict)
    # round -> node_id -> FollowVotes
    follow_votes: dict = dataclasses.field(default_factory=dict)
    # round -> lowest weak-coin VRF output seen
    coin: dict = dataclasses.field(default_factory=dict)


class ProtocolDriver:
    def __init__(self, *, db: Database, oracle: Oracle, pubsub: PubSub,
                 genesis_id: bytes, verifier: EdVerifier | None = None,
                 proposal_duration: float = 1.0,
                 first_voting_round_duration: float = 2.0,
                 voting_round_duration: float = 1.0,
                 rounds_number: int = 4, grace_period: float = 0.5,
                 kappa: int = 40, theta: float = 0.25,
                 on_fallback_used=None, wall=time.time):
        self.db = db
        self.oracle = oracle
        self.pubsub = pubsub
        self.genesis_id = genesis_id
        self.verifier = verifier or EdVerifier(prefix=genesis_id)
        self.proposal_duration = proposal_duration
        self.first_duration = first_voting_round_duration
        self.round_duration = voting_round_duration
        self.rounds = max(rounds_number, 1)
        self.grace = grace_period
        self.kappa = kappa
        self.theta = theta
        self.on_fallback_used = on_fallback_used
        self.wall = wall
        self._states: dict[int, _EpochState] = {}
        self._ready: dict[int, asyncio.Event] = {}
        self._vrf = VrfVerifier()
        pubsub.register(TOPIC_BEACON_PROPOSAL, self._on_proposal)
        pubsub.register(TOPIC_BEACON_FIRST, self._on_first)
        pubsub.register(TOPIC_BEACON_FOLLOW, self._on_follow)
        pubsub.register(TOPIC_BEACON_WEAK_COIN, self._on_coin)

    # --- timing ------------------------------------------------------

    def protocol_duration(self) -> float:
        return (self.proposal_duration + self.first_duration
                + self.rounds * self.round_duration + self.grace)

    def _state(self, epoch: int) -> _EpochState:
        return self._states.setdefault(epoch, _EpochState())

    def _bootstrap(self, epoch: int) -> bytes:
        return sum256(self.genesis_id, struct.pack("<I", epoch))[:BEACON_SIZE]

    # --- public reads ------------------------------------------------

    async def get(self, epoch: int) -> bytes:
        """The beacon for ``epoch`` (blocks until decided or falls back
        after the full protocol window with a recorded reason)."""
        if epoch <= 1:
            return self._bootstrap(epoch)
        stored = miscstore.get_beacon(self.db, epoch)
        if stored is not None:
            return stored
        ev = self._ready.setdefault(epoch, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(),
                                   timeout=self.protocol_duration() + self.grace)
        except asyncio.TimeoutError:
            pass
        stored = miscstore.get_beacon(self.db, epoch)
        if stored is not None:
            return stored
        self._record_fallback(epoch, "timeout waiting for beacon protocol")
        return miscstore.get_beacon(self.db, epoch) or self._bootstrap(epoch)

    def get_now(self, epoch: int) -> bytes:
        if epoch <= 1:
            return self._bootstrap(epoch)
        stored = miscstore.get_beacon(self.db, epoch)
        return stored if stored is not None else self._bootstrap(epoch)

    def _record_fallback(self, epoch: int, reason: str) -> None:
        log.warning("epoch %d: beacon fallback (%s)", epoch, reason)
        if miscstore.get_beacon(self.db, epoch) is None:
            # GUESS, not FALLBACK: this is a locally-derived provisional
            # value, which the protocol (or any network adoption) may
            # overwrite
            miscstore.set_beacon(self.db, epoch, self._bootstrap(epoch),
                                 source=miscstore.BEACON_GUESS)
        if self.on_fallback_used:
            self.on_fallback_used(epoch, reason)
        self._ready.setdefault(epoch, asyncio.Event()).set()

    # --- gossip handlers ---------------------------------------------

    def _proposal_eligible(self, epoch: int, proof: bytes) -> bool:
        """VRF-threshold eligibility: expect ~kappa proposers per epoch
        (reference beacon proposal checker). Small nets pass trivially."""
        count = max(self.oracle.cache.epoch_count(epoch), 1)
        thresh = min(FIXED, FIXED * self.kappa // count)
        return frac_from_bytes(vrf_output(proof)) < thresh

    async def _on_proposal(self, peer: bytes, data: bytes) -> bool:
        try:
            msg = BeaconProposal.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        key = self.oracle.vrf_key(msg.epoch, msg.atx_id)
        if key is None or key != msg.node_id:
            return False
        if not self._vrf.verify(key, proposal_alpha(msg.epoch), msg.vrf_proof):
            return False
        if not self._proposal_eligible(msg.epoch, msg.vrf_proof):
            return False
        st = self._state(msg.epoch)
        now = self.wall()
        if st.started is None:
            grade = 1  # we haven't started the phase locally; be generous
        elif now <= st.started + self.proposal_duration + self.grace:
            grade = 1
        elif now <= st.started + 2 * (self.proposal_duration + self.grace):
            grade = 0
        else:
            return False  # far too late
        st.proposals.setdefault(msg.node_id,
                                (proposal_id(msg.vrf_proof), grade))
        return True

    def _vote_weight(self, epoch: int, atx_id: bytes,
                     node_id: bytes) -> int | None:
        info = self.oracle.cache.get(epoch, atx_id)
        if info is None or info.malicious or info.node_id != node_id:
            return None
        return info.weight

    async def _on_first(self, peer: bytes, data: bytes) -> bool:
        try:
            msg = FirstVotes.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        if self._vote_weight(msg.epoch, msg.atx_id, msg.node_id) is None:
            return False
        if not self.verifier.verify(Domain.BEACON_FIRST_MSG, msg.node_id,
                                    msg.signed_bytes(), msg.signature):
            return False
        self._state(msg.epoch).first_votes.setdefault(msg.node_id, msg)
        return True

    async def _on_follow(self, peer: bytes, data: bytes) -> bool:
        try:
            msg = FollowVotes.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        if msg.round < 1 or msg.round > self.rounds:
            return False
        if self._vote_weight(msg.epoch, msg.atx_id, msg.node_id) is None:
            return False
        if not self.verifier.verify(Domain.BEACON_FOLLOWUP_MSG, msg.node_id,
                                    msg.signed_bytes(), msg.signature):
            return False
        st = self._state(msg.epoch)
        st.follow_votes.setdefault(msg.round, {}).setdefault(msg.node_id, msg)
        return True

    async def _on_coin(self, peer: bytes, data: bytes) -> bool:
        try:
            msg = WeakCoinMsg.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        key = self.oracle.vrf_key(msg.epoch, msg.atx_id)
        if key is None or key != msg.node_id:
            return False
        if not self._vrf.verify(key, weak_coin_alpha(msg.epoch, msg.round),
                                msg.vrf_proof):
            return False
        out = vrf_output(msg.vrf_proof)
        st = self._state(msg.epoch)
        cur = st.coin.get(msg.round)
        if cur is None or out < cur:
            st.coin[msg.round] = out
        return True

    # --- the per-epoch protocol --------------------------------------

    async def _sleep_until(self, deadline: float,
                           done=None, tick: float = 0.02) -> None:
        """Wait for the wall-clock deadline, or early-complete when
        ``done()`` says every active weight has been heard."""
        while True:
            now = self.wall()
            if now >= deadline:
                return
            if done is not None and done():
                return
            await asyncio.sleep(min(tick, deadline - now))

    def _total_weight(self, epoch: int) -> int:
        return self.oracle.cache.epoch_weight(epoch)

    def _voted_weight(self, epoch: int, votes: dict) -> int:
        total = 0
        for node_id, msg in votes.items():
            w = self._vote_weight(epoch, msg.atx_id, node_id)
            if w:
                total += w
        return total

    async def run_epoch(self, epoch: int, signer, vrf_signer,
                        atx_id: bytes | None,
                        participants: list | None = None) -> bytes:
        """Run the full protocol for ``epoch``. Observers (atx_id=None)
        tally without voting and still converge on the majority value.
        Multi-identity nodes pass ``participants`` as a list of
        (signer, vrf_signer, atx_id) — every identity proposes and votes
        with its own weight (reference beacon iterates registered
        signers)."""
        if epoch <= 1:
            return self._bootstrap(epoch)
        stored = miscstore.get_beacon(self.db, epoch)
        if stored is not None:
            if (miscstore.beacon_source(self.db, epoch)
                    != miscstore.BEACON_GUESS):
                # final, or a NETWORK-adopted fallback (sync majority /
                # checkpoint / bootstrap file): a late joiner re-running
                # the protocol solo would overwrite the network's value
                # with a self-derived one and mark it final
                # (code-review r3)
                return stored
            # stored is OUR OWN timeout-guess (an early get() fell back
            # to the local bootstrap derivation): the protocol hasn't
            # actually run — run it and let the decided value overwrite
            # the provisional one (ADVICE r2)
        if participants is None:
            participants = ([(signer, vrf_signer, atx_id)]
                            if atx_id is not None else [])
        st = self._state(epoch)
        start = self.wall()
        if st.started is None:
            st.started = start
        total_w = self._total_weight(epoch)

        # --- phase 1: proposals ---
        for p_signer, p_vrf, p_atx in participants:
            proof = p_vrf.prove(proposal_alpha(epoch))
            if self._proposal_eligible(epoch, proof):
                msg = BeaconProposal(epoch=epoch, atx_id=p_atx,
                                     node_id=p_signer.node_id,
                                     vrf_proof=proof)
                await self.pubsub.publish(TOPIC_BEACON_PROPOSAL,
                                          msg.to_bytes())
        await self._sleep_until(start + self.proposal_duration)

        valid = sorted(p for p, g in st.proposals.values() if g == 1)
        late = sorted(p for p, g in st.proposals.values() if g == 0)

        # --- phase 2: first voting round ---
        for p_signer, _p_vrf, p_atx in participants:
            fv = FirstVotes(epoch=epoch, valid=valid, late=late,
                            atx_id=p_atx, node_id=p_signer.node_id,
                            signature=bytes(64))
            fv.signature = p_signer.sign(Domain.BEACON_FIRST_MSG,
                                         fv.signed_bytes())
            await self.pubsub.publish(TOPIC_BEACON_FIRST, fv.to_bytes())
        first_deadline = start + self.proposal_duration + self.first_duration
        await self._sleep_until(
            first_deadline,
            done=lambda: total_w > 0 and self._voted_weight(
                epoch, st.first_votes) >= total_w)

        # tally first votes: FOR valid, AGAINST late
        candidates: set[bytes] = set(valid) | set(late)
        margins: dict[bytes, int] = {}
        for node_id, msg in st.first_votes.items():
            w = self._vote_weight(epoch, msg.atx_id, node_id) or 0
            for p in msg.valid:
                candidates.add(p)
                margins[p] = margins.get(p, 0) + w
            for p in msg.late:
                candidates.add(p)
                margins[p] = margins.get(p, 0) - w

        # --- phase 3: follow-up rounds with weak coin ---
        theta_w = max(int(self.theta * total_w), 1)
        own: set[bytes] = {p for p in candidates if margins.get(p, 0) > 0}
        for rnd in range(1, self.rounds + 1):
            round_start = first_deadline + (rnd - 1) * self.round_duration
            for p_signer, p_vrf, p_atx in participants:
                # weak coin VRF for this round
                wc = WeakCoinMsg(epoch=epoch, round=rnd, atx_id=p_atx,
                                 node_id=p_signer.node_id,
                                 vrf_proof=p_vrf.prove(
                                     weak_coin_alpha(epoch, rnd)))
                await self.pubsub.publish(TOPIC_BEACON_WEAK_COIN,
                                          wc.to_bytes())
                fw = FollowVotes(epoch=epoch, round=rnd,
                                 votes_for=sorted(own), atx_id=p_atx,
                                 node_id=p_signer.node_id,
                                 signature=bytes(64))
                fw.signature = p_signer.sign(Domain.BEACON_FOLLOWUP_MSG,
                                             fw.signed_bytes())
                await self.pubsub.publish(TOPIC_BEACON_FOLLOW, fw.to_bytes())
            votes = st.follow_votes.setdefault(rnd, {})
            await self._sleep_until(
                round_start + self.round_duration,
                done=lambda v=votes: total_w > 0 and self._voted_weight(
                    epoch, v) >= total_w)
            # weighted tally of this round's votes
            margins = {}
            for node_id, msg in votes.items():
                w = self._vote_weight(epoch, msg.atx_id, node_id) or 0
                fset = set(msg.votes_for)
                for p in candidates:
                    margins[p] = margins.get(p, 0) + (w if p in fset else -w)
            coin_bit = bool(st.coin.get(rnd, b"\0")[-1] & 1)
            nxt: set[bytes] = set()
            for p in candidates:
                m = margins.get(p, 0)
                if m > theta_w:
                    nxt.add(p)
                elif m < -theta_w:
                    continue
                elif coin_bit:
                    # weak coin decides inside the theta band
                    nxt.add(p)
            own = nxt

        if own:
            beacon = sum256(*sorted(own))[:BEACON_SIZE]
            miscstore.set_beacon(self.db, epoch, beacon,
                                 source=miscstore.BEACON_PROTOCOL)
            log.info("epoch %d: beacon %s from %d proposals", epoch,
                     beacon.hex(), len(own))
            self._ready.setdefault(epoch, asyncio.Event()).set()
        else:
            self._record_fallback(epoch, "no proposals survived voting")
            beacon = miscstore.get_beacon(self.db, epoch) or \
                self._bootstrap(epoch)
        self._states.pop(epoch - 2, None)  # bounded memory
        return beacon

    def on_fallback(self, epoch: int, beacon: bytes) -> None:
        """Bootstrap/sync-provided beacon (reference beacon.go:239
        UpdateBeacon). A fallback value may supersede an earlier fallback
        (a later peer majority corrects a poisoned/raced first write) but
        never a protocol-decided beacon."""
        source = miscstore.beacon_source(self.db, epoch)
        if source == miscstore.BEACON_PROTOCOL:
            return
        miscstore.set_beacon(self.db, epoch, beacon,
                             source=miscstore.BEACON_FALLBACK)
        self._ready.setdefault(epoch, asyncio.Event()).set()
