"""Beacon: per-epoch shared randomness.

Mirrors the reference beacon's role (reference beacon/beacon.go: VRF
proposal phase, grading, voting rounds with a weak-coin tie break, a
weighted majority fixing a 4-byte beacon per epoch; fallback to bootstrap
values when the protocol cannot complete). M2 implements the proposal
phase + deterministic aggregation (lowest-k VRF proposals hashed); the
multi-round voting and weak coin land with M4 — the seam (`get`,
`run_epoch`, the gossip topic) is final.

Genesis epochs 0 and 1 use hash(genesis_id || epoch), as the reference
does (bootstrap beacon).
"""

from __future__ import annotations

import asyncio
import dataclasses
import struct

from ..core import codec
from ..core.codec import fixed, u32
from ..core.hashing import sum256
from ..core.signing import vrf_output, VrfVerifier
from ..p2p.pubsub import TOPIC_BEACON_PROPOSAL, PubSub
from ..storage import misc as miscstore
from ..storage.db import Database
from .eligibility import Oracle

BEACON_SIZE = 4
K_BEST = 8


def proposal_alpha(epoch: int) -> bytes:
    return b"BEACON" + struct.pack("<I", epoch)


@codec.register
class BeaconProposal:
    epoch: int
    atx_id: bytes
    node_id: bytes
    vrf_proof: bytes

    FIELDS = [("epoch", u32), ("atx_id", fixed(32)), ("node_id", fixed(32)),
              ("vrf_proof", fixed(80))]


class ProtocolDriver:
    def __init__(self, *, db: Database, oracle: Oracle, pubsub: PubSub,
                 genesis_id: bytes, proposal_duration: float = 1.0):
        self.db = db
        self.oracle = oracle
        self.pubsub = pubsub
        self.genesis_id = genesis_id
        self.proposal_duration = proposal_duration
        # epoch -> node_id -> vrf output (dedup: replayed/duplicate
        # deliveries must not change the lowest-K selection)
        self._proposals: dict[int, dict[bytes, bytes]] = {}
        self._ready: dict[int, asyncio.Event] = {}
        self._vrf = VrfVerifier()
        pubsub.register(TOPIC_BEACON_PROPOSAL, self._gossip)

    def _bootstrap(self, epoch: int) -> bytes:
        return sum256(self.genesis_id, struct.pack("<I", epoch))[:BEACON_SIZE]

    async def get(self, epoch: int) -> bytes:
        """The beacon for ``epoch`` (blocks until decided or bootstraps)."""
        if epoch <= 1:
            return self._bootstrap(epoch)
        stored = miscstore.get_beacon(self.db, epoch)
        if stored is not None:
            return stored
        ev = self._ready.setdefault(epoch, asyncio.Event())
        try:
            await asyncio.wait_for(ev.wait(), timeout=self.proposal_duration * 4)
        except asyncio.TimeoutError:
            pass
        stored = miscstore.get_beacon(self.db, epoch)
        return stored if stored is not None else self._bootstrap(epoch)

    def get_now(self, epoch: int) -> bytes:
        if epoch <= 1:
            return self._bootstrap(epoch)
        stored = miscstore.get_beacon(self.db, epoch)
        return stored if stored is not None else self._bootstrap(epoch)

    # --- gossip -----------------------------------------------------

    async def _gossip(self, peer: bytes, data: bytes) -> bool:
        try:
            msg = BeaconProposal.from_bytes(data)
        except (codec.DecodeError, ValueError):
            return False
        # proposer must hold an ATX targeting this epoch
        key = self.oracle.vrf_key(msg.epoch, msg.atx_id)
        if key is None:
            return False
        if not self._vrf.verify(key, proposal_alpha(msg.epoch), msg.vrf_proof):
            return False
        out = vrf_output(msg.vrf_proof)
        self._proposals.setdefault(msg.epoch, {}).setdefault(msg.node_id, out)
        return True

    # --- per-epoch run ----------------------------------------------

    async def run_epoch(self, epoch: int, signer, vrf_signer,
                        atx_id: bytes | None) -> bytes:
        """Participate in the protocol for ``epoch`` (call at the start of
        the last layers of epoch-1, i.e. before it begins; standalone calls
        it right at epoch start)."""
        if epoch <= 1:
            return self._bootstrap(epoch)
        if atx_id is not None:
            msg = BeaconProposal(epoch=epoch, atx_id=atx_id,
                                 node_id=signer.node_id,
                                 vrf_proof=vrf_signer.prove(proposal_alpha(epoch)))
            await self.pubsub.publish(TOPIC_BEACON_PROPOSAL, msg.to_bytes())
        await asyncio.sleep(self.proposal_duration)
        props = sorted(self._proposals.get(epoch, {}).values())[:K_BEST]
        if props:
            beacon = sum256(*props)[:BEACON_SIZE]
            source = miscstore.BEACON_PROTOCOL
        else:
            # saw no proposals: this is a local bootstrap, not a protocol
            # decision — leave it supersedable by a later synced majority
            beacon = self._bootstrap(epoch)
            source = miscstore.BEACON_FALLBACK
        miscstore.set_beacon(self.db, epoch, beacon, source=source)
        ev = self._ready.setdefault(epoch, asyncio.Event())
        ev.set()
        return beacon

    def on_fallback(self, epoch: int, beacon: bytes) -> None:
        """Bootstrap/sync-provided beacon (reference beacon.go:239
        UpdateBeacon). A fallback value may supersede an earlier fallback
        (a later peer majority corrects a poisoned/raced first write) but
        never a protocol-decided beacon."""
        source = miscstore.beacon_source(self.db, epoch)
        if source == miscstore.BEACON_PROTOCOL:
            return
        miscstore.set_beacon(self.db, epoch, beacon,
                             source=miscstore.BEACON_FALLBACK)
        self._ready.setdefault(epoch, asyncio.Event()).set()
