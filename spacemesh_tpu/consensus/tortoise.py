"""Tortoise: self-healing vote-counting finality.

Mirrors the reference tortoise's contract (reference tortoise/algorithm.go
public facade: OnAtx/OnBallot/OnBlock/OnBeacon/OnHareOutput/TallyVotes/
EncodeVotes/Updates/Results; verifying mode counts ballot opinions toward a
weight threshold, tortoise/verifying.go; opinions are encoded relative to a
base ballot with exception lists, tortoise/opinion; a JSON tracer records
every input for offline replay, tortoise/tracer.go).

This implementation materializes each ballot's full opinion (base chain
resolved at ingestion), keeps a sliding window of layers, and advances the
verified frontier when every block decision in a layer clears the margin
threshold — a faithful verifying tortoise. Full-mode recount (healing after
partitions) re-tallies from the materialized opinions, since they are kept
for the whole window.

Local opinion: within hdist of the tip, hare outputs are trusted
(reference tortoise counts them as the node's own opinion); beyond, only
accumulated ballot weight decides.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Optional

from ..core.types import Ballot, Opinion
from ..storage.cache import AtxCache

EMPTY = bytes(32)  # "layer is empty" sentinel in hare outputs

SUPPORT, AGAINST, ABSTAIN = 1, -1, 0


@dataclasses.dataclass
class BallotInfo:
    id: bytes
    layer: int
    weight: int
    # layer -> set of supported block ids (full, base-resolved)
    supports: dict[int, set[bytes]]
    abstains: set[int]
    malicious: bool = False


@dataclasses.dataclass
class Update:
    layer: int
    block_id: bytes       # EMPTY for "layer verified empty"
    valid: bool


class Tortoise:
    def __init__(self, cache: AtxCache, layers_per_epoch: int, hdist: int = 10,
                 window: int = 1000,
                 tracer: Optional[Callable[[str], None]] = None):
        self.cache = cache
        self.layers_per_epoch = layers_per_epoch
        self.hdist = hdist
        self.window = window
        self._trace = tracer
        self.verified = 0           # highest fully-decided layer
        self.processed = 0
        self._ballots: dict[bytes, BallotInfo] = {}
        self._ballots_by_layer: dict[int, list[bytes]] = {}
        self._blocks: dict[int, set[bytes]] = {}
        self._hare: dict[int, bytes] = {}
        self._validity: dict[bytes, bool] = {}
        self._updates: list[Update] = []
        self._epoch_weight: dict[int, int] = {}

    # --- tracing -------------------------------------------------------

    def _t(self, kind: str, **kw) -> None:
        if self._trace:
            enc = {k: (v.hex() if isinstance(v, bytes) else v)
                   for k, v in kw.items()}
            self._trace(json.dumps({"ev": kind, **enc}, sort_keys=True))

    # --- inputs --------------------------------------------------------

    def on_block(self, layer: int, block_id: bytes) -> None:
        self._t("block", layer=layer, id=block_id)
        self._blocks.setdefault(layer, set()).add(block_id)

    def on_hare_output(self, layer: int, block_id: bytes) -> None:
        self._t("hare", layer=layer, id=block_id)
        self._hare[layer] = block_id

    def on_malfeasance(self, node_id: bytes) -> None:
        self._t("malfeasance", id=node_id)
        self.cache.set_malicious(node_id)

    def on_ballot(self, ballot: Ballot, weight: int) -> None:
        """Resolve the ballot's opinion against its base and store it."""
        bid = ballot.id
        if bid in self._ballots:
            return
        self._t("ballot", layer=ballot.layer, id=bid, weight=weight,
                base=ballot.opinion.base)
        base = self._ballots.get(ballot.opinion.base)
        supports: dict[int, set[bytes]] = {}
        abstains: set[int] = set()
        if base is not None:
            supports = {lyr: set(s) for lyr, s in base.supports.items()}
            abstains = set(base.abstains)
        block_layers = {b: lyr for lyr, blocks in self._blocks.items()
                        for b in blocks}
        for b in ballot.opinion.support:
            lyr = block_layers.get(b)
            if lyr is not None:
                supports.setdefault(lyr, set()).add(b)
                abstains.discard(lyr)
        for b in ballot.opinion.against:
            lyr = block_layers.get(b)
            if lyr is not None and lyr in supports:
                supports[lyr].discard(b)
        for lyr in ballot.opinion.abstain:
            abstains.add(lyr)
            supports.pop(lyr, None)
        info = BallotInfo(
            id=bid, layer=ballot.layer, weight=weight, supports=supports,
            abstains=abstains,
            malicious=self.cache.is_malicious(ballot.node_id))
        self._ballots[bid] = info
        self._ballots_by_layer.setdefault(ballot.layer, []).append(bid)

    # --- counting ------------------------------------------------------

    def _threshold(self, target_layer: int, last: int) -> int:
        """Margin needed: a fraction of the ballot weight expected between
        the target and the tip (reference tortoise/threshold.go)."""
        epoch = target_layer // self.layers_per_epoch
        w = self.cache.epoch_weight(epoch)
        if w == 0:
            return 1
        span = max(last - target_layer, 1)
        per_layer = w // self.layers_per_epoch or 1
        return max(per_layer * min(span, self.window) // 3, 1)

    def _margin(self, target_layer: int, block_id: bytes, last: int) -> int:
        m = 0
        for lyr in range(target_layer + 1, last + 1):
            for bid in self._ballots_by_layer.get(lyr, ()):
                info = self._ballots[bid]
                if info.malicious:
                    continue
                if target_layer in info.abstains:
                    continue
                sup = info.supports.get(target_layer, set())
                m += info.weight if block_id in sup else -info.weight
        return m

    def tally_votes(self, last: int) -> None:
        """Advance the verified frontier up to ``last`` - 1."""
        self.processed = max(self.processed, last)
        self._t("tally", last=last)
        frontier = self.verified
        for layer in range(self.verified + 1, last):
            decided_all = True
            blocks = self._blocks.get(layer, set())
            t = self._threshold(layer, last)
            for b in sorted(blocks):
                margin = self._margin(layer, b, last)
                if margin > t:
                    decided = True
                elif margin < -t:
                    decided = False
                elif last - layer < self.hdist and layer in self._hare:
                    decided = self._hare[layer] == b
                else:
                    decided_all = False
                    continue
                if self._validity.get(b) != decided:
                    self._validity[b] = decided
                    self._updates.append(Update(layer, b, decided))
            if not blocks:
                # empty layer: decided by hare's "empty" or by abstain decay
                if layer in self._hare and self._hare[layer] == EMPTY:
                    pass
                elif last - layer < self.hdist:
                    decided_all = False
            if decided_all:
                frontier = layer
            else:
                break
        if frontier != self.verified:
            self.verified = frontier
            self._t("verified", layer=frontier)
        self._evict()

    def _evict(self) -> None:
        low = self.verified - self.window
        for store in (self._ballots_by_layer, self._blocks):
            for lyr in [x for x in store if x < low]:
                if store is self._ballots_by_layer:
                    for bid in store[lyr]:
                        self._ballots.pop(bid, None)
                del store[lyr]

    def updates(self) -> list[Update]:
        out, self._updates = self._updates, []
        return out

    def valid_blocks(self, layer: int) -> list[bytes]:
        return sorted(b for b in self._blocks.get(layer, set())
                      if self._validity.get(b))

    def is_valid(self, block_id: bytes) -> bool:
        return bool(self._validity.get(block_id))

    # --- vote encoding -------------------------------------------------

    def encode_votes(self, for_layer: int) -> Opinion:
        """Build the opinion for a new ballot in ``for_layer``: pick the
        newest known ballot as base, express the local opinion (hare within
        hdist, validity beyond) as exceptions (reference
        tortoise/algorithm.go:EncodeVotes)."""
        base_id = EMPTY
        base_info = None
        for lyr in sorted(self._ballots_by_layer, reverse=True):
            if lyr >= for_layer:
                continue
            cands = [self._ballots[b] for b in self._ballots_by_layer[lyr]
                     if not self._ballots[b].malicious]
            if cands:
                base_info = max(cands, key=lambda i: (i.weight, i.id))
                base_id = base_info.id
                break
        support, against, abstain = [], [], []
        start = max(1, for_layer - self.window)
        for lyr in range(start, for_layer):
            local: set[bytes] = set()
            if lyr in self._hare and self.processed - lyr < self.hdist:
                if self._hare[lyr] != EMPTY:
                    local = {self._hare[lyr]}
            else:
                local = {b for b in self._blocks.get(lyr, set())
                         if self._validity.get(b)}
                if not local and lyr > self.verified and lyr not in self._hare:
                    abstain.append(lyr)
                    continue
            base_sup = (base_info.supports.get(lyr, set())
                        if base_info else set())
            support += sorted(local - base_sup)
            against += sorted(base_sup - local)
        return Opinion(base=base_id, support=support, against=against,
                       abstain=abstain)
