"""Tortoise: self-healing vote-counting finality, as array ops.

Mirrors the reference tortoise's contract (reference tortoise/algorithm.go
public facade: OnAtx/OnBallot/OnBlock/OnBeacon/OnHareOutput/TallyVotes/
EncodeVotes/Updates/Results; verifying mode tortoise/verifying.go; full
mode healing recount tortoise/full.go; mode switching on threshold
crossing tortoise/tortoise.go:397; recovery from storage
tortoise/recover.go:20; JSON tracer for offline replay tortoise/tracer.go).

The vote state is a dense int8 matrix V[ballots, blocks] over the active
window — +1 support, -1 against (the default for any block the ballot's
chain covers), 0 abstain/not-covered — plus a weight vector. A layer's
margins are then one masked mat-vec:

    margins = (weights * (ballot_layer > L)) @ V[:, cols(L)]

which is the "turn vote counting into array ops" design SURVEY.md §7
prescribes (the reference walks ballot graphs in Go; this formulation
lets numpy/XLA tile the count — BenchmarkTallyVotes territory).

Decision rule per block (reference semantics):
  margin >  global threshold     -> valid      (verifying mode)
  margin < -global threshold     -> invalid
  within hdist and hare decided  -> hare's opinion   (hare trust)
  older than hdist+zdist         -> full/healing mode:
      |margin| > local threshold -> sign of margin (tortoise/full.go)
      else                       -> weak coin of the latest layer
                                    (tortoise/tortoise.go:287-306
                                    getFullVote reasonCoinflip)
  otherwise                      -> undecided (frontier stops)

Thresholds (reference tortoise/threshold.go): the LOCAL threshold is
one layer's expected weight / 3 (localThresholdFraction); the GLOBAL
threshold is the expected weight in (target, last] / 3
(adversarialWeightFraction) + local.

Ballots whose beacon mismatches the epoch beacon vote at zero weight
until ``bad_beacon_delay`` layers have passed (reference
tortoise/tortoise.go:198 checkBallotAndVotes + BadBeaconVoteDelayLayers,
algorithm config) — a grinding adversary can't steer margins with
wrong-beacon ballots inside the confidence window.

Support votes for blocks not yet known are kept PENDING and resolved when
the block arrives (round-1 advisor fix: they must not silently count as
against while sync delivers data out of order).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Callable, Optional

import numpy as np

from ..core.types import Ballot, Opinion
from ..storage.cache import AtxCache

EMPTY = bytes(32)  # "layer is empty" sentinel in hare outputs

VERIFYING, FULL = "verifying", "full"


@dataclasses.dataclass
class BallotInfo:
    id: bytes
    layer: int
    weight: int
    node_id: bytes
    # layer -> set of supported block ids (full, base-resolved)
    supports: dict[int, set[bytes]]
    abstains: set[int]
    malicious: bool = False
    bad_beacon: bool = False


@dataclasses.dataclass
class Update:
    layer: int
    block_id: bytes       # EMPTY for "layer verified empty"
    valid: bool


class Tortoise:
    def __init__(self, cache: AtxCache, layers_per_epoch: int, hdist: int = 10,
                 window: int = 1000, zdist: int = 8,
                 bad_beacon_delay: int | None = None,
                 tracer: Optional[Callable[[str], None]] = None):
        self.cache = cache
        self.layers_per_epoch = layers_per_epoch
        self.hdist = hdist
        self.zdist = zdist
        # reference BadBeaconVoteDelayLayers (tortoise config): how long
        # wrong-beacon ballots stay muted; defaults to zdist
        self.bad_beacon_delay = zdist if bad_beacon_delay is None \
            else bad_beacon_delay
        self.window = window
        self._trace = tracer
        self.verified = 0           # highest fully-decided layer
        self.processed = 0
        self.mode = VERIFYING
        # --- array state (the vote matrix) ---
        self._V = np.zeros((256, 256), np.int8)
        self._weights = np.zeros(256, np.int64)
        self._bad_beacon_row = np.zeros(256, bool)
        self._row_layer = np.zeros(256, np.int32)
        self._col_layer = np.zeros(256, np.int32)
        self._rows = 0
        self._cols = 0
        self._abstain: dict[int, np.ndarray] = {}      # layer -> bool[rowcap]
        self._col_of: dict[bytes, int] = {}            # block id -> col
        self._col_block: list[bytes] = []              # col -> block id
        self._layer_cols: dict[int, list[int]] = {}    # layer -> cols
        self._row_ballot: list[bytes] = []             # row -> ballot id
        self._ballot_row: dict[bytes, int] = {}
        self._node_rows: dict[bytes, list[int]] = {}
        self._pending: dict[bytes, set[bytes]] = {}    # block id -> ballots
        # ballots whose BASE ballot hasn't arrived yet: ingesting them
        # now would lose the base chain's inherited support and count it
        # as against (the reference decodes votes against the base and
        # errors on a missing one, tortoise/state.go decodeVotes) —
        # queue until the base shows up
        self._pending_base: dict[bytes, list[tuple]] = {}
        # --- object state ---
        self._ballots: dict[bytes, BallotInfo] = {}
        self._ballots_by_layer: dict[int, list[bytes]] = {}
        # layers at/below the verified frontier touched by LATE evidence
        # (a block or ballot votes arriving after verification — fork
        # healing): tally must re-examine them, the reference emits
        # validity updates below verified and the mesh reverts
        # (tortoise results/mesh.go:302)
        self._dirty: int | None = None
        self._blocks: dict[int, set[bytes]] = {}
        self._hare: dict[int, bytes] = {}
        self._coin: dict[int, bool] = {}   # layer -> weak coin
        self._validity: dict[bytes, bool] = {}
        self._updates: list[Update] = []
        self._t("init", lpe=layers_per_epoch, hdist=hdist, zdist=zdist,
                window=window)

    # --- tracing -------------------------------------------------------

    def _t(self, kind: str, **kw) -> None:
        if self._trace:
            enc = {k: (v.hex() if isinstance(v, bytes) else v)
                   for k, v in kw.items()}
            self._trace(json.dumps({"ev": kind, **enc}, sort_keys=True))

    # --- array plumbing ------------------------------------------------

    def _grow_rows(self) -> None:
        cap = self._V.shape[0] * 2
        self._V = np.vstack([self._V, np.zeros_like(self._V)])
        self._weights = np.resize(self._weights, cap)
        self._weights[self._rows:] = 0
        self._bad_beacon_row = np.resize(self._bad_beacon_row, cap)
        self._bad_beacon_row[self._rows:] = False
        self._row_layer = np.resize(self._row_layer, cap)
        self._row_layer[self._rows:] = 0
        for lyr, arr in self._abstain.items():
            new = np.zeros(cap, bool)
            new[:len(arr)] = arr
            self._abstain[lyr] = new

    def _grow_cols(self) -> None:
        cap = self._V.shape[1] * 2
        self._V = np.hstack([self._V, np.zeros_like(self._V)])
        self._col_layer = np.resize(self._col_layer, cap)
        self._col_layer[self._cols:] = 0

    def _abstain_arr(self, layer: int) -> np.ndarray:
        arr = self._abstain.get(layer)
        if arr is None:
            arr = np.zeros(self._V.shape[0], bool)
            self._abstain[layer] = arr
        return arr

    # --- inputs --------------------------------------------------------

    def _mark_dirty(self, layer: int) -> None:
        if layer <= self.verified:
            self._dirty = layer if self._dirty is None \
                else min(self._dirty, layer)

    def on_block(self, layer: int, block_id: bytes) -> None:
        if block_id in self._col_of:
            return
        self._t("block", layer=layer, id=block_id)
        self._mark_dirty(layer)
        self._blocks.setdefault(layer, set()).add(block_id)
        if self._cols == self._V.shape[1]:
            self._grow_cols()
        col = self._cols
        self._cols += 1
        self._col_of[block_id] = col
        self._col_block.append(block_id)
        self._col_layer[col] = layer
        self._layer_cols.setdefault(layer, []).append(col)
        # existing ballots vote against by default where their chain covers
        # this layer, except where they abstain
        n = self._rows
        if n:
            covered = self._row_layer[:n] > layer
            ab = self._abstain.get(layer)
            if ab is not None:
                covered = covered & ~ab[:n]
            self._V[:n, col] = np.where(covered, -1, 0).astype(np.int8)
        # resolve pending support votes now that the block's layer is known
        for bid in self._pending.pop(block_id, ()):
            info = self._ballots.get(bid)
            row = self._ballot_row.get(bid)
            if info is None or row is None:
                continue
            if info.layer > layer and layer not in info.abstains:
                # clone-on-write: the layer set may be shared with the
                # base chain (see _ingest_one)
                info.supports[layer] = \
                    set(info.supports.get(layer, ())) | {block_id}
                self._V[row, col] = 1

    def on_hare_output(self, layer: int, block_id: bytes) -> None:
        self._t("hare", layer=layer, id=block_id)
        self._hare[layer] = block_id

    def on_weak_coin(self, layer: int, coin: bool) -> None:
        """Per-layer weak coin from hare's preround VRFs (reference
        tortoise/tortoise.go:303 layer.coinflip; the coin of the LATEST
        layer breaks zero-margin ties during healing)."""
        self._t("coin", layer=layer, coin=coin)
        self._coin[layer] = coin

    def on_malfeasance(self, node_id: bytes) -> None:
        self._t("malfeasance", id=node_id)
        self.cache.set_malicious(node_id)
        for row in self._node_rows.get(node_id, ()):
            self._weights[row] = 0
        had_ballots = False
        for info in self._ballots.values():
            if info.node_id == node_id:
                info.malicious = True
                had_ballots = True
        if had_ballots:
            # the zeroed weight may have been load-bearing anywhere below
            # the frontier (against-votes are implicit, so per-target
            # marking would under-mark): full re-tally of the retained
            # window on the next pass. Malfeasance is rare; the tally is
            # one vectorized mat-vec per layer (reference re-validates
            # on malfeasance too)
            self._mark_dirty(max(self.verified - self.window, 0))

    def on_ballot(self, ballot: Ballot, weight: int,
                  bad_beacon: bool = False) -> None:
        """Resolve the ballot's opinion against its base and store it."""
        self._ingest(ballot.id, ballot.layer, ballot.node_id,
                     ballot.opinion, weight, bad_beacon=bad_beacon)

    def _ingest(self, bid: bytes, layer: int, node_id: bytes,
                opinion: Opinion, weight: int,
                bad_beacon: bool = False) -> None:
        if not self._ingest_one(bid, layer, node_id, opinion, weight,
                                bad_beacon):
            return
        # resolve ballots that were waiting for an ingested ballot as
        # their base — ITERATIVE worklist, one stack frame total: a
        # reverse-ordered chain as long as the queue cap must not
        # recurse (code-review r3: per-link recursion hit Python's
        # limit on ~1000-deep backfills)
        work = self._pending_base.pop(bid, [])
        while work:
            args = work.pop()
            if self._ingest_one(*args[:5], bad_beacon=args[5]):
                winfo = self._ballots.get(args[0])
                if winfo is not None and args[1] > self.verified:
                    # a resolved waiter's whole inherited opinion is new
                    # weight on old layers: late-mark it all
                    for lyr in winfo.supports:
                        self._mark_dirty(lyr)
                    for lyr in winfo.abstains:
                        self._mark_dirty(lyr)
                work.extend(self._pending_base.pop(args[0], []))

    def _ingest_one(self, bid: bytes, layer: int, node_id: bytes,
                    opinion: Opinion, weight: int,
                    bad_beacon: bool = False) -> bool:
        """Ingest ONE ballot; True if it landed (False: duplicate or
        queued behind an unknown base)."""
        if bid in self._ballots:
            return False
        if opinion.base != EMPTY and opinion.base not in self._ballots:
            # base not here yet (sync/gossip reordering): queue — capped
            # so unknown-base spam can't grow memory
            waiters = self._pending_base.setdefault(opinion.base, [])
            if len(waiters) < 64 and len(self._pending_base) < 4096:
                waiters.append((bid, layer, node_id, opinion, weight,
                                bad_beacon))
            return False
        self._t("ballot", id=bid, layer=layer, node=node_id,
                weight=weight, base=opinion.base, bad=bad_beacon,
                support=[b.hex() for b in opinion.support],
                against=[b.hex() for b in opinion.against],
                abstain=list(opinion.abstain))
        base = self._ballots.get(opinion.base)
        supports: dict[int, set[bytes]] = {}
        abstains: set[int] = set()
        if base is not None:
            # copy-on-write: the dict is shallow-copied, the per-layer
            # SETS are shared with the base chain until first mutation
            # (_own below / on_block pending resolution). A deep copy
            # here is O(window) per ballot — at mainnet shape (50
            # ballots/layer, 1000-layer window) that alone dominated the
            # whole tally (docs/TORTOISE_STRESS.md).
            supports = dict(base.supports)
            abstains = set(base.abstains)
        owned: set[int] = set()

        def _own(lyr: int) -> set:
            if lyr not in owned:
                supports[lyr] = set(supports.get(lyr, ()))
                owned.add(lyr)
            return supports[lyr]
        pend: list[bytes] = []
        against = set(opinion.against)
        # pending votes INHERIT through the base chain: if the base ballot
        # is still waiting on a block, this ballot's opinion includes that
        # support too (exception lists are deltas) — unless it explicitly
        # votes against it
        if base is not None:
            for blk, waiters in self._pending.items():
                if opinion.base in waiters and blk not in against:
                    pend.append(blk)
        for b in opinion.support:
            col = self._col_of.get(b)
            if col is not None:
                lyr = int(self._col_layer[col])
                _own(lyr).add(b)
                abstains.discard(lyr)
            else:
                pend.append(b)
        for b in against:
            col = self._col_of.get(b)
            if col is not None:
                lyr = int(self._col_layer[col])
                if lyr in supports:
                    _own(lyr).discard(b)
        for lyr in opinion.abstain:
            abstains.add(lyr)
            supports.pop(lyr, None)
        malicious = self.cache.is_malicious(node_id)
        info = BallotInfo(id=bid, layer=layer, weight=weight,
                          node_id=node_id, supports=supports,
                          abstains=abstains, malicious=malicious,
                          bad_beacon=bad_beacon)
        self._ballots[bid] = info
        self._ballots_by_layer.setdefault(layer, []).append(bid)

        # --- matrix row ---
        if self._rows == self._V.shape[0]:
            self._grow_rows()
        row = self._rows
        self._rows += 1
        self._row_ballot.append(bid)
        self._ballot_row[bid] = row
        self._node_rows.setdefault(node_id, []).append(row)
        self._weights[row] = 0 if malicious else weight
        self._bad_beacon_row[row] = bad_beacon
        self._row_layer[row] = layer
        c = self._cols
        if c:
            self._V[row, :c] = np.where(self._col_layer[:c] < layer,
                                        -1, 0).astype(np.int8)
        for lyr in abstains:
            self._abstain_arr(lyr)[row] = True
            cols = self._layer_cols.get(lyr)
            if cols:
                self._V[row, cols] = 0
        for lyr, blocks in supports.items():
            for b in blocks:
                col = self._col_of.get(b)
                if col is not None:
                    self._V[row, col] = 1
        for b in pend:
            self._pending.setdefault(b, set()).add(bid)
        # late votes on already-verified layers force a re-tally there.
        # A ballot arriving through the NORMAL flow only changes old
        # margins via its explicit exception lists (inherited supports
        # repeat its base's already-counted direction), so only the
        # deltas are dirty-marked; a LATE ballot (backfilled below the
        # frontier, or resolved from the unknown-base queue) contributes
        # its whole inherited opinion as new weight — mark it all
        # (code-review r3: marking inherited supports unconditionally
        # made every tally rescan the full window)
        if layer <= self.verified:
            for lyr in supports:
                self._mark_dirty(lyr)
            for lyr in abstains:
                self._mark_dirty(lyr)
        else:
            for b in opinion.support + opinion.against:
                col = self._col_of.get(b)
                if col is not None:
                    self._mark_dirty(int(self._col_layer[col]))
            for lyr in opinion.abstain:
                self._mark_dirty(lyr)
        return True

    # --- counting ------------------------------------------------------

    def _local_threshold(self, last: int) -> int:
        """One layer's expected weight / 3 (reference
        tortoise/threshold.go localThresholdFraction;
        tortoise.go:311-316 updateLast recomputes it per epoch)."""
        w = self.cache.epoch_weight(last // self.layers_per_epoch)
        if w == 0:
            return 1
        return max(w // self.layers_per_epoch // 3, 1)

    def _threshold(self, target_layer: int, last: int) -> int:
        """GLOBAL threshold: expected ballot weight in (target, last] / 3
        (adversarialWeightFraction) + the local threshold (reference
        tortoise/threshold.go computeGlobalThreshold; the window caps the
        span like computeExpectedWeightInWindow). Summed per EPOCH, not
        per layer — O(epochs-in-span) (code-review r3: a per-layer loop
        made catch-up tallies O(layers*window))."""
        span = min(max(last - target_layer, 1), self.window)
        lpe = self.layers_per_epoch
        lo, hi = target_layer + 1, target_layer + span  # inclusive range
        total = 0
        for epoch in range(lo // lpe, hi // lpe + 1):
            n_layers = (min(hi, (epoch + 1) * lpe - 1)
                        - max(lo, epoch * lpe) + 1)
            if n_layers > 0:
                total += self.cache.epoch_weight(epoch) // lpe * n_layers
        if total == 0:
            return 1
        return max(total // 3, 1) + self._local_threshold(last)

    def _margins(self, layer: int, last: int) -> tuple[list[bytes], np.ndarray]:
        """Margins for every block in ``layer``: one masked mat-vec."""
        cols = self._layer_cols.get(layer, [])
        if not cols:
            return [], np.zeros(0, np.int64)
        n = self._rows
        active = (self._row_layer[:n] > layer) & (self._row_layer[:n] <= last)
        # wrong-beacon ballots stay muted until bad_beacon_delay layers
        # past their own layer (reference BadBeaconVoteDelayLayers)
        muted = self._bad_beacon_row[:n] & \
            (last - self._row_layer[:n] <= self.bad_beacon_delay)
        w = np.where(active & ~muted, self._weights[:n], 0)
        margins = w @ self._V[:n, cols].astype(np.int64)
        return [self._col_block[c] for c in cols], margins

    def tally_votes(self, last: int) -> None:
        """Advance the verified frontier up to ``last`` - 1; re-examine
        verified layers marked dirty by late evidence (fork healing)."""
        self.processed = max(self.processed, last)
        self._t("tally", last=last)
        old_verified = self.verified
        start = old_verified + 1
        if self._dirty is not None:
            start = min(start, self._dirty)
            self._dirty = None
        frontier = start - 1
        flipped_below = False  # validity changed at/below old verified
        healed = False
        for layer in range(start, last):
            decided_all = True
            t = self._threshold(layer, last)
            heal = last - layer > self.hdist + self.zdist
            blocks, margins = self._margins(layer, last)
            for b, margin in zip(blocks, margins):
                margin = int(margin)
                if margin > t:
                    decided = True
                elif margin < -t:
                    decided = False
                elif last - layer < self.hdist and layer in self._hare:
                    decided = self._hare[layer] == b
                elif heal:
                    # full-mode healing: past the confidence window the
                    # count decides (tortoise/full.go); a margin inside
                    # the local threshold is a genuine tie — break it
                    # with the weak coin of the LATEST layer so every
                    # node falls on the same side (tortoise.go:287-306
                    # getFullVote reasonCoinflip)
                    lt = self._local_threshold(last)
                    if margin > lt:
                        decided = True
                    elif margin < -lt:
                        decided = False
                    else:
                        # latest recorded coin at-or-before last-1: in a
                        # quiescent net (no hare running) the newest
                        # shared coin still converges all nodes, where
                        # strict last-1 would deadlock the frontier
                        coin = self._coin.get(last - 1)
                        if coin is None and self._coin:
                            past = [x for x in self._coin if x <= last - 1]
                            if past:
                                coin = self._coin[max(past)]
                        if coin is None:
                            decided_all = False
                            continue
                        decided = coin
                    healed = True
                else:
                    decided_all = False
                    continue
                if self._validity.get(b) != decided:
                    self._validity[b] = decided
                    self._updates.append(Update(layer, b, decided))
                    if layer <= self.verified:
                        flipped_below = True
            if not blocks:
                # empty layer: decided by hare's "empty", by distance, or
                # by healing
                if layer in self._hare and self._hare[layer] == EMPTY:
                    pass
                elif last - layer < self.hdist:
                    decided_all = False
            if decided_all:
                frontier = layer
            else:
                if layer <= old_verified:
                    # dirty re-tally stopped short of the old frontier:
                    # keep the remaining region marked or the late
                    # evidence above this layer is silently forgotten
                    # (code-review r3)
                    self._dirty = layer
                break
        if healed and self.mode != FULL:
            self.mode = FULL
            self._t("mode", mode=FULL)
        elif not healed and self.mode != VERIFYING and last - frontier <= self.hdist:
            self.mode = VERIFYING
            self._t("mode", mode=VERIFYING)
        if frontier > self.verified or (frontier < self.verified
                                        and flipped_below):
            # regression is real only when a validity actually flipped
            # in the re-examined region; a dirty re-tally that merely
            # found an old layer momentarily undecidable (e.g. no coin
            # recorded yet) must not drag the frontier back
            # (code-review r3)
            self.verified = frontier
            self._t("verified", layer=frontier)
        self._evict()

    # --- eviction / compaction ----------------------------------------

    def _evict(self) -> None:
        low = self.verified - self.window
        stale_layers = [x for x in self._ballots_by_layer if x < low]
        stale_blocks = [x for x in self._blocks if x < low]
        if not stale_layers and not stale_blocks:
            return
        # hysteresis: compaction rebuilds the whole matrix (O(rows*cols));
        # once the frontier advances one layer per tally, evicting eagerly
        # would pay that rebuild EVERY tally. Let a chunk of stale layers
        # accumulate so the cost amortizes to O(rebuild / chunk) per layer
        # (the steady-state tally regression docs/TORTOISE_STRESS.md
        # caught: 2.3ms -> 280ms/layer at mainnet shape without this).
        chunk = max(self.window // 10, 16)
        if (len(stale_layers) < chunk and len(stale_blocks) < chunk):
            return
        for lyr in stale_layers:
            for bid in self._ballots_by_layer[lyr]:
                self._ballots.pop(bid, None)
                self._ballot_row.pop(bid, None)
            del self._ballots_by_layer[lyr]  # _compact rebuilds _node_rows
        for lyr in stale_blocks:
            del self._blocks[lyr]
        self._compact(low)

    def _compact(self, low: int) -> None:
        """Rebuild the matrix keeping only rows/cols inside the window."""
        keep_rows = [r for r in range(self._rows)
                     if int(self._row_layer[r]) >= low
                     and self._row_ballot[r] in self._ballots]
        keep_cols = [c for c in range(self._cols)
                     if int(self._col_layer[c]) >= low]
        V = np.zeros_like(self._V)
        V[:len(keep_rows), :len(keep_cols)] = \
            self._V[np.ix_(keep_rows, keep_cols)]
        self._V = V
        self._weights[:len(keep_rows)] = self._weights[keep_rows]
        self._weights[len(keep_rows):] = 0
        self._bad_beacon_row[:len(keep_rows)] = \
            self._bad_beacon_row[keep_rows]
        self._bad_beacon_row[len(keep_rows):] = False
        self._row_layer[:len(keep_rows)] = self._row_layer[keep_rows]
        self._row_layer[len(keep_rows):] = 0
        self._col_layer[:len(keep_cols)] = self._col_layer[keep_cols]
        self._col_layer[len(keep_cols):] = 0
        self._row_ballot = [self._row_ballot[r] for r in keep_rows]
        self._col_block = [self._col_block[c] for c in keep_cols]
        self._ballot_row = {b: i for i, b in enumerate(self._row_ballot)}
        self._col_of = {b: i for i, b in enumerate(self._col_block)}
        self._rows = len(keep_rows)
        self._cols = len(keep_cols)
        self._layer_cols = {}
        for c, b in enumerate(self._col_block):
            self._layer_cols.setdefault(int(self._col_layer[c]), []).append(c)
        self._node_rows = {}
        for i, bid in enumerate(self._row_ballot):
            info = self._ballots.get(bid)
            if info is not None:
                self._node_rows.setdefault(info.node_id, []).append(i)
        for lyr in [x for x in self._abstain if x < low]:
            del self._abstain[lyr]
        for lyr in [x for x in self._coin if x < low]:
            del self._coin[lyr]
        # hare opinions and per-block validity below the window can never
        # be consulted again (margins/encode_votes only span the window;
        # the mesh persists validity to storage) — without eviction these
        # grow without bound over a node's lifetime
        for lyr in [x for x in self._hare if x < low]:
            del self._hare[lyr]
        live_cols = set(self._col_of)
        self._validity = {b: v for b, v in self._validity.items()
                          if b in live_cols}
        # pending votes whose waiters were all evicted can never resolve
        self._pending = {blk: live for blk, ws in self._pending.items()
                         if (live := {b for b in ws if b in self._ballots})}
        # queued unknown-base ballots older than the window are dead
        self._pending_base = {
            base: live for base, ws in self._pending_base.items()
            if (live := [w for w in ws if w[1] >= low])}
        for lyr, arr in list(self._abstain.items()):
            new = np.zeros(self._V.shape[0], bool)
            for i, r in enumerate(keep_rows):
                new[i] = arr[r] if r < len(arr) else False
            self._abstain[lyr] = new

    # --- outputs -------------------------------------------------------

    def updates(self) -> list[Update]:
        out, self._updates = self._updates, []
        return out

    def valid_blocks(self, layer: int) -> list[bytes]:
        return sorted(b for b in self._blocks.get(layer, set())
                      if self._validity.get(b))

    def hare_of(self, layer: int) -> bytes | None:
        """The recorded hare output (or adopted certificate) for the
        layer; EMPTY means hare decided empty, None means undecided."""
        return self._hare.get(layer)

    def is_valid(self, block_id: bytes) -> bool:
        return bool(self._validity.get(block_id))

    def verdict(self, block_id: bytes) -> bool | None:
        """True/False once the tortoise decided; None while undecided —
        callers that treat hare output as provisional need the
        three-way answer (mesh._block_to_apply)."""
        return self._validity.get(block_id)

    # --- vote encoding -------------------------------------------------

    def encode_votes(self, for_layer: int) -> Opinion:
        """Build the opinion for a new ballot in ``for_layer``: pick the
        newest known ballot as base, express the local opinion (hare within
        hdist, validity beyond) as exceptions (reference
        tortoise/algorithm.go:EncodeVotes)."""
        base_id = EMPTY
        base_info = None
        for lyr in sorted(self._ballots_by_layer, reverse=True):
            if lyr >= for_layer:
                continue
            cands = [self._ballots[b] for b in self._ballots_by_layer[lyr]
                     if not self._ballots[b].malicious]
            if cands:
                base_info = max(cands, key=lambda i: (i.weight, i.id))
                base_id = base_info.id
                break
        support, against, abstain = [], [], []
        start = max(1, for_layer - self.window)
        for lyr in range(start, for_layer):
            local: set[bytes] = set()
            if lyr in self._hare and self.processed - lyr < self.hdist:
                if self._hare[lyr] != EMPTY:
                    local = {self._hare[lyr]}
            else:
                local = {b for b in self._blocks.get(lyr, set())
                         if self._validity.get(b)}
                if not local and lyr > self.verified and lyr not in self._hare:
                    abstain.append(lyr)
                    continue
            base_sup = (base_info.supports.get(lyr, set())
                        if base_info else set())
            support += sorted(local - base_sup)
            against += sorted(base_sup - local)
        return Opinion(base=base_id, support=support, against=against,
                       abstain=abstain)

    # --- recovery (reference tortoise/recover.go:20) -------------------

    @classmethod
    def recover(cls, db, cache: AtxCache, oracle, *, layers_per_epoch: int,
                hdist: int, zdist: int, window: int,
                tracer=None) -> "Tortoise":
        """Rebuild tortoise state from storage after a restart: blocks and
        their persisted validity, certified/applied hare outputs, then
        ballots in layer order (so bases resolve, reference recover.go
        replays in the same order)."""
        from ..storage import ballots as ballotstore
        from ..storage import blocks as blockstore
        from ..storage import layers as layerstore
        from ..storage import misc as miscstore

        t = cls(cache, layers_per_epoch, hdist=hdist, zdist=zdist,
                window=window, tracer=tracer)
        processed = layerstore.processed(db)
        if processed < 0:
            return t
        low = max(1, processed - window)
        for layer in range(low, processed + 1):
            for bid in blockstore.ids_in_layer(db, layer):
                t.on_block(layer, bid)
                validity = blockstore.validity(db, bid)
                if validity == blockstore.VALID:
                    t._validity[bid] = True
                elif validity == blockstore.INVALID:
                    t._validity[bid] = False
            cert = miscstore.certified_block(db, layer)
            applied = layerstore.applied_block(db, layer)
            if cert is not None:
                t.on_hare_output(layer, cert)
            elif applied is not None:
                t.on_hare_output(layer, applied)
        # Ballots at or below the 0004 block-id-rewrite boundary carry
        # signed vote lists naming pre-rewrite block ids; replaying them
        # would resolve every support as against and could flip validity
        # of in-window blocks (ADVICE r4). Persisted per-block verdicts
        # (loaded above) already cover those layers.
        ballot_low = max(low, miscstore.migration_boundary(db) + 1)
        for layer in range(ballot_low, processed + 1):
            for ballot in ballotstore.in_layer(db, layer):
                epoch = layer // layers_per_epoch
                info = cache.get(epoch, ballot.atx_id)
                if info is None:
                    continue
                # shared with live ingest (miner.ingest_ballot) —
                # recover must not flag ballots the live path left
                # unflagged, nor weigh them differently: the stored
                # (already-validated) ref-ballot eligibility count
                # bounds the per-eligibility weight on trusted
                # networks, the local recomputation otherwise
                epoch_data = ballotstore.resolve_epoch_data(
                    db, ballot, layers_per_epoch)
                if epoch_data is not None and oracle.trusts_declared(epoch):
                    num = epoch_data.eligibility_count
                else:
                    num = oracle.num_slots(epoch, ballot.atx_id)
                unit = info.weight // max(num, 1)
                declared = epoch_data.beacon if epoch_data is not None \
                    else None
                local = miscstore.get_beacon(db, epoch)
                bad = (declared is not None and local is not None
                       and declared != local)
                t.on_ballot(ballot, unit * len(ballot.eligibilities),
                            bad_beacon=bad)
        t.processed = processed
        t.verified = max(
            min(layerstore.last_applied(db), processed) - 1, 0)
        return t


# --- trace replay (reference tortoise/tracer.go:79 RunTrace) ---------------


def replay_trace(lines, cache: AtxCache | None = None,
                 tracer=None) -> Tortoise:
    """Rebuild a Tortoise by replaying a recorded JSON trace. The trace is
    self-contained: ballot events carry their full opinion and weight."""
    cache = cache or AtxCache()
    t: Tortoise | None = None
    for line in lines:
        line = line.strip()
        if not line:
            continue
        ev = json.loads(line)
        kind = ev["ev"]
        if kind == "init":
            t = Tortoise(cache, ev["lpe"], hdist=ev["hdist"],
                         zdist=ev.get("zdist", 8), window=ev["window"],
                         tracer=tracer)
        elif t is None:
            raise ValueError("trace does not start with an init event")
        elif kind == "block":
            t.on_block(ev["layer"], bytes.fromhex(ev["id"]))
        elif kind == "hare":
            t.on_hare_output(ev["layer"], bytes.fromhex(ev["id"]))
        elif kind == "coin":
            t.on_weak_coin(ev["layer"], bool(ev["coin"]))
        elif kind == "malfeasance":
            t.on_malfeasance(bytes.fromhex(ev["id"]))
        elif kind == "ballot":
            op = Opinion(
                base=bytes.fromhex(ev["base"]),
                support=[bytes.fromhex(x) for x in ev["support"]],
                against=[bytes.fromhex(x) for x in ev["against"]],
                abstain=list(ev["abstain"]))
            t._ingest(bytes.fromhex(ev["id"]), ev["layer"],
                      bytes.fromhex(ev["node"]), op, ev["weight"],
                      bad_beacon=bool(ev.get("bad", False)))
        elif kind == "tally":
            t.tally_votes(ev["last"])
    if t is None:
        raise ValueError("empty trace")
    return t
