"""Identity signatures and VRF.

Mirrors the reference signing package (reference signing/signer.go:157
EdSigner with domain separation + genesis-prefix, signing/verifier.go
EdVerifier, signing/vrf.go ECVRF via curve25519-voi):

- EdSigner/EdVerifier: ed25519 (via the `cryptography` library) over
  ``prefix || domain_byte || message`` where prefix is the genesis id —
  signatures from different networks or domains never collide.
- VrfSigner/VrfVerifier: ECVRF-EDWARDS25519-SHA512-TAI (RFC 9381 suite
  0x03), implemented from spec in pure Python (curve arithmetic below).
  The VRF output (beta) drives eligibility sampling and the beacon's weak
  coin, so it must be a *proof* (unique, verifiable), not a bare signature.

A native twin (native/ecvrf.cpp, ~20x faster) handles prove/verify/
output when it builds; the Python implementation is the fallback AND the
test oracle (tests/test_native_ecvrf.py pins bit-identical behavior).
Set SPACEMESH_NO_NATIVE_VRF=1 to force the Python path.
"""

from __future__ import annotations

import enum
import hashlib

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey,
    Ed25519PublicKey,
)
from cryptography.hazmat.primitives import serialization
from cryptography.exceptions import InvalidSignature

PUBLIC_KEY_SIZE = 32
PRIVATE_KEY_SIZE = 64  # seed || public, like the reference's ed25519
SIGNATURE_SIZE = 64
VRF_PROOF_SIZE = 80
VRF_OUTPUT_SIZE = 64


class Domain(enum.IntEnum):
    """Signature domains (reference signing/signer.go:18-38)."""

    ATX = 0
    BEACON_FIRST_MSG = 1
    BEACON_FOLLOWUP_MSG = 2
    BALLOT = 3
    HARE = 4
    POET = 5
    BEACON_PROPOSAL = 6
    MALFEASANCE = 7
    TX = 8               # this framework's tx envelope (vm/vm.py)
    CERTIFY = 9
    TRANSPORT = 10       # p2p channel-binding signature (p2p/noise.py)
    POET_CERT = 11       # poet certifier certificates (consensus/certifier.py)


# --- ed25519 identity signatures -----------------------------------------


class EdSigner:
    def __init__(self, seed: bytes | None = None, prefix: bytes = b""):
        if seed is None:
            self._sk = Ed25519PrivateKey.generate()
        else:
            if len(seed) not in (32, 64):
                raise ValueError("seed must be 32 (seed) or 64 (seed||pub) bytes")
            self._sk = Ed25519PrivateKey.from_private_bytes(seed[:32])
        self.prefix = prefix
        self._pub = self._sk.public_key().public_bytes(
            serialization.Encoding.Raw, serialization.PublicFormat.Raw)

    @property
    def node_id(self) -> bytes:
        return self._pub

    @property
    def public_key(self) -> bytes:
        return self._pub

    def private_bytes(self) -> bytes:
        seed = self._sk.private_bytes(
            serialization.Encoding.Raw, serialization.PrivateFormat.Raw,
            serialization.NoEncryption())
        return seed + self._pub

    def sign(self, domain: Domain, msg: bytes) -> bytes:
        return self._sk.sign(self.prefix + bytes([domain]) + msg)

    def vrf_signer(self) -> "VrfSigner":
        seed = self._sk.private_bytes(
            serialization.Encoding.Raw, serialization.PrivateFormat.Raw,
            serialization.NoEncryption())
        return VrfSigner(seed, self._pub)


class EdVerifier:
    def __init__(self, prefix: bytes = b""):
        self.prefix = prefix

    def verify(self, domain: Domain, public_key: bytes, msg: bytes,
               sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE or len(public_key) != PUBLIC_KEY_SIZE:
            return False
        try:
            Ed25519PublicKey.from_public_bytes(public_key).verify(
                sig, self.prefix + bytes([domain]) + msg)
            return True
        except (InvalidSignature, ValueError):
            return False


# --- edwards25519 arithmetic (for the VRF) --------------------------------

_P = 2**255 - 19
_Q = 2**252 + 27742317777372353535851937790883648493
_D = (-121665 * pow(121666, _P - 2, _P)) % _P
_SQRT_M1 = pow(2, (_P - 1) // 4, _P)


def _inv(x: int) -> int:
    return pow(x, _P - 2, _P)


# extended homogeneous coordinates (X, Y, Z, T), x*y == z*t
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
_BY = 46316835694926478169428394003475163141307993866256225615783033603165251855960
_B = (_BX, _BY, 1, (_BX * _BY) % _P)
_ID = (0, 1, 1, 0)


def _pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % _P
    b = ((y1 + x1) * (y2 + x2)) % _P
    c = (2 * t1 * t2 * _D) % _P
    dd = (2 * z1 * z2) % _P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return ((e * f) % _P, (g * h) % _P, (f * g) % _P, (e * h) % _P)


def _pt_mul(s: int, p):
    out = _ID
    while s:
        if s & 1:
            out = _pt_add(out, p)
        p = _pt_add(p, p)
        s >>= 1
    return out


def _pt_eq(p, q) -> bool:
    # cross-multiply to compare projective points
    return ((p[0] * q[2] - q[0] * p[2]) % _P == 0
            and (p[1] * q[2] - q[1] * p[2]) % _P == 0)


def _pt_encode(p) -> bytes:
    zi = _inv(p[2])
    x = (p[0] * zi) % _P
    y = (p[1] * zi) % _P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _pt_decode(data: bytes):
    if len(data) != 32:
        return None
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= _P:
        return None
    # recover x: x^2 = (y^2 - 1) / (d*y^2 + 1)
    u = (y * y - 1) % _P
    v = (_D * y * y + 1) % _P
    x = (u * v**3 % _P) * pow(u * v**7 % _P, (_P - 5) // 8, _P) % _P
    vx2 = (v * x * x) % _P
    if vx2 == u % _P:
        pass
    elif vx2 == (-u) % _P:
        x = (x * _SQRT_M1) % _P
    else:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = _P - x
    return (x, y, 1, (x * y) % _P)


# --- ECVRF-EDWARDS25519-SHA512-TAI (RFC 9381, suite 0x03) -----------------

_SUITE = b"\x03"

_NATIVE_VRF_UNSET = object()
_NATIVE_VRF = _NATIVE_VRF_UNSET


def _native_vrf():
    """libsmtpu_ecvrf handle, or None (build failure / opt-out)."""
    global _NATIVE_VRF
    if _NATIVE_VRF is _NATIVE_VRF_UNSET:
        import ctypes
        import os

        lib = None
        if not os.environ.get("SPACEMESH_NO_NATIVE_VRF"):
            from ..native import load

            lib = load("ecvrf")
            if lib is not None:
                for fn, args in (
                        ("smtpu_vrf_public_key", 2),
                        ("smtpu_vrf_output", 2)):
                    getattr(lib, fn).argtypes = \
                        [ctypes.c_char_p] * args
                    getattr(lib, fn).restype = ctypes.c_int
                for fn in ("smtpu_vrf_prove", "smtpu_vrf_verify"):
                    getattr(lib, fn).argtypes = [
                        ctypes.c_char_p, ctypes.c_char_p,
                        ctypes.c_size_t, ctypes.c_char_p]
                    getattr(lib, fn).restype = ctypes.c_int
        _NATIVE_VRF = lib
    return _NATIVE_VRF


def _expand_key(seed32: bytes) -> tuple[int, bytes]:
    h = hashlib.sha512(seed32).digest()
    x = int.from_bytes(h[:32], "little")
    x &= (1 << 254) - 8
    x |= 1 << 254
    return x, h[32:]


def _hash_to_curve_tai(y_bytes: bytes, alpha: bytes):
    ctr = 0
    while ctr < 256:
        h = hashlib.sha512(
            _SUITE + b"\x01" + y_bytes + alpha + bytes([ctr]) + b"\x00"
        ).digest()[:32]
        pt = _pt_decode(h)
        if pt is not None:
            return _pt_mul(8, pt)  # clear cofactor
        ctr += 1
    raise RuntimeError("hash_to_curve failed")  # pragma: no cover


def _challenge(points: list) -> int:
    data = _SUITE + b"\x02" + b"".join(_pt_encode(p) for p in points) + b"\x00"
    return int.from_bytes(hashlib.sha512(data).digest()[:16], "little")


class VrfSigner:
    def __init__(self, seed32: bytes, public_key: bytes | None = None):
        if len(seed32) != 32:
            raise ValueError("vrf seed must be 32 bytes")
        self._seed = seed32
        self._x, self._nonce_key = _expand_key(seed32)
        # the Python scalar mult for the public key costs ~1/4 of a full
        # Python prove, and VrfSigners are constructed per eligibility
        # check — when the native library is up it derives the key and
        # the Python point stays lazy (code-review r5)
        self.__y = None
        lib = _native_vrf()
        if lib is not None:
            import ctypes

            buf = ctypes.create_string_buffer(32)
            if lib.smtpu_vrf_public_key(seed32, buf) == 0:
                self.public_key = buf.raw
            else:  # pragma: no cover - native failure
                self.public_key = _pt_encode(self._y_point)
        else:
            self.public_key = _pt_encode(self._y_point)
        if public_key is not None and public_key != self.public_key:
            raise ValueError("public key mismatch")

    @property
    def _y_point(self):
        if self.__y is None:
            self.__y = _pt_mul(self._x, _B)
        return self.__y

    def prove(self, alpha: bytes) -> bytes:
        lib = _native_vrf()
        if lib is not None:
            import ctypes

            buf = ctypes.create_string_buffer(VRF_PROOF_SIZE)
            if lib.smtpu_vrf_prove(self._seed, alpha, len(alpha),
                                   buf) == 0:
                return buf.raw
            # fall through to the Python twin on any native failure
        h_pt = _hash_to_curve_tai(self.public_key, alpha)
        h_bytes = _pt_encode(h_pt)
        gamma = _pt_mul(self._x, h_pt)
        k = int.from_bytes(
            hashlib.sha512(self._nonce_key + h_bytes).digest(), "little") % _Q
        c = _challenge([self._y_point, h_pt, gamma, _pt_mul(k, _B),
                        _pt_mul(k, h_pt)])
        s = (k + c * self._x) % _Q
        return (_pt_encode(gamma) + c.to_bytes(16, "little")
                + s.to_bytes(32, "little"))

    def sign(self, alpha: bytes) -> bytes:  # reference naming: vrf "signature"
        return self.prove(alpha)


def vrf_output(proof: bytes) -> bytes:
    """beta = proof_to_hash(pi): the uniform VRF output (64 bytes)."""
    lib = _native_vrf()
    if lib is not None and len(proof) >= 32:
        import ctypes

        out = ctypes.create_string_buffer(64)
        rc = lib.smtpu_vrf_output(proof[:32], out)
        if rc == 0:
            return out.raw
        raise ValueError("invalid vrf proof")
    gamma = _pt_decode(proof[:32])
    if gamma is None:
        raise ValueError("invalid vrf proof")
    cg = _pt_mul(8, gamma)
    return hashlib.sha512(_SUITE + b"\x03" + _pt_encode(cg) + b"\x00").digest()


class VrfVerifier:
    def verify(self, public_key: bytes, alpha: bytes, proof: bytes) -> bool:
        if len(proof) != VRF_PROOF_SIZE or len(public_key) != 32:
            return False
        lib = _native_vrf()
        if lib is not None:
            return bool(lib.smtpu_vrf_verify(public_key, alpha,
                                             len(alpha), proof))
        y = _pt_decode(public_key)
        gamma = _pt_decode(proof[:32])
        if y is None or gamma is None:
            return False
        c = int.from_bytes(proof[32:48], "little")
        s = int.from_bytes(proof[48:80], "little")
        if s >= _Q:
            return False
        h_pt = _hash_to_curve_tai(public_key, alpha)
        # U = s*B - c*Y ; V = s*H - c*Gamma
        neg = lambda p: ((-p[0]) % _P, p[1], p[2], (-p[3]) % _P)  # noqa: E731
        u = _pt_add(_pt_mul(s, _B), _pt_mul(c, neg(y)))
        v = _pt_add(_pt_mul(s, h_pt), _pt_mul(c, neg(gamma)))
        return _challenge([y, h_pt, gamma, u, v]) == c
