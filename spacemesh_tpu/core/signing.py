"""Identity signatures and VRF.

Mirrors the reference signing package (reference signing/signer.go:157
EdSigner with domain separation + genesis-prefix, signing/verifier.go
EdVerifier, signing/vrf.go ECVRF via curve25519-voi):

- EdSigner/EdVerifier: ed25519 (via the `cryptography` library) over
  ``prefix || domain_byte || message`` where prefix is the genesis id —
  signatures from different networks or domains never collide.
- VrfSigner/VrfVerifier: ECVRF-EDWARDS25519-SHA512-TAI (RFC 9381 suite
  0x03), implemented from spec in pure Python (curve arithmetic below).
  The VRF output (beta) drives eligibility sampling and the beacon's weak
  coin, so it must be a *proof* (unique, verifiable), not a bare signature.

A native twin (native/ecvrf.cpp, ~20x faster) handles prove/verify/
output when it builds; the Python implementation is the fallback AND the
test oracle (tests/test_native_ecvrf.py pins bit-identical behavior).
Set SPACEMESH_NO_NATIVE_VRF=1 to force the Python path.
"""

from __future__ import annotations

import enum
import hashlib
import os

try:  # the fast path: OpenSSL ed25519 via pyca/cryptography
    from cryptography.hazmat.primitives.asymmetric.ed25519 import (
        Ed25519PrivateKey,
        Ed25519PublicKey,
    )
    from cryptography.exceptions import InvalidSignature

    _HAVE_CRYPTOGRAPHY = True
except ModuleNotFoundError:  # pure-Python ed25519 over the VRF's curve
    # code below (RFC 8032); containers without `cryptography` must not
    # lose the whole identity layer
    _HAVE_CRYPTOGRAPHY = False

PUBLIC_KEY_SIZE = 32
PRIVATE_KEY_SIZE = 64  # seed || public, like the reference's ed25519
SIGNATURE_SIZE = 64
VRF_PROOF_SIZE = 80
VRF_OUTPUT_SIZE = 64


class Domain(enum.IntEnum):
    """Signature domains (reference signing/signer.go:18-38)."""

    ATX = 0
    BEACON_FIRST_MSG = 1
    BEACON_FOLLOWUP_MSG = 2
    BALLOT = 3
    HARE = 4
    POET = 5
    BEACON_PROPOSAL = 6
    MALFEASANCE = 7
    TX = 8               # this framework's tx envelope (vm/vm.py)
    CERTIFY = 9
    TRANSPORT = 10       # p2p channel-binding signature (p2p/noise.py)
    POET_CERT = 11       # poet certifier certificates (consensus/certifier.py)


# --- ed25519 identity signatures -----------------------------------------


class EdSigner:
    def __init__(self, seed: bytes | None = None, prefix: bytes = b""):
        if seed is None:
            seed = os.urandom(32)
        elif len(seed) not in (32, 64):
            raise ValueError("seed must be 32 (seed) or 64 (seed||pub) bytes")
        self._seed = seed[:32]
        self.prefix = prefix
        if _HAVE_CRYPTOGRAPHY:
            self._sk = Ed25519PrivateKey.from_private_bytes(self._seed)
            from cryptography.hazmat.primitives import serialization

            self._pub = self._sk.public_key().public_bytes(
                serialization.Encoding.Raw, serialization.PublicFormat.Raw)
        else:
            self._sk = None
            self._scalar, self._nonce_prefix = _expand_key(self._seed)
            self._pub = _pt_encode(_pt_mul_base(self._scalar))

    @property
    def node_id(self) -> bytes:
        return self._pub

    @property
    def public_key(self) -> bytes:
        return self._pub

    def private_bytes(self) -> bytes:
        return self._seed + self._pub

    def sign(self, domain: Domain, msg: bytes) -> bytes:
        data = self.prefix + bytes([domain]) + msg
        if self._sk is not None:
            return self._sk.sign(data)
        # RFC 8032 EdDSA over the VRF's curve arithmetic
        r = int.from_bytes(
            hashlib.sha512(self._nonce_prefix + data).digest(),
            "little") % _Q
        r_enc = _pt_encode(_pt_mul_base(r))
        k = int.from_bytes(
            hashlib.sha512(r_enc + self._pub + data).digest(),
            "little") % _Q
        s = (r + k * self._scalar) % _Q
        return r_enc + s.to_bytes(32, "little")

    def vrf_signer(self) -> "VrfSigner":
        return VrfSigner(self._seed, self._pub)


class EdVerifier:
    def __init__(self, prefix: bytes = b""):
        self.prefix = prefix

    def verify(self, domain: Domain, public_key: bytes, msg: bytes,
               sig: bytes) -> bool:
        if len(sig) != SIGNATURE_SIZE or len(public_key) != PUBLIC_KEY_SIZE:
            return False
        data = self.prefix + bytes([domain]) + msg
        if _HAVE_CRYPTOGRAPHY:
            try:
                Ed25519PublicKey.from_public_bytes(public_key).verify(
                    sig, data)
                return True
            except (InvalidSignature, ValueError):
                return False
        return _ed_verify_cached(public_key, data, sig)

    def verify_many(self, items) -> list[bool]:
        """Batch-verify ``(domain, public_key, msg, sig)`` tuples —
        decisions identical to per-item verify(), but one random-linear-
        combination multi-scalar check instead of N ladders (the
        verification farm's sig backend; see ed25519_batch_verify)."""
        return ed25519_batch_verify([
            (pk, self.prefix + bytes([dom]) + msg, sig)
            for dom, pk, msg, sig in items])


# --- edwards25519 arithmetic (for the VRF) --------------------------------

_P = 2**255 - 19
_Q = 2**252 + 27742317777372353535851937790883648493
_D = (-121665 * pow(121666, _P - 2, _P)) % _P
_SQRT_M1 = pow(2, (_P - 1) // 4, _P)


def _inv(x: int) -> int:
    return pow(x, _P - 2, _P)


# extended homogeneous coordinates (X, Y, Z, T), x*y == z*t
_BX = 15112221349535400772501151409588531511454012693041857206046113283949847762202
_BY = 46316835694926478169428394003475163141307993866256225615783033603165251855960
_B = (_BX, _BY, 1, (_BX * _BY) % _P)
_ID = (0, 1, 1, 0)


def _pt_add(p, q):
    x1, y1, z1, t1 = p
    x2, y2, z2, t2 = q
    a = ((y1 - x1) * (y2 - x2)) % _P
    b = ((y1 + x1) * (y2 + x2)) % _P
    c = (2 * t1 * t2 * _D) % _P
    dd = (2 * z1 * z2) % _P
    e, f, g, h = b - a, dd - c, dd + c, b + a
    return ((e * f) % _P, (g * h) % _P, (f * g) % _P, (e * h) % _P)


def _pt_mul(s: int, p):
    out = _ID
    while s:
        if s & 1:
            out = _pt_add(out, p)
        p = _pt_add(p, p)
        s >>= 1
    return out


_B_DOUBLES: list | None = None


def _pt_mul_base(s: int):
    """s*B via a cached table of B's doublings — the ed25519 fallback
    signs/verifies against the base point constantly; halving the adds
    matters when this is the only ed25519 in the container."""
    global _B_DOUBLES
    if _B_DOUBLES is None:
        table, p = [], _B
        for _ in range(256):
            table.append(p)
            p = _pt_add(p, p)
        _B_DOUBLES = table
    out = _ID
    i = 0
    while s:
        if s & 1:
            out = _pt_add(out, _B_DOUBLES[i])
        s >>= 1
        i += 1
    return out


def _mul8(p):
    """8*P via three doublings (the unified add formula doubles too)."""
    p = _pt_add(p, p)
    p = _pt_add(p, p)
    return _pt_add(p, p)


def _ed_check(a_pt, r_pt, s: int, k: int) -> bool:
    """The COFACTORED verification equation: 8*(s*B) == 8*(R + k*A)
    (ZIP-215 / ed25519consensus style, except encodings stay canonical).

    Cofactored — not RFC 8032's cofactorless s*B == R + k*A — because
    the batch path must be decision-identical to this check: under the
    cofactorless equation an adversarial signature whose R carries a
    small-order torsion component fails per-item but slips through a
    random-linear-combination batch with probability ~1/8 (z_i ≡ 0
    mod 8 annihilates the defect), so batch acceptance would not imply
    per-item acceptance. Multiplying by the cofactor maps every term
    into the prime-order subgroup, where the random 128-bit z_i make
    batch and per-item verdicts agree except with probability 2^-128.
    Honest signatures (torsion-free R, A) verify identically under both
    equations; only adversarial small-order components see the OpenSSL
    path (cofactorless) diverge — and mixed-backend networks already
    require a uniform suite (see p2p/noise.py's module note).

    8*(s*B) folds to (8s mod Q)*B since B generates the prime-order
    subgroup; R and k*A may carry torsion, so the right side must
    double the POINT three times.
    """
    return _pt_eq(_pt_mul_base(8 * s % _Q),
                  _mul8(_pt_add(r_pt, _pt_mul(k, a_pt))))


def _ed_verify_py(public_key: bytes, data: bytes, sig: bytes) -> bool:
    """Pure-Python ed25519 verify (cofactored — see _ed_check)."""
    a_pt = _pt_decode(public_key)
    r_pt = _pt_decode(sig[:32])
    if a_pt is None or r_pt is None:
        return False
    s = int.from_bytes(sig[32:], "little")
    if s >= _Q:
        return False
    k = int.from_bytes(
        hashlib.sha512(sig[:32] + public_key + data).digest(),
        "little") % _Q
    return _ed_check(a_pt, r_pt, s, k)


def _pt_neg(p):
    return ((-p[0]) % _P, p[1], p[2], (-p[3]) % _P)


# verdict LRU for the pure-Python path: a multi-identity node (and the
# in-proc multinode tests) verifies the SAME gossip signature once per
# consumer; at ~3 ms per Python ladder that repeat work dominates. The
# reference caches verified objects by id for the same reason. OpenSSL
# (~50 us) skips this — the cache churn would cost more than it saves.
_VERIFY_CACHE: dict = {}
_VERIFY_CACHE_MAX = 8192
_VERIFY_CACHE_LOCK = None  # created lazily; farm backends run in threads


def _cache_put(key: bytes, ok: bool) -> None:
    global _VERIFY_CACHE_LOCK
    if _VERIFY_CACHE_LOCK is None:
        import threading

        _VERIFY_CACHE_LOCK = threading.Lock()
    with _VERIFY_CACHE_LOCK:
        if len(_VERIFY_CACHE) >= _VERIFY_CACHE_MAX:
            # dicts iterate in insertion order: evict the oldest half
            for k in list(_VERIFY_CACHE)[:_VERIFY_CACHE_MAX // 2]:
                del _VERIFY_CACHE[k]
        _VERIFY_CACHE[key] = ok


def _ed_verify_cached(public_key: bytes, data: bytes, sig: bytes) -> bool:
    key = hashlib.sha256(public_key + sig + data).digest()
    hit = _VERIFY_CACHE.get(key)  # GIL-atomic read; misses just recompute
    if hit is not None:
        return hit
    ok = _ed_verify_py(public_key, data, sig)
    _cache_put(key, ok)
    return ok


def clear_verify_cache() -> None:
    """Drop cached ed25519 verdicts (benchmarks comparing verification
    paths must not let one path's warm cache subsidize the other)."""
    _VERIFY_CACHE.clear()


def _msm(pairs):
    """Multi-scalar multiplication Σ s_i·P_i (Pippenger buckets):
    ~(N + 2^c) point adds per window instead of N full ladders — the
    reason batch verification beats N serial verifies. Window width c
    adapts to the point count so small groups don't pay a 256-bucket
    constant."""
    n = len(pairs)
    c = max(2, min(8, n.bit_length() - 1))
    result = _ID
    top = ((256 + c - 1) // c) * c - c
    for w in range(top, -1, -c):
        for _ in range(c):
            result = _pt_add(result, result)
        buckets: list = [None] * (1 << c)
        for s, p in pairs:
            idx = (s >> w) & ((1 << c) - 1)
            if idx:
                b = buckets[idx]
                buckets[idx] = p if b is None else _pt_add(b, p)
        running = None
        total = None
        for i in range((1 << c) - 1, 0, -1):
            b = buckets[i]
            if b is not None:
                running = b if running is None else _pt_add(running, b)
            if running is not None:
                total = running if total is None else _pt_add(total, running)
        if total is not None:
            result = _pt_add(result, total)
    return result


def ed25519_batch_verify(items: list[tuple[bytes, bytes, bytes]]
                         ) -> list[bool]:
    """Batch-verify ``(public_key, data, signature)`` triples.

    The random-linear-combination check (the dalek/ed25519consensus
    technique): with fresh 128-bit coefficients z_i,

        8·(Σ z_i·s_i)·B  ==  8·(Σ z_i·R_i + Σ (z_i·k_i)·A_i)

    holds for an all-valid batch, and fails with probability 1-2^-128
    if ANY signature is invalid. Both sides are multiplied by the
    cofactor — and per-item verification uses the same cofactored
    equation (_ed_check) — because a cofactorLESS batch is unsound
    against torsion: a signature with a small-order defect in R passes
    the combination with probability ~1/8, so batch acceptance would
    not imply per-item acceptance. One Pippenger multi-scalar
    multiplication replaces N independent double-scalar ladders. On
    batch failure every candidate is re-checked individually, so the
    returned decisions are always EXACTLY the per-item verdicts —
    callers never observe a semantic difference, only the speed.

    With `cryptography` present, per-item OpenSSL beats the pure-Python
    MSM and is used instead (it also releases the GIL, so callers can
    chunk across threads).
    """
    results = [False] * len(items)
    cand = []  # (index, A, R, s, k, cache_key) for plausible sigs
    for i, (pk, data, sig) in enumerate(items):
        if len(sig) != SIGNATURE_SIZE or len(pk) != PUBLIC_KEY_SIZE:
            continue
        if _HAVE_CRYPTOGRAPHY:
            try:
                Ed25519PublicKey.from_public_bytes(pk).verify(sig, data)
                results[i] = True
            except (InvalidSignature, ValueError):
                pass
            continue
        key = hashlib.sha256(pk + sig + data).digest()
        hit = _VERIFY_CACHE.get(key)
        if hit is not None:  # shares the inline path's verdict LRU
            results[i] = hit
            continue
        a_pt = _pt_decode(pk)
        r_pt = _pt_decode(sig[:32])
        s = int.from_bytes(sig[32:], "little")
        if a_pt is None or r_pt is None or s >= _Q:
            continue
        k = int.from_bytes(
            hashlib.sha512(sig[:32] + pk + data).digest(), "little") % _Q
        cand.append((i, a_pt, r_pt, s, k, key))
    if _HAVE_CRYPTOGRAPHY or not cand:
        return results
    batched_ok = False
    if len(cand) >= 8:  # MSM setup overhead beats tiny batches
        zs = [int.from_bytes(os.urandom(16), "little") | (1 << 127)
              for _ in cand]
        lhs = sum(z * g[3] for z, g in zip(zs, cand)) % _Q
        pairs = []
        for z, (_, a_pt, r_pt, _, k, _key) in zip(zs, cand):
            pairs.append((z, r_pt))
            pairs.append((z * k % _Q, a_pt))
        batched_ok = _pt_eq(_pt_mul_base(8 * lhs % _Q),
                            _mul8(_msm(pairs)))
        # a failed combo means at least one invalid signature: fall
        # through to per-item checks so every caller gets its exact
        # verdict. (Bisecting instead re-verifies the clean halves with
        # fresh MSMs — for realistic contamination that costs MORE than
        # one serial pass, so the penalty is kept flat: one wasted MSM,
        # ~1.3x serial.)
    for i, a_pt, r_pt, s, k, key in cand:
        ok = batched_ok or _ed_check(a_pt, r_pt, s, k)
        results[i] = ok
        _cache_put(key, ok)
    return results


def _pt_eq(p, q) -> bool:
    # cross-multiply to compare projective points
    return ((p[0] * q[2] - q[0] * p[2]) % _P == 0
            and (p[1] * q[2] - q[1] * p[2]) % _P == 0)


def _pt_encode(p) -> bytes:
    zi = _inv(p[2])
    x = (p[0] * zi) % _P
    y = (p[1] * zi) % _P
    return (y | ((x & 1) << 255)).to_bytes(32, "little")


def _pt_decode(data: bytes):
    if len(data) != 32:
        return None
    y = int.from_bytes(data, "little")
    sign = y >> 255
    y &= (1 << 255) - 1
    if y >= _P:
        return None
    # recover x: x^2 = (y^2 - 1) / (d*y^2 + 1)
    u = (y * y - 1) % _P
    v = (_D * y * y + 1) % _P
    x = (u * v**3 % _P) * pow(u * v**7 % _P, (_P - 5) // 8, _P) % _P
    vx2 = (v * x * x) % _P
    if vx2 == u % _P:
        pass
    elif vx2 == (-u) % _P:
        x = (x * _SQRT_M1) % _P
    else:
        return None
    if x == 0 and sign:
        return None
    if x & 1 != sign:
        x = _P - x
    return (x, y, 1, (x * y) % _P)


# --- ECVRF-EDWARDS25519-SHA512-TAI (RFC 9381, suite 0x03) -----------------

_SUITE = b"\x03"

_NATIVE_VRF_UNSET = object()
_NATIVE_VRF = _NATIVE_VRF_UNSET


def _native_vrf():
    """libsmtpu_ecvrf handle, or None (build failure / opt-out)."""
    global _NATIVE_VRF
    if _NATIVE_VRF is _NATIVE_VRF_UNSET:
        import ctypes
        import os

        lib = None
        if not os.environ.get("SPACEMESH_NO_NATIVE_VRF"):
            from ..native import load

            lib = load("ecvrf")
            if lib is not None:
                for fn, args in (
                        ("smtpu_vrf_public_key", 2),
                        ("smtpu_vrf_output", 2)):
                    getattr(lib, fn).argtypes = \
                        [ctypes.c_char_p] * args
                    getattr(lib, fn).restype = ctypes.c_int
                for fn in ("smtpu_vrf_prove", "smtpu_vrf_verify"):
                    getattr(lib, fn).argtypes = [
                        ctypes.c_char_p, ctypes.c_char_p,
                        ctypes.c_size_t, ctypes.c_char_p]
                    getattr(lib, fn).restype = ctypes.c_int
        _NATIVE_VRF = lib
    return _NATIVE_VRF


def _expand_key(seed32: bytes) -> tuple[int, bytes]:
    h = hashlib.sha512(seed32).digest()
    x = int.from_bytes(h[:32], "little")
    x &= (1 << 254) - 8
    x |= 1 << 254
    return x, h[32:]


def _hash_to_curve_tai(y_bytes: bytes, alpha: bytes):
    ctr = 0
    while ctr < 256:
        h = hashlib.sha512(
            _SUITE + b"\x01" + y_bytes + alpha + bytes([ctr]) + b"\x00"
        ).digest()[:32]
        pt = _pt_decode(h)
        if pt is not None:
            return _pt_mul(8, pt)  # clear cofactor
        ctr += 1
    raise RuntimeError("hash_to_curve failed")  # pragma: no cover


def _challenge(points: list) -> int:
    data = _SUITE + b"\x02" + b"".join(_pt_encode(p) for p in points) + b"\x00"
    return int.from_bytes(hashlib.sha512(data).digest()[:16], "little")


class VrfSigner:
    def __init__(self, seed32: bytes, public_key: bytes | None = None):
        if len(seed32) != 32:
            raise ValueError("vrf seed must be 32 bytes")
        self._seed = seed32
        self._x, self._nonce_key = _expand_key(seed32)
        # the Python scalar mult for the public key costs ~1/4 of a full
        # Python prove, and VrfSigners are constructed per eligibility
        # check — when the native library is up it derives the key and
        # the Python point stays lazy (code-review r5)
        self.__y = None
        lib = _native_vrf()
        if lib is not None:
            import ctypes

            buf = ctypes.create_string_buffer(32)
            if lib.smtpu_vrf_public_key(seed32, buf) == 0:
                self.public_key = buf.raw
            else:  # pragma: no cover - native failure
                self.public_key = _pt_encode(self._y_point)
        else:
            self.public_key = _pt_encode(self._y_point)
        if public_key is not None and public_key != self.public_key:
            raise ValueError("public key mismatch")

    @property
    def _y_point(self):
        if self.__y is None:
            self.__y = _pt_mul(self._x, _B)
        return self.__y

    def prove(self, alpha: bytes) -> bytes:
        lib = _native_vrf()
        if lib is not None:
            import ctypes

            buf = ctypes.create_string_buffer(VRF_PROOF_SIZE)
            if lib.smtpu_vrf_prove(self._seed, alpha, len(alpha),
                                   buf) == 0:
                return buf.raw
            # fall through to the Python twin on any native failure
        h_pt = _hash_to_curve_tai(self.public_key, alpha)
        h_bytes = _pt_encode(h_pt)
        gamma = _pt_mul(self._x, h_pt)
        k = int.from_bytes(
            hashlib.sha512(self._nonce_key + h_bytes).digest(), "little") % _Q
        c = _challenge([self._y_point, h_pt, gamma, _pt_mul(k, _B),
                        _pt_mul(k, h_pt)])
        s = (k + c * self._x) % _Q
        return (_pt_encode(gamma) + c.to_bytes(16, "little")
                + s.to_bytes(32, "little"))

    def sign(self, alpha: bytes) -> bytes:  # reference naming: vrf "signature"
        return self.prove(alpha)


def vrf_output(proof: bytes) -> bytes:
    """beta = proof_to_hash(pi): the uniform VRF output (64 bytes)."""
    lib = _native_vrf()
    if lib is not None and len(proof) >= 32:
        import ctypes

        out = ctypes.create_string_buffer(64)
        rc = lib.smtpu_vrf_output(proof[:32], out)
        if rc == 0:
            return out.raw
        raise ValueError("invalid vrf proof")
    gamma = _pt_decode(proof[:32])
    if gamma is None:
        raise ValueError("invalid vrf proof")
    cg = _pt_mul(8, gamma)
    return hashlib.sha512(_SUITE + b"\x03" + _pt_encode(cg) + b"\x00").digest()


class VrfVerifier:
    def verify(self, public_key: bytes, alpha: bytes, proof: bytes) -> bool:
        if len(proof) != VRF_PROOF_SIZE or len(public_key) != 32:
            return False
        lib = _native_vrf()
        if lib is not None:
            return bool(lib.smtpu_vrf_verify(public_key, alpha,
                                             len(alpha), proof))
        y = _pt_decode(public_key)
        gamma = _pt_decode(proof[:32])
        if y is None or gamma is None:
            return False
        c = int.from_bytes(proof[32:48], "little")
        s = int.from_bytes(proof[48:80], "little")
        if s >= _Q:
            return False
        h_pt = _hash_to_curve_tai(public_key, alpha)
        # U = s*B - c*Y ; V = s*H - c*Gamma
        neg = lambda p: ((-p[0]) % _P, p[1], p[2], (-p[3]) % _P)  # noqa: E731
        u = _pt_add(_pt_mul(s, _B), _pt_mul(c, neg(y)))
        v = _pt_add(_pt_mul(s, h_pt), _pt_mul(c, neg(gamma)))
        return _challenge([y, h_pt, gamma, u, v]) == c
