"""Canonical binary codec for all wire/storage types.

The reference uses SCALE code-generation for every domain type (reference
codec/codec.go:22-67 wrapping spacemeshos/go-scale). Here the same goals —
deterministic bytes, compact varints, no reflection at encode time — are met
with a small combinator schema: each message type declares a ``FIELDS`` list
of (name, codec) pairs and gets encode/decode/roundtrip for free. Canonical
means: exactly one valid encoding per value (decoders reject non-minimal
varints and trailing bytes at the top level).

Wire grammar:
  u8/u16/u32/u64    little-endian fixed width
  compact           LEB128-like varint, minimal-length enforced
  bytes[N]          fixed-size raw
  bytes             compact length || raw
  str               utf-8 as bytes
  option(C)         0x00 | 0x01 || C
  vec(C)            compact count || items
  struct(T)         nested FIELDS
"""

from __future__ import annotations

import dataclasses
import io
import sys
from typing import Any, Callable


class DecodeError(ValueError):
    pass


class Codec:
    """A pair of (encode into buffer, decode from reader)."""

    def __init__(self, enc: Callable[[io.BytesIO, Any], None],
                 dec: Callable[[io.BufferedReader], Any]):
        self.enc = enc
        self.dec = dec


def _read(r, n: int) -> bytes:
    if n > sys.maxsize:
        # a lying length prefix from the network must reject, not crash:
        # io.BytesIO.read raises OverflowError past index size
        raise DecodeError(f"implausible length {n}")
    b = r.read(n)
    if len(b) != n:
        raise DecodeError(f"unexpected EOF: wanted {n} bytes, got {len(b)}")
    return b


def _uint(width: int) -> Codec:
    def enc(w, v):
        if not 0 <= v < (1 << (8 * width)):
            raise ValueError(f"u{8*width} out of range: {v}")
        w.write(int(v).to_bytes(width, "little"))
    return Codec(enc, lambda r: int.from_bytes(_read(r, width), "little"))


u8 = _uint(1)
u16 = _uint(2)
u32 = _uint(4)
u64 = _uint(8)


def _compact_enc(w, v):
    if v < 0:
        raise ValueError("compact is unsigned")
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            w.write(bytes([b | 0x80]))
        else:
            w.write(bytes([b]))
            return


def _compact_dec(r) -> int:
    shift = 0
    out = 0
    while True:
        b = _read(r, 1)[0]
        out |= (b & 0x7F) << shift
        if not (b & 0x80):
            if b == 0 and shift != 0:
                raise DecodeError("non-minimal compact encoding")
            if shift > 63 or out >= 1 << 64:
                # the shift guard alone leaks values up to ~2^70: the
                # final byte lands at shift 63 with 7 bits of payload
                raise DecodeError("compact overflows u64")
            return out
        shift += 7
        if shift > 63:
            raise DecodeError("compact overflows u64")


compact = Codec(_compact_enc, _compact_dec)


def fixed(n: int) -> Codec:
    def enc(w, v: bytes):
        if len(v) != n:
            raise ValueError(f"expected {n} bytes, got {len(v)}")
        w.write(v)
    return Codec(enc, lambda r: _read(r, n))


def _bytes_enc(w, v: bytes):
    _compact_enc(w, len(v))
    w.write(v)


def _bytes_dec(r) -> bytes:
    return _read(r, _compact_dec(r))


var_bytes = Codec(_bytes_enc, _bytes_dec)

string = Codec(lambda w, v: _bytes_enc(w, v.encode("utf-8")),
               lambda r: _bytes_dec(r).decode("utf-8"))


def _bool_dec(r):
    b = _read(r, 1)[0]
    if b > 1:
        raise DecodeError(f"invalid bool byte {b}")
    return bool(b)


boolean = Codec(lambda w, v: w.write(b"\x01" if v else b"\x00"), _bool_dec)


def option(c: Codec) -> Codec:
    def enc(w, v):
        if v is None:
            w.write(b"\x00")
        else:
            w.write(b"\x01")
            c.enc(w, v)

    def dec(r):
        tag = _read(r, 1)[0]
        if tag == 0:
            return None
        if tag == 1:
            return c.dec(r)
        raise DecodeError(f"invalid option tag {tag}")
    return Codec(enc, dec)


def vec(c: Codec, max_len: int = 1 << 24) -> Codec:
    def enc(w, v):
        if len(v) > max_len:
            raise ValueError(f"vec too long: {len(v)} > {max_len}")
        _compact_enc(w, len(v))
        for item in v:
            c.enc(w, item)

    def dec(r):
        count = _compact_dec(r)
        if count > max_len:
            raise DecodeError(f"vec too long: {count} > {max_len}")
        return [c.dec(r) for _ in range(count)]
    return Codec(enc, dec)


def struct(cls) -> Codec:
    """Codec for a dataclass with a FIELDS schema."""
    def enc(w, v):
        for name, c in cls.FIELDS:
            c.enc(w, getattr(v, name))

    def dec(r):
        kw = {name: c.dec(r) for name, c in cls.FIELDS}
        return cls(**kw)
    return Codec(enc, dec)


def encode(value, codec: Codec | None = None) -> bytes:
    """Encode a value (dataclass with FIELDS, or explicit codec)."""
    c = codec or struct(type(value))
    w = io.BytesIO()
    c.enc(w, value)
    return w.getvalue()


def decode(data: bytes, cls_or_codec) -> Any:
    """Decode; rejects trailing bytes (canonical top-level framing)."""
    c = cls_or_codec if isinstance(cls_or_codec, Codec) else struct(cls_or_codec)
    r = io.BytesIO(data)
    v = c.dec(r)
    rest = r.read(1)
    if rest:
        raise DecodeError("trailing bytes after message")
    return v


def codec_for(cls) -> Codec:
    return struct(cls)


def register(cls):
    """Class decorator: dataclass + cached struct codec + helpers."""
    cls = dataclasses.dataclass(cls)
    c = struct(cls)
    cls.CODEC = c
    cls.to_bytes = lambda self: encode(self, c)
    cls.from_bytes = classmethod(lambda k, data: decode(data, c))
    return cls
