"""Deterministic fixed-point binomial sampling for committee eligibility.

The reference draws an identity's hare seat count from the binomial CDF
over its weight (reference hare3/eligibility/oracle.go:324-375 with the
spacemeshos/fixed package): the identity runs ``n = weight`` Bernoulli
trials at ``p = committee_size / total_weight``; its VRF output supplies a
uniform fraction and the count is the inverse-CDF sample

    x = min { k : BinCDF(n, p, k) > vrf_frac }

so E[count] = committee_size * w_i / W with the true binomial variance —
the committee-size analysis the protocol's safety margins depend on.
The validator recomputes the same x from the same inputs
(oracle.go:297-340: accept iff BinCDF(n,p,x-1) <= vrf_frac < BinCDF(n,p,x),
which is exactly "x equals the sample").

All arithmetic is integer fixed-point at 2**SCALE_BITS so prover and
validator agree bit-for-bit on every platform. Python's big ints make the
intermediate products exact; the only rounding is the explicit >> at each
multiply, identical everywhere.

Deviations from the reference, documented:
- 128 fractional bits (the reference's fixed package uses fewer), so
  (1-p)^n underflows only when the identity's expected seat count exceeds
  ~88 (it would need >11% of total weight at committee 800);
- on that underflow the sample saturates to round(n*p) deterministically
  instead of panicking (oracle.go:311-321 wraps a recover() around it) —
  a whale that deep is eligible with near-certainty either way;
- the scan is capped at 2**16 - 1 matching the reference's uint16 count.
"""

from __future__ import annotations

SCALE_BITS = 128
ONE = 1 << SCALE_BITS
COUNT_CAP = (1 << 16) - 1


def _mul(a: int, b: int) -> int:
    return (a * b) >> SCALE_BITS


def _div(a: int, b: int) -> int:
    return (a << SCALE_BITS) // b


def fixed_pow(base: int, e: int) -> int:
    """base**e by squaring, base in fixed point, e a non-negative int."""
    acc = ONE
    while e:
        if e & 1:
            acc = _mul(acc, base)
        base = _mul(base, base)
        e >>= 1
    return acc


def frac_from_bytes(b: bytes) -> int:
    """First 8 bytes of a VRF output -> uniform fraction in [0, ONE).

    Mirrors the reference's calcVrfFrac (oracle.go:208, fixed.FracFromBytes
    over sig[:8], little-endian)."""
    return int.from_bytes(b[:8], "little") << (SCALE_BITS - 64)


def binomial_count(n: int, p_num: int, p_den: int, frac: int) -> int:
    """Inverse-CDF sample of Binomial(n, p_num/p_den) at ``frac``.

    ``frac`` is fixed-point in [0, ONE). Walks the pmf recurrence
    pmf(k+1) = pmf(k) * (n-k)/(k+1) * p/(1-p) accumulating the CDF until
    it exceeds frac — counts are << 2**16 in practice so the walk is
    short (same shape as the reference's CalcEligibility loop,
    oracle.go:368-375).
    """
    if n <= 0 or p_num <= 0:
        return 0
    if p_num >= p_den:
        return min(n, COUNT_CAP)
    p = _div(p_num, p_den)
    q = ONE - p
    pmf = fixed_pow(q, n)
    if pmf == 0:
        # (1-p)^n underflowed 128 fractional bits: expected count > ~88.
        # Deterministic saturation (documented deviation, see module doc).
        return min((n * p_num + p_den // 2) // p_den, COUNT_CAP)
    cdf = pmf
    x = 0
    while cdf <= frac and x < min(n, COUNT_CAP):
        pmf = _div(_mul(pmf * (n - x), p) // (x + 1), q)
        x += 1
        cdf += pmf
        if pmf == 0 and cdf <= frac:
            # right-tail underflow: every remaining pmf term is below
            # resolution; frac can never be reached. The sample is in the
            # far tail — saturate at the cap the same way both sides.
            return min(n, COUNT_CAP)
    return x


def bin_cdf(n: int, p_num: int, p_den: int, x: int) -> int:
    """BinCDF(n, p, x) in fixed point (test/diagnostic surface)."""
    if x < 0:
        return 0
    if n <= 0 or p_num <= 0:
        return ONE
    if p_num >= p_den:
        return ONE if x >= n else 0
    p = _div(p_num, p_den)
    q = ONE - p
    pmf = fixed_pow(q, n)
    cdf = pmf
    for k in range(min(x, n)):
        pmf = _div(_mul(pmf * (n - k), p) // (k + 1), q)
        cdf += pmf
    return min(cdf, ONE)
