"""BLAKE3 hashing — the framework's canonical hash.

The reference hashes everything with blake3 (reference hash/hash.go:16
`hash.Sum` via zeebo/blake3, with 32- and 20-byte variants). This is an
independent from-spec implementation (IV/rounds/permutation per the BLAKE3
paper: 7-round compression, 1024-byte chunks, binary tree with the
chunk-stack merge rule).

The ONE-SHOT paths (sum256/sum160/keyed — every gossip message id, codec
content id, address and merkle node) dispatch to the native C++ twin
(native/blake3.cpp, ~1000x the pure-Python rate, built on demand and
loaded via ctypes); this module stays the reference implementation,
vector-tested, and the fallback when the toolchain is absent.
``Hasher`` (incremental) is Python-only — it sits on cold paths.
"""

from __future__ import annotations

import ctypes as _ctypes
import struct as _struct

_IV = (0x6A09E667, 0xBB67AE85, 0x3C6EF372, 0xA54FF53A,
       0x510E527F, 0x9B05688C, 0x1F83D9AB, 0x5BE0CD19)

_PERM = (2, 6, 3, 10, 7, 0, 4, 13, 1, 11, 12, 5, 9, 14, 15, 8)

CHUNK_START = 1
CHUNK_END = 2
PARENT = 4
ROOT = 8
KEYED_HASH = 16

_CHUNK_LEN = 1024
_BLOCK_LEN = 64
_MASK = 0xFFFFFFFF


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (32 - n))) & _MASK


def _compress(cv, block_words, counter: int, block_len: int, flags: int):
    s = [cv[0], cv[1], cv[2], cv[3], cv[4], cv[5], cv[6], cv[7],
         _IV[0], _IV[1], _IV[2], _IV[3],
         counter & _MASK, (counter >> 32) & _MASK, block_len, flags]
    m = list(block_words)

    def g(a, b, c, d, mx, my):
        s[a] = (s[a] + s[b] + mx) & _MASK
        s[d] = _rotr(s[d] ^ s[a], 16)
        s[c] = (s[c] + s[d]) & _MASK
        s[b] = _rotr(s[b] ^ s[c], 12)
        s[a] = (s[a] + s[b] + my) & _MASK
        s[d] = _rotr(s[d] ^ s[a], 8)
        s[c] = (s[c] + s[d]) & _MASK
        s[b] = _rotr(s[b] ^ s[c], 7)

    for r in range(7):
        g(0, 4, 8, 12, m[0], m[1])
        g(1, 5, 9, 13, m[2], m[3])
        g(2, 6, 10, 14, m[4], m[5])
        g(3, 7, 11, 15, m[6], m[7])
        g(0, 5, 10, 15, m[8], m[9])
        g(1, 6, 11, 12, m[10], m[11])
        g(2, 7, 8, 13, m[12], m[13])
        g(3, 4, 9, 14, m[14], m[15])
        if r != 6:
            m = [m[_PERM[i]] for i in range(16)]

    return [(s[i] ^ s[i + 8]) & _MASK for i in range(8)] + \
           [(s[i + 8] ^ cv[i]) & _MASK for i in range(8)]


def _words(block: bytes):
    return _struct.unpack("<16I", block)


class _ChunkState:
    __slots__ = ("cv", "chunk_counter", "block", "blocks_compressed", "flags")

    def __init__(self, key, chunk_counter: int, flags: int):
        self.cv = list(key)
        self.chunk_counter = chunk_counter
        self.block = b""
        self.blocks_compressed = 0
        self.flags = flags

    def len(self) -> int:
        return self.blocks_compressed * _BLOCK_LEN + len(self.block)

    def _start_flag(self) -> int:
        return CHUNK_START if self.blocks_compressed == 0 else 0

    def update(self, data: bytes) -> None:
        while data:
            if len(self.block) == _BLOCK_LEN:
                self.cv = _compress(self.cv, _words(self.block),
                                    self.chunk_counter, _BLOCK_LEN,
                                    self.flags | self._start_flag())[:8]
                self.blocks_compressed += 1
                self.block = b""
            take = min(_BLOCK_LEN - len(self.block), len(data))
            self.block += data[:take]
            data = data[take:]

    def output(self):
        block = self.block + b"\x00" * (_BLOCK_LEN - len(self.block))
        return (self.cv, _words(block), self.chunk_counter, len(self.block),
                self.flags | self._start_flag() | CHUNK_END)


def _parent_output(left_cv, right_cv, key, flags):
    return (list(key), tuple(left_cv + right_cv), 0, _BLOCK_LEN,
            flags | PARENT)


class Hasher:
    """Incremental BLAKE3 (unkeyed or 32-byte-keyed)."""

    def __init__(self, key: bytes | None = None):
        if key is None:
            self._key = _IV
            self._flags = 0
        else:
            if len(key) != 32:
                raise ValueError("key must be 32 bytes")
            self._key = _struct.unpack("<8I", key)
            self._flags = KEYED_HASH
        self._chunk = _ChunkState(self._key, 0, self._flags)
        self._stack: list[list[int]] = []
        self._total_chunks = 0

    def update(self, data: bytes) -> "Hasher":
        while data:
            if self._chunk.len() == _CHUNK_LEN:
                cv, words, counter, blen, flags = self._chunk.output()
                chunk_cv = _compress(cv, words, counter, blen, flags)[:8]
                self._push_chunk(chunk_cv)
                self._chunk = _ChunkState(self._key, self._total_chunks,
                                          self._flags)
            take = min(_CHUNK_LEN - self._chunk.len(), len(data))
            self._chunk.update(data[:take])
            data = data[take:]
        return self

    def _push_chunk(self, cv) -> None:
        self._total_chunks += 1
        total = self._total_chunks
        while total & 1 == 0:
            left = self._stack.pop()
            cv = _compress(*_parent_output(left, cv, self._key,
                                           self._flags))[:8]
            total >>= 1
        self._stack.append(cv)

    def digest(self, length: int = 32) -> bytes:
        # fold the stack right-to-left over the final (possibly partial) chunk
        out = self._chunk.output()
        for left in reversed(self._stack):
            cv = _compress(*out)[:8]
            out = _parent_output(left, cv, self._key, self._flags)
        cv, words, counter, blen, flags = out
        result = b""
        block_counter = 0
        while len(result) < length:
            wide = _compress(cv, words, block_counter, blen, flags | ROOT)
            result += _struct.pack("<16I", *wide)
            block_counter += 1
        return result[:length]

    def hexdigest(self, length: int = 32) -> str:
        return self.digest(length).hex()


# --- native dispatch -------------------------------------------------------

_native = None


def _load_native():
    global _native
    if _native is not None:
        return _native
    try:
        from .. import native as _native_mod

        lib = _native_mod.load("blake3")
    except Exception:  # pragma: no cover — packaging edge
        lib = None
    if lib is not None:
        lib.smtpu_blake3.argtypes = [
            _ctypes.c_char_p, _ctypes.c_size_t, _ctypes.c_char_p,
            _ctypes.c_char_p, _ctypes.c_size_t]
        lib.smtpu_blake3.restype = None
    _native = lib if lib is not None else False
    return _native


def _hash_oneshot(data: bytes, key: bytes | None, length: int) -> bytes:
    lib = _load_native()
    if lib:
        out = _ctypes.create_string_buffer(length)
        lib.smtpu_blake3(data, len(data), key, out, length)
        return out.raw
    h = Hasher(key=key)
    h.update(data)
    return h.digest(length)


def sum256(*chunks: bytes) -> bytes:
    """32-byte hash of the concatenation (reference hash.Sum)."""
    return _hash_oneshot(b"".join(chunks), None, 32)


def sum160(*chunks: bytes) -> bytes:
    """20-byte truncated hash (reference hash/hash.go Sum20 for addresses)."""
    return _hash_oneshot(b"".join(chunks), None, 20)


def keyed(key: bytes, *chunks: bytes) -> bytes:
    if len(key) != 32:
        raise ValueError("key must be 32 bytes")
    return _hash_oneshot(b"".join(chunks), key, 32)
