"""Core primitives: canonical codec, hashing, signing, domain types.

The layer-0 of SURVEY.md §1 (reference codec/, hash/, signing/,
common/types/): everything above — storage, consensus, networking, the VM —
speaks these types and their canonical byte encodings.
"""
