"""Domain types — the vocabulary every layer above speaks.

Mirrors the reference's common/types (reference common/types/activation.go,
ballot.go, block.go, transaction.go, poet.go, address.go, layer.go,
epoch.go, nodeid.go): 32-byte content ids computed as blake3 of the
canonical encoding, u32 layer/epoch ordinals, 24-byte bech32 addresses.
All wire structs declare codec FIELDS (core/codec.py) and get canonical
bytes + ids from them.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from . import codec
from .codec import compact, fixed, option, string, u8, u16, u32, u64, var_bytes, vec
from .hashing import sum160, sum256

HASH32 = fixed(32)
HASH20 = fixed(20)
SIG = fixed(64)
VRF_SIG = fixed(80)

EMPTY32 = bytes(32)

ADDRESS_SIZE = 24
ADDRESS = fixed(ADDRESS_SIZE)


# --- layers and epochs -----------------------------------------------------


class LayerID(int):
    """Layer ordinal (u32). Plain int subclass: arithmetic stays natural."""

    def epoch(self, layers_per_epoch: int) -> int:
        return self // layers_per_epoch

    def first_in_epoch(self, layers_per_epoch: int) -> bool:
        return self % layers_per_epoch == 0


def epoch_first_layer(epoch: int, layers_per_epoch: int) -> LayerID:
    return LayerID(epoch * layers_per_epoch)


# --- bech32 addresses ------------------------------------------------------

_B32 = "qpzry9x8gf2tvdw0s3jn54khce6mua7l"


def _bech32_polymod(values):
    gen = (0x3B6A57B2, 0x26508E6D, 0x1EA119FA, 0x3D4233DD, 0x2A1462B3)
    chk = 1
    for v in values:
        top = chk >> 25
        chk = ((chk & 0x1FFFFFF) << 5) ^ v
        for i in range(5):
            chk ^= gen[i] if ((top >> i) & 1) else 0
    return chk


def _hrp_expand(hrp):
    return [ord(c) >> 5 for c in hrp] + [0] + [ord(c) & 31 for c in hrp]


def _to5(data: bytes):
    acc = bits = 0
    out = []
    for b in data:
        acc = (acc << 8) | b
        bits += 8
        while bits >= 5:
            bits -= 5
            out.append((acc >> bits) & 31)
    if bits:
        out.append((acc << (5 - bits)) & 31)
    return out


def _from5(data):
    acc = bits = 0
    out = bytearray()
    for v in data:
        acc = (acc << 5) | v
        bits += 5
        while bits >= 8:
            bits -= 8
            out.append((acc >> bits) & 0xFF)
    return bytes(out)


class Address:
    """24-byte account address, rendered bech32 with a network HRP
    (reference common/types/address.go)."""

    __slots__ = ("raw",)
    HRP = "sm"

    def __init__(self, raw: bytes):
        if len(raw) != ADDRESS_SIZE:
            raise ValueError(f"address must be {ADDRESS_SIZE} bytes")
        self.raw = bytes(raw)

    @classmethod
    def from_public_key(cls, template: bytes, *args: bytes) -> "Address":
        # principal address = 4 zero bytes || last 20 bytes of
        # blake3(template || spawn args) — stable across networks
        return cls(bytes(4) + sum160(template, *args))

    def encode(self, hrp: str | None = None) -> str:
        hrp = hrp or self.HRP
        data = _to5(self.raw)
        values = _hrp_expand(hrp) + data
        poly = _bech32_polymod(values + [0] * 6) ^ 1
        checksum = [(poly >> 5 * (5 - i)) & 31 for i in range(6)]
        return hrp + "1" + "".join(_B32[d] for d in data + checksum)

    @classmethod
    def decode(cls, s: str) -> "Address":
        pos = s.rfind("1")
        if pos < 1:
            raise ValueError("invalid bech32 address")
        hrp, rest = s[:pos], s[pos + 1:]
        try:
            data = [_B32.index(c) for c in rest.lower()]
        except ValueError as e:
            raise ValueError("invalid bech32 character") from e
        if _bech32_polymod(_hrp_expand(hrp) + data) != 1:
            raise ValueError("bad bech32 checksum")
        raw = _from5(data[:-6])
        if len(raw) != ADDRESS_SIZE:
            raise ValueError("bad address payload length")
        return cls(raw)

    def __eq__(self, other):
        return isinstance(other, Address) and self.raw == other.raw

    def __hash__(self):
        return hash(self.raw)

    def __repr__(self):
        return f"Address({self.encode()})"


addr_codec = codec.Codec(
    lambda w, v: w.write(v.raw),
    lambda r: Address(codec._read(r, ADDRESS_SIZE)))


# --- POST / NIPoST wire types ---------------------------------------------


@codec.register
class Post:
    """The space proof (reference common/types/poet.go Post)."""

    nonce: int
    indices: list[int]
    pow_nonce: int

    FIELDS = [("nonce", u32), ("indices", vec(compact, 1 << 12)),
              ("pow_nonce", u64)]


@codec.register
class PostMetadataWire:
    challenge: bytes
    labels_per_unit: int

    FIELDS = [("challenge", HASH32), ("labels_per_unit", u64)]


@codec.register
class MerkleProof:
    leaf_index: int
    nodes: list[bytes]

    FIELDS = [("leaf_index", u64), ("nodes", vec(HASH32, 64))]


@codec.register
class NIPost:
    """Non-interactive PoST: membership in a poet round + space proof
    (reference common/types/activation.go NIPost)."""

    membership: MerkleProof
    post: Post
    post_metadata: PostMetadataWire

    FIELDS = [("membership", codec.struct(MerkleProof)),
              ("post", codec.struct(Post)),
              ("post_metadata", codec.struct(PostMetadataWire))]


@codec.register
class PoetProof:
    """Poet round proof: merkle root over members + tick count
    (reference common/types/poet.go PoetProofMessage, simplified: the poet
    statement is the root; members prove inclusion via MerkleProof)."""

    poet_id: bytes
    round_id: str
    root: bytes
    ticks: int

    FIELDS = [("poet_id", HASH32), ("round_id", string),
              ("root", HASH32), ("ticks", u64)]

    @property
    def id(self) -> bytes:
        return sum256(self.to_bytes())


# --- activation (ATX) ------------------------------------------------------


@codec.register
class ActivationTx:
    """ATX: one identity's per-epoch commitment of space
    (reference common/types/activation.go, wire activation/wire/wire_v1.go).
    """

    publish_epoch: int
    prev_atx: bytes              # EMPTY32 for initial
    pos_atx: bytes               # positioning ATX
    commitment_atx: Optional[bytes]   # set on initial ATX only
    initial_post: Optional[Post]      # set on initial ATX only
    nipost: NIPost
    num_units: int
    vrf_nonce: int
    vrf_public_key: bytes        # ECVRF key for eligibility proofs
    coinbase: bytes              # Address.raw
    node_id: bytes               # smesher public key
    signature: bytes

    FIELDS = [
        ("publish_epoch", u32),
        ("prev_atx", HASH32),
        ("pos_atx", HASH32),
        ("commitment_atx", option(HASH32)),
        ("initial_post", option(codec.struct(Post))),
        ("nipost", codec.struct(NIPost)),
        ("num_units", u32),
        ("vrf_nonce", u64),
        ("vrf_public_key", HASH32),
        ("coinbase", ADDRESS),
        ("node_id", HASH32),
        ("signature", SIG),
    ]

    def signed_bytes(self) -> bytes:
        clone = dataclasses.replace(self, signature=bytes(64))
        return clone.to_bytes()

    @property
    def id(self) -> bytes:
        return sum256(self.to_bytes())

    def target_epoch(self) -> int:
        return self.publish_epoch + 1


@codec.register
class MarriageCert:
    """Partner's consent to join the signer's equivocation set
    (reference activation/wire/wire_v2.go:198 MarriageCertificate):
    ``signature`` is the partner's ed25519 over
    Domain.ATX || "marry" || primary node id."""

    partner_id: bytes
    signature: bytes

    FIELDS = [("partner_id", HASH32), ("signature", SIG)]

    @staticmethod
    def message(primary_id: bytes) -> bytes:
        return b"marry" + primary_id


@codec.register
class SubPostV2:
    """One married identity's contribution inside a merged ATX
    (reference activation/wire/wire_v2.go:227 SubPostV2)."""

    node_id: bytes
    prev_atx: bytes              # EMPTY32 for initial
    num_units: int
    vrf_nonce: int
    nipost: NIPost

    FIELDS = [("node_id", HASH32), ("prev_atx", HASH32),
              ("num_units", u32), ("vrf_nonce", u64),
              ("nipost", codec.struct(NIPost))]


@codec.register
class ActivationTxV2:
    """Merged / multi-identity ATX (reference activation/wire/wire_v2.go:17
    ActivationTxV2): one envelope signed by the primary identity carries a
    SubPost per married identity plus the marriage certificates binding
    them into one equivocation set."""

    publish_epoch: int
    pos_atx: bytes
    coinbase: bytes
    marriages: list[MarriageCert]
    subposts: list[SubPostV2]
    node_id: bytes               # primary (envelope signer)
    signature: bytes

    FIELDS = [
        ("publish_epoch", u32),
        ("pos_atx", HASH32),
        ("coinbase", ADDRESS),
        ("marriages", vec(codec.struct(MarriageCert), 256)),
        ("subposts", vec(codec.struct(SubPostV2), 256)),
        ("node_id", HASH32),
        ("signature", SIG),
    ]

    def signed_bytes(self) -> bytes:
        clone = dataclasses.replace(self, signature=bytes(64))
        return clone.to_bytes()

    @property
    def id(self) -> bytes:
        return sum256(self.to_bytes())

    def target_epoch(self) -> int:
        return self.publish_epoch + 1

    def identity_atx_id(self, node_id: bytes) -> bytes:
        """Per-identity synthetic ATX id: merged ATXs still give each
        identity its own id for eligibility/cache keying."""
        return sum256(self.id, node_id)


# --- ballots / proposals / blocks -----------------------------------------


@codec.register
class EpochData:
    """First-ballot-of-epoch payload: beacon + active set root
    (reference common/types/ballot.go EpochData)."""

    beacon: bytes
    active_set_root: bytes
    eligibility_count: int

    FIELDS = [("beacon", fixed(4)), ("active_set_root", HASH32),
              ("eligibility_count", u16)]


@codec.register
class VotingEligibility:
    """VRF eligibility proof for one proposal slot
    (reference common/types/ballot.go VotingEligibility)."""

    j: int
    sig: bytes

    FIELDS = [("j", u32), ("sig", VRF_SIG)]


@codec.register
class Opinion:
    """Votes relative to a base ballot (reference common/types/ballot.go
    Votes): support/against lists of block ids, abstained layers."""

    base: bytes
    support: list[bytes]
    against: list[bytes]
    abstain: list[int]

    FIELDS = [("base", HASH32), ("support", vec(HASH32)),
              ("against", vec(HASH32)), ("abstain", vec(u32))]


@codec.register
class Ballot:
    layer: int
    atx_id: bytes
    epoch_data: Optional[EpochData]
    ref_ballot: bytes            # EMPTY32 when epoch_data present
    eligibilities: list[VotingEligibility]
    opinion: Opinion
    node_id: bytes
    signature: bytes

    FIELDS = [
        ("layer", u32),
        ("atx_id", HASH32),
        ("epoch_data", option(codec.struct(EpochData))),
        ("ref_ballot", HASH32),
        ("eligibilities", vec(codec.struct(VotingEligibility), 1 << 10)),
        ("opinion", codec.struct(Opinion)),
        ("node_id", HASH32),
        ("signature", SIG),
    ]

    def signed_bytes(self) -> bytes:
        return dataclasses.replace(self, signature=bytes(64)).to_bytes()

    @property
    def id(self) -> bytes:
        return sum256(self.to_bytes())


@codec.register
class Proposal:
    """Per-layer proposal: a ballot plus the proposed tx ids
    (reference common/types/block.go Proposal = Ballot + TxIDs + mesh hash,
    carrying its own signature over the whole thing so a relayer cannot
    re-attach different tx_ids to an honest ballot)."""

    ballot: Ballot
    tx_ids: list[bytes]
    mesh_hash: bytes
    signature: bytes

    FIELDS = [("ballot", codec.struct(Ballot)), ("tx_ids", vec(HASH32)),
              ("mesh_hash", HASH32), ("signature", SIG)]

    def signed_bytes(self) -> bytes:
        return dataclasses.replace(self, signature=bytes(64)).to_bytes()

    @property
    def id(self) -> bytes:
        return sum256(self.to_bytes())


@codec.register
class Reward:
    """Block reward entry (reference common/types/block.go AnyReward:
    {ATXID, Weight}; coinbase carried too since our apply path pays it
    directly rather than re-resolving the ATX)."""

    atx_id: bytes
    coinbase: bytes
    weight: int

    FIELDS = [("atx_id", HASH32), ("coinbase", ADDRESS), ("weight", u64)]


@codec.register
class Block:
    """The per-layer agreed block (reference common/types/block.go)."""

    layer: int
    tick_height: int
    rewards: list[Reward]
    tx_ids: list[bytes]

    FIELDS = [("layer", u32), ("tick_height", u64),
              ("rewards", vec(codec.struct(Reward), 1 << 12)),
              ("tx_ids", vec(HASH32, 1 << 16))]

    @property
    def id(self) -> bytes:
        return sum256(self.to_bytes())


@codec.register
class CertifyMessage:
    layer: int
    block_id: bytes
    eligibility_count: int
    proof: bytes                 # VRF proof of certifier eligibility
    atx_id: bytes                # the ATX backing the eligibility claim
    node_id: bytes
    signature: bytes

    FIELDS = [("layer", u32), ("block_id", HASH32),
              ("eligibility_count", u16), ("proof", VRF_SIG),
              ("atx_id", HASH32), ("node_id", HASH32), ("signature", SIG)]

    def signed_bytes(self) -> bytes:
        return dataclasses.replace(self, signature=bytes(64)).to_bytes()


@codec.register
class Certificate:
    """Post-hare block certificate (reference blocks/certifier.go):
    aggregated eligibility-weighted signatures over the hare output."""

    block_id: bytes
    signatures: list[CertifyMessage]

    FIELDS = [("block_id", HASH32),
              ("signatures", vec(codec.struct(CertifyMessage), 1 << 11))]


# --- transactions ----------------------------------------------------------


@codec.register
class Transaction:
    """Raw signed transaction; parsing/validation is the VM's job
    (reference common/types/transaction.go keeps raw + parsed cache)."""

    raw: bytes

    FIELDS = [("raw", var_bytes)]

    @property
    def id(self) -> bytes:
        return sum256(self.raw)


@codec.register
class TransactionResult:
    status: int            # 0 success, 1 failure (invalid nonce/balance...)
    message: str
    gas_consumed: int
    fee: int
    layer: int
    block: bytes

    FIELDS = [("status", u8), ("message", string), ("gas_consumed", u64),
              ("fee", u64), ("layer", u32), ("block", HASH32)]


# --- malfeasance -----------------------------------------------------------


@codec.register
class MalfeasanceProof:
    """Two conflicting signed messages from one identity
    (reference malfeasance/wire: MultipleATXs / MultipleBallots /
    HareEquivocation; domain says which)."""

    domain: int
    msg1: bytes
    sig1: bytes
    msg2: bytes
    sig2: bytes
    node_id: bytes

    FIELDS = [("domain", u8), ("msg1", var_bytes), ("sig1", SIG),
              ("msg2", var_bytes), ("sig2", SIG), ("node_id", HASH32)]

    @property
    def id(self) -> bytes:
        return sum256(self.to_bytes())
