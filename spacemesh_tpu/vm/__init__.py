"""Deterministic account-template VM (reference genvm/: no user bytecode,
a fixed registry of account templates — wallet, multisig, vesting, vault —
with spawn/spend transaction lifecycle, nonces, gas, and a running state
root over account updates)."""

from .vm import VM, TxValidity  # noqa: F401
