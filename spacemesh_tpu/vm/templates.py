"""Account templates: wallet, multisig, vesting, vault.

Mirrors the reference's template registry (reference genvm/vm.go:68-74
registers wallet/multisig/vesting/vault from genvm/templates/). A template
defines: spawn-argument parsing, the principal address derivation, spend
authorization (signature scheme), and any template-specific spend rules
(vesting schedule, vault drip).

Template addresses are well-known 24-byte constants (index in the last
byte), as in the reference's core.Address template handles.
"""

from __future__ import annotations

import dataclasses

from ..core import codec
from ..core.codec import fixed, u8, u32, u64, vec
from ..core.signing import EdVerifier
from ..core.types import ADDRESS_SIZE, Address

WALLET = bytes(23) + b"\x01"
MULTISIG = bytes(23) + b"\x02"
VESTING = bytes(23) + b"\x03"
VAULT = bytes(23) + b"\x04"


@codec.register
class WalletSpawnArgs:
    public_key: bytes
    FIELDS = [("public_key", fixed(32))]


@codec.register
class MultisigSpawnArgs:
    required: int
    public_keys: list[bytes]
    FIELDS = [("required", u8), ("public_keys", vec(fixed(32), 10))]


@codec.register
class VaultSpawnArgs:
    owner: bytes                  # controlling (vesting) account address
    total_amount: int
    initial_unlock: int
    vesting_start: int            # layer
    vesting_end: int              # layer
    FIELDS = [("owner", fixed(ADDRESS_SIZE)), ("total_amount", u64),
              ("initial_unlock", u64), ("vesting_start", u32),
              ("vesting_end", u32)]


class TemplateError(ValueError):
    pass


class BaseTemplate:
    address: bytes
    name: str

    def principal(self, spawn_args: bytes) -> Address:
        return Address.from_public_key(self.address, spawn_args)

    def parse_spawn(self, args: bytes):
        raise NotImplementedError

    def authorize(self, state: bytes, verifier: EdVerifier, domain,
                  msg: bytes, sigs: list[bytes]) -> bool:
        raise NotImplementedError

    def base_gas(self) -> int:
        return 100


class WalletTemplate(BaseTemplate):
    """Single-signature account (reference genvm/templates/wallet)."""

    address = WALLET
    name = "wallet"

    def parse_spawn(self, args: bytes) -> bytes:
        WalletSpawnArgs.from_bytes(args)  # validates
        return args

    def authorize(self, state, verifier, domain, msg, sigs) -> bool:
        if len(sigs) != 1:
            return False
        pk = WalletSpawnArgs.from_bytes(state).public_key
        return verifier.verify(domain, pk, msg, sigs[0])


class MultisigTemplate(BaseTemplate):
    """k-of-n ed25519 (reference genvm/templates/multisig)."""

    address = MULTISIG
    name = "multisig"

    def parse_spawn(self, args: bytes) -> bytes:
        a = MultisigSpawnArgs.from_bytes(args)
        if not (1 <= a.required <= len(a.public_keys) <= 10):
            raise TemplateError("invalid multisig spawn: k-of-n out of range")
        if len(set(a.public_keys)) != len(a.public_keys):
            raise TemplateError("duplicate multisig keys")
        return args

    def authorize(self, state, verifier, domain, msg, sigs) -> bool:
        a = MultisigSpawnArgs.from_bytes(state)
        if len(sigs) < a.required:
            return False
        used = set()
        good = 0
        for sig in sigs:
            for i, pk in enumerate(a.public_keys):
                if i in used:
                    continue
                if verifier.verify(domain, pk, msg, sig):
                    used.add(i)
                    good += 1
                    break
        return good >= a.required

    def base_gas(self) -> int:
        return 300


class VestingTemplate(MultisigTemplate):
    """Multisig that can additionally drain a vault on schedule
    (reference genvm/templates/vesting — multisig + DrainVault method)."""

    address = VESTING
    name = "vesting"


class VaultTemplate(BaseTemplate):
    """Time-locked funds, spendable only by the owner account up to the
    vested amount (reference genvm/templates/vault)."""

    address = VAULT
    name = "vault"

    def parse_spawn(self, args: bytes) -> bytes:
        a = VaultSpawnArgs.from_bytes(args)
        if a.vesting_end < a.vesting_start:
            raise TemplateError("vault vesting_end before vesting_start")
        if a.initial_unlock > a.total_amount:
            raise TemplateError("vault initial unlock exceeds total")
        return args

    def authorize(self, state, verifier, domain, msg, sigs) -> bool:
        # a vault has no keys: spends happen only via the owner's
        # DrainVault, authorized against the OWNER account (vm.py)
        return False

    @staticmethod
    def vested(args: VaultSpawnArgs, layer: int) -> int:
        if layer < args.vesting_start:
            return 0
        if layer >= args.vesting_end:
            return args.total_amount
        span = args.vesting_end - args.vesting_start
        linear = (args.total_amount - args.initial_unlock) * (
            layer - args.vesting_start) // span
        return args.initial_unlock + linear


REGISTRY: dict[bytes, BaseTemplate] = {
    t.address: t for t in (WalletTemplate(), MultisigTemplate(),
                           VestingTemplate(), VaultTemplate())
}
