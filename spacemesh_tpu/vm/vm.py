"""The VM: parse -> verify -> execute -> rewards, one sql tx per layer.

Mirrors the reference's genvm (reference genvm/vm.go:192-291 Apply:
executes a block's transactions against layered account state, writes
accounts + receipts in one transaction, maintains a sequential blake3
state root; :124 Revert). Methods: SPAWN (instantiate a template into a
principal account), SPEND (transfer), DRAIN_VAULT (owner-authorized vault
withdrawal). Gas = base template cost + per-byte cost; fee = gas *
gas_price, burned from the principal.

Transaction wire format (this framework's own; the reference uses
scale-encoded athena txs):

  TxBody{principal, method u8, template(spawn only), nonce u64,
         gas_price u64, payload bytes, sigs vec<sig64>}
  signed message = genesis_prefix || domain(TX) || body-without-sigs
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from ..core import codec
from ..core.codec import fixed, option, u8, u64, var_bytes, vec
from ..core.hashing import sum256
from ..core.signing import Domain, EdVerifier
from ..core.types import ADDRESS_SIZE, Address, Reward, Transaction, TransactionResult
from ..storage import transactions as txstore
from ..storage.db import Database
from . import templates as T

GAS_PER_BYTE = 1
BASE_REWARD = 50_000_000_000  # per-layer issuance before fees (smidge)


class Method(enum.IntEnum):
    SPAWN = 0
    SPEND = 1
    DRAIN_VAULT = 2


class TxValidity(enum.IntEnum):
    VALID = 0
    INVALID_NONCE = 1
    INSUFFICIENT_FUNDS = 2
    BAD_SIGNATURE = 3
    MALFORMED = 4
    NOT_SPAWNED = 5


@codec.register
class SpendPayload:
    destination: bytes
    amount: int
    FIELDS = [("destination", fixed(ADDRESS_SIZE)), ("amount", u64)]


@codec.register
class DrainPayload:
    vault: bytes
    destination: bytes
    amount: int
    FIELDS = [("vault", fixed(ADDRESS_SIZE)),
              ("destination", fixed(ADDRESS_SIZE)), ("amount", u64)]


@codec.register
class TxBody:
    principal: bytes
    method: int
    template: Optional[bytes]
    nonce: int
    gas_price: int
    payload: bytes
    sigs: list[bytes]

    FIELDS = [("principal", fixed(ADDRESS_SIZE)), ("method", u8),
              ("template", option(fixed(ADDRESS_SIZE))), ("nonce", u64),
              ("gas_price", u64), ("payload", var_bytes),
              ("sigs", vec(fixed(64), 10))]

    def unsigned_bytes(self) -> bytes:
        return dataclasses.replace(self, sigs=[]).to_bytes()


@dataclasses.dataclass
class Account:
    address: bytes
    balance: int = 0
    next_nonce: int = 0
    template: bytes | None = None
    state: bytes | None = None


class Staged:
    """Layered read-through cache over the accounts table
    (reference genvm/core/staged_cache.go)."""

    def __init__(self, db: Database):
        self.db = db
        self.cache: dict[bytes, Account] = {}
        self.touched: set[bytes] = set()

    def get(self, address: bytes) -> Account:
        if address not in self.cache:
            row = txstore.account(self.db, address)
            if row is None:
                self.cache[address] = Account(address=address)
            else:
                self.cache[address] = Account(
                    address=address, balance=row["balance"],
                    next_nonce=row["next_nonce"], template=row["template"],
                    state=row["state"])
        return self.cache[address]

    def touch(self, address: bytes) -> Account:
        self.touched.add(address)
        return self.get(address)


class VM:
    """One instance per node; Apply is called by the mesh executor."""

    def __init__(self, db: Database, verifier: EdVerifier):
        self.db = db
        self.verifier = verifier

    # --- parsing / syntactic validation (used by mempool too) ---------

    def parse(self, tx: Transaction) -> TxBody | None:
        try:
            return TxBody.from_bytes(tx.raw)
        except (codec.DecodeError, ValueError):
            return None

    def validate(self, body: TxBody, *, check_sig: bool = True
                 ) -> TxValidity:
        """Syntactic + signature validation against CURRENT state."""
        staged = Staged(self.db)
        return self._check(staged, body, check_sig=check_sig)

    def _check(self, staged: Staged, body: TxBody, *, check_sig: bool,
               layer: int | None = None) -> TxValidity:
        acct = staged.get(body.principal)
        if body.method == Method.SPAWN:
            if body.template not in T.REGISTRY:
                return TxValidity.MALFORMED
            tmpl = T.REGISTRY[body.template]
            try:
                tmpl.parse_spawn(body.payload)
            except (ValueError, codec.DecodeError):
                return TxValidity.MALFORMED
            if tmpl.principal(body.payload).raw != body.principal:
                return TxValidity.MALFORMED
            if acct.template is not None:
                return TxValidity.MALFORMED  # already spawned
            if check_sig and body.template != T.VAULT:
                if not tmpl.authorize(body.payload, self.verifier, Domain.TX,
                                      self._msg(body), body.sigs):
                    return TxValidity.BAD_SIGNATURE
        else:
            if acct.template is None:
                return TxValidity.NOT_SPAWNED
            tmpl = T.REGISTRY.get(acct.template)
            if tmpl is None:
                return TxValidity.MALFORMED
            try:
                if body.method == Method.SPEND:
                    SpendPayload.from_bytes(body.payload)
                elif body.method == Method.DRAIN_VAULT:
                    DrainPayload.from_bytes(body.payload)
                else:
                    return TxValidity.MALFORMED
            except (codec.DecodeError, ValueError):
                return TxValidity.MALFORMED
            if check_sig and not tmpl.authorize(
                    acct.state, self.verifier, Domain.TX,
                    self._msg(body), body.sigs):
                return TxValidity.BAD_SIGNATURE
        if body.nonce != acct.next_nonce:
            return TxValidity.INVALID_NONCE
        return TxValidity.VALID

    def _msg(self, body: TxBody) -> bytes:
        return body.unsigned_bytes()

    def gas(self, body: TxBody) -> int:
        base = 100
        if body.method == Method.SPAWN and body.template in T.REGISTRY:
            base = T.REGISTRY[body.template].base_gas()
        return base + GAS_PER_BYTE * len(body.payload)

    def apply_genesis(self, allocations: dict[bytes, int]) -> bytes:
        """Fund genesis accounts (reference config/mainnet.go:91-190 bakes
        genesis accounts; vaults are funded with their total_amount)."""
        with self.db.tx():
            staged = Staged(self.db)
            for addr, amount in allocations.items():
                staged.touch(addr).balance = amount
            from ..storage import layers as layerstore
            root = self._persist(staged, 0)
            layerstore.set_applied(self.db, 0, bytes(32), root)
            return root

    # --- execution ----------------------------------------------------

    def apply(self, layer: int, block_id: bytes, txs: list[Transaction],
              rewards: list[Reward]) -> tuple[list[TransactionResult], bytes]:
        """Execute a block. Returns per-tx results + new state root.
        Everything commits in one sql transaction."""
        with self.db.tx():
            staged = Staged(self.db)
            results: list[TransactionResult] = []
            fees = 0
            for tx in txs:
                res = self._exec_one(staged, layer, block_id, tx)
                fees += res.fee
                results.append(res)
                txstore.add_tx(self.db, tx)  # ensure presence
                txstore.set_result(self.db, tx.id, layer, block_id, res)

            total_weight = sum(r.weight for r in rewards) or 1
            pot = BASE_REWARD + fees
            # rewards are keyed per ATX on the wire (AnyReward); a multi-
            # identity smesher repeats one coinbase, and the ledger row is
            # per (coinbase, layer) — aggregate BEFORE writing or the
            # upsert clobbers earlier shares
            per_coinbase: dict[bytes, tuple[int, int]] = {}
            for r in rewards:
                share = pot * r.weight // total_weight
                base = BASE_REWARD * r.weight // total_weight
                acct = staged.touch(bytes(r.coinbase))
                acct.balance += share
                tot, lay = per_coinbase.get(bytes(r.coinbase), (0, 0))
                per_coinbase[bytes(r.coinbase)] = (tot + share, lay + base)
            from ..storage.misc import add_reward
            for coinbase, (share, base) in per_coinbase.items():
                add_reward(self.db, coinbase, layer, share, base)

            state_root = self._persist(staged, layer)
            return results, state_root

    def _exec_one(self, staged: Staged, layer: int, block_id: bytes,
                  tx: Transaction) -> TransactionResult:
        def fail(status: TxValidity, msg: str, gas=0, fee=0):
            return TransactionResult(status=int(status), message=msg,
                                     gas_consumed=gas, fee=fee, layer=layer,
                                     block=block_id)

        body = self.parse(tx)
        if body is None:
            return fail(TxValidity.MALFORMED, "undecodable")
        validity = self._check(staged, body, check_sig=True, layer=layer)
        if validity != TxValidity.VALID:
            return fail(validity, validity.name.lower())

        gas = self.gas(body)
        fee = gas * body.gas_price
        principal = staged.touch(body.principal)
        if principal.balance < fee:
            return fail(TxValidity.INSUFFICIENT_FUNDS, "cannot cover fee")
        principal.balance -= fee
        principal.next_nonce = body.nonce + 1

        if body.method == Method.SPAWN:
            principal.template = body.template
            principal.state = body.payload
        elif body.method == Method.SPEND:
            p = SpendPayload.from_bytes(body.payload)
            if principal.balance < p.amount:
                return fail(TxValidity.INSUFFICIENT_FUNDS,
                            "balance below amount", gas, fee)
            principal.balance -= p.amount
            staged.touch(p.destination).balance += p.amount
        elif body.method == Method.DRAIN_VAULT:
            p = DrainPayload.from_bytes(body.payload)
            vault = staged.touch(p.vault)
            if vault.template != T.VAULT:
                return fail(TxValidity.MALFORMED, "not a vault", gas, fee)
            args = T.VaultSpawnArgs.from_bytes(vault.state)
            if args.owner != body.principal:
                return fail(TxValidity.BAD_SIGNATURE, "not vault owner",
                            gas, fee)
            vested = T.VaultTemplate.vested(args, layer)
            drained = args.total_amount - vault.balance
            available = min(vault.balance, max(vested - drained, 0))
            if p.amount > available:
                return fail(TxValidity.INSUFFICIENT_FUNDS,
                            "exceeds vested amount", gas, fee)
            vault.balance -= p.amount
            staged.touch(p.destination).balance += p.amount

        return TransactionResult(status=int(TxValidity.VALID), message="",
                                 gas_consumed=gas, fee=fee, layer=layer,
                                 block=block_id)

    def _persist(self, staged: Staged, layer: int) -> bytes:
        """Write touched accounts; state root = blake3 chain over the
        previous root and sorted account updates (reference genvm/vm.go
        updateStateHash)."""
        from ..storage import layers as layerstore
        prev = layerstore.state_hash(self.db, layer - 1) or bytes(32)
        root = prev
        for addr in sorted(staged.touched):
            acct = staged.cache[addr]
            txstore.update_account(
                self.db, addr, layer, acct.balance, acct.next_nonce,
                acct.template, acct.state)
            # template + state must be committed too: two states differing
            # only in spawned template or template args (e.g. vault owner)
            # must not share a root (ADVICE r1)
            # variable-length fields are length-prefixed: template/state
            # boundary shifts must change the root (ADVICE r2)
            template = acct.template or b""
            state = acct.state or b""
            root = sum256(root, addr,
                          acct.balance.to_bytes(8, "little"),
                          acct.next_nonce.to_bytes(8, "little"),
                          len(template).to_bytes(4, "little"), template,
                          len(state).to_bytes(4, "little"), state)
        return root

    def revert(self, to_layer: int) -> None:
        """Drop account state above ``to_layer`` (reference genvm/vm.go:124)."""
        with self.db.tx():
            txstore.revert_accounts_above(self.db, to_layer)

    def state_root(self, layer: int) -> bytes | None:
        from ..storage import layers as layerstore
        return layerstore.state_hash(self.db, layer)
