"""Transaction-building SDK (reference genvm/sdk: used by tests, the
genesis generator, and wallets to assemble signed txs)."""

from __future__ import annotations

from ..core import codec
from ..core.signing import Domain, EdSigner
from ..core.types import Address, Transaction
from . import templates as T
from .vm import DrainPayload, Method, SpendPayload, TxBody


def wallet_address(public_key: bytes) -> Address:
    args = codec.encode(T.WalletSpawnArgs(public_key=public_key))
    return T.REGISTRY[T.WALLET].principal(args)


def multisig_address(required: int, public_keys: list[bytes]) -> Address:
    args = codec.encode(T.MultisigSpawnArgs(required=required,
                                            public_keys=public_keys))
    return T.REGISTRY[T.MULTISIG].principal(args)


def vault_address(args: T.VaultSpawnArgs) -> Address:
    return T.REGISTRY[T.VAULT].principal(args.to_bytes())


def _finish(body: TxBody, signers: list[EdSigner]) -> Transaction:
    msg = body.unsigned_bytes()
    body.sigs = [s.sign(Domain.TX, msg) for s in signers]
    return Transaction(raw=body.to_bytes())


def spawn_wallet(signer: EdSigner, nonce: int = 0, gas_price: int = 1
                 ) -> Transaction:
    args = codec.encode(T.WalletSpawnArgs(public_key=signer.public_key))
    body = TxBody(principal=wallet_address(signer.public_key).raw,
                  method=int(Method.SPAWN), template=T.WALLET, nonce=nonce,
                  gas_price=gas_price, payload=args, sigs=[])
    return _finish(body, [signer])


def spawn_multisig(required: int, signers: list[EdSigner], nonce: int = 0,
                   gas_price: int = 1) -> Transaction:
    keys = [s.public_key for s in signers]
    args = codec.encode(T.MultisigSpawnArgs(required=required,
                                            public_keys=keys))
    body = TxBody(principal=multisig_address(required, keys).raw,
                  method=int(Method.SPAWN), template=T.MULTISIG, nonce=nonce,
                  gas_price=gas_price, payload=args, sigs=[])
    return _finish(body, signers[:required])


def spawn_vault(args: T.VaultSpawnArgs, nonce: int = 0) -> Transaction:
    body = TxBody(principal=vault_address(args).raw, method=int(Method.SPAWN),
                  template=T.VAULT, nonce=nonce, gas_price=0,
                  payload=args.to_bytes(), sigs=[])
    return Transaction(raw=body.to_bytes())


def spend(principal: Address, signers: list[EdSigner], destination: Address,
          amount: int, nonce: int, gas_price: int = 1) -> Transaction:
    payload = codec.encode(SpendPayload(destination=destination.raw,
                                        amount=amount))
    body = TxBody(principal=principal.raw, method=int(Method.SPEND),
                  template=None, nonce=nonce, gas_price=gas_price,
                  payload=payload, sigs=[])
    return _finish(body, signers)


def drain_vault(owner: Address, signers: list[EdSigner], vault: Address,
                destination: Address, amount: int, nonce: int,
                gas_price: int = 1) -> Transaction:
    payload = codec.encode(DrainPayload(vault=vault.raw,
                                        destination=destination.raw,
                                        amount=amount))
    body = TxBody(principal=owner.raw, method=int(Method.DRAIN_VAULT),
                  template=None, nonce=nonce, gas_price=gas_price,
                  payload=payload, sigs=[])
    return _finish(body, signers)
