"""On-TPU validation + benchmark suite (VERDICT r3 item 1).

Run ONLY when the axon tunnel answers (tpu_watchdog.sh gates on the probe).
Each section is independently guarded; partial results are still written.

Produces:
  tpu_results/validate_<ts>.json   -- machine-readable section results
  appends human-readable progress to stderr (watchdog tees into its log)

Sections:
  devices     platform / device kind sanity
  bitexact    scrypt XLA path vs hashlib.scrypt at N=8192 (on device)
  bitexact_pl Pallas ROMix (compiled, NOT interpret) vs hashlib
  race        XLA vs Pallas ROMix labels/s across batch sizes, N=8192
  proving     proving-hash throughput (labels/s scanned)
  pow         k2pow nonce-scan throughput
  entry       __graft_entry__.entry() compile+run on the real chip
  cpu         hashlib.scrypt single-core baseline (vs_baseline denominator)
"""

import hashlib
import json
import os
import sys
import time
import traceback

RESULTS = {"ts": time.time(), "sections": {}}


def log(*a):
    print("[tpu_validate]", *a, file=sys.stderr, flush=True)


def section(name):
    def deco(fn):
        def run():
            t0 = time.perf_counter()
            try:
                out = fn()
                RESULTS["sections"][name] = {
                    "ok": True, "dt": time.perf_counter() - t0, **(out or {})}
                log(f"{name}: OK {RESULTS['sections'][name]}")
            except Exception as e:  # noqa: BLE001 - record and continue
                RESULTS["sections"][name] = {
                    "ok": False, "dt": time.perf_counter() - t0,
                    "error": f"{type(e).__name__}: {e}",
                    "trace": traceback.format_exc()[-2000:]}
                log(f"{name}: FAIL {e}")
        return run
    return deco


N = int(os.environ.get("VALIDATE_N", 8192))


def ref_labels(commitment, indices, n):
    return [hashlib.scrypt(commitment, salt=int(i).to_bytes(8, "little"),
                           n=n, r=1, p=1, maxmem=256 * 1024 * 1024, dklen=16)
            for i in indices]


@section("devices")
def sec_devices():
    import jax
    d = jax.devices()[0]
    return {"platform": d.platform, "kind": getattr(d, "device_kind", "?"),
            "n": len(jax.devices()),
            "backend": jax.default_backend()}


@section("bitexact")
def sec_bitexact():
    import numpy as np
    from spacemesh_tpu.ops import scrypt

    commitment = hashlib.sha256(b"tpu-validate").digest()
    idx = np.array([0, 1, 2, 1000, 2**32 - 1, 2**32, 2**40 + 17, 123456789],
                   dtype=np.uint64)
    os.environ.pop("SPACEMESH_ROMIX", None)
    got = scrypt.scrypt_labels(commitment, idx, n=N)
    want = ref_labels(commitment, idx, N)
    bad = [i for i, w in enumerate(want) if got[i].tobytes() != w]
    if bad:
        raise AssertionError(f"XLA labels mismatch at {bad}")
    return {"n": N, "labels_checked": len(idx)}


@section("bitexact_pallas")
def sec_bitexact_pallas():
    import jax
    import numpy as np
    from spacemesh_tpu.ops import scrypt
    from spacemesh_tpu.ops.romix_pallas import LANE_TILE, _romix_pallas_jit

    commitment = hashlib.sha256(b"tpu-validate").digest()
    idx = np.arange(LANE_TILE, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    cw = scrypt.commitment_to_words(commitment)
    inner, outer, blk = scrypt._stage_expand(
        jax.numpy.asarray(cw), jax.numpy.asarray(lo), jax.numpy.asarray(hi))
    blk2 = _romix_pallas_jit(blk, n=N, interpret=False)  # REAL lowering
    words = scrypt._stage_finish(inner, outer, blk2)
    got = np.frombuffer(scrypt.labels_to_bytes(words), np.uint8).reshape(-1, 16)
    want = ref_labels(commitment, idx, N)
    bad = [i for i, w in enumerate(want) if got[i].tobytes() != w]
    if bad:
        raise AssertionError(f"pallas labels mismatch at {bad}")
    return {"n": N, "labels_checked": len(idx)}


def _time_romix(fn, blk, reps=3):
    import jax
    fn(blk).block_until_ready()  # compile
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(blk)
    out.block_until_ready()
    return (time.perf_counter() - t0) / reps


@section("race")
def sec_race():
    import jax
    import jax.numpy as jnp
    import numpy as np
    from spacemesh_tpu.ops import scrypt
    from spacemesh_tpu.ops.romix_pallas import _romix_pallas_jit

    commitment = hashlib.sha256(b"tpu-validate").digest()
    out = {}
    for b in [int(x) for x in os.environ.get(
            "VALIDATE_BATCH", "1024,2048,4096,8192,16384").split(",")]:
        idx = np.arange(b, dtype=np.uint64)
        lo, hi = scrypt.split_indices(idx)
        _, _, blk = scrypt._stage_expand(
            jnp.asarray(scrypt.commitment_to_words(commitment)),
            jnp.asarray(lo), jnp.asarray(hi))
        row = {}
        try:
            dt = _time_romix(lambda x: scrypt._stage_romix_xla(x, n=N), blk)
            row["xla_labels_per_s"] = round(b / dt, 1)
        except Exception as e:  # noqa: BLE001
            row["xla_error"] = f"{type(e).__name__}: {e}"[:300]
        try:
            dt = _time_romix(
                lambda x: _romix_pallas_jit(x, n=N, interpret=False), blk)
            row["pallas_labels_per_s"] = round(b / dt, 1)
        except Exception as e:  # noqa: BLE001
            row["pallas_error"] = f"{type(e).__name__}: {e}"[:300]
        out[str(b)] = row
        log(f"race b={b}: {row}")
    return {"batches": out}


@section("proving")
def sec_proving():
    import jax.numpy as jnp
    import numpy as np
    from spacemesh_tpu.ops import proving

    b = 1 << 16
    chw = jnp.asarray(np.arange(8, dtype=np.uint32))
    lo = jnp.arange(b, dtype=jnp.uint32)
    hi = jnp.zeros(b, jnp.uint32)
    lw = jnp.asarray(np.random.default_rng(0).integers(
        0, 2**32, size=(4, b), dtype=np.uint64).astype(np.uint32))
    proving.proving_hash_jit(chw, jnp.uint32(0), lo, hi, lw).block_until_ready()
    t0 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        v = proving.proving_hash_jit(chw, jnp.uint32(0), lo, hi, lw)
    v.block_until_ready()
    dt = (time.perf_counter() - t0) / reps
    return {"labels_scanned_per_s": round(b / dt, 1)}


@section("pow")
def sec_pow():
    import numpy as np
    from spacemesh_tpu.ops import pow as powmod

    if not hasattr(powmod, "k2pow_scan_rate"):
        # measure via public API: time a search over a fixed nonce window
        return {"skipped": "no scan-rate hook"}
    return {"rate": powmod.k2pow_scan_rate()}


@section("entry")
def sec_entry():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = fn(*args)
    import jax
    jax.block_until_ready(out)
    return {"compiled": True}


@section("cpu_baseline")
def sec_cpu():
    commitment = hashlib.sha256(b"tpu-validate").digest()
    t0 = time.perf_counter()
    cnt = 24
    ref_labels(commitment, range(cnt), N)
    return {"labels_per_s": round(cnt / (time.perf_counter() - t0), 1)}


def main():
    os.makedirs("tpu_results", exist_ok=True)
    for fn in [sec_devices, sec_bitexact, sec_bitexact_pallas, sec_race,
               sec_proving, sec_pow, sec_entry, sec_cpu]:
        fn()
    path = os.path.join("tpu_results", f"validate_{int(time.time())}.json")
    with open(path, "w") as f:
        json.dump(RESULTS, f, indent=1)
    log(f"wrote {path}")
    # overall ok if the two bit-exact sections and the race ran
    core = ["devices", "bitexact", "bitexact_pallas", "race"]
    ok = all(RESULTS["sections"].get(s, {}).get("ok") for s in core)
    print(json.dumps({"validate_ok": ok, "path": path}))
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
