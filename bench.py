"""Headline benchmark: POST init labels/sec on one chip (mainnet N=8192).

Prints THREE JSON lines for the init side. The headline first:
  {"metric": "post_init_labels_per_sec...", "value": N, "unit": "labels/s",
   "vs_baseline": N, "impl": "xla"|"xla-rows"|"pallas", "chunk": ...,
   "tuned": "race"|"cache"|..., "fused": true}
("impl"/"chunk" are the ROMix kernel decision the autotuner raced and
persisted — ops/autotune.py, docs/ROMIX_KERNEL.md — and "fused" records
that expand->romix->finish ran as one jitted program), then the
kernel-only rate, isolating the memory-hard ROMix core from the PBKDF2
envelope + pipeline overhead around it:
  {"metric": "post_init_kernel_labels_per_sec", ...}
then the compile cost, tracked separately from steady-state throughput:
  {"metric": "post_init_compile_s", "value": N, "unit": "s", ...}

Steady state is measured pipelined — all reps dispatched back-to-back and
synced once at the end, the way the streaming initializer drives the
device — so inter-rep host sync gaps don't pollute the number. Compiled
executables are reused across reps and across runs: the persistent
compilation cache (utils/accel.py) makes the 17-26s per-shape compile a
once-per-machine cost, so `post_init_compile_s` on a warm host drops to
the cache-deserialize time.

vs_baseline is the speedup over the reference CPU labeling path measured
in-process (hashlib.scrypt = OpenSSL scrypt, the same labeling function the
reference's CPU provider computes; the reference publishes no numbers of
its own — BASELINE.md). Progress goes to stderr; stdout carries only the
JSON lines.

After the POST metrics, a verification benchmark (ISSUE 2) runs a mixed
workload (ed25519 sigs + VRF proofs + POST proofs + poet memberships,
>=10% invalid) through the inline serial path and through the
verification farm (spacemesh_tpu/verify/), emitting:
  {"metric": "verify_serial_s", ...}
  {"metric": "verify_batched_s", ..., "speedup": serial/batched}
Both paths are warmed first so the numbers compare steady-state
throughput, not XLA compile time; decisions are asserted bit-identical.

Between the init and verify benchmarks, the PROVE side (ISSUE 3) measures
the streaming prover against the legacy serial scan over one shared
reduced-parameter store, emitting:
  {"metric": "post_prove_labels_per_sec", ..., "serial": N, "speedup": N}
Both provers must produce bit-identical proofs (asserted) and the
pipelined proof must verify; the rate is store labels covered per second
until the winning nonce is decided — the streaming pipeline's sound early
exit plus read/compute overlap is what the speedup measures
(docs/POST_PROVING.md).

After the kernel-only line, the MESH headline (ISSUE 6): the autotuned
multi-device path — label lanes sharded over virtual host devices on the
CPU fallback (8 forced, the same count every test/driver entry point
already configures), device count and layout chosen by the autotuner's
mesh race (ops/autotune.py) — measured in a SUBPROCESS so the forced
host-device split cannot degrade the single-device lines above it. The
probe returns the sha256 digest of its sharded labels; the parent
recomputes the single-device digest (only when a mesh rate was actually
measured) and refuses to print the headline — exiting non-zero, so CI
goes red — on any mismatch:
  {"metric": "post_init_labels_per_sec_mesh", "value": N,
   "unit": "labels/s", "devices": D, "impl": ..., "vs_single": N,
   "vs_baseline": N, "bit_identical": true}
On a real multi-device accelerator the same measurement runs in-process
(the devices are physical; nothing to force). BENCH_MESH=0 disables.

After the prove bench, the MULTI-TENANT headline (ISSUE 11/16): 16
tenants' small init jobs through the runtime scheduler's packed
fair-share admission (spacemesh_tpu/runtime/) vs the same jobs run one
tenant at a time, per-tenant sha256 label digests + VRF nonces asserted
identical before any rate is reported (a mismatch exits non-zero). On
the CPU platform (unless BENCH_MESH=0) the measurement runs in a
SUBPROCESS with forced virtual host devices — the environment where the
scheduler's pack dispatch routes through the mesh-sharded program —
for the same single-device-honesty reason as the mesh probe;
"pack_devices" records how the tuned routing actually dispatched packs:
  {"metric": "post_multi_tenant_labels_per_sec", ..., "tenants": 16,
   "pack_devices": D, "sequential": N, "vs_sequential": N,
   "bit_identical": true}

After the farm verify bench, the VERIFYD headline (ISSUE 13): the same
mixed workload plus k2pow witnesses through the standalone verification
service (spacemesh_tpu/verifyd/) over real sockets — a multi-client
open-loop load vs a serial one-at-a-time client, every verdict asserted
identical to inline verification before any rate is reported:
  {"metric": "verifyd_proofs_per_sec", "value": N, "unit": "items/s",
   "p99_ms": N, "serial": N, "vs_serial": N, "bit_identical": true}

Last, the SIM FABRIC headline (ISSUE 18): the 514-node pure-fabric
``storm-512-bench`` scenario on the event-wheel hub (twice, replay
determinism) and on the legacy task-per-node hub, scenario digests
asserted identical across all three runs before any rate is reported:
  {"metric": "sim_fabric_events_per_sec", "value": N, "unit": "events/s",
   "legacy": N, "vs_legacy": N, "bit_identical": true}

And the MULTI-PROCESS fabric line (ISSUE 19): the same scenario with
the event wheel sharded over host cores (sim/shard.py) vs
single-process, all four digests asserted identical first; hosts
without >= 2 cores keep the fabric single-process and say so:
  {"metric": "sim_fabric_mp_events_per_sec", "value": N,
   "unit": "events/s", "single": N, "vs_single_process": N,
   "shards": W, "cores": C, "bit_identical": true}

Env knobs: BENCH_BATCH (label lanes per program), BENCH_N (scrypt N),
BENCH_REPS, BENCH_CPU_LABELS, BENCH_VERIFY_ITEMS (0 disables the verify
bench), BENCH_PROVE_LABELS (store size; 0 disables the prove bench),
BENCH_PROVE_BATCH, BENCH_TENANTS / BENCH_TENANT_LABELS / BENCH_TENANT_N
/ BENCH_TENANT_REPS / BENCH_PACK_LANES (the multi-tenant line; tenants=0
disables), BENCH_VERIFYD_ITEMS / BENCH_VERIFYD_CLIENTS /
BENCH_VERIFYD_PER_REQUEST / BENCH_VERIFYD_WORKERS (the verifyd line;
items=0 disables), BENCH_FLEET_ITEMS / BENCH_FLEET_REPLICAS /
BENCH_FLEET_CLIENTS / BENCH_FLEET_PER_REQUEST / BENCH_FLEET_WORKERS /
BENCH_FLEET_REPS / BENCH_FLEET_PIN / BENCH_FLEET_MIN_SPEEDUP (the
verifyd fleet line; items=0 disables; replicas pin to disjoint core
slices when the host has one per replica, and MIN_SPEEDUP enforces the
>= 1.5x fleet floor only on such hosts),
BENCH_MESH (0 disables the mesh line AND pins the
multi-tenant bench in-process single-device), BENCH_MESH_TIMEOUT /
BENCH_MT_TIMEOUT (probe subprocess seconds, default 1800),
BENCH_SIM_FABRIC (0/off disables the sim fabric line) /
BENCH_SIM_FABRIC_TIMEOUT (per-run subprocess seconds, default 600),
BENCH_SIM_FABRIC_MP (0/off disables the multi-process fabric line) /
BENCH_SIM_FABRIC_MP_SHARDS (worker count; default min(cores, light//64))
/ BENCH_SIM_FABRIC_MP_TIMEOUT (default 900) /
BENCH_SIM_FABRIC_MP_MIN_SPEEDUP (the >= 1.5x floor, enforced only
where the parent and every worker get their own core),
SPACEMESH_JAX_CACHE (cache dir, `off` to disable), plus the kernel
overrides SPACEMESH_ROMIX / SPACEMESH_ROMIX_CHUNK /
SPACEMESH_ROMIX_AUTOTUNE / SPACEMESH_MESH (docs/ROMIX_KERNEL.md).
"""

import hashlib
import json
import os
import shutil
import subprocess
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def cpu_labels_per_sec(commitment: bytes, n: int, count: int) -> float:
    t0 = time.perf_counter()
    for i in range(count):
        hashlib.scrypt(commitment, salt=i.to_bytes(8, "little"), n=n, r=1,
                       p=1, maxmem=256 * 1024 * 1024, dklen=16)
    dt = time.perf_counter() - t0
    return count / dt


# probe + CPU fallback shared with tools/profiler.py — ONE copy of the
# wedged-tunnel handling (spacemesh_tpu/utils/accel.py)


def measure_mesh(n: int, batch: int, reps: int) -> dict:
    """Measure the autotuned multi-device label path for one shape.

    Runs the full decide (mesh dimension included — this races and
    persists on a cold host), shards the same (commitment, indices)
    batch the single-device headline used over the winning device count,
    and returns a JSON-able doc carrying the sha256 ``digest`` of the
    sharded labels — the caller compares it against the single-device
    digest before reporting any rate. ``devices`` is 1 when the tuner
    honestly concluded single-device wins on this host."""
    import jax
    import numpy as np

    from spacemesh_tpu.ops import autotune, scrypt

    decision = autotune.decide(n, batch, max_devices=None)
    doc = {"devices": decision.devices, "impl": decision.impl,
           "chunk": decision.chunk, "tuned": decision.source,
           "devices_visible": jax.device_count()}
    if decision.devices <= 1:
        return doc
    from spacemesh_tpu.parallel import mesh as pmesh

    mesh = pmesh.data_mesh(jax.devices()[:decision.devices])
    commitment = hashlib.sha256(b"bench-commitment").digest()
    cw = scrypt.commitment_to_words(commitment)
    idx = np.arange(batch, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    t0 = time.perf_counter()
    words = pmesh.scrypt_labels_sharded(mesh, cw, lo, hi, n=n,
                                        impl=decision.impl)
    words.block_until_ready()
    doc["compile_s"] = round(time.perf_counter() - t0, 2)
    doc["digest"] = hashlib.sha256(
        scrypt.labels_to_bytes(np.asarray(words))).hexdigest()
    t0 = time.perf_counter()
    outs = [pmesh.scrypt_labels_sharded(mesh, cw, lo, hi, n=n,
                                        impl=decision.impl)
            for _ in range(reps)]
    jax.block_until_ready(outs)
    doc["labels_per_sec"] = round(reps * batch / (time.perf_counter() - t0),
                                  1)
    return doc


def mesh_probe_main() -> int:
    """Child-process entry (``bench.py --mesh-probe``): pin the CPU
    platform, force the virtual host devices (which would degrade the
    parent's single-device numbers — the reason this is a subprocess),
    and print the measure_mesh doc as the last stdout line."""
    n = int(os.environ["BENCH_MESH_N"])
    batch = int(os.environ["BENCH_MESH_BATCH"])
    reps = int(os.environ.get("BENCH_MESH_REPS", 3))

    from spacemesh_tpu.utils import accel

    accel.force_cpu_platform()  # the parent only probes on CPU fallback
    accel.ensure_host_devices()
    accel.enable_persistent_cache()
    doc = measure_mesh(n, batch, reps)
    print(json.dumps(doc), flush=True)
    return 0


def mt_probe_main() -> int:
    """Child-process entry (``bench.py --mt-probe``): the multi-tenant
    packer bench on the CPU fallback with forced virtual host devices —
    the environment where the scheduler's pack dispatch routes through
    the mesh-sharded program (runtime/scheduler.py _dispatch_pack). A
    subprocess for the same reason the mesh probe is one: the forced
    device split would degrade the parent's single-device lines. The
    bit-identity gate (per-tenant sha256 + VRF nonce vs the sequential
    Initializer) runs INSIDE this child and exits non-zero on any
    divergence; the parent propagates that as a red build."""
    from spacemesh_tpu.utils import accel

    accel.force_cpu_platform()
    accel.ensure_host_devices()
    accel.enable_persistent_cache()
    multi_tenant_bench()
    return 0


def run_mt_probe() -> None:
    """Run multi_tenant_bench in a subprocess with forced host devices,
    forwarding its JSON line; a failed child fails the bench."""
    timeout = int(os.environ.get("BENCH_MT_TIMEOUT", 1800))
    log(f"multi-tenant probe: packed admission over the mesh in a "
        f"subprocess (<= {timeout}s) ...")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mt-probe"],
            env=dict(os.environ), timeout=timeout,
            capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        log("multi-tenant probe: timed out; skipping the line")
        return
    sys.stderr.write(r.stderr)
    for line in r.stdout.strip().splitlines():
        try:
            json.loads(line)
        except ValueError:
            continue
        print(line, flush=True)
    if r.returncode != 0:
        # the child's bit-identity gate (or an outright crash) — red
        log(f"multi-tenant probe: FAILED (rc={r.returncode})")
        sys.exit(1)


def run_mesh_probe(n: int, batch: int, reps: int) -> dict | None:
    """Run measure_mesh in a subprocess with forced host devices."""
    env = dict(os.environ,
               BENCH_MESH_N=str(n), BENCH_MESH_BATCH=str(batch),
               BENCH_MESH_REPS=str(reps))
    timeout = int(os.environ.get("BENCH_MESH_TIMEOUT", 1800))
    log(f"mesh probe: racing + measuring the sharded path in a "
        f"subprocess (<= {timeout}s) ...")
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--mesh-probe"],
            env=env, timeout=timeout, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        log("mesh probe: timed out; skipping the mesh headline")
        return None
    sys.stderr.write(r.stderr)
    if r.returncode != 0:
        log(f"mesh probe: failed (rc={r.returncode}); skipping")
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    log("mesh probe: no JSON doc on stdout; skipping")
    return None


def prove_bench(labels: int, batch: int, reps: int = 3) -> None:
    """Streaming vs legacy-serial proving over one shared store.

    The deterministic reduced-parameter fixture lives in
    spacemesh_tpu/post/workload.py (ONE copy, shared with the profiler's
    --prove view); it asserts the two paths' proofs are bit-identical and
    verifiable before this reports a number.
    """
    import tempfile

    from spacemesh_tpu.post import workload

    with tempfile.TemporaryDirectory() as d:
        log(f"prove store: {labels} labels (scrypt N=2) ...")
        prover = workload.build(d, labels, batch)
        doc = workload.compare_serial_vs_pipelined(prover, reps=reps)

    serial_rate = labels / doc["serial_s"]
    pipe_rate = labels / doc["pipelined_s"]
    stats = doc["stats"]
    log(f"prove: serial {doc['serial_s'] * 1e3:.1f}ms, pipelined "
        f"{doc['pipelined_s'] * 1e3:.1f}ms ({doc['speedup']:.2f}x, "
        f"nonce {doc['proof'].nonce}, "
        f"early_exit={stats.get('early_exited')})")
    print(json.dumps({
        "metric": "post_prove_labels_per_sec",
        "value": round(pipe_rate, 1),
        "unit": "labels/s",
        "serial": round(serial_rate, 1),
        "speedup": round(pipe_rate / serial_rate, 2),
        "labels": labels, "batch": batch,
        "proof_nonce": doc["proof"].nonce,
        "early_exited": bool(stats.get("early_exited")),
        "verified": True,
    }))


def multi_tenant_bench() -> None:
    """16-tenant aggregate init throughput vs one-tenant-at-a-time.

    The workload is the multi-tenant service shape (ROADMAP #1): many
    smeshers each submitting a SMALL init job — per-job ownership pays
    one session (writer pool, watchdogs, metadata, drain) and one
    under-filled device program per tenant, while the runtime scheduler
    (spacemesh_tpu/runtime/) packs all tenants' lanes into full-bucket
    fused programs through one always-fed engine window.  Reduced N
    (like the prove bench's reduced-parameter store) keeps the measured
    quantity the orchestration gap, not the scrypt math — the same
    choice ROADMAP #5 motivates ("the gap is orchestration").

    Before ANY rate is reported, every tenant's label bytes (sha256)
    and VRF nonce from the scheduled path are asserted identical to the
    sequential Initializer's; a mismatch exits non-zero so CI goes red.
    Emits:
      {"metric": "post_multi_tenant_labels_per_sec", "value": N,
       "unit": "labels/s", "tenants": T, "sequential": N,
       "vs_sequential": N, "bit_identical": true}
    """
    import shutil
    import tempfile
    from pathlib import Path

    tenants = int(os.environ.get("BENCH_TENANTS", 16))
    labels = int(os.environ.get("BENCH_TENANT_LABELS", 128))
    n = int(os.environ.get("BENCH_TENANT_N", 8))
    reps = int(os.environ.get("BENCH_TENANT_REPS", 3))
    pack = int(os.environ.get("BENCH_PACK_LANES", 2048))

    from spacemesh_tpu.post import initializer
    from spacemesh_tpu.post.data import LabelStore
    from spacemesh_tpu.runtime import TenantScheduler

    ids = [(f"smesher-{i:02d}",
            hashlib.sha256(b"bench-mt-node-%d" % i).digest(),
            hashlib.sha256(b"bench-mt-commit-%d" % i).digest())
           for i in range(tenants)]
    total = tenants * labels

    def fingerprint(dir_, meta) -> tuple:
        store = LabelStore(dir_, meta)
        digest = hashlib.sha256(store.read_labels(0, labels)).hexdigest()
        store.close()
        return digest, meta.vrf_nonce, meta.vrf_nonce_value

    log(f"multi-tenant: {tenants} tenants x {labels} labels (N={n}, "
        f"pack={pack}) ...")
    with tempfile.TemporaryDirectory() as d:
        root = Path(d)

        def seq_round(tag: str) -> dict:
            prints = {}
            for tid, node, commit in ids:
                dir_ = root / f"{tag}-{tid}"
                meta, _res = initializer.initialize(
                    dir_, node_id=node, commitment=commit, num_units=1,
                    labels_per_unit=labels, scrypt_n=n,
                    max_file_size=1 << 24, batch_size=labels, mesh=None)
                prints[tid] = fingerprint(dir_, meta)
                shutil.rmtree(dir_)
            return prints

        sched = TenantScheduler(workers=2, pack_lanes=pack)
        for tid, _, _ in ids:
            sched.register_tenant(tid)

        def mt_round(tag: str) -> dict:
            handles = [
                (tid, sched.submit_init(
                    tid, root / f"{tag}-{tid}", node_id=node,
                    commitment=commit, num_units=1,
                    labels_per_unit=labels, scrypt_n=n,
                    max_file_size=1 << 24))
                for tid, node, commit in ids]
            prints = {}
            for tid, h in handles:
                meta = h.result(timeout=600)
                prints[tid] = fingerprint(root / f"{tag}-{tid}", meta)
                shutil.rmtree(root / f"{tag}-{tid}")
            return prints

        try:
            # warm both paths' executables (compile cost is its own
            # bench line; this line measures steady-state admission —
            # the scheduler's pack linger keeps measured packs full)
            seq_prints = seq_round("warm-seq")
            mt_prints = mt_round("warm-mt")
            best_seq = best_mt = float("inf")
            for r in range(reps):
                t0 = time.perf_counter()
                seq_round(f"s{r}")
                best_seq = min(best_seq, time.perf_counter() - t0)
                t0 = time.perf_counter()
                mt_round(f"m{r}")
                best_mt = min(best_mt, time.perf_counter() - t0)
        finally:
            sched.close()

    for tid, _, _ in ids:
        if seq_prints[tid] != mt_prints[tid]:
            # divergence must be a red build, not a quietly odd rate
            log(f"multi-tenant: FAILED — tenant {tid} diverged from the "
                f"sequential path: {seq_prints[tid]} != {mt_prints[tid]}")
            sys.exit(1)

    seq_rate = total / best_seq
    mt_rate = total / best_mt
    # how the packer's dispatch actually routed at the pack bucket: the
    # same tuned routing runtime/scheduler.py _dispatch_pack consults —
    # 1 means the tuner honestly kept single-device on this host
    from spacemesh_tpu.ops import autotune, scrypt

    devs, _d = autotune.resolve_auto_mesh(n, scrypt.shape_bucket(pack))
    pack_devices = len(devs) if devs is not None else 1
    log(f"multi-tenant: sequential {best_seq * 1e3:.0f}ms "
        f"({seq_rate:,.0f} labels/s), scheduled {best_mt * 1e3:.0f}ms "
        f"({mt_rate:,.0f} labels/s, {mt_rate / seq_rate:.2f}x, "
        f"pack_devices={pack_devices})")
    print(json.dumps({
        "metric": "post_multi_tenant_labels_per_sec",
        "value": round(mt_rate, 1),
        "unit": "labels/s",
        "tenants": tenants,
        "labels_per_tenant": labels,
        "n": n,
        "pack_lanes": pack,
        "pack_devices": pack_devices,
        "sequential": round(seq_rate, 1),
        "vs_sequential": round(mt_rate / seq_rate, 2),
        "bit_identical": True,  # per-tenant sha256 + VRF nonce checked
        #                         above; a mismatch exits non-zero
    }))


def verify_bench(total_items: int) -> None:
    """Serial vs farm-batched verification over one mixed workload."""
    import tempfile

    from spacemesh_tpu.verify import workload

    # composition: POST-heavy (the workload this repo accelerates) plus
    # the gossip sig/VRF/membership mix, ~12% invalid/malformed spread
    # across every kind. POST requests replicate 24 distinct proofs
    # (~8x, the gossip re-delivery fanout) — the farm's dedup is part of
    # what is being measured and is reported in the output.
    posts = max(total_items // 2, 8)
    vrfs = max(total_items // 8, 8)
    mems = max(total_items // 8, 8)
    sigs = max(total_items - posts - vrfs - mems, 16)
    with tempfile.TemporaryDirectory() as d:
        log(f"verify workload: {sigs} sigs + {vrfs} vrfs + {mems} "
            f"memberships + {posts} post proofs ...")
        w = workload.build(d, sigs=sigs, vrfs=vrfs, posts=posts,
                           memberships=mems, post_challenges=24)
        doc = workload.compare_serial_vs_farm(w)

    stats = doc["stats"]
    log(f"verify: serial {doc['serial_s']:.2f}s, "
        f"farm {doc['batched_s']:.2f}s "
        f"({doc['items']} items, {doc['rejected']} rejected, "
        f"occupancy<= {stats['max_occupancy']}, "
        f"dedup {stats['dedup_hits']})")
    print(json.dumps({
        "metric": "verify_serial_s", "value": round(doc["serial_s"], 3),
        "unit": "s", "items": doc["items"], "rejected": doc["rejected"],
    }))
    print(json.dumps({
        "metric": "verify_batched_s", "value": round(doc["batched_s"], 3),
        "unit": "s", "items": doc["items"],
        "speedup": doc["speedup"],
        "batches": stats["batches"],
        "max_occupancy": stats["max_occupancy"],
        "dedup_hits": stats["dedup_hits"],
    }))


def verifyd_bench(total_items: int) -> None:
    """verifyd headline: proofs verified/sec AT p99 latency under a
    heavy mixed open-loop load, through the network service over real
    sockets (spacemesh_tpu/verifyd/), vs a serial one-at-a-time client.

    The workload is the BASELINE.json second-metric shape scaled to the
    host (mixed NIPoST proofs + signatures + VRFs + memberships + k2pow
    witnesses; the 10k-NIPoST config is BENCH_VERIFYD_ITEMS=10000 on
    real hardware).  Before ANY rate is reported every verdict from the
    service — serial and open-loop — is asserted identical to inline
    verification; a mismatch exits non-zero so CI goes red.  Emits:
      {"metric": "verifyd_proofs_per_sec", "value": N, "unit":
       "items/s", "p99_ms": N, "serial": N, "vs_serial": N,
       "clients": C, "bit_identical": true, ...}
    """
    import asyncio
    import tempfile

    clients_n = int(os.environ.get("BENCH_VERIFYD_CLIENTS", 3))
    per_req = int(os.environ.get("BENCH_VERIFYD_PER_REQUEST", 32))
    posts = max(total_items // 4, 4)
    pows = max(total_items // 8, 8)
    vrfs = max(total_items // 16, 4)
    mems = max(total_items // 16, 4)
    sigs = max(total_items - posts - pows - vrfs - mems, 16)

    from spacemesh_tpu.core import signing
    from spacemesh_tpu.verify import workload
    from spacemesh_tpu.verifyd import VerifydClient, VerifydServer

    with tempfile.TemporaryDirectory() as d:
        log(f"verifyd workload: {sigs} sigs + {vrfs} vrfs + {mems} "
            f"memberships + {pows} k2pow + {posts} post proofs ...")
        w = workload.build(d, sigs=sigs, vrfs=vrfs, posts=posts,
                           memberships=mems, pows=pows,
                           post_challenges=min(24, posts))
        expected = w.inline_all()

        async def run() -> dict:
            server = VerifydServer(
                listen="127.0.0.1:0", post_params=w.post_params,
                post_seed=w.post_seed,
                workers=int(os.environ.get("BENCH_VERIFYD_WORKERS", 8)),
                default_rate=1e9, default_burst=1e9,
                max_pending_items=1 << 20)
            server.service.farm.ed_verifier = w.ed
            server.service.farm.vrf_verifier = w.vrf
            try:
                port = await server.start()
                base = f"http://127.0.0.1:{port}"
                reqs = w.requests

                cs = [VerifydClient(base, f"load-{i}")
                      for i in range(clients_n)]
                for c in cs:
                    await c.register(max_inflight=8)
                shards = [list(range(i, len(reqs), clients_n))
                          for i in range(clients_n)]
                lat: list = []
                got = [None] * len(reqs)

                async def one(c, idxs):
                    t1 = time.perf_counter()
                    vs = await c.verify([reqs[i] for i in idxs])
                    lat.append(time.perf_counter() - t1)
                    for i, v in zip(idxs, vs):
                        got[i] = v

                async def open_loop() -> None:
                    # open loop: every client's whole request schedule
                    # is issued up front; completions never gate
                    # arrivals
                    tasks = [one(c, shard[j:j + per_req])
                             for c, shard in zip(cs, shards)
                             for j in range(0, len(shard), per_req)]
                    await asyncio.gather(*tasks)

                # warm both paths' EXACT shapes untimed (per-bucket XLA
                # compiles are a once-per-machine cost the persistent
                # cache amortizes, not throughput): the POST verify
                # shape ladder first — farm batch composition varies
                # run to run, so every power-of-two bucket the farm can
                # produce is compiled up front — then the full
                # open-loop schedule once, plus a serial pass
                from spacemesh_tpu.post import verifier as post_verifier

                post_items = [r.item for r in reqs if r.kind == "post"]
                if post_items:
                    t0 = time.perf_counter()
                    k = 1
                    while k <= min(2 * len(post_items), 256):
                        await asyncio.to_thread(
                            post_verifier.verify_many,
                            (post_items * 3)[:k], w.post_params,
                            seed=w.post_seed)
                        k *= 2
                    log(f"verifyd: post shape-ladder warm "
                        f"{time.perf_counter() - t0:.1f}s")
                serial = VerifydClient(base, "serial")
                await serial.register()
                await open_loop()
                if got != expected:
                    return {"diverged": "warm"}
                # second warm pass: batch composition is timing-
                # dependent, so one pass can miss buckets the timed
                # phase would then compile
                got = [None] * len(reqs)
                await open_loop()
                if got != expected:
                    return {"diverged": "warm"}
                got = [None] * len(reqs)
                lat.clear()
                warm_serial = await serial.serial_verify(reqs)
                if warm_serial != expected:
                    return {"diverged": "warm-serial"}

                # best-of-N reps per phase (like every other bench
                # line): steady-state throughput, not scheduler noise
                reps = int(os.environ.get("BENCH_VERIFYD_REPS", 2))
                serial_s = float("inf")
                for _ in range(reps):
                    signing.clear_verify_cache()
                    t0 = time.perf_counter()
                    serial_got = await serial.serial_verify(reqs)
                    serial_s = min(serial_s, time.perf_counter() - t0)
                    if serial_got != expected:
                        return {"diverged": "serial"}
                await serial.aclose()

                # p99 is taken from the SAME rep whose wall time is
                # reported — "throughput at p99" must not pair one
                # rep's rate with another rep's tail
                open_s, best_lat = float("inf"), []
                for _ in range(reps):
                    signing.clear_verify_cache()
                    got = [None] * len(reqs)
                    lat.clear()
                    t0 = time.perf_counter()
                    await open_loop()
                    el = time.perf_counter() - t0
                    if got != expected:
                        return {"diverged": "open-loop"}
                    if el < open_s:
                        open_s, best_lat = el, list(lat)
                lat = best_lat
                for c in cs:
                    await c.aclose()
                if got != expected:
                    return {"diverged": "open-loop"}
                lat.sort()
                p99 = lat[min(int(len(lat) * 0.99), len(lat) - 1)]
                stats = server.service.stats_doc()
                return {"serial_s": serial_s, "open_s": open_s,
                        "p99_s": p99, "requests": len(lat),
                        "farm_batches": stats["farm"]["batches"],
                        "shed": stats["shed"],
                        "targets": stats["tuner"]["targets"]}
            finally:
                await server.close()

        doc = asyncio.run(run())

    if "diverged" in doc:
        # divergence must be a red build, not a quietly odd rate
        log(f"verifyd: FAILED — {doc['diverged']} verdicts diverged "
            f"from inline verification")
        sys.exit(1)
    n = len(expected)
    serial_rate = n / doc["serial_s"]
    open_rate = n / doc["open_s"]
    log(f"verifyd: serial {doc['serial_s']:.2f}s "
        f"({serial_rate:,.0f} items/s), open-loop {doc['open_s']:.2f}s "
        f"({open_rate:,.0f} items/s, {open_rate / serial_rate:.2f}x, "
        f"p99 {doc['p99_s'] * 1e3:.1f}ms, "
        f"{doc['farm_batches']} farm batches)")
    print(json.dumps({
        "metric": "verifyd_proofs_per_sec",
        "value": round(open_rate, 1),
        "unit": "items/s",
        "p99_ms": round(doc["p99_s"] * 1e3, 2),
        "serial": round(serial_rate, 1),
        "vs_serial": round(open_rate / serial_rate, 2),
        "clients": clients_n,
        "items": n,
        "requests": doc["requests"],
        "shed": doc["shed"],
        "batch_targets": doc["targets"],
        "bit_identical": True,  # serial + open-loop verdicts checked
        #                         against inline above; a mismatch
        #                         exits non-zero before this line
    }))


# child-process replica for the fleet bench: one real verifyd server
# per OS process (the fleet's whole point is capacity past one
# process), bound ports printed as the first stdout line, serving until
# stdin closes
_FLEET_REPLICA_SRC = r"""
import asyncio, json, sys

cfg = json.loads(sys.argv[1])


async def main():
    from spacemesh_tpu.post.prover import ProofParams
    from spacemesh_tpu.verifyd.server import VerifydServer

    params = ProofParams(
        k1=cfg["k1"], k2=cfg["k2"], k3=cfg["k3"],
        pow_difficulty=bytes.fromhex(cfg["pow_difficulty"]))
    server = VerifydServer(
        listen="127.0.0.1:0", post_params=params,
        post_seed=bytes.fromhex(cfg["post_seed"]),
        workers=cfg["workers"], default_rate=1e9, default_burst=1e9,
        max_pending_items=1 << 20)
    try:
        port = await server.start()
        print(json.dumps({"port": port}), flush=True)
        await asyncio.get_running_loop().run_in_executor(
            None, sys.stdin.read)
    finally:
        await server.close()


asyncio.run(main())
"""


class _SentinelFarm:
    """The fleet bench's local farm: reaching it means a replica
    failed mid-measurement — fail loudly, never quietly fold local
    verification into a 'fleet' rate."""

    async def submit(self, req, lane=None):
        raise RuntimeError("fleet bench fell back to the local farm")


def _spawn_fleet_replicas(count: int, cfg: dict,
                          pins: list | None = None) -> list:
    here = os.path.dirname(os.path.abspath(__file__))
    procs = []
    try:
        for i in range(count):
            argv = [sys.executable, "-c", _FLEET_REPLICA_SRC,
                    json.dumps(cfg)]
            if pins is not None:
                argv = ["taskset", "-c",
                        ",".join(str(c) for c in pins[i])] + argv
            p = subprocess.Popen(
                argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                text=True, cwd=here)
            procs.append(p)
        for p in procs:
            line = p.stdout.readline()
            p.port = json.loads(line)["port"]
        return procs
    except Exception:
        _stop_fleet_replicas(procs)
        raise


def _stop_fleet_replicas(procs: list) -> None:
    for p in procs:
        try:
            p.stdin.close()
        except Exception:  # noqa: BLE001 — already dead is fine
            pass
    for p in procs:
        try:
            p.wait(timeout=30)
        except Exception:  # noqa: BLE001 — drain hang: don't leak it
            p.kill()


def fleet_bench(total_items: int) -> None:
    """verifyd FLEET headline (ISSUE 17): proofs/sec through a
    3-replica fleet of real verifyd server PROCESSES behind the
    FleetRouter's consistent-hash placement, vs the same workload
    through a single verifyd process driven by the identical
    FleetVerifier plumbing (1-replica fleet — same client overhead, so
    the ratio isolates the fleet's capacity, not the driver).

    The mix is verification-heavy (k2pow + POST dominate) so the
    measured resource is server-side compute — the thing replicas
    multiply.  Every verdict from BOTH phases is asserted identical to
    inline verification before any rate is reported; a mismatch or any
    local-farm fallback exits non-zero.  Emits:
      {"metric": "verifyd_fleet_proofs_per_sec", "value": N,
       "unit": "items/s", "single": N, "vs_single": N, "replicas": 3,
       "clients": C, "cores": C, "pinned": bool,
       "bit_identical": true}

    Replica processes (and the baseline) pin to disjoint core slices
    when the host has one per replica — one replica per host is the
    fleet's deployment, and without pinning a lone XLA process eats
    every core and the ratio measures contention, not capacity.  The
    >= 1.5x acceptance floor (BENCH_FLEET_MIN_SPEEDUP=1.5) is enforced
    only on such hosts; elsewhere the benchtrend ``vs_single`` gate
    guards regressions.
    """
    import asyncio
    import tempfile

    replicas_n = int(os.environ.get("BENCH_FLEET_REPLICAS", 3))
    clients_n = int(os.environ.get("BENCH_FLEET_CLIENTS", 6))
    per_req = int(os.environ.get("BENCH_FLEET_PER_REQUEST", 8))
    workers = int(os.environ.get("BENCH_FLEET_WORKERS", 4))
    reps = int(os.environ.get("BENCH_FLEET_REPS", 2))
    min_speedup = float(os.environ.get("BENCH_FLEET_MIN_SPEEDUP", 0))

    # one replica per HOST is the fleet's deployment: model it by
    # pinning each replica process to its own disjoint core slice (the
    # baseline gets exactly one slice — a single host's capacity).
    # Unpinned, a lone XLA process already eats every core and N
    # co-scheduled replicas can only contend, so the ratio would
    # measure the host, not the fleet.
    try:
        cores = sorted(os.sched_getaffinity(0))
    except AttributeError:   # non-linux fallback
        cores = list(range(os.cpu_count() or 1))
    pin = (int(os.environ.get("BENCH_FLEET_PIN", 1)) != 0
           and shutil.which("taskset") is not None
           and len(cores) >= replicas_n > 1)
    slices = None
    if pin:
        per_slice = len(cores) // replicas_n
        slices = [cores[i * per_slice:(i + 1) * per_slice]
                  for i in range(replicas_n)]

    pows = max(total_items // 2, 8)
    posts = max(total_items // 8, 4)
    vrfs = max(total_items // 16, 4)
    mems = max(total_items // 16, 4)
    sigs = max(total_items - pows - posts - vrfs - mems, 16)

    from spacemesh_tpu.verify import workload
    from spacemesh_tpu.verifyd.fleet import fleet_from_urls

    with tempfile.TemporaryDirectory() as d:
        log(f"fleet workload: {sigs} sigs + {vrfs} vrfs + {mems} "
            f"memberships + {pows} k2pow + {posts} post proofs ...")
        w = workload.build(d, sigs=sigs, vrfs=vrfs, posts=posts,
                           memberships=mems, pows=pows,
                           post_challenges=min(8, posts))
        expected = w.inline_all()
        reqs = w.requests
        cfg = {"k1": w.post_params.k1, "k2": w.post_params.k2,
               "k3": w.post_params.k3,
               "pow_difficulty": w.post_params.pow_difficulty.hex(),
               "post_seed": w.post_seed.hex(), "workers": workers}

        cids = [f"load-{i}" for i in range(clients_n)]
        shards = [list(range(i, len(reqs), clients_n))
                  for i in range(clients_n)]

        async def drive(urls: list[str]) -> float:
            """Open-loop load through a FleetVerifier over ``urls``;
            returns best-of-reps wall seconds (inf on divergence)."""
            fv = fleet_from_urls(urls, farm=_SentinelFarm(),
                                 client_id="bench")
            try:
                fv.start()
                # pre-register every driver identity with open-loop
                # quotas (FleetVerifier's lazy register is a reconfig
                # no-op, so these knobs stick); a quota shed mid-run
                # would poison a breaker and fail the bench
                for rep in fv.router.replicas.values():
                    for cid in cids:
                        await rep.endpoint.register(
                            cid, max_queued=1 << 16, max_inflight=64)
                got = [None] * len(reqs)

                async def one(cid, idxs):
                    vs = await fv.verify_batch(
                        [reqs[i] for i in idxs], client_id=cid)
                    for i, v in zip(idxs, vs):
                        got[i] = v

                async def open_loop():
                    tasks = [one(cid, shard[j:j + per_req])
                             for cid, shard in zip(cids, shards)
                             for j in range(0, len(shard), per_req)]
                    await asyncio.gather(*tasks)

                # two untimed passes: per-shape farm compiles inside
                # each replica process are a once-per-host cost, and
                # batch composition varies pass to pass
                for _ in range(2):
                    got = [None] * len(reqs)
                    await open_loop()
                    if got != expected:
                        return float("inf")
                best = float("inf")
                for _ in range(reps):
                    got = [None] * len(reqs)
                    t0 = time.perf_counter()
                    await open_loop()
                    el = time.perf_counter() - t0
                    if got != expected:
                        return float("inf")
                    best = min(best, el)
                if fv.stats["local"] or fv.stats["local_fastfail"]:
                    return float("inf")   # a replica died mid-run
                return best
            finally:
                await fv.aclose()

        def phase(count: int) -> float:
            pins = slices[:count] if slices is not None else None
            procs = _spawn_fleet_replicas(count, cfg, pins)
            try:
                urls = [f"http://127.0.0.1:{p.port}" for p in procs]
                return asyncio.run(drive(urls))
            finally:
                _stop_fleet_replicas(procs)

        if pin:
            log(f"fleet: pinning each replica to "
                f"{len(slices[0])} core(s) of {len(cores)}")
        else:
            log(f"fleet: NOT pinning ({len(cores)} core(s) for "
                f"{replicas_n} replicas) — a single XLA process "
                f"already saturates this host, so vs_single measures "
                f"overhead, not fleet capacity")
        log(f"fleet: single-process baseline ({workers} workers) ...")
        single_s = phase(1)
        log(f"fleet: {replicas_n}-replica fleet ...")
        fleet_s = phase(replicas_n)

    if single_s == float("inf") or fleet_s == float("inf"):
        log("fleet: FAILED — verdicts diverged from inline "
            "verification or the fleet fell back to the local farm")
        sys.exit(1)
    n = len(expected)
    single_rate = n / single_s
    fleet_rate = n / fleet_s
    ratio = fleet_rate / single_rate
    log(f"fleet: single {single_s:.2f}s ({single_rate:,.0f} items/s), "
        f"{replicas_n} replicas {fleet_s:.2f}s ({fleet_rate:,.0f} "
        f"items/s, {ratio:.2f}x)")
    print(json.dumps({
        "metric": "verifyd_fleet_proofs_per_sec",
        "value": round(fleet_rate, 1),
        "unit": "items/s",
        "single": round(single_rate, 1),
        "vs_single": round(ratio, 2),
        "replicas": replicas_n,
        "clients": clients_n,
        "items": n,
        "cores": len(cores),
        "pinned": bool(pin),
        "bit_identical": True,  # both phases' verdicts checked against
        #                         inline above; a mismatch exits
        #                         non-zero before this line
    }))
    # the >= 1.5x acceptance floor needs one core slice per replica
    # (BENCH_FLEET_MIN_SPEEDUP=1.5 on such hosts); everywhere else the
    # benchtrend vs_single gate is the regression guard
    if min_speedup > 0 and pin and ratio < min_speedup:
        log(f"fleet: FAILED — {ratio:.2f}x < required "
            f"{min_speedup:.2f}x speedup over a single replica")
        sys.exit(1)


# Child body for one fabric measurement. A subprocess per run because
# the fabric is chosen at hub-construction time from the environment and
# because each run must start from a cold loop/registry — measuring both
# fabrics in one process would let the first run's compiled/warmed state
# (and its metric registry) bleed into the second.
_SIM_FABRIC_SRC = """\
import json, pathlib, sys, tempfile, time

from spacemesh_tpu.sim import builtin
from spacemesh_tpu.sim.scenario import run_scenario

with tempfile.TemporaryDirectory() as d:
    t0 = time.perf_counter()
    r = run_scenario(builtin("storm-512-bench"), tmp=pathlib.Path(d))
    wall = time.perf_counter() - t0
hub = r.stats["hub"]
print(json.dumps({
    "ok": r.ok, "digest": r.digest, "sim_wall": round(wall, 3),
    "delivered": hub["delivered"], "relayed": hub["relayed"]}))
"""


def sim_fabric_bench() -> None:
    """Event-wheel scenario fabric vs the legacy task-per-node hub.

    Runs the 514-node ``storm-512-bench`` scenario (sim/scenarios.py: a
    pure-fabric shape — smeshing and tracing off, sparse heartbeats, a
    long quiet tail — so the measurement is the hub's idle+relay cost,
    not the shared consensus/crypto floor) once per fabric in fresh
    subprocesses: the event fabric twice (replay determinism) and the
    legacy hub once.  The scenario digest — the full consensus/coverage
    event record — must be IDENTICAL across all three runs before any
    rate is reported; a divergent world means the fabrics delivered
    different messages and the ratio would be fiction:
      {"metric": "sim_fabric_events_per_sec", "value": N,
       "unit": "events/s", "vs_legacy": N, "bit_identical": true}
    The rate counts useful deliveries (frames handed to a subscriber)
    per wall second; both fabrics deliver the same world, so vs_legacy
    is a pure cost ratio, not a throughput-shape artifact.
    """
    timeout = int(os.environ.get("BENCH_SIM_FABRIC_TIMEOUT", 600))
    log(f"sim fabric: storm-512-bench on both fabrics "
        f"(subprocess runs, <= {timeout}s each) ...")

    def run_one(fabric: str, tag: str) -> dict | None:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SPACEMESH_ROMIX_AUTOTUNE="off",
                   SPACEMESH_SIM_FABRIC=fabric)
        try:
            r = subprocess.run(
                [sys.executable, "-c", _SIM_FABRIC_SRC], env=env,
                timeout=timeout, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            log(f"sim fabric: {tag} timed out (> {timeout}s)")
            return None
        if r.returncode != 0:
            log(f"sim fabric: {tag} failed (rc={r.returncode})")
            sys.stderr.write(r.stderr)
            return None
        doc = None
        for line in reversed(r.stdout.strip().splitlines()):
            try:
                doc = json.loads(line)
                break
            except ValueError:
                continue
        if not isinstance(doc, dict) or not doc.get("ok"):
            log(f"sim fabric: {tag} scenario asserts failed")
            return None
        log(f"sim fabric: {tag}: {doc['sim_wall']:.2f}s, "
            f"{doc['delivered']} delivered, {doc['relayed']} relayed, "
            f"digest {doc['digest'][:16]}")
        return doc

    new1 = run_one("", "event #1")
    new2 = run_one("", "event #2")
    leg = run_one("legacy", "legacy")
    if new1 is None or new2 is None or leg is None:
        log("sim fabric: FAILED — a measurement run did not complete")
        sys.exit(1)
    if new1["digest"] != new2["digest"]:
        log(f"sim fabric: FAILED — event fabric replay diverged "
            f"({new1['digest'][:16]} vs {new2['digest'][:16]})")
        sys.exit(1)
    if new1["digest"] != leg["digest"]:
        log(f"sim fabric: FAILED — event vs legacy digests diverged "
            f"({new1['digest'][:16]} vs {leg['digest'][:16]})")
        sys.exit(1)

    wall_new = min(new1["sim_wall"], new2["sim_wall"])
    rate_new = new1["delivered"] / wall_new
    rate_leg = leg["delivered"] / leg["sim_wall"]
    ratio = rate_new / rate_leg
    log(f"sim fabric: event {wall_new:.2f}s ({rate_new:,.0f} events/s), "
        f"legacy {leg['sim_wall']:.2f}s ({rate_leg:,.0f} events/s, "
        f"{ratio:.2f}x)")
    print(json.dumps({
        "metric": "sim_fabric_events_per_sec",
        "value": round(rate_new, 1),
        "unit": "events/s",
        "legacy": round(rate_leg, 1),
        "vs_legacy": round(ratio, 2),
        "delivered": new1["delivered"],
        "relayed": new1["relayed"],
        "event_wall_s": round(wall_new, 2),
        "legacy_wall_s": round(leg["sim_wall"], 2),
        "bit_identical": True,  # all three digests checked identical
        #                         above; a mismatch exits non-zero
        #                         before this line
    }))


def sim_fabric_mp_bench() -> None:
    """Sharded (multi-process) scenario fabric vs single-process.

    Runs ``storm-512-bench`` with the event wheel sharded over host
    cores (sim/shard.py: conservative virtual-time windows over pipes)
    and single-process, twice each in fresh subprocesses.  The scenario
    is the CLEAN-LINK world — no RNG is ever drawn from the data-plane
    policies — so all four digests (two per shard count) must be
    IDENTICAL before any rate is reported; a divergence means the
    sharded fabric delivered a different world and the ratio would be
    fiction:
      {"metric": "sim_fabric_mp_events_per_sec", "value": N,
       "unit": "events/s", "single": N, "vs_single_process": N,
       "shards": W, "cores": C, "bit_identical": true}
    On hosts without at least two usable cores the fabric is kept
    single-process and the verdict says so honestly (shards=1,
    vs_single_process=1.0) rather than faking a speedup through
    oversubscription; the >= 1.5x acceptance floor
    (BENCH_SIM_FABRIC_MP_MIN_SPEEDUP) is enforced only where the
    parent and every worker get their own core — everywhere else the
    benchtrend vs_single_process gate is the regression guard.
    """
    timeout = int(os.environ.get("BENCH_SIM_FABRIC_MP_TIMEOUT", 900))
    cores = sorted(os.sched_getaffinity(0))
    want = int(os.environ.get("BENCH_SIM_FABRIC_MP_SHARDS", 0))
    shards = want or min(len(cores), 510 // 64)
    capable = len(cores) >= 2 and shards >= 2
    # fleet-bench pattern: the >= 1.5x floor is enforced only where the
    # parent and every worker get their own core (oversubscribed or
    # shared runners measure contention, not the fabric) — everywhere
    # else the benchtrend vs_single_process gate is the guard
    pinned = capable and len(cores) >= shards + 1
    min_speedup = float(os.environ.get(
        "BENCH_SIM_FABRIC_MP_MIN_SPEEDUP", 1.5 if pinned else 0))
    log(f"sim fabric mp: storm-512-bench single-process vs "
        f"{shards}-shard on {len(cores)} core(s) "
        f"(subprocess runs, <= {timeout}s each) ...")

    def run_one(w: int, tag: str) -> dict | None:
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   SPACEMESH_ROMIX_AUTOTUNE="off",
                   SPACEMESH_SIM_FABRIC="",
                   SPACEMESH_SIM_SHARDS=str(w))
        try:
            r = subprocess.run(
                [sys.executable, "-c", _SIM_FABRIC_SRC], env=env,
                timeout=timeout, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            log(f"sim fabric mp: {tag} timed out (> {timeout}s)")
            return None
        if r.returncode != 0:
            log(f"sim fabric mp: {tag} failed (rc={r.returncode})")
            sys.stderr.write(r.stderr)
            return None
        doc = None
        for line in reversed(r.stdout.strip().splitlines()):
            try:
                doc = json.loads(line)
                break
            except ValueError:
                continue
        if not isinstance(doc, dict) or not doc.get("ok"):
            log(f"sim fabric mp: {tag} scenario asserts failed")
            return None
        log(f"sim fabric mp: {tag}: {doc['sim_wall']:.2f}s, "
            f"{doc['delivered']} delivered, digest {doc['digest'][:16]}")
        return doc

    if capable and not pinned:
        log(f"sim fabric mp: NOT enforcing the speedup floor "
            f"({len(cores)} core(s) for {shards} workers + parent) — "
            f"vs_single_process measures contention here, so the "
            f"benchtrend ratio gate is the regression guard")
    s1 = run_one(1, "single #1")
    s2 = run_one(1, "single #2")
    if s1 is None or s2 is None:
        log("sim fabric mp: FAILED — a single-process run did not "
            "complete")
        sys.exit(1)
    if s1["digest"] != s2["digest"]:
        log(f"sim fabric mp: FAILED — single-process replay diverged "
            f"({s1['digest'][:16]} vs {s2['digest'][:16]})")
        sys.exit(1)
    wall_single = min(s1["sim_wall"], s2["sim_wall"])
    rate_single = s1["delivered"] / wall_single

    if not capable:
        log(f"sim fabric mp: kept single-process — {len(cores)} "
            f"core(s) visible; sharding would oversubscribe, not "
            f"speed up")
        print(json.dumps({
            "metric": "sim_fabric_mp_events_per_sec",
            "value": round(rate_single, 1),
            "unit": "events/s",
            "single": round(rate_single, 1),
            "vs_single_process": 1.0,
            "delivered": s1["delivered"],
            "shards": 1,
            "cores": len(cores),
            "pinned": False,
            "kept_single_process": True,
            "bit_identical": True,  # both single-process digests
            #                         checked identical above
        }))
        return

    m1 = run_one(shards, f"{shards}-shard #1")
    m2 = run_one(shards, f"{shards}-shard #2")
    if m1 is None or m2 is None:
        log("sim fabric mp: FAILED — a sharded run did not complete")
        sys.exit(1)
    if m1["digest"] != m2["digest"]:
        log(f"sim fabric mp: FAILED — sharded replay diverged "
            f"({m1['digest'][:16]} vs {m2['digest'][:16]})")
        sys.exit(1)
    if m1["digest"] != s1["digest"]:
        # clean links draw nothing from the net RNG, so W=1 and W=k
        # must land the IDENTICAL digest (docs/SCENARIOS.md)
        log(f"sim fabric mp: FAILED — sharded vs single digests "
            f"diverged ({m1['digest'][:16]} vs {s1['digest'][:16]})")
        sys.exit(1)

    wall_mp = min(m1["sim_wall"], m2["sim_wall"])
    rate_mp = m1["delivered"] / wall_mp
    ratio = rate_mp / rate_single
    log(f"sim fabric mp: single {wall_single:.2f}s "
        f"({rate_single:,.0f} events/s), {shards} shards "
        f"{wall_mp:.2f}s ({rate_mp:,.0f} events/s, {ratio:.2f}x)")
    print(json.dumps({
        "metric": "sim_fabric_mp_events_per_sec",
        "value": round(rate_mp, 1),
        "unit": "events/s",
        "single": round(rate_single, 1),
        "vs_single_process": round(ratio, 2),
        "delivered": m1["delivered"],
        "shards": shards,
        "cores": len(cores),
        "pinned": pinned,
        "single_wall_s": round(wall_single, 2),
        "mp_wall_s": round(wall_mp, 2),
        "bit_identical": True,  # all four digests checked identical
        #                         above; a mismatch exits non-zero
        #                         before this line
    }))
    if min_speedup > 0 and ratio < min_speedup:
        log(f"sim fabric mp: FAILED — {ratio:.2f}x < required "
            f"{min_speedup:.2f}x speedup over single-process")
        sys.exit(1)


def main() -> None:
    n = int(os.environ.get("BENCH_N", 8192))
    reps = int(os.environ.get("BENCH_REPS", 3))
    cpu_count = int(os.environ.get("BENCH_CPU_LABELS", 24))
    batches = [int(b) for b in os.environ.get(
        "BENCH_BATCH", "8192,4096,2048,1024").split(",")]

    commitment = hashlib.sha256(b"bench-commitment").digest()

    from spacemesh_tpu.utils import accel

    cache_dir = accel.enable_persistent_cache()
    log(f"persistent compile cache: {cache_dir or 'disabled'}")

    fallback = ""
    if not accel.ensure_usable_platform():
        log("accelerator unreachable; falling back to CPU platform")
        fallback = "_cpufallback"
        # big batches only waste compile time on host CPU; add a smaller
        # candidate the TPU sweep skips (cache-friendlier ROMix scratch)
        batches = [b for b in batches if b <= 2048] or [1024]
        if 512 not in batches:
            batches.append(512)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from spacemesh_tpu.ops import scrypt

    dev = jax.devices()[0]
    log(f"device: {dev} platform={dev.platform}")

    cw = jnp.asarray(scrypt.commitment_to_words(commitment))
    compile_times: dict[int, float] = {}

    def measure(batch: int) -> float:
        idx = np.arange(batch, dtype=np.uint64)
        lo_, hi_ = scrypt.split_indices(idx)
        lo, hi = jnp.asarray(lo_), jnp.asarray(hi_)
        t0 = time.perf_counter()
        out = scrypt.scrypt_labels_jit(cw, lo, hi, n=n)
        out.block_until_ready()
        compile_s = time.perf_counter() - t0
        compile_times.setdefault(batch, compile_s)
        log(f"batch={batch}: compile+first run {compile_s:.1f}s")
        # steady state: the compiled executable is reused for every rep,
        # all reps enqueued back-to-back, one sync at the end (pipelined,
        # as post/initializer.py drives the device)
        t0 = time.perf_counter()
        outs = [scrypt.scrypt_labels_jit(cw, lo, hi, n=n)
                for _ in range(reps)]
        jax.block_until_ready(outs)
        return reps * batch / (time.perf_counter() - t0)

    best_rate, best_batch = 0.0, 0
    for batch in batches:
        try:
            rate = measure(batch)
            log(f"batch={batch}: {rate:,.0f} labels/s")
            if rate > best_rate:
                best_rate, best_batch = rate, batch
        except Exception as e:  # noqa: BLE001 — e.g. HBM OOM at big batches
            log(f"batch={batch}: failed ({type(e).__name__}: {e})")
    if best_rate == 0.0:
        raise SystemExit("all batch sizes failed")

    # the kernel choice (xla / xla-rows / pallas, lane chunk) was raced
    # and persisted by ops/autotune.py inside the first measure() call;
    # a second bench run on this host reuses the persisted winner with
    # no re-race (docs/ROMIX_KERNEL.md)
    from spacemesh_tpu.ops import autotune

    decision = autotune.decide(n, best_batch)
    log(f"romix kernel: impl={decision.impl} chunk={decision.chunk} "
        f"(source={decision.source})")

    # kernel-only throughput: the ROMix stage alone on the autotune
    # calibration workload — isolates the memory-hard core from the
    # PBKDF2 envelope + host dispatch that the headline number includes
    x = jnp.asarray(autotune.calibration_block(best_batch))
    interpret = decision.impl == "pallas" and dev.platform != "tpu"

    def romix_only():
        return scrypt.romix_tuned(x, n=n, impl=decision.impl,
                                  chunk=decision.chunk, interpret=interpret)

    romix_only().block_until_ready()  # compile (shared with the race)
    t0 = time.perf_counter()
    jax.block_until_ready([romix_only() for _ in range(reps)])
    kernel_rate = reps * best_batch / (time.perf_counter() - t0)
    log(f"kernel-only (romix): {kernel_rate:,.0f} labels/s")

    def single_device_digest() -> str:
        # single-device label digest for the mesh bit-identity check (one
        # more steady-state run of the compiled executable); only paid
        # when a mesh measurement actually produced a rate to vet
        idx = np.arange(best_batch, dtype=np.uint64)
        lo_, hi_ = scrypt.split_indices(idx)
        single_words = scrypt.scrypt_labels_jit(
            cw, jnp.asarray(lo_), jnp.asarray(hi_), n=n)
        return hashlib.sha256(
            scrypt.labels_to_bytes(np.asarray(single_words))).hexdigest()

    mesh_doc = None
    if os.environ.get("BENCH_MESH", "1") not in ("0", "off"):
        if fallback or jax.default_backend() == "cpu":
            # CPU platform — via probe fallback OR an explicit
            # JAX_PLATFORMS=cpu (CI's mesh-smoke job): forced virtual
            # host devices split the CPU thread pool, so the mesh
            # measurement lives in a subprocess — the numbers above stay
            # honest single-device-with-all-threads
            mesh_doc = run_mesh_probe(n, best_batch, reps)
        elif jax.device_count() > 1:
            mesh_doc = measure_mesh(n, best_batch, reps)
    if mesh_doc is not None and mesh_doc.get("labels_per_sec") \
            and mesh_doc.get("digest") != single_device_digest():
        # corrupted sharded labels must be a red build, not a quietly
        # missing headline (CI greps can't tell absent from broken)
        log(f"mesh: FAILED — sharded labels diverged from the "
            f"single-device digest at n={n} b={best_batch} "
            f"d={mesh_doc.get('devices')}")
        sys.exit(1)

    log(f"CPU baseline: {cpu_count} labels via hashlib.scrypt ...")
    cpu_rate = cpu_labels_per_sec(commitment, n, cpu_count)
    log(f"cpu: {cpu_rate:,.1f} labels/s (single core, OpenSSL)")

    print(json.dumps({
        "metric": f"post_init_labels_per_sec_n{n}_b{best_batch}{fallback}",
        "value": round(best_rate, 1),
        "unit": "labels/s",
        "vs_baseline": round(best_rate / cpu_rate, 2),
        "impl": decision.impl,
        "chunk": decision.chunk,
        "tuned": decision.source,
        "fused": True,  # expand->romix->finish as one jitted program
    }))
    print(json.dumps({
        "metric": "post_init_kernel_labels_per_sec",
        "value": round(kernel_rate, 1),
        "unit": "labels/s",
        "impl": decision.impl,
        "chunk": decision.chunk,
        "batch": best_batch,
    }))
    if mesh_doc is not None and mesh_doc.get("labels_per_sec"):
        mesh_rate = mesh_doc["labels_per_sec"]
        log(f"mesh: {mesh_rate:,.0f} labels/s over "
            f"{mesh_doc['devices']} devices ({mesh_rate / best_rate:.2f}x "
            f"single-device)")
        print(json.dumps({
            "metric": f"post_init_labels_per_sec_mesh_n{n}"
                      f"_b{best_batch}{fallback}",
            "value": mesh_rate,
            "unit": "labels/s",
            "devices": mesh_doc["devices"],
            "devices_visible": mesh_doc.get("devices_visible"),
            "impl": mesh_doc["impl"],
            "tuned": mesh_doc.get("tuned"),
            "vs_single": round(mesh_rate / best_rate, 2),
            "vs_baseline": round(mesh_rate / cpu_rate, 2),
            "compile_s": mesh_doc.get("compile_s"),
            "bit_identical": True,  # digest-checked above; a mismatch
            #                         exits non-zero before this line
        }))
    elif mesh_doc is not None:
        log(f"mesh: autotuner kept single-device "
            f"(devices={mesh_doc.get('devices')}); no mesh headline")

    # compile cost of the winning shape, reported separately: near-zero on
    # a warm persistent cache, the full XLA compile on a cold one
    print(json.dumps({
        "metric": "post_init_compile_s",
        "value": round(compile_times.get(best_batch, 0.0), 2),
        "unit": "s",
        "cache_dir": cache_dir or "",
    }))

    prove_labels = int(os.environ.get("BENCH_PROVE_LABELS", 1 << 16))
    if prove_labels > 0:
        prove_bench(prove_labels,
                    int(os.environ.get("BENCH_PROVE_BATCH", 2048)))

    if int(os.environ.get("BENCH_TENANTS", 16)) > 0:
        if (fallback or jax.default_backend() == "cpu") \
                and os.environ.get("BENCH_MESH", "1") not in ("0", "off"):
            # CPU platform: measure the packer over forced virtual host
            # devices in a subprocess (the mesh-sharded pack dispatch),
            # keeping this process honestly single-device
            run_mt_probe()
        else:
            multi_tenant_bench()

    verify_items = int(os.environ.get("BENCH_VERIFY_ITEMS", 512))
    if verify_items > 0:
        verify_bench(verify_items)

    verifyd_items = int(os.environ.get("BENCH_VERIFYD_ITEMS", 384))
    if verifyd_items > 0:
        verifyd_bench(verifyd_items)

    fleet_items = int(os.environ.get("BENCH_FLEET_ITEMS", 384))
    if fleet_items > 0:
        fleet_bench(fleet_items)

    if os.environ.get("BENCH_SIM_FABRIC", "1") not in ("0", "off"):
        sim_fabric_bench()

    if os.environ.get("BENCH_SIM_FABRIC_MP", "1") not in ("0", "off"):
        sim_fabric_mp_bench()


if __name__ == "__main__":
    if "--mesh-probe" in sys.argv[1:]:
        raise SystemExit(mesh_probe_main())
    if "--mt-probe" in sys.argv[1:]:
        raise SystemExit(mt_probe_main())
    main()
