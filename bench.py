"""Headline benchmark: POST init labels/sec on one chip (mainnet N=8192).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "labels/s", "vs_baseline": N}

vs_baseline is the speedup over the reference CPU labeling path measured
in-process (hashlib.scrypt = OpenSSL scrypt, the same labeling function the
reference's CPU provider computes; the reference publishes no numbers of
its own — BASELINE.md). Progress goes to stderr; stdout carries only the
JSON line.

Env knobs: BENCH_BATCH (label lanes per program), BENCH_N (scrypt N),
BENCH_REPS, BENCH_CPU_LABELS.
"""

import hashlib
import json
import os
import sys
import time


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def cpu_labels_per_sec(commitment: bytes, n: int, count: int) -> float:
    t0 = time.perf_counter()
    for i in range(count):
        hashlib.scrypt(commitment, salt=i.to_bytes(8, "little"), n=n, r=1,
                       p=1, maxmem=256 * 1024 * 1024, dklen=16)
    dt = time.perf_counter() - t0
    return count / dt


# probe + CPU fallback shared with tools/profiler.py — ONE copy of the
# wedged-tunnel handling (spacemesh_tpu/utils/accel.py)


def main() -> None:
    n = int(os.environ.get("BENCH_N", 8192))
    reps = int(os.environ.get("BENCH_REPS", 3))
    cpu_count = int(os.environ.get("BENCH_CPU_LABELS", 24))
    batches = [int(b) for b in os.environ.get(
        "BENCH_BATCH", "8192,4096,2048,1024").split(",")]

    commitment = hashlib.sha256(b"bench-commitment").digest()

    from spacemesh_tpu.utils import accel

    fallback = ""
    if not accel.ensure_usable_platform():
        log("accelerator unreachable; falling back to CPU platform")
        fallback = "_cpufallback"
        batches = [b for b in batches if b <= 2048] or [1024]

    import jax
    import jax.numpy as jnp
    import numpy as np

    from spacemesh_tpu.ops import scrypt

    dev = jax.devices()[0]
    log(f"device: {dev} platform={dev.platform}")

    cw = jnp.asarray(scrypt.commitment_to_words(commitment))

    def measure(batch: int) -> float:
        idx = np.arange(batch, dtype=np.uint64)
        lo_, hi_ = scrypt.split_indices(idx)
        lo, hi = jnp.asarray(lo_), jnp.asarray(hi_)
        t0 = time.perf_counter()
        out = scrypt.scrypt_labels_jit(cw, lo, hi, n=n)
        out.block_until_ready()
        log(f"batch={batch}: compile+first run "
            f"{time.perf_counter() - t0:.1f}s")
        rate = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            out = scrypt.scrypt_labels_jit(cw, lo, hi, n=n)
            out.block_until_ready()
            rate = max(rate, batch / (time.perf_counter() - t0))
        return rate

    best_rate, best_batch = 0.0, 0
    for batch in batches:
        try:
            rate = measure(batch)
            log(f"batch={batch}: {rate:,.0f} labels/s")
            if rate > best_rate:
                best_rate, best_batch = rate, batch
        except Exception as e:  # noqa: BLE001 — e.g. HBM OOM at big batches
            log(f"batch={batch}: failed ({type(e).__name__}: {e})")
    if best_rate == 0.0:
        raise SystemExit("all batch sizes failed")

    impl = "xla"
    if not fallback:
        # race the contiguous-row Pallas ROMix candidate at the winning
        # batch (docs/ROUND2_NOTES.md analysis; only meaningful on real
        # TPU — the CPU interpreter executes each DMA in Python)
        try:
            os.environ["SPACEMESH_ROMIX"] = "pallas"
            pallas_rate = measure(best_batch)
            log(f"pallas romix @ batch={best_batch}: "
                f"{pallas_rate:,.0f} labels/s")
            if pallas_rate > best_rate:
                best_rate, impl = pallas_rate, "pallas"
        except Exception as e:  # noqa: BLE001 — candidate may not compile
            log(f"pallas romix failed ({type(e).__name__}: {e})")
        finally:
            os.environ.pop("SPACEMESH_ROMIX", None)
    log(f"winner: {impl} romix")

    log(f"CPU baseline: {cpu_count} labels via hashlib.scrypt ...")
    cpu_rate = cpu_labels_per_sec(commitment, n, cpu_count)
    log(f"cpu: {cpu_rate:,.1f} labels/s (single core, OpenSSL)")

    print(json.dumps({
        "metric": f"post_init_labels_per_sec_n{n}_b{best_batch}{fallback}",
        "value": round(best_rate, 1),
        "unit": "labels/s",
        "vs_baseline": round(best_rate / cpu_rate, 2),
    }))


if __name__ == "__main__":
    main()
