#!/bin/bash
# Persistent TPU-tunnel watchdog (VERDICT r3 "Next round" item 1).
# Probes jax.devices() under the axon platform on a timer for the whole
# round, logs EVERY attempt, and on first success runs tpu_validate.py
# (bit-exactness + ROMix race + throughput) exactly once per heal.
cd /root/repo
LOG=tpu_watchdog.log
MARK=tpu_results/VALIDATE_OK
mkdir -p tpu_results
echo "$(date -Is) watchdog start (pid $$)" >> "$LOG"
while true; do
  if timeout 120 python -c "import jax; d=jax.devices()[0]; print(d.platform, getattr(d,'device_kind','?'))" >> "$LOG" 2>&1; then
    echo "$(date -Is) probe OK" >> "$LOG"
    if [ ! -f "$MARK" ]; then
      echo "$(date -Is) running tpu_validate.py" >> "$LOG"
      if timeout 3000 python tpu_validate.py >> "$LOG" 2>&1; then
        touch "$MARK"
        echo "$(date -Is) VALIDATE OK" >> "$LOG"
      else
        echo "$(date -Is) validate failed/partial (see tpu_results/)" >> "$LOG"
      fi
    fi
    sleep 1200
  else
    echo "$(date -Is) probe timeout/fail" >> "$LOG"
    sleep 420
  fi
done
