"""Unit + equivalence coverage for the shared device-job runtime.

The engine (runtime/engine.py) replaced four hand-rolled copies of the
bounded dispatch->retire window; these tests pin its contracts — window
bound, FIFO retires, early exit, stop-discard, fallback — and then the
bit-identity of each migrated pipeline against its pre-runtime twin at
ragged totals (ISSUE 11 test satellite).  The lane-admission primitives
the farm now consumes (runtime/queue.py) get the review-fix semantics
asserted at unit level (the cancelled-waiter slot handoff).
"""

import asyncio
import enum
import hashlib

import numpy as np
import pytest

from spacemesh_tpu.runtime import engine
from spacemesh_tpu.runtime.queue import KindLanes, LaneGroup, QueueClosed
from spacemesh_tpu.utils import metrics


# --- Pipeline ----------------------------------------------------------


def test_pipeline_window_bound_and_fifo():
    depths = []
    retired = []
    pipe = engine.Pipeline(kind="t", inflight=3,
                           on_inflight=depths.append)
    res = pipe.run(range(10), dispatch=lambda i: i * 10,
                   retire=lambda t: retired.append(t))
    assert res is None
    assert retired == [i * 10 for i in range(10)]  # FIFO
    assert max(depths) == 3                        # bounded window
    assert pipe.stats.batches == 10
    assert not pipe.stats.early_exited and not pipe.stats.stopped


def test_pipeline_early_exit_abandons_inflight():
    dispatched = []
    retired = []

    def retire(t):
        retired.append(t)
        return "winner" if t == 2 else None

    pipe = engine.Pipeline(kind="t", inflight=3)
    res = pipe.run(iter(range(100)), dispatch=lambda i: dispatched.append(i)
                   or i, retire=retire)
    assert res == "winner"
    assert pipe.stats.early_exited
    # items 0,1,2 dispatched before the first retire could fire at
    # window depth 3; the early exit at ticket 2 stops the stream well
    # short of 100 and abandons the rest
    assert retired == [0, 1, 2]
    assert len(dispatched) < 10


def test_pipeline_stop_discards_pending():
    stop = [False]
    retired = []

    def dispatch(i):
        if i == 4:
            stop[0] = True
        return i

    pipe = engine.Pipeline(kind="t", inflight=8, stop=lambda: stop[0])
    res = pipe.run(range(10), dispatch, retired.append)
    assert res is None
    assert pipe.stats.stopped
    assert retired == []  # discarded, never retired


def test_pipeline_fallback_on_dispatch_failure():
    before = sum(metrics.runtime_fallbacks.sample().values())

    def dispatch(i):
        if i == 1:
            raise RuntimeError("device down")
        return ("dev", i)

    pipe = engine.Pipeline(kind="t", inflight=2,
                           fallback=lambda i, exc: ("host", i))
    out = []
    pipe.run(range(3), dispatch, out.append)
    assert out == [("dev", 0), ("host", 1), ("dev", 2)]
    assert pipe.stats.fallbacks == 1
    assert sum(metrics.runtime_fallbacks.sample().values()) == before + 1

    # without a fallback the exception propagates
    with pytest.raises(RuntimeError):
        engine.Pipeline(kind="t").run(range(3), dispatch, out.append)


def test_pipeline_sustained_failure_repays_device_without_breaker():
    """The pre-remediation regression, pinned: with no breaker there is
    no memory between batches — a permanently dead backend is re-paid
    the failing dispatch on EVERY batch."""
    attempts = [0]

    def dispatch(i):
        attempts[0] += 1
        raise RuntimeError("device permanently dead")

    pipe = engine.Pipeline(kind="t-nobreak", inflight=2,
                           fallback=lambda i, exc: ("host", i))
    pipe.run(range(50), dispatch, lambda t: None)
    assert attempts[0] == 50            # one failing attempt per batch
    assert pipe.stats.fallbacks == 50


def test_pipeline_breaker_stops_repaying_dead_device():
    """ISSUE 15 satellite: after the breaker trips, dispatch goes
    straight to fallback — exactly N device attempts for an M>>N-batch
    run, and runtime_fallbacks_total still counts every batch."""
    from spacemesh_tpu.obs import remediate

    clock = [0.0]  # frozen: the open breaker never reaches half-open
    br = remediate.CircuitBreaker("t-dev", failure_budget=3,
                                  window_s=60.0, cooldown_s=30.0,
                                  time_source=lambda: clock[0])
    attempts = [0]

    def dispatch(i):
        attempts[0] += 1
        raise RuntimeError("device permanently dead")

    before = sum(metrics.runtime_fallbacks.sample().values())
    out = []
    pipe = engine.Pipeline(kind="t-break", inflight=2, breaker=br,
                           fallback=lambda i, exc: ("host", i, exc))
    pipe.run(range(50), dispatch, out.append)
    assert attempts[0] == 3             # the budget, NOT one per batch
    assert len(out) == 50               # every batch still answered
    assert pipe.stats.fallbacks == 50
    assert sum(metrics.runtime_fallbacks.sample().values()) == before + 50
    assert br.state == remediate.OPEN
    # post-trip batches carry the typed BreakerOpen, not the stale
    # device error
    assert isinstance(out[-1][2], remediate.BreakerOpen)
    # device recovery: cooldown elapses, ONE probe re-closes, dispatch
    # resumes on the device path
    clock[0] = 100.0
    good = engine.Pipeline(kind="t-break", inflight=2, breaker=br,
                           fallback=lambda i, exc: ("host", i, exc))
    dev_out = []
    good.run(range(5), lambda i: ("dev", i), dev_out.append)
    assert good.stats.fallbacks == 0
    assert dev_out == [("dev", i) for i in range(5)]
    assert br.state == remediate.CLOSED


def test_pipeline_breaker_open_without_fallback_raises_typed():
    from spacemesh_tpu.obs import remediate

    br = remediate.CircuitBreaker("t-nofb", failure_budget=1,
                                  cooldown_s=30.0,
                                  time_source=lambda: 0.0)
    br.record_failure()
    with pytest.raises(remediate.BreakerOpen):
        engine.Pipeline(kind="t-nofb", breaker=br).run(
            range(3), lambda i: i, lambda t: None)


def test_pipeline_idle_sentinel_retires_without_dispatch():
    retired = []
    pipe = engine.Pipeline(kind="t", inflight=8)

    def items():
        yield 1
        yield 2
        assert pipe.pending_count == 2
        yield engine.IDLE      # retires 1
        yield engine.IDLE      # retires 2
        assert pipe.pending_count == 0
        yield engine.IDLE      # no-op on an empty window
        yield 3

    pipe.run(items(), dispatch=lambda i: i, retire=retired.append)
    assert retired == [1, 2, 3]


def test_pipeline_tenant_label_on_metrics():
    before = metrics.runtime_dispatched.sample().get(
        (("kind", "t-label"), ("tenant", "alice")), 0)
    pipe = engine.Pipeline(kind="t-label", tenant="alice", inflight=1)
    pipe.run(range(3), lambda i: i, lambda t: None)
    after = metrics.runtime_dispatched.sample()[
        (("kind", "t-label"), ("tenant", "alice"))]
    assert after == before + 3


# --- LaneGroup / KindLanes --------------------------------------------


class _L(enum.IntEnum):
    HI = 0
    LO = 1


class _Entry:
    def __init__(self, lane, deadline=0.0):
        self.lane = lane
        self.deadline = deadline


def test_lane_group_bounds_and_release():
    async def main():
        g = LaneGroup(_L, {_L.HI: 2, _L.LO: 1})
        g.bind(asyncio.get_running_loop())
        await g.acquire(_L.LO)   # room: returns immediately
        g.add(_L.LO)
        waiter = asyncio.ensure_future(g.acquire(_L.LO))
        await asyncio.sleep(0)
        assert not waiter.done()  # lane full: parked
        g.release(_L.LO)
        await asyncio.wait_for(waiter, 1)

    asyncio.run(main())


def test_lane_group_cancelled_waiter_hands_slot_on():
    """The PR-2 review-fix semantics, now asserted at the runtime
    layer: a waiter cancelled after release() resolved it must hand
    the freed slot to the next waiter."""

    async def main():
        g = LaneGroup(_L, {_L.HI: 1, _L.LO: 1})
        g.bind(asyncio.get_running_loop())
        g.add(_L.LO)  # full
        a = asyncio.ensure_future(g.acquire(_L.LO))
        b = asyncio.ensure_future(g.acquire(_L.LO))
        for _ in range(3):
            await asyncio.sleep(0)
        g.release(_L.LO)   # resolves a's waiter
        a.cancel()         # ...which a never consumes
        with pytest.raises(asyncio.CancelledError):
            await a
        await asyncio.wait_for(b, 1)  # hangs without the handoff

    asyncio.run(main())


def test_lane_group_close_fails_waiters():
    async def main():
        g = LaneGroup(_L, {_L.HI: 1, _L.LO: 1},
                      make_exc=lambda: QueueClosed("closed"))
        g.bind(asyncio.get_running_loop())
        g.add(_L.LO)
        w = asyncio.ensure_future(g.acquire(_L.LO))
        await asyncio.sleep(0)
        g.closed = True
        g.fail_waiters()
        with pytest.raises(QueueClosed):
            await w

    asyncio.run(main())


def test_kind_lanes_priority_and_promote():
    async def main():
        g = LaneGroup(_L, {_L.HI: 8, _L.LO: 8})
        g.bind(asyncio.get_running_loop())
        kl = KindLanes(g)
        lo1, lo2 = _Entry(_L.LO, 5.0), _Entry(_L.LO, 6.0)
        hi = _Entry(_L.HI, 9.0)
        for e in (lo1, lo2, hi):
            kl.append(e)
        assert kl.count() == 3 and g.total() == 3
        assert kl.earliest_deadline() == 5.0
        # promote lo2 to HI (the dedup-hit path): removed + re-added
        assert kl.remove(lo2)
        lo2.lane = _L.HI
        kl.append(lo2)
        batch = kl.take(10)
        assert batch == [hi, lo2, lo1]  # HI lane drains first
        assert not kl.remove(lo1)       # already taken

    asyncio.run(main())


# --- migrated-pipeline equivalence (pre-runtime twins) -----------------


def _host_vrf_nonce(label_bytes: bytes) -> int:
    halves = np.frombuffer(label_bytes, dtype="<u8").reshape(-1, 2)
    return int(np.lexsort((np.arange(halves.shape[0]),
                           halves[:, 0], halves[:, 1]))[0])


@pytest.mark.parametrize("total", [1, 7, 1000])
def test_initializer_on_engine_matches_reference(tmp_path, total):
    from spacemesh_tpu.ops import scrypt
    from spacemesh_tpu.post import initializer
    from spacemesh_tpu.post.data import LabelStore

    node = hashlib.sha256(b"rt-node").digest()
    commit = hashlib.sha256(b"rt-commit").digest()
    d = tmp_path / f"init-{total}"
    meta, res = initializer.initialize(
        d, node_id=node, commitment=commit, num_units=1,
        labels_per_unit=total, scrypt_n=2, max_file_size=1 << 20,
        batch_size=128)
    store = LabelStore(d, meta)
    got = store.read_labels(0, total)
    store.close()
    ref = scrypt.scrypt_labels(
        commit, np.arange(total, dtype=np.uint64), n=2).tobytes()
    assert got == ref
    assert meta.vrf_nonce == _host_vrf_nonce(ref)
    assert res.labels_written == total


def test_prover_on_engine_matches_serial_twin(tmp_path):
    from spacemesh_tpu.post import workload

    prover = workload.build(str(tmp_path / "st"), 1039, 256)
    pipelined = prover.prove(workload.CHALLENGE)
    serial = prover.prove_serial(workload.CHALLENGE)
    assert pipelined == serial
    assert workload.verify_proof(pipelined, 1039)


def test_prove_session_steps_match_inline(tmp_path):
    from spacemesh_tpu.post import workload

    prover = workload.build(str(tmp_path / "st"), 512, 256)
    session = prover.session(workload.CHALLENGE, tenant="alice")
    try:
        proof = None
        steps = 0
        while proof is None:
            proof = session.step()
            steps += 1
            assert steps < 100
        assert session.done
    finally:
        session.close()
    assert proof == prover.prove_serial(workload.CHALLENGE)
    # close is idempotent; a closed session refuses to step
    session.close()
    with pytest.raises(RuntimeError):
        session.step()


def test_k2pow_on_engine_matches_serial_twin():
    import jax.numpy as jnp

    from spacemesh_tpu.ops import pow as k2pow

    ch = hashlib.sha256(b"rt-pow-c").digest()
    nid = hashlib.sha256(b"rt-pow-n").digest()
    diff = bytes([0, 16]) + bytes([255]) * 30

    def serial(batch):
        st = jnp.asarray(k2pow.prefix_state(ch, nid))
        tgt = jnp.asarray(k2pow._words_be(diff))
        for i in range(1 << 16):
            nn = np.arange(i * batch, (i + 1) * batch, dtype=np.uint64)
            ok = np.asarray(k2pow.below_target_jit(
                k2pow.pow_hash_batch_jit(
                    st, jnp.asarray((nn & 0xFFFFFFFF).astype(np.uint32)),
                    jnp.asarray((nn >> 32).astype(np.uint32))), tgt))
            hits = np.nonzero(ok)[0]
            if hits.size:
                return int(nn[hits[0]])

    got = k2pow.search(ch, nid, diff, batch=2048)
    assert got == serial(2048)
    assert k2pow.verify(ch, nid, diff, got)
    # exhaustion is still None, not an exception
    assert k2pow.search(ch, nid, bytes(32), batch=64, max_batches=2) is None


def test_k2pow_host_fallback_identical(monkeypatch):
    from spacemesh_tpu.ops import pow as k2pow

    ch = hashlib.sha256(b"rt-pow-fb-c").digest()
    nid = hashlib.sha256(b"rt-pow-fb-n").digest()
    diff = bytes([0, 16]) + bytes([255]) * 30
    want = k2pow.search(ch, nid, diff, batch=2048)

    def boom(*a, **k):
        raise RuntimeError("device down")

    monkeypatch.setattr(k2pow, "pow_hash_batch_jit", boom)
    assert k2pow.search(ch, nid, diff, batch=2048) == want
