"""Verification farm (spacemesh_tpu/verify/): adversarial batches,
lanes, dedup, cancellation, deadline-expiry, backpressure, and the
sync-fallback contract (ISSUE 2).

The core acceptance property: a farm dispatch mixing valid, invalid,
and structurally malformed proofs must resolve EVERY future with
exactly the accept/reject decision the inline verifier gives for that
item — batching is a scheduling change, never a semantic one.
"""

import asyncio
import dataclasses
import threading
import time

import pytest

from spacemesh_tpu.consensus import malfeasance
from spacemesh_tpu.core import types
from spacemesh_tpu.core.signing import Domain, EdSigner, EdVerifier
from spacemesh_tpu.p2p.pubsub import PubSub
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage.cache import AtxCache
from spacemesh_tpu.verify import workload
from spacemesh_tpu.verify.farm import (
    FarmClosed,
    Lane,
    SigRequest,
    VerificationFarm,
)


@pytest.fixture(scope="module")
def wl(tmp_path_factory):
    """One small mixed workload (includes malformed items) per module —
    the POST init + proofs inside are the expensive part."""
    d = tmp_path_factory.mktemp("verify-wl")
    return workload.build(str(d), sigs=20, vrfs=6, posts=10,
                          memberships=8, post_challenges=2)


def _farm_for(wl, **kw):
    kw.setdefault("ed_verifier", wl.ed)
    kw.setdefault("vrf_verifier", wl.vrf)
    kw.setdefault("post_params", wl.post_params)
    kw.setdefault("post_seed", wl.post_seed)
    return VerificationFarm(**kw)


def _sig_reqs(n, valid=True, salt=b""):
    s = EdSigner(seed=bytes(31) + b"\x01")
    out = []
    for i in range(n):
        msg = b"m" + salt + i.to_bytes(4, "little")
        sig = s.sign(Domain.HARE, msg)
        if not valid:
            sig = bytes(64)
        out.append(SigRequest(int(Domain.HARE), s.public_key, msg, sig))
    return out


class _BlockingBackend:
    """Wrap farm._run_backend so the FIRST dispatch blocks on an event
    (simulating a slow device pass) while later dispatches run live."""

    def __init__(self, farm, block_first=1, sleep_s=0.0):
        self.real = farm._run_backend
        self.gate = threading.Event()
        self.block_left = block_first
        self.sleep_s = sleep_s
        self.lock = threading.Lock()
        farm._run_backend = self  # type: ignore[method-assign]

    def __call__(self, kind, reqs):
        with self.lock:
            blocked = self.block_left > 0
            self.block_left -= 1 if blocked else 0
        if blocked:
            assert self.gate.wait(30), "test gate never released"
        if self.sleep_s:
            time.sleep(self.sleep_s)
        return self.real(kind, reqs)


# --- decision parity ------------------------------------------------------


def test_adversarial_batch_matches_inline(wl):
    """Valid + invalid + malformed, all lanes, one farm: bit-identical
    accept/reject decisions vs the inline verifiers."""
    expected = wl.inline_all()
    assert 0 < sum(expected) < len(expected), "workload must be mixed"

    async def main():
        farm = _farm_for(wl)
        lanes = [Lane.BLOCK, Lane.GOSSIP, Lane.SYNC]
        got = await asyncio.gather(
            *(farm.submit(r, lane=lanes[i % 3])
              for i, r in enumerate(wl.requests)))
        await farm.aclose()
        return got

    got = asyncio.run(main())
    assert got == expected


def test_parity_across_repeat_submission(wl):
    """Same workload a second time through one farm (dedup entries from
    resolved batches must not leak stale verdicts)."""

    async def main():
        farm = _farm_for(wl)
        first = await asyncio.gather(*(farm.submit(r)
                                       for r in wl.requests))
        second = await asyncio.gather(*(farm.submit(r)
                                        for r in wl.requests))
        await farm.aclose()
        return first, second

    first, second = asyncio.run(main())
    assert first == second == wl.inline_all()


# --- scheduler behavior ---------------------------------------------------


def test_dedup_shares_one_verdict():
    async def main():
        farm = VerificationFarm()
        bb = _BlockingBackend(farm)
        [req] = _sig_reqs(1)
        t1 = asyncio.ensure_future(farm.submit(req))
        await asyncio.sleep(0.05)  # t1's batch is now blocked in dispatch
        t2 = asyncio.ensure_future(farm.submit(req))
        t3 = asyncio.ensure_future(farm.submit(req))
        await asyncio.sleep(0.05)
        bb.gate.set()
        got = await asyncio.gather(t1, t2, t3)
        stats = dict(farm.stats)
        await farm.aclose()
        return got, stats

    got, stats = asyncio.run(main())
    assert got == [True, True, True]
    assert stats["dedup_hits"] >= 2
    assert stats["items"] == 1  # one request ever reached a backend


def test_dedup_promotes_to_higher_priority_lane():
    """A BLOCK-lane submit that dedups onto a queued SYNC twin must pull
    the entry into the BLOCK lane — not inherit SYNC's queue position."""

    async def main():
        farm = VerificationFarm(max_inflight=1)
        bb = _BlockingBackend(farm)
        first = asyncio.ensure_future(farm.submit(_sig_reqs(1)[0]))
        await asyncio.sleep(0.05)  # dispatch blocked; cap=1 saturated
        [req] = _sig_reqs(1, salt=b"pm")
        sync_t = asyncio.ensure_future(farm.submit(req, lane=Lane.SYNC))
        await asyncio.sleep(0.02)  # queued, held by the in-flight cap
        t0 = time.perf_counter()
        # without promotion this waits on the capped SYNC entry until
        # the gate opens; with it, BLOCK bypasses the cap at its deadline
        ok = await asyncio.wait_for(farm.submit(req, lane=Lane.BLOCK), 5)
        latency = time.perf_counter() - t0
        bb.gate.set()
        assert await sync_t is True  # the shared verdict reached both
        assert await first is True
        await farm.aclose()
        return ok, latency

    ok, latency = asyncio.run(main())
    assert ok is True
    assert latency < 1.0, latency


def test_deadline_dispatches_partial_batch():
    """With the backend busy, queued requests must dispatch when the
    lane's max-latency deadline expires — NOT wait for max_batch."""

    async def main():
        farm = VerificationFarm(max_batch=10_000)
        bb = _BlockingBackend(farm)
        first = asyncio.ensure_future(farm.submit(_sig_reqs(1)[0]))
        await asyncio.sleep(0.05)  # first dispatch now blocked
        reqs = _sig_reqs(5, salt=b"dl")
        t0 = time.perf_counter()
        got = await asyncio.gather(*(farm.submit(r) for r in reqs))
        latency = time.perf_counter() - t0
        stats = dict(farm.stats)
        bb.gate.set()
        assert await first is True
        await farm.aclose()
        return got, latency, stats

    got, latency, stats = asyncio.run(main())
    assert got == [True] * 5
    # 5ms gossip deadline, generous CI margin — the point is "well under
    # forever", since max_batch can never fill
    assert latency < 5.0
    assert stats["max_occupancy"] >= 5  # the 5 coalesced into one batch


def test_block_lane_not_starved_by_sync_flood():
    """Acceptance: a saturated sync lane never delays block-critical
    dispatch beyond its deadline (the BLOCK lane bypasses the in-flight
    cap and is drained first)."""

    async def main():
        farm = VerificationFarm(max_batch=8, max_inflight=2)
        _BlockingBackend(farm, block_first=0, sleep_s=0.15)
        flood = [asyncio.ensure_future(farm.submit(r, lane=Lane.SYNC))
                 for r in _sig_reqs(160, salt=b"fl")]
        await asyncio.sleep(0.05)  # flood is mid-dispatch, lanes deep
        t0 = time.perf_counter()
        ok = await farm.submit(_sig_reqs(1, salt=b"blk")[0],
                               lane=Lane.BLOCK)
        block_latency = time.perf_counter() - t0
        still_pending = sum(1 for f in flood if not f.done())
        await asyncio.gather(*flood)
        await farm.aclose()
        return ok, block_latency, still_pending

    ok, block_latency, still_pending = asyncio.run(main())
    assert ok is True
    # 160 sync items at 0.15s per 8-item batch ≈ seconds of flood; the
    # block item must not ride out the whole flood
    assert block_latency < 1.0, block_latency
    assert still_pending > 16, still_pending  # flood genuinely mid-drain


def test_sync_backpressure_bounds_queue():
    async def main():
        farm = VerificationFarm(lane_bounds={Lane.SYNC: 4})
        bb = _BlockingBackend(farm, block_first=100)
        tasks = [asyncio.ensure_future(farm.submit(r, lane=Lane.SYNC))
                 for r in _sig_reqs(12, salt=b"bp")]
        await asyncio.sleep(0.1)
        peak = farm.stats["queue_peak"]["sync"]
        bb.gate.set()
        bb.block_left = 0
        got = await asyncio.gather(*tasks)
        await farm.aclose()
        return peak, got

    peak, got = asyncio.run(main())
    assert peak <= 4  # submitters beyond the bound BLOCKED, not queued
    assert got == [True] * 12  # and everyone still got a verdict


def test_cancelled_caller_leaves_batch_intact():
    async def main():
        farm = VerificationFarm()
        bb = _BlockingBackend(farm)
        first = asyncio.ensure_future(farm.submit(_sig_reqs(1)[0]))
        await asyncio.sleep(0.05)
        reqs = _sig_reqs(3, salt=b"cx")
        tasks = [asyncio.ensure_future(farm.submit(r)) for r in reqs]
        await asyncio.sleep(0)
        tasks[1].cancel()
        bb.gate.set()
        results = await asyncio.gather(*tasks, return_exceptions=True)
        assert await first is True
        await farm.aclose()
        return results

    r = asyncio.run(main())
    assert r[0] is True and r[2] is True
    assert isinstance(r[1], asyncio.CancelledError)


def test_cancelled_waiter_hands_freed_slot_to_next():
    """A backpressure waiter cancelled AFTER _release_lane resolved it
    (but before its submit resumed) must pass the freed slot on —
    otherwise the grant is lost and surviving waiters can park forever
    once the lane drains with no further releases."""

    async def main():
        farm = VerificationFarm()
        assert await farm.submit(_sig_reqs(1, salt=b"w0")[0]) is True
        lane = Lane.SYNC
        # the lane accounting lives in the shared runtime queue now
        # (runtime/queue.py LaneGroup) — same semantics, one copy
        group = farm._group
        group._count[lane] = farm.lane_bounds[lane]  # lane "full"
        b = asyncio.ensure_future(
            farm.submit(_sig_reqs(1, salt=b"wb")[0], lane=lane))
        c = asyncio.ensure_future(
            farm.submit(_sig_reqs(1, salt=b"wc")[0], lane=lane))
        for _ in range(3):
            await asyncio.sleep(0)
        assert len(group._waiters[lane]) == 2
        group.release(lane)       # frees one slot: resolves b's waiter
        b.cancel()                # ...which b will never consume
        with pytest.raises(asyncio.CancelledError):
            await b
        ok = await asyncio.wait_for(c, 5)  # hangs without the handoff
        await farm.aclose()
        return ok

    assert asyncio.run(main()) is True


def test_sync_shutdown_with_live_loop_fails_pending():
    """App.close() runs the SYNC shutdown(); on error-path teardown the
    loop may still be alive — queued requests and backpressure waiters
    must then fail with FarmClosed instead of hanging forever."""

    async def main():
        farm = VerificationFarm(max_inflight=1,
                                lane_bounds={Lane.SYNC: 1})
        bb = _BlockingBackend(farm)
        inflight = asyncio.ensure_future(farm.submit(_sig_reqs(1)[0]))
        await asyncio.sleep(0.05)  # dispatched and blocked in backend
        queued = asyncio.ensure_future(
            farm.submit(_sig_reqs(1, salt=b"q")[0], lane=Lane.SYNC))
        waiting = asyncio.ensure_future(
            farm.submit(_sig_reqs(1, salt=b"w")[0], lane=Lane.SYNC))
        await asyncio.sleep(0.02)  # queued fills the lane; waiting parks
        farm.shutdown()  # the sync path, loop still running
        with pytest.raises(FarmClosed):
            await asyncio.wait_for(queued, 5)
        with pytest.raises(FarmClosed):
            await asyncio.wait_for(waiting, 5)
        bb.gate.set()  # already-dispatched work still completes
        assert await inflight is True
        await farm.aclose()

    asyncio.run(main())


def test_close_fails_pending_with_farm_closed():
    async def main():
        # max_inflight=1: with the first dispatch blocked, later submits
        # stay QUEUED (the cap holds them) instead of dispatching at the
        # deadline — the state aclose() must fail fast
        farm = VerificationFarm(max_inflight=1)
        bb = _BlockingBackend(farm)
        inflight = asyncio.ensure_future(farm.submit(_sig_reqs(1)[0]))
        await asyncio.sleep(0.05)
        queued = asyncio.ensure_future(
            farm.submit(_sig_reqs(1, salt=b"q")[0]))
        await asyncio.sleep(0.02)
        closer = asyncio.ensure_future(farm.aclose())
        await asyncio.sleep(0.02)
        with pytest.raises(FarmClosed):
            await queued  # queued-but-undispatched work fails fast
        bb.gate.set()  # let the in-flight dispatch finish
        assert await inflight is True  # already-dispatched work completes
        await closer
        with pytest.raises(FarmClosed):
            await farm.submit(_sig_reqs(1, salt=b"z")[0])

    asyncio.run(main())


# --- handler integration: farm path == inline path ------------------------


def _signed_ballot(signer, layer, salt=0):
    b = types.Ballot(
        layer=layer, atx_id=bytes([salt]) * 32, epoch_data=None,
        ref_ballot=bytes(32), eligibilities=[],
        opinion=types.Opinion(base=bytes(32), support=[], against=[],
                              abstain=[]),
        node_id=signer.node_id, signature=bytes(64))
    return dataclasses.replace(
        b, signature=signer.sign(Domain.BALLOT, b.signed_bytes()))


def test_malfeasance_handler_parity_and_fallback():
    """The same proofs through (a) the sync fallback (farm=None) and
    (b) the farm path produce identical decisions; the fallback needs
    no event-loop machinery beyond the caller's."""
    prefix = b"vf-test"
    s = EdSigner(prefix=prefix)
    good = malfeasance.proof_from_ballots(_signed_ballot(s, 5, 1),
                                          _signed_ballot(s, 5, 2))
    bad = malfeasance.proof_from_ballots(_signed_ballot(s, 5, 1),
                                         _signed_ballot(s, 6, 2))
    forged = dataclasses.replace(good, sig2=bytes(64))

    def handler(farm):
        # fresh db per proof: condemning the identity once would make
        # every later proof short-circuit to "already known"
        return malfeasance.Handler(
            db=dbmod.open_state(), cache=AtxCache(),
            verifier=EdVerifier(prefix=prefix), pubsub=PubSub(),
            farm=farm)

    expected = [asyncio.run(handler(None).process_async(p))
                for p in (good, bad, forged)]
    assert expected == [True, False, False]

    async def main():
        farm = VerificationFarm(ed_verifier=EdVerifier(prefix=prefix))
        got = [await handler(farm).process_async(p)
               for p in (good, bad, forged)]
        await farm.aclose()
        return got

    assert asyncio.run(main()) == expected


# --- ed25519 batch verification (core/signing.py) -------------------------


def test_ed25519_rfc8032_vector():
    """RFC 8032 test vector 2 (msg = 0x72): pins the pure-Python
    fallback and the OpenSSL path to the same wire signatures, so nodes
    on containers with and without `cryptography` interoperate."""
    from spacemesh_tpu.core import signing

    seed = bytes.fromhex("4ccd089b28ff96da9db6c346ec114e0f"
                         "5b8a319f35aba624da8cf6ed4fb8a6fb")
    pk = bytes.fromhex("3d4017c3e843895a92b70aa74d1b7ebc"
                       "9c982ccf2ec4968cc0cd55f12af4660c")
    sig = bytes.fromhex(
        "92a009a9f0d4cab8720e820b5f642540"
        "a2b27b5416503f8fb3762223ebdb69da"
        "085ac1e43e15996e458f3613d0f11d8c"
        "387b2eaeb4302aeeb00d291612bb0c00")
    s = signing.EdSigner(seed=seed)  # prefix b"": raw RFC message
    assert s.public_key == pk
    # domain byte 0x72 + empty msg == the vector's one-byte message
    assert s.sign(0x72, b"") == sig
    v = signing.EdVerifier()
    assert v.verify(0x72, pk, b"", sig)
    assert not v.verify(0x72, pk, b"x", sig)


def test_ed25519_batch_verify_matches_serial():
    from spacemesh_tpu.core.signing import Domain, EdSigner, EdVerifier

    v = EdVerifier(prefix=b"bt")
    signers = [EdSigner(prefix=b"bt") for _ in range(3)]
    items = []
    for i in range(24):
        s = signers[i % 3]
        msg = b"bmsg" + i.to_bytes(2, "little")
        sig = s.sign(Domain.HARE, msg)
        if i % 5 == 0:
            sig = bytes(64) if i % 2 else sig[:40]  # invalid / malformed
        items.append((int(Domain.HARE), s.public_key, msg, sig))
    serial = [v.verify(d, p, m, g) for d, p, m, g in items]
    assert 0 < sum(serial) < len(serial)
    assert v.verify_many(items) == serial
    # all-valid fast path too (no fallback pass)
    valid = [it for it, ok in zip(items, serial) if ok]
    assert v.verify_many(valid) == [True] * len(valid)


def test_ed25519_torsion_defect_single_batch_parity():
    """An adversarial signature whose R carries a small-order torsion
    component: under the old cofactorless-single / RLC-batch split the
    batch accepted it with probability ~1/8 while single verify always
    rejected — nondeterministic farm-vs-inline divergence. Both paths
    are now cofactored (signing._ed_check) and must agree,
    deterministically, and accept it."""
    import hashlib

    from spacemesh_tpu.core import signing

    if signing._HAVE_CRYPTOGRAPHY:
        pytest.skip("OpenSSL backend (cofactorless) in use; this pins "
                    "the pure-Python cofactored path")

    # project an arbitrary curve point onto the torsion subgroup: Q*P
    # is P's small-order component (nonzero for ~7/8 of points)
    t8 = None
    i = 0
    while t8 is None:
        pt = signing._pt_decode(
            hashlib.sha256(b"torsion%d" % i).digest())
        i += 1
        if pt is None:
            continue
        cand = signing._pt_mul(signing._Q, pt)
        if not signing._pt_eq(cand, signing._ID):
            t8 = cand

    # forge: honest (r, s) but publish R' = R + T — the prime-order
    # part of the equation holds, the torsion part does not
    seed = bytes(31) + b"\x07"
    scalar, nonce_prefix = signing._expand_key(seed)
    pub = signing._pt_encode(signing._pt_mul_base(scalar))
    msg = b"torsion-msg"
    data = bytes([int(Domain.ATX)]) + msg
    r = int.from_bytes(
        hashlib.sha512(nonce_prefix + data).digest(),
        "little") % signing._Q
    r_enc = signing._pt_encode(
        signing._pt_add(signing._pt_mul_base(r), t8))
    k = int.from_bytes(
        hashlib.sha512(r_enc + pub + data).digest(),
        "little") % signing._Q
    s = (r + k * scalar) % signing._Q
    forged = r_enc + s.to_bytes(32, "little")

    v = EdVerifier()
    honest = EdSigner(seed=bytes(31) + b"\x09")
    items = [(int(Domain.ATX), pub, msg, forged)]
    for j in range(9):  # ≥8 candidates so the MSM batch path engages
        m = b"hm%d" % j
        items.append((int(Domain.ATX), honest.public_key, m,
                      honest.sign(Domain.ATX, m)))
    for _ in range(3):  # the old divergence was probabilistic
        signing.clear_verify_cache()
        batch = v.verify_many(items)
        signing.clear_verify_cache()
        serial = [v.verify(d, p, m, g) for d, p, m, g in items]
        assert batch == serial
        assert serial[0] is True  # pins the cofactored equation
    # a genuinely invalid signature still fails both paths
    bad = list(items[1])
    bad[3] = bytes(64)
    signing.clear_verify_cache()
    assert v.verify_many(items + [tuple(bad)])[-1] is False


# --- pubsub hardening (satellite) -----------------------------------------


def test_pubsub_raising_handler_does_not_block_others():
    from spacemesh_tpu.utils.metrics import pubsub_handler_drops

    ps = PubSub()
    seen = []

    async def bad(peer, data):
        raise RuntimeError("boom")

    async def good(peer, data):
        seen.append(data)
        return True

    ps.register("t1", bad)
    ps.register("t1", good)
    before = sum(pubsub_handler_drops._values.values())
    # a raising handler counts as a REJECT but must not stop delivery
    assert asyncio.run(ps.deliver("t1", b"p", b"payload")) is False
    assert seen == [b"payload"]
    assert sum(pubsub_handler_drops._values.values()) == before + 1
