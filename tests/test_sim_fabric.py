"""Event-fabric units (sim/net.py EventMeshHub): wheel ordering and
generation checks, light-relay control-plane elision + deterministic
relay sets, fault-epoch cache invalidation, dirty-set heartbeat
retirement, and fabric selection. Cross-fabric behavior parity runs at
hub level here; the digest-level cross-fabric check is bench.py's
sim_fabric_events_per_sec gate (storm-512-bench on both fabrics)."""

import asyncio

from spacemesh_tpu.core.hashing import sum256
from spacemesh_tpu.p2p.gossipmesh import relay_sample
from spacemesh_tpu.p2p.pubsub import PubSub
from spacemesh_tpu.sim.net import (
    EventMeshHub,
    LegacyMeshHub,
    LinkPolicy,
    MeshHub,
    SimNetwork,
)
from spacemesh_tpu.utils.vclock import run_virtual

N = [b"%02d" % i + bytes(30) for i in range(16)]


def _network(n=8, seed=3, degree=4):
    net = SimNetwork(seed, degree=degree)
    for name in N[:n]:
        net.add_node(name)
    net.build_topology()
    return net


def _join(hub, names, *, light=False):
    """PubSub endpoints with a counting accept-all handler on t1."""
    counts = {}
    for name in names:
        ps = PubSub(node_name=name, deliver_self=False)
        counts[name] = []

        async def h(peer, data, _n=name):
            counts[_n].append(data)
            return True

        ps.register("t1", h)
        hub.join(ps, light=light)
    return counts


def _frame(tag: bytes):
    data = b"payload-" + tag
    return ("msg", N[0], ("t1", sum256(b"t1", data), data))


# --- the event wheel --------------------------------------------------


def test_wheel_fires_by_instant_then_seq():
    """Frames pop in (delivery instant, schedule seq) order: an earlier
    instant wins regardless of schedule order, and ties replay in
    schedule order — the determinism the digest contract rides on."""

    async def go():
        net = _network(4)
        hub = EventMeshHub(net)
        counts = _join(hub, N[:4])
        dst = N[1]
        hub._schedule(5.0, dst, _frame(b"a"))   # seq 0 @ t+5
        hub._schedule(3.0, dst, _frame(b"b"))   # seq 1 @ t+3
        hub._schedule(5.0, dst, _frame(b"c"))   # seq 2 @ t+5 (ties a)
        assert hub.stats["events_scheduled"] == 3
        await asyncio.sleep(6.0)                # virtual: instant wall
        await hub.drain()
        assert counts[dst] == [b"payload-b", b"payload-a", b"payload-c"]
        assert hub.stats["events_fired"] == 3

    run_virtual(go(), timeout=60)


def test_wheel_drops_frames_for_churned_incarnation():
    """Churn while a frame is in flight: suspend bumps the node's
    generation, so the wheel pop discards the stale frame — a resumed
    node must never see pre-crash traffic."""

    async def go():
        net = _network(4)
        hub = EventMeshHub(net)
        counts = _join(hub, N[:4])
        dst = N[2]
        hub._schedule(2.0, dst, _frame(b"pre-crash"))
        hub.suspend(dst)
        hub.resume(dst)
        dropped0 = hub.stats["dropped"]
        await asyncio.sleep(3.0)
        await hub.drain()
        assert counts[dst] == []
        assert hub.stats["dropped"] == dropped0 + 1
        # the resumed incarnation still receives fresh traffic
        hub._schedule(1.0, dst, _frame(b"post-restart"))
        await asyncio.sleep(2.0)
        await hub.drain()
        assert counts[dst] == [b"payload-post-restart"]

    run_virtual(go(), timeout=60)


def test_delayed_delivery_waits_for_the_instant():
    """A policy delay holds frames in the wheel until their virtual
    instant — they must not leak early through the zero-delay path."""

    async def go():
        net = _network(4)
        hub = EventMeshHub(net)
        counts = _join(hub, N[:4])
        net.set_link_policy(LinkPolicy(delay=5.0))
        pub = hub._nodes[N[0]]
        await pub.publish("t1", b"late")
        await asyncio.sleep(0.1)
        assert all(not counts[n] for n in N[1:4]), "must not arrive early"
        # multi-hop flood: each relay hop adds 5s; bound is hops * delay
        await asyncio.sleep(30.0)
        await hub.drain()
        assert all(counts[n] == [b"late"] for n in N[1:4])

    run_virtual(go(), timeout=120)


# --- light relays -----------------------------------------------------


def test_light_relays_run_no_control_plane():
    async def go():
        net = _network(8)
        hub = EventMeshHub(net)
        _join(hub, N[:2])                       # 2 mesh nodes
        counts = _join(hub, N[2:8], light=True)  # 6 light relays
        assert all(n not in hub._gossip for n in N[2:8])
        assert all(n in hub._gossip for n in N[:2])
        pub = hub._nodes[N[0]]
        await pub.publish("t1", b"m")
        await hub.drain()
        assert all(counts[n] == [b"m"] for n in N[2:8])
        # heartbeats only ever visit mesh nodes
        for _ in range(3):
            hub.heartbeat()
        assert hub.stats["hb_visits"] <= 3 * len(hub._gossip)

    asyncio.run(go())


def test_relay_sets_deterministic_and_epoch_cached():
    net = _network(8)
    hub = EventMeshHub(net)
    _join(hub, N[:8], light=True)
    name = N[3]
    got = hub._relay_targets(name, "t1")
    # sha256-ranked sample of the CURRENT neighbor set — cross-process
    # stable, so both ends of a replayed scenario pick the same edges
    assert got == relay_sample("t1", name, net.neighbors(name),
                               hub.gossip_degree)
    assert hub._relay_targets(name, "t1") is got, "cached within an epoch"
    net.partition([[name]])
    after = hub._relay_targets(name, "t1")
    assert after is not got, "fault epoch bump must invalidate the cache"
    assert after == (), "a one-node island has no relay targets"
    assert hub._relay_targets(name, "t1", exclude=N[0]) == []


# --- fault-epoch memoization ------------------------------------------


def test_network_caches_invalidate_on_fault_epoch():
    net = _network(6)
    a, b = N[0], N[1]
    assert net.reachable(a, b)
    miss0 = net.cache_stats["miss"]
    assert net.reachable(a, b) and net.reachable(b, a)
    assert net.cache_stats["miss"] == miss0, "repeat lookups must hit"
    assert net.cache_stats["hit"] >= 2
    e0 = net.epoch
    net.partition([[a], [b]])
    assert net.epoch > e0
    assert not net.reachable(a, b), "stale True would mask the partition"
    assert b not in net.neighbors(a)
    net.set_link_policy(LinkPolicy(loss=0.5), a, b)
    assert net.policy(a, b).loss == 0.5, "policy memo must refresh too"
    net.heal()
    assert net.reachable(a, b)


# --- dirty-set heartbeats ---------------------------------------------


def test_heartbeat_retires_quiet_nodes_and_redirties_on_fault():
    async def go():
        net = _network(6)
        hub = EventMeshHub(net)
        _join(hub, N[:6])
        pub = hub._nodes[N[0]]
        await pub.publish("t1", b"m")
        await hub.drain()
        assert hub._dirty, "traffic must dirty the mesh nodes"
        # beats retire nodes once control work and the message cache age
        # out; afterwards a quiet network costs zero visits per beat
        for _ in range(20):
            hub.heartbeat()
            await hub.drain()
        assert not hub._dirty
        visits = hub.stats["hb_visits"]
        hub.heartbeat()
        assert hub.stats["hb_visits"] == visits, "quiet beat visits nobody"
        # a fault moves every live mesh node's neighbor set: re-dirty
        net.partition([[N[0], N[1]]])
        hub.heartbeat()
        assert hub.stats["hb_visits"] > visits

    asyncio.run(go())


# --- fabric selection / parity ----------------------------------------


def test_fabric_selector_env(monkeypatch):
    monkeypatch.delenv("SPACEMESH_SIM_FABRIC", raising=False)
    assert isinstance(MeshHub(_network(4)), EventMeshHub)
    monkeypatch.setenv("SPACEMESH_SIM_FABRIC", "legacy")
    assert isinstance(MeshHub(_network(4)), LegacyMeshHub)


def test_fabrics_agree_on_clean_world_delivery():
    """Same seed, same publishes, clean links: both fabrics deliver the
    same messages to the same nodes exactly once (the hub-level core of
    the bench's digest-equality gate)."""

    def run(cls):
        async def go():
            net = _network(8, seed=11)
            hub = cls(net)
            counts = _join(hub, N[:8])
            for i in range(3):
                await hub._nodes[N[i]].publish("t1", b"m%d" % i)
                await hub.drain()
            return {n: sorted(v) for n, v in counts.items()}

        return asyncio.run(go())

    event, legacy = run(EventMeshHub), run(LegacyMeshHub)
    expect = {n: sorted(b"m%d" % i for i in range(3) if N[i] != n)
              for n in N[:8]}
    assert event == expect
    assert legacy == expect
