"""Full-size scenario acceptance runs (ISSUE 8).

``storm-256``: 256 nodes on one virtual-clock loop — gossip storm,
3-way partition with degraded links, light-node churn, the adversarial
payload set (malformed ATXs, torsion signatures, duplication flood),
heal — asserting Tortoise re-convergence and zero consensus divergence
from SLIs/traces with no sleep-based waits.

``timeskew-kill`` ports the assertions of the old randomly-seeded
multi-process cluster chaos suite (tests/test_cluster_chaos.py, now
tier-2 only) onto the seeded deterministic fabric.

The replay-determinism contract (same seed => byte-identical digest) is
exercised at engine scale in tests/test_sim_engine.py and per-push at
64 nodes by the scenario-smoke CI job; the 256-node double run is
tier-2 (one run already costs ~1.5 min of tier-1 budget).
"""

import time

import pytest

from spacemesh_tpu.sim import builtin, run_scenario

_STORM_WALL = {}


@pytest.fixture(scope="module")
def storm_result(tmp_path_factory):
    t0 = time.perf_counter()
    r = run_scenario(builtin("storm-256"),
                     tmp=tmp_path_factory.mktemp("storm256"))
    _STORM_WALL["s"] = time.perf_counter() - t0
    return r


def test_storm_256_converges_with_green_slos(storm_result):
    r = storm_result
    assert r.ok, [a for a in r.asserts if not a["ok"]]
    kinds = {a["kind"]: a for a in r.asserts}
    assert kinds["converged"]["ok"], kinds["converged"]
    assert kinds["progress"]["ok"]
    assert kinds["slo_green"]["ok"], kinds["slo_green"]
    assert kinds["trace_valid"]["ok"]


def test_storm_256_exercised_the_fault_vocabulary(storm_result):
    r = storm_result
    hub, net = r.stats["hub"], r.stats["net"]
    assert net["loss"] > 0, "link_policy loss never fired"
    assert net["dup"] > 0, "link duplication never fired"
    assert hub["dup"] > 0, "seen-caches never absorbed a duplicate"
    assert hub["rejected"] > 0, \
        "adversarial payloads were never rejected by a validator"
    # every scripted fault landed and is digest-recorded
    for needle in ("fault phase=partition partition islands=0|1,2,3",
                   "adversary what=malformed_atx",
                   "adversary what=torsion_sig",
                   "adversary what=dup_flood",
                   "churn light=", "fault phase=heal heal"):
        assert any(needle in line for line in r.events), needle
    # the full consensus record of every live node is in the digest
    assert sum(1 for line in r.events if " record full=" in line) == 4


def test_storm_256_storm_reached_the_whole_fabric(storm_result):
    kinds = {(a["kind"], a["phase"]): a for a in storm_result.asserts}
    cov = kinds[("storm_coverage", "storm")]
    assert cov["ok"], cov


def test_storm_256_inside_the_tier1_wall_budget(storm_result):
    """The event fabric's reason to exist (ISSUE 18): storm-256 ran at
    ~85s wall on the task-per-node hub — a quarter of the whole tier-1
    budget. The wheel runs it in ~18s; 40s is the regression tripwire
    with slack for a loaded CI runner."""
    assert storm_result.ok
    assert _STORM_WALL["s"] <= 40.0, \
        f"storm-256 took {_STORM_WALL['s']:.1f}s wall (budget 40s)"


def test_timeskew_kill_ports_cluster_chaos_assertions(tmp_path):
    r = run_scenario(builtin("timeskew-kill"), tmp=tmp_path)
    assert r.ok, [a for a in r.asserts if not a["ok"]]
    assert any("fault phase=skew timeskew full=2" in line
               for line in r.events)
    assert any("record full=1 killed" in line for line in r.events)
    kinds = {a["kind"]: a for a in r.asserts}
    # survivors (incl. the formerly skewed node) agree on applied
    # blocks and state roots — the old subprocess suite's verdict
    assert kinds["converged"]["ok"], kinds["converged"]


def test_crash_store_restart_recovers_surviving_stores(tmp_path):
    """Crash + netsplit at once: full 2 is partitioned into its own
    island and SIGKILLed, then after heal RESTARTS over its surviving
    on-disk stores and must re-sync into byte-identical consensus with
    the majority (the PR-13 recovery path, now a scripted fault)."""
    r = run_scenario(builtin("crash-store"), tmp=tmp_path)
    assert r.ok, [a for a in r.asserts if not a["ok"]]
    assert any("fault phase=partition-crash kill full=2" in line
               for line in r.events)
    assert any("fault phase=heal-restart restart full=2" in line
               for line in r.events)
    kinds = {a["kind"]: a for a in r.asserts}
    assert kinds["converged"]["ok"], kinds["converged"]
    assert kinds["progress"]["ok"]


def test_eclipse_campaign_rejects_and_reconverges(tmp_path):
    """Eclipse a minority full across the epoch boundary while attacker
    lights feed it malformed ATXs: every hostile payload dies as a
    TYPED rejection, the victim re-syncs to zero divergence after the
    eclipse clears, and the run replays byte-identically (ISSUE 19)."""
    a = run_scenario(builtin("eclipse-campaign"), tmp=tmp_path / "a")
    assert a.ok, [x for x in a.asserts if not x["ok"]]
    kinds = {x["kind"]: x for x in a.asserts}
    assert kinds["converged"]["ok"], kinds["converged"]
    assert kinds["hub_stat"]["ok"], kinds["hub_stat"]
    assert kinds["hub_stat"]["value"] >= 1, \
        "no adversarial payload was ever rejected"
    assert kinds["slo_green"]["ok"], kinds["slo_green"]
    for needle in ("fault phase=eclipse eclipse victim=",
                   "adversary what=malformed_atx",
                   "fault phase=heal clear_eclipse"):
        assert any(needle in line for line in a.events), needle
    b = run_scenario(builtin("eclipse-campaign"), tmp=tmp_path / "b")
    assert b.ok
    assert a.digest == b.digest


@pytest.mark.slow
def test_soak_epochs_state_roots_agree_at_every_boundary(tmp_path):
    """The multi-epoch soak (tier-2): 3.5 epochs of storm + VM tx
    traffic on the sharded fabric; state roots must agree across the
    live fulls at EVERY epoch boundary and the windowed SLOs stay
    green — the slow-divergence drift detector (ISSUE 19)."""
    r = run_scenario(builtin("soak-epochs"), tmp=tmp_path)
    assert r.ok, [x for x in r.asserts if not x["ok"]]
    kinds = {x["kind"]: x for x in r.asserts}
    assert kinds["epoch_roots"]["ok"], kinds["epoch_roots"]
    assert len(kinds["epoch_roots"]["value"]["epoch_layers"]) >= 3, \
        "fewer than three epoch boundaries were checked"
    assert not kinds["epoch_roots"]["value"]["diverged"]
    assert kinds["slo_green"]["ok"], kinds["slo_green"]
    assert kinds["converged"]["ok"]


@pytest.mark.slow
def test_storm_4096_runs_on_the_sharded_fabric(tmp_path):
    """The four-thousand-node drill (tier-2): storm-1024's geometry at
    4x the relay population, affordable only with the event wheel
    sharded over host cores (ISSUE 19)."""
    r = run_scenario(builtin("storm-4096"), tmp=tmp_path)
    assert r.ok, [x for x in r.asserts if not x["ok"]]
    kinds = {x["kind"]: x for x in r.asserts}
    assert kinds["converged"]["ok"], kinds["converged"]
    assert kinds["slo_green"]["ok"]
    assert r.stats["hub"]["delivered"] > 400_000


@pytest.mark.slow
def test_storm_256_replay_is_byte_identical(tmp_path):
    """The acceptance determinism clause at full scale (tier-2: two
    ~256-node runs; the per-push CI job proves it at 64 nodes)."""
    a = run_scenario(builtin("storm-256"), tmp=tmp_path / "a")
    b = run_scenario(builtin("storm-256"), tmp=tmp_path / "b")
    assert a.ok and b.ok
    assert a.digest == b.digest


@pytest.mark.slow
def test_storm_1024_converges_and_replays_identically(tmp_path):
    """The thousand-node acceptance drill (ISSUE 18): 1024 nodes —
    mostly light relays — through storm, 3-way partition, churn, three
    concurrent adversaries, heal; converged, green SLOs, and the same
    seed replays to a byte-identical digest. Tier-2 (two ~40s runs);
    the per-push storm-smoke CI job runs the same pair."""
    a = run_scenario(builtin("storm-1024"), tmp=tmp_path / "a")
    assert a.ok, [x for x in a.asserts if not x["ok"]]
    kinds = {x["kind"]: x for x in a.asserts}
    assert kinds["converged"]["ok"], kinds["converged"]
    assert kinds["slo_green"]["ok"]
    assert a.stats["hub"]["delivered"] > 100_000
    b = run_scenario(builtin("storm-1024"), tmp=tmp_path / "b")
    assert b.ok
    assert a.digest == b.digest


# --- self-healing scenarios (ISSUE 15, sim/failover.py) -----------------


def test_verifyd_outage_heals_and_replays_identically():
    """The tentpole acceptance drill: verifyd killed mid-load, the node
    keeps verifying locally with zero verdict divergence and a green
    BLOCK-lane SLO, bounds its attempts against the dead service to
    the breaker budget + probes, and fails back after recovery — twice,
    byte-identical digests."""
    from spacemesh_tpu.sim.failover import run_scenario as run_failover

    a = run_failover(builtin("verifyd-outage"))
    b = run_failover(builtin("verifyd-outage"))
    assert a.ok, [x for x in a.asserts if not x["ok"]]
    assert b.ok
    assert a.digest == b.digest
    kinds = {x["kind"]: x for x in a.asserts}
    assert kinds["no_wrong_verdicts"]["ok"]
    assert kinds["outage_local"]["ok"], kinds["outage_local"]
    assert kinds["remote_attempts_bounded"]["ok"]
    assert kinds["failback"]["ok"], kinds["failback"]
    assert kinds["breaker_sequence"]["ok"]
    assert kinds["slo_green"]["ok"], kinds["slo_green"]
    # the outage and both breaker edges are digest-recorded
    assert any(e.get("fault") == "kill_verifyd" for e in a.events)
    assert any(e.get("fault") == "restore_verifyd" for e in a.events)
    assert any(e.get("breaker") == "open" for e in a.events)
    assert any(e.get("breaker") == "closed" for e in a.events)


def test_runtime_degrade_bounds_device_attempts():
    """The runtime breaker drill: N device attempts across an M>>N
    fault span, host fallback bit-identical, breaker re-closes."""
    from spacemesh_tpu.sim.failover import run_scenario as run_failover

    a = run_failover(builtin("runtime-degrade"))
    b = run_failover(builtin("runtime-degrade"))
    assert a.ok, [x for x in a.asserts if not x["ok"]]
    assert a.digest == b.digest
    rt = a.stats["runtime"]
    fault_span = 30 - 10
    assert rt["device_attempts_in_fault"] < fault_span, \
        "breaker never stopped the per-batch re-pay"
    assert rt["fallbacks"] >= fault_span
    assert rt["breaker"]["state"] == "closed"


# --- fleet scenario (ISSUE 17, sim/fleet.py) -----------------------------


def test_fleet_drill_survives_chaos_and_replays_identically():
    """The fleet acceptance drill: sharded admission to the fleet-wide
    bound, registry_full re-routing, work stealing off the hot replica,
    a replica kill with bounded corpse attempts and survivor serving,
    a full blackout served locally with zero verdict divergence, remote
    failback, the autoscaling signal reacting — twice, byte-identical
    digests."""
    from spacemesh_tpu.sim.fleet import run_scenario as run_fleet

    a = run_fleet(builtin("fleet"))
    b = run_fleet(builtin("fleet"))
    assert a.ok, [x for x in a.asserts if not x["ok"]]
    assert b.ok
    assert a.digest == b.digest
    kinds = {x["kind"]: x for x in a.asserts}
    for k in ("no_wrong_verdicts", "typed_sheds_only", "fleet_bound",
              "reroutes", "steals", "blackout_local",
              "dead_replica_attempts_bounded", "breaker_sequence",
              "failback", "autoscale", "slo_green"):
        assert kinds[k]["ok"], kinds[k]
    # the kill, the blackout and both breaker edges are digest-recorded
    assert any(e.get("fault") == "kill_replica" for e in a.events)
    assert any(e.get("fault") == "blackout" for e in a.events)
    assert any(e.get("breaker") == "open" for e in a.events)
    assert any(e.get("breaker") == "closed" for e in a.events)


def test_byzantine_verifyd_audit_catches_flipped_verdicts():
    """The byzantine drill (ISSUE 18 diversity): replica r1 keeps its
    transport and admission healthy but flips every verdict. The
    verdict audit must detect it, trip ONLY r1's breaker, keep serving
    correct verdicts from the survivors, and fail back after restore —
    twice, byte-identical digests, zero wrong verdicts to any caller."""
    from spacemesh_tpu.sim.fleet import run_scenario as run_fleet

    a = run_fleet(builtin("byzantine-verifyd"))
    b = run_fleet(builtin("byzantine-verifyd"))
    assert a.ok, [x for x in a.asserts if not x["ok"]]
    assert b.ok
    assert a.digest == b.digest
    kinds = {x["kind"]: x for x in a.asserts}
    for k in ("no_wrong_verdicts", "byzantine_detected",
              "breaker_sequence", "path_served", "failback", "slo_green"):
        assert kinds[k]["ok"], kinds[k]
    assert any(e.get("fault") == "byzantine_replica" for e in a.events)
    assert any(e.get("fault") == "restore_byzantine" for e in a.events)
