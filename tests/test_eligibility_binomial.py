"""Binomial committee sampling (VERDICT r3 item 2).

The seat count must be a true inverse-CDF binomial sample over the
identity's weight (reference hare3/eligibility/oracle.go:324-375), not an
expectation + one fractional draw: same mean, but the full binomial
variance the committee-size analysis depends on.
"""

import math
from fractions import Fraction

from spacemesh_tpu.consensus.eligibility import Oracle, hare_alpha
from spacemesh_tpu.core import fixedpoint
from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo

GEN = b"binom-test-genesis!!"
ONE = fixedpoint.ONE


def exact_cdf(n, p, x):
    """Exact rational Binomial(n, p) CDF for cross-checking."""
    p = Fraction(p)
    return sum(math.comb(n, k) * p**k * (1 - p) ** (n - k)
               for k in range(x + 1))


def test_bin_cdf_matches_exact_rational():
    for n, num, den in [(10, 1, 4), (40, 3, 10), (100, 1, 100), (7, 6, 7)]:
        for x in range(n + 1):
            got = fixedpoint.bin_cdf(n, num, den, x) / ONE
            want = float(exact_cdf(n, Fraction(num, den), x))
            assert abs(got - want) < 1e-12, (n, num, den, x)
        # truncating fixed-point multiplies only ever lose mass, so the
        # CDF lands just under ONE; 2**68 ulps at 128 frac bits = 1e-18
        assert fixedpoint.bin_cdf(n, num, den, n) >= ONE - (1 << 68)


def test_count_is_inverse_cdf():
    n, num, den = 50, 2, 10
    cdf = [fixedpoint.bin_cdf(n, num, den, x) for x in range(n + 1)]
    for frac in [0, ONE // 7, ONE // 3, ONE // 2, 2 * ONE // 3,
                 9 * ONE // 10, ONE - 1]:
        want = next((x for x in range(n + 1) if cdf[x] > frac), n)
        assert fixedpoint.binomial_count(n, num, den, frac) == want


def test_empirical_distribution_binomial():
    """Counts over many uniform draws match Binomial(n, p): mean AND
    variance (the old expectation+fraction scheme had variance < p(1-p),
    never the binomial's npq)."""
    n, num, den = 64, 1, 8  # E = 8, Var = 7
    draws = 4000
    counts = []
    for i in range(draws):
        frac = (i * 2 + 1) * ONE // (2 * draws)  # uniform grid on [0,1)
        counts.append(fixedpoint.binomial_count(n, num, den, frac))
    mean = sum(counts) / draws
    var = sum((c - mean) ** 2 for c in counts) / draws
    e, v = n * num / den, n * (num / den) * (1 - num / den)
    assert abs(mean - e) < 0.2, mean
    assert abs(var - v) / v < 0.1, var


def test_degenerate_and_saturation_cases():
    assert fixedpoint.binomial_count(0, 1, 2, 0) == 0
    assert fixedpoint.binomial_count(10, 0, 2, 0) == 0
    # p >= 1: every trial succeeds
    assert fixedpoint.binomial_count(10, 5, 5, ONE // 2) == 10
    # underflow saturation: (1-p)^n below 128-bit resolution -> round(np)
    assert fixedpoint.binomial_count(400, 1, 2, ONE // 2) == 200
    # ... and still capped at uint16
    assert fixedpoint.binomial_count(10**6, 1, 2, ONE // 2) \
        == fixedpoint.COUNT_CAP
    # count cap: uint16 parity with the reference
    assert fixedpoint.binomial_count(10**9, 999, 1000, ONE - 1) \
        == fixedpoint.COUNT_CAP


def _oracle(weights, committee=40, epoch=1):
    cache = AtxCache()
    signers, atx_ids = [], []
    for i, w in enumerate(weights):
        s = EdSigner(prefix=GEN)
        atx_id = b"BATX%04d" % i + bytes(24)
        cache.add(epoch, atx_id, AtxInfo(
            node_id=s.node_id, weight=w, base_height=0, height=1,
            num_units=1, vrf_nonce=0, vrf_public_key=s.node_id))
        signers.append(s)
        atx_ids.append(atx_id)
    return Oracle(cache, 4), signers, atx_ids


def test_prover_validator_agree_and_forged_count_rejected():
    beacon = b"\x01\x02\x03\x04"
    oracle, signers, atx_ids = _oracle([100, 300, 50], committee=40)
    layer, epoch = 5, 1
    seen_any = False
    for rnd in range(6):
        for s, atx in zip(signers, atx_ids):
            el = oracle.hare_eligibility(
                s.vrf_signer(), beacon, layer, rnd, epoch, atx, 40)
            if el is None:
                continue
            proof, count = el
            seen_any = True
            assert oracle.validate_hare(
                beacon, layer, rnd, epoch, atx, 40, proof, count)
            # forged counts (the attack the count derivation prevents)
            assert not oracle.validate_hare(
                beacon, layer, rnd, epoch, atx, 40, proof, count + 1)
            assert not oracle.validate_hare(
                beacon, layer, rnd, epoch, atx, 40, proof, 0)
    assert seen_any


def test_committee_scale_when_committee_exceeds_total():
    """committee > total_weight triggers the reference's rescale
    (oracle.go:275-281): p = 1/W per weight-unit-trial, n = w*C."""
    oracle, signers, atx_ids = _oracle([2, 3], committee=40)
    n, p_num, p_den = oracle._binomial_params(1, atx_ids[0], 40)
    assert (n, p_num, p_den) == (2 * 40, 40, 5 * 40)


def test_empirical_committee_size_over_rounds():
    """Across many (layer, round) draws the realized committee size is
    centered on the target with binomial spread."""
    beacon = b"\x09\x09\x09\x09"
    committee = 20
    oracle, signers, atx_ids = _oracle([10] * 12, committee=committee)
    sizes = []
    for layer in range(30):
        for rnd in range(4):
            tot = 0
            for s, atx in zip(signers, atx_ids):
                el = oracle.hare_eligibility(
                    s.vrf_signer(), beacon, layer, rnd, 1, atx, committee)
                if el:
                    tot += el[1]
            sizes.append(tot)
    mean = sum(sizes) / len(sizes)
    assert abs(mean - committee) < 2.0, mean
    # variance must exist (old scheme: whole-part deterministic, var ~ p(1-p)
    # per identity only); binomial committee var = C*(1 - C/W) ~ 16.7 here
    var = sum((x - mean) ** 2 for x in sizes) / len(sizes)
    assert var > 5.0, var
