"""External poet: daemon subprocess, remote client, multi-poet selection.

Reference parity: external poet servers reached by a client, multi-poet
registration with best-by-ticks proof selection (activation/poet.go,
nipost.go:349/getBestProof). The daemon runs as a REAL subprocess
(`python -m spacemesh_tpu.tools.poet_server`).
"""

import asyncio
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

from spacemesh_tpu.consensus.poet import verify_membership
from spacemesh_tpu.consensus.poet_remote import MultiPoet, RemotePoetClient

REPO = Path(__file__).resolve().parent.parent


def _spawn_poet(ticks, seed):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-u", "-m", "spacemesh_tpu.tools.poet_server",
         "--listen", "127.0.0.1:0", "--ticks", str(ticks),
         "--id-seed", seed],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, env=env,
        text=True, cwd=str(REPO))
    line = proc.stdout.readline()
    ev = json.loads(line)
    assert ev["event"] == "Serving"
    return proc, (ev["host"], ev["port"])


@pytest.fixture(scope="module")
def poets():
    procs = []
    addrs = []
    for ticks, seed in ((32, "poet-slow"), (128, "poet-strong")):
        proc, addr = _spawn_poet(ticks, seed)
        procs.append(proc)
        addrs.append(addr)
    yield addrs
    for proc in procs:
        proc.terminate()
        proc.wait(timeout=10)


def test_register_execute_and_membership(poets):
    client = RemotePoetClient(poets[0])
    challenge = b"ch-aaaaaaaaaaaaaaaaaaaaaaaaaaaaaa"[:32]

    async def go():
        await client.register("7", challenge)
        result = await client.execute_round("7")
        proof = result.membership(challenge)
        assert proof is not None
        assert verify_membership(challenge, proof, result.proof.root,
                                 leaf_count=len(result.members))
        # result() replays the stored round
        again = client.result("7")
        assert again is not None
        assert again.proof.root == result.proof.root

    asyncio.run(asyncio.wait_for(go(), 30))


def test_multi_poet_picks_best_by_ticks(poets):
    clients = [RemotePoetClient(a) for a in poets]
    mp = MultiPoet(clients)
    challenge = b"ch-bbbbbbbbbbbbbbbbbbbbbbbbbbbbbb"[:32]

    async def go():
        await mp.register("9", challenge)
        result = await mp.execute_round("9")
        # the 128-tick poet must win
        assert result.proof.ticks == 128
        assert result.membership(challenge) is not None

    asyncio.run(asyncio.wait_for(go(), 30))


def test_multi_poet_survives_dead_poet(poets):
    clients = [RemotePoetClient(a) for a in poets]
    # add a dead address: connection refused must not sink the fan-out
    class Dead:
        poet_id = b"\0" * 32

        async def register(self, r, c, node_id=None, signature=None,
                           cert=None):
            raise ConnectionRefusedError

        async def execute_round(self, r):
            raise ConnectionRefusedError

        def result(self, r):
            return None

    mp = MultiPoet([Dead()] + clients)
    challenge = b"ch-cccccccccccccccccccccccccccccc"[:32]

    async def go():
        await mp.register("11", challenge)
        result = await mp.execute_round("11")
        assert result.proof.ticks == 128

    asyncio.run(asyncio.wait_for(go(), 30))
