"""Bit-exactness of the JAX scrypt labeler against hashlib.scrypt.

This is the TPU-build equivalent of the reference's e2e CGo tests
(reference activation/e2e) which validate byte-compatibility of proofs: the
CPU ground truth here is Python's OpenSSL-backed scrypt.
"""

import hashlib

import numpy as np
import pytest

from spacemesh_tpu.ops import scrypt


def cpu_label(commitment: bytes, index: int, n: int, dklen: int = 16) -> bytes:
    salt = int(index).to_bytes(8, "little")
    return hashlib.scrypt(commitment, salt=salt, n=n, r=1, p=1,
                          maxmem=256 * 1024 * 1024, dklen=dklen)


COMMIT = bytes(range(32))


@pytest.mark.parametrize("n", [2, 16, 8192])
def test_labels_match_hashlib(n):
    if n == 8192:  # mainnet N: keep the CPU-test cost bounded
        idx = np.array([0, 12345], dtype=np.uint64)
    else:
        idx = np.array([0, 1, 2, 7, 12345, 2**32 - 1, 2**32, 2**40 + 17],
                       dtype=np.uint64)
    got = scrypt.scrypt_labels(COMMIT, idx, n=n)
    for k, i in enumerate(idx):
        want = np.frombuffer(cpu_label(COMMIT, int(i), n), dtype=np.uint8)
        assert bytes(got[k]) == bytes(want), f"label mismatch at index {i}, n={n}"


def test_different_commitments_differ():
    idx = np.arange(4, dtype=np.uint64)
    a = scrypt.scrypt_labels(COMMIT, idx, n=16)
    b = scrypt.scrypt_labels(bytes(32), idx, n=16)
    assert not np.array_equal(a, b)


def test_input_validation():
    idx = np.array([1], dtype=np.uint64)
    for bad_n in (0, 1, 3, 6, 2**16, 2**20):
        with pytest.raises(ValueError):
            scrypt.scrypt_labels(COMMIT, idx, n=bad_n)
    with pytest.raises(ValueError):
        scrypt.scrypt_labels(b"short", idx, n=4)
    # scalar index is promoted to a 1-element batch
    got = scrypt.scrypt_labels(COMMIT, 5, n=4)
    assert bytes(got[0]) == cpu_label(COMMIT, 5, 4)


def test_multi_commitment_labels_match_hashlib():
    # per-lane keys: B=5 distinct commitments, non-contiguous indices
    commits = [hashlib.sha256(b"m%d" % i).digest() for i in range(5)]
    idx = np.array([0, 3, 9, 2**33, 77], dtype=np.uint64)
    got = scrypt.scrypt_labels_multi(
        np.stack([np.frombuffer(c, dtype=np.uint8) for c in commits]), idx, n=16)
    for k in range(5):
        want = hashlib.scrypt(commits[k], salt=int(idx[k]).to_bytes(8, "little"),
                              n=16, r=1, p=1, dklen=16)
        assert bytes(got[k]) == want, f"lane {k}"
    # B=1 and empty
    one = scrypt.scrypt_labels_multi(
        np.frombuffer(commits[0], dtype=np.uint8)[None], [7], n=16)
    assert bytes(one[0]) == cpu_label(commits[0], 7, 16)
    empty = scrypt.scrypt_labels_multi(
        np.zeros((0, 32), dtype=np.uint8), np.array([], dtype=np.uint64), n=16)
    assert empty.shape == (0, 16)
    with pytest.raises(ValueError):
        scrypt.scrypt_labels_multi(
            np.zeros((2, 32), dtype=np.uint8), [1, 2, 3], n=16)


def test_sha256_words_vs_hashlib():
    from spacemesh_tpu.ops import sha256 as s
    for msg in (b"", b"abc", b"x" * 55, b"y" * 56, b"z" * 200):
        got = np.asarray(s.sha256_words(np.asarray(s.pad_message_np(msg))))
        want = np.frombuffer(hashlib.sha256(msg).digest(), dtype=">u4")
        assert np.array_equal(got.astype(">u4"), want), f"sha256 mismatch len={len(msg)}"


def test_label_shape_and_determinism():
    idx = np.arange(33, dtype=np.uint64)
    a = scrypt.scrypt_labels(COMMIT, idx, n=8)
    b = scrypt.scrypt_labels(COMMIT, idx, n=8)
    assert a.shape == (33, scrypt.LABEL_BYTES)
    assert np.array_equal(a, b)
