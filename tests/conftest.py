"""Test config: force JAX onto a virtual 8-device CPU platform.

Tests must not require TPU hardware; multi-chip sharding is exercised on a
virtual CPU mesh (SURVEY.md §7 test carry-over (f)).

Subtlety: the container's sitecustomize imports jax and registers the "axon"
TPU-tunnel PJRT plugin at interpreter startup — before pytest loads this
conftest — and pins JAX_PLATFORMS=axon in the environment. Setting env vars
here is therefore too late for jax's own config; we must go through
jax.config. The XLA_FLAGS update still works because the CPU client is only
instantiated at first backend use, which happens inside tests.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for any subprocesses tests spawn
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import tempfile  # noqa: E402

# a developer shell with SPACEMESH_TRACE set must not arm the span
# tracer for the whole suite (tests that want a capture call
# tracing.start() themselves — tests/test_tracing.py)
os.environ.pop("SPACEMESH_TRACE", None)

# likewise an operator shell with JSON logging on must not change the
# log format tests parse (tests that want JSON lines call
# logging.configure(json_lines=True) themselves — tests/test_health_engine.py)
os.environ.pop("SPACEMESH_LOG_JSON", None)

# the runtime sanitizers (utils/sanitize.py) must not arm for the whole
# suite from a developer/CI shell: several tests dispatch deliberately
# odd shapes with bucketing disabled (tests that want the sanitizer call
# sanitize.enable() themselves — tests/test_spacecheck.py)
os.environ.pop("SPACEMESH_SANITIZE", None)

# the ROMix autotuner (ops/autotune.py) must stay deterministic and cheap
# under test: no implicit candidate races, and never persist winners into
# the developer's real cache root. The autotune tests opt back in with
# monkeypatch (tests/test_romix_autotune.py).
os.environ.setdefault("SPACEMESH_ROMIX_AUTOTUNE", "off")
os.environ.setdefault(
    "SPACEMESH_ROMIX_CACHE",
    os.path.join(tempfile.gettempdir(),
                 f"spacemesh-test-romix-{os.getpid()}.json"))

# the verifyd batch tuner (verifyd/batchtune.py) mirrors the ROMix
# autotuner's discipline: no implicit backend races under test, and
# never persist measured rates into the developer's real cache root
# (tests that want a race opt back in with monkeypatch)
os.environ.setdefault("SPACEMESH_VERIFYD_TUNE", "off")
os.environ.setdefault(
    "SPACEMESH_VERIFYD_TUNE_CACHE",
    os.path.join(tempfile.gettempdir(),
                 f"spacemesh-test-batchtune-{os.getpid()}.json"))

# spacecheck's incremental findings cache (tools/spacecheck/engine.py)
# must never mix test scratch trees into the developer's real cache
# file (tests/test_racecheck.py point it at their own tmp paths)
os.environ.setdefault(
    "SPACEMESH_SPACECHECK_CACHE",
    os.path.join(tempfile.gettempdir(),
                 f"spacemesh-test-spacecheck-{os.getpid()}.json"))

import jax  # noqa: E402  (import order is the point here)

jax.config.update("jax_platforms", "cpu")

# persistent XLA compile cache: the suite's jit compiles are paid once per
# machine, not once per pytest invocation (utils/accel.py; SPACEMESH_JAX_CACHE
# still wins, =off disables)
from spacemesh_tpu.utils import accel  # noqa: E402

accel.enable_persistent_cache()


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: tier-2 heavyweight scenarios (multi-process clusters); "
        "the tier-1 command runs -m 'not slow'")
