"""QUIC-lite transport (VERDICT r3 item 5).

Two halves:
- protocol-level: ARQ reliability under injected loss, ordered delivery,
  connection-id routing across an address migration;
- the FULL TCP behavior matrix re-run over QuicHost — same noise
  handshake, gossip, req/resp, peer-exchange, impersonation and cookie
  rejection semantics over UDP (reference p2p/host.go:166
  EnableQUICTransport: same libp2p stack over a second transport).
"""

import asyncio

import pytest

from spacemesh_tpu.p2p.quic import QuicEndpoint, QuicHost

import tests.test_transport as tt


# --- protocol level ---------------------------------------------------------


def test_ordered_delivery_under_loss():
    """20% outbound DATA loss: retransmission must still deliver every
    byte, in order."""

    async def go():
        got = asyncio.Queue()

        async def on_accept(reader, writer):
            while True:
                chunk = await reader.read(4096)
                if not chunk:
                    break
                got.put_nowait(chunk)

        server = QuicEndpoint(on_accept=on_accept)
        await server.listen("127.0.0.1", 0)
        client = QuicEndpoint(loss_rate=0.2)
        await client.listen("127.0.0.1", 0)
        reader, writer = await client.connect(server.address)
        payload = bytes(range(256)) * 2000  # 512 KB >> one window
        writer.write(payload)
        await writer.drain()
        received = b""
        while len(received) < len(payload):
            received += await asyncio.wait_for(got.get(), 20)
        assert received == payload
        assert client.stats["dropped"] > 0  # loss actually happened
        writer.close()
        server.close()
        client.close()

    asyncio.run(go())


def test_syn_flood_is_admission_controlled():
    """A spoofed SYN flood (no follow-up DATA) must stop allocating
    connection state at MAX_HALF_OPEN, while a legitimate client that
    completes the exchange and sends DATA still gets through
    (ADVICE r4: unbounded _by_id growth)."""
    import os
    import struct

    from spacemesh_tpu.p2p import quic as q

    async def go():
        got = asyncio.Queue()

        async def on_accept(reader, writer):
            got.put_nowait(await reader.readexactly(5))

        server = QuicEndpoint(on_accept=on_accept)
        await server.listen("127.0.0.1", 0)
        flood = QuicEndpoint()
        await flood.listen("127.0.0.1", 0)
        # raw SYNs with random client ids, never followed by DATA.
        # Paced waves with yields (not one burst): a tight sendto loop
        # can overflow the receiver's UDP socket buffer under suite
        # load, and kernel-dropped SYNs never reach admission control —
        # the refusal this test asserts then simply doesn't happen
        # (flaked once in the PR-9 tier-1 run with rx=49/96). Condition
        # wait, bounded waves: stop as soon as a refusal is observed.
        for _ in range(6):
            for _ in range(q.MAX_HALF_OPEN):
                pkt = q.HEADER.pack(q.MAGIC, q.SYN, bytes(8), 0, 0) \
                    + os.urandom(8)
                flood.transport.sendto(pkt, server.address)
                await asyncio.sleep(0)  # let the receiver drain
            await asyncio.sleep(0.05)
            if server.stats.get("syn_refused", 0) > 0:
                break
        assert len(server._by_id) <= q.MAX_HALF_OPEN
        assert server.stats.get("syn_refused", 0) > 0
        # free admission slots arrive as half-open conns idle out; a
        # real client under partial flood may need retries, but with the
        # table at the cap the endpoint must refuse, not grow
        flood.close()
        server.close()

    asyncio.run(go())


def test_syn_then_fin_releases_half_open_slot():
    """A connection closed before its first DATA must release its
    half-open admission slot (code-review r5: the FIN path skipped the
    decrement, so 64 connect-and-close clients would permanently lock
    the endpoint against all new inbound connections)."""
    import os

    from spacemesh_tpu.p2p import quic as q

    async def go():
        server = QuicEndpoint(on_accept=lambda r, w: asyncio.sleep(0))
        await server.listen("127.0.0.1", 0)
        flood = QuicEndpoint()
        await flood.listen("127.0.0.1", 0)
        for _ in range(5):
            cid = os.urandom(8)
            flood.transport.sendto(
                q.HEADER.pack(q.MAGIC, q.SYN, bytes(8), 0, 0) + cid,
                server.address)
            await asyncio.sleep(0.05)
            conn = next(c for c in server._by_id.values()
                        if c.remote_id == cid)
            flood.transport.sendto(
                q.HEADER.pack(q.MAGIC, q.FIN, conn.local_id, 0, 0),
                server.address)
        await asyncio.sleep(0.1)
        assert server.half_open_count == 0
        assert len(server._by_id) == 0
        flood.close()
        server.close()

    asyncio.run(go())


def test_legit_client_admitted_below_cap():
    """Half-open accounting clears on first DATA: a normal dial+send is
    unaffected by admission control and leaves no half-open residue."""
    async def go():
        got = asyncio.Queue()

        async def on_accept(reader, writer):
            got.put_nowait(await reader.readexactly(5))

        server = QuicEndpoint(on_accept=on_accept)
        await server.listen("127.0.0.1", 0)
        client = QuicEndpoint()
        await client.listen("127.0.0.1", 0)
        reader, writer = await client.connect(server.address)
        writer.write(b"hello")
        await writer.drain()
        assert await asyncio.wait_for(got.get(), 5) == b"hello"
        assert all(not c.half_open for c in server._by_id.values())
        writer.close()
        server.close()
        client.close()

    asyncio.run(go())


def test_counting_reader_tracks_buffered_bytes():
    """Flow-control backpressure reads CountingReader.buffered, not
    asyncio internals (ADVICE r4)."""
    async def go():
        from spacemesh_tpu.p2p.quic import CountingReader

        r = CountingReader()
        r.feed_data(b"abcdef")
        assert r.buffered == 6
        assert await r.readexactly(2) == b"ab"
        assert r.buffered == 4
        assert await r.read(4) == b"cdef"
        assert r.buffered == 0
        r.feed_data(b"xy")
        r.feed_eof()
        with pytest.raises(asyncio.IncompleteReadError):
            await r.readexactly(3)
        assert r.buffered == 0  # partial counted as consumed

        # delegating methods must not double-count (code-review r5:
        # readuntil and read(-1) -> read(n) re-enter the counting
        # overrides; a second count drives buffered negative and
        # disables backpressure forever)
        r2 = CountingReader()
        r2.feed_data(b"one\ntwo")
        assert await r2.readuntil(b"\n") == b"one\n"
        assert r2.buffered == 3
        r2.feed_eof()
        assert await r2.read(-1) == b"two"
        assert r2.buffered == 0

        # readline() is refused outright (ADVICE r5): its
        # LimitOverrunError recovery truncates the private buffer behind
        # the counter's back, silently corrupting flow-control accounting
        r3 = CountingReader()
        r3.feed_data(b"line\n")
        with pytest.raises(NotImplementedError):
            await r3.readline()
        assert r3.buffered == 5  # nothing consumed by the refusal

    asyncio.run(go())


def test_connection_survives_address_migration():
    """Packets are routed by destination connection id, not source
    address (QUIC connection migration): a client that rebinds its UDP
    socket keeps the connection."""

    async def go():
        got = asyncio.Queue()

        async def on_accept(reader, writer):
            while True:
                chunk = await reader.read(4096)
                if not chunk:
                    break
                got.put_nowait(chunk)

        server = QuicEndpoint(on_accept=on_accept)
        await server.listen("127.0.0.1", 0)
        client = QuicEndpoint()
        await client.listen("127.0.0.1", 0)
        reader, writer = await client.connect(server.address)
        writer.write(b"before-migration")
        await writer.drain()
        assert await asyncio.wait_for(got.get(), 5) == b"before-migration"
        # simulate migration: rebind the client onto a NEW port, keep ids
        conn = next(iter(client._by_id.values()))
        client.transport.close()
        await client.listen("127.0.0.1", 0)
        writer.write(b"after-migration")
        await writer.drain()
        assert await asyncio.wait_for(got.get(), 5) == b"after-migration"
        assert conn.remote_addr == server.address
        server.close()
        client.close()

    asyncio.run(go())


def test_fin_closes_both_sides():
    async def go():
        peers = asyncio.Queue()

        async def on_accept(reader, writer):
            peers.put_nowait((reader, writer))

        server = QuicEndpoint(on_accept=on_accept)
        await server.listen("127.0.0.1", 0)
        client = QuicEndpoint()
        await client.listen("127.0.0.1", 0)
        reader, writer = await client.connect(server.address)
        s_reader, _ = await asyncio.wait_for(peers.get(), 5)
        writer.close()
        assert await asyncio.wait_for(s_reader.read(), 5) == b""  # EOF
        server.close()
        client.close()

    asyncio.run(go())


# --- full Host behavior matrix over QUIC ------------------------------------
#
# Every TCP transport test runs unchanged with Host swapped for QuicHost:
# the seam contract (noise channel over a reliable ordered stream) is
# transport-agnostic by design.


@pytest.fixture(autouse=True)
def _swap_host(monkeypatch):
    monkeypatch.setattr(tt, "Host", QuicHost)


def test_quic_gossip_and_relay_line_topology():
    tt.test_gossip_and_relay_line_topology()


def test_quic_genesis_cookie_rejects_wrong_network():
    tt.test_genesis_cookie_rejects_wrong_network()


def test_quic_request_response_and_unknown_protocol():
    tt.test_request_response_and_unknown_protocol()


def test_quic_drop_peer_on_repeated_validation_reject():
    tt.test_drop_peer_on_repeated_validation_reject()


def test_quic_reconnects_to_restarted_peer():
    tt.test_reconnects_to_restarted_peer()


def test_quic_peer_exchange_discovers_third_node():
    tt.test_peer_exchange_discovers_third_node()


def test_quic_impersonation_rejected():
    tt.test_impersonation_rejected()


# --- multi-process cluster + chaos over QUIC --------------------------------


@pytest.mark.slow  # tier-2 like its TCP twin (test_process_net.py)
def test_quic_three_process_cluster_with_kill(tmp_path):
    """The process-net scenario over QUIC: three OS processes, UDP-only
    traffic, one SIGKILLed mid-run; survivors converge (the TCP twin is
    tests/test_process_net.py)."""
    import json
    import signal
    import time

    import tests.test_process_net as pn
    from spacemesh_tpu.storage import atxs as atxstore
    from spacemesh_tpu.storage import db as dbmod
    from spacemesh_tpu.storage import layers as layerstore

    genesis = float(int(time.time()) + pn.PREPARE_BUDGET)
    pa, pb, pc = pn._free_port(), pn._free_port(), pn._free_port()
    boot = [f"127.0.0.1:{pa}"]

    def write_cfg(name, smesh):
        cfg = {
            "data_dir": str(tmp_path / name),
            "layer_duration": pn.LAYER_SEC,
            "layers_per_epoch": pn.LPE,
            "slots_per_layer": 2,
            "genesis": {"time": genesis},
            "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64,
                     "k2": 8, "k3": 4, "min_num_units": 1,
                     "pow_difficulty": "20" + "ff" * 31},
            "smeshing": {"start": smesh, "num_units": 1, "init_batch": 128},
            "hare": {"committee_size": 20, "round_duration": 0.1,
                     "preround_delay": 0.35, "iteration_limit": 2},
            "beacon": {"proposal_duration": 0.1},
            "tortoise": {"hdist": 4, "window_size": 50},
            "p2p": {"transport": "quic"},
        }
        path = tmp_path / f"{name}.json"
        path.write_text(json.dumps(cfg))
        return path

    procs, logs = {}, {}
    for name, port, bootnodes, smesh in (
            ("a", pa, [], True), ("b", pb, boot, False),
            ("c", pc, boot, False)):
        procs[name], logs[name] = pn._spawn(
            write_cfg(name, smesh), port, bootnodes,
            tmp_path / f"{name}.log")

    kill_at = genesis + pn.LAYER_SEC * (pn.LPE + 1.5)
    time.sleep(max(kill_at - time.time(), 0))
    procs["b"].send_signal(signal.SIGKILL)

    deadline = genesis + pn.LAYER_SEC * pn.UNTIL + 90
    rcs = {}
    try:
        for name in ("a", "c"):
            rcs[name] = procs[name].wait(
                timeout=max(deadline - time.time(), 5))
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        for log in logs.values():
            log.close()

    tails = {n: (tmp_path / f"{n}.log").read_text()[-2000:]
             for n in ("a", "c")}
    assert rcs.get("a") == 0, f"node A failed:\n{tails['a']}"
    assert rcs.get("c") == 0, f"node C failed:\n{tails['c']}"
    # convergence: the observer saw the smesher's ATXs and applied layers
    sa = dbmod.open_state(tmp_path / "a" / "state.db")
    sc = dbmod.open_state(tmp_path / "c" / "state.db")
    assert atxstore.count(sc) >= 1
    assert atxstore.count(sc) == atxstore.count(sa)
    la, lc = layerstore.last_applied(sa), layerstore.last_applied(sc)
    assert min(la, lc) >= pn.LPE + 1, (la, lc)
    for lyr in range(1, min(la, lc) + 1):
        ha = layerstore.aggregated_hash(sa, lyr)
        hc = layerstore.aggregated_hash(sc, lyr)
        assert ha == hc, f"aggregated hash diverges at layer {lyr}"
