"""spacecheck static analyzer + runtime sanitizers (ISSUE 9).

Every rule gets a minimal offending fixture and a fixed/pragma'd twin;
the CLI/baseline workflow is exercised end to end (seeded violation ->
nonzero exit; stale or unjustified baseline -> nonzero exit); the
sanitizers catch an injected event-loop block and an off-bucket
compile, and stay silent on the clean paths.
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from spacemesh_tpu.tools.spacecheck import baseline as baseline_mod
from spacemesh_tpu.tools.spacecheck import engine
from spacemesh_tpu.tools.spacecheck.__main__ import main as cli_main
from spacemesh_tpu.utils import sanitize


def run_fixture(tmp_path, rel, source, select=None):
    """Write ``source`` at ``rel`` under a scratch project root and
    analyze it. Returns the findings list."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings, errors = engine.run_paths(
        [str(path)], project_root=str(tmp_path),
        select={select} if select else None)
    assert not errors, errors
    return findings


def rules_of(findings):
    return sorted({f.rule for f in findings})


# --- SC001 clock discipline ---------------------------------------------


SC001_BAD = """
    import time
    import asyncio

    def deadline():
        return time.time() + 5.0

    def backoff(loop):
        return loop.time()

    async def wait():
        await asyncio.sleep(1.5)
"""


def test_sc001_flags_wall_clock_in_scope(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/sim/bad_clock.py", SC001_BAD)
    msgs = [f.message for f in fs if f.rule == "SC001"]
    assert len(msgs) == 3
    assert any("time.time()" in m for m in msgs)
    assert any("loop" in m for m in msgs)
    assert any("asyncio.sleep(1.5)" in m for m in msgs)


def test_sc001_out_of_scope_module_is_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/ops/bad_clock.py", SC001_BAD)
    assert not [f for f in fs if f.rule == "SC001"]


def test_sc001_injected_time_source_is_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/sim/good_clock.py", """
        import time

        def deadline(now=None):
            return (time.time() if now is None else now) + 5.0

        class Thing:
            def __init__(self, time_source=time.monotonic):
                self._now = time_source

            def until(self):
                return self._now() + 1.0
    """)
    assert not [f for f in fs if f.rule == "SC001"]


def test_sc001_line_and_module_pragmas(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/obs/line_pragma.py", """
        import time

        def stamp():
            return time.time()  # spacecheck: ok=SC001 display only
    """)
    assert not [f for f in fs if f.rule == "SC001"]
    fs = run_fixture(tmp_path, "spacemesh_tpu/obs/module_pragma.py", """
        # spacecheck: wall-clock-ok — operator tool, real wall time wanted
        import time

        def stamp():
            return time.time()

        def stamp2():
            return time.monotonic()
    """)
    assert not [f for f in fs if f.rule == "SC001"]


def test_sc001_sleep_zero_yield_is_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/sim/yield_ok.py", """
        import asyncio

        async def cooperate():
            await asyncio.sleep(0)
    """)
    assert not [f for f in fs if f.rule == "SC001"]


# --- SC002 async-blocking -----------------------------------------------


def test_sc002_flags_blocking_in_async(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/api/busy.py", """
        import subprocess
        import time

        async def handler():
            time.sleep(0.1)
            with open("/tmp/x") as f:
                f.read()
            subprocess.run(["true"])
            out.block_until_ready()
    """, select="SC002")
    assert len(fs) == 4
    assert all(f.rule == "SC002" for f in fs)


def test_sc002_clean_patterns(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/api/tidy.py", """
        import asyncio
        import time

        def sync_helper():
            time.sleep(0.1)       # not in async def
            with open("/x") as f:
                return f.read()

        async def handler():
            # blocking work routed off the loop; bare references to
            # blocking callables are fine
            data = await asyncio.to_thread(sync_helper)
            await asyncio.to_thread(time.sleep, 0.1)

            def nested():
                time.sleep(0.5)   # nested sync def runs via executor

            return data
    """, select="SC002")
    assert not fs


def test_sc002_pragma(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/api/startup.py", """
        async def boot():
            # spacecheck: ok=SC002 one tiny config read at startup, before serving
            with open("/etc/cfg") as f:
                return f.read()
    """, select="SC002")
    assert not fs


# --- SC003 donation safety ----------------------------------------------


SC003_BAD = """
    import functools
    import jax

    step = jax.jit(_step_impl, donate_argnums=(0,))

    def run(carry, x):
        out = step(carry, x)
        return out, carry.sum()   # read after donation
"""


def test_sc003_flags_read_after_donation(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/ops/bad_donate.py",
                     SC003_BAD, select="SC003")
    assert len(fs) == 1
    assert "donated to step()" in fs[0].message


def test_sc003_rebind_and_copy_are_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/ops/good_donate.py", """
        import jax
        import jax.numpy as jnp

        step = jax.jit(_step_impl, donate_argnums=(0,))

        def rotate(carry, xs):
            for x in xs:
                carry = step(carry, x)   # rebind clears the mark
            return carry

        def retry(carry, x):
            backup = jnp.asarray(carry) + 0   # copy BEFORE donating
            out = step(carry, x)
            return out, backup.sum()
    """, select="SC003")
    assert not fs


def test_sc003_decorated_and_cross_module(tmp_path):
    (tmp_path / "spacemesh_tpu/ops").mkdir(parents=True)
    (tmp_path / "spacemesh_tpu/ops/kern.py").write_text(textwrap.dedent("""
        import functools
        import jax

        @functools.partial(jax.jit, donate_argnums=(1,))
        def fold(x, carry):
            return x + carry
    """))
    (tmp_path / "spacemesh_tpu/ops/user.py").write_text(textwrap.dedent("""
        from . import kern

        def use(x, carry):
            out = kern.fold(x, carry)
            return out, carry[0]    # cross-module read-after-donate
    """))
    findings, errors = engine.run_paths(
        [str(tmp_path / "spacemesh_tpu")], project_root=str(tmp_path),
        select={"SC003"})
    assert not errors
    assert len(findings) == 1 and "fold()" in findings[0].message


def test_sc003_augassign_reads(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/ops/aug_donate.py", """
        import jax

        step = jax.jit(_impl, donate_argnums=(0,))

        def bad(carry, x):
            step(carry, x)
            carry += 1            # read half of += touches the buffer
            return carry
    """, select="SC003")
    assert len(fs) == 1 and "aug-assigned" in fs[0].message


# --- SC004 pairing ------------------------------------------------------


def test_sc004_register_without_unregister(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/bad_probe.py", """
        from ..obs.health import HEALTH

        def run(wd):
            HEALTH.register("post.init", wd.check)
            do_work()
    """, select="SC004")
    assert len(fs) == 1 and "register" in fs[0].message


def test_sc004_unregister_in_finally_and_class_split(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/good_probe.py", """
        from ..obs.health import HEALTH

        def run(wd):
            HEALTH.register("post.init", wd.check)
            try:
                do_work()
            finally:
                HEALTH.unregister("post.init", wd.check)

        class Component:
            def start(self):
                HEALTH.register("comp", self._probe)

            def close(self):
                HEALTH.unregister("comp", self._probe)
    """, select="SC004")
    assert not fs


def test_sc004_unregister_off_finally_flags(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/leaky_probe.py", """
        from ..obs.health import HEALTH

        def run(wd):
            HEALTH.register("post.init", wd.check)
            do_work()   # raises -> unregister skipped
            HEALTH.unregister("post.init", wd.check)
    """, select="SC004")
    assert len(fs) == 1 and "not under finally" in fs[0].message


def test_sc004_manual_span_brackets(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/ops/spans.py", """
        def bad(tracing):
            sp = tracing.span("x")
            sp.__enter__()
            work()
            sp.__exit__(None, None, None)   # skipped if work() raises

        def good(tracing):
            sp = tracing.span("x")
            sp.__enter__()
            try:
                work()
            finally:
                sp.__exit__(None, None, None)
    """, select="SC004")
    assert len(fs) == 1 and fs[0].snippet == 'sp.__enter__()'


def test_sc004_local_fd_and_executor(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/tools/handles.py", """
        from concurrent.futures import ThreadPoolExecutor

        def bad():
            f = open("/tmp/x")
            data = f.read()
            f.close()            # skipped on a raising read
            return data

        def bad2():
            pool = ThreadPoolExecutor(2)
            pool.submit(print)

        def good():
            with open("/tmp/x") as f:
                return f.read()

        def good_finally():
            f = open("/tmp/x")
            try:
                return f.read()
            finally:
                f.close()

        def good_escape():
            f = open("/tmp/x")
            return f             # caller owns the lifecycle
    """, select="SC004")
    assert len(fs) == 2
    assert {f.snippet.split(" =")[0] for f in fs} == {"f", "pool"}


def test_sc004_runtime_job_handles(tmp_path):
    """ISSUE 11: the defect class the runtime deleted must not re-enter
    through its own API — an orphaned JobHandle is flagged; consumed,
    finally-cancelled, and escaping handles are not."""
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/jobs.py", """
        def bad(sched, d):
            h = sched.submit_init("t", d, node_id=b"", commitment=b"",
                                  num_units=1, labels_per_unit=1)
            do_other_work()      # h never consumed: failure unobserved

        def good_result(sched, d):
            h = sched.submit_prove("t", d, b"ch")
            return h.result(timeout=60)

        def good_cancel(sched, d):
            h = sched.submit_verify("t", [])
            try:
                poll()
            finally:
                h.cancel()

        def bad_cancel_off_finally(sched, d):
            h = sched.submit_pow("t", b"c", b"n", b"d")
            poll()               # raises -> cancel skipped, job orphaned
            h.cancel()

        def good_escape(sched, jobs):
            h = sched.submit_call("t", work)
            jobs.append(h)       # tracked elsewhere

        def good_future_escape(sched, wrap):
            h = sched.submit_call("t", work)
            return wrap(h.future)
    """, select="SC004")
    assert len(fs) == 2
    assert all("job handle" in f.message for f in fs)
    assert {f.snippet.split(" =")[0] for f in fs} == {"h"}


def test_sc004_register_tenant_pairing(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/tenants.py", """
        def bad(sched):
            sched.register_tenant("alice")
            serve()

        def good_finally(sched):
            sched.register_tenant("bob")
            try:
                serve()
            finally:
                sched.unregister_tenant("bob")

        class Worker:
            def start(self, sched):
                sched.register_tenant("carol")

            def stop(self, sched):
                sched.unregister_tenant("carol")
    """, select="SC004")
    assert len(fs) == 1 and "register_tenant" in fs[0].message
    assert fs[0].line == 3


def test_sc004_register_client_pairing(tmp_path):
    """The verifyd client lifecycle (ISSUE 13): registration without a
    paired unregister pins per-client series and admission state."""
    fs = run_fixture(tmp_path, "spacemesh_tpu/verifyd/clients.py", """
        def bad(service):
            service.register_client("alice")
            serve()

        def good_finally(service):
            service.register_client("bob")
            try:
                serve()
            finally:
                service.unregister_client("bob")

        class Gateway:
            def on_connect(self, service, cid):
                service.register_client(cid)

            def on_disconnect(self, service, cid):
                service.unregister_client(cid)
    """, select="SC004")
    assert len(fs) == 1 and "register_client" in fs[0].message
    assert fs[0].line == 3


def test_sc004_register_client_unpaired_off_finally(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/verifyd/leaky.py", """
        def run(service):
            service.register_client("a")
            serve()   # raises -> unregister skipped
            service.unregister_client("a")
    """, select="SC004")
    assert len(fs) == 1 and "not under finally" in fs[0].message


def test_sc004_verifyd_server_start_close_pairing(tmp_path):
    """A started verifyd server needs a finally-paired close (or must
    escape — the lifecycle is handed elsewhere)."""
    fs = run_fixture(tmp_path, "spacemesh_tpu/tools/verifyd_cli.py", """
        from ..verifyd import VerifydServer

        async def bad():
            server = VerifydServer(listen="127.0.0.1:0")
            await server.start()
            await serve_forever()

        async def good():
            server = VerifydServer(listen="127.0.0.1:0")
            try:
                await server.start()
                await serve_forever()
            finally:
                await server.close()

        async def escapes(registry):
            server = VerifydServer(listen="127.0.0.1:0")
            await server.start()
            return server   # caller owns the lifecycle now

        async def never_started():
            server = VerifydServer(listen="127.0.0.1:0")
            return describe(server.port)
    """, select="SC004")
    assert len(fs) == 1 and "finally-paired close" in fs[0].message
    assert fs[0].line == 6  # anchored at the start() call


def test_sc004_breaker_and_action_registry_pairing(tmp_path):
    """The ISSUE 15 remediation lifecycles: BREAKERS/ACTIONS
    registrations pair with unregister (finally or class split); an
    unpaired breaker pins its per-component series forever."""
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/breakers.py", """
        from ..obs.remediate import ACTIONS, BREAKERS, CircuitBreaker

        def bad(br):
            BREAKERS.register(br)
            run_forever()

        def good_finally(br):
            BREAKERS.register(br)
            try:
                run_forever()
            finally:
                BREAKERS.unregister(br)

        def good_hook_finally(pipe):
            ACTIONS.register("post.init", "restart_component",
                             pipe.stop)
            try:
                run_forever()
            finally:
                ACTIONS.unregister("post.init", "restart_component",
                                   pipe.stop)

        class Component:
            def start(self):
                ACTIONS.register("comp", "restart_component",
                                 self.restart)

            def close(self):
                ACTIONS.unregister("comp", "restart_component",
                                   self.restart)
    """, select="SC004")
    assert len(fs) == 1 and "BREAKERS/ACTIONS register" in fs[0].message
    assert fs[0].line == 5  # the bad() register call


def test_sc004_breaker_unregister_off_finally_flags(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/leaky_breaker.py", """
        from ..obs.remediate import BREAKERS

        def run(br):
            BREAKERS.register(br)
            serve()   # raises -> unregister skipped
            BREAKERS.unregister(br)
    """, select="SC004")
    assert len(fs) == 1 and "not under finally" in fs[0].message


def test_sc004_remediation_engine_start_close_pairing(tmp_path):
    """RemediationEngine/FailoverVerifier follow the started-must-close
    rule: a leaked engine keeps consuming bus verdicts."""
    fs = run_fixture(tmp_path, "spacemesh_tpu/tools/remed_cli.py", """
        from ..obs.remediate import RemediationEngine
        from ..verifyd.failover import FailoverVerifier

        async def bad(bus):
            engine = RemediationEngine(bus=bus)
            engine.start()
            await serve_forever()

        async def good(bus):
            engine = RemediationEngine(bus=bus)
            try:
                engine.start()
                await serve_forever()
            finally:
                engine.close()

        async def good_failover(remote, farm):
            fv = FailoverVerifier(remote=remote, farm=farm)
            try:
                fv.start()
                await drive(fv)
            finally:
                await fv.aclose()

        async def escapes(bus, registry):
            engine = RemediationEngine(bus=bus)
            engine.start()
            return engine   # caller owns the lifecycle now
    """, select="SC004")
    assert len(fs) == 1 and "finally-paired close" in fs[0].message
    assert fs[0].line == 7  # anchored at the start() call


def test_sc004_fleet_started_must_close(tmp_path):
    """ISSUE 17 fleet lifecycles: a started FleetRouter/FleetVerifier
    needs a finally-paired close (or must escape) — a leaked router
    pins every replica's breaker and fleet_replica_* series."""
    fs = run_fixture(tmp_path, "spacemesh_tpu/tools/fleet_cli.py", """
        from ..verifyd.fleet import FleetRouter, FleetVerifier

        async def bad(farm):
            router = FleetRouter(seed=1)
            router.start()
            await router.serve_forever()

        async def good(farm):
            fv = FleetVerifier(router=make_router(), farm=farm,
                               own_router=True)
            try:
                fv.start()
                await fv.serve_forever()
            finally:
                await fv.aclose()

        async def escapes(farm):
            router = FleetRouter(seed=1)
            router.start()
            return router   # caller owns the lifecycle now
    """, select="SC004")
    assert len(fs) == 1 and "finally-paired close" in fs[0].message
    assert fs[0].line == 6  # anchored at bad()'s start() call


def test_sc004_register_replica_pairing(tmp_path):
    """register_replica pairs with unregister_replica (finally or the
    class split), exactly like tenants and clients: a replica that left
    the fleet must not pin its breaker and per-replica series."""
    fs = run_fixture(tmp_path, "spacemesh_tpu/verifyd/fleet_ops.py", """
        def bad(router, endpoint):
            router.register_replica("r9", endpoint)
            drive(router)

        def good_finally(router, endpoint):
            router.register_replica("r9", endpoint)
            try:
                drive(router)
            finally:
                router.unregister_replica("r9")

        class Pool:
            def attach(self, name, endpoint):
                self.router.register_replica(name, endpoint)

            def detach(self, name):
                self.router.unregister_replica(name)
    """, select="SC004")
    assert len(fs) == 1 and "register_replica" in fs[0].message
    assert fs[0].line == 3  # the bad() register call


def test_sc004_register_replica_unpaired_off_finally(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/verifyd/fleet_leak.py", """
        def run(router, endpoint):
            router.register_replica("r9", endpoint)
            drive(router)   # raises -> unregister skipped
            router.unregister_replica("r9")
    """, select="SC004")
    assert len(fs) == 1 and "not under finally" in fs[0].message


# --- SC005 metrics hygiene ----------------------------------------------


def test_sc005_creation_in_function_and_fstring_labels(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/obs/bad_metrics.py", """
        from ..utils.metrics import REGISTRY

        hits = REGISTRY.counter("hits_total", "ok at module scope")

        def lazy(name):
            c = REGISTRY.counter("late_total", "created mid-run")
            return c

        def record(peer):
            hits.inc(peer=f"{peer}")         # cardinality bomb
            hits.inc(**{"peer": "x"})        # non-literal label schema
    """, select="SC005")
    msgs = [f.message for f in fs]
    assert len(fs) == 3
    assert any("inside a function" in m for m in msgs)
    assert any("f-string label value" in m for m in msgs)
    assert any("splat label names" in m for m in msgs)


def test_sc005_duplicate_names_across_files(tmp_path):
    (tmp_path / "spacemesh_tpu/a").mkdir(parents=True)
    (tmp_path / "spacemesh_tpu/a/m1.py").write_text(
        'from ..utils.metrics import REGISTRY\n'
        'x = REGISTRY.counter("dup_total", "first")\n')
    (tmp_path / "spacemesh_tpu/a/m2.py").write_text(
        'from ..utils.metrics import REGISTRY\n'
        'y = REGISTRY.counter("dup_total", "second")\n')
    findings, errors = engine.run_paths(
        [str(tmp_path / "spacemesh_tpu")], project_root=str(tmp_path),
        select={"SC005"})
    assert not errors
    assert len(findings) == 1
    assert "already registered" in findings[0].message
    assert findings[0].path.endswith("m2.py")


def test_sc005_bounded_literal_labels_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/obs/good_metrics.py", """
        from ..utils.metrics import REGISTRY

        drops = REGISTRY.counter("drops_total", "by reason")

        def record(e):
            drops.inc(reason=type(e).__name__)   # bounded enum: fine
    """, select="SC005")
    assert not fs


# --- SC006 bare/swallowing excepts --------------------------------------


def test_sc006_flags_and_accepts_justified(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/consensus/swallow.py", """
        def bad():
            try:
                risky()
            except:
                pass

        def bad2():
            try:
                risky()
            except Exception:
                pass

        def good_logged(log):
            try:
                risky()
            except Exception as e:
                log.warning("risky failed: %r", e)

        def good_justified():
            try:
                risky()
            except Exception:  # noqa: BLE001 — best-effort cache warm, next tick retries
                pass

        def good_pragma():
            try:
                risky()
            except Exception:  # spacecheck: ok=SC006 teardown path, error already surfaced upstream
                pass
    """, select="SC006")
    assert len(fs) == 2
    assert {"bare except" in f.message or "broad except" in f.message
            for f in fs} == {True}


def test_sc006_out_of_scope_package_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/tools/swallow.py", """
        def tool():
            try:
                risky()
            except Exception:
                pass
    """, select="SC006")
    assert not fs


# --- SC009 durability (fsync-bracketed persistence) ----------------------


def test_sc009_flags_naked_rename_persistence(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/ops/persist.py", """
        import os
        from pathlib import Path

        def save_cache(tmp, path):
            os.replace(tmp, path)

        def save_cache2(tmp, path):
            os.rename(tmp, path)

        def save_cache3(doc, path: Path):
            tmp = path.with_suffix(".tmp")
            tmp.write_text(doc)
            tmp.replace(path)

        def move(p: Path, dest: Path):
            p.rename(dest)

        def constant_target(tmp: Path):
            tmp.replace("cache.json")
    """, select="SC009")
    assert len(fs) == 5
    assert all(f.rule == "SC009" for f in fs)
    assert any("os.replace" in f.message for f in fs)
    assert any("utils/fsio" in f.message for f in fs)


def test_sc009_fixed_twin_and_string_replace_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/ops/persist_ok.py", """
        import json
        from ..utils import fsio

        def save_cache(doc, path):
            fsio.atomic_write_text(path, json.dumps(doc))

        def publish_built(tmp, lib):
            fsio.persist(tmp, lib)

        def munge(s: str) -> str:
            # str.replace takes two+ args: never a rename
            return s.replace("a", "b").replace("c", "d", 1)

        def label(v):
            return str(v).replace("\\n", " ")
    """, select="SC009")
    assert not fs


def test_sc009_pragma_and_out_of_package_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/tools/mover.py", """
        def archive(key_file):
            key_file.rename(key_file.with_suffix(".merged"))  # spacecheck: ok=SC009 archival move of an already-durable file
    """, select="SC009")
    assert not fs
    # the fsio module itself implements the discipline: exempt
    fs = run_fixture(tmp_path, "spacemesh_tpu/utils/fsio.py", """
        import os

        def replace(src, dst):
            os.replace(src, dst)
    """, select="SC009")
    assert not fs
    # outside the package: none of spacecheck's business
    fs = run_fixture(tmp_path, "scripts/move.py", """
        import os

        def mv(a, b):
            os.replace(a, b)
    """, select="SC009")
    assert not fs


# --- engine: pragmas, fingerprints, errors ------------------------------


def test_unparseable_file_is_an_error(tmp_path):
    p = tmp_path / "spacemesh_tpu" / "broken.py"
    p.parent.mkdir(parents=True)
    p.write_text("def broken(:\n")
    findings, errors = engine.run_paths([str(p)],
                                        project_root=str(tmp_path))
    assert errors and "broken.py" in errors[0]


def test_fingerprints_survive_code_motion(tmp_path):
    src = """
        import time

        def deadline():
            return time.time() + 5.0
    """
    fs1 = run_fixture(tmp_path, "spacemesh_tpu/sim/move1.py", src)
    # same offending line, 40 lines further down the file
    fs2 = run_fixture(tmp_path, "spacemesh_tpu/sim/move1.py",
                      "\n" * 40 + textwrap.dedent(src))
    assert fs1[0].fingerprint == fs2[0].fingerprint
    assert fs1[0].line != fs2[0].line


def test_identical_lines_match_baseline_as_multiset(tmp_path):
    # identical offending lines share a fingerprint; the baseline
    # matches them as a multiset, so a SECOND identical violation added
    # above a grandfathered one surfaces as exactly one new finding —
    # it can never steal the existing entry's suppression
    fs1 = run_fixture(tmp_path, "spacemesh_tpu/sim/twice.py", """
        import time

        def a():
            return time.time()
    """)
    assert len(fs1) == 1
    bl = {fs1[0].fingerprint: [{"fingerprint": fs1[0].fingerprint,
                                "rule": "SC001",
                                "justification": "grandfathered"}]}
    fs2 = run_fixture(tmp_path, "spacemesh_tpu/sim/twice.py", """
        import time

        def zero():
            return time.time()

        def a():
            return time.time()
    """)
    assert len(fs2) == 2
    assert fs2[0].fingerprint == fs2[1].fingerprint == fs1[0].fingerprint
    new, suppressed, stale = baseline_mod.split(fs2, bl)
    assert len(new) == 1 and len(suppressed) == 1 and not stale
    # and with only the original line, nothing is new or stale
    new, suppressed, stale = baseline_mod.split(fs1, bl)
    assert not new and len(suppressed) == 1 and not stale


def test_write_baseline_preserves_justifications(tmp_path):
    _seed_violation(tmp_path)
    args = [str(tmp_path / "spacemesh_tpu"), "--root", str(tmp_path)]
    bl = tmp_path / "bl.json"
    assert cli_main(args + ["--write-baseline", str(bl)]) == 0
    doc = json.loads(bl.read_text())
    doc["findings"][0]["justification"] = "carefully reviewed, accepted"
    bl.write_text(json.dumps(doc))
    # add a second (different) violation, regenerate: the existing
    # justification survives, only the new entry is TODO
    (tmp_path / "spacemesh_tpu/sim/seeded2.py").write_text(
        "import time\n\ndef worse():\n    return time.monotonic()\n")
    assert cli_main(args + ["--write-baseline", str(bl)]) == 0
    doc = json.loads(bl.read_text())
    justs = {e["path"]: e["justification"] for e in doc["findings"]}
    assert justs["spacemesh_tpu/sim/seeded.py"] == \
        "carefully reviewed, accepted"
    assert justs["spacemesh_tpu/sim/seeded2.py"] == "TODO"


# --- CLI + baseline workflow --------------------------------------------


def _seed_violation(root, rule="SC001"):
    p = root / "spacemesh_tpu" / "sim"
    p.mkdir(parents=True, exist_ok=True)
    (p / "seeded.py").write_text(
        "import time\n\ndef bad():\n    return time.time()\n")


SEEDS = {
    "SC001": "import time\ndef f():\n    return time.time()\n",
    "SC002": "import time\nasync def f():\n    time.sleep(1)\n",
    "SC003": ("import jax\ns = jax.jit(i, donate_argnums=(0,))\n"
              "def f(c):\n    s(c)\n    return c\n"),
    "SC004": ("def f(HEALTH, wd):\n"
              "    HEALTH.register('x', wd)\n    work()\n"),
    "SC005": ("from ..utils.metrics import REGISTRY\n"
              "c = REGISTRY.counter('x_total', 'h')\n"
              "def f(v):\n    c.inc(reason=f'{v}')\n"),
    "SC006": "def f():\n    try:\n        g()\n    except:\n        pass\n",
    "SC007": ("import threading\n"
              "class P:\n"
              "    def __init__(self):\n"
              "        self._lock = threading.Lock()\n"
              "        self._n = 0\n"
              "        self._t = threading.Thread(target=self._w)\n"
              "    def _w(self):\n"
              "        with self._lock:\n"
              "            self._n += 1\n"
              "    def read(self):\n"
              "        return self._n\n"),
    "SC008": ("import threading\n"
              "A = threading.Lock()\n"
              "B = threading.Lock()\n"
              "def f():\n"
              "    with A:\n"
              "        with B:\n"
              "            pass\n"
              "def g():\n"
              "    with B:\n"
              "        with A:\n"
              "            pass\n"),
    "SC009": ("import os\n"
              "def persist(tmp, path):\n"
              "    os.replace(tmp, path)\n"),
}


@pytest.mark.parametrize("rule", sorted(SEEDS))
def test_seeded_violation_fails_cli(tmp_path, rule, capsys):
    # acceptance criterion: seeding any one of the six rule violations
    # into a scratch file makes the runner exit non-zero. The scratch
    # file lands in a package the rule's scope covers (SC006 only scans
    # consensus/verify/p2p; SC001 only the virtual-time packages).
    pkg = "consensus" if rule == "SC006" else "sim"
    p = tmp_path / "spacemesh_tpu" / pkg
    p.mkdir(parents=True)
    (p / "seeded.py").write_text(SEEDS[rule])
    rc = cli_main([str(p / "seeded.py"), "--root", str(tmp_path),
                   "--no-baseline", "--select", rule])
    out = capsys.readouterr().out
    assert rc == 1
    assert rule in out


def test_clean_file_passes_cli(tmp_path, capsys):
    p = tmp_path / "spacemesh_tpu" / "sim"
    p.mkdir(parents=True)
    (p / "clean.py").write_text("def ok(now):\n    return now + 1\n")
    rc = cli_main([str(p / "clean.py"), "--root", str(tmp_path)])
    assert rc == 0


def test_github_format(tmp_path, capsys):
    _seed_violation(tmp_path)
    rc = cli_main([str(tmp_path / "spacemesh_tpu"), "--root",
                   str(tmp_path), "--format", "github"])
    out = capsys.readouterr().out
    assert rc == 1
    assert out.startswith("::error file=spacemesh_tpu/sim/seeded.py,")
    assert "title=spacecheck SC001" in out


def test_baseline_workflow(tmp_path, capsys):
    _seed_violation(tmp_path)
    args = [str(tmp_path / "spacemesh_tpu"), "--root", str(tmp_path)]
    bl = tmp_path / "spacecheck_baseline.json"

    # 1. --write-baseline emits TODO justifications ...
    rc = cli_main(args + ["--write-baseline", str(bl)])
    assert rc == 0
    # 2. ... which the checker REJECTS until replaced
    rc = cli_main(args + ["--baseline", str(bl)])
    assert rc == 2
    # 3. justified baseline passes
    doc = json.loads(bl.read_text())
    for ent in doc["findings"]:
        ent["justification"] = "grandfathered: legacy tool, tracked in #9"
    bl.write_text(json.dumps(doc))
    rc = cli_main(args + ["--baseline", str(bl)])
    assert rc == 0
    # 4. a NEW finding still fails against the baseline
    (tmp_path / "spacemesh_tpu/sim/seeded2.py").write_text(
        "import time\n\ndef worse():\n    return time.monotonic()\n")
    rc = cli_main(args + ["--baseline", str(bl)])
    assert rc == 1
    os.unlink(tmp_path / "spacemesh_tpu/sim/seeded2.py")
    # 5. fixing the original finding makes its entry STALE -> failure
    (tmp_path / "spacemesh_tpu/sim/seeded.py").write_text(
        "def fixed(now):\n    return now\n")
    rc = cli_main(args + ["--baseline", str(bl)])
    err = capsys.readouterr().err
    assert rc == 2
    assert "STALE" in err


def test_unjustified_pragma_does_not_suppress(tmp_path):
    # the pragma is the one suppression channel that could bypass the
    # justification contract — a bare `ok=SC001` must not count
    fs = run_fixture(tmp_path, "spacemesh_tpu/sim/bare_pragma.py", """
        import time

        def stamp():
            return time.time()  # spacecheck: ok=SC001
    """)
    assert [f for f in fs if f.rule == "SC001"]


def test_select_does_not_stale_other_rules_baseline(tmp_path, capsys):
    # --select computes no findings for deselected rules; their
    # baseline entries must not be reported as rot (exit 2)
    p = tmp_path / "spacemesh_tpu" / "consensus"
    p.mkdir(parents=True)
    (p / "seeded.py").write_text(SEEDS["SC006"])
    args = [str(tmp_path / "spacemesh_tpu"), "--root", str(tmp_path)]
    bl = tmp_path / "bl.json"
    assert cli_main(args + ["--write-baseline", str(bl)]) == 0
    doc = json.loads(bl.read_text())
    for ent in doc["findings"]:
        ent["justification"] = "grandfathered teardown swallow, tracked"
    bl.write_text(json.dumps(doc))
    rc = cli_main(args + ["--baseline", str(bl), "--select", "SC001"])
    assert rc == 0, capsys.readouterr().err


def test_baseline_rejects_missing_justification(tmp_path):
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({
        "version": 1,
        "findings": [{"fingerprint": "abc", "rule": "SC001",
                      "path": "x.py", "snippet": "s",
                      "justification": ""}]}))
    with pytest.raises(baseline_mod.BaselineError):
        baseline_mod.load(str(bl))


def test_real_tree_is_clean():
    # the shipped tree + checked-in baseline must pass: this is the CI
    # contract, asserted from inside tier-1 too so a regression fails
    # fast locally, not just in the spacecheck job
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    res = subprocess.run(
        [sys.executable, "-m", "spacemesh_tpu.tools.spacecheck",
         "--root", root],
        cwd=root, capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr


# --- runtime sanitizers -------------------------------------------------


@pytest.fixture
def armed_sanitizer():
    sanitize.clear_violations()
    sanitize.enable(slow_threshold_s=0.05)
    yield sanitize
    sanitize.disable()
    sanitize.clear_violations()


def test_sanitizer_catches_injected_loop_block(armed_sanitizer):
    async def main():
        loop = asyncio.get_running_loop()
        loop.call_soon(lambda: time.sleep(0.12))
        await asyncio.sleep(0.01)

    asyncio.run(main())
    hits = [v for v in sanitize.violations() if v.kind == "slow-callback"]
    assert hits and hits[0].seconds >= 0.05


def test_sanitizer_slow_callback_attributes_span(armed_sanitizer):
    from spacemesh_tpu.utils import tracing

    tracing.start(capacity=64)
    try:
        seen: dict = {}

        async def main():
            with tracing.span("blocky") as sp:
                seen["id"] = sp.id
                loop = asyncio.get_running_loop()
                # call_soon copies the CURRENT context -> the span id
                # travels into the callback's contextvars
                loop.call_soon(lambda: time.sleep(0.1))
            await asyncio.sleep(0.01)

        asyncio.run(main())
    finally:
        tracing.stop()
    hits = [v for v in sanitize.violations() if v.kind == "slow-callback"]
    assert hits and hits[0].span == seen["id"]


def test_sanitizer_quiet_on_fast_callbacks(armed_sanitizer):
    async def main():
        loop = asyncio.get_running_loop()
        for _ in range(50):
            loop.call_soon(lambda: None)
        await asyncio.sleep(0.01)

    asyncio.run(main())
    assert not sanitize.violations()


def test_sanitizer_off_bucket_compile_raises(armed_sanitizer,
                                             monkeypatch):
    from spacemesh_tpu.ops import scrypt

    monkeypatch.setenv("SPACEMESH_SHAPE_BUCKETS", "off")
    cw = scrypt.commitment_to_words(b"\x01" * 32)
    lo, hi = scrypt.split_indices(np.arange(7, dtype=np.uint64))
    with pytest.raises(sanitize.SanitizeError, match="off-bucket"):
        scrypt.scrypt_labels_jit(cw, lo, hi, n=2)
    assert any(v.kind == "jit-shape" for v in sanitize.violations())


def test_sanitizer_bucketed_dispatch_clean(armed_sanitizer):
    from spacemesh_tpu.ops import scrypt

    cw = scrypt.commitment_to_words(b"\x01" * 32)
    lo, hi = scrypt.split_indices(np.arange(7, dtype=np.uint64))
    out = scrypt.scrypt_labels_jit(cw, lo, hi, n=2)  # pads 7 -> 8
    assert out.shape == (4, 7)
    assert not [v for v in sanitize.violations() if v.kind == "jit-shape"]


def test_sanitizer_registry_thread_affinity(armed_sanitizer):
    from spacemesh_tpu.utils import metrics

    reg = metrics.Registry()
    reg.counter("spacecheck_test_main_ok_total")  # owner thread: fine

    caught: list = []

    def off_thread():
        try:
            reg.counter("spacecheck_test_off_thread_total")
        except sanitize.SanitizeError as e:
            caught.append(e)

    t = threading.Thread(target=off_thread)
    t.start()
    t.join()
    assert caught, "off-thread instrument creation did not raise"
    # recording (not creating) from a worker thread stays legal
    c = reg.counter("spacecheck_test_record_total")

    t = threading.Thread(target=lambda: c.inc(kind="worker"))
    t.start()
    t.join()
    assert c.sample()[(("kind", "worker"),)] == 1.0


def test_sanitizer_disabled_is_free():
    sanitize.disable()
    sanitize.clear_violations()
    from spacemesh_tpu.ops import scrypt

    # off: no raise on odd shapes, no recording
    sanitize.on_jit_shape("labels_fused", 7)
    assert not sanitize.violations()

    async def main():
        loop = asyncio.get_running_loop()
        loop.call_soon(lambda: time.sleep(0.06))
        await asyncio.sleep(0.01)

    asyncio.run(main())
    assert not sanitize.violations()


def test_sanitizer_env_boot(tmp_path):
    # SPACEMESH_SANITIZE=1 arms the sanitizer at import (the CI
    # sanitize-smoke path) and a tiny init runs CLEAN under it
    code = textwrap.dedent("""
        import hashlib, tempfile
        from spacemesh_tpu.utils import sanitize
        assert sanitize.enabled(), "env did not arm the sanitizer"
        from spacemesh_tpu.post import initializer
        with tempfile.TemporaryDirectory() as d:
            info = initializer.initialize(
                d, node_id=hashlib.sha256(b"n").digest(),
                commitment=hashlib.sha256(b"c").digest(), num_units=1,
                labels_per_unit=256, scrypt_n=2, max_file_size=4096,
                batch_size=128)
        bad = [v for v in sanitize.violations()
               if v.kind in ("jit-shape", "registry-thread")]
        assert not bad, bad
        print("sanitized init clean")
    """)
    env = os.environ | {"SPACEMESH_SANITIZE": "1", "JAX_PLATFORMS": "cpu"}
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "sanitized init clean" in res.stdout
