"""Sync/fetch trust hardening: lying peers, scoring, certs, fork finder.

Round-2 VERDICT item 8: a late joiner must converge despite a lying peer
(reference cross-checks opinions across peers, syncer/data_fetch.go; peer
scoring fetch/peers/peers.go; fork finder syncer/find_fork.go; cert
verification on adoption; malfeasance sync syncer/malsync).
"""

import asyncio
import struct
import time

import pytest

from spacemesh_tpu.core.hashing import sum256
from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.p2p import fetch as fetch_mod
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.p2p.server import LoopbackNet, Server
from spacemesh_tpu.p2p.sync import Syncer
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.storage import misc as miscstore

LPE = 3
LAYER_SEC = 0.8

GENESIS_PLACEHOLDER = float(int(time.time()) + 3600)


def _config(tmp_path, name, smesh):
    return load("standalone", overrides={
        "data_dir": str(tmp_path / name),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": GENESIS_PLACEHOLDER},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": smesh, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.1,
                 "preround_delay": 0.35, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.1},
        "tortoise": {"hdist": 4, "window_size": 50},
    })


class LiarServer(Server):
    """A peer that serves garbage layer data, a forged certificate, and a
    fake beacon — everything a malicious peer could use to poison a late
    joiner."""

    def __init__(self):
        super().__init__(b"liar" + bytes(28))
        self.fake_block = sum256(b"fake block id")

        async def lie_layer(peer, data):
            return fetch_mod.LayerData(
                ballots=[], blocks=[self.fake_block],
                certified=self.fake_block).to_bytes()

        async def lie_cert(peer, data):
            from spacemesh_tpu.core.types import Certificate

            return Certificate(block_id=self.fake_block,
                               signatures=[]).to_bytes()

        async def lie_beacon(peer, data):
            return b"\xba\xad\xf0\x0d"

        async def empty(peer, data):
            return b""

        self.register(fetch_mod.P_LAYER, lie_layer)
        self.register("ct/1", lie_cert)
        self.register("bk/1", lie_beacon)
        self.register(fetch_mod.P_EPOCH, empty)
        self.register("pt/1", empty)
        self.register("ml/1", empty)
        self.register("lh/1", empty)
        self.register(fetch_mod.P_HASH, self._lie_hashes)

    async def _lie_hashes(self, peer, data):
        req = fetch_mod.HashRequest.from_bytes(data)
        # serve garbage bytes for every requested id
        return fetch_mod.HashResponse(
            blobs=[b"garbage" for _ in req.hashes]).to_bytes()


@pytest.fixture(scope="module")
def network(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("synchard")
    hub = LoopbackHub()
    net = LoopbackNet()
    liar = LiarServer()
    net.join(liar)

    def make(name, smesh):
        cfg = _config(tmp, name, smesh)
        signer = EdSigner(prefix=cfg.genesis.genesis_id)
        ps = PubSub(node_name=signer.node_id)
        hub.join(ps)
        app = App(cfg, signer=signer, pubsub=ps)
        app.connect_network(net)
        return app

    a = make("a", smesh=True)
    holder = {}

    async def go():
        await a.prepare()
        genesis = time.time() + 0.3
        a.clock = clock_mod.LayerClock(genesis, LAYER_SEC)
        until = 2 * LPE + 1
        task_a = asyncio.create_task(a.run(until_layer=until))
        await asyncio.sleep(LAYER_SEC * (LPE + 1))
        # C joins late; the liar is among its peers
        c = make("c", smesh=False)
        c.clock = clock_mod.LayerClock(genesis, LAYER_SEC)
        holder["c"] = c
        await c.syncer.synchronize()
        await task_a
        await c.syncer.synchronize()

    asyncio.run(asyncio.wait_for(go(), timeout=180))
    return a, holder["c"], liar


def test_late_joiner_converges_despite_lying_peer(network):
    a, c, liar = network
    applied_a = layerstore.last_applied(a.state)
    applied_c = layerstore.last_applied(c.state)
    assert applied_c >= applied_a - 1
    for lyr in range(LPE, applied_c + 1):
        assert blockstore.ids_in_layer(a.state, lyr) == \
            blockstore.ids_in_layer(c.state, lyr), f"layer {lyr} diverged"
    # the liar's fabricated block must not exist anywhere in C
    assert blockstore.get(c.state, liar.fake_block) is None


def test_forged_certificate_rejected(network):
    a, c, liar = network
    # no layer in C is certified by the liar's fake block
    for lyr in range(1, layerstore.last_applied(c.state) + 1):
        assert miscstore.certified_block(c.state, lyr) != liar.fake_block


def test_lying_peer_scored_down(network):
    a, c, liar = network
    # the liar served garbage blobs; its score must be above any honest
    # peer's and (with this much lying) past the drop threshold
    score = c.fetch._peer_score.get(liar.node_id, 0)
    assert score >= c.fetch.bad_peer_threshold, score
    assert liar.node_id not in c.fetch.peers()


def test_beacon_not_poisoned_by_single_liar(network):
    a, c, liar = network
    for epoch in (0, 1, 2):
        assert miscstore.get_beacon(c.state, epoch) != b"\xba\xad\xf0\x0d"


def test_malfeasance_syncs(network):
    """Mark an identity malicious on A; C learns it on the next pass."""
    a, c, liar = network
    from spacemesh_tpu.consensus import malfeasance as mal_mod
    from spacemesh_tpu.consensus.hare import HareMessage
    from spacemesh_tpu.core.signing import Domain

    evil = EdSigner(prefix=a.cfg.genesis.genesis_id)

    def hare_msg(values):
        m = HareMessage(layer=2, iteration=0, round=0, values=values,
                        eligibility_proof=bytes(80), eligibility_count=1,
                        atx_id=bytes(32), node_id=evil.node_id,
                        cert_msgs=[], signature=bytes(64))
        m.signature = evil.sign(Domain.HARE, m.signed_bytes())
        return m

    m1, m2 = hare_msg([sum256(b"p1")]), hare_msg([sum256(b"p2")])
    proof = mal_mod.MalfeasanceProof(
        domain=int(Domain.HARE), msg1=m1.signed_bytes(), sig1=m1.signature,
        msg2=m2.signed_bytes(), sig2=m2.signature, node_id=evil.node_id)
    assert a.malfeasance.process(proof)

    async def go():
        await c.syncer.synchronize()

    asyncio.run(go())
    assert miscstore.is_malicious(c.state, evil.node_id)


def test_fork_finder_bisects_divergence():
    """Unit: a peer whose aggregated hashes diverge from layer 5 on makes
    the syncer call on_fork(5)."""
    net = LoopbackNet()
    me = Server(b"m" * 32)
    peer = Server(b"p" * 32)
    net.join(me)
    net.join(peer)

    local = {lyr: sum256(b"shared", bytes([lyr])) for lyr in range(1, 11)}
    remote = dict(local)
    for lyr in range(5, 11):
        remote[lyr] = sum256(b"forked", bytes([lyr]))

    async def serve_hash(_, data):
        lyr = struct.unpack("<I", data)[0]
        return remote.get(lyr, b"")

    peer.register("lh/1", serve_hash)
    forks = []

    fetch = fetch_mod.Fetch(me)
    syncer = Syncer(
        fetch=fetch, current_layer=lambda: 10,
        processed_layer=lambda: 10,
        process_layer=None, layers_per_epoch=LPE,
        layer_hash=lambda lyr: local.get(lyr),
        on_fork=forks.append)

    async def go():
        assert await syncer._check_fork()

    asyncio.run(go())
    assert forks == [5]
