"""End-to-end POST cycle: initialize -> resume -> prove -> verify.

The TPU-build analogue of the reference's activation/e2e tests (real CGo
post with tiny units): tiny label counts, fastnet-style scrypt N=2,
full byte-level roundtrip through the disk format.
"""

import dataclasses
import hashlib

import numpy as np
import pytest

from spacemesh_tpu.ops import scrypt
from spacemesh_tpu.post import initializer, verifier
from spacemesh_tpu.post.data import PostMetadata
from spacemesh_tpu.post.prover import Proof, ProofParams, Prover

NODE = hashlib.sha256(b"node-1").digest()
COMMIT = hashlib.sha256(b"commitment-1").digest()
CH = hashlib.sha256(b"poet-ref").digest()

PARAMS = ProofParams(k1=64, k2=16, k3=8,
                     pow_difficulty=bytes([32]) + bytes([255]) * 31)


@pytest.fixture(scope="module")
def unit(tmp_path_factory):
    d = tmp_path_factory.mktemp("post")
    meta, res = initializer.initialize(
        d, node_id=NODE, commitment=COMMIT, num_units=2,
        labels_per_unit=512, scrypt_n=2, max_file_size=4096,
        batch_size=256)
    return d, meta, res


def test_init_writes_correct_labels(unit):
    d, meta, res = unit
    assert meta.labels_written == 1024
    assert res.labels_per_s > 0
    store = initializer.Initializer(d, meta).store
    got = np.frombuffer(store.read_labels(100, 8), dtype=np.uint8).reshape(8, 16)
    want = scrypt.scrypt_labels(COMMIT, np.arange(100, 108, dtype=np.uint64), n=2)
    assert np.array_equal(got, want)
    # multiple files were produced (max_file_size 4096 = 256 labels/file)
    assert (d / "postdata_0.bin").exists() and (d / "postdata_3.bin").exists()


def test_vrf_nonce_is_global_min(unit):
    d, meta, _ = unit
    labels = scrypt.scrypt_labels(COMMIT, np.arange(1024, dtype=np.uint64), n=2)
    lo = labels[:, :8].copy().view("<u8").ravel()
    hi = labels[:, 8:].copy().view("<u8").ravel()
    k = int(np.lexsort((lo, hi))[0])
    assert meta.vrf_nonce == k
    assert bytes.fromhex(meta.vrf_nonce_value) == bytes(labels[k])


def test_resume_after_partial_init(tmp_path):
    # first pass: stop after 1 batch via the progress callback
    calls = []

    def stop_soon(done, total):
        calls.append(done)
        if done >= 256:
            init.stop()

    meta = PostMetadata(node_id=NODE.hex(), commitment=COMMIT.hex(),
                        scrypt_n=2, num_units=1, labels_per_unit=1024,
                        max_file_size=1 << 20)
    init = initializer.Initializer(tmp_path, meta, batch_size=256,
                                   progress=stop_soon)
    init.run()
    assert init.status == initializer.Status.STOPPED
    partial = PostMetadata.load(tmp_path)
    assert 0 < partial.labels_written < 1024

    # second pass: resume to completion; data must equal a fresh init
    meta2, _ = initializer.initialize(
        tmp_path, node_id=NODE, commitment=COMMIT, num_units=1,
        labels_per_unit=1024, scrypt_n=2, max_file_size=1 << 20,
        batch_size=256)
    assert meta2.labels_written == 1024
    store = initializer.Initializer(tmp_path, meta2).store
    got = np.frombuffer(store.read_labels(0, 1024), dtype=np.uint8).reshape(-1, 16)
    want = scrypt.scrypt_labels(COMMIT, np.arange(1024, dtype=np.uint64), n=2)
    assert np.array_equal(got, want)


def test_mismatched_params_rejected(unit):
    d, _, _ = unit
    with pytest.raises(ValueError, match="different"):
        initializer.initialize(d, node_id=NODE, commitment=COMMIT,
                               num_units=2, labels_per_unit=512, scrypt_n=4,
                               max_file_size=4096)


@pytest.fixture(scope="module")
def proof(unit):
    # the default prove path — the streaming pipelined scan
    d, meta, _ = unit
    return Prover(d, PARAMS, batch_labels=512).prove(CH)


@pytest.fixture(scope="module")
def serial_proof(unit):
    # the legacy synchronous scan kept as baseline/fallback
    d, meta, _ = unit
    return Prover(d, PARAMS, batch_labels=512).prove_serial(CH)


def _item(meta: PostMetadata, pr: Proof) -> verifier.VerifyItem:
    return verifier.VerifyItem(
        proof=pr, challenge=CH, node_id=NODE, commitment=COMMIT,
        scrypt_n=meta.scrypt_n, total_labels=meta.total_labels)


def test_prove_verify_roundtrip(unit, proof):
    _, meta, _ = unit
    assert len(proof.indices) == PARAMS.k2
    assert proof.indices == sorted(proof.indices)
    assert verifier.verify(_item(meta, proof), PARAMS)


def test_serial_roundtrip_and_identity(unit, proof, serial_proof):
    # the legacy path verifies too, and the pipelined prover's proof is
    # bit-identical to it (nonce, indices, pow_nonce) for a fixed challenge
    _, meta, _ = unit
    assert verifier.verify(_item(meta, serial_proof), PARAMS)
    assert serial_proof == proof


@pytest.mark.parametrize("serial", [False, True],
                         ids=["pipelined", "serial"])
def test_wrong_nonce_rejected_both_paths(unit, proof, serial_proof, serial):
    _, meta, _ = unit
    pr = serial_proof if serial else proof
    bad = dataclasses.replace(pr, nonce=pr.nonce + 1)
    assert not verifier.verify(
        dataclasses.replace(_item(meta, pr), proof=bad), PARAMS)


@pytest.mark.parametrize("serial", [False, True],
                         ids=["pipelined", "serial"])
def test_corrupted_labels_rejected_both_paths(unit, tmp_path, serial):
    # a store whose labels were corrupted on disk yields proofs the
    # verifier's recompute rejects — through either prove path
    import shutil

    d, meta, _ = unit
    bad_dir = tmp_path / "corrupt"
    shutil.copytree(d, bad_dir)
    for f in sorted(bad_dir.glob("postdata_*.bin")):
        raw = bytearray(f.read_bytes())
        raw[::16] = bytes((b ^ 0x5A) for b in raw[::16])  # hit every label
        f.write_bytes(raw)
    prover = Prover(bad_dir, PARAMS, batch_labels=512, pipelined=not serial)
    pr = prover.prove_serial(CH) if serial else prover.prove(CH)
    assert not verifier.verify(
        verifier.VerifyItem(proof=pr, challenge=CH, node_id=NODE,
                            commitment=COMMIT, scrypt_n=meta.scrypt_n,
                            total_labels=meta.total_labels), PARAMS)


def test_tampered_proofs_rejected(unit, proof):
    _, meta, _ = unit
    good = _item(meta, proof)

    bad_idx = dataclasses.replace(
        proof, indices=[(i + 1) % meta.total_labels for i in proof.indices])
    assert not verifier.verify(dataclasses.replace(good, proof=bad_idx), PARAMS)

    bad_nonce = dataclasses.replace(proof, nonce=proof.nonce + 1)
    assert not verifier.verify(dataclasses.replace(good, proof=bad_nonce), PARAMS)

    bad_pow = dataclasses.replace(proof, pow_nonce=proof.pow_nonce + 1)
    assert not verifier.verify(dataclasses.replace(good, proof=bad_pow), PARAMS)

    dup = dataclasses.replace(
        proof, indices=[proof.indices[0]] * PARAMS.k2)
    assert not verifier.verify(dataclasses.replace(good, proof=dup), PARAMS)

    short = dataclasses.replace(proof, indices=proof.indices[:PARAMS.k2 - 1])
    assert not verifier.verify(dataclasses.replace(good, proof=short), PARAMS)

    oob = dataclasses.replace(
        proof, indices=proof.indices[:-1] + [meta.total_labels])
    assert not verifier.verify(dataclasses.replace(good, proof=oob), PARAMS)

    wrong_commit = dataclasses.replace(good, commitment=hashlib.sha256(b"x").digest())
    assert not verifier.verify(wrong_commit, PARAMS)


def test_batch_verify_mixed(unit, proof):
    _, meta, _ = unit
    good = _item(meta, proof)
    bad = dataclasses.replace(
        good, proof=dataclasses.replace(proof, nonce=proof.nonce + 3))
    out = verifier.verify_many([good, bad, good], PARAMS)
    assert out == [True, False, True]


def test_proof_dict_roundtrip(proof):
    assert Proof.from_dict(proof.to_dict()) == proof
