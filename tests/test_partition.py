"""Partition healing: two islands diverge, merge, and reconverge.

VERDICT round-2 item 4 "done" criterion: a partition-healing test where
two hubs are merged and the network reconverges (reference
tortoise/full.go healing + syncer/find_fork.go; systest partition_test).

Deterministic asymmetry: node A holds 3/4 of the weight (3 identities),
node B 1/4. During the partition A keeps certifying blocks (15/20
committee seats >= threshold 11) while B's island produces empty layers
(5 seats). After the merge, B's fork finder detects the aggregated-hash
divergence, rolls back, and resyncs onto A's chain.
"""

import asyncio
import time

import pytest

from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.p2p.server import LoopbackNet
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import layers as layerstore

LPE = 8            # one long epoch: the whole scenario rides the
                   # bootstrap beacon, so islands cannot diverge on it
LAYER_SEC = 0.9
PARTITION_AT = 10  # B leaves before this layer ticks
MERGE_AT = 13      # B rejoins before this one
UNTIL = 14

GENESIS_PLACEHOLDER = float(int(time.time()) + 3600)


def _config(tmp_path, name, num_identities, num_units):
    return load("standalone", overrides={
        "data_dir": str(tmp_path / name),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": GENESIS_PLACEHOLDER},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": num_units,
                     "init_batch": 128, "num_identities": num_identities},
        "hare": {"committee_size": 20, "round_duration": 0.1,
                 "preround_delay": 0.3, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.1},
        "tortoise": {"hdist": 4, "zdist": 2, "window_size": 50},
    })


@pytest.fixture(scope="module")
def healed(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("partition")
    hub = LoopbackHub()
    net = LoopbackNet()

    def make(name, n_ids, units):
        cfg = _config(tmp, name, n_ids, units)
        signer = EdSigner(prefix=cfg.genesis.genesis_id)
        ps = PubSub(node_name=signer.node_id)
        hub.join(ps)
        app = App(cfg, signer=signer, pubsub=ps)
        app.connect_network(net)
        return app, ps

    a, ps_a = make("a", 3, 1)   # 3/4 of the weight
    b, ps_b = make("b", 1, 1)   # 1/4

    async def go():
        await asyncio.gather(a.prepare(), b.prepare())
        genesis = time.time() + 0.3
        for app in (a, b):
            app.clock = clock_mod.LayerClock(genesis, LAYER_SEC)
        task_a = asyncio.create_task(a.run(until_layer=UNTIL))
        task_b = asyncio.create_task(b.run(until_layer=UNTIL))

        # partition: B drops off the network before PARTITION_AT ticks
        await asyncio.sleep(max(genesis + LAYER_SEC * (PARTITION_AT - 1)
                                + 0.3 - time.time(), 0))
        hub.leave(ps_b)
        net.leave(b.server)

        # merge: B rejoins before MERGE_AT
        await asyncio.sleep(max(genesis + LAYER_SEC * (MERGE_AT - 1)
                                + 0.3 - time.time(), 0))
        hub.join(ps_b)
        net.join(b.server)

        await asyncio.gather(task_a, task_b)
        print("post-run A applied:", layerstore.last_applied(a.state),
              "B applied:", layerstore.last_applied(b.state))
        # healing: fork detection -> rollback -> resync, until B's chain
        # matches A's at the merge frontier (bounded; the loop absorbs
        # scheduling jitter under full-suite load)
        deadline = time.time() + 120
        while time.time() < deadline:
            ok = await b.syncer.synchronize()
            match = (layerstore.last_applied(b.state) >= MERGE_AT - 1
                     and layerstore.aggregated_hash(b.state, MERGE_AT - 1)
                     == layerstore.aggregated_hash(a.state, MERGE_AT - 1))
            print(f"heal: synced={ok} "
                  f"B applied={layerstore.last_applied(b.state)} "
                  f"match={match}")
            if match:
                break
            await asyncio.sleep(0.2)

    asyncio.run(asyncio.wait_for(go(), timeout=240))
    return a, b


def test_a_kept_certifying_through_partition(healed):
    a, b = healed
    partition_layers = [lyr for lyr in range(PARTITION_AT, MERGE_AT)
                        if blockstore.ids_in_layer(a.state, lyr)]
    assert partition_layers, \
        "A (majority island) should have produced blocks during partition"


def test_b_reconverges_after_merge(healed):
    """APPLIED blocks must agree per layer. (The raw block pool may hold
    extras — e.g. a block B's hare minted in the rejoin instant that
    healing then discarded — the pool is content-addressed and unapplied
    leftovers are harmless.)"""
    a, b = healed
    # assert through the merge frontier: the live tip keeps moving and is
    # inherently racy, but everything up to MERGE_AT-1 must agree
    top = min(layerstore.last_applied(a.state),
              layerstore.last_applied(b.state), MERGE_AT - 1)
    assert top >= MERGE_AT - 1
    for lyr in range(LPE, top + 1):
        applied_a = layerstore.applied_block(a.state, lyr)
        applied_b = layerstore.applied_block(b.state, lyr)
        assert applied_a == applied_b, \
            f"layer {lyr}: islands still diverged after healing"


def test_state_roots_match_after_healing(healed):
    a, b = healed
    top = min(layerstore.last_applied(a.state),
              layerstore.last_applied(b.state), MERGE_AT - 1)
    ra = layerstore.state_hash(a.state, top)
    rb = layerstore.state_hash(b.state, top)
    assert ra == rb, f"state divergence at layer {top} after healing"


def test_aggregated_hashes_match_after_healing(healed):
    a, b = healed
    top = min(layerstore.last_applied(a.state),
              layerstore.last_applied(b.state), MERGE_AT - 1)
    for lyr in range(PARTITION_AT - 1, top + 1):
        ha = layerstore.aggregated_hash(a.state, lyr)
        hb = layerstore.aggregated_hash(b.state, lyr)
        assert ha == hb, f"aggregated hash diverged at layer {lyr}"
