"""Partition healing: islands diverge, merge, and reconverge — on a
VIRTUAL clock, so the result is machine-load independent.

VERDICT round-2 item 1: the round-2 version of this test drove consensus
off the real wall clock (0.9 s layers, `time.time()` genesis) and failed
under load. The reference mandates injected fake clocks for exactly this
reason (timesync/clock_test.go's clockwork pattern; systest partition
scenarios in systest/tests/partition_test.go). Here every component reads
time from a VirtualClockLoop: logical ordering is exact, wall time is
whatever the hashing costs.

Scenario (reference tortoise/full.go healing + syncer/find_fork.go):
node A holds 3/4 of the weight (3 identities), node B 1/4. During the
partition A keeps certifying blocks (15/20 expected committee seats >=
threshold 11) while B's island produces empty layers. After the merge,
B's fork finder detects the aggregated-hash divergence, rolls back, and
resyncs onto A's chain.
"""

import asyncio
import hashlib
import pathlib

import pytest

from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.p2p.server import LoopbackNet
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.utils.vclock import VirtualClockLoop, cancel_all_tasks

LPE = 8            # one long epoch: the whole scenario rides the
                   # bootstrap beacon, so islands cannot diverge on it
LAYER_SEC = 2.0    # virtual seconds — generous; costs no wall time
PARTITION_AT = 10  # B leaves before this layer ticks
MERGE_AT = 13      # B rejoins before this one
UNTIL = 14


def _config(tmp_path, name, num_identities, num_units):
    return load("standalone", overrides={
        "data_dir": str(tmp_path / name),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": 0.0},  # replaced per-run with virtual time
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": num_units,
                     "init_batch": 128, "num_identities": num_identities},
        "hare": {"committee_size": 20, "round_duration": 0.2,
                 "preround_delay": 0.5, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.2},
        "tortoise": {"hdist": 4, "zdist": 2, "window_size": 50},
    })


def _mknode(tmp, hub, net, name, n_ids, units, time_source):
    cfg = _config(tmp, name, n_ids, units)
    # DETERMINISTIC identities: with the virtual clock fixing the
    # schedule and fixed keys fixing every VRF roll (eligibility,
    # leaders, coins), the whole scenario replays identically run to
    # run — the reference pins test identities the same way
    key_dir = pathlib.Path(cfg.data_dir) / "identities"
    key_dir.mkdir(parents=True, exist_ok=True)
    signers = []
    for i in range(n_ids):
        seed = hashlib.sha256(f"partition-{name}-{i}".encode()).digest()
        s = EdSigner(seed=seed, prefix=cfg.genesis.genesis_id)
        fname = "local.key" if i == 0 else f"local_{i:02d}.key"
        (key_dir / fname).write_text(s.private_bytes().hex())
        signers.append(s)
    signer = signers[0]
    ps = PubSub(node_name=signer.node_id)
    hub.join(ps)
    app = App(cfg, signer=signer, pubsub=ps, time_source=time_source)
    app.connect_network(net)
    return app, ps


async def _heal_until(apps, reference_app, target_layer, now,
                      deadline: float = 300.0):
    """Drive each app's syncer until its applied chain matches the
    reference app's aggregated hash at ``target_layer`` (virtual-time
    bounded)."""
    t0 = now()
    want = layerstore.aggregated_hash(reference_app.state, target_layer)
    while now() - t0 < deadline:
        done = True
        for app in apps:
            if app is reference_app:
                continue
            await app.syncer.synchronize()
            got = (layerstore.last_applied(app.state) >= target_layer
                   and layerstore.aggregated_hash(app.state, target_layer)
                   == want)
            done = done and got
        if done:
            return
        await asyncio.sleep(0.5)


@pytest.fixture(scope="module")
def healed(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("partition")
    loop = VirtualClockLoop()
    hub = LoopbackHub()
    net = LoopbackNet()

    a, ps_a = _mknode(tmp, hub, net, "a", 3, 1, loop.time)
    b, ps_b = _mknode(tmp, hub, net, "b", 1, 1, loop.time)

    async def go():
        await asyncio.gather(a.prepare(), b.prepare())
        genesis = loop.time() + 1.0
        for app in (a, b):
            app.clock = clock_mod.LayerClock(genesis, LAYER_SEC,
                                             time_source=loop.time)
        task_a = asyncio.create_task(a.run(until_layer=UNTIL))
        task_b = asyncio.create_task(b.run(until_layer=UNTIL))

        async def at_layer_start(lyr, margin=0.5):
            await asyncio.sleep(
                max(genesis + LAYER_SEC * (lyr - 1) + margin - loop.time(),
                    0))

        # partition: B drops off the network before PARTITION_AT ticks
        await at_layer_start(PARTITION_AT)
        hub.leave(ps_b)
        net.leave(b.server)

        # merge: B rejoins before MERGE_AT
        await at_layer_start(MERGE_AT)
        hub.join(ps_b)
        net.join(b.server)

        await asyncio.gather(task_a, task_b)
        # healing: fork detection -> rollback -> resync, until B's chain
        # matches A's at the merge frontier
        await _heal_until([b], a, MERGE_AT - 1, loop.time)

    try:
        loop.run_until_complete(asyncio.wait_for(go(), 10_000))
    finally:
        loop.run_until_complete(cancel_all_tasks())
    return a, b


def test_a_kept_certifying_through_partition(healed):
    a, b = healed
    partition_layers = [lyr for lyr in range(PARTITION_AT, MERGE_AT)
                        if blockstore.ids_in_layer(a.state, lyr)]
    assert partition_layers, \
        "A (majority island) should have produced blocks during partition"


def test_b_reconverges_after_merge(healed):
    """APPLIED blocks must agree per layer. (The raw block pool may hold
    extras — e.g. a block B's hare minted in the rejoin instant that
    healing then discarded — the pool is content-addressed and unapplied
    leftovers are harmless.)"""
    a, b = healed
    top = min(layerstore.last_applied(a.state),
              layerstore.last_applied(b.state), MERGE_AT - 1)
    assert top >= MERGE_AT - 1
    for lyr in range(LPE, top + 1):
        applied_a = layerstore.applied_block(a.state, lyr)
        applied_b = layerstore.applied_block(b.state, lyr)
        assert applied_a == applied_b, \
            f"layer {lyr}: islands still diverged after healing"


def test_state_roots_match_after_healing(healed):
    a, b = healed
    top = min(layerstore.last_applied(a.state),
              layerstore.last_applied(b.state), MERGE_AT - 1)
    ra = layerstore.state_hash(a.state, top)
    rb = layerstore.state_hash(b.state, top)
    assert ra == rb, f"state divergence at layer {top} after healing"


def test_aggregated_hashes_match_after_healing(healed):
    a, b = healed
    top = min(layerstore.last_applied(a.state),
              layerstore.last_applied(b.state), MERGE_AT - 1)
    for lyr in range(PARTITION_AT - 1, top + 1):
        ha = layerstore.aggregated_hash(a.state, lyr)
        hb = layerstore.aggregated_hash(b.state, lyr)
        assert ha == hb, f"aggregated hash diverged at layer {lyr}"


# --- asymmetric three-island case (VERDICT r2 item 1 "done" criterion) ---

@pytest.fixture(scope="module")
def healed3(tmp_path_factory):
    """Three islands: A (2 identities), B (1), C (1). The net partitions
    into {A}, {B}, {C} — NO island holds a certifying majority (committee
    threshold 11 > A's expected 10 seats), so every island coasts on
    empty/uncertified layers — then all three merge and must converge on
    one chain via tortoise + sync. The run continues well past the merge
    (UNTIL3) so layers orphaned at the merge instant leave the hdist
    window and tortoise healing (margins + weak coin) decides them
    (reference tortoise/full.go + tortoise.go:287-306)."""
    tmp = tmp_path_factory.mktemp("partition3")
    loop = VirtualClockLoop()
    hub = LoopbackHub()
    net = LoopbackNet()
    UNTIL3 = 20

    a, ps_a = _mknode(tmp, hub, net, "a", 2, 1, loop.time)
    b, ps_b = _mknode(tmp, hub, net, "b", 1, 1, loop.time)
    c, ps_c = _mknode(tmp, hub, net, "c", 1, 1, loop.time)
    apps = [a, b, c]
    pss = [ps_a, ps_b, ps_c]

    async def go():
        await asyncio.gather(*(x.prepare() for x in apps))
        genesis = loop.time() + 1.0
        for app in apps:
            app.clock = clock_mod.LayerClock(genesis, LAYER_SEC,
                                             time_source=loop.time)
        tasks = [asyncio.create_task(x.run(until_layer=UNTIL3))
                 for x in apps]

        async def at_layer_start(lyr, margin=0.5):
            await asyncio.sleep(
                max(genesis + LAYER_SEC * (lyr - 1) + margin - loop.time(),
                    0))

        await at_layer_start(PARTITION_AT)
        for ps, app in ((ps_b, b), (ps_c, c)):
            hub.leave(ps)
            net.leave(app.server)

        await at_layer_start(MERGE_AT)
        for ps, app in ((ps_b, b), (ps_c, c)):
            hub.join(ps)
            net.join(app.server)

        await asyncio.gather(*tasks)
        await _heal_until([b, c], a, MERGE_AT - 1, loop.time)

    try:
        loop.run_until_complete(asyncio.wait_for(go(), 10_000))
    finally:
        loop.run_until_complete(cancel_all_tasks())
    return apps


def test_three_islands_reconverge(healed3):
    a, b, c = healed3
    top = min(*(layerstore.last_applied(x.state) for x in healed3),
              MERGE_AT - 1)
    assert top >= MERGE_AT - 1
    for lyr in range(LPE, top + 1):
        blocks = {layerstore.applied_block(x.state, lyr) for x in healed3}
        assert len(blocks) == 1, \
            f"layer {lyr}: three islands still diverged after healing"
    roots = {layerstore.state_hash(x.state, top) for x in healed3}
    assert len(roots) == 1, "state divergence after 3-island healing"
