"""VM: spawn/spend/vault lifecycle, gas, determinism, revert.

The TPU-build analogue of reference genvm/vm_test.go.
"""

import pytest

from spacemesh_tpu.core import signing, types
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.storage import transactions as txstore
from spacemesh_tpu.vm import VM, TxValidity, sdk
from spacemesh_tpu.vm import templates as T
from spacemesh_tpu.vm.vm import BASE_REWARD


@pytest.fixture
def env():
    state = dbmod.open_state()
    verifier = signing.EdVerifier(prefix=b"testnet")
    vm = VM(state, verifier)
    alice = signing.EdSigner(prefix=b"testnet")
    bob = signing.EdSigner(prefix=b"testnet")
    a_addr = sdk.wallet_address(alice.public_key)
    b_addr = sdk.wallet_address(bob.public_key)
    vm.apply_genesis({a_addr.raw: 10**12, b_addr.raw: 10**9})
    return state, vm, alice, bob, a_addr, b_addr


def test_spawn_and_spend(env):
    state, vm, alice, bob, a_addr, b_addr = env
    blk = b"\x01" * 32
    txs = [sdk.spawn_wallet(alice),
           sdk.spend(a_addr, [alice], b_addr, 1000, nonce=1)]
    results, root = vm.apply(1, blk, txs, rewards=[])
    assert [r.status for r in results] == [0, 0]
    assert root != bytes(32)
    a = txstore.account(state, a_addr.raw)
    b = txstore.account(state, b_addr.raw)
    assert b["balance"] == 10**9 + 1000
    fees = sum(r.fee for r in results)
    assert a["balance"] == 10**12 - 1000 - fees
    assert a["next_nonce"] == 2


def test_unspawned_account_cannot_spend(env):
    state, vm, alice, bob, a_addr, b_addr = env
    results, _ = vm.apply(1, bytes(32),
                          [sdk.spend(a_addr, [alice], b_addr, 5, nonce=0)], [])
    assert results[0].status == int(TxValidity.NOT_SPAWNED)


def test_wrong_nonce_and_replay(env):
    state, vm, alice, bob, a_addr, b_addr = env
    spawn = sdk.spawn_wallet(alice)
    vm.apply(1, bytes(32), [spawn], [])
    # replaying the same spawn: nonce 0 already consumed
    results, _ = vm.apply(2, bytes(32), [spawn], [])
    assert results[0].status in (int(TxValidity.INVALID_NONCE),
                                 int(TxValidity.MALFORMED))
    tx = sdk.spend(a_addr, [alice], b_addr, 5, nonce=5)
    results, _ = vm.apply(3, bytes(32), [tx], [])
    assert results[0].status == int(TxValidity.INVALID_NONCE)


def test_bad_signature(env):
    state, vm, alice, bob, a_addr, b_addr = env
    vm.apply(1, bytes(32), [sdk.spawn_wallet(alice)], [])
    forged = sdk.spend(a_addr, [bob], b_addr, 5, nonce=1)  # bob signs alice's acct
    results, _ = vm.apply(2, bytes(32), [forged], [])
    assert results[0].status == int(TxValidity.BAD_SIGNATURE)


def test_overspend(env):
    state, vm, alice, bob, a_addr, b_addr = env
    vm.apply(1, bytes(32), [sdk.spawn_wallet(alice)], [])
    results, _ = vm.apply(2, bytes(32),
                          [sdk.spend(a_addr, [alice], b_addr, 10**15, nonce=1)], [])
    assert results[0].status == int(TxValidity.INSUFFICIENT_FUNDS)
    # fee was still charged, nonce still advanced (failed txs burn gas)
    a = txstore.account(state, a_addr.raw)
    assert a["next_nonce"] == 2
    assert a["balance"] < 10**12


def test_rewards_distribution(env):
    state, vm, alice, bob, a_addr, b_addr = env
    rewards = [types.Reward(atx_id=bytes(32), coinbase=a_addr.raw, weight=3),
               types.Reward(atx_id=bytes(32), coinbase=b_addr.raw, weight=1)]
    vm.apply(1, bytes(32), [], rewards)
    a = txstore.account(state, a_addr.raw)
    b = txstore.account(state, b_addr.raw)
    assert a["balance"] == 10**12 + BASE_REWARD * 3 // 4
    assert b["balance"] == 10**9 + BASE_REWARD // 4


def test_multisig_flow(env):
    state, vm, alice, bob, a_addr, b_addr = env
    carol = signing.EdSigner(prefix=b"testnet")
    keys = [alice, bob, carol]
    m_addr = sdk.multisig_address(2, [s.public_key for s in keys])
    vm.apply_genesis({m_addr.raw: 10**10})
    ok = sdk.spawn_multisig(2, keys)
    results, _ = vm.apply(1, bytes(32), [ok], [])
    assert results[0].status == 0
    # 1 signature is not enough for 2-of-3
    under = sdk.spend(m_addr, [alice], b_addr, 10, nonce=1)
    results, _ = vm.apply(2, bytes(32), [under], [])
    assert results[0].status == int(TxValidity.BAD_SIGNATURE)
    good = sdk.spend(m_addr, [alice, carol], b_addr, 10, nonce=1)
    results, _ = vm.apply(3, bytes(32), [good], [])
    assert results[0].status == 0


def test_vault_vesting_schedule(env):
    state, vm, alice, bob, a_addr, b_addr = env
    vm.apply(1, bytes(32), [sdk.spawn_wallet(alice)], [])
    args = T.VaultSpawnArgs(owner=a_addr.raw, total_amount=1000,
                            initial_unlock=100, vesting_start=10,
                            vesting_end=20)
    v_addr = sdk.vault_address(args)
    vm.apply_genesis({v_addr.raw: 1000})
    results, _ = vm.apply(2, bytes(32), [sdk.spawn_vault(args)], [])
    assert results[0].status == 0

    # before vesting start: nothing available
    r, _ = vm.apply(5, bytes(32), [sdk.drain_vault(
        a_addr, [alice], v_addr, b_addr, 1, nonce=1)], [])
    assert r[0].status == int(TxValidity.INSUFFICIENT_FUNDS)
    # mid-schedule: initial_unlock + half of the linear part
    r, _ = vm.apply(15, bytes(32), [sdk.drain_vault(
        a_addr, [alice], v_addr, b_addr, 550, nonce=2)], [])
    assert r[0].status == 0
    # but not more than vested
    r, _ = vm.apply(16, bytes(32), [sdk.drain_vault(
        a_addr, [alice], v_addr, b_addr, 300, nonce=3)], [])
    assert r[0].status == int(TxValidity.INSUFFICIENT_FUNDS)
    # non-owner cannot drain
    vm.apply(17, bytes(32), [sdk.spawn_wallet(bob, nonce=0)], [])
    r, _ = vm.apply(18, bytes(32), [sdk.drain_vault(
        b_addr, [bob], v_addr, b_addr, 10, nonce=1)], [])
    assert r[0].status == int(TxValidity.BAD_SIGNATURE)
    # after vesting end: the remainder drains
    r, _ = vm.apply(25, bytes(32), [sdk.drain_vault(
        a_addr, [alice], v_addr, b_addr, 450, nonce=4)], [])
    assert r[0].status == 0


def test_determinism_across_instances():
    def run():
        state = dbmod.open_state()
        verifier = signing.EdVerifier(prefix=b"d")
        vm = VM(state, verifier)
        alice = signing.EdSigner(seed=bytes(32), prefix=b"d")
        bob = signing.EdSigner(seed=bytes([1]) + bytes(31), prefix=b"d")
        a = sdk.wallet_address(alice.public_key)
        b = sdk.wallet_address(bob.public_key)
        vm.apply_genesis({a.raw: 10**9})
        _, root1 = vm.apply(1, bytes(32), [sdk.spawn_wallet(alice)], [])
        _, root2 = vm.apply(2, bytes(32),
                            [sdk.spend(a, [alice], b, 42, nonce=1)],
                            [types.Reward(atx_id=bytes(32), coinbase=b.raw, weight=1)])
        return root1, root2
    assert run() == run()


def test_revert(env):
    state, vm, alice, bob, a_addr, b_addr = env
    vm.apply(1, bytes(32), [sdk.spawn_wallet(alice)], [])
    layerstore.set_applied(state, 1, bytes(32), b"\x01" * 32)
    vm.apply(2, bytes(32), [sdk.spend(a_addr, [alice], b_addr, 7, nonce=1)], [])
    before = txstore.account(state, b_addr.raw)["balance"]
    vm.revert(1)
    after = txstore.account(state, b_addr.raw)["balance"]
    assert before == 10**9 + 7 and after == 10**9
    # nonce rolled back too: the spend can re-apply
    r, _ = vm.apply(2, bytes(32), [sdk.spend(a_addr, [alice], b_addr, 7, nonce=1)], [])
    assert r[0].status == 0
