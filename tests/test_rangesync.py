"""sync2 rangesync: XOR-Fenwick fingerprints + bisection reconciliation.

Reference sync2/rangesync/rangesync.go (recursive range reconciliation,
DefaultMaxSendRange=16) and fingerprint.go (XOR fingerprints).  The
efficiency test pins the point of the subsystem: a small symmetric
difference reconciles with far fewer keys on the wire than a full
exchange.
"""

import asyncio
import hashlib

from spacemesh_tpu.p2p.rangesync import (
    P_RANGESYNC,
    TOP,
    ZERO,
    OrderedSet,
    RangeSyncClient,
    RangeSyncResponder,
    XorFenwick,
    _xor,
)
from spacemesh_tpu.p2p.server import LoopbackNet, Server


def key(i: int) -> bytes:
    return hashlib.sha256(b"k%d" % i).digest()


def test_fenwick_matches_naive_xor():
    keys = sorted(key(i) for i in range(50))
    fen = XorFenwick(len(keys))
    for i, k in enumerate(keys):
        fen.update(i, k)
    for lo in (0, 7, 31):
        for hi in (lo, lo + 1, 42, 50):
            want = bytes(32)
            for k in keys[lo:hi]:
                want = _xor(want, k)
            assert _xor(fen.prefix(hi), fen.prefix(lo)) == want


def test_ordered_set_fingerprints_and_lazy_adds():
    s = OrderedSet(key(i) for i in range(10))
    fp_all, n = s.fingerprint()
    assert n == 10
    s.add(key(99))
    s.add(key(99))  # dupes collapse
    fp2, n2 = s.fingerprint()
    assert n2 == 11
    assert fp2 == _xor(fp_all, key(99))
    lo, hi = sorted([key(3), key(7)])
    fp_range, cnt = s.fingerprint(lo, hi)
    naive = bytes(32)
    c = 0
    for k in s.keys():
        if lo <= k < hi:
            naive = _xor(naive, k)
            c += 1
    assert (fp_range, cnt) == (naive, c)


def _pair(local_keys, remote_keys):
    net = LoopbackNet()
    a, b = Server(b"A" * 32), Server(b"B" * 32)
    net.join(a)
    net.join(b)
    remote = OrderedSet(remote_keys)
    b.register(P_RANGESYNC,
               RangeSyncResponder(lambda name: remote
                                  if name == "s" else None).handle)
    local = OrderedSet(local_keys)
    client = RangeSyncClient(a, b.node_id, "s")
    return local, client


def test_reconcile_finds_exactly_the_missing_keys():
    universe = [key(i) for i in range(400)]
    local_keys = universe[:390]          # missing 10 of theirs
    remote_keys = universe[5:]           # and they lack 5 of ours
    local, client = _pair(local_keys, remote_keys)

    async def go():
        missing = await client.reconcile(local)
        assert sorted(missing) == sorted(universe[390:])

    asyncio.run(go())


def test_equal_sets_need_one_roundtrip():
    keys = [key(i) for i in range(1000)]
    local, client = _pair(keys, keys)

    async def go():
        missing = await client.reconcile(local)
        assert missing == []
        assert client.roundtrips == 1  # root fingerprints matched

    asyncio.run(go())


def test_small_diff_beats_full_exchange():
    """1000-key sets differing in 8 keys: the keys that cross the wire
    are O(diff * max_send_range), nowhere near the 1000 a full exchange
    ships (the reference subsystem's reason to exist)."""
    universe = [key(i) for i in range(1008)]
    local_keys = universe[:1000]
    remote_keys = universe[:992] + universe[1000:]
    local, client = _pair(local_keys, remote_keys)

    async def go():
        transferred = 0
        orig_items = client._items

        async def counting_items(x, y):
            nonlocal transferred
            items = await orig_items(x, y)
            transferred += len(items)
            return items

        client._items = counting_items
        missing = await client.reconcile(local)
        assert sorted(missing) == sorted(universe[1000:])
        # every differing leaf range ships <= max_send_range keys and
        # there are 16 difference sites: worst case 256, full exchange
        # is 1000+
        assert transferred <= 16 * 16, f"{transferred} keys shipped"
        assert client.roundtrips < 120

    asyncio.run(go())


def test_empty_local_pulls_everything():
    keys = [key(i) for i in range(100)]
    local, client = _pair([], keys)

    async def go():
        missing = await client.reconcile(local)
        assert sorted(missing) == sorted(keys)

    asyncio.run(go())


def test_node_serves_epoch_atx_sets(tmp_path):
    """The App registers rs/1: a peer reconciles an epoch's ATX ids
    against a live node's state (the sync2 integration seam)."""
    from spacemesh_tpu.node.app import App
    from spacemesh_tpu.node.config import load

    cfg = load("standalone", overrides={
        "data_dir": str(tmp_path / "node"),
        "smeshing": {"start": False},
    })
    app = App(cfg)
    try:
        from spacemesh_tpu.core.types import (
            ActivationTx,
            MerkleProof,
            NIPost,
            Post,
            PostMetadataWire,
        )
        from spacemesh_tpu.core.signing import EdSigner
        from spacemesh_tpu.p2p.server import LoopbackNet
        from spacemesh_tpu.storage import atxs as atxstore

        nipost = NIPost(
            membership=MerkleProof(leaf_index=0, nodes=[]),
            post=Post(nonce=0, indices=[1], pow_nonce=0),
            post_metadata=PostMetadataWire(challenge=bytes(32),
                                           labels_per_unit=256))
        ids = []
        for i in range(5):
            s = EdSigner(prefix=cfg.genesis.genesis_id)
            atx = ActivationTx(
                publish_epoch=2, prev_atx=bytes(32), pos_atx=bytes(32),
                commitment_atx=None, initial_post=None, nipost=nipost,
                num_units=1, vrf_nonce=0, vrf_public_key=s.node_id,
                coinbase=bytes(24), node_id=s.node_id,
                signature=bytes(64))
            atxstore.add(app.state, atx, tick_height=1)
            ids.append(atx.id)

        net = LoopbackNet()
        app.connect_network(net)
        peer = Server(b"P" * 32)
        net.join(peer)

        async def go():
            client = RangeSyncClient(peer, app.server.node_id, "atx/2")
            missing = await client.reconcile(OrderedSet())
            assert sorted(missing) == sorted(ids)
            # unknown set name answers empty, reconcile degrades safely
            c2 = RangeSyncClient(peer, app.server.node_id, "nope")
            try:
                await c2.reconcile(OrderedSet())
            except ValueError:
                pass  # malformed/empty answer surfaces as an error, not a hang

        asyncio.run(go())
    finally:
        app.close()
