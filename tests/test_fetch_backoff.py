"""Fetch retry hardening: capped exponential backoff + jitter between
retry rounds, per-peer penalty windows after transport-level chunk
failures, and the definitive-miss fast path (ISSUE 8 satellite —
previously a failed chunk retried the whole id set elsewhere
immediately, hammering a flapping peer set)."""

import asyncio
import random

import pytest

from spacemesh_tpu.core.hashing import sum256
from spacemesh_tpu.p2p import fetch as fetch_mod
from spacemesh_tpu.p2p.fetch import Fetch, HashRequest, HashResponse
from spacemesh_tpu.p2p.server import LoopbackNet, RequestError, Server

A, B, C = (b"A" * 32), (b"B" * 32), (b"C" * 32)


class FlakyServer(Server):
    """Serves hs/1 from a blob dict, failing the first ``fail_first``
    requests with a transport error; counts every request."""

    def __init__(self, node_id, blobs=None, fail_first=0):
        super().__init__(node_id)
        self.blobs = dict(blobs or {})
        self.fail_first = fail_first
        self.requests = 0

        async def serve(peer, data):
            self.requests += 1
            if self.requests <= self.fail_first:
                raise RequestError("flap")
            req = HashRequest.from_bytes(data)
            return HashResponse(
                blobs=[self.blobs.get(h, b"") for h in req.hashes]
            ).to_bytes()

        self.register(fetch_mod.P_HASH, serve)


def _fetch(server, **kw):
    kw.setdefault("backoff_base", 0.01)
    kw.setdefault("backoff_cap", 0.05)
    kw.setdefault("penalty_base", 0.05)
    kw.setdefault("rng", random.Random(1))
    return Fetch(server, **kw)


def _ids(blobs):
    return {sum256(b): b for b in blobs}


def test_transient_failure_retries_with_backoff_and_succeeds():
    """A peer that flaps once serves the chunk on the retry round —
    within ONE get_hashes call, after a backoff."""
    blobs = _ids([b"one", b"two"])
    net = LoopbackNet()
    me = Server(A)
    net.join(me)
    peer = FlakyServer(B, blobs, fail_first=1)
    net.join(peer)
    f = _fetch(me)

    async def go():
        return await f.get_hashes(99, list(blobs))

    result = asyncio.run(go())
    assert all(result.values()), result
    assert peer.requests >= 2, "no retry round happened"


def test_definitive_miss_does_not_retry():
    """Peers that ANSWER (empty blob = don't have it) are definitive:
    no extra retry rounds, no backoff sleeps."""
    net = LoopbackNet()
    me = Server(A)
    net.join(me)
    peer = FlakyServer(B, {})          # healthy but empty
    net.join(peer)
    f = _fetch(me, retry_rounds=5)

    async def go():
        return await f.get_hashes(99, [sum256(b"nope")])

    result = asyncio.run(go())
    assert result == {sum256(b"nope"): False}
    assert peer.requests == 1, \
        f"definitive miss must not re-poll the peer ({peer.requests})"


def test_penalty_window_skips_flapping_peer_and_expires():
    net = LoopbackNet()
    me = Server(A)
    net.join(me)
    net.join(FlakyServer(B))
    net.join(FlakyServer(C))
    f = _fetch(me, penalty_base=0.5, penalty_cap=30.0)

    async def go():
        f._chunk_failure(B)
        assert f.penalized(B)
        assert f.peers() == [C], "penalized peer selected"
        # escalation: consecutive failures double the window
        w1 = f._penalty_until[B] - f._now()
        f._chunk_failure(B)
        w2 = f._penalty_until[B] - f._now()
        assert w2 > w1 * 1.5
        # success clears both the penalty and the escalation state
        f.report_success(B)
        assert not f.penalized(B) and B in f.peers()
        # everyone penalized -> fall back rather than stall sync
        f._chunk_failure(B)
        f._chunk_failure(C)
        assert set(f.peers()) == {B, C}

    asyncio.run(go())


def test_penalty_window_expires_on_the_loop_clock():
    net = LoopbackNet()
    me = Server(A)
    net.join(me)
    net.join(FlakyServer(B))
    net.join(FlakyServer(C))
    f = _fetch(me, penalty_base=0.05)

    async def go():
        f._chunk_failure(B)
        assert f.peers() == [C]
        await asyncio.sleep(0.1)
        assert set(f.peers()) == {B, C}, "window did not expire"

    asyncio.run(go())


def test_backoff_is_capped_and_jittered():
    net = LoopbackNet()
    me = Server(A)
    net.join(me)
    f = _fetch(me, backoff_base=0.01, backoff_cap=0.02,
               rng=random.Random(7))
    delays = []

    async def go():
        loop = asyncio.get_running_loop()
        for rnd in (0, 1, 5, 9):
            t0 = loop.time()
            await f._backoff(rnd)
            delays.append(loop.time() - t0)

    asyncio.run(go())
    assert all(d <= 0.02 * 1.1 + 0.02 for d in delays), delays  # capped
    assert delays[0] < 0.02, "jitter floor"


def test_bad_blob_still_penalizes_score_not_window():
    """A VALIDATOR reject (bad content from a responsive peer) keeps
    the heavier score penalty but is not a transport flap — the peer
    stays selectable for other hints."""
    blob = b"payload"
    wrong_id = sum256(b"something-else")
    net = LoopbackNet()
    me = Server(A)
    net.join(me)
    peer = FlakyServer(B, {wrong_id: blob})
    net.join(peer)
    f = _fetch(me, retry_rounds=2)

    async def never_ok(h, b):
        return False

    f.set_validator(99, never_ok)

    async def go():
        return await f.get_hashes(99, [wrong_id])

    result = asyncio.run(go())
    assert result == {wrong_id: False}
    assert f.failure_score(B) >= 3
    assert not f.penalized(B)
    assert peer.requests == 1, "validator reject is definitive too"
