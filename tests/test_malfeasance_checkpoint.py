"""Malfeasance proofs + checkpoint generate/recover."""

import asyncio
import dataclasses

import pytest

from spacemesh_tpu.consensus import malfeasance
from spacemesh_tpu.core import types
from spacemesh_tpu.core.signing import Domain, EdSigner, EdVerifier
from spacemesh_tpu.node import checkpoint
from spacemesh_tpu.p2p.pubsub import PubSub
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.storage import misc as miscstore
from spacemesh_tpu.storage import transactions as txstore
from spacemesh_tpu.storage.cache import AtxCache

PREFIX = b"mal-test"


def _signed_ballot(signer, layer, salt=0):
    b = types.Ballot(
        layer=layer, atx_id=bytes([salt]) * 32, epoch_data=None,
        ref_ballot=bytes(32), eligibilities=[],
        opinion=types.Opinion(base=bytes(32), support=[], against=[],
                              abstain=[]),
        node_id=signer.node_id, signature=bytes(64))
    return dataclasses.replace(
        b, signature=signer.sign(Domain.BALLOT, b.signed_bytes()))


@pytest.fixture
def env():
    db = dbmod.open_state()
    cache = AtxCache()
    verifier = EdVerifier(prefix=PREFIX)
    pubsub = PubSub()
    handler = malfeasance.Handler(db=db, cache=cache, verifier=verifier,
                                  pubsub=pubsub)
    return db, cache, handler


def test_double_ballot_proof(env):
    db, cache, handler = env
    s = EdSigner(prefix=PREFIX)
    b1 = _signed_ballot(s, 5, salt=1)
    b2 = _signed_ballot(s, 5, salt=2)
    proof = malfeasance.proof_from_ballots(b1, b2)
    assert handler.validate(proof)
    assert handler.process(proof)
    assert miscstore.is_malicious(db, s.node_id)
    assert cache.is_malicious(s.node_id)
    # idempotent
    assert handler.process(proof)


def test_invalid_proofs_rejected(env):
    db, cache, handler = env
    s = EdSigner(prefix=PREFIX)
    other = EdSigner(prefix=PREFIX)
    b1 = _signed_ballot(s, 5, salt=1)
    b2 = _signed_ballot(s, 6, salt=2)      # different layer: no conflict
    assert not handler.validate(malfeasance.proof_from_ballots(b1, b2))
    # same message twice
    p = malfeasance.proof_from_ballots(b1, b1)
    assert not handler.validate(p)
    # forged signature
    b3 = _signed_ballot(other, 5, salt=3)
    forged = malfeasance.MalfeasanceProof(
        domain=int(Domain.BALLOT), msg1=b1.signed_bytes(), sig1=b1.signature,
        msg2=b3.signed_bytes(), sig2=b3.signature, node_id=s.node_id)
    assert not handler.validate(forged)
    assert not miscstore.is_malicious(db, s.node_id)


def test_gossip_roundtrip(env):
    db, cache, handler = env
    s = EdSigner(prefix=PREFIX)
    proof = malfeasance.proof_from_ballots(
        _signed_ballot(s, 9, salt=1), _signed_ballot(s, 9, salt=2))

    async def run():
        assert await handler._gossip(b"peer", proof.to_bytes())
        assert not await handler._gossip(b"peer", b"garbage")
    asyncio.run(run())
    assert miscstore.is_malicious(db, s.node_id)


def _atx(node, epoch):
    return types.ActivationTx(
        publish_epoch=epoch, prev_atx=bytes(32), pos_atx=bytes(32),
        commitment_atx=None, initial_post=None,
        nipost=types.NIPost(
            membership=types.MerkleProof(leaf_index=0, nodes=[]),
            post=types.Post(nonce=0, indices=[1], pow_nonce=0),
            post_metadata=types.PostMetadataWire(challenge=bytes(32),
                                                 labels_per_unit=64)),
        num_units=2, vrf_nonce=1, vrf_public_key=bytes(32),
        coinbase=bytes(24), node_id=node, signature=bytes(64))


def test_checkpoint_roundtrip(tmp_path):
    db = dbmod.open_state()
    txstore.update_account(db, b"\x01" * 24, 5, 1000, 2, None, None)
    txstore.update_account(db, b"\x02" * 24, 7, 500, 0, None, None)
    a1 = _atx(b"\x0a" * 32, 1)
    atxstore.add(db, a1, tick_height=64)
    miscstore.set_beacon(db, 2, b"\xaa\xbb\xcc\xdd")
    layerstore.set_applied(db, 7, bytes(32), b"\x07" * 32)

    path = tmp_path / "checkpoint.json"
    snap = checkpoint.write(db, path)
    assert snap["layer"] == 7 and len(snap["accounts"]) == 2

    # restore into a fresh DB
    db2 = dbmod.open_state()
    # own ATX in db2 that must survive recovery
    own = _atx(b"\x0b" * 32, 2)
    atxstore.add(db2, own, tick_height=10)
    checkpoint.recover_file(db2, path, preserve_node_id=b"\x0b" * 32)

    assert txstore.account(db2, b"\x01" * 24)["balance"] == 1000
    assert atxstore.get(db2, a1.id) == a1
    assert atxstore.tick_height(db2, a1.id) == 64
    assert atxstore.get(db2, own.id) == own, "own ATX lineage lost"
    assert miscstore.get_beacon(db2, 2) == b"\xaa\xbb\xcc\xdd"
    assert layerstore.last_applied(db2) == 7
    assert layerstore.state_hash(db2, 7) == b"\x07" * 32


def test_checkpoint_version_check():
    db = dbmod.open_state()
    with pytest.raises(ValueError, match="version"):
        checkpoint.recover(db, {"version": 99})
