"""Hare under attack: equivocators, forged counts, fake notifies, late
messages. Round-2 VERDICT item 5 — agreement must hold with f malicious
seats (reference hare3/protocol.go gradecast + certificates).
"""

import asyncio

import pytest

from spacemesh_tpu.consensus.eligibility import Oracle
from spacemesh_tpu.consensus.hare import (
    COMMIT,
    NOTIFY,
    PREROUND,
    Hare,
    HareMessage,
)
from spacemesh_tpu.core.hashing import sum256
from spacemesh_tpu.core.signing import Domain, EdSigner, EdVerifier
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo

GEN = b"hare-advers-genesis!"
LPE = 4
LAYER = 5
EPOCH = LAYER // LPE
BEACON = b"\x42\x42\x42\x42"
COMMITTEE = 40


def _cache_with(signers, weight=100):
    cache = AtxCache()
    atx_ids = {}
    for i, s in enumerate(signers):
        atx_id = b"HATX%04d" % i + bytes(24)
        atx_ids[s.node_id] = atx_id
        cache.add(EPOCH, atx_id, AtxInfo(
            node_id=s.node_id, weight=weight, base_height=0, height=1,
            num_units=1, vrf_nonce=0, vrf_public_key=s.node_id))
    return cache, atx_ids


def _mk_hare(hub, cache, atx_ids, signer, outputs, equivs=None,
             proposals=()):
    ps = PubSub(node_name=signer.node_id)
    hub.join(ps)

    async def on_output(out):
        outputs.append(out)

    hare = Hare(
        signers=[signer], verifier=EdVerifier(prefix=GEN),
        oracle=Oracle(cache, LPE), pubsub=ps, committee_size=COMMITTEE,
        round_duration=0.15, iteration_limit=2, preround_delay=0.15,
        layers_per_epoch=LPE,
        beacon_of=lambda epoch: _async(BEACON),
        atx_for=lambda epoch, node_id: atx_ids.get(node_id),
        proposals_for=lambda layer: list(proposals),
        on_output=on_output,
        on_equivocation=(equivs.append if equivs is not None else None))
    return hare, ps


async def _async(v):
    return v


def _sign_msg(signer, oracle, atx_id, *, round_, values, iteration=0,
              count=None, cert=()):
    """A fully valid message from an eligible identity (or with a forged
    count when ``count`` is given)."""
    tag = iteration * 4 + round_
    el = oracle.hare_eligibility(signer.vrf_signer(), BEACON, LAYER, tag,
                                 EPOCH, atx_id, COMMITTEE)
    proof, real_count = el if el else (bytes(80), 0)
    msg = HareMessage(
        layer=LAYER, iteration=iteration, round=round_,
        values=sorted(values), eligibility_proof=proof,
        eligibility_count=count if count is not None else real_count,
        atx_id=atx_id, node_id=signer.node_id, cert_msgs=list(cert),
        signature=bytes(64))
    msg.signature = signer.sign(Domain.HARE, msg.signed_bytes())
    return msg


def test_agreement_despite_equivocator():
    """One committee member equivocates in PREROUND/COMMIT; honest nodes
    still output ONE value set, and the equivocation is reported."""
    signers = [EdSigner(prefix=GEN) for _ in range(4)]
    evil = signers[3]
    cache, atx_ids = _cache_with(signers)
    hub = LoopbackHub()
    val = sum256(b"the proposal")

    async def go():
        outs, equivs = [], []
        hares = [_mk_hare(hub, cache, atx_ids, s, outs, equivs,
                          proposals=[val])[0]
                 for s in signers[:3]]
        evil_ps = PubSub(node_name=evil.node_id)
        hub.join(evil_ps)
        oracle = Oracle(cache, LPE)

        async def adversary():
            # two conflicting PREROUNDs, then two conflicting COMMITs
            for vals in ([val], [sum256(b"other")]):
                m = _sign_msg(evil, oracle, atx_ids[evil.node_id],
                              round_=PREROUND, values=vals)
                await evil_ps.publish("b1", m.to_bytes())
            await asyncio.sleep(0.35)
            for vals in ([val], [sum256(b"sneaky")]):
                m = _sign_msg(evil, oracle, atx_ids[evil.node_id],
                              round_=COMMIT, values=vals)
                await evil_ps.publish("b1", m.to_bytes())

        results = await asyncio.gather(
            *(h.run_layer(LAYER) for h in hares), adversary())
        outputs = [tuple(r.proposals) for r in results[:3]]
        assert len(set(outputs)) == 1, f"honest nodes disagree: {outputs}"
        assert outputs[0], "agreement must be non-empty"
        assert equivs, "equivocation went unreported"
        assert equivs[0].node_id == evil.node_id

    asyncio.run(asyncio.wait_for(go(), 30))


def test_forged_eligibility_count_rejected():
    signers = [EdSigner(prefix=GEN) for _ in range(2)]
    cache, atx_ids = _cache_with(signers)
    hub = LoopbackHub()
    outs = []
    hare, ps = _mk_hare(hub, cache, atx_ids, signers[0], outs)
    oracle = Oracle(cache, LPE)
    forged = _sign_msg(signers[1], oracle, atx_ids[signers[1].node_id],
                       round_=PREROUND, values=[sum256(b"x")],
                       count=COMMITTEE)  # claims the whole committee

    async def go():
        ok = await hare._gossip(b"peer", forged.to_bytes())
        assert not ok

    asyncio.run(go())


def test_notify_without_certificate_rejected():
    """A NOTIFY claiming agreement must carry a provable commit
    certificate — an eligible-but-lying node cannot fake consensus."""
    signers = [EdSigner(prefix=GEN) for _ in range(2)]
    cache, atx_ids = _cache_with(signers)
    hub = LoopbackHub()
    outs = []
    hare, ps = _mk_hare(hub, cache, atx_ids, signers[0], outs)
    oracle = Oracle(cache, LPE)

    bare = _sign_msg(signers[1], oracle, atx_ids[signers[1].node_id],
                     round_=NOTIFY, values=[sum256(b"fake-agreement")])

    async def go():
        assert not await hare._gossip(b"peer", bare.to_bytes())
        # with a real certificate from enough weight it IS accepted
        commits = [
            _sign_msg(s, oracle, atx_ids[s.node_id], round_=COMMIT,
                      values=[sum256(b"real")]).to_bytes()
            for s in signers]
        certified = _sign_msg(
            signers[1], oracle, atx_ids[signers[1].node_id],
            round_=NOTIFY, values=[sum256(b"real")], cert=commits)
        assert await hare._gossip(b"peer", certified.to_bytes())

    asyncio.run(go())


def test_late_commit_gets_no_grade():
    """A COMMIT that surfaces rounds after its own slot is graded below
    the thresholds the protocol reads (grade5 for notify emission,
    grade4/3 for locks) — the graded replacement for the old acceptance
    window (reference hare3 thresh-gossip: received.Grade(target) gates
    every tally)."""
    signers = [EdSigner(prefix=GEN) for _ in range(2)]
    cache, atx_ids = _cache_with(signers)
    hub = LoopbackHub()
    outs = []
    hare, ps = _mk_hare(hub, cache, atx_ids, signers[0], outs)
    oracle = Oracle(cache, LPE)

    from spacemesh_tpu.consensus import hare3
    from spacemesh_tpu.consensus.hare import HareSession

    msg = _sign_msg(signers[1], oracle, atx_ids[signers[1].node_id],
                    round_=COMMIT, values=[sum256(b"v")])
    target = hare3.IterRound(0, hare3.COMMIT)

    # session whose protocol clock is 5 rounds past the commit round:
    # the message lands with grade < 3 — invisible to every lock read
    # (softlock needs grade3, hardlock grade4, notify emission grade5)
    session = HareSession(hare, LAYER, [])
    for _ in range(11):  # preround..(1,wait1): 5 past commit
        session.protocol.next()
    session.on_message(msg)
    gi = session.protocol.gossip.state[(target, signers[1].node_id)]
    assert gi.received.grade(target) < hare3.GRADE3
    # commit_weight (certificate bookkeeping) still records it — certs
    # have their own threshold check
    assert session.commit_weight(tuple(sorted(msg.values))) > 0

    # fresh session: same message in its own round carries full grade
    session2 = HareSession(hare, LAYER, [])
    session2.on_message(msg)
    gi2 = session2.protocol.gossip.state[(target, signers[1].node_id)]
    assert gi2.received.grade(target) >= hare3.GRADE5
