"""Out-of-process POST worker: transport, supervisor restart, proofs.

The reference runs proving in a separate babysat process speaking gRPC
(reference activation/post_supervisor.go, api/grpcserver/post_service.go);
here the worker is `python -m spacemesh_tpu.post serve` and the node dials
it with RemotePostClient. End-to-end: init tiny POST data on disk, serve
it from a REAL subprocess, prove + verify through the wire, kill the
worker and watch the supervisor restart it.
"""

import hashlib

import pytest

from spacemesh_tpu.post import initializer, verifier
from spacemesh_tpu.post.prover import ProofParams
from spacemesh_tpu.post.remote import RemotePostClient
from spacemesh_tpu.post.supervisor import PostSupervisor

NODE_ID = hashlib.sha256(b"worker-test-node").digest()
COMMITMENT = hashlib.sha256(b"worker-test-commitment").digest()
PARAMS = ProofParams(k1=64, k2=8, k3=4,
                     pow_difficulty=b"\x20" + b"\xff" * 31)


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    base = tmp_path_factory.mktemp("postworker")
    d = base / NODE_ID.hex()[:16]
    initializer.initialize(
        d, node_id=NODE_ID, commitment=COMMITMENT, num_units=1,
        labels_per_unit=256, scrypt_n=2, batch_size=128)
    return base


@pytest.fixture(scope="module")
def supervisor(data_dir):
    sup = PostSupervisor(data_dir, listen="127.0.0.1:0", params=PARAMS,
                         restart_backoff=0.2)
    sup.start(timeout=120)
    yield sup
    sup.stop()


def test_info_over_the_wire(supervisor):
    client = RemotePostClient(supervisor.address, NODE_ID)
    info = client.info()
    assert info.node_id == NODE_ID
    assert info.commitment == COMMITMENT
    assert info.num_units == 1
    assert info.labels_per_unit == 256
    assert client.ping() == [NODE_ID]


def test_proof_over_the_wire_verifies(supervisor):
    client = RemotePostClient(supervisor.address, NODE_ID, timeout=300)
    challenge = hashlib.sha256(b"worker-challenge").digest()
    proof, meta = client.proof(challenge)
    assert len(proof.indices) == PARAMS.k2
    ok = verifier.verify(verifier.VerifyItem(
        proof=proof, challenge=challenge, node_id=NODE_ID,
        commitment=COMMITMENT, scrypt_n=2, total_labels=256), PARAMS)
    assert ok, "remote proof failed local verification"


def test_unknown_identity_is_an_error(supervisor):
    client = RemotePostClient(supervisor.address, b"\x42" * 32)
    with pytest.raises(RuntimeError, match="not registered"):
        client.info()


def test_supervisor_restarts_killed_worker(supervisor):
    assert supervisor.alive()
    before = supervisor.restarts
    supervisor._proc.kill()
    client = RemotePostClient(supervisor.address, NODE_ID, timeout=10)

    import time
    deadline = time.time() + 60
    while time.time() < deadline:
        try:
            if client.ping() == [NODE_ID]:
                break
        except (OSError, RuntimeError):
            time.sleep(0.3)
    else:
        raise AssertionError("worker did not come back after kill")
    assert supervisor.restarts > before
