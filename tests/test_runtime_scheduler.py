"""Multi-tenant scheduler coverage (runtime/scheduler.py, ISSUE 11).

Scheduler units — fair share under starvation pressure, deadline (EDF)
admission, quota enforcement, gang-scheduled prove windows, cancel and
close semantics — plus the packed-init bit-identity suite (scheduled
multi-tenant output == solo Initializer output, per tenant, at ragged
totals) and the multi-tenant e2e asserting per-tenant spans and
metrics.
"""

import hashlib
import threading
import time

import numpy as np
import pytest

from spacemesh_tpu.runtime import TenantScheduler
from spacemesh_tpu.runtime.scheduler import QuotaExceeded, SchedulerClosed
from spacemesh_tpu.utils import metrics, tracing

N = 2
PACK = 256


def _ids(count, salt=b""):
    return [(f"t{i}", hashlib.sha256(b"node-%d" % i + salt).digest(),
             hashlib.sha256(b"commit-%d" % i + salt).digest())
            for i in range(count)]


def _reference(commit, total):
    from spacemesh_tpu.ops import scrypt

    return scrypt.scrypt_labels(
        commit, np.arange(total, dtype=np.uint64), n=N).tobytes()


def _host_vrf_nonce(label_bytes):
    halves = np.frombuffer(label_bytes, dtype="<u8").reshape(-1, 2)
    return int(np.lexsort((np.arange(halves.shape[0]),
                           halves[:, 0], halves[:, 1]))[0])


# --- packed init bit-identity -----------------------------------------


def test_packed_init_matches_solo_initializer(tmp_path):
    """4 tenants at ragged totals (1, 7, 300, 1000): the scheduled
    packed path must write byte-identical labels and the identical VRF
    nonce the solo device-scan Initializer persists."""
    from spacemesh_tpu.post.data import LabelStore

    totals = [1, 7, 300, 1000]
    ids = _ids(4)
    with TenantScheduler(workers=1, pack_lanes=PACK) as sched:
        handles = []
        for (tid, node, commit), total in zip(ids, totals):
            sched.register_tenant(tid)
            handles.append((tid, commit, total, sched.submit_init(
                tid, tmp_path / tid, node_id=node, commitment=commit,
                num_units=1, labels_per_unit=total, scrypt_n=N,
                max_file_size=1 << 20)))
        for tid, commit, total, h in handles:
            meta = h.result(timeout=300)
            store = LabelStore(tmp_path / tid, meta)
            got = store.read_labels(0, total)
            store.close()
            ref = _reference(commit, total)
            assert got == ref, f"{tid}: packed labels diverge"
            assert meta.vrf_nonce == _host_vrf_nonce(ref)
            assert meta.labels_written == total


def test_packed_init_resume(tmp_path):
    """A partially-initialized directory resumes through the scheduler:
    only the remaining labels are computed and the final state matches
    a from-scratch run (labels deterministic, min-merge idempotent)."""
    from spacemesh_tpu.post import initializer
    from spacemesh_tpu.post.data import LabelStore

    tid, node, commit = _ids(1, salt=b"resume")[0]
    d = tmp_path / "resume"
    # first half via the solo path, stopped early
    init = initializer.Initializer(
        d, initializer.open_or_create_meta(
            d, node_id=node, commitment=commit, num_units=1,
            labels_per_unit=500, scrypt_n=N, max_file_size=1 << 20),
        batch_size=128, mesh=None)
    init.progress = lambda done, total: done >= 256 and init.stop()
    init.run()
    resumed_at = init.meta.labels_written
    assert 0 < resumed_at < 500
    with TenantScheduler(workers=1, pack_lanes=PACK) as sched:
        sched.register_tenant(tid)
        h = sched.submit_init(tid, d, node_id=node, commitment=commit,
                              num_units=1, labels_per_unit=500,
                              scrypt_n=N, max_file_size=1 << 20)
        meta = h.result(timeout=300)
    store = LabelStore(d, meta)
    got = store.read_labels(0, 500)
    store.close()
    ref = _reference(commit, 500)
    assert got == ref
    assert meta.vrf_nonce == _host_vrf_nonce(ref)


# --- scheduler units ---------------------------------------------------


def test_fair_share_under_starvation_pressure():
    """A tenant flooding 24 jobs cannot starve a 3-job tenant: with
    equal weights the light tenant's jobs complete interleaved near the
    front, not after the flood."""
    order = []
    with TenantScheduler(workers=1, autostart=False) as sched:
        sched.register_tenant("flood")
        sched.register_tenant("light")
        handles = []
        for i in range(24):
            handles.append(sched.submit_call(
                "flood", lambda i=i: (time.sleep(0.002),
                                      order.append(("flood", i)))[1]))
        for i in range(3):
            handles.append(sched.submit_call(
                "light", lambda i=i: (time.sleep(0.002),
                                      order.append(("light", i)))[1]))
        sched.start()
        for h in handles:
            h.result(timeout=60)
    light_done = [k for k, (t, _) in enumerate(order) if t == "light"]
    # stride scheduling alternates; all three light jobs land within
    # the first ~8 completions even against the 24-deep flood
    assert max(light_done) < 10, order


def test_deadline_job_jumps_fair_share_order():
    order = []
    gate = threading.Event()
    with TenantScheduler(workers=1, autostart=False) as sched:
        sched.register_tenant("a")
        sched.register_tenant("b")
        hs = [sched.submit_call("a", lambda: gate.wait(10))]
        hs += [sched.submit_call("a", lambda i=i: order.append(("a", i)))
               for i in range(3)]
        # b's job is already overdue: it must run BEFORE a's queued
        # backlog even though a is the only tenant the fair-share pick
        # has history for
        hs.append(sched.submit_call("b", lambda: order.append(("b", 0)),
                                    deadline_s=0.0))
        boosts = sum(metrics.runtime_deadline_boosts.sample().values())
        sched.start()
        gate.set()
        for h in hs:
            h.result(timeout=60)
    assert order[0] == ("b", 0), order
    assert sum(metrics.runtime_deadline_boosts.sample().values()) > boosts


def test_quota_max_queued_rejects():
    with TenantScheduler(workers=1, autostart=False) as sched:
        sched.register_tenant("q", max_queued=2)
        gate = threading.Event()
        h1 = sched.submit_call("q", lambda: gate.wait(10))
        h2 = sched.submit_call("q", lambda: None)
        with pytest.raises(QuotaExceeded):
            sched.submit_call("q", lambda: None)
        sched.start()
        gate.set()
        h1.result(timeout=30)
        h2.result(timeout=30)
        # slots freed: admission works again
        sched.submit_call("q", lambda: True).result(timeout=30)


def test_quota_max_inflight_caps_concurrency():
    peak = [0]
    live = [0]
    lock = threading.Lock()

    def job():
        with lock:
            live[0] += 1
            peak[0] = max(peak[0], live[0])
        time.sleep(0.01)
        with lock:
            live[0] -= 1

    with TenantScheduler(workers=4, autostart=False) as sched:
        sched.register_tenant("capped", max_inflight=1)
        hs = [sched.submit_call("capped", job) for _ in range(6)]
        sched.start()
        for h in hs:
            h.result(timeout=60)
    assert peak[0] == 1, f"max_inflight=1 tenant ran {peak[0]} quanta"


def test_gang_windows_serialize_prove_passes(tmp_path, monkeypatch):
    """gang_windows=1: two tenants' prove windows never overlap on the
    device even with two free workers (the window's donated carries own
    the device for the pass)."""
    from spacemesh_tpu.post import workload
    from spacemesh_tpu.post.prover import Prover

    dirs = []
    for i in range(2):
        d = str(tmp_path / f"store-{i}")
        workload.build(d, 512, 256)
        dirs.append(d)

    live = [0]
    peak = [0]
    lock = threading.Lock()
    orig = Prover._scan_window

    def traced(self, *a, **kw):
        with lock:
            live[0] += 1
            peak[0] = max(peak[0], live[0])
        try:
            return orig(self, *a, **kw)
        finally:
            with lock:
                live[0] -= 1

    monkeypatch.setattr(Prover, "_scan_window", traced)
    with TenantScheduler(workers=2, gang_windows=1) as sched:
        sched.register_tenant("p0")
        sched.register_tenant("p1")
        hs = [sched.submit_prove(f"p{i}", dirs[i], workload.CHALLENGE,
                                 workload.PARAMS, batch_labels=256)
              for i in range(2)]
        proofs = [h.result(timeout=600) for h in hs]
    assert peak[0] == 1, "two prove windows overlapped despite gang=1"
    for proof in proofs:
        assert workload.verify_proof(proof, 512)


def test_cancel_and_unregister_and_close(tmp_path):
    import concurrent.futures

    sched = TenantScheduler(workers=1, autostart=False)
    sched.register_tenant("x")
    gate = threading.Event()
    try:
        running = sched.submit_call("x", lambda: gate.wait(10))
        queued = sched.submit_call("x", lambda: None)
        assert queued.cancel()
        sched.start()
        with pytest.raises(concurrent.futures.CancelledError):
            queued.result(timeout=10)
        # unregister fails that tenant's still-queued jobs and drops
        # its per-tenant gauge series
        sched.register_tenant("gone")
        orphan = sched.submit_call("gone", lambda: None)
        sched.unregister_tenant("gone")
        with pytest.raises(SchedulerClosed):
            orphan.result(timeout=10)
        assert (("tenant", "gone"),) \
            not in metrics.runtime_tenant_queued.sample()
        gate.set()
        assert running.result(timeout=30) is True
        # close fails whatever is still queued; handles never strand
        stuck = sched.submit_call("x", lambda: gate.wait(10))
        blocked = sched.submit_call("x", lambda: None)
    finally:
        sched.close()
    with pytest.raises(SchedulerClosed):
        blocked.result(timeout=10)
    # the running-at-close job either finished or failed closed — but
    # its handle must be resolved either way
    assert stuck.done()


def test_unregister_with_lanes_in_flight_resolves_handle(tmp_path):
    """Unregistering a tenant whose init job still has packed lanes in
    flight (and more unpacked) must still resolve the handle — the
    in-flight segments finalize the errored job when they retire
    instead of stranding it in the jobs table forever."""
    from spacemesh_tpu.runtime import scheduler as sched_mod

    tid, node, commit = _ids(1, salt=b"strand")[0]
    sched = TenantScheduler(workers=1, pack_lanes=128, autostart=False)
    # slow the retire path down so lanes are reliably in flight when
    # the unregister lands
    orig = TenantScheduler._retire_pack

    def slow_retire(self, ticket):
        time.sleep(0.05)
        return orig(self, ticket)

    sched._retire_pack = slow_retire.__get__(sched)
    try:
        sched.register_tenant(tid)
        h = sched.submit_init(tid, tmp_path / "strand", node_id=node,
                              commitment=commit, num_units=1,
                              labels_per_unit=1000, scrypt_n=N,
                              max_file_size=1 << 20)
        sched.start()
        # wait until the packer actually has lanes outstanding
        job = sched._jobs[h.id]
        for _ in range(200):
            if job.outstanding > 0:
                break
            time.sleep(0.005)
        sched.unregister_tenant(tid)
        with pytest.raises((sched_mod.SchedulerClosed, Exception)):
            h.result(timeout=60)   # resolves (closed), never strands
        assert sched.drain(timeout=30)
    finally:
        sched.close()


def test_prove_session_parked_is_not_watched(tmp_path):
    """A session waiting between scheduler quanta (or in the pow gate)
    has no batch counter to advance: its liveness watchdog must be
    inactive while parked, active only inside a window scan — else
    every gang-queued tenant reads as a post.prove stall."""
    from spacemesh_tpu.post import workload

    prover = workload.build(str(tmp_path / "st"), 256, 256)
    session = prover.session(workload.CHALLENGE)
    try:
        assert not session._scanning       # parked: not watched
        assert session._wd.active() is False
        session.step()                     # pow gate quantum
        assert session._wd.active() is False  # still parked
        proof = None
        while proof is None:
            proof = session.step()
        assert session._wd.active() is False  # done: not watched
    finally:
        session.close()


# --- multi-tenant e2e: mixed load, per-tenant observability ------------


def test_multi_tenant_mixed_e2e(tmp_path):
    """4 tenants, mixed init+prove+verify+pow through one scheduler:
    every output bit-identical to its single-tenant twin, and the
    capture carries per-tenant spans + per-tenant metrics."""
    from spacemesh_tpu.post import workload
    from spacemesh_tpu.post.data import LabelStore
    from spacemesh_tpu.post.verifier import VerifyItem

    ids = _ids(4, salt=b"e2e")
    prove_dir = str(tmp_path / "prove-store")
    prover = workload.build(prove_dir, 512, 256)
    serial_proof = prover.prove_serial(workload.CHALLENGE)

    labels_before = {
        tid: metrics.runtime_tenant_labels.sample().get(
            (("tenant", tid),), 0) for tid, _, _ in ids}
    tracing.start(capacity=65536)
    try:
        with TenantScheduler(workers=2, pack_lanes=PACK) as sched:
            inits = []
            for tid, node, commit in ids:
                sched.register_tenant(tid)
                inits.append((tid, commit, sched.submit_init(
                    tid, tmp_path / tid, node_id=node, commitment=commit,
                    num_units=1, labels_per_unit=200, scrypt_n=N,
                    max_file_size=1 << 20)))
            sched.register_tenant("prover")
            hp = sched.submit_prove("prover", prove_dir,
                                    workload.CHALLENGE, workload.PARAMS,
                                    batch_labels=256)
            proof = hp.result(timeout=600)
            assert proof == serial_proof
            item = VerifyItem(proof=proof, challenge=workload.CHALLENGE,
                              node_id=workload.NODE,
                              commitment=workload.COMMITMENT,
                              scrypt_n=2, total_labels=512)
            hv = sched.submit_verify("prover", [item], workload.PARAMS,
                                     seed=b"e2e-seed".ljust(32, b"\0"))
            assert hv.result(timeout=300) == [True]
            for tid, commit, h in inits:
                meta = h.result(timeout=300)
                store = LabelStore(tmp_path / tid, meta)
                got = store.read_labels(0, 200)
                store.close()
                assert got == _reference(commit, 200)
    finally:
        tracing.stop()

    doc = tracing.export()
    tracing.validate(doc)
    spans = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    by_tenant = {}
    for e in spans:
        t = e.get("args", {}).get("tenant")
        if t:
            by_tenant.setdefault(t, set()).add(e["name"])
    # every init tenant appears in the capture (pack segments), and the
    # prover tenant's quanta do too
    for tid, _, _ in ids:
        assert "runtime.segment" in by_tenant.get(tid, set()), by_tenant
    assert "runtime.quantum" in by_tenant.get("prover", set())
    # per-tenant label accounting advanced for every tenant
    after = metrics.runtime_tenant_labels.sample()
    for tid, _, _ in ids:
        assert after.get((("tenant", tid),), 0) \
            >= labels_before[tid] + 200
