"""Operator tool tail: genesisgen, gen-p2p-identity, activeset,
poet certifier (VERDICT r2 item 10; reference cmd/genesisgen,
cmd/gen-p2p-identity, cmd/activeset, activation/certifier.go:246)."""

import asyncio
import hashlib
import io
import json
from contextlib import redirect_stdout

import pytest

from spacemesh_tpu.core.signing import EdSigner, EdVerifier
from spacemesh_tpu.node.config import GenesisConfig
from spacemesh_tpu.tools import activeset, gen_p2p_identity, genesisgen


def _run(tool_main, argv) -> list[dict]:
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = tool_main(argv)
    assert rc == 0, buf.getvalue()
    return [json.loads(line) for line in buf.getvalue().splitlines()]


def test_genesisgen_roundtrip():
    lines = _run(genesisgen.main,
                 ["--time", "2026-01-01T00:00:00Z", "--extra", "t-net",
                  "-n", "3"])
    head, keys = lines[0], lines[1:]
    assert len(keys) == 3
    # genesis id matches the config the node would derive
    import datetime

    ts = datetime.datetime.fromisoformat(
        "2026-01-01T00:00:00+00:00").timestamp()
    assert head["genesis_id"] == \
        GenesisConfig(time=ts, extra_data="t-net").genesis_id.hex()
    # each key reloads into a signer with the advertised id
    prefix = bytes.fromhex(head["genesis_id"])
    for k in keys:
        s = EdSigner(seed=bytes.fromhex(k["private"]), prefix=prefix)
        assert s.node_id.hex() == k["id"]
        assert len(bytes.fromhex(k["commitment"])) == 32


def test_genesisgen_rejects_bad_time():
    assert genesisgen.main(["--time", "not-a-time"]) == 1


def test_gen_p2p_identity_writes_node_key(tmp_path):
    (out,) = _run(gen_p2p_identity.main, ["--data-dir", str(tmp_path)])
    key_file = tmp_path / "identities" / "local.key"
    assert key_file.exists()
    prefix = GenesisConfig(time=0.0, extra_data="tpu-mainnet").genesis_id
    s = EdSigner(seed=bytes.fromhex(key_file.read_text().strip()),
                 prefix=prefix)
    assert s.node_id.hex() == out["node_id"]
    # the node picks it up as its primary identity
    from spacemesh_tpu.node.app import App
    from spacemesh_tpu.node.config import load

    cfg = load("standalone", overrides={"data_dir": str(tmp_path),
                                        "genesis": {"time": 0.0}})
    cfg.genesis.extra_data = "tpu-mainnet"
    app = App(cfg)
    try:
        assert app.signer.node_id.hex() == out["node_id"]
    finally:
        app.close()
    # refuses to clobber
    assert gen_p2p_identity.main(["--data-dir", str(tmp_path)]) == 1


def test_activeset_reads_epoch_atxs(tmp_path):
    from spacemesh_tpu.storage import db as dbmod

    # reuse a populated state db from a quick standalone prepare run?
    # cheaper: store two hand-built ATXs directly
    from spacemesh_tpu.core.types import (
        ActivationTx,
        MerkleProof,
        NIPost,
        Post,
        PostMetadataWire,
    )
    from spacemesh_tpu.storage import atxs as atxstore

    db = dbmod.open_state(tmp_path / "state.db")
    prefix = b"\x01" * 20
    nipost = NIPost(
        membership=MerkleProof(leaf_index=0, nodes=[]),
        post=Post(nonce=0, indices=[1, 2], pow_nonce=0),
        post_metadata=PostMetadataWire(challenge=bytes(32),
                                       labels_per_unit=256))
    for i in range(2):
        s = EdSigner(prefix=prefix)
        atx = ActivationTx(
            publish_epoch=3, prev_atx=bytes(32), pos_atx=bytes(32),
            commitment_atx=None, initial_post=None, nipost=nipost,
            num_units=2 + i, vrf_nonce=0,
            vrf_public_key=s.node_id, coinbase=bytes(24),
            node_id=s.node_id, signature=bytes(64))
        atxstore.add(db, atx, tick_height=10)

    (out,) = _run(activeset.main, ["3", str(tmp_path / "state.db")])
    assert out["epoch"] == 3
    assert out["count"] == 2
    assert out["total_weight"] == (2 * 10) + (3 * 10)
    db.close()


def test_node_obtains_poet_cert_from_configured_certifier(tmp_path):
    """poet_certifier config -> the node proves + certifies each identity
    at smeshing start and carries the cert into poet registration."""
    from spacemesh_tpu.consensus.certifier import (
        CertifierDaemon,
        CertifierService,
        verify_cert,
    )
    from spacemesh_tpu.node.app import App
    from spacemesh_tpu.node.config import load
    from spacemesh_tpu.post.prover import ProofParams

    params = ProofParams(k1=64, k2=8, k3=4,
                         pow_difficulty=b"\x20" + b"\xff" * 31)
    certifier_signer = EdSigner()
    service = CertifierService(certifier_signer, params, scrypt_n=2)

    async def go():
        daemon = CertifierDaemon(service)
        host, port = await daemon.start()
        cfg = load("standalone", overrides={
            "data_dir": str(tmp_path / "node"),
            "poet_certifier": f"{host}:{port}",
            "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64,
                     "k2": 8, "k3": 4, "min_num_units": 1,
                     "pow_difficulty": "20" + "ff" * 31},
            "smeshing": {"start": True, "num_units": 1,
                         "init_batch": 128},
        })
        app = App(cfg)
        try:
            await asyncio.wait_for(app.prepare(), 300)
            for b in app.atx_builders:
                cert = b.poet_cert
                assert cert is not None, "builder never certified"
                assert cert.node_id == b.signer.node_id
                assert verify_cert(cert, certifier_signer.public_key,
                                   EdVerifier())
        finally:
            app.close()
            await daemon.stop()

    asyncio.run(go())


def test_certifier_flow_gates_poet_registration(tmp_path):
    """POST proof -> certifier cert -> cert-gated poet accepts; no cert
    or forged cert -> rejected (activation/certifier.go:246 +
    cert-checking poet)."""
    from spacemesh_tpu.consensus.certifier import (
        CertifierClient,
        CertifierDaemon,
        CertifierService,
        PoetCert,
    )
    from spacemesh_tpu.consensus.poet import PoetService
    from spacemesh_tpu.post import initializer
    from spacemesh_tpu.post.prover import ProofParams, Prover

    node_id = hashlib.sha256(b"cert-node").digest()
    commitment = hashlib.sha256(b"cert-commitment").digest()
    params = ProofParams(k1=64, k2=8, k3=4,
                         pow_difficulty=b"\x20" + b"\xff" * 31)
    d = tmp_path / "post"
    initializer.initialize(d, node_id=node_id, commitment=commitment,
                           num_units=1, labels_per_unit=256, scrypt_n=2,
                           batch_size=128)
    challenge = hashlib.sha256(b"cert-challenge").digest()
    proof = Prover(d, params, batch_labels=256).prove(challenge)

    certifier_signer = EdSigner()
    service = CertifierService(certifier_signer, params, scrypt_n=2)

    async def go():
        daemon = CertifierDaemon(service)
        addr = await daemon.start()
        try:
            client = CertifierClient(addr)
            # blocking socket calls go off-loop (the daemon runs here)
            assert await asyncio.to_thread(client.pubkey) == \
                certifier_signer.public_key
            cert = await asyncio.to_thread(
                client.certificate, proof=proof, challenge=challenge,
                node_id=node_id, commitment=commitment, num_units=1,
                labels_per_unit=256)
            # caching: second call hits the cache (same object)
            again = client.certificate(
                proof=proof, challenge=challenge, node_id=node_id,
                commitment=commitment, num_units=1, labels_per_unit=256)
            assert again is cert

            # the registering identity must HOLD the certified key:
            # registration is bound by a POET-domain signature.  The POST
            # data's node_id in this test is a hash, not an ed25519 key,
            # so mint a cert for a real signer's id directly (the signing
            # path is what's under test here, not the proof re-check).
            from spacemesh_tpu.core.signing import Domain

            id_signer = EdSigner()
            cert2 = PoetCert(node_id=id_signer.node_id, expiry=0.0,
                             signature=b"")
            cert2.signature = certifier_signer.sign(
                Domain.POET_CERT, cert2.signed_bytes())
            poet = PoetService(poet_id=b"p" * 32, ticks=4,
                               certifier_pubkey=certifier_signer.public_key,
                               verifier=EdVerifier())
            sig = id_signer.sign(Domain.POET, b"r1" + challenge)
            await poet.register("r1", challenge,
                                node_id=id_signer.node_id,
                                signature=sig, cert=cert2)
            with pytest.raises(PermissionError):
                await poet.register("r1", challenge)  # nothing presented
            with pytest.raises(PermissionError):  # cert/identity mismatch
                await poet.register("r1", challenge, node_id=node_id,
                                    signature=sig, cert=cert2)
            forged = PoetCert(node_id=id_signer.node_id, expiry=0.0,
                              signature=b"\x00" * 64)
            with pytest.raises(PermissionError):
                await poet.register("r1", challenge,
                                    node_id=id_signer.node_id,
                                    signature=sig, cert=forged)
            with pytest.raises(PermissionError):  # wrong reg signature
                await poet.register("r2", challenge,
                                    node_id=id_signer.node_id,
                                    signature=sig, cert=cert2)

            # a proof that does not verify is refused by the certifier
            bad = hashlib.sha256(b"other").digest()
            with pytest.raises(RuntimeError, match="verification|failed"):
                await asyncio.to_thread(
                    client.certificate, proof=proof, challenge=challenge,
                    node_id=bad, commitment=commitment, num_units=1,
                    labels_per_unit=256)
        finally:
            await daemon.stop()

    asyncio.run(go())


def test_profiler_lists_providers_and_recommends(capsys):
    """Operator tuning tool (reference post_supervisor.go:105-127
    Providers()/Benchmark(); post-rs profiler binary): providers
    enumerate, a tiny benchmark produces per-provider rates and a
    recommendation with an init-batch suggestion for device providers."""
    import json as _json

    from spacemesh_tpu.tools import profiler

    assert profiler.main(["--providers", "--no-probe"]) == 0
    doc = _json.loads(capsys.readouterr().out)
    ids = [p["id"] for p in doc["providers"]]
    assert "cpu:openssl" in ids
    assert any(i.startswith("jax:") for i in ids)

    assert profiler.main(["--n", "2", "--batches", "32", "--reps", "1",
                          "--cpu-labels", "4", "--no-probe"]) == 0
    doc = _json.loads(capsys.readouterr().out)
    assert doc["scrypt_n"] == 2
    rec = doc["recommendation"]
    assert rec["labels_per_sec"] > 0
    assert "hours_per_space_unit" in rec
    rates = [p["labels_per_sec"] for p in doc["providers"]]
    assert rates == sorted(rates, reverse=True)
    jax_row = next(p for p in doc["providers"]
                   if p["id"].startswith("jax:"))
    assert jax_row["best_batch"] == 32


def test_profiler_verify_benchmark(capsys):
    """--verify measures proofs/second through the batched verifier
    (BASELINE config 3's metric) on a real tiny unit + proof."""
    import json as _json

    from spacemesh_tpu.tools import profiler

    assert profiler.main(["--verify", "--verify-batches", "10,20",
                          "--no-probe"]) == 0
    doc = _json.loads(capsys.readouterr().out)
    rates = doc["verify"]
    assert [r["batch"] for r in rates] == [10, 20]
    assert all(r["proofs_per_sec"] > 0 for r in rates)


def test_profiler_pipeline_stage_timings(capsys):
    """--pipeline dumps per-stage (dispatch/fetch/write/stall) host
    seconds of a real streaming init, so a stalled stage is visible
    without a full profile (docs/POST_PIPELINE.md)."""
    import json as _json

    from spacemesh_tpu.tools import profiler

    assert profiler.main(["--pipeline", "--n", "2",
                          "--pipeline-labels", "512",
                          "--pipeline-batch", "256", "--no-probe"]) == 0
    doc = _json.loads(capsys.readouterr().out)
    assert doc["labels_per_sec"] > 0
    assert set(doc["stages"]) >= {"dispatch_s", "fetch_s",
                                  "write_stall_s", "write_s"}
    assert doc["stages"]["batches"] == 2
    assert doc["bottleneck"] in ("dispatch_s", "fetch_s", "write_stall_s")
