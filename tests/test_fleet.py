"""verifyd fleet control plane (ISSUE 17, verifyd/fleet.py).

The FleetVerifier's chain contract on an injected clock — primary
replica while healthy, chain-walk under per-replica breakers on
transport failure, typed-shed re-routing (``registry_full`` re-places
the client instead of surfacing), work stealing off hot replicas,
fleet-wide admission bound, the start/aclose breaker+series lifecycle,
the autoscaling signal fold — plus the re-route churn loop proving a
moved client's per-shard metric series do NOT leak (the PR-12
pattern), and the cookbook client's ``replica_hint`` hop path.  Whole-
plane choreography under chaos is the ``fleet`` sim scenario's job
(tests/test_sim_scenarios.py).
"""

import asyncio
import math

import pytest

from spacemesh_tpu.obs import remediate
from spacemesh_tpu.utils import metrics
from spacemesh_tpu.verify.farm import Lane, SigRequest
from spacemesh_tpu.verifyd.client import RetryPolicy, VerifydClient
from spacemesh_tpu.verifyd.fleet import FleetRouter, FleetVerifier
from spacemesh_tpu.verifyd.service import Shed, VerifydService


class Clock:
    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


class FakeReq:
    def __init__(self, i: int, kind: str = "sig"):
        self.i = i
        self.kind = kind


class FakeEndpoint:
    """Scriptable replica endpoint: verdict = (i % 2 == 0)."""

    def __init__(self):
        self.calls = 0
        self.registers: list[str] = []
        self.unregisters: list[str] = []
        self.fail_with = None        # exception, or a list popped per call

    async def register(self, client, **kwargs):
        self.registers.append(str(client))
        return {"client": str(client)}

    async def unregister(self, client):
        self.unregisters.append(str(client))

    async def verify(self, reqs, *, client, lane="gossip",
                     deadline_s=None):
        self.calls += 1
        fail = self.fail_with
        if isinstance(fail, list):
            fail = fail.pop(0) if fail else None
        if fail is not None:
            raise fail
        return [r.i % 2 == 0 for r in reqs]


class FakeFarm:
    """Local twin computing the SAME verdicts (the farm contract)."""

    def __init__(self):
        self.submits = 0

    async def submit(self, req, lane=Lane.GOSSIP) -> bool:
        self.submits += 1
        return req.i % 2 == 0


REQS = [FakeReq(i) for i in range(4)]
WANT = [True, False, True, False]


def _fleet(clock, n=3, max_clients=64, **kw):
    kw.setdefault("breaker_kw", {"failure_budget": 2, "cooldown_s": 4.0,
                                 "cooldown_cap_s": 8.0})
    router = FleetRouter(seed=3, time_source=clock.now, **kw)
    eps = {}
    for i in range(n):
        ep = FakeEndpoint()
        eps[f"r{i}"] = ep
        router.register_replica(f"r{i}", ep, max_clients=max_clients)
    farm = FakeFarm()
    fv = FleetVerifier(router=router, farm=farm, client_id="node",
                       own_router=True, time_source=clock.now)
    return fv, router, eps, farm


def _chain(router, cid="node"):
    router.place_client(cid)
    return [router.placement.replica_of(cid)] + [
        m for m in router.placement.ring.walk(cid)
        if m != router.placement.replica_of(cid)]


# --- the chain ------------------------------------------------------------


def test_primary_serves_and_registers_once():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock)
        assert await fv.verify_batch(REQS, Lane.BLOCK) == WANT
        assert await fv.submit(FakeReq(2)) is True
        primary = router.placement.replica_of("node")
        assert eps[primary].calls == 2
        assert eps[primary].registers == ["node"]
        assert all(ep.calls == 0 for name, ep in eps.items()
                   if name != primary)
        assert farm.submits == 0 and fv.stats["remote_ok"] == 2

    asyncio.run(run())


def test_dead_primary_chain_moves_on_same_call_then_skips_it():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock)
        order = _chain(router)
        eps[order[0]].fail_with = ConnectionError("down")
        # budget 2: both failing calls STILL answer — from the next
        # replica on the chain, same call, not the local farm
        for _ in range(2):
            assert await fv.verify_batch(REQS) == WANT
        assert router.replicas[order[0]].breaker.state == remediate.OPEN
        assert eps[order[0]].calls == 2 and eps[order[1]].calls == 2
        assert farm.submits == 0
        # open: the corpse is not re-paid, the chain starts at order[1]
        for _ in range(5):
            assert await fv.verify_batch(REQS) == WANT
        assert eps[order[0]].calls == 2 and eps[order[1]].calls == 7
        assert fv.stats["remote_ok"] == 7

    asyncio.run(run())


def test_whole_fleet_dead_local_then_fastfail():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock)
        for ep in eps.values():
            ep.fail_with = ConnectionError("down")
        # budget 2, every call pays each closed replica once: two calls
        # open all three breakers; every call still answers with the
        # bit-identical local verdicts
        for _ in range(2):
            assert await fv.verify_batch(REQS) == WANT
        assert all(r.breaker.state == remediate.OPEN
                   for r in router.replicas.values())
        assert fv.stats["local"] == 2
        calls_before = sum(ep.calls for ep in eps.values())
        assert await fv.verify_batch(REQS) == WANT
        assert sum(ep.calls for ep in eps.values()) == calls_before
        assert fv.stats["local_fastfail"] == 1

    asyncio.run(run())


# --- typed sheds ----------------------------------------------------------


def test_registry_full_reroutes_without_tripping():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock)
        order = _chain(router)
        eps[order[0]].fail_with = Shed("registry_full", "replica full")
        assert await fv.verify_batch(REQS) == WANT
        # config-class: the breaker did NOT trip, the client moved
        assert router.replicas[order[0]].breaker.state \
            == remediate.CLOSED
        assert router.placement.replica_of("node") != order[0]
        assert router.stats["reroutes"] >= 1
        assert metrics.fleet_replica_sheds.sample()[
            (("reason", "registry_full"), ("replica", order[0]))] >= 1
        # next call goes straight to the new home
        eps[order[0]].fail_with = None
        assert await fv.verify_batch(REQS) == WANT
        assert eps[order[0]].calls == 1

    asyncio.run(run())


def test_shutting_down_reroutes_and_trips():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(
            clock, breaker_kw={"failure_budget": 1, "cooldown_s": 4.0,
                               "cooldown_cap_s": 8.0})
        order = _chain(router)
        eps[order[0]].fail_with = Shed("shutting_down", "draining")
        assert await fv.verify_batch(REQS) == WANT
        # a draining replica is both avoided (re-route) and tripped
        assert router.replicas[order[0]].breaker.state == remediate.OPEN
        assert router.placement.replica_of("node") != order[0]
        assert metrics.remediation_actions.sample().get(
            (("action", "failover_replica"),
             ("component", f"verifyd.replica.{order[0]}"),
             ("outcome", "ok")), 0) >= 1

    asyncio.run(run())


def test_unregistered_retries_same_replica_once():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock)
        order = _chain(router)
        # replica restarted and lost the registration: shed once, then
        # serve — the SAME replica answers after a re-register
        eps[order[0]].fail_with = [Shed("unregistered", "who?")]
        assert await fv.verify_batch(REQS) == WANT
        assert eps[order[0]].calls == 2
        assert eps[order[0]].registers == ["node", "node"]
        assert eps[order[1]].calls == 0
        assert router.replicas[order[0]].breaker.state \
            == remediate.CLOSED

    asyncio.run(run())


def test_fleet_wide_bound_sheds_typed():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock, n=2, max_clients=1)
        assert router.fleet_max_clients() == 2
        await fv.verify_batch(REQS, client_id="c0")
        await fv.verify_batch(REQS, client_id="c1")
        with pytest.raises(Shed) as ei:
            await fv.verify_batch(REQS, client_id="c2")
        assert ei.value.reason == "registry_full"
        # the bound is about NEW placements: placed clients still serve
        assert await fv.verify_batch(REQS, client_id="c0") == WANT

    asyncio.run(run())


# --- work stealing --------------------------------------------------------


def test_hot_primary_is_stolen_from():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock)
        order = _chain(router)
        # fold SLIs: primary's queue p99 4x over its SLO share, the
        # others idle -> primary scores hot, coolest healthy wins
        router.update_signals(
            {f"fleet_replica_{order[0]}_queue_p99": 1.0})
        chain = router.chain("node", ["sig"])
        assert chain[0] != order[0] and order[0] in chain
        assert router.stats["steals"] == 1
        assert await fv.verify_batch(REQS) == WANT
        assert eps[order[0]].calls == 0   # served by the steal target

    asyncio.run(run())


def test_kind_heat_steals_only_hot_kinds_and_decays():
    async def run():
        clock = Clock(t=100.0)
        fv, router, eps, farm = _fleet(clock)
        order = _chain(router)
        for _ in range(3):
            router.note_shed(order[0], "overload", kinds=["pow"])
        assert router.chain("node", ["pow"])[0] != order[0]
        assert router.chain("node", ["sig"])[0] == order[0]
        # heat is an EWMA on the injected clock: it decays away
        clock.advance(300.0)
        assert router.chain("node", ["pow"])[0] == order[0]

    asyncio.run(run())


def test_steal_needs_margin_and_a_healthy_target():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock)
        order = _chain(router)
        # everyone equally hot: stealing would just move the hot spot
        router.update_signals(
            {f"fleet_replica_{n}_queue_p99": 1.0 for n in order})
        assert router.steal_target(order[0]) is None
        # the only cool replica has an OPEN breaker: not a target
        router.update_signals(
            {f"fleet_replica_{n}_queue_p99": 1.0
             for n in order[:2]})
        for _ in range(2):
            router.replicas[order[2]].breaker.record_failure()
        assert router.replicas[order[2]].breaker.state == remediate.OPEN
        assert router.steal_target(order[0]) is None

    asyncio.run(run())


# --- autoscaling signal ---------------------------------------------------


def test_update_signals_scores_and_desired_replicas():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock)
        sig = router.update_signals({
            "fleet_replica_r0_queue_p99": 0.5,   # 2x over SLO share
            "fleet_replica_r1_shed_per_sec": 3.0,
            "fleet_replica_r2_queue_p99": 0.025,
        })
        assert sig["scores"]["r0"] == pytest.approx(2.0)
        assert sig["scores"]["r1"] == pytest.approx(3.0)
        assert sig["scores"]["r2"] == pytest.approx(0.1)
        mean = (2.0 + 3.0 + 0.1) / 3
        assert sig["desired_replicas"] == math.ceil(3 * mean / 0.7)
        assert metrics.fleet_desired_replicas.sample()[()] \
            == sig["desired_replicas"]
        # idle fleet wants the floor, not zero
        assert router.update_signals({})["desired_replicas"] \
            == router.min_replicas

    asyncio.run(run())


# --- lifecycle: breakers + series -----------------------------------------


def test_start_aclose_registers_and_removes_everything():
    async def run():
        clock = Clock()
        fv, router, eps, farm = _fleet(clock)
        fv.start()
        try:
            for name in ("r0", "r1", "r2"):
                assert f"verifyd.replica.{name}" \
                    in remediate.BREAKERS.names()
            router.update_signals({})
            assert (("replica", "r1"),) \
                in metrics.fleet_replica_load.sample()
            # a replica leaving the fleet takes its series and breaker
            moved = router.unregister_replica("r1")
            assert all(old == "r1" for _c, old, _n in moved)
            assert "verifyd.replica.r1" not in remediate.BREAKERS.names()
            assert (("replica", "r1"),) \
                not in metrics.fleet_replica_load.sample()
        finally:
            await fv.aclose()
        assert all(f"verifyd.replica.{n}"
                   not in remediate.BREAKERS.names()
                   for n in ("r0", "r2"))
        assert metrics.fleet_replicas.sample()[()] == 0

    asyncio.run(run())


# --- re-route churn: zero leaked series -----------------------------------


class _SvcEndpoint:
    """In-process endpoint over a real sharded VerifydService (the
    churn loop needs the true per-shard registries and series)."""

    def __init__(self, svc: VerifydService):
        self.svc = svc

    async def register(self, client, **kwargs):
        self.svc.register_client(str(client), **kwargs)
        return {"client": str(client)}

    async def unregister(self, client):
        self.svc.unregister_client(str(client))

    async def verify(self, reqs, *, client, lane="gossip",
                     deadline_s=None):  # pragma: no cover - unused
        raise AssertionError("churn test never verifies")


def test_reroute_churn_leaks_no_per_shard_series():
    """100 re-routes between two real shards: every hop's flush_stale
    unregisters the client from the shard it LEFT, so no
    ``{shard}/{cid}`` series and no tenant state survive the churn."""

    async def run():
        cid = "churnling"
        services = {n: VerifydService(shard=n, workers=1)
                    for n in ("a", "b")}
        router = FleetRouter(seed=5)
        try:
            for n, svc in services.items():
                router.register_replica(n, _SvcEndpoint(svc))
            router.place_client(cid)
            for _ in range(100):
                cur = router.placement.replica_of(cid)
                rep = router.replicas[cur]
                await rep.endpoint.register(cid)
                rep.registered.add(cid)
                assert cid in services[cur].clients
                # the shard sheds registry_full -> the router moves the
                # client and flushes the stale registration
                assert router.reroute(cid, avoid=cur,
                                      reason="registry_full") != cur
                await router.flush_stale()
                assert cid not in services[cur].clients
                assert len(services[cur].clients) == 0
            # the identity left no trace on either shard's books
            last = router.placement.replica_of(cid)
            router.replicas[last].registered.discard(cid)
            router.forget_client(cid)
            assert all(not svc.clients for svc in services.values())
            assert cid not in metrics.REGISTRY.expose()
        finally:
            for n in list(services):
                router.unregister_replica(n)
            await router.aclose()
            for svc in services.values():
                await svc.aclose()

    asyncio.run(run())


# --- the cookbook client's replica_hint hop path --------------------------


class _HopClient(VerifydClient):
    """_post driven by a url-keyed script instead of sockets."""

    def __init__(self, servers, start_url, **kw):
        kw.setdefault("retry", None)
        kw.setdefault("session", object())   # never used: _post is ours
        kw.setdefault("sleep", self._fake_sleep)
        super().__init__(start_url, "c", **kw)
        self.servers = servers   # url -> {path: doc | [docs]}
        self.posts: list[tuple[str, str, dict]] = []
        self.sleeps: list[float] = []

    async def _fake_sleep(self, s):
        self.sleeps.append(s)

    async def _post(self, path, body):
        self.posts.append((self.base_url, path, body))
        doc = self.servers[self.base_url][path]
        if isinstance(doc, list):
            doc = doc.pop(0)
        return 200, doc


_OK_REG = {"status": "OK"}
_OK_VERIFY = {"status": "OK", "verdicts": [True, False]}
_SIG = SigRequest(domain=1, public_key=b"\x01" * 32, msg=b"m",
                  signature=b"\x02" * 64)


def _shed_doc(reason, hint=None):
    doc = {"status": "SHED", "reason": reason, "detail": "x"}
    if hint is not None:
        doc["replica_hint"] = hint
    return doc


def test_client_hops_to_hinted_replica_without_sleeping():
    async def run():
        c = _HopClient({
            "http://a": {"/v1/client/register": _OK_REG,
                         "/v1/verify": _shed_doc("registry_full",
                                                 "http://b")},
            "http://b": {"/v1/client/register": _OK_REG,
                         "/v1/verify": _OK_VERIFY},
        }, "http://a", retry=RetryPolicy(max_attempts=5))
        await c.register(weight=2.0)
        assert await c.verify([_SIG]) == [True, False]
        assert c.base_url == "http://b" and c.sleeps == []
        # the hop re-registered with the ORIGINAL knobs
        reg_b = [b for u, p, b in c.posts
                 if u == "http://b" and p == "/v1/client/register"]
        assert reg_b == [{"client": "c", "weight": 2.0}]

    asyncio.run(run())


def test_client_chases_chained_hints():
    async def run():
        # a is draining and points at b; b is ALSO draining and points
        # at c; c serves — the hop loop chases hints, each url once
        c = _HopClient({
            "http://a": {"/v1/verify": _shed_doc("shutting_down",
                                                 "http://b")},
            "http://b": {"/v1/client/register":
                         _shed_doc("shutting_down", "http://c")},
            "http://c": {"/v1/client/register": _OK_REG,
                         "/v1/verify": _OK_VERIFY},
        }, "http://a")
        assert await c.verify([_SIG]) == [True, False]
        assert c.base_url == "http://c" and c.sleeps == []

    asyncio.run(run())


def test_client_falls_back_to_configured_ring_without_hint():
    async def run():
        c = _HopClient({
            "http://a": {"/v1/verify": _shed_doc("registry_full")},
            "http://b": {"/v1/client/register": _OK_REG,
                         "/v1/verify": _OK_VERIFY},
        }, "http://a", fallback_urls=("http://b",))
        assert await c.verify([_SIG]) == [True, False]
        assert c.base_url == "http://b"

    asyncio.run(run())


def test_client_hop_exhaustion_reraises_typed():
    async def run():
        # the hint points back at an already-tried replica and there
        # are no fallbacks: the lifecycle shed surfaces immediately
        c = _HopClient({
            "http://a": {"/v1/verify": _shed_doc("registry_full",
                                                 "http://a")},
        }, "http://a", retry=RetryPolicy(max_attempts=5))
        with pytest.raises(Shed) as ei:
            await c.verify([_SIG])
        assert ei.value.reason == "registry_full"
        assert c.sleeps == []
        assert [p for _u, p, _b in c.posts] == ["/v1/verify"]

    asyncio.run(run())
