"""In-proc multi-node network: convergence + late-join sync.

The TestNetwork tier of the reference's test strategy (reference
node/test_network.go boots N full nodes fully connected in one process):
node A smeshes; observers B (live from genesis) and C (joins late, syncs)
must converge on A's ATXs, blocks, and applied state.

De-flaked (ISSUE 9 satellite, the PR-8 recipe): signers are built from
FIXED seeds — random keys made A's VRF proposal-slot and hare-committee
draws probabilistic, and a rare unlucky draw left a mid layer without a
certified hare output, so observers applied it through a different path
(state-root divergence at that layer, the last tier-1 flake standing
after PR 8). The salt is CHOSEN so the single smesher's draws carry
margin (blocks land in every post-genesis layer of the run). And the
final catch-up is a CONDITION WAIT for B as well as C: both observers'
syncers are driven until their applied frontier reaches A's, bounded in
virtual time, instead of hoping the background run got there before its
until_layer stop.
"""

import asyncio
import hashlib

import pytest

from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.p2p.server import LoopbackNet
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.utils.vclock import VirtualClockLoop, cancel_all_tasks

LPE = 3
LAYER_SEC = 2.0  # virtual seconds (VirtualClockLoop) — costs no wall time


# ONE genesis timestamp for the whole network: genesis_id (the signature
# prefix and golden ATX) derives from it, so per-node values would put the
# nodes on different networks entirely.
GENESIS_PLACEHOLDER = 1_700_000_600.0  # fixed: virtual time is deterministic


def _config(tmp_path, name, smesh):
    return load("standalone", overrides={
        "data_dir": str(tmp_path / name),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": GENESIS_PLACEHOLDER},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": smesh, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.2,
                 "preround_delay": 0.5, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.2},
        "tortoise": {"hdist": 4, "window_size": 50},
    })


@pytest.fixture(scope="module")
def network(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("multinode")
    loop = VirtualClockLoop()
    hub = LoopbackHub()
    net = LoopbackNet()

    def make(name, smesh):
        cfg = _config(tmp, name, smesh)
        # fixed seed per node: the smesher's VRF draws (proposal slots,
        # hare committee seats) are deterministic, so a once-green salt
        # can never re-roll into the empty-layer/missed-cert draw that
        # used to diverge state roots ~rarely
        signer = EdSigner(
            seed=hashlib.sha256(b"multinode-1-%s" % name.encode()).digest(),
            prefix=cfg.genesis.genesis_id)
        ps = PubSub(node_name=signer.node_id)
        hub.join(ps)
        app = App(cfg, signer=signer, pubsub=ps, time_source=loop.time)
        app.connect_network(net)
        return app

    a = make("a", smesh=True)
    b = make("b", smesh=False)
    c_holder = {}

    async def go():
        await a.prepare()
        genesis = loop.time() + 1.0
        for app in (a, b):
            app.clock = clock_mod.LayerClock(genesis, LAYER_SEC,
                                             time_source=loop.time)
        until = 2 * LPE + 1
        task_a = asyncio.create_task(a.run(until_layer=until))
        task_b = asyncio.create_task(b.run(until_layer=until))
        # C joins after one full epoch has passed
        await asyncio.sleep(LAYER_SEC * (LPE + 1))
        c = make("c", smesh=False)
        c.clock = clock_mod.LayerClock(genesis, LAYER_SEC,
                                       time_source=loop.time)
        c_holder["app"] = c
        synced = await c.syncer.synchronize()
        await asyncio.gather(task_a, task_b)
        # final catch-up after A/B stopped: CONDITION WAIT driving both
        # observers' syncers until each reaches A's applied frontier
        # (virtual-time bounded) — B's background run may have stopped
        # at until_layer before applying the final hare output
        deadline = loop.time() + 300
        target = layerstore.last_applied(a.state) - 1
        while loop.time() < deadline:
            await b.syncer.synchronize()
            await c.syncer.synchronize()
            if layerstore.last_applied(b.state) >= target \
                    and layerstore.last_applied(c.state) >= target:
                break
            await asyncio.sleep(0.2)
        return synced

    try:
        loop.run_until_complete(asyncio.wait_for(go(), 10_000))
    finally:
        loop.run_until_complete(cancel_all_tasks())
    return a, b, c_holder["app"]


def test_atx_propagates_to_observers(network):
    a, b, c = network
    for epoch in (0, 1):
        mine = atxstore.by_node_in_epoch(a.state, a.signer.node_id, epoch)
        assert mine is not None
        assert atxstore.get(b.state, mine.id) is not None, f"B missing epoch-{epoch} ATX"
        assert atxstore.get(c.state, mine.id) is not None, f"C missing epoch-{epoch} ATX"


def test_blocks_converge_on_live_observer(network):
    a, b, c = network
    layers_with_blocks = [lyr for lyr in range(LPE, 2 * LPE + 2)
                          if blockstore.in_layer(a.state, lyr)]
    assert layers_with_blocks, "A generated no blocks"
    for lyr in layers_with_blocks:
        ids_a = blockstore.ids_in_layer(a.state, lyr)
        ids_b = blockstore.ids_in_layer(b.state, lyr)
        assert ids_a == ids_b, f"layer {lyr}: A and B disagree on blocks"


def test_late_joiner_catches_up(network):
    a, b, c = network
    # C fetched A's blocks and applied layers up to (near) the tip
    applied_a = layerstore.last_applied(a.state)
    applied_c = layerstore.last_applied(c.state)
    assert applied_c >= applied_a - 1, (applied_c, applied_a)
    for lyr in range(LPE, applied_c + 1):
        ids_a = blockstore.ids_in_layer(a.state, lyr)
        ids_c = blockstore.ids_in_layer(c.state, lyr)
        assert ids_a == ids_c, f"layer {lyr}: A and C disagree on blocks"


def test_state_roots_converge(network):
    a, b, c = network
    lyr = min(layerstore.last_applied(a.state), layerstore.last_applied(b.state),
              layerstore.last_applied(c.state))
    assert lyr >= LPE
    ra = layerstore.state_hash(a.state, lyr)
    rb = layerstore.state_hash(b.state, lyr)
    rc = layerstore.state_hash(c.state, lyr)
    assert ra == rb == rc, f"state divergence at layer {lyr}"
