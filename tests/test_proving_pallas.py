"""Pallas proving-scan kernel vs the XLA reference path (interpret mode)."""

import hashlib

import numpy as np

from spacemesh_tpu.ops import proving, proving_pallas, scrypt

CH = hashlib.sha256(b"pallas-ch").digest()
COMMIT = hashlib.sha256(b"pallas-commit").digest()


def test_pallas_scan_matches_reference():
    total = 1024
    idx = np.arange(total, dtype=np.uint64)
    labels = scrypt.scrypt_labels(COMMIT, idx, n=2)
    t = proving.threshold_u32(200, total)
    got = proving_pallas.proving_scan(CH, 5, idx, labels, t, n_nonces=4,
                                      interpret=True)
    assert got.shape == (4, total)
    assert got.any(), "expected some qualifying labels at this threshold"
    for k in range(4):
        vals = proving.proving_hashes(CH, 5 + k, idx, labels)
        assert np.array_equal(got[k], vals < t), f"nonce {k} mismatch"


def test_pallas_scan_padding():
    # batch not a multiple of the lane tile: wrapper pads + trims
    total = 700
    idx = np.arange(total, dtype=np.uint64)
    labels = scrypt.scrypt_labels(COMMIT, idx, n=2)
    t = proving.threshold_u32(100, total)
    got = proving_pallas.proving_scan(CH, 0, idx, labels, t, n_nonces=2,
                                      interpret=True)
    assert got.shape == (2, total)
    vals = proving.proving_hashes(CH, 0, idx, labels)
    assert np.array_equal(got[0], vals < t)


def _step_both(count, batch, nonce_base, n_nonces, start=0, max_hits=8):
    """Run the compacted prove step through Pallas (interpret) and XLA on
    the same padded batch; return both (counts, decoded hits) sets."""
    import jax.numpy as jnp

    idx = np.arange(start, start + batch, dtype=np.uint64)
    labels = scrypt.scrypt_labels(COMMIT, idx[:count], n=2)
    padded = np.concatenate(
        [labels, np.zeros((batch - count, labels.shape[1]), labels.dtype)])
    t = proving.threshold_u32(120, count)
    cw = jnp.asarray(proving.challenge_words(CH))
    lo, hi = scrypt.split_indices(idx)
    args = (cw, jnp.uint32(nonce_base), jnp.asarray(lo), jnp.asarray(hi),
            jnp.asarray(scrypt.labels_to_words(padded)), jnp.uint32(t))
    tail = (jnp.uint32(count), jnp.uint32(start & 0xFFFFFFFF),
            jnp.uint32(start >> 32))
    out = []
    for step in (proving.prove_scan_step_jit,
                 lambda *a, **kw: proving_pallas.prove_scan_step_pallas(
                     *a, interpret=True, **kw)):
        counts, carry = proving.init_hit_state(n_nonces, max_hits)
        counts, bc, carry = step(*args, counts, carry, *tail,
                                 n_nonces=n_nonces, max_hits=max_hits)
        out.append((np.asarray(counts),
                    [proving.decode_hits(counts, carry, k, max_hits)
                     for k in range(n_nonces)]))
    # ground truth from the scalar host path, restricted to valid lanes
    want_counts, want_hits = [], []
    for k in range(n_nonces):
        vals = proving.proving_hashes(CH, nonce_base + k, idx[:count], labels)
        hits = np.nonzero(vals < t)[0]
        want_counts.append(len(hits))
        want_hits.append([int(start + i) for i in hits[:max_hits]])
    return out, (np.asarray(want_counts), want_hits)


def test_step_equivalence_unaligned_tail():
    # a ragged tail batch (count % LANE_TILE != 0) is padded to the full
    # shape and masked on device; Pallas and XLA must agree bit-for-bit
    # with the host ground truth, with no pad-lane hits leaking in
    (xla, pallas), (want_counts, want_hits) = _step_both(
        count=700, batch=1024, nonce_base=0, n_nonces=4)
    for counts, hits in (xla, pallas):
        assert np.array_equal(counts, want_counts)
        assert hits == want_hits


def test_step_equivalence_window_crossing_group_boundary():
    # nonce window straddling a group boundary (base 24 with 16 nonces
    # covers groups 1 and 2): both kernels must key every nonce correctly
    (xla, pallas), (want_counts, want_hits) = _step_both(
        count=512, batch=512, nonce_base=24, n_nonces=16)
    for counts, hits in (xla, pallas):
        assert np.array_equal(counts, want_counts)
        assert hits == want_hits
