"""Pallas proving-scan kernel vs the XLA reference path (interpret mode)."""

import hashlib

import numpy as np

from spacemesh_tpu.ops import proving, proving_pallas, scrypt

CH = hashlib.sha256(b"pallas-ch").digest()
COMMIT = hashlib.sha256(b"pallas-commit").digest()


def test_pallas_scan_matches_reference():
    total = 1024
    idx = np.arange(total, dtype=np.uint64)
    labels = scrypt.scrypt_labels(COMMIT, idx, n=2)
    t = proving.threshold_u32(200, total)
    got = proving_pallas.proving_scan(CH, 5, idx, labels, t, n_nonces=4,
                                      interpret=True)
    assert got.shape == (4, total)
    assert got.any(), "expected some qualifying labels at this threshold"
    for k in range(4):
        vals = proving.proving_hashes(CH, 5 + k, idx, labels)
        assert np.array_equal(got[k], vals < t), f"nonce {k} mismatch"


def test_pallas_scan_padding():
    # batch not a multiple of the lane tile: wrapper pads + trims
    total = 700
    idx = np.arange(total, dtype=np.uint64)
    labels = scrypt.scrypt_labels(COMMIT, idx, n=2)
    t = proving.threshold_u32(100, total)
    got = proving_pallas.proving_scan(CH, 0, idx, labels, t, n_nonces=2,
                                      interpret=True)
    assert got.shape == (2, total)
    vals = proving.proving_hashes(CH, 0, idx, labels)
    assert np.array_equal(got[0], vals < t)
