"""Native C++ BLAKE3 vs the pure-Python reference implementation.

The Python module is vector-tested elsewhere (test_core.py); here the
native twin must match it bit-for-bit across the shapes that exercise
every tree rule: sub-block, block boundaries, chunk boundaries, deep
merge stacks, keyed mode, and long XOF outputs."""

import os
import random

import pytest

from spacemesh_tpu import native
from spacemesh_tpu.core import hashing


@pytest.fixture(scope="module")
def lib():
    lib = native.load("blake3")
    if lib is None:
        pytest.skip("native toolchain unavailable")
    return lib


def _python_hash(data: bytes, key=None, length=32) -> bytes:
    h = hashing.Hasher(key=key)
    h.update(data)
    return h.digest(length)


SIZES = [0, 1, 31, 32, 63, 64, 65, 127, 128, 512, 1023, 1024, 1025,
         2048, 3072, 4096, 5000, 16384, 31744, 65536 + 17]


def test_native_matches_python_across_tree_shapes(lib):
    rng = random.Random(42)
    for size in SIZES:
        data = bytes(rng.randrange(256) for _ in range(min(size, 4096)))
        data = (data * (size // max(len(data), 1) + 1))[:size]
        assert hashing._hash_oneshot(data, None, 32) == \
            _python_hash(data), f"size {size} diverged"


def test_native_keyed_and_lengths(lib):
    key = bytes(range(32))
    for size in (0, 65, 1024, 4097):
        data = b"\xab" * size
        for length in (20, 32, 64, 131):
            want = _python_hash(data, key=key, length=length)
            got = hashing._hash_oneshot(data, key, length)
            assert got == want, (size, length)


def test_api_functions_use_native(lib):
    # sum256/sum160/keyed concatenate chunks before dispatch
    a, b = b"hello ", b"world" * 300
    assert hashing.sum256(a, b) == _python_hash(a + b)
    assert hashing.sum160(a, b) == _python_hash(a + b, length=20)
    key = b"k" * 32
    assert hashing.keyed(key, a, b) == _python_hash(a + b, key=key)


def test_native_is_actually_fast(lib):
    import time

    data = b"x" * 512
    n = 20_000
    t0 = time.perf_counter()
    for i in range(n):
        hashing.sum256(data)
    rate = n / (time.perf_counter() - t0)
    # pure python runs ~650/s; native must be orders beyond it
    assert rate > 20_000, f"native path too slow: {rate:,.0f}/s"


def test_rebuild_on_stale_lib(tmp_path):
    """build.py recompiles when the source is newer than the .so."""
    src = native._DIR / "blake3.cpp"
    lib_path = native._DIR / "libsmtpu_blake3.so"
    if not lib_path.exists():
        pytest.skip("no prior build")
    os.utime(src)  # source now newer
    assert native._build("blake3") is not None
    assert lib_path.stat().st_mtime >= src.stat().st_mtime
