"""Crash-safe POST storage (ISSUE 14): deterministic disk-fault
injection, fsync discipline, and verified recovery.

The acceptance harness sweeps a crash injection across EVERY write-path
op site of a tiny init (power-cut and torn-write variants) and asserts
each restart converges — without manual intervention — to a store
bit-identical (sha256) to an uninjected run. No test sleeps: faults
fire at exact operation counts (post/faultfs.py). ENOSPC must degrade
(post.store probe + /readyz) and resume, never kill; metadata
corruption is a typed error; every durable-persistence helper in
utils/fsio.py survives a simulated power cut mid-save.
"""

from __future__ import annotations

import errno
import hashlib
import json
import os
import threading
import time
import zlib
from pathlib import Path
from unittest import mock

import pytest

from spacemesh_tpu.obs import health as health_mod
from spacemesh_tpu.post import faultfs, initializer
from spacemesh_tpu.post.data import (LabelStore, LabelWriteError,
                                     PostMetaCorrupt, PostMetadata,
                                     recover_store)
from spacemesh_tpu.utils import fsio, metrics, tracing

NODE = hashlib.sha256(b"crash-node").digest()
COMMIT = hashlib.sha256(b"crash-commitment").digest()

TOTAL = 256
BATCH = 128
N = 2
FILE_BYTES = 2048  # 128 labels per file -> 2 files


def _init_kwargs(**over):
    kw = dict(node_id=NODE, commitment=COMMIT, num_units=1,
              labels_per_unit=TOTAL, scrypt_n=N, max_file_size=FILE_BYTES,
              batch_size=BATCH, writers=1, mesh=None, save_barrier=True,
              meta_interval_s=1e9, meta_interval_labels=BATCH)
    kw.update(over)
    return kw


def _store_state(d):
    meta = PostMetadata.load(d)
    store = LabelStore(d, meta)
    try:
        sha = hashlib.sha256(
            store.read_labels(0, meta.total_labels)).hexdigest()
    finally:
        store.close()
    return sha, meta.vrf_nonce, meta.vrf_nonce_value


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """Uninjected init through a counting FaultFS: ground truth sha256
    plus the total mutating-op count that defines the crash sites."""
    d = tmp_path_factory.mktemp("crash-ref")
    fs = faultfs.FaultFS()
    initializer.initialize(d, fs=fs, **_init_kwargs())
    assert not fs.injected
    return d, _store_state(d), fs.write_ops


# --- the acceptance sweep -------------------------------------------------


def test_crash_sweep_every_op_site_bit_identical(tmp_path, reference):
    """For EVERY write-path op index, in power-cut and torn-write
    variants: crash at exactly that op, reboot (un-fsynced bytes and
    un-committed renames vanish), reopen, resume — the completed store
    must be bit-identical to the uninjected reference. Deterministic:
    no sleeps, faults at exact op counts."""
    _, ref_state, total_ops = reference
    assert total_ops > 0
    failures = []
    for op in range(1, total_ops + 1):
        for kind in ("powercut", "torn"):
            d = tmp_path / f"crash-{op}-{kind}"
            plan = faultfs.FaultPlan(
                [faultfs.FaultSpec(op=op, kind=kind)], seed=11)
            fs = faultfs.FaultFS(plan)
            crashed = 0
            for _ in range(5):
                try:
                    initializer.initialize(d, fs=fs, **_init_kwargs())
                    break
                except BaseException as e:  # noqa: BLE001 — PowerCut behind pool errors
                    assert faultfs.power_cut_behind(e) is not None, \
                        f"op {op} {kind}: non-powercut failure {e!r}"
                    fs.reboot()
                    crashed += 1
            else:
                failures.append((op, kind, "did not converge"))
                continue
            assert crashed >= 1, \
                f"op {op} {kind}: fault never surfaced ({fs.injected})"
            if _store_state(d) != ref_state:
                failures.append((op, kind, "store diverged"))
    assert not failures, failures


def test_recovery_emits_span_and_metrics(tmp_path, reference):
    """init.recover spans and post_store_recovery_* /
    post_store_fault_injections_total move when a crash is repaired."""
    _, ref_state, total_ops = reference
    inj0 = sum(metrics.post_store_fault_injections.sample().values())
    rec0 = sum(metrics.post_store_recovery_runs.sample().values())
    tracing.start(capacity=16384)
    try:
        plan = faultfs.FaultPlan(
            [faultfs.FaultSpec(op=max(total_ops - 2, 1),
                               kind="powercut")], seed=5)
        fs = faultfs.FaultFS(plan)
        with pytest.raises(BaseException) as ei:
            initializer.initialize(tmp_path, fs=fs, **_init_kwargs())
        assert faultfs.power_cut_behind(ei.value) is not None
        fs.reboot()
        initializer.initialize(tmp_path, fs=fs, **_init_kwargs())
        doc = tracing.export()
        names = {e["name"] for e in doc["traceEvents"]}
        assert "init.recover" in names
    finally:
        tracing.stop()
    assert _store_state(tmp_path) == ref_state
    assert sum(metrics.post_store_fault_injections.sample().values()) \
        > inj0
    assert sum(metrics.post_store_recovery_runs.sample().values()) >= rec0


def test_eio_in_writer_fails_typed_and_resumes(tmp_path, reference):
    """A non-ENOSPC disk error still fails the run (typed, with errno),
    and the next open resumes to a bit-identical store."""
    _, ref_state, _ = reference
    plan = faultfs.FaultPlan(
        [faultfs.FaultSpec(op=1, kind="eio")], seed=2)
    fs = faultfs.FaultFS(plan)
    with pytest.raises(LabelWriteError) as ei:
        initializer.initialize(tmp_path, fs=fs, **_init_kwargs())
    assert ei.value.errno == errno.EIO
    initializer.initialize(tmp_path, fs=fs, **_init_kwargs())
    assert _store_state(tmp_path) == ref_state


def test_short_writes_are_retried_to_completion(tmp_path, reference):
    """A POSIX short write (faultfs 'short') is looped by write_labels,
    not surfaced: the run completes first try, bit-identical."""
    _, ref_state, _ = reference
    plan = faultfs.FaultPlan(
        [faultfs.FaultSpec(op=1, kind="short")], seed=9)
    fs = faultfs.FaultFS(plan)
    initializer.initialize(tmp_path, fs=fs, **_init_kwargs())
    assert [e["kind"] for e in fs.injected] == ["short"]
    assert _store_state(tmp_path) == ref_state


# --- ENOSPC: degraded, not dead ------------------------------------------


def test_enospc_degrades_readyz_then_resumes(tmp_path, reference):
    """ENOSPC mid-init parks the pipeline: the post.store probe (and a
    HealthEngine /readyz report) flips degraded WITHOUT process exit,
    and init resumes to bit-identical completion when the fault plan
    releases space. Deterministic: the hold window is measured in ops
    (every retry advances the counter), sampled from the injection
    hook — no sleeps beyond the writer's own 10ms retry interval."""
    _, ref_state, _ = reference
    waits0 = sum(metrics.post_store_enospc_waits.sample().values())
    engine = health_mod.HealthEngine(time_source=lambda: 1000.0)
    seen = []

    def on_inject(spec, n):
        if spec.kind != "enospc" or len(seen) > 3:
            return
        report = engine.tick(1000.0)
        ent = report["components"].get("post.store")
        if ent is not None:
            seen.append((report["ready"], ent["healthy"], ent["reason"]))

    plan = faultfs.FaultPlan(
        [faultfs.FaultSpec(op=1, kind="enospc", hold_ops=5)],
        seed=4, on_inject=on_inject)
    fs = faultfs.FaultFS(plan)
    initializer.initialize(tmp_path, fs=fs, enospc_retry_s=0.01,
                           **_init_kwargs())
    assert _store_state(tmp_path) == ref_state
    # the probe flipped while space was exhausted...
    degraded = [s for s in seen if not s[1]]
    assert degraded, f"post.store never flipped degraded: {seen}"
    assert not degraded[0][0], "/readyz stayed ready through ENOSPC"
    assert "enospc" in degraded[0][2]
    assert sum(metrics.post_store_enospc_waits.sample().values()) > waits0
    # ...and cleared with the session
    assert "post.store" not in health_mod.HEALTH.names()
    assert metrics.post_store_degraded.sample().get((), 1.0) == 0.0


def test_enospc_with_full_queue_unblocks_submitters(tmp_path):
    """enospc_wait=False: ENOSPC is a typed failure, and a submitter
    blocked on the FULL queue unblocks with the typed error instead of
    deadlocking against a pool that will never drain it."""
    meta = PostMetadata(node_id=NODE.hex(), commitment=COMMIT.hex(),
                        scrypt_n=N, num_units=1, labels_per_unit=TOTAL,
                        max_file_size=1 << 20)
    store = LabelStore(tmp_path, meta)
    gate = threading.Event()

    def failing(self, start, labels):
        gate.wait(10)
        raise OSError(errno.ENOSPC, "disk full (injected)")

    outcome = []

    with mock.patch.object(LabelStore, "write_labels", failing):
        w = store.start_writer(threads=1, queue_depth=1,
                               enospc_wait=False)
        try:
            w.submit(0, bytes(16))          # worker takes it, parks on gate
            w.submit(1, bytes(16))          # fills the 1-deep queue

            def blocked_submit():
                try:
                    w.submit(2, bytes(16))  # blocks: queue full
                    outcome.append(("queued", None))
                except LabelWriteError as e:
                    outcome.append(("raised", e.errno))

            t = threading.Thread(target=blocked_submit)
            t.start()
            gate.set()                      # ENOSPC lands; pool fails typed
            t.join(timeout=10)
            assert not t.is_alive(), "submitter deadlocked on a dead pool"
            with pytest.raises(LabelWriteError) as ei:
                w.drain()
            assert ei.value.errno == errno.ENOSPC
            assert outcome and outcome[0][0] in ("queued", "raised")
            if outcome[0][0] == "raised":
                assert outcome[0][1] == errno.ENOSPC
        finally:
            gate.set()
            w.close(drain=False)
            store.close()


# --- fsync discipline & interval checksums -------------------------------


def test_durable_means_fsynced(tmp_path):
    """flushed() advances per completed write; durable() only at
    checkpoint/drain boundaries, after the label files are fsynced —
    and the checkpoint hands back the interval CRC the recovery path
    verifies."""
    meta = PostMetadata(node_id=NODE.hex(), commitment=COMMIT.hex(),
                        scrypt_n=N, num_units=1, labels_per_unit=TOTAL,
                        max_file_size=1 << 20)
    store = LabelStore(tmp_path, meta)
    w = store.start_writer(threads=1)
    try:
        payload = bytes(range(256)) * (BATCH * 16 // 256)
        w.submit(0, payload)
        deadline = time.monotonic() + 10
        while w.flushed() < BATCH:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert w.durable() == 0, "durable advanced without an fsync"
        d, crc = w.checkpoint()
        assert d == BATCH and w.durable() == BATCH
        assert crc == zlib.crc32(payload)
    finally:
        w.close(drain=False)
        store.close()


def test_tail_corruption_rolls_back_to_verified_checkpoint(tmp_path,
                                                           reference):
    """Flip bytes inside the LAST checkpoint interval on disk: reopen
    detects the CRC mismatch, truncates back to the last verified
    boundary, and the resume recomputes to a bit-identical store."""
    _, ref_state, _ = reference
    initializer.initialize(tmp_path, **_init_kwargs())
    meta = PostMetadata.load(tmp_path)
    assert len(meta.intervals) >= 2, meta.intervals
    first_end = meta.intervals[0][0]
    # corrupt a byte past the first checkpoint (in the last interval)
    lpf = meta.labels_per_file
    fi, within = divmod(first_end, lpf)
    path = tmp_path / f"postdata_{fi}.bin"
    raw = bytearray(path.read_bytes())
    raw[within * 16] ^= 0xFF
    path.write_bytes(raw)

    report = recover_store(tmp_path, meta)
    assert report.intervals_dropped >= 1
    assert report.cursor == first_end
    assert meta.labels_written == first_end

    initializer.initialize(tmp_path, **_init_kwargs())
    assert _store_state(tmp_path) == ref_state


def test_bytes_past_cursor_are_truncated(tmp_path, reference):
    """Garbage appended past the durable cursor (a torn flush that beat
    the crash) is truncated on reopen; extra files wholly past the
    cursor are removed."""
    _, ref_state, _ = reference
    initializer.initialize(tmp_path, **_init_kwargs())
    meta = PostMetadata.load(tmp_path)
    # roll the claim back one interval, then fake torn bytes beyond it
    meta.intervals.pop()
    meta.labels_written = meta.intervals[-1][0]
    meta.save(tmp_path)
    last = sorted(tmp_path.glob("postdata_*.bin"))[-1]
    with open(last, "ab") as fh:
        fh.write(b"\x99" * 7)  # a torn, non-record-aligned tail
    stray = tmp_path / "postdata_9.bin"
    stray.write_bytes(b"\x77" * 64)

    meta2 = PostMetadata.load(tmp_path)
    report = recover_store(tmp_path, meta2)
    assert report.truncated_bytes > 0
    assert report.removed_files >= 1
    assert not stray.exists()

    initializer.initialize(tmp_path, **_init_kwargs())
    assert _store_state(tmp_path) == ref_state


def test_legacy_metadata_without_intervals_backfills(tmp_path, reference,
                                                     monkeypatch):
    """A pre-checksum store (no intervals ledger) is trusted as-is and
    its ledger backfilled in BOUNDED segments — a single whole-store
    interval would make every later reopen's tail verification a
    full-store scan."""
    from spacemesh_tpu.post import data as data_mod

    _, ref_state, _ = reference
    initializer.initialize(tmp_path, **_init_kwargs())
    meta = PostMetadata.load(tmp_path)
    meta.intervals = []
    meta.save(tmp_path)
    monkeypatch.setattr(data_mod, "BACKFILL_INTERVAL_LABELS", BATCH)
    meta2 = PostMetadata.load(tmp_path)
    recover_store(tmp_path, meta2)
    assert meta2.intervals and meta2.intervals[-1][0] == TOTAL
    assert len(meta2.intervals) == TOTAL // BATCH, meta2.intervals
    # the backfilled ledger verifies on the next reopen
    meta3 = PostMetadata.load(tmp_path)
    report = recover_store(tmp_path, meta3)
    assert report.intervals_dropped == 0
    assert report.verified_labels <= BATCH  # tail segment only
    assert _store_state(tmp_path) == ref_state


def test_fresh_dir_with_stray_label_files_is_wiped(tmp_path, reference):
    """Crash before the first metadata save: label bytes with no durable
    claim are wiped, and the fresh init converges bit-identically."""
    _, ref_state, _ = reference
    (tmp_path / "postdata_0.bin").write_bytes(b"\x55" * 333)
    initializer.initialize(tmp_path, **_init_kwargs())
    assert _store_state(tmp_path) == ref_state


def test_read_fd_cache_invalidated_across_recovery(tmp_path):
    """A cached read fd pins the pre-recovery inode; recovery must
    invalidate the cache so later reads see the repaired file, not the
    unlinked one."""
    initializer.initialize(tmp_path, **_init_kwargs())
    meta = PostMetadata.load(tmp_path)
    store = LabelStore(tmp_path, meta)
    good = store.read_labels(0, TOTAL)  # caches one fd per file
    # replace file 0 with a NEW inode: same first interval, garbage tail
    lpf = meta.labels_per_file
    f0 = tmp_path / "postdata_0.bin"
    os.unlink(f0)
    f0.write_bytes(good[:lpf * 16])
    f1 = tmp_path / "postdata_1.bin"
    os.unlink(f1)
    f1.write_bytes(b"\x13" * (TOTAL - lpf) * 16)

    report = recover_store(tmp_path, meta, store=store)
    assert report.intervals_dropped >= 1  # garbage tail failed its CRC
    assert meta.labels_written == lpf
    # prove the cache really dropped: a direct write to the CURRENT
    # inode must be visible through the store
    with open(f0, "r+b") as fh:
        fh.write(b"\xEE" * 16)
    assert store.read_labels(0, 1) == b"\xEE" * 16, \
        "cached fd served the pre-recovery inode"
    store.close()


# --- typed metadata errors & staging cleanup ------------------------------


def test_corrupt_metadata_raises_typed(tmp_path):
    p = tmp_path / "postdata_metadata.json"
    p.write_text('{"node_id": "ab", "trunca')  # torn JSON
    with pytest.raises(PostMetaCorrupt) as ei:
        PostMetadata.load(tmp_path)
    assert str(p) in str(ei.value)
    assert ei.value.path == str(p)
    p.write_text('{"unexpected_key": 1}')  # parseable, wrong schema
    with pytest.raises(PostMetaCorrupt):
        PostMetadata.load(tmp_path)
    p.write_text('["not", "an", "object"]')
    with pytest.raises(PostMetaCorrupt):
        PostMetadata.load(tmp_path)


def test_stale_staging_tmps_removed_on_load(tmp_path):
    meta = PostMetadata(node_id=NODE.hex(), commitment=COMMIT.hex(),
                        scrypt_n=N, num_units=1, labels_per_unit=TOTAL,
                        max_file_size=1 << 20)
    meta.save(tmp_path)
    stale_new = tmp_path / "postdata_metadata.json.tmp.9999"
    stale_legacy = tmp_path / "postdata_metadata.tmp"
    stale_new.write_text("{half-written")
    stale_legacy.write_text("{older-half-written")
    loaded = PostMetadata.load(tmp_path)
    assert loaded.labels_written == 0
    assert not stale_new.exists() and not stale_legacy.exists()


def test_powercut_mid_metadata_save_keeps_old_content(tmp_path):
    """The fsio contract end-to-end: a power cut anywhere inside the
    durable save sequence leaves the OLD metadata intact after reboot
    (possibly plus a stray tmp, which the next load clears)."""
    meta = PostMetadata(node_id=NODE.hex(), commitment=COMMIT.hex(),
                        scrypt_n=N, num_units=1, labels_per_unit=TOTAL,
                        max_file_size=1 << 20, labels_written=42)
    meta.save(tmp_path)
    # a save is pwrite + fsync + replace + fsync_dir = 4 mutating ops
    for op in range(1, 5):
        plan = faultfs.FaultPlan(
            [faultfs.FaultSpec(op=op, kind="powercut")], seed=1)
        fs = faultfs.FaultFS(plan)
        meta2 = PostMetadata(node_id=NODE.hex(), commitment=COMMIT.hex(),
                             scrypt_n=N, num_units=1,
                             labels_per_unit=TOTAL,
                             max_file_size=1 << 20, labels_written=777)
        with pytest.raises(faultfs.PowerCut):
            meta2.save(tmp_path, fs=fs)
        fs.reboot()
        assert PostMetadata.load(tmp_path).labels_written == 42, \
            f"op {op}: old metadata not intact after reboot"
    # and with no fault, the new content lands
    meta2 = PostMetadata.load(tmp_path)
    meta2.labels_written = 777
    meta2.save(tmp_path)
    assert PostMetadata.load(tmp_path).labels_written == 777


def test_persist_directory_fsyncs_contained_files(tmp_path):
    """fsio.persist on a directory payload (flight bundles) must fsync
    every file INSIDE before the rename — fsyncing only the directory
    inode makes the names durable while the data can still be lost."""
    src = tmp_path / "bundle.tmp"
    src.mkdir()
    (src / "manifest.json").write_text("m")
    (src / "trace.json").write_text("t")
    fs = faultfs.FaultFS()
    fsio.persist(src, tmp_path / "bundle", fs=fs)
    # 2 file fsyncs + tmp-dir fsync + rename + parent-dir fsync
    assert fs.write_ops == 5, fs.write_ops
    assert (tmp_path / "bundle" / "manifest.json").read_text() == "m"


def test_scheduler_resume_preserves_checkpoint_ledger(tmp_path, reference):
    """A scheduler-finalized resume must extend the checkpoint ledger
    to cover the cursor it persists — a cursor ahead of a stale ledger
    would be rolled BACK (durable labels truncated) by the next
    reopen's recovery."""
    from spacemesh_tpu.runtime import TenantScheduler

    _, ref_state, _ = reference
    # phase 1: a partial Initializer session leaves cursor + ledger
    meta = initializer.open_or_create_meta(
        tmp_path, node_id=NODE, commitment=COMMIT, num_units=1,
        labels_per_unit=TOTAL, scrypt_n=N, max_file_size=FILE_BYTES)
    init = initializer.Initializer(
        tmp_path, meta, batch_size=BATCH, writers=1, mesh=None,
        inflight=1, save_barrier=True, meta_interval_s=1e9,
        meta_interval_labels=BATCH,
        progress=lambda done, total: init.stop())
    init.run()
    partial = PostMetadata.load(tmp_path)
    assert 0 < partial.labels_written < TOTAL and partial.intervals

    # phase 2: the scheduler's packed path finishes the store
    with TenantScheduler(workers=2, pack_lanes=BATCH) as sched:
        sched.register_tenant("t")
        try:
            sched.submit_init(
                "t", tmp_path, node_id=NODE, commitment=COMMIT,
                num_units=1, labels_per_unit=TOTAL, scrypt_n=N,
                max_file_size=FILE_BYTES).result(timeout=300)
        finally:
            sched.unregister_tenant("t")
    done = PostMetadata.load(tmp_path)
    assert done.labels_written == TOTAL
    assert done.intervals[-1][0] == TOTAL, \
        f"ledger {done.intervals} does not cover the cursor"

    # phase 3: reopen recovery must keep every durable label
    report = recover_store(tmp_path, PostMetadata.load(tmp_path))
    assert report.rolled_back_labels == 0
    assert report.truncated_bytes == 0
    assert _store_state(tmp_path) == ref_state


def test_atomic_write_survives_powercut_after_dir_fsync(tmp_path):
    """Once the dir fsync retires, the new payload IS durable."""
    target = tmp_path / "winners.json"
    target.write_text("old")
    plan = faultfs.FaultPlan(
        [faultfs.FaultSpec(op=5, kind="powercut")], seed=1)
    fs = faultfs.FaultFS(plan)
    fsio.atomic_write_text(target, "new", fs=fs)  # 4 ops: completes
    fs.reboot()
    assert target.read_text() == "new"


# --- prover-side read resilience ------------------------------------------


def test_prover_reads_retry_transient_eio(tmp_path, reference):
    _, ref_state, _ = reference
    retries0 = sum(metrics.post_store_read_retries.sample().values())
    initializer.initialize(tmp_path, **_init_kwargs())
    meta = PostMetadata.load(tmp_path)
    plan = faultfs.FaultPlan(
        [faultfs.FaultSpec(op=1, kind="eio", on="read")], seed=1)
    fs = faultfs.FaultFS(plan)
    store = LabelStore(tmp_path, meta, fs=fs)
    try:
        got = store.read_labels(0, TOTAL)
    finally:
        store.close()
    assert hashlib.sha256(got).hexdigest() == ref_state[0]
    assert [e["kind"] for e in fs.injected] == ["eio"]
    assert sum(metrics.post_store_read_retries.sample().values()) \
        > retries0


# --- the sim scenario (CI pins --repeat 2 digest equality) ----------------


def test_crash_recovery_scenario_replays_byte_identical():
    from spacemesh_tpu.sim import crash_recovery as crashrec
    from spacemesh_tpu.sim.scenarios import builtin

    script = builtin("crash-recovery", seed=3)
    script["crash_every"] = 7  # bounded sweep keeps tier-1 fast
    r1 = crashrec.run_scenario(script)
    r2 = crashrec.run_scenario(script)
    assert r1.ok, [a for a in r1.asserts if not a["ok"]]
    assert r1.digest == r2.digest, "crash-recovery digest not replay-stable"
    kinds = {a["kind"] for a in r1.asserts}
    assert {"bit_identical", "recovered", "enospc_degraded",
            "fault_metrics"} <= kinds
    json.loads(r1.to_json())  # CLI-serializable


def test_scenario_registry_lists_crash_recovery():
    from spacemesh_tpu.sim.scenarios import builtin_names

    assert "crash-recovery" in builtin_names()
