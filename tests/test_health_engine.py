"""Health & SLO engine (spacemesh_tpu/obs/): stall watchdogs, SLO burn
accounting, the flight recorder, trace-correlated JSON logs, the
/healthz //readyz //debug/flight surface, and the ISSUE 7 acceptance
capture — one init+prove+farm run with the engine enabled, every timing
assertion driven by an injected clock, zero sleeps."""

import asyncio
import io
import json
import logging as pylogging
import threading
from types import SimpleNamespace

import pytest
from aiohttp import ClientSession

from spacemesh_tpu.api.http import ApiServer
from spacemesh_tpu.node import events as events_mod
from spacemesh_tpu.obs import flight as flight_mod
from spacemesh_tpu.obs import health as health_mod
from spacemesh_tpu.obs import sli as sli_mod
from spacemesh_tpu.utils import logging as slog
from spacemesh_tpu.utils import metrics as metrics_mod
from spacemesh_tpu.utils import tracing

from test_http_debug import parse_exposition


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


# --- watchdogs ----------------------------------------------------------


def test_watchdog_progress_stall_idle_rebaseline():
    v = {"n": 0, "active": True}
    wd = health_mod.Watchdog("x", progress=lambda: v["n"],
                             deadline_s=5.0, active=lambda: v["active"])
    assert wd.check(0.0)[0]
    assert wd.check(4.0)[0]                      # quiet but in deadline
    ok, reason = wd.check(6.0)
    assert not ok and "stalled" in reason and "6.0s" in reason
    v["n"] = 1
    assert wd.check(7.0)[0]                      # progress heals
    v["active"] = False
    ok, reason = wd.check(100.0)
    assert ok and reason == "idle"
    v["active"] = True
    # first check after re-activation re-baselines: a long-idle
    # component is not instantly accused of a 93s stall
    assert wd.check(200.0)[0]
    assert not wd.check(206.0)[0]


def test_watchdog_raising_probe_is_unhealthy():
    def boom():
        raise RuntimeError("dead counter")

    wd = health_mod.Watchdog("x", progress=boom, deadline_s=1.0)
    ok, reason = wd.check(0.0)
    assert not ok and "probe raised" in reason


def test_registry_register_replace_unregister():
    reg = health_mod.HealthRegistry()
    probe_a = lambda now: (True, "a")  # noqa: E731
    probe_b = lambda now: (False, "b")  # noqa: E731
    reg.register("c", probe_a)
    reg.register("c", probe_b)                   # replace
    assert reg.report(0.0)["c"] == {"healthy": False, "reason": "b"}
    reg.unregister("c", probe_a)                 # stale unregister: no-op
    assert reg.names() == ["c"]
    reg.unregister("c", probe_b)
    assert reg.names() == []

    def raising(now):
        raise ValueError("probe bug")

    reg.register("r", raising)
    assert not reg.report(0.0)["r"]["healthy"]


# --- SLO burn + engine transitions --------------------------------------


def _engine(tmp_path, fake, budget=0.0, window_s=30.0):
    reg = metrics_mod.Registry()
    lat = reg.histogram("lat", buckets=(0.01, 0.1, 1.0, float("inf")))
    bus = events_mod.EventBus()
    spec = sli_mod.SliSpec("lat_p95", "lat", "quantile", q=0.95)
    slo = health_mod.Slo(name="latency", sli="lat_p95", target=0.1,
                         window_s=window_s, budget=budget)
    engine = health_mod.HealthEngine(
        registry=reg, health=health_mod.HealthRegistry(), bus=bus,
        slis=[spec], slos=[slo], window_s=window_s,
        spool_dir=tmp_path / "flight", time_source=fake)
    return engine, lat, bus


def test_slo_breach_transition_event_metric_flight(tmp_path):
    fake = FakeClock()
    engine, lat, bus = _engine(tmp_path, fake)
    sub = bus.subscribe(events_mod.SloBreach, size=16)
    engine.tick()                                 # baseline snapshot
    for _ in range(20):
        lat.observe(0.005)
    fake.advance(5.0)
    rep = engine.tick()
    assert rep["slos"]["latency"]["breached"] is False
    assert rep["slos"]["latency"]["value"] <= 0.1
    before = metrics_mod.slo_breaches.sample().get((("slo", "latency"),), 0)
    for _ in range(20):
        lat.observe(0.5)                          # violating era
    fake.advance(5.0)
    rep = engine.tick()
    assert rep["slos"]["latency"]["breached"] is True
    assert rep["slos"]["latency"]["value"] > 0.1
    # transition artifacts: one counter inc, one bus event, one bundle
    after = metrics_mod.slo_breaches.sample().get((("slo", "latency"),), 0)
    assert after - before == 1
    ev = sub.queue.get_nowait()
    assert ev.slo == "latency" and ev.value > 0.1
    bundles = engine.recorder.bundles()
    assert len(bundles) == 1
    manifest = json.loads((bundles[0] / "manifest.json").read_text())
    assert manifest["reason"] == "slo:latency"
    # a second tick while still breached is NOT a new transition
    fake.advance(1.0)
    engine.tick()
    assert metrics_mod.slo_breaches.sample().get(
        (("slo", "latency"),), 0) == after
    # violating marks age out of the window -> recovery
    fake.advance(40.0)
    rep = engine.tick()
    assert rep["slos"]["latency"]["breached"] is False


def test_slo_budget_tolerates_brief_violation(tmp_path):
    fake = FakeClock()
    engine, lat, bus = _engine(tmp_path, fake, budget=0.5, window_s=30.0)
    engine.tick()
    for _ in range(5):
        lat.observe(0.5)
    fake.advance(5.0)
    rep = engine.tick()                           # violating, burn ~0
    assert rep["slos"]["latency"]["breached"] is False
    # stay violating long enough to burn past half the window
    for _ in range(4):
        for _ in range(5):
            lat.observe(0.5)
        fake.advance(5.0)
        rep = engine.tick()
    assert rep["slos"]["latency"]["burn"] > 0.5
    assert rep["slos"]["latency"]["breached"] is True


def test_burn_freezes_when_sli_goes_unknown(tmp_path):
    """A violating era followed by idleness: once the SLI window empties
    (value None) the stale violating mark must stop accruing burn — one
    bad tick plus silence is not a breach."""
    fake = FakeClock()
    reg = metrics_mod.Registry()
    lat = reg.histogram("lat", buckets=(0.01, 0.1, 1.0, float("inf")))
    spec = sli_mod.SliSpec("lat_p95", "lat", "quantile", q=0.95)
    slo = health_mod.Slo(name="latency", sli="lat_p95", target=0.1,
                         window_s=60.0, budget=0.3)
    engine = health_mod.HealthEngine(
        registry=reg, health=health_mod.HealthRegistry(),
        slis=[spec], slos=[slo], window_s=10.0,   # short SLI window
        time_source=fake)
    engine.tick()
    for _ in range(5):
        lat.observe(0.5)                          # one violating burst
    burns = []
    for _ in range(10):                           # 50s of idle ticking
        fake.advance(5.0)
        rep = engine.tick()
        burns.append(rep["slos"]["latency"]["burn"])
    # the burst ages out of the 10s SLI window after ~2 ticks; burn must
    # freeze at the observed violating time (~10s/60s), never trend to 1
    assert max(burns) < 0.3, burns
    assert rep["slos"]["latency"]["breached"] is False
    assert burns[-1] <= burns[2]


def test_flight_failed_dump_does_not_arm_rate_limit(tmp_path):
    fake = FakeClock()
    spool = tmp_path / "spool"
    spool.parent.mkdir(parents=True, exist_ok=True)
    spool.write_text("a file where the spool dir should be")
    rec = flight_mod.FlightRecorder(spool, min_interval_s=60,
                                    time_source=fake)
    assert rec.dump("slo:x", now=fake()) is None   # mkdir fails: OSError
    spool.unlink()                                 # condition clears
    fake.advance(1.0)
    # NOT forced, still within min_interval of the failure — must write
    assert rec.dump("slo:x", now=fake()) is not None


def test_live_tracks_loop_not_request_ticks():
    """Once run() starts, request-driven /readyz ticks must not mask a
    wedged background loop."""
    fake = FakeClock()
    engine = health_mod.HealthEngine(
        registry=metrics_mod.Registry(), health=health_mod.HealthRegistry(),
        slis=[], slos=[], interval_s=5.0, time_source=fake)

    async def drive():
        engine.ensure_running()
        await asyncio.sleep(0)        # run() records _loop_started_at
        assert engine.live()
        fake.advance(60.0)            # loop never ticked (real sleep(5))
        engine.tick()                 # a request-driven evaluation
        assert not engine.live()      # ...does not revive liveness
        engine.close()

    asyncio.run(drive())


def test_component_transition_emits_event_and_metric(tmp_path):
    fake = FakeClock()
    engine, lat, bus = _engine(tmp_path, fake)
    sub = bus.subscribe(events_mod.ComponentHealth, size=16)
    state = {"ok": True}
    engine.health.register(
        "widget", lambda now: (state["ok"], "because"))
    engine.tick()
    state["ok"] = False
    fake.advance(1.0)
    rep = engine.tick()
    assert rep["ready"] is False
    ev = sub.queue.get_nowait()
    assert ev.component == "widget" and ev.healthy is False
    state["ok"] = True
    fake.advance(1.0)
    assert engine.tick()["ready"] is True
    assert sub.queue.get_nowait().healthy is True
    # unregistered probes drop out of the report AND the gauge: a
    # finished component must not pin component_healthy{...}=0 forever
    state["ok"] = False
    fake.advance(1.0)
    engine.tick()
    engine.health.unregister("widget")
    fake.advance(1.0)
    assert "widget" not in engine.tick()["components"]
    assert (("component", "widget"),) not in \
        metrics_mod.component_healthy.sample()


# --- flight recorder ----------------------------------------------------


def test_flight_recorder_rate_limit_force_prune(tmp_path):
    fake = FakeClock()
    rec = flight_mod.FlightRecorder(tmp_path / "spool", min_interval_s=60,
                                    keep=2, time_source=fake)
    p1 = rec.dump("slo:first", now=fake())
    assert p1 is not None and p1.is_dir()
    assert rec.dump("slo:second", now=fake.advance(10)) is None  # limited
    p3 = rec.dump("manual", now=fake(), force=True)              # bypass
    assert p3 is not None
    p4 = rec.dump("stall:late", now=fake.advance(120))
    assert p4 is not None
    assert len(rec.bundles()) == 2                # keep=2 pruned oldest
    bundle = flight_mod.read_bundle(p4)
    assert bundle["manifest"]["reason"] == "stall:late"
    tracing.validate(bundle["trace"])             # idempotent revalidate
    assert bundle["metrics_samples"] > 0
    doc = flight_mod.digest(bundle)
    assert doc["reason"] == "stall:late"


def test_flight_read_bundle_rejects_corruption(tmp_path):
    rec = flight_mod.FlightRecorder(tmp_path / "spool")
    p = rec.dump("manual", force=True)
    (p / "trace.json").write_text('{"traceEvents": [{"bad": 1}]}')
    with pytest.raises(ValueError):
        flight_mod.read_bundle(p)
    with pytest.raises(FileNotFoundError):
        flight_mod.read_bundle(tmp_path / "nope")


def test_flight_events_serialize_bytes(tmp_path):
    bus = events_mod.EventBus()
    bus.emit(events_mod.AtxEvent(atx_id=b"\xab" * 4, node_id=b"\x01" * 4,
                                 epoch=3))
    rec = flight_mod.FlightRecorder(tmp_path / "spool")
    p = rec.dump("manual", force=True, events=list(bus.recent))
    evs = json.loads((p / "events.json").read_text())
    assert evs[-1]["type"] == "AtxEvent"
    assert evs[-1]["event"]["atx_id"] == "ab" * 4


# --- trace-correlated JSON logs -----------------------------------------


def test_json_log_lines_carry_span_id():
    root = pylogging.getLogger(slog.ROOT)
    saved = root.handlers[:]
    root.handlers = []
    buf = io.StringIO()
    tracing.stop()
    try:
        slog.configure(json_lines=True, stream=buf)
        tracing.start(capacity=64)
        log = slog.get("health")
        with tracing.span("health.tick") as sp:
            log.warning("SLO breach: %s", "latency")
        log.warning("outside any span")
        lines = [json.loads(l) for l in buf.getvalue().splitlines()]
        assert lines[0]["msg"] == "SLO breach: latency"
        assert lines[0]["level"] == "WARNING"
        assert lines[0]["logger"] == "smtpu.health"
        assert lines[0]["span"] == sp.id          # -> Perfetto args.id
        assert "span" not in lines[1]
        # the span id in the log line exists in the trace export
        doc = tracing.export()
        assert any(e["args"].get("id") == sp.id
                   for e in doc["traceEvents"] if e.get("args"))
    finally:
        tracing.stop()
        root.handlers = saved


def test_log_json_env_knob(monkeypatch):
    monkeypatch.setenv("SPACEMESH_LOG_JSON", "1")
    assert slog.json_mode_enabled()
    monkeypatch.setenv("SPACEMESH_LOG_JSON", "off")
    assert not slog.json_mode_enabled()


# --- HTTP surface -------------------------------------------------------


def _with_server(api, coro):
    async def run():
        port = await api.start()
        base = f"http://127.0.0.1:{port}"
        try:
            async with ClientSession() as s:
                return await coro(s, base)
        finally:
            await api.stop()

    return asyncio.run(run())


def test_http_health_surface(tmp_path):
    fake = FakeClock()
    reg = metrics_mod.Registry()
    engine = health_mod.HealthEngine(
        registry=reg, health=health_mod.HealthRegistry(), slis=[],
        slos=[], spool_dir=tmp_path / "flight", time_source=fake)
    state = {"ok": True}
    engine.health.register("widget", lambda now: (state["ok"], "r"))
    node = SimpleNamespace(health_engine=engine)
    api = ApiServer(node, listen="127.0.0.1:0")

    async def go(s, base):
        healthz = await (await s.get(f"{base}/healthz")).json()
        ready_r = await s.get(f"{base}/readyz")
        ready = (ready_r.status, await ready_r.json())
        state["ok"] = False
        fake.advance(1.0)
        bad_r = await s.get(f"{base}/readyz")
        bad = (bad_r.status, await bad_r.json())
        flight_r = await s.post(f"{base}/debug/flight?reason=op-request")
        return healthz, ready, bad, (flight_r.status,
                                     await flight_r.json())

    healthz, ready, bad, flight = _with_server(api, go)
    assert healthz["status"] == "ok" and healthz["engine"] is True
    assert ready[0] == 200 and ready[1]["ready"] is True
    assert bad[0] == 503
    assert bad[1]["components"]["widget"] == {"healthy": False,
                                              "reason": "r"}
    assert flight[0] == 200
    assert flight[1]["reason"] == "op-request"
    bundle = flight_mod.read_bundle(flight[1]["bundle"])
    assert bundle["manifest"]["reason"] == "op-request"


def test_http_health_without_engine():
    """Stub embedders without an engine: alive, and /readyz still
    answers from the global health registry."""
    api = ApiServer(SimpleNamespace(), listen="127.0.0.1:0")

    async def go(s, base):
        h = await s.get(f"{base}/healthz")
        r = await s.get(f"{base}/readyz")
        f = await s.post(f"{base}/debug/flight")
        return (h.status, await h.json()), (r.status, await r.json()), \
            f.status

    (hs, hj), (rs, rj), fs = _with_server(api, go)
    assert hs == 200 and hj["engine"] is False
    assert rs in (200, 503) and "components" in rj
    assert fs == 409


def test_healthz_reports_wedged_tick_loop(tmp_path):
    fake = FakeClock()
    engine = health_mod.HealthEngine(
        registry=metrics_mod.Registry(), health=health_mod.HealthRegistry(),
        slis=[], slos=[], interval_s=5.0, time_source=fake)
    engine.tick()
    assert engine.live()
    fake.advance(60.0)                            # 12 intervals of silence
    assert not engine.live()
    api = ApiServer(SimpleNamespace(health_engine=engine),
                    listen="127.0.0.1:0")

    async def go(s, base):
        r = await s.get(f"{base}/healthz")
        return r.status, await r.json()

    status, doc = _with_server(api, go)
    assert status == 503 and doc["status"] == "wedged"


# --- the ISSUE 7 acceptance capture -------------------------------------


@pytest.mark.usefixtures("tmp_path")
def test_acceptance_init_prove_farm_stall_flight(tmp_path):
    """One init+prove+farm run with the engine enabled. Asserts, with no
    sleep anywhere: windowed p99s for >= 3 SLIs; an artificially stalled
    LabelWriter trips its watchdog within its deadline; /readyz reports
    the component unhealthy with a reason; the flight bundle validates
    (trace passes tracing.validate, metrics snapshot parses strictly);
    and ``profiler --flight`` digests it."""
    from spacemesh_tpu.post import workload
    from spacemesh_tpu.post.data import LabelStore, PostMetadata
    from spacemesh_tpu.verify.farm import VerificationFarm

    from test_verify_farm import _sig_reqs

    tracing.stop()
    tracing.start(capacity=65536)
    fake = FakeClock(1000.0)
    bus = events_mod.EventBus()
    engine = health_mod.HealthEngine(
        bus=bus, spool_dir=tmp_path / "flight", window_s=300.0,
        time_source=fake)
    registered_writer = None
    writer = None
    gate = threading.Event()
    try:
        engine.tick()                             # SLI window baseline
        # --- the workload: init + prove + farm -----------------------
        prover = workload.build(str(tmp_path / "post"), labels=2048,
                                batch=512)
        proof = prover.prove(workload.CHALLENGE)
        assert workload.verify_proof(proof, 2048)
        # pipeline watchdogs unregistered cleanly on the way out
        for name in ("post.init", "post.prove", "post.writer"):
            assert name not in health_mod.HEALTH.names()

        async def farm_run():
            farm = VerificationFarm()
            try:
                got = await asyncio.gather(
                    *(farm.submit(r) for r in _sig_reqs(24)))
                assert all(got)
            finally:
                await farm.aclose()

        asyncio.run(farm_run())
        fake.advance(30.0)
        report = engine.tick()
        p99 = {k: v for k, v in report["slis"].items()
               if k.endswith("_p99")}
        assert len(p99) >= 3, report["slis"]
        assert {"prove_window_p99", "farm_queue_wait_p99",
                "farm_dispatch_p99"} <= set(p99)
        assert all(v > 0 for v in p99.values())
        assert report["slis"]["init_labels_per_sec"] > 0

        # --- artificially stalled LabelWriter ------------------------
        meta = PostMetadata(
            node_id="00" * 32, commitment="11" * 32, scrypt_n=2,
            num_units=1, labels_per_unit=256, max_file_size=1 << 20)
        store = LabelStore(tmp_path / "stall", meta)
        store.write_labels = lambda start, labels: gate.wait(60)
        writer = store.start_writer(threads=1, queue_depth=4)
        writer.submit(0, b"\x00" * 16 * 8)        # worker wedges on gate
        wd = health_mod.writer_watchdog(writer, deadline_s=5.0)
        registered_writer = wd.check
        health_mod.HEALTH.register("post.writer", registered_writer)
        assert engine.tick()["components"]["post.writer"]["healthy"]
        fake.advance(4.0)                         # inside the deadline
        assert engine.tick()["components"]["post.writer"]["healthy"]
        fake.advance(2.0)                         # 6s > 5s deadline
        report = engine.tick()
        ent = report["components"]["post.writer"]
        assert ent["healthy"] is False
        assert "stalled" in ent["reason"] and "deadline" in ent["reason"]
        assert report["ready"] is False

        # --- /readyz over HTTP reports it with the reason ------------
        api = ApiServer(SimpleNamespace(health_engine=engine),
                        listen="127.0.0.1:0")

        async def go(s, base):
            r = await s.get(f"{base}/readyz")
            return r.status, await r.json()

        status, doc = _with_server(api, go)
        assert status == 503
        assert doc["components"]["post.writer"]["healthy"] is False
        assert "stalled" in doc["components"]["post.writer"]["reason"]

        # --- the stall transition auto-dumped a flight bundle --------
        bundles = engine.recorder.bundles()
        assert bundles, "stall transition did not spool a bundle"
        bundle = flight_mod.read_bundle(bundles[-1])   # validates trace
        assert "stall:post.writer" in bundle["manifest"]["reason"]
        samples = parse_exposition(
            (bundles[-1] / "metrics.prom").read_text())
        names = {n for n, _, _ in samples}
        assert "post_prove_window_seconds_bucket" in names
        assert "component_healthy" in names
        # the capture in the bundle is the REAL workload's trace
        span_names = {e["name"] for e in bundle["trace"]["traceEvents"]}
        assert {"init.run", "prove.run", "farm.batch"} <= span_names
        # recent events rode along (ComponentHealth transition at least)
        types = {e["type"] for e in bundle["events"]}
        assert "ComponentHealth" in types

        # --- profiler --flight digests it without error --------------
        from spacemesh_tpu.tools import profiler

        assert profiler.main(["--flight", str(bundles[-1])]) == 0
    finally:
        gate.set()
        if writer is not None:
            writer.close(drain=False)
        if registered_writer is not None:
            health_mod.HEALTH.unregister("post.writer", registered_writer)
        tracing.stop()
