"""Storage layer: migrations, per-entity queries, tx semantics, cache."""

import pytest

from spacemesh_tpu.core import types
from spacemesh_tpu.storage import atxs, ballots, blocks, cache, db, layers, misc, transactions


@pytest.fixture
def state():
    return db.open_state()


def _atx(epoch=1, node=b"\x01" * 32, units=4):
    return types.ActivationTx(
        publish_epoch=epoch, prev_atx=bytes(32), pos_atx=bytes(32),
        commitment_atx=None, initial_post=None,
        nipost=types.NIPost(
            membership=types.MerkleProof(leaf_index=0, nodes=[]),
            post=types.Post(nonce=0, indices=[1], pow_nonce=0),
            post_metadata=types.PostMetadataWire(challenge=bytes(32),
                                                 labels_per_unit=64)),
        num_units=units, vrf_nonce=7, vrf_public_key=bytes(32),
        coinbase=bytes(24), node_id=node,
        signature=bytes(64))


def test_atx_roundtrip(state):
    a = _atx()
    atxs.add(state, a, tick_height=100)
    assert atxs.has(state, a.id)
    assert atxs.get(state, a.id) == a
    assert atxs.tick_height(state, a.id) == 100
    view = atxs.by_node_in_epoch(state, a.node_id, 1)
    assert view.id == a.id and view.prev_atx == a.prev_atx
    assert view.num_units == a.num_units and view.version == 1
    assert atxs.ids_in_epoch(state, 1) == [a.id]
    assert atxs.count_in_epoch(state, 1) == 1
    assert atxs.count_in_epoch(state, 2) == 0
    b = _atx(epoch=2)
    atxs.add(state, b)
    assert atxs.latest_by_node(state, a.node_id).publish_epoch == 2


def test_migration_version_check(tmp_path):
    path = tmp_path / "s.db"
    db.open_state(path).close()
    # a database from a newer build (higher user_version) is refused
    import sqlite3
    conn = sqlite3.connect(path)
    conn.execute("PRAGMA user_version=99")
    conn.close()
    with pytest.raises(RuntimeError, match="newer"):
        db.open_state(path)


def test_tx_rollback(state):
    a = _atx()
    with pytest.raises(RuntimeError):
        with state.tx():
            atxs.add(state, a)
            raise RuntimeError("boom")
    assert not atxs.has(state, a.id)


def test_layers_and_blocks(state):
    blk = types.Block(layer=3, tick_height=0, rewards=[], tx_ids=[])
    blocks.add(state, blk)
    assert blocks.get(state, blk.id) == blk
    assert blocks.validity(state, blk.id) == blocks.UNDECIDED
    blocks.set_valid(state, blk.id)
    assert blocks.contextually_valid(state, 3) == [blk.id]
    blocks.set_invalid(state, blk.id)
    assert blocks.contextually_valid(state, 3) == []

    assert layers.processed(state) == -1
    layers.set_processed(state, 0)
    layers.set_processed(state, 1)
    assert layers.processed(state) == 1
    layers.set_applied(state, 1, blk.id, b"\x09" * 32)
    assert layers.applied_block(state, 1) == blk.id
    assert layers.state_hash(state, 1) == b"\x09" * 32
    assert layers.last_applied(state) == 1


def test_ballots_refballot(state):
    ed = types.EpochData(beacon=b"\x01\x02\x03\x04",
                         active_set_root=bytes(32), eligibility_count=3)
    b1 = types.Ballot(layer=8, atx_id=bytes(32), epoch_data=ed,
                      ref_ballot=bytes(32), eligibilities=[],
                      opinion=types.Opinion(base=bytes(32), support=[],
                                            against=[], abstain=[]),
                      node_id=b"\x05" * 32, signature=bytes(64))
    b2 = types.Ballot(layer=9, atx_id=bytes(32), epoch_data=None,
                      ref_ballot=b1.id, eligibilities=[],
                      opinion=types.Opinion(base=bytes(32), support=[],
                                            against=[], abstain=[]),
                      node_id=b"\x05" * 32, signature=bytes(64))
    ballots.add(state, b1)
    ballots.add(state, b2)
    assert ballots.refballot(state, b"\x05" * 32, 8, 12) == b1
    assert {b.id for b in ballots.in_layer(state, 9)} == {b2.id}


def test_misc_entities(state):
    misc.set_beacon(state, 2, b"\xaa\xbb\xcc\xdd")
    assert misc.get_beacon(state, 2) == b"\xaa\xbb\xcc\xdd"
    assert misc.get_beacon(state, 3) is None

    proof = types.MalfeasanceProof(domain=1, msg1=b"a", sig1=bytes(64),
                                   msg2=b"b", sig2=bytes(64),
                                   node_id=b"\x07" * 32)
    misc.set_malicious(state, b"\x07" * 32, proof)
    assert misc.is_malicious(state, b"\x07" * 32)
    assert misc.malfeasance_proof(state, b"\x07" * 32) == proof
    assert misc.all_malicious(state) == [b"\x07" * 32]

    pp = types.PoetProof(poet_id=bytes(32), round_id="5", root=b"\x01" * 32,
                         ticks=777)
    misc.add_poet_proof(state, pp)
    assert misc.poet_proof(state, pp.id) == pp
    assert misc.poet_proof_for_round(state, bytes(32), "5") == pp

    misc.add_active_set(state, b"\x0a" * 32, 2, [b"\x01" * 32, b"\x02" * 32])
    assert misc.active_set(state, b"\x0a" * 32) == [b"\x01" * 32, b"\x02" * 32]

    cert = types.Certificate(block_id=b"\x03" * 32, signatures=[])
    misc.add_certificate(state, 4, cert)
    assert misc.certificate(state, 4) == cert
    assert misc.certified_block(state, 4) == b"\x03" * 32


def test_transactions_accounts(state):
    tx = types.Transaction(raw=b"\x01\x02\x03")
    transactions.add_tx(state, tx, principal=b"\x0b" * 24, nonce=0)
    assert transactions.get_tx(state, tx.id) == tx
    assert transactions.has_tx(state, tx.id)
    assert len(transactions.pending_by_principal(state, b"\x0b" * 24)) == 1
    res = types.TransactionResult(status=0, message="", gas_consumed=100,
                                  fee=5, layer=3, block=bytes(32))
    transactions.set_result(state, tx.id, 3, bytes(32), res)
    assert transactions.result(state, tx.id) == res
    assert transactions.pending_by_principal(state, b"\x0b" * 24) == []

    transactions.update_account(state, b"\x0c" * 24, 1, 100, 0)
    transactions.update_account(state, b"\x0c" * 24, 5, 80, 1)
    assert transactions.account(state, b"\x0c" * 24)["balance"] == 80
    assert transactions.account(state, b"\x0c" * 24, at_layer=3)["balance"] == 100
    transactions.revert_accounts_above(state, 3)
    assert transactions.account(state, b"\x0c" * 24)["balance"] == 100


def test_atx_cache():
    c = cache.AtxCache()
    c.add(2, b"\x01" * 32, cache.AtxInfo(node_id=b"\xaa" * 32, weight=40,
                                         base_height=0, height=10,
                                         num_units=4, vrf_nonce=1))
    c.add(2, b"\x02" * 32, cache.AtxInfo(node_id=b"\xbb" * 32, weight=60,
                                         base_height=0, height=12,
                                         num_units=6, vrf_nonce=2))
    assert c.epoch_weight(2) == 100
    assert c.weight_for_set(2, [b"\x01" * 32]) == 40
    c.set_malicious(b"\xaa" * 32)
    assert c.epoch_weight(2) == 60
    assert c.is_malicious(b"\xaa" * 32)
    assert c.get(2, b"\x01" * 32).malicious
    c.evict(3)
    assert c.get(2, b"\x01" * 32) is None


def test_local_db():
    local = db.open_local()
    local.exec("INSERT INTO nipost_state (node_id, phase) VALUES (?,?)",
               (b"\x01" * 32, 1))
    assert local.one("SELECT phase FROM nipost_state WHERE node_id=?",
                     (b"\x01" * 32,))["phase"] == 1


# --- reader pool / latency metrics / vacuum (VERDICT r3 item 10) ----------


def test_reader_pool_does_not_serialize_behind_writer(tmp_path):
    """With a read pool, a SELECT completes while another thread holds a
    long write transaction — WAL snapshot readers bypass the writer lock
    (reference sql/database.go pooled connections)."""
    import threading
    import time as _time

    d = db.open_state(tmp_path / "pool.db", read_pool=2)
    d.exec("INSERT INTO layers (id, processed) VALUES (1, 1)")

    in_tx = threading.Event()
    release = threading.Event()

    def long_writer():
        with d.tx():
            d.exec("INSERT INTO layers (id, processed) VALUES (2, 1)")
            in_tx.set()
            release.wait(timeout=30)

    t = threading.Thread(target=long_writer)
    t.start()
    assert in_tx.wait(timeout=10)
    start = _time.perf_counter()
    rows = d.all("SELECT id FROM layers ORDER BY id")
    elapsed = _time.perf_counter() - start
    # snapshot isolation: committed data only, and promptly
    assert [r["id"] for r in rows] == [1]
    assert elapsed < 5.0, "read serialized behind the open write tx"
    release.set()
    t.join()
    assert [r["id"] for r in d.all("SELECT id FROM layers ORDER BY id")] \
        == [1, 2]
    d.close()


def test_tx_reads_its_own_uncommitted_writes(tmp_path):
    """Inside tx() the calling thread's reads use the WRITER handle —
    pooled readers cannot see uncommitted rows."""
    d = db.open_state(tmp_path / "ryw.db", read_pool=2)
    with d.tx():
        d.exec("INSERT INTO layers (id, processed) VALUES (7, 1)")
        assert d.one("SELECT processed FROM layers WHERE id=7")["processed"] \
            == 1
        assert len(d.all("SELECT id FROM layers")) == 1
    d.close()


def test_maybe_vacuum_reclaims_after_bulk_delete(tmp_path):
    d = db.open_state(tmp_path / "vac.db")
    with d.tx():
        for i in range(2000):
            d.exec("INSERT INTO layers (id, processed) VALUES (?, 1)",
                   (i + 10,))
    before = d.one("PRAGMA page_count")[0]
    d.exec("DELETE FROM layers")
    assert d.maybe_vacuum(min_free_fraction=0.2) is True
    assert d.one("PRAGMA page_count")[0] < before
    # nothing left to reclaim
    assert d.maybe_vacuum(min_free_fraction=0.2) is False
    d.close()


def test_query_latency_metrics_recorded():
    from spacemesh_tpu.utils.metrics import REGISTRY

    d = db.open_state()
    d.all("SELECT id FROM layers")
    text = REGISTRY.expose()
    assert "sql_state_query_seconds" in text
    assert "sql_state_queries" in text
    d.close()
