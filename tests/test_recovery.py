"""Restart recovery: caches and tortoise state rebuilt from storage."""

import asyncio
import time

import pytest

from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import layers as layerstore

LPE = 3
LAYER_SEC = 0.7


@pytest.fixture(scope="module")
def restarted(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("recovery")
    overrides = {
        "data_dir": str(tmp / "node"),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": time.time() + 3600},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.06,
                 "preround_delay": 0.2, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.05},
        "tortoise": {"hdist": 4, "window_size": 50},
    }
    app = App(load("standalone", overrides=overrides))

    async def first_life():
        await app.prepare()
        app.clock = clock_mod.LayerClock(time.time() + 0.3, LAYER_SEC)
        await app.run(until_layer=2 * LPE)

    asyncio.run(asyncio.wait_for(first_life(), timeout=120))
    app.close()

    # restart: a fresh App over the same data dir
    app2 = App(load("standalone", overrides=overrides))
    return app, app2


def test_atx_cache_recovered(restarted):
    app, app2 = restarted
    for epoch in (1, 2):
        ids = atxstore.ids_in_epoch(app2.state, epoch - 1)
        assert ids, f"no ATXs published in epoch {epoch - 1}"
        for atx_id in ids:
            info = app2.cache.get(epoch, atx_id)
            assert info is not None, "cache not warmed"
            assert info.weight > 0
            orig = app.cache.get(epoch, atx_id)
            assert orig is not None and info.weight == orig.weight


def test_tortoise_state_recovered(restarted):
    app, app2 = restarted
    assert app2.tortoise.processed == layerstore.processed(app2.state)
    assert app2.tortoise.verified >= 0
    # hare outputs (certified/applied blocks) were re-fed
    applied_layers = [lyr for lyr in range(1, 2 * LPE + 1)
                      if layerstore.applied_block(app2.state, lyr)]
    for lyr in applied_layers:
        assert lyr in app2.tortoise._hare
    # ballots carry weight again
    assert any(app2.tortoise._ballots_by_layer.get(lyr)
               for lyr in range(LPE, 2 * LPE + 1)), "no ballots recovered"


def test_recovered_node_keeps_running(restarted):
    app, app2 = restarted

    async def second_life():
        # same network genesis; continue for two more layers
        app2.clock = clock_mod.LayerClock(
            time.time() - (2 * LPE) * LAYER_SEC + 0.3, LAYER_SEC)
        await app2.run(until_layer=2 * LPE + 2)

    asyncio.run(asyncio.wait_for(second_life(), timeout=60))
    assert layerstore.processed(app2.state) >= 2 * LPE + 1
