"""16-node longevity steps + cluster-wide equivocation detection
(VERDICT r3 item 7).

Mirrors two reference systest scenarios on the deterministic in-proc
virtual-clock network (the subprocess tier is covered by
tests/test_cluster_chaos.py; 16 real processes would multiply the wall
clock for the same code paths):

- systest/tests/steps_test.go — longevity: the network runs for several
  epochs and INCREMENTAL per-epoch invariants must hold (every smesher
  published an ATX, one beacon network-wide, every layer applied and
  converged);
- systest/tests/distributed_post_verification_test.go /
  malfeasance gossip — an equivocating smesher publishes two different
  proposals for one (layer, signer) slot set mid-run; every honest node
  must detect it and hold the malfeasance proof.
"""

import asyncio
import dataclasses
import hashlib
import pathlib

import pytest

from spacemesh_tpu.core.signing import Domain, EdSigner
from spacemesh_tpu.core.types import Opinion, Proposal
from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.p2p.pubsub import TOPIC_PROPOSAL, LoopbackHub, PubSub
from spacemesh_tpu.p2p.server import LoopbackNet
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import ballots as ballotstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.storage import misc as miscstore
from spacemesh_tpu.utils.vclock import VirtualClockLoop, cancel_all_tasks

N = 16
SMESHERS = 4
LPE = 3
LAYER_SEC = 2.0
UNTIL = 4 * LPE + 1          # four full epochs and a bit
EQUIVOCATE_AT = 3 * LPE      # epoch-3 injection: with weight-propor-
                             # tional slots each smesher builds ~one
                             # ballot per epoch, landing anywhere in the
                             # epoch's layers — the search window must
                             # cover the whole epoch
GENESIS_PLACEHOLDER = 1_700_001_600.0


def _config(tmp, name, smesh):
    return load("standalone", overrides={
        "data_dir": str(tmp / name),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": GENESIS_PLACEHOLDER},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": smesh, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.2,
                 "preround_delay": 0.5, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.2},
        "tortoise": {"hdist": 4, "window_size": 50},
    })


@pytest.fixture(scope="module")
def sixteen(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("sixteen")
    loop = VirtualClockLoop()
    hub = LoopbackHub()
    net = LoopbackNet()
    apps = []

    for i in range(N):
        name = f"n{i:02d}"
        cfg = _config(tmp, name, smesh=i < SMESHERS)
        # deterministic identities pin every VRF roll (same rationale
        # as tests/test_partition.py)
        key_dir = pathlib.Path(cfg.data_dir) / "identities"
        key_dir.mkdir(parents=True, exist_ok=True)
        seed = hashlib.sha256(f"sixteen-{name}".encode()).digest()
        signer = EdSigner(seed=seed, prefix=cfg.genesis.genesis_id)
        (key_dir / "local.key").write_text(signer.private_bytes().hex())
        ps = PubSub(node_name=signer.node_id)
        hub.join(ps)
        app = App(cfg, signer=signer, pubsub=ps, time_source=loop.time)
        app.connect_network(net)
        apps.append(app)

    equivocator = apps[0]
    injected = {}

    async def go():
        await asyncio.gather(*(a.prepare() for a in apps))
        genesis = loop.time() + 1.0
        for a in apps:
            a.clock = clock_mod.LayerClock(genesis, LAYER_SEC,
                                           time_source=loop.time)
        tasks = [asyncio.create_task(a.run(until_layer=UNTIL))
                 for a in apps]

        async def inject_equivocation():
            # wait until the equivocator has built a ballot at or after
            # EQUIVOCATE_AT, then publish a DIFFERENT ballot for the
            # same (layer, signer): same valid VRF eligibilities, other
            # opinion — content-addressed id differs, the double-ballot
            # check fires on every honest node
            deadline = loop.time() + LAYER_SEC * (UNTIL + 4)
            orig = None
            while loop.time() < deadline and orig is None:
                for lyr in range(EQUIVOCATE_AT, UNTIL + 1):
                    mine = ballotstore.by_node_in_layer(
                        equivocator.state, equivocator.signer.node_id, lyr)
                    if mine:
                        orig = mine[0]
                        break
                if orig is None:
                    await asyncio.sleep(LAYER_SEC / 4)
            assert orig is not None, "equivocator never built a ballot"
            twin = dataclasses.replace(
                orig,
                epoch_data=None,
                ref_ballot=orig.id if orig.epoch_data is not None
                else orig.ref_ballot,
                opinion=Opinion(base=bytes(32), support=[], against=[],
                                abstain=[]),
                signature=bytes(64))
            twin = dataclasses.replace(
                twin, signature=equivocator.signer.sign(
                    Domain.BALLOT, twin.signed_bytes()))
            assert twin.id != orig.id
            prop = Proposal(ballot=twin, tx_ids=[], mesh_hash=bytes(32),
                            signature=bytes(64))
            prop = dataclasses.replace(
                prop, signature=equivocator.signer.sign(
                    Domain.BALLOT, prop.signed_bytes()))
            await equivocator.pubsub.publish(TOPIC_PROPOSAL,
                                             prop.to_bytes())
            injected["layer"] = twin.layer

        inj = asyncio.create_task(inject_equivocation())
        await asyncio.gather(*tasks)
        await inj

    try:
        loop.run_until_complete(asyncio.wait_for(go(), 30_000))
    finally:
        loop.run_until_complete(cancel_all_tasks())
    return apps, injected


def test_every_epoch_step_holds(sixteen):
    """Longevity steps: per-epoch invariants accumulate — each epoch's
    assertions must hold on top of all earlier epochs'."""
    apps, _ = sixteen
    head = apps[1]  # an honest observer
    for epoch in range(0, 3):
        ids = atxstore.ids_in_epoch(head.state, epoch)
        assert len(ids) >= SMESHERS, \
            f"epoch {epoch}: {len(ids)} ATXs < {SMESHERS} smeshers"
        # one beacon network-wide; bootstrap epochs may derive theirs
        # on the fly (not stored), so the invariant is "no split", with
        # presence required once the protocol runs (epoch >= 2)
        beacons = {miscstore.get_beacon(a.state, epoch + 1) for a in apps}
        beacons.discard(None)
        assert len(beacons) <= 1, \
            f"epoch {epoch + 1}: beacon split {beacons}"
        if epoch + 1 >= 2:
            assert beacons, f"epoch {epoch + 1}: no beacon stored"


def test_all_sixteen_converge(sixteen):
    apps, _ = sixteen
    head = apps[1]
    target = min(layerstore.last_applied(a.state) for a in apps)
    assert target >= UNTIL - 2, f"cluster stalled at {target}"
    want = layerstore.aggregated_hash(head.state, target)
    assert want is not None
    for a in apps:
        assert layerstore.aggregated_hash(a.state, target) == want, \
            f"node diverged at layer {target}"


def test_equivocation_proof_propagates_cluster_wide(sixteen):
    apps, injected = sixteen
    assert injected, "equivocation was never injected"
    bad = apps[0].signer.node_id
    missing = [i for i, a in enumerate(apps[1:], 1)
               if miscstore.malfeasance_proof(a.state, bad) is None]
    assert not missing, \
        f"nodes {missing} lack the equivocation proof"
    # and the equivocator's identity is flagged in every cache, so its
    # ATXs lose eligibility everywhere (AtxCache.set_malicious taints
    # the node id across epochs)
    for i, a in enumerate(apps[1:], 1):
        assert bad in a.cache._malicious, f"node {i} cache not flagged"
