"""Native ECVRF (native/ecvrf.cpp) vs the pure-Python twin.

Same pattern as tests/test_native_blake3.py: the from-spec Python
implementation in core/signing.py is the ORACLE; the native library must
be bit-identical on proofs, outputs, and accept/reject decisions —
including rejection edges (flipped bits, s >= q, non-canonical points).
"""

import contextlib
import ctypes
import hashlib
import os
import random

import pytest

from spacemesh_tpu.core import signing
from spacemesh_tpu.native import load

lib = load("ecvrf")
pytestmark = pytest.mark.skipif(lib is None, reason="native build failed")


@pytest.fixture
def python_path(monkeypatch):
    """Force core/signing.py onto its pure-Python path."""
    monkeypatch.setattr(signing, "_NATIVE_VRF", None)


@contextlib.contextmanager
def forced_python():
    saved = signing._NATIVE_VRF
    signing._NATIVE_VRF = None
    try:
        yield
    finally:
        signing._NATIVE_VRF = saved


def test_differential_prove_verify_output():
    """Randomized differential: proofs are deterministic (RFC 9381 TAI
    nonce), so native and Python must produce IDENTICAL bytes, verify
    each other's proofs, and agree on the output hash."""
    rng = random.Random(0xECF)
    for trial in range(12):
        seed = hashlib.sha256(b"dvrf-%d" % trial).digest()
        alpha = bytes(rng.getrandbits(8)
                      for _ in range(rng.randrange(1, 100)))
        with forced_python():
            py_signer = signing.VrfSigner(seed)
            py_proof = py_signer.prove(alpha)
            py_out = signing.vrf_output(py_proof)
            pk = py_signer.public_key

        npk = ctypes.create_string_buffer(32)
        assert lib.smtpu_vrf_public_key(seed, npk) == 0
        assert npk.raw == pk, f"trial {trial}: pk mismatch"
        nproof = ctypes.create_string_buffer(80)
        assert lib.smtpu_vrf_prove(seed, alpha, len(alpha), nproof) == 0
        assert nproof.raw == py_proof, f"trial {trial}: proof mismatch"
        assert lib.smtpu_vrf_verify(pk, alpha, len(alpha), py_proof) == 1
        nout = ctypes.create_string_buffer(64)
        assert lib.smtpu_vrf_output(py_proof[:32], nout) == 0
        assert nout.raw == py_out, f"trial {trial}: beta mismatch"


def test_rejections_agree(python_path):
    """Bit flips anywhere in pk/proof/alpha must be rejected by BOTH
    implementations (never accepted by one and not the other)."""
    seed = hashlib.sha256(b"rej").digest()
    signer = signing.VrfSigner(seed)
    alpha = b"alpha-rejections"
    proof = signer.prove(alpha)  # python path (fixture)
    pk = signer.public_key
    pyv = signing.VrfVerifier()
    rng = random.Random(7)
    for _ in range(40):
        what = rng.randrange(3)
        p, k, a = bytearray(proof), bytearray(pk), bytearray(alpha)
        if what == 0:
            p[rng.randrange(len(p))] ^= 1 << rng.randrange(8)
        elif what == 1:
            k[rng.randrange(len(k))] ^= 1 << rng.randrange(8)
        else:
            a[rng.randrange(len(a))] ^= 1 << rng.randrange(8)
        py = pyv.verify(bytes(k), bytes(a), bytes(p))
        nat = bool(lib.smtpu_vrf_verify(bytes(k), bytes(a), len(a),
                                        bytes(p)))
        assert py == nat, f"divergence: what={what} py={py} native={nat}"


def test_s_out_of_range_rejected():
    seed = hashlib.sha256(b"srange").digest()
    with forced_python():
        signer = signing.VrfSigner(seed)
        proof = signer.prove(b"a")
        pk = signer.public_key
    # s >= q: set the scalar's top bytes
    bad = proof[:48] + b"\xff" * 32
    assert lib.smtpu_vrf_verify(pk, b"a", 1, bad) == 0


def test_native_is_default_and_faster():
    """The wired-in path actually uses the native library, and it beats
    the Python oracle by a wide margin (informational floor: 5x)."""
    import time

    if os.environ.get("SPACEMESH_NO_NATIVE_VRF"):
        pytest.skip("native disabled by env")
    seed = hashlib.sha256(b"perf").digest()
    signer = signing.VrfSigner(seed)
    alpha = b"perf-alpha"
    proof = signer.prove(alpha)
    v = signing.VrfVerifier()
    assert v.verify(signer.public_key, alpha, proof)

    n = 60
    t0 = time.perf_counter()
    for _ in range(n):
        v.verify(signer.public_key, alpha, proof)
    fast = n / (time.perf_counter() - t0)

    with forced_python():
        t0 = time.perf_counter()
        for _ in range(6):
            signing.VrfVerifier().verify(signer.public_key, alpha, proof)
        slow = 6 / (time.perf_counter() - t0)
    assert fast > 5 * slow, (fast, slow)
