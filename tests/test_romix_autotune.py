"""ROMix kernel autotuner: race/persist/override semantics + cross-impl
bit-exactness (ops/autotune.py, ops/scrypt.py tuned dispatch).

The decision surface under test (docs/ROMIX_KERNEL.md):

  env (SPACEMESH_ROMIX / SPACEMESH_ROMIX_CHUNK)  >  persisted winner
  >  race (persisted)  >  static default

plus the Pallas failure contract: an explicit SPACEMESH_ROMIX=pallas
request RAISES when the kernel cannot run, while an autotuned/cached
pallas selection falls back to XLA once, logged and counted in
post_romix_fallback_total.
"""

import hashlib
import json

import jax.numpy as jnp
import numpy as np
import pytest

from spacemesh_tpu.ops import autotune, scrypt
from spacemesh_tpu.ops import romix_pallas as rp

N = 16


@pytest.fixture
def tuner(tmp_path, monkeypatch):
    """Fresh autotune world: private cache file, racing enabled, no
    overrides, no memoized measurements."""
    path = tmp_path / "romix_autotune.json"
    monkeypatch.setenv(autotune.ENV_CACHE, str(path))
    monkeypatch.delenv(autotune.ENV_IMPL, raising=False)
    monkeypatch.delenv(autotune.ENV_CHUNK, raising=False)
    monkeypatch.delenv(autotune.ENV_AUTOTUNE, raising=False)
    autotune.reset_memo()
    return path


def _seed(path, key, impl, chunk, rate=123.0):
    doc = {}
    if path.exists():
        doc = json.loads(path.read_text())
    doc[key] = {"impl": impl, "chunk": chunk, "labels_per_sec": rate}
    path.write_text(json.dumps(doc))


def test_race_on_miss_then_cache_hit(tuner):
    d = autotune.decide(N, 64, platform="cpu")
    assert d.source == "race"
    assert d.impl in autotune.IMPLS
    # the winner was persisted with the expected key
    doc = json.loads(tuner.read_text())
    key = autotune._key("cpu", N, 64)
    assert key in doc and doc[key]["impl"] == d.impl
    assert doc[key]["raced"], "race measurements should be recorded"

    # a fresh process (memos cleared) must NOT re-race: cache hit
    autotune.reset_memo()

    def boom(*a, **k):  # pragma: no cover - only on regression
        raise AssertionError("re-raced despite persisted winner")

    orig = autotune._race_measurements
    try:
        autotune._race_measurements = boom
        d2 = autotune.decide(N, 64, platform="cpu")
    finally:
        autotune._race_measurements = orig
    assert d2.source == "cache"
    assert (d2.impl, d2.chunk) == (d.impl, d.chunk)


def test_corrupt_cache_ignored(tuner, monkeypatch):
    tuner.write_text("{not json at all")
    monkeypatch.setenv(autotune.ENV_AUTOTUNE, "off")
    d = autotune.decide(N, 32, platform="cpu")
    assert d.source == "default"  # fell through, did not raise
    # and a rewrite heals the file
    autotune._store(autotune._key("cpu", N, 32),
                    {"impl": "xla", "chunk": None, "labels_per_sec": 1.0})
    assert json.loads(tuner.read_text())


def test_autotune_off_uses_default(tuner, monkeypatch):
    monkeypatch.setenv(autotune.ENV_AUTOTUNE, "off")
    d = autotune.decide(N, 64, platform="cpu")
    assert d.source == "default"
    assert not tuner.exists(), "default decisions are not persisted"


def test_env_impl_beats_cached_winner(tuner, monkeypatch):
    _seed(tuner, autotune._key("cpu", N, 64), "xla-rows", 2)
    assert autotune.decide(N, 64, platform="cpu").impl == "xla-rows"
    monkeypatch.setenv(autotune.ENV_IMPL, "xla")
    d = autotune.decide(N, 64, platform="cpu")
    assert (d.impl, d.source, d.explicit_impl) == ("xla", "env", True)
    # env impl == cached impl inherits the cached chunk
    monkeypatch.setenv(autotune.ENV_IMPL, "xla-rows")
    assert autotune.decide(N, 64, platform="cpu").chunk == 2


def test_env_chunk_beats_cached_winner(tuner, monkeypatch):
    _seed(tuner, autotune._key("cpu", N, 64), "xla-rows", 2)
    monkeypatch.setenv(autotune.ENV_CHUNK, "8")
    d = autotune.decide(N, 64, platform="cpu")
    assert (d.impl, d.chunk, d.source) == ("xla-rows", 8, "env")
    monkeypatch.setenv(autotune.ENV_CHUNK, "0")  # explicit unchunked
    assert autotune.decide(N, 64, platform="cpu").chunk is None
    # a chunk as wide as the batch is normalized away
    monkeypatch.setenv(autotune.ENV_CHUNK, "64")
    assert autotune.decide(N, 64, platform="cpu").chunk is None


def test_bad_env_values_rejected(tuner, monkeypatch):
    monkeypatch.setenv(autotune.ENV_IMPL, "cuda")
    with pytest.raises(ValueError, match="SPACEMESH_ROMIX"):
        autotune.decide(N, 64, platform="cpu")
    monkeypatch.delenv(autotune.ENV_IMPL)
    monkeypatch.setenv(autotune.ENV_CHUNK, "-3")
    with pytest.raises(ValueError, match="SPACEMESH_ROMIX_CHUNK"):
        autotune.decide(N, 64, platform="cpu")


def test_garbage_cache_entry_ignored(tuner, monkeypatch):
    monkeypatch.setenv(autotune.ENV_AUTOTUNE, "off")
    _seed(tuner, autotune._key("cpu", N, 64), "not-an-impl", "nope")
    d = autotune.decide(N, 64, platform="cpu")
    assert d.source == "default"  # invalid entry treated as a miss


# --- cross-impl bit-exactness -------------------------------------------

UNALIGNED = (1, 7, 128, 1000)


@pytest.mark.parametrize("batch", UNALIGNED)
def test_xla_impl_sweep_bit_exact(batch):
    """Word-major, contiguous-row, and chunked variants agree on
    unaligned batch sizes (chunk 16 forces pad-and-trim at 7 and 1000)."""
    x = jnp.asarray(autotune.calibration_block(batch))
    want = np.asarray(scrypt.romix_tuned(x, n=N, impl="xla", chunk=None,
                                         interpret=False))
    for impl, chunk in (("xla-rows", None), ("xla", 16), ("xla-rows", 16)):
        got = np.asarray(scrypt.romix_tuned(x, n=N, impl=impl, chunk=chunk,
                                            interpret=False))
        assert (got == want).all(), f"{impl}/chunk={chunk} diverged at B={batch}"


@pytest.mark.parametrize("batch", (1, 7))
def test_pallas_padded_bit_exact(batch):
    """The lane-padding wrapper makes the Pallas kernel agree on batches
    below the tile (interpret mode executes every DMA in Python, so the
    wider sweep lives in tests/test_romix_pallas.py)."""
    x = jnp.asarray(autotune.calibration_block(batch))
    want = np.asarray(scrypt.romix_tuned(x, n=N, impl="xla", chunk=None,
                                         interpret=False))
    got = np.asarray(rp.romix_pallas_padded(x, n=N, lane_tile=8,
                                            interpret=True))
    assert (got == want).all(), f"pallas pad diverged at B={batch}"


def test_labels_env_override_end_to_end(tuner, monkeypatch):
    """A forced impl+chunk flows through the fused label pipeline and
    still matches hashlib ground truth."""
    monkeypatch.setenv(autotune.ENV_IMPL, "xla-rows")
    monkeypatch.setenv(autotune.ENV_CHUNK, "4")
    commitment = hashlib.sha256(b"autotune-e2e").digest()
    got = scrypt.scrypt_labels(commitment, np.arange(7, dtype=np.uint64),
                               n=N)
    for i in (0, 3, 6):
        want = hashlib.scrypt(commitment, salt=int(i).to_bytes(8, "little"),
                              n=N, r=1, p=1, dklen=16)
        assert bytes(got[i]) == want, f"label {i} mismatch"


# --- pallas failure contract --------------------------------------------


def _break_pallas(monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("mosaic exploded")

    monkeypatch.setattr(rp, "romix_pallas_padded", boom)


def test_explicit_pallas_request_raises_on_failure(tuner, monkeypatch):
    _break_pallas(monkeypatch)
    monkeypatch.setenv(autotune.ENV_IMPL, "pallas")
    commitment = hashlib.sha256(b"pallas-must-raise").digest()
    with pytest.raises(RuntimeError, match="explicitly requested"):
        # unique (n, batch) shape so the jit cache cannot satisfy the
        # call without re-entering the (broken) pallas dispatch
        scrypt.scrypt_labels(commitment, np.arange(5, dtype=np.uint64), n=4)


def test_cached_pallas_winner_falls_back_and_counts(tuner, monkeypatch):
    from spacemesh_tpu.utils import metrics

    _break_pallas(monkeypatch)
    # decisions are keyed by the BUCKETED batch — the executable shape a
    # 6-lane call actually runs at (ops/scrypt.py shape_bucket)
    _seed(tuner, autotune._key("cpu", 4, scrypt.shape_bucket(6)),
          "pallas", None)
    before = sum(metrics.post_romix_fallback._values.values())
    commitment = hashlib.sha256(b"pallas-falls-back").digest()
    got = scrypt.scrypt_labels(commitment, np.arange(6, dtype=np.uint64),
                               n=4)
    want = hashlib.scrypt(commitment, salt=(2).to_bytes(8, "little"),
                          n=4, r=1, p=1, dklen=16)
    assert bytes(got[2]) == want, "XLA fallback result wrong"
    after = sum(metrics.post_romix_fallback._values.values())
    assert after == before + 1, "fallback not counted"
