"""The autotuner's mesh dimension + shape bucketing + warmcache (ISSUE 6).

Three contracts, asserted rather than eyeballed:

* **sharded bit-identity** — an init session whose autotuned winner says
  ``devices > 1`` writes byte-identical labels (and the same VRF nonce)
  as the single-device path, across ragged totals (1 / 7 / 1000) whose
  tail batches exercise the bucket-then-mesh pad in
  post/initializer.py ``_dispatch``;
* **bucketed executable reuse** — ragged batch sizes inside one
  power-of-two bucket share ONE compiled executable
  (ops/scrypt.py ``shape_bucket``), measured by the in-process compile
  counter, not by timing;
* **warmcache round-trip** — a cold ``tools/warmcache.py`` run populates
  the persistent XLA cache so a second (warm) run's per-program compile
  seconds collapse to ~0 (the bench's ``post_init_compile_s`` contract).
"""

import hashlib
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spacemesh_tpu.ops import autotune, scrypt
from spacemesh_tpu.parallel.mesh import data_mesh, scrypt_labels_sharded
from spacemesh_tpu.post import initializer
from spacemesh_tpu.post.data import LabelStore, PostMetadata
from spacemesh_tpu.utils import metrics

NODE = hashlib.sha256(b"mesh-node").digest()
COMMIT = hashlib.sha256(b"mesh-commitment").digest()
N = 2
BATCH = 256


@pytest.fixture
def tuner(tmp_path, monkeypatch):
    """Fresh autotune world (same shape as tests/test_romix_autotune.py):
    private winners file, no overrides, no memoized decisions. Racing
    stays OFF (conftest) — these tests seed winners explicitly."""
    path = tmp_path / "romix_autotune.json"
    monkeypatch.setenv(autotune.ENV_CACHE, str(path))
    monkeypatch.delenv(autotune.ENV_IMPL, raising=False)
    monkeypatch.delenv(autotune.ENV_CHUNK, raising=False)
    monkeypatch.delenv(autotune.ENV_MESH, raising=False)
    autotune.reset_memo()
    yield path
    autotune.reset_memo()


def _seed_mesh_winner(path, n, batch, devices, impl="xla"):
    """Persist a mesh winner under the key the initializer's decide()
    call (max_devices=None -> dev_cap 8 on the virtual 8-device host)
    actually looks up: the BUCKETED batch hint."""
    key = autotune._key("cpu", n, scrypt.shape_bucket(batch),
                        autotune._device_cap(None))
    doc = json.loads(path.read_text()) if path.exists() else {}
    doc[key] = {"impl": impl, "chunk": None, "devices": devices,
                "labels_per_sec": 9999.0}
    path.write_text(json.dumps(doc))


def _disk_labels(d, count):
    meta = PostMetadata.load(d)
    return LabelStore(d, meta).read_labels(0, count)


# --- sharded-vs-single bit-identity across ragged totals ------------------


@pytest.mark.parametrize("total", (1, 7, 1000))
def test_autotuned_mesh_init_bit_identical(total, tuner, tmp_path):
    """End to end through the initializer: a seeded devices=4 winner
    routes batches over the mesh (bucket pad + mesh pad + trim), and the
    bytes on disk — and the VRF nonce — match the single-device ground
    truth exactly. total=1 also proves the devices<=batch clamp: a
    4-device winner cannot shard one lane, so the session honestly runs
    single-device."""
    hint = min(BATCH, total)
    _seed_mesh_winner(tuner, N, hint, devices=4)

    d = tmp_path / f"mesh-{total}"
    meta, _res = initializer.initialize(
        d, node_id=NODE, commitment=COMMIT, num_units=1,
        labels_per_unit=total, scrypt_n=N, max_file_size=1 << 20,
        batch_size=BATCH, mesh="auto")

    assert meta.labels_written == total
    got = np.frombuffer(_disk_labels(d, total), dtype=np.uint8)
    want = scrypt.scrypt_labels(COMMIT, np.arange(total, dtype=np.uint64),
                                n=N)
    assert np.array_equal(got.reshape(-1, 16), want), \
        f"sharded labels diverged from single-device at total={total}"
    lo = want[:, :8].copy().view("<u8").ravel()
    hi = want[:, 8:].copy().view("<u8").ravel()
    assert meta.vrf_nonce == int(np.lexsort((lo, hi))[0])

    expected_devices = 4 if total >= 4 else 1
    assert metrics.post_mesh_devices._values.get(()) == expected_devices


def test_mesh_decision_consumed_and_reported(tuner, tmp_path):
    """The seeded winner is what the session runs with (gauge + shard
    metrics), and shard-imbalance telemetry appears for sharded runs."""
    _seed_mesh_winner(tuner, N, BATCH, devices=4)
    metrics.post_mesh_shard_imbalance.set(-1.0)
    d = tmp_path / "telemetry"
    initializer.initialize(
        d, node_id=NODE, commitment=COMMIT, num_units=1,
        labels_per_unit=512, scrypt_n=N, max_file_size=1 << 20,
        batch_size=BATCH, mesh="auto")
    assert metrics.post_mesh_devices._values.get(()) == 4
    imb = metrics.post_mesh_shard_imbalance._values.get(())
    assert imb is not None and 0.0 <= imb <= 1.0


@pytest.mark.parametrize("impl", ("xla", "xla-rows"))
def test_sharded_impl_passthrough_bit_identity(impl):
    """Both raced mesh layouts produce identical labels through the
    sharded entry point (the winner's impl rides into the dispatch)."""
    idx = np.arange(64, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    want = scrypt.scrypt_labels(COMMIT, idx, n=4)
    mesh = data_mesh(jax.devices()[:4])
    cw = scrypt.commitment_to_words(COMMIT)
    words = scrypt_labels_sharded(mesh, cw, lo, hi, n=4, impl=impl)
    got = np.frombuffer(scrypt.labels_to_bytes(np.asarray(words)),
                        dtype=np.uint8).reshape(-1, 16)
    assert np.array_equal(got, want), f"impl={impl} diverged under mesh"


# --- decision-surface units for the mesh dimension ------------------------


def test_read_mesh_env_parsing(monkeypatch):
    monkeypatch.delenv(autotune.ENV_MESH, raising=False)
    assert autotune.read_mesh_env() is None
    monkeypatch.setenv(autotune.ENV_MESH, "auto")
    assert autotune.read_mesh_env() is None
    monkeypatch.setenv(autotune.ENV_MESH, "off")
    assert autotune.read_mesh_env() == 1
    monkeypatch.setenv(autotune.ENV_MESH, "3")
    assert autotune.read_mesh_env() == 3
    monkeypatch.setenv(autotune.ENV_MESH, "on")
    assert autotune.read_mesh_env() == jax.device_count()
    monkeypatch.setenv(autotune.ENV_MESH, "lots")
    with pytest.raises(ValueError, match="SPACEMESH_MESH"):
        autotune.read_mesh_env()
    monkeypatch.setenv(autotune.ENV_MESH, "-2")
    with pytest.raises(ValueError, match="SPACEMESH_MESH"):
        autotune.read_mesh_env()


def test_mesh_candidates_grid():
    assert autotune.mesh_candidates(8) == [2, 4, 8]
    assert autotune.mesh_candidates(3) == [2]
    assert autotune.mesh_candidates(1) == []
    assert autotune.mesh_candidates(16, cap=4) == [2, 4]
    # the raced grid includes per-device-count rows for both CPU layouts
    combos = autotune.candidates("cpu", N, autotune.CAL_BATCH, mesh_cap=8)
    assert ("xla", None, 8) in combos and ("xla-rows", None, 4) in combos
    # single-device callers never see mesh rows
    assert all(dev == 1 for _, _, dev in
               autotune.candidates("cpu", N, autotune.CAL_BATCH))


def test_winner_noise_band_prefers_fewer_devices():
    """Within the calibration noise band the narrowest mesh wins (the
    fixed 512-lane calibration flatters wide meshes; sharding overhead
    grows with the production batch). Outside the band, rate wins."""
    rows = [
        {"impl": "xla", "chunk": None, "devices": 8, "labels_per_sec": 69.0},
        {"impl": "xla", "chunk": None, "devices": 4, "labels_per_sec": 67.0},
        {"impl": "xla-rows", "chunk": None, "devices": 1,
         "labels_per_sec": 59.0},
    ]
    assert autotune._select_winner(rows)["devices"] == 4
    # a single-device row inside the band beats every mesh row: a mesh
    # "win" within noise is not a win
    rows[2]["labels_per_sec"] = 66.0
    assert autotune._select_winner(rows)["devices"] == 1
    # far apart: the fastest row wins regardless of width
    rows[1]["labels_per_sec"] = rows[2]["labels_per_sec"] = 30.0
    assert autotune._select_winner(rows)["devices"] == 8
    # equal devices tie-break back to rate
    rows = [{"impl": "xla", "chunk": None, "devices": 2,
             "labels_per_sec": 50.0},
            {"impl": "xla-rows", "chunk": None, "devices": 2,
             "labels_per_sec": 51.0}]
    assert autotune._select_winner(rows)["impl"] == "xla-rows"


def test_mesh_off_holds_through_the_race_path(tuner, monkeypatch):
    """SPACEMESH_MESH=off with racing ENABLED (the production default —
    conftest pins autotune off, which used to mask this): the decision
    must collapse to the single-device budget before the race, so the
    race can neither select nor persist a devices>1 row."""
    monkeypatch.setenv(autotune.ENV_MESH, "off")
    monkeypatch.delenv(autotune.ENV_AUTOTUNE, raising=False)
    calls = []

    def fake_race(platform, n, batch, dev_cap=1, pin_devices=None):
        calls.append((dev_cap, pin_devices))
        return autotune.Decision("xla", None, "race")

    monkeypatch.setattr(autotune, "race", fake_race)
    d = autotune.decide(N, BATCH, platform="cpu", max_devices=None)
    assert d.devices == 1
    assert calls == [(1, None)], \
        "the off switch must clamp the race's device budget to 1"


def test_failed_race_candidates_not_retried(tuner, monkeypatch):
    """A candidate that failed is persisted as a 0-rate row: the next
    decide must not see it as missing (re-racing it every process), and
    it must never win."""
    key = autotune._meas_key("cpu", N)
    rows = [{"impl": "xla", "chunk": None, "devices": 1,
             "labels_per_sec": 100.0}]
    rows += [{"impl": impl, "chunk": c, "devices": dv,
              "labels_per_sec": 0.0, "failed": "RuntimeError"}
             for impl, c, dv in autotune.candidates(
                 "cpu", N, autotune.CAL_BATCH,
                 mesh_cap=autotune._device_cap(None))
             if not (impl == "xla" and c is None and dv == 1)]
    doc = json.loads(tuner.read_text()) if tuner.exists() else {}
    doc[key] = {"raced": rows}
    tuner.write_text(json.dumps(doc))
    monkeypatch.delenv(autotune.ENV_AUTOTUNE, raising=False)
    monkeypatch.setattr(autotune, "_race_rows",
                        lambda *a, **k: pytest.fail("re-raced a failed "
                                                    "candidate"))
    d = autotune.decide(N, BATCH, platform="cpu", max_devices=None)
    assert (d.impl, d.devices) == ("xla", 1)


def test_forced_mesh_device_count_beats_cached_winner(tuner, monkeypatch):
    _seed_mesh_winner(tuner, N, BATCH, devices=8)
    d = autotune.decide(N, BATCH, platform="cpu", max_devices=None)
    assert (d.devices, d.source) == (8, "cache")
    monkeypatch.setenv(autotune.ENV_MESH, "2")
    d = autotune.decide(N, BATCH, platform="cpu", max_devices=None)
    assert (d.devices, d.source) == (2, "env")
    monkeypatch.setenv(autotune.ENV_MESH, "off")
    d = autotune.decide(N, BATCH, platform="cpu", max_devices=None)
    assert d.devices == 1
    # the cap-1 lookup (ops/scrypt.py per-call dispatch) is untouched by
    # the mesh winner: it must never try to shard
    monkeypatch.delenv(autotune.ENV_MESH)
    assert autotune.decide(N, BATCH, platform="cpu").devices == 1


# --- bucketed executable reuse (the compile counter, not a stopwatch) -----


def test_bucketed_shapes_share_one_executable(tuner):
    """Every ragged batch inside a power-of-two bucket reuses the
    bucket's executable; crossing the bucket boundary mints exactly one
    more. Asserted on the jit cache-entry counter."""
    n = 64  # a (n, shape) family no other test compiles
    cw = jnp.asarray(scrypt.commitment_to_words(COMMIT))

    def labels(b):
        lo, hi = scrypt.split_indices(np.arange(b, dtype=np.uint64))
        return scrypt.scrypt_labels_jit(cw, jnp.asarray(lo),
                                        jnp.asarray(hi), n=n)

    base = scrypt.compiled_shape_count()
    out5 = labels(5)
    assert out5.shape == (4, 5)  # trimmed back to the caller's batch
    assert scrypt.compiled_shape_count() == base + 1
    for b in (6, 7, 8):
        assert labels(b).shape == (4, b)
    assert scrypt.compiled_shape_count() == base + 1, \
        "ragged batches 5..8 must share the bucket-8 executable"
    labels(9)  # bucket 16
    assert scrypt.compiled_shape_count() == base + 2

    # bit-identity of the pad-and-trim against ground truth
    want = scrypt.scrypt_labels(COMMIT, np.arange(5, dtype=np.uint64), n=n)
    got = np.frombuffer(scrypt.labels_to_bytes(np.asarray(out5)),
                        dtype=np.uint8).reshape(-1, 16)
    assert np.array_equal(got, want)


def test_bucketed_min_scan_carry_is_exact(tuner):
    """Pad lanes repeat the last index: the VRF min-scan's carry must be
    identical to the unpadded result (first-occurrence wins)."""
    n = 64
    total = 11  # bucket 16: 5 pad lanes
    idx = np.arange(total, dtype=np.uint64)
    lo, hi = scrypt.split_indices(idx)
    cw = jnp.asarray(scrypt.commitment_to_words(COMMIT))
    base = scrypt.compiled_shape_count()
    words, _carry, snap = scrypt.scrypt_labels_with_min(
        cw, jnp.asarray(lo), jnp.asarray(hi),
        jnp.asarray(scrypt.vrf_carry_init()), n=n)
    assert words.shape == (4, total)
    assert scrypt.compiled_shape_count() == base + 1

    want = scrypt.scrypt_labels(COMMIT, idx, n=n)
    wlo = want[:, :8].copy().view("<u8").ravel()
    whi = want[:, 8:].copy().view("<u8").ravel()
    want_k = int(np.lexsort((wlo, whi))[0])
    decoded = scrypt.vrf_carry_decode(snap)
    assert decoded is not None and decoded[0] == want_k


def test_shape_bucket_contract(monkeypatch):
    assert scrypt.shape_bucket(1) == 1
    assert scrypt.shape_bucket(5) == 8
    assert scrypt.shape_bucket(8) == 8
    assert scrypt.shape_bucket(1000) == 1024
    monkeypatch.setenv(scrypt.ENV_BUCKETS, "off")
    assert scrypt.shape_bucket(1000) == 1000


# --- warmcache round-trip: cold compile -> warm ~0 ------------------------


def _run_warmcache(cache_dir, tmp_path):
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               SPACEMESH_JAX_CACHE=str(cache_dir),
               SPACEMESH_ROMIX_CACHE=str(tmp_path / "tune.json"),
               SPACEMESH_ROMIX_AUTOTUNE="off")
    r = subprocess.run(
        [sys.executable, "-m", "spacemesh_tpu.tools.warmcache",
         "--n", "32", "--batches", "64", "--no-mesh", "--no-probe"],
        env=env, capture_output=True, text=True, timeout=570)
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout)


def test_warmcache_cold_then_warm(tmp_path):
    """The CLI's first (cold) run pays the XLA compiles into the
    persistent cache; a second process deserializes instead — every
    per-program second collapses below the bench's 1s warm budget
    (`post_init_compile_s` contract, ISSUE 6)."""
    cache = tmp_path / "xla-cache"
    cold = _run_warmcache(cache, tmp_path)
    assert cold["cache_dir"] and cold["shapes"], cold
    cold_s = cold["shapes"][0]["programs"]
    assert cold_s, "cold run compiled nothing"

    warm = _run_warmcache(cache, tmp_path)
    warm_s = warm["shapes"][0]["programs"]
    assert set(warm_s) == set(cold_s)
    for prog, secs in warm_s.items():
        # warm = deserialize + trace, no XLA compile. The absolute floor
        # absorbs loaded CI containers; the relative bound is the
        # contract (a cache miss re-pays the FULL compile at ~1.0x cold,
        # far over both; measured warm restores land at 0.2-0.4x on a
        # throttled 2-core container, so 0.5x keeps headroom without
        # losing the miss/hit separation)
        assert secs <= max(1.0, 0.5 * cold_s[prog]), \
            f"{prog} took {secs}s warm (cold {cold_s[prog]}s) — " \
            "persistent cache did not round-trip"
    # and warming was not a no-op: the cold run actually compiled
    assert max(cold_s.values()) > max(warm_s.values())
