"""Scenario-engine units: topology, fault semantics, hub delivery
under faults, req/resp reachability, adversarial payload builders, and
one tiny end-to-end engine run (the full-size scenarios live in
tests/test_sim_scenarios.py)."""

import asyncio

import pytest

from spacemesh_tpu.p2p.pubsub import PubSub
from spacemesh_tpu.p2p.server import RequestError, Server
from spacemesh_tpu.sim import faults as faults_mod
from spacemesh_tpu.sim.net import LinkPolicy, MeshHub, SimNet, SimNetwork
from spacemesh_tpu.utils.vclock import run_virtual

N = [b"%02d" % i + bytes(30) for i in range(12)]


def _network(n=8, seed=3, degree=4):
    net = SimNetwork(seed, degree=degree)
    for name in N[:n]:
        net.add_node(name)
    net.build_topology()
    return net


def _hub_nodes(net, hub, n=8):
    """PubSub endpoints with a counting accept-all handler on t1."""
    counts = {}

    def mk(name):
        ps = PubSub(node_name=name, deliver_self=False)
        counts[name] = []

        async def h(peer, data, _n=name):
            counts[_n].append(data)
            return True

        ps.register("t1", h)
        hub.join(ps)
        return ps

    return [mk(name) for name in N[:n]], counts


# --- topology / reachability -----------------------------------------


def test_topology_deterministic_and_connected():
    a, b = _network(10, seed=5), _network(10, seed=5)
    assert a.adj == b.adj
    assert _network(10, seed=6).adj != a.adj  # seed matters
    # ring guarantees connectivity
    seen, frontier = set(), [N[0]]
    while frontier:
        cur = frontier.pop()
        if cur in seen:
            continue
        seen.add(cur)
        frontier.extend(a.adj[cur])
    assert seen == set(N[:10])
    for name in N[:10]:
        assert len(a.adj[name]) >= 2


def test_partition_eclipse_block_down_semantics():
    net = _network(6)
    a, b, c = N[0], N[1], N[2]
    assert net.reachable(a, b)
    net.partition([[a], [b]])          # c et al stay in group 0
    assert not net.reachable(a, b)
    assert not net.reachable(a, c)     # different groups (1 vs 0)
    assert net.reachable(c, N[3])      # both unlisted -> same island
    net.heal()
    assert net.reachable(a, b)
    net.eclipse(a, [b])
    assert net.reachable(a, b) and not net.reachable(a, c)
    assert not net.reachable(c, a)     # symmetric: c may not reach in
    net.clear_eclipse(a)
    net.block_link(a, b)
    assert not net.reachable(a, b) and net.reachable(a, c)
    net.unblock_link(a, b)
    net.set_down(b, True)
    assert not net.reachable(a, b) and b not in net.neighbors(a)
    net.set_down(b, False)
    assert net.reachable(a, b)


# --- hub delivery under faults ---------------------------------------


def test_hub_floods_with_dedup_and_respects_partition():
    async def go():
        net = _network(8)
        hub = MeshHub(net)
        nodes, counts = _hub_nodes(net, hub, 8)
        await nodes[0].publish("t1", b"m1")
        await hub.drain()
        for name in N[1:8]:
            assert counts[name] == [b"m1"], "everyone hears it once"
        # 3-way partition: only the publisher's island hears m2
        net.partition([[N[0], N[1]], [N[2], N[3]]])
        await nodes[0].publish("t1", b"m2")
        await hub.drain()
        assert counts[N[1]] == [b"m1", b"m2"]
        for name in N[2:8]:
            assert counts[name] == [b"m1"]
        net.heal()

    asyncio.run(go())


def test_hub_link_loss_and_churn():
    async def go():
        net = _network(6)
        hub = MeshHub(net)
        nodes, counts = _hub_nodes(net, hub, 6)
        net.set_link_policy(LinkPolicy(loss=1.0))
        await nodes[0].publish("t1", b"lost")
        await hub.drain()
        assert all(not counts[n] for n in N[1:6])
        assert net.stats["loss"] > 0
        net.set_link_policy(LinkPolicy())
        # churn: a suspended node misses traffic, a resumed one rejoins
        hub.suspend(N[2])
        await nodes[0].publish("t1", b"while-down")
        await hub.drain()
        assert counts[N[2]] == [] and counts[N[1]] == [b"while-down"]
        hub.resume(N[2])
        await nodes[0].publish("t1", b"back")
        await hub.drain()
        assert counts[N[2]] == [b"back"]

    asyncio.run(go())


def test_hub_duplication_and_delay_on_virtual_clock():
    async def go():
        net = _network(4)
        hub = MeshHub(net)
        nodes, counts = _hub_nodes(net, hub, 4)
        net.set_link_policy(LinkPolicy(dup=1.0))
        await nodes[0].publish("t1", b"dup")
        await hub.drain()
        # duplicated on every link, but the seen-cache absorbs it
        assert all(counts[n] == [b"dup"] for n in N[1:4])
        assert net.stats["dup"] > 0 and hub.stats["dup"] > 0
        net.set_link_policy(LinkPolicy(delay=5.0))
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        await nodes[0].publish("t1", b"late")
        await asyncio.sleep(0.1)
        assert all(counts[n] == [b"dup"] for n in N[1:4]), \
            "delayed frame must not arrive early"
        await asyncio.sleep(6.0)   # virtual seconds — instant wall time
        await hub.drain()
        assert all(counts[n] == [b"dup", b"late"] for n in N[1:4])
        assert loop.time() - t0 < 30

    run_virtual(go(), timeout=120)


# --- req/resp over the sim net ---------------------------------------


def test_simnet_route_respects_partitions_and_loss():
    async def go():
        net = _network(4)
        simnet = SimNet(net)
        servers = []
        for name in N[:4]:
            srv = Server(name)

            async def echo(peer, data):
                return b"ok:" + data

            srv.register("e/1", echo)
            simnet.join(srv)
            servers.append(srv)
        a, b = servers[0], servers[1]
        assert await a.request(N[1], "e/1", b"hi") == b"ok:hi"
        assert N[1] in a.peers() and N[0] in b.peers()
        net.partition([[N[0]], [N[1]]])
        assert N[1] not in a.peers(), "peers() must see the partition"
        with pytest.raises(RequestError):
            await a.request(N[1], "e/1", b"x")
        net.heal()
        net.set_link_policy(LinkPolicy(loss=1.0))
        with pytest.raises(RequestError):
            await a.request(N[1], "e/1", b"x")
        net.set_link_policy(LinkPolicy())
        assert await a.request(N[1], "e/1", b"y") == b"ok:y"

    asyncio.run(go())


# --- adversarial payload builders ------------------------------------


def test_torsion_hare_message_is_wire_valid_and_cofactored():
    from spacemesh_tpu.consensus.hare import HareMessage
    from spacemesh_tpu.core import signing
    from spacemesh_tpu.core.signing import Domain, EdVerifier

    blob = faults_mod.torsion_hare_message(layer=5, seed=9)
    msg = HareMessage.from_bytes(blob)
    assert msg.layer == 5 and len(msg.signature) == 64
    if signing._HAVE_CRYPTOGRAPHY:
        pytest.skip("OpenSSL backend (cofactorless) in use")
    # ZIP-215 cofactored verification accepts the torsion-in-R
    # signature on EVERY path — the old split diverged here (PR 2)
    v = EdVerifier()
    assert v.verify(Domain.HARE, msg.node_id, msg.signed_bytes(),
                    msg.signature)
    items = [(int(Domain.HARE), msg.node_id, msg.signed_bytes(),
              msg.signature)] * 9
    assert all(v.verify_many(items)), "batch path must agree with inline"


def test_malformed_atx_blobs_are_deterministic():
    a = faults_mod.malformed_atx_blobs(3, 6)
    assert a == faults_mod.malformed_atx_blobs(3, 6)
    assert a != faults_mod.malformed_atx_blobs(4, 6)
    assert any(len(b) < 64 for b in a), "truncated variants present"


def test_fault_vocabulary_rejects_unknown():
    class Eng:
        network = _network(4)
        fulls: list = []
        lights: list = []

    with pytest.raises(faults_mod.FaultError):
        faults_mod.apply_fault(Eng(), {"kind": "meteor-strike"})
    line = faults_mod.apply_fault(
        Eng(), {"kind": "link_policy", "loss": 0.5, "delay": 0.1})
    assert "loss=0.5" in line and "delay=0.1" in line
    assert Eng.network.default_policy.loss == 0.5
    faults_mod.apply_fault(Eng(), {"kind": "link_policy"})
    assert Eng.network.default_policy.loss == 0.0


# --- tiny end-to-end engine run --------------------------------------


def test_engine_smoke_end_to_end_and_replays_identically(tmp_path):
    """Two full nodes + a light fabric through the whole engine:
    convergence, SLI presence, SLO verdicts, trace validation, storm
    coverage — run TWICE from the same seed into fresh data dirs; the
    event digests must be byte-identical (replay-from-seed contract)."""
    from spacemesh_tpu.sim import builtin, run_scenario

    result = run_scenario(builtin("smoke", light=6),
                          tmp=tmp_path / "run1")
    assert result.ok, result.asserts
    kinds = {a["kind"]: a for a in result.asserts}
    assert kinds["converged"]["ok"]
    assert kinds["storm_coverage"]["value"] == 1.0
    assert kinds["slo_green"]["ok"]
    assert kinds["trace_valid"]["ok"]
    assert len(result.digest) == 64
    assert any("record full=0" in line for line in result.events)
    assert result.stats["hub"]["delivered"] > 0

    replay = run_scenario(builtin("smoke", light=6),
                          tmp=tmp_path / "run2")
    assert replay.ok, replay.asserts
    assert replay.digest == result.digest, \
        "same seed must replay to a byte-identical event digest"
