"""racecheck (ISSUE 12): SC007/SC008 static concurrency rules, the
spacecheck incremental cache + --jobs, and the runtime lockset race
sanitizer (SPACEMESH_SANITIZE=race).

Every static rule gets an offending fixture and a fixed/annotated twin;
the runtime side seeds an unguarded cross-thread write, a lock-order
inversion and a held-lock-across-await (the last detected both
statically and at runtime), and stays quiet on the clean multi-tenant
scheduler path.
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from spacemesh_tpu.tools.spacecheck import engine
from spacemesh_tpu.tools.spacecheck.__main__ import main as cli_main
from spacemesh_tpu.utils import sanitize


def run_fixture(tmp_path, rel, source, select=None):
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(source))
    findings, errors = engine.run_paths(
        [str(path)], project_root=str(tmp_path),
        select={select} if select else None)
    assert not errors, errors
    return findings


# --- SC007 lock discipline ----------------------------------------------


SC007_BAD = """
    import threading

    class Pool:
        def __init__(self):
            self._lock = threading.Lock()
            self._cursor = 0
            self._t = threading.Thread(target=self._worker)

        def _worker(self):
            with self._lock:
                self._cursor += 1

        def snapshot(self):
            return self._cursor      # bare read off-thread
"""


def test_sc007_flags_mixed_locked_bare_access(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/pool.py", SC007_BAD,
                     select="SC007")
    assert len(fs) == 1
    assert "_cursor" in fs[0].message and "snapshot()" in fs[0].message


def test_sc007_consistently_locked_twin_is_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/pool_ok.py", """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._cursor = 0
                self._t = threading.Thread(target=self._worker)

            def _worker(self):
                with self._lock:
                    self._cursor += 1

            def snapshot(self):
                with self._lock:
                    return self._cursor
    """, select="SC007")
    assert not fs


def test_sc007_condition_aliases_to_root_lock(tmp_path):
    # with self._idle (Condition(self._lock)) counts as holding _lock
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/cond.py", """
        import threading

        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._idle = threading.Condition(self._lock)
                self._durable = 0
                self._t = threading.Thread(target=self._worker)

            def _worker(self):
                with self._idle:
                    self._durable += 1

            def durable(self):
                with self._lock:
                    return self._durable
    """, select="SC007")
    assert not fs


def test_sc007_exemptions(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/exempt.py", """
        import threading

        class Pool:
            def __init__(self):
                self._lock = threading.Lock()
                self._cursor = 0
                self._mode = "x"     # written only here: read-only
                self._t = threading.Thread(target=self._worker)

            def _worker(self):
                with self._lock:
                    self._cursor += 1

            def kind(self):
                return self._mode    # immutable after construction: ok

            # guarded by: self._lock — callers hold it across the pick
            def pick(self):
                return self._cursor

            def peek(self):
                return self._cursor  # guarded by: self._lock (caller)

            def loop_view(self):
                # spacecheck: loop-only — read on the event loop thread only
                return self._cursor
    """, select="SC007")
    assert not fs


def test_sc007_non_threaded_class_is_skipped(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/single.py", """
        import threading

        class Local:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def bump(self):
                with self._lock:
                    self._n += 1

            def read(self):
                return self._n
    """, select="SC007")
    assert not fs


def test_sc007_container_mutation_counts_as_write(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/table.py", """
        import threading

        class Table:
            def __init__(self):
                self._lock = threading.Lock()
                self._jobs = {}
                self._t = threading.Thread(target=self._worker)

            def _worker(self):
                with self._lock:
                    self._jobs.pop("x", None)

            def put(self, k, v):
                self._jobs[k] = v    # bare container write
    """, select="SC007")
    assert len(fs) == 1 and "_jobs" in fs[0].message


def test_sc007_nested_closure_is_bare_even_inside_with(tmp_path):
    # a closure built under the lock RUNS later, without it
    fs = run_fixture(tmp_path, "spacemesh_tpu/post/closure.py", """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._state = 0
                self._t = threading.Thread(target=self._worker)

            def _worker(self):
                with self._lock:
                    self._state += 1

            def make(self):
                with self._lock:
                    return lambda: self._state
    """, select="SC007")
    assert len(fs) == 1 and "make()" in fs[0].message


# --- SC008 lock order ----------------------------------------------------


SC008_BAD = """
    import threading

    class AB:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one(self):
            with self._a:
                with self._b:
                    pass

        def two(self):
            with self._b:
                with self._a:
                    pass
"""


def test_sc008_flags_cycle_at_both_edges(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/runtime/ab.py", SC008_BAD,
                     select="SC008")
    assert len(fs) == 2
    assert all("lock-order cycle" in f.message for f in fs)


def test_sc008_consistent_order_is_clean(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/runtime/ab_ok.py", """
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._a:
                    with self._b:
                        pass
    """, select="SC008")
    assert not fs


def test_sc008_call_through_edge(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/runtime/call.py", """
        import threading

        class AB:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def helper(self):
                with self._b:
                    pass

            def one(self):
                with self._a:
                    self.helper()     # edge a -> b via the call

            def two(self):
                with self._b:
                    with self._a:     # edge b -> a: cycle
                        pass
    """, select="SC008")
    assert len(fs) == 2
    assert any("via self.helper()" in f.message for f in fs)


def test_sc008_await_under_threading_lock(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/api/wedge.py", """
        import asyncio
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            async def bad(self):
                with self._lock:
                    await asyncio.sleep(0.1)

            async def good(self):
                with self._lock:
                    snapshot = 1
                await asyncio.sleep(0.1)
                return snapshot
    """, select="SC008")
    assert len(fs) == 1
    assert "await inside" in fs[0].message and "bad()" in fs[0].message


def test_sc008_cross_function_cycle_in_one_module(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/runtime/mod.py", """
        import threading

        LOCK_A = threading.Lock()
        LOCK_B = threading.Lock()

        def fwd():
            with LOCK_A:
                with LOCK_B:
                    pass

        def rev():
            with LOCK_B:
                with LOCK_A:
                    pass
    """, select="SC008")
    assert len(fs) == 2


# --- SC002 extensions ----------------------------------------------------


def test_sc002_annotated_queue_binding_is_tracked(tmp_path):
    # the codebase's own idiom is an ANNOTATED assignment
    # (`self._q: queue.Queue = queue.Queue(...)`, post/data.py) — the
    # AnnAssign shape must register the queue var too (review fix)
    fs = run_fixture(tmp_path, "spacemesh_tpu/api/annq.py", """
        import queue

        class H:
            def __init__(self):
                self._q: queue.Queue = queue.Queue()

            async def bad(self):
                return self._q.get()
    """, select="SC002")
    assert len(fs) == 1 and "get() blocks" in fs[0].message


def test_sc002_future_result_and_queue_in_async(tmp_path):
    fs = run_fixture(tmp_path, "spacemesh_tpu/api/block.py", """
        import queue

        class H:
            def __init__(self):
                self._q = queue.Queue()

            async def bad(self, sched):
                h = sched.submit_prove("t", "/d", b"c")
                proof = h.result()            # blocking future wait
                job = h.future.result()       # ditto through .future
                item = self._q.get()          # blocking queue handoff
                self._q.put(item)
                return proof, job

            async def good(self, txstore, state, tid):
                res = txstore.result(state, tid)   # argful: a module fn
                self._q.put_nowait(res)
                return self._q.get_nowait()
    """, select="SC002")
    assert len(fs) == 4
    msgs = " ".join(f.message for f in fs)
    assert "h.result()" in msgs and "h.future.result()" in msgs
    assert "get() blocks" in msgs and "put() blocks" in msgs


# --- incremental cache + --jobs ------------------------------------------


def _seed_tree(tmp_path):
    pkg = tmp_path / "spacemesh_tpu" / "sim"
    pkg.mkdir(parents=True, exist_ok=True)
    (pkg / "a.py").write_text(
        "import time\n\ndef bad():\n    return time.time()\n")
    (pkg / "b.py").write_text("def ok(now):\n    return now + 1\n")
    return pkg


def test_cache_cold_and_warm_runs_are_identical(tmp_path):
    _seed_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    paths = [str(tmp_path / "spacemesh_tpu")]
    cold, errs = engine.run_paths(paths, project_root=str(tmp_path),
                                  cache=cache)
    assert not errs and cold
    assert os.path.exists(cache)
    warm, errs = engine.run_paths(paths, project_root=str(tmp_path),
                                  cache=cache)
    assert not errs
    assert [vars(f) for f in warm] == [vars(f) for f in cold]


def test_warm_run_is_a_pure_cache_hit(tmp_path, monkeypatch):
    # rules never execute on a warm identical tree: crash every rule
    # and the warm run still reproduces the cold findings
    _seed_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    paths = [str(tmp_path / "spacemesh_tpu")]
    cold, _ = engine.run_paths(paths, project_root=str(tmp_path),
                               cache=cache)
    monkeypatch.setattr(engine, "_check_context",
                        lambda *a: (_ for _ in ()).throw(
                            AssertionError("rules ran on a warm tree")))
    warm, errs = engine.run_paths(paths, project_root=str(tmp_path),
                                  cache=cache)
    assert not errs
    assert [vars(f) for f in warm] == [vars(f) for f in cold]


def test_cache_invalidates_on_any_file_change(tmp_path):
    pkg = _seed_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    paths = [str(tmp_path / "spacemesh_tpu")]
    cold, _ = engine.run_paths(paths, project_root=str(tmp_path),
                               cache=cache)
    assert len(cold) == 1
    # cross-file soundness: editing ONE file recomputes the whole tree
    (pkg / "b.py").write_text(
        "import time\n\ndef worse():\n    return time.monotonic()\n")
    fresh, _ = engine.run_paths(paths, project_root=str(tmp_path),
                                cache=cache)
    assert len(fresh) == 2
    warm, _ = engine.run_paths(paths, project_root=str(tmp_path),
                               cache=cache)
    assert [vars(f) for f in warm] == [vars(f) for f in fresh]


def test_select_runs_bypass_the_cache(tmp_path):
    _seed_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    findings, _ = engine.run_paths([str(tmp_path / "spacemesh_tpu")],
                                   project_root=str(tmp_path),
                                   cache=cache, select={"SC001"})
    assert findings
    assert not os.path.exists(cache)


def test_jobs_parallel_findings_match_serial(tmp_path):
    pkg = _seed_tree(tmp_path)
    (pkg / "c.py").write_text(textwrap.dedent(SC007_BAD))
    (pkg / "d.py").write_text(textwrap.dedent(SC008_BAD))
    paths = [str(tmp_path / "spacemesh_tpu")]
    serial, errs1 = engine.run_paths(paths, project_root=str(tmp_path))
    par, errs2 = engine.run_paths(paths, project_root=str(tmp_path),
                                  jobs=3)
    assert [vars(f) for f in par] == [vars(f) for f in serial]
    assert errs1 == errs2
    assert {f.rule for f in serial} >= {"SC001", "SC007", "SC008"}


def test_cli_jobs_and_cache_flags(tmp_path, capsys):
    _seed_tree(tmp_path)
    cache = str(tmp_path / "cli_cache.json")
    args = [str(tmp_path / "spacemesh_tpu"), "--root", str(tmp_path),
            "--cache", cache, "--jobs", "2"]
    assert cli_main(args) == 1           # the seeded SC001 fails it
    assert os.path.exists(cache)
    assert cli_main(args) == 1           # warm: same verdict
    assert cli_main(args[:3] + ["--no-cache"]) == 1


# --- runtime sanitizer: modes + thresholds -------------------------------


def test_mode_parsing():
    assert sanitize.parse_modes("1") == frozenset(sanitize.KINDS)
    assert sanitize.parse_modes("all") == frozenset(sanitize.KINDS)
    assert sanitize.parse_modes("race") == {sanitize.KIND_RACE}
    assert sanitize.parse_modes("lockset") == {sanitize.KIND_RACE}
    assert sanitize.parse_modes("slow, shape") == \
        {sanitize.KIND_SLOW, sanitize.KIND_SHAPE}
    assert sanitize.parse_modes("registry-thread") == \
        {sanitize.KIND_REGISTRY}
    assert sanitize.parse_modes("") == frozenset()
    assert sanitize.parse_modes("off") == frozenset()
    assert sanitize.parse_modes(None) == frozenset()
    # unknown tokens are ignored, never arm everything
    assert sanitize.parse_modes("bogus") == frozenset()
    assert sanitize.parse_modes("race,bogus") == {sanitize.KIND_RACE}


def test_slow_threshold_parsing():
    assert sanitize.parse_slow_threshold(None) is None
    assert sanitize.parse_slow_threshold("") is None
    assert sanitize.parse_slow_threshold("250") == 0.25
    assert sanitize.parse_slow_threshold("1") == 0.001
    # edge values fall back to the default, silently neither silencing
    # nor spamming the check
    assert sanitize.parse_slow_threshold("0") is None
    assert sanitize.parse_slow_threshold("-10") is None
    assert sanitize.parse_slow_threshold("garbage") is None


@pytest.fixture
def race_mode():
    sanitize.clear_violations()
    sanitize.enable(modes=["race"])
    yield sanitize
    sanitize.disable()
    sanitize.clear_violations()


def test_race_mode_arms_only_race(race_mode):
    assert sanitize.race_enabled()
    assert sanitize.enabled(sanitize.KIND_RACE)
    assert not sanitize.enabled(sanitize.KIND_SLOW)
    assert not sanitize.enabled(sanitize.KIND_SHAPE)
    # the shape guard stays dormant under race-only
    sanitize.on_jit_shape("labels_fused", 7)
    assert not sanitize.violations()


def test_env_boot_race_mode(tmp_path):
    code = textwrap.dedent("""
        from spacemesh_tpu.utils import sanitize
        assert sanitize.enabled()
        assert sanitize.race_enabled()
        assert not sanitize.enabled(sanitize.KIND_SLOW)
        assert isinstance(sanitize.lock("x"), sanitize.TrackedLock)
        print("race boot ok")
    """)
    env = os.environ | {"SPACEMESH_SANITIZE": "race",
                        "JAX_PLATFORMS": "cpu"}
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "race boot ok" in res.stdout


def test_env_boot_garbage_mode_stays_off():
    code = ("from spacemesh_tpu.utils import sanitize; "
            "assert not sanitize.enabled(); print('off ok')")
    env = os.environ | {"SPACEMESH_SANITIZE": "bogus",
                        "JAX_PLATFORMS": "cpu"}
    res = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stdout + res.stderr


# --- runtime sanitizer: seeded races -------------------------------------


def test_seeded_cross_thread_write_with_attribution(race_mode):
    from spacemesh_tpu.utils import tracing

    tracing.start(capacity=64)
    try:
        field = sanitize.SharedField("test.cursor")
        lock = sanitize.lock("test.lock")
        with lock:
            field.touch()                      # thread A, locked
        seen = {}

        def racer():
            with tracing.span("racer.write") as sp:
                seen["span"] = sp.id
                field.touch()                  # thread B, bare

        t = threading.Thread(target=racer, name="racer")
        t.start()
        t.join()
    finally:
        tracing.stop()
    hits = [v for v in sanitize.violations() if v.kind == "race"]
    assert len(hits) == 1
    v = hits[0]
    assert "test.cursor" in v.detail
    assert v.thread == "racer" and v.stack and "racer" in v.stack
    assert v.other_stack, "the first thread's stack must be attached"
    assert v.span == seen["span"]


def test_consistent_locking_stays_quiet(race_mode):
    field = sanitize.SharedField("test.quiet")
    lock = sanitize.lock("test.quiet.lock")
    with lock:
        field.touch()

    def worker():
        for _ in range(50):
            with lock:
                field.touch()

    ts = [threading.Thread(target=worker) for _ in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not sanitize.violations()


def test_seeded_lock_order_inversion(race_mode):
    a = sanitize.lock("order.A")
    b = sanitize.lock("order.B")
    with a:
        with b:
            pass

    def inverted():
        with b:
            with a:
                pass

    t = threading.Thread(target=inverted, name="inverter")
    t.start()
    t.join()
    hits = [v for v in sanitize.violations() if v.kind == "lock-order"]
    assert len(hits) == 1
    v = hits[0]
    assert "order.A" in v.detail and "order.B" in v.detail
    assert v.stack and "inverted" in v.stack
    assert v.other_stack, "the first ordering's stack must be attached"


def test_condition_wait_releases_held_key(race_mode):
    lock = sanitize.lock("cond.lock")
    cond = sanitize.condition("cond.idle", lock)
    woke = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            woke.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:          # acquirable because wait() dropped the lock
        cond.notify_all()
    t.join(timeout=5)
    assert woke and not sanitize.violations()


def test_lock_across_await_detected_at_runtime(race_mode):
    lk = sanitize.lock("held.lock")

    async def wedge():
        with lk:
            await asyncio.sleep(0.01)

    asyncio.run(wedge())
    hits = [v for v in sanitize.violations()
            if v.kind == "lock-across-await"]
    assert hits and "held.lock" in hits[0].detail


def test_lock_across_await_detected_statically(tmp_path):
    # the same defect's static twin: SC008 flags it without running
    fs = run_fixture(tmp_path, "spacemesh_tpu/api/wedge2.py", """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            async def wedge(self):
                with self._lock:
                    await do_io()
    """, select="SC008")
    assert len(fs) == 1 and "await inside" in fs[0].message


def test_clean_scheduler_e2e_stays_quiet(race_mode, tmp_path):
    """The fixed tree's multi-tenant path reports nothing — this is the
    regression test for the ISSUE 12 sweep fixes (the scheduler's
    _lane_cost_ema EMA is now read-modify-written under the scheduler
    lock; pre-fix, this exact run reported an empty candidate lockset
    on runtime.scheduler.tenants' EMA touch)."""
    import hashlib

    from spacemesh_tpu.runtime import TenantScheduler

    ids = [(f"t{i}", hashlib.sha256(b"rc-n%d" % i).digest(),
            hashlib.sha256(b"rc-c%d" % i).digest()) for i in range(2)]
    with TenantScheduler(workers=2, pack_lanes=128,
                         writer_threads=1) as sched:
        handles = []
        for tid, node, commit in ids:
            sched.register_tenant(tid)
            handles.append(sched.submit_init(
                tid, tmp_path / tid, node_id=node, commitment=commit,
                num_units=1, labels_per_unit=160, scrypt_n=2,
                max_file_size=1 << 20))
        for h in handles:
            h.result(timeout=300)
        for tid, _, _ in ids:
            sched.unregister_tenant(tid)
    bad = sanitize.violations()
    assert not bad, "\n".join(f"{v.kind}: {v.detail}\n  {v.stack}"
                              for v in bad)


def test_violation_counter_survives_flight_bundle(race_mode, tmp_path):
    from spacemesh_tpu.obs import flight as flight_mod
    from spacemesh_tpu.utils import metrics

    before = metrics.sanitize_violations.sample().get(
        (("kind", "race"),), 0.0)
    field = sanitize.SharedField("test.flight")
    lock = sanitize.lock("test.flight.lock")
    with lock:
        field.touch()
    t = threading.Thread(target=field.touch)
    t.start()
    t.join()
    assert [v for v in sanitize.violations() if v.kind == "race"]
    after = metrics.sanitize_violations.sample()[(("kind", "race"),)]
    assert after == before + 1
    rec = flight_mod.FlightRecorder(tmp_path / "spool",
                                    time_source=lambda: 1000.0)
    path = rec.dump("test:race", now=1000.0, force=True)
    assert path is not None
    bundle = flight_mod.read_bundle(path)
    prom = (path / "metrics.prom").read_text()
    assert f'sanitize_violations_total{{kind="race"}} {after}' in prom
    kinds = {v["kind"] for v in bundle["manifest"]["sanitize_violations"]}
    assert "race" in kinds


def test_owner_write_reset_allows_ownership_handoff(race_mode):
    # LaneGroup.bind() recreates its state on a new event loop, which
    # may live on another thread: reset() must forget the dead owner
    # instead of reporting the sanctioned handoff as a race (review fix)
    f = sanitize.SharedField("test.handoff", mode="owner-write")
    f.touch()                       # main thread claims

    def rebound_owner():
        f.reset()                   # the rebind path
        f.touch()                   # new owner, legitimately

    t = threading.Thread(target=rebound_owner)
    t.start()
    t.join()
    assert not sanitize.violations()


def test_lanegroup_rebind_resets_owner(race_mode):
    import enum

    from spacemesh_tpu.runtime.queue import LaneGroup

    class L(enum.IntEnum):
        ONLY = 0

    group = LaneGroup(L, {L.ONLY: 4})

    async def drive():
        group.bind(asyncio.get_running_loop())
        group.add(L.ONLY)
        group.release(L.ONLY)

    asyncio.run(drive())            # first loop: this thread owns

    def second_loop():
        asyncio.run(drive())        # rebind from ANOTHER thread

    t = threading.Thread(target=second_loop)
    t.start()
    t.join()
    assert not sanitize.violations(), sanitize.violations()


def test_enable_unknown_mode_token_is_ignored_not_fatal():
    sanitize.clear_violations()
    try:
        sanitize.enable(modes=["bogus", "race"])
        assert sanitize.race_enabled()
        assert not sanitize.enabled(sanitize.KIND_SLOW)
        sanitize.enable(modes=["slowcallback"])   # typo: nothing arms
        assert not sanitize.enabled()
    finally:
        sanitize.disable()


def test_cli_path_subset_does_not_clobber_full_cache(tmp_path):
    pkg = _seed_tree(tmp_path)
    cache = str(tmp_path / "cache.json")
    env_key = "SPACEMESH_SPACECHECK_CACHE"
    old = os.environ.get(env_key)
    os.environ[env_key] = cache
    try:
        root_args = ["--root", str(tmp_path)]
        assert cli_main(root_args) == 1          # full default-path run
        doc = json.loads(open(cache).read())
        # a targeted run over one file must not overwrite the full-tree
        # doc with a subset (review fix) ...
        assert cli_main([str(pkg / "b.py")] + root_args) == 0
        assert json.loads(open(cache).read()) == doc
        # ... while an explicit --cache FILE is the caller's own
        mine = str(tmp_path / "mine.json")
        assert cli_main([str(pkg / "b.py")] + root_args +
                        ["--cache", mine]) == 0
        assert os.path.exists(mine)
    finally:
        if old is None:
            os.environ.pop(env_key, None)
        else:
            os.environ[env_key] = old


def test_tracked_primitives_off_by_default():
    sanitize.disable()
    assert isinstance(sanitize.lock("x"), type(threading.Lock()))
    assert isinstance(sanitize.condition("x"), threading.Condition)
    f = sanitize.SharedField("off.field")
    f.touch()   # no state, no report
    assert not sanitize.violations()
