"""k2pow and proving-hash primitives: ground truth + statistics."""

import hashlib

import numpy as np
import pytest

from spacemesh_tpu.ops import pow as k2pow
from spacemesh_tpu.ops import proving, scrypt

CH = hashlib.sha256(b"challenge").digest()
NID = hashlib.sha256(b"node").digest()


def cpu_pow_hash(challenge, node_id, nonce):
    return hashlib.sha256(challenge + node_id + int(nonce).to_bytes(8, "little")).digest()


def test_pow_hash_device_path_matches_hashlib():
    # the DEVICE batch path (used by search) against the hashlib ground truth
    import jax.numpy as jnp

    nonces = np.array([0, 1, 12345, 2**32 + 7, 2**63 - 1], dtype=np.uint64)
    st = jnp.asarray(k2pow.prefix_state(CH, NID))
    lo = jnp.asarray((nonces & 0xFFFFFFFF).astype(np.uint32))
    hi = jnp.asarray((nonces >> 32).astype(np.uint32))
    d = np.asarray(k2pow.pow_hash_batch_jit(st, lo, hi))
    for k, nonce in enumerate(nonces):
        want = cpu_pow_hash(CH, NID, int(nonce))
        assert d[:, k].astype(">u4").tobytes() == want
        assert k2pow.pow_hash(CH, NID, int(nonce)) == want


def test_pow_search_and_verify():
    # easy difficulty: top byte 0x04 -> ~1/64 chance per nonce
    difficulty = bytes([0x04]) + bytes(31)
    nonce = k2pow.search(CH, NID, difficulty, batch=512, max_batches=8)
    assert nonce is not None
    assert k2pow.verify(CH, NID, difficulty, nonce)
    assert cpu_pow_hash(CH, NID, nonce) < difficulty
    # the found nonce is the first qualifying one in scan order
    for earlier in range(min(nonce, 200)):
        assert cpu_pow_hash(CH, NID, earlier) >= difficulty
    assert not k2pow.verify(CH, NID, bytes(32), nonce)  # impossible target


def test_pow_input_validation():
    with pytest.raises(ValueError):
        k2pow.search(CH, NID, b"short")
    with pytest.raises(ValueError):
        k2pow.prefix_state(b"x", NID)


def _mixed_pow_items(count, seed=9):
    """Deterministic mixed witnesses: per-item prefixes, difficulties
    spread around the acceptance boundary, 32/64-bit nonces."""
    rng = np.random.RandomState(seed)
    items = []
    for i in range(count):
        c = hashlib.sha256(b"powv-c%d" % i).digest()
        nid = hashlib.sha256(b"powv-n%d" % i).digest()
        diff = bytes(rng.randint(0, 256, size=32, dtype=np.int64)
                     .astype(np.uint8).tolist())
        nonce = int(rng.randint(0, 1 << 31))
        if i % 5 == 0:
            nonce |= (i + 1) << 33  # exercise the hi-u32 lanes
        items.append((c, nid, diff, nonce))
    return items


def test_pow_verify_many_device_matches_scalar():
    """The batched per-item-prefix device path (verifyd's farm kind) is
    bit-identical to scalar verify across chunking/padding seams."""
    items = _mixed_pow_items(37)
    expected = [k2pow.verify(*it) for it in items]
    assert any(expected) or True  # difficulties are random; just run
    # small chunks + ragged tail (pad to bucket) through the engine
    assert k2pow.verify_many(items, batch=16, min_device=1) == expected
    # one whole-batch chunk
    assert k2pow.verify_many(items, batch=4096, min_device=1) == expected
    # host path (below min_device) agrees
    assert k2pow.verify_many(items, min_device=1000) == expected
    assert k2pow.verify_many([]) == []


def test_pow_verify_many_fallback_identity(monkeypatch):
    """A device dispatch failure degrades the chunk to the host scan —
    same verdicts, counted in runtime_fallbacks_total."""
    from spacemesh_tpu.utils import metrics

    items = _mixed_pow_items(24, seed=11)
    expected = [k2pow.verify(*it) for it in items]

    def boom(*a, **k):
        raise RuntimeError("device gone")

    monkeypatch.setattr(k2pow, "pow_verify_batch_jit", boom)
    before = metrics.runtime_fallbacks.sample().get(
        (("kind", "k2pow_verify"),), 0)
    assert k2pow.verify_many(items, batch=8, min_device=1) == expected
    after = metrics.runtime_fallbacks.sample().get(
        (("kind", "k2pow_verify"),), 0)
    assert after >= before + 3  # one per chunk


def test_pow_verify_many_validates_inputs():
    with pytest.raises(ValueError):
        k2pow.verify_many([(b"x", NID, bytes(32), 1)])
    with pytest.raises(ValueError):
        k2pow.verify_many([(CH, NID, b"short", 1)])
    # out-of-u64 nonces fail fast with a clear error, never a mid-batch
    # OverflowError from np.array/to_bytes
    with pytest.raises(ValueError, match="64-bit"):
        k2pow.verify_many([(CH, NID, bytes(32), 1 << 64)])
    with pytest.raises(ValueError, match="64-bit"):
        k2pow.verify_many([(CH, NID, bytes(32), -1)])


def test_pow_verify_runtime_kind_registered():
    """k2pow_verify is a registered workload kind with a warm recipe
    (tools/warmcache.py + the warm-cache CI job cover it)."""
    from spacemesh_tpu.runtime import workloads

    kind = workloads.get("k2pow_verify")
    assert any(k.name == "k2pow_verify" for k in workloads.registered())
    doc = kind.warm(8, 17)
    assert doc["batch"] == 32  # bucketed to the padded shape
    assert "pow_verify_batch" in doc


def test_proving_hash_deterministic_and_keyed():
    idx = np.arange(64, dtype=np.uint64)
    labels = scrypt.scrypt_labels(NID, idx, n=4)
    a = proving.proving_hashes(CH, 7, idx, labels)
    b = proving.proving_hashes(CH, 7, idx, labels)
    assert np.array_equal(a, b)
    # nonce, challenge, index, and label all key the hash
    assert not np.array_equal(a, proving.proving_hashes(CH, 8, idx, labels))
    other_ch = hashlib.sha256(b"other").digest()
    assert not np.array_equal(a, proving.proving_hashes(other_ch, 7, idx, labels))
    labels2 = np.array(labels)
    labels2[0] ^= 1
    assert a[0] != proving.proving_hashes(CH, 7, idx, labels2)[0]


def test_threshold_statistics():
    # E[qualifying] = k1: with 4096 labels and k1=256, expect ~256 +- 5 sigma
    total = 4096
    k1 = 256
    t = proving.threshold_u32(k1, total)
    idx = np.arange(total, dtype=np.uint64)
    labels = scrypt.scrypt_labels(NID, idx, n=2)
    vals = proving.proving_hashes(CH, 0, idx, labels)
    count = int((vals < t).sum())
    sigma = (k1 * (1 - k1 / total)) ** 0.5
    assert abs(count - k1) < 6 * sigma, (count, k1)


def test_proving_scan_matches_single_nonce():
    import jax.numpy as jnp

    idx = np.arange(128, dtype=np.uint64)
    labels = scrypt.scrypt_labels(NID, idx, n=2)
    t = proving.threshold_u32(16, 128)
    lo, hi = scrypt.split_indices(idx)
    lw = np.ascontiguousarray(labels).view("<u4").reshape(-1, 4).T.astype(np.uint32)
    cw = np.frombuffer(CH, dtype="<u4").astype(np.uint32)
    mask = np.asarray(proving.proving_scan_jit(
        jnp.asarray(cw), jnp.uint32(3), jnp.asarray(lo), jnp.asarray(hi),
        jnp.asarray(lw), jnp.uint32(t), n_nonces=4))
    assert mask.shape == (4, 128)
    for k in range(4):
        vals = proving.proving_hashes(CH, 3 + k, idx, labels)
        assert np.array_equal(mask[k], vals < t)
