"""Streaming prove pipeline: reader pool, compacted hits, proof identity.

The prover-side mirror of test_post_pipeline.py: the pipelined scan must
produce bit-identical proofs to the legacy serial path over every backend
(XLA, Pallas-interpret, virtual mesh), read the store at most once per
nonce window, and keep the per-batch device->host traffic to compacted
hits instead of masks.
"""

import hashlib
import os
import threading
import time

import numpy as np
import pytest

from spacemesh_tpu.ops import proving, scrypt
from spacemesh_tpu.post import initializer
from spacemesh_tpu.post.data import LabelReader, LabelStore, PostMetadata
from spacemesh_tpu.post.prover import ProofParams, Prover
from spacemesh_tpu.utils import metrics

NODE = hashlib.sha256(b"pipe-node").digest()
COMMIT = hashlib.sha256(b"pipe-commit").digest()
CH = hashlib.sha256(b"pipe-challenge").digest()

PARAMS = ProofParams(k1=64, k2=16, k3=8,
                     pow_difficulty=bytes([255]) * 32)


@pytest.fixture(scope="module")
def unit(tmp_path_factory):
    d = tmp_path_factory.mktemp("prove-pipe")
    meta, _ = initializer.initialize(
        d, node_id=NODE, commitment=COMMIT, num_units=1,
        labels_per_unit=2048, scrypt_n=2, max_file_size=8192,
        batch_size=512)
    return d, meta


@pytest.fixture(scope="module")
def serial_proof(unit):
    d, _ = unit
    return Prover(d, PARAMS, batch_labels=512).prove_serial(CH)


# -- proof identity across backends -----------------------------------------


def test_pipelined_matches_serial(unit, serial_proof):
    d, _ = unit
    prover = Prover(d, PARAMS, batch_labels=512, pipelined=True)
    assert prover.prove(CH) == serial_proof
    assert prover.last_stats is not None
    assert prover.last_stats.batches > 0


def test_wide_window_matches_serial(unit, serial_proof):
    # window spanning several nonce groups still picks the serial winner
    d, _ = unit
    prover = Prover(d, PARAMS, batch_labels=512, window_groups=4)
    assert prover.prove(CH) == serial_proof


def test_pallas_backend_matches_serial(unit, serial_proof):
    d, _ = unit
    prover = Prover(d, PARAMS, batch_labels=512, use_pallas=True)
    assert prover.prove(CH) == serial_proof


def test_sharded_backend_matches_serial(unit, serial_proof, monkeypatch):
    # conftest forces 8 virtual CPU devices; SPACEMESH_MESH=1 opts the
    # prover into lane sharding on them (as test_parallel does for init)
    d, _ = unit
    monkeypatch.setenv("SPACEMESH_MESH", "1")
    prover = Prover(d, PARAMS, batch_labels=512)
    assert prover._resolve_mesh() is not None
    assert prover.prove(CH) == serial_proof


def test_ragged_tail_single_shape(unit, serial_proof):
    # 2048 labels with batch 768: ragged 512-label tail is padded, not
    # recompiled or path-flipped; proof unchanged
    d, _ = unit
    prover = Prover(d, PARAMS, batch_labels=768)
    assert prover.batch_labels % proving.HIT_SEGMENT == 0
    assert prover.prove(CH) == serial_proof


# -- disk frugality + compacted D2H -----------------------------------------


def _read_bytes() -> float:
    return metrics.post_store_read_bytes._values.get((), 0.0)


def test_one_disk_pass_per_window(unit):
    d, meta = unit
    store_bytes = meta.total_labels * scrypt.LABEL_BYTES
    prover = Prover(d, PARAMS, batch_labels=512)
    before = _read_bytes()
    prover.prove(CH)
    stats = prover.last_stats
    read = _read_bytes() - before
    # at most one full store read per scanned nonce window (the reader may
    # have prefetched past an early exit by at most its queue depth)
    slack = prover.reader_queue * prover.batch_labels * scrypt.LABEL_BYTES
    assert read <= stats.windows * store_bytes + slack
    assert stats.windows >= 1


def test_early_exit_reads_less_than_store(unit):
    # k1=64 >> k2=16: nonce 0 qualifies after a fraction of the store, so
    # the sound early exit fires and the pass never reads the whole store
    d, meta = unit
    prover = Prover(d, PARAMS, batch_labels=256, inflight=1,
                    reader_queue=1)
    before = _read_bytes()
    proof = prover.prove(CH)
    read = _read_bytes() - before
    assert prover.last_stats.early_exited
    assert proof.nonce == 0
    assert read < meta.total_labels * scrypt.LABEL_BYTES


def test_d2h_is_compacted_hits_not_masks(unit):
    d, meta = unit
    prover = Prover(d, PARAMS, batch_labels=512)
    prover.prove(CH)
    stats = prover.last_stats
    # full masks would be nonce_group * batch bytes per batch; the
    # compacted path moves one count vector per batch plus one hit-pair
    # carry per pass
    mask_bytes = stats.batches * prover.nonce_group * prover.batch_labels
    assert stats.d2h_bytes < mask_bytes / 8
    assert stats.d2h_bytes > 0


# -- the compacted-scan step itself -----------------------------------------


def test_prove_step_accumulates_across_batches():
    import jax.numpy as jnp

    total, b, ng, cap = 1024, 512, 4, 8
    labels = scrypt.scrypt_labels(COMMIT, np.arange(total, dtype=np.uint64),
                                  n=2)
    t = proving.threshold_u32(24, total)
    cw = jnp.asarray(proving.challenge_words(CH))
    counts, carry = proving.init_hit_state(ng, cap)
    for start in range(0, total, b):
        idx = np.arange(start, start + b, dtype=np.uint64)
        lo, hi = scrypt.split_indices(idx)
        lw = scrypt.labels_to_words(labels[start:start + b])
        counts, _, carry = proving.prove_scan_step_jit(
            cw, jnp.uint32(0), jnp.asarray(lo), jnp.asarray(hi),
            jnp.asarray(lw), jnp.uint32(t), counts, carry,
            jnp.uint32(b), jnp.uint32(start), jnp.uint32(0),
            n_nonces=ng, max_hits=cap)
    counts_np = np.asarray(counts)
    for k in range(ng):
        vals = proving.proving_hashes(CH, k, np.arange(total, dtype=np.uint64),
                                      labels)
        want = np.nonzero(vals < t)[0]
        assert counts_np[k] == len(want)
        got = proving.decode_hits(counts, carry, k, cap)
        assert got == [int(i) for i in want[:cap]]


def test_prove_step_high_index_batches():
    # global label indices past 2^32: the u32 lo/hi split must carry
    import jax.numpy as jnp

    b, ng, cap = 256, 2, 8
    start = (1 << 32) - 128  # batch straddles the u32 boundary
    idx = np.arange(start, start + b, dtype=np.uint64)
    labels = scrypt.scrypt_labels(COMMIT, idx, n=2)
    t = proving.threshold_u32(32, b)
    cw = jnp.asarray(proving.challenge_words(CH))
    counts, carry = proving.init_hit_state(ng, cap)
    lo, hi = scrypt.split_indices(idx)
    lw = scrypt.labels_to_words(labels)
    counts, _, carry = proving.prove_scan_step_jit(
        cw, jnp.uint32(0), jnp.asarray(lo), jnp.asarray(hi),
        jnp.asarray(lw), jnp.uint32(t), counts, carry, jnp.uint32(b),
        jnp.uint32(start & 0xFFFFFFFF), jnp.uint32(start >> 32),
        n_nonces=ng, max_hits=cap)
    for k in range(ng):
        vals = proving.proving_hashes(CH, k, idx, labels)
        want = [int(start + i) for i in np.nonzero(vals < t)[0][:cap]]
        assert proving.decode_hits(counts, carry, k, cap) == want


# -- LabelReader pool --------------------------------------------------------


def _tiny_store(tmp_path, labels=512):
    meta = PostMetadata(node_id=NODE.hex(), commitment=COMMIT.hex(),
                        scrypt_n=2, num_units=1, labels_per_unit=labels,
                        max_file_size=1 << 20, labels_written=labels)
    store = LabelStore(tmp_path, meta)
    data = bytes(range(256)) * (labels * scrypt.LABEL_BYTES // 256)
    store.write_labels(0, data)
    return store, data


def test_reader_delivers_in_plan_order(tmp_path):
    store, data = _tiny_store(tmp_path)
    ranges = [(i * 64, 64) for i in range(8)]
    reader = store.start_reader(ranges, threads=3, depth=2)
    try:
        for start, count in ranges:
            lb = scrypt.LABEL_BYTES
            assert reader.get() == data[start * lb:(start + count) * lb]
    finally:
        reader.close()
    assert reader.bytes_read == len(data)


def test_reader_bounded_readahead(tmp_path):
    store, _ = _tiny_store(tmp_path)
    ranges = [(i * 32, 32) for i in range(16)]
    reader = store.start_reader(ranges, threads=2, depth=3)
    try:
        time.sleep(0.2)  # let the pool run ahead as far as it is allowed
        with reader._cond:
            buffered = len(reader._results)
        assert buffered <= 3
        for _ in ranges:
            reader.get()
    finally:
        reader.close()


def test_reader_error_propagates(tmp_path):
    store, data = _tiny_store(tmp_path)
    ranges = [(0, 32), (100000, 32)]  # second range is past EOF
    reader = store.start_reader(ranges, threads=1, depth=2)
    try:
        time.sleep(0.3)  # let the pool buffer slot 0 AND fail slot 1
        # an in-order result buffered before the failure still delivers;
        # the error surfaces on the range that is actually missing
        assert reader.get() == data[:32 * 16]
        with pytest.raises(RuntimeError, match="label reader failed"):
            reader.get()
    finally:
        reader.close()


def test_reader_close_mid_plan(tmp_path):
    store, _ = _tiny_store(tmp_path)
    ranges = [(i * 16, 16) for i in range(32)]
    reader = store.start_reader(ranges, threads=2, depth=2)
    reader.get()
    reader.close()  # early exit: pending reads dropped, no hang
    assert all(not t.is_alive() for t in reader._threads)


def test_read_fds_cached(tmp_path):
    store, data = _tiny_store(tmp_path)
    for _ in range(5):
        assert store.read_labels(10, 4) == data[10 * 16:14 * 16]
    assert len(store._read_fds) == 1
    store.close()
    assert not store._read_fds
    # reads reopen transparently after close
    assert store.read_labels(0, 2) == data[:32]
    store.close()


def test_read_fds_thread_safe(tmp_path):
    store, data = _tiny_store(tmp_path)
    errs = []

    def hammer():
        try:
            for i in range(50):
                assert store.read_labels(i % 32, 8) \
                    == data[(i % 32) * 16:((i % 32) + 8) * 16]
        except BaseException as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    store.close()


# -- knob plumbing -----------------------------------------------------------


def test_env_knobs(unit, monkeypatch):
    d, _ = unit
    monkeypatch.setenv("SPACEMESH_PROVE_PIPELINE", "off")
    monkeypatch.setenv("SPACEMESH_PROVE_WINDOW_GROUPS", "3")
    monkeypatch.setenv("SPACEMESH_PROVE_INFLIGHT", "5")
    monkeypatch.setenv("SPACEMESH_PROVE_READERS", "4")
    monkeypatch.setenv("SPACEMESH_PROVE_QUEUE", "7")
    p = Prover(d, PARAMS)
    assert not p.pipelined
    assert (p.window_groups, p.inflight, p.readers, p.reader_queue) \
        == (3, 5, 4, 7)
    # explicit args beat the environment
    p = Prover(d, PARAMS, pipelined=True, window_groups=1, inflight=2,
               readers=1, reader_queue=2)
    assert p.pipelined
    assert (p.window_groups, p.inflight, p.readers, p.reader_queue) \
        == (1, 2, 1, 2)


def test_post_client_prove_opts(unit):
    from spacemesh_tpu.post.service import PostClient

    d, meta = unit
    client = PostClient(d, PARAMS, batch_labels=512, pipelined=False)
    proof, got_meta = client.proof(CH)
    assert got_meta.total_labels == meta.total_labels
    assert proof == Prover(d, PARAMS, batch_labels=512).prove(CH)
