"""Fuzz the remote-input surfaces (VERDICT r3 item 9).

Three layers, mirroring the reference's fuzz strategy (scripts/fuzz.sh +
gofuzz seeds in common/types): raw transport framing, gossip handler
inputs, and req/resp server handlers. The invariant everywhere: malformed
bytes from the network may be rejected, but must never take the node (or
its event loop) down.
"""

import asyncio
import os
import random

import pytest

from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.p2p.server import LoopbackNet
from tests.test_transport import GEN, _mk, _wait

SEED = 0xF0220


def _garbage_corpus(rng, valid_blobs=(), n=120):
    """Noise, truncations, bit flips, and pathological frames."""
    out = []
    for _ in range(n):
        kind = rng.randrange(4 if valid_blobs else 2)
        if kind == 0:
            out.append(bytes(rng.getrandbits(8)
                             for _ in range(rng.randrange(256))))
        elif kind == 1:  # length-prefix lies: huge / zero / negative-ish
            out.append(rng.choice([
                b"\xff\xff\xff\xff" + os.urandom(16),
                b"\x00\x00\x00\x00",
                (1 << 20).to_bytes(4, "little") + os.urandom(64),
            ]))
        elif kind == 2:
            base = rng.choice(valid_blobs)
            out.append(base[:rng.randrange(len(base))])
        else:
            base = bytearray(rng.choice(valid_blobs))
            base[rng.randrange(len(base))] ^= 1 << rng.randrange(8)
            out.append(bytes(base))
    return out


# --- transport framing ------------------------------------------------------


def test_tcp_host_survives_raw_garbage():
    """Pre-handshake garbage over raw sockets — noise floods, lying
    length prefixes, half-frames, abrupt closes — must leave the host
    able to serve a legitimate peer."""

    async def go():
        rng = random.Random(SEED)
        host, ps, _ = _mk(b"z")
        await host.start()
        addr = host.address

        for blob in _garbage_corpus(rng, n=60):
            try:
                r, w = await asyncio.open_connection(*addr)
                w.write(blob)
                await w.drain()
                if rng.random() < 0.5:
                    await asyncio.sleep(0.01)
                w.close()
            except OSError:
                pass  # the host may hang up mid-write; that's fine
        await asyncio.sleep(0.2)

        # the host is still alive and does real work
        peer, psp, _ = _mk(b"y")
        got = []

        async def h(p, data):
            got.append(data)
            return True

        psp.register("fz", h)
        await peer.start()
        await peer._dial(addr)
        await _wait(lambda: len(peer.nodes) >= 1)
        await ps.publish("fz", b"still-alive")
        await _wait(lambda: got)
        assert got == [b"still-alive"]
        await peer.stop()
        await host.stop()

    asyncio.run(asyncio.wait_for(go(), 60))


def test_quic_endpoint_survives_raw_garbage():
    """Random datagrams (wrong magic, lying headers, truncated packets)
    against the UDP endpoint; a legitimate connection still completes."""
    from spacemesh_tpu.p2p.quic import QuicEndpoint

    async def go():
        rng = random.Random(SEED + 1)
        got = asyncio.Queue()

        async def on_accept(reader, writer):
            got.put_nowait(await reader.readexactly(4))

        server = QuicEndpoint(on_accept=on_accept)
        await server.listen("127.0.0.1", 0)
        thrower = QuicEndpoint()
        await thrower.listen("127.0.0.1", 0)
        for blob in _garbage_corpus(rng, n=80):
            thrower.transport.sendto(blob, server.address)
        await asyncio.sleep(0.2)

        client = QuicEndpoint()
        await client.listen("127.0.0.1", 0)
        reader, writer = await client.connect(server.address)
        writer.write(b"ping")
        await writer.drain()
        assert await asyncio.wait_for(got.get(), 5) == b"ping"
        for e in (server, thrower, client):
            e.close()

    asyncio.run(asyncio.wait_for(go(), 30))


# --- gossip + req/resp handlers over a full node ---------------------------


@pytest.fixture(scope="module")
def wired_app(tmp_path_factory):
    """An App with every gossip topic and server protocol registered
    (constructor + connect_network wiring; no POST init needed)."""
    tmp = tmp_path_factory.mktemp("fuzz_app")
    cfg = load("standalone", overrides={
        "data_dir": str(tmp / "node"),
        "layers_per_epoch": 3,
        "genesis": {"time": 1_700_000_000.0},
        "smeshing": {"start": False},
    })
    signer = EdSigner(prefix=cfg.genesis.genesis_id)
    ps = PubSub(node_name=signer.node_id)
    LoopbackHub().join(ps)
    app = App(cfg, signer=signer, pubsub=ps)
    app.connect_network(LoopbackNet())
    yield app, ps
    app.close()


def _valid_gossip_samples():
    from tests.test_tools_fuzz import _wire_samples

    return [s.to_bytes() for s in _wire_samples()]


def test_gossip_handlers_never_crash(wired_app):
    """Every registered topic handler fed noise/truncated/mutated blobs:
    rejection (False/None) is fine, an escaped exception is a crashed
    gossip task on a real node."""
    app, ps = wired_app
    rng = random.Random(SEED + 2)
    corpus = _garbage_corpus(rng, _valid_gossip_samples(), n=80)
    topics = dict(ps._handlers)
    assert len(topics) >= 5, f"expected a wired node, got {list(topics)}"

    async def go():
        peer = b"F" * 32
        for topic, handlers in topics.items():
            for handler in handlers:
                for blob in corpus:
                    try:
                        await asyncio.wait_for(handler(peer, blob), 10)
                    except asyncio.TimeoutError:
                        raise AssertionError(
                            f"{topic}: handler hung on fuzz input")
                    # any other exception escapes -> test failure

    asyncio.run(asyncio.wait_for(go(), 600))


def test_server_handlers_reject_garbage_without_hanging(wired_app):
    """Req/resp protocol handlers under fuzz: the transport catches
    handler exceptions and returns an error response (transport.py
    _serve), so the contract here is bounded work — no hang, no event
    loop corruption — for every registered protocol."""
    app, ps = wired_app
    rng = random.Random(SEED + 3)
    corpus = _garbage_corpus(rng, _valid_gossip_samples(), n=40)
    protocols = dict(app.server._protocols)
    assert protocols, "no server protocols registered"

    async def go():
        peer = b"F" * 32
        for proto, handler in protocols.items():
            for blob in corpus:
                try:
                    await asyncio.wait_for(handler(peer, blob), 10)
                except asyncio.TimeoutError:
                    raise AssertionError(f"{proto}: handler hung")
                except Exception:
                    pass  # becomes an error response on the wire

    asyncio.run(asyncio.wait_for(go(), 600))
