"""Pallas ROMix race candidate: bit-exact vs the XLA path + hashlib.

Interpret mode on CPU (the kernel's DMA orchestration runs in the
Pallas interpreter); on TPU the same call compiles via Mosaic — the
SPACEMESH_ROMIX=pallas flag races the two implementations on identical
inputs (docs/ROUND2_NOTES.md "Pallas ROMix" analysis).
"""

import hashlib

import jax.numpy as jnp
import numpy as np
import pytest

from spacemesh_tpu.ops import scrypt
from spacemesh_tpu.ops.romix_pallas import LANE_TILE, romix_pallas

N = 16
B = 16  # small: the interpreter executes every DMA in Python


def _random_block(b):
    rng = np.random.RandomState(7)
    return jnp.asarray(rng.randint(0, 2**32, size=(32, b), dtype=np.uint64)
                       .astype(np.uint32))


def test_pallas_romix_matches_xla_gather_path():
    x = _random_block(B)
    want = np.asarray(scrypt.romix_r1(x, N))
    got = np.asarray(romix_pallas(x, n=N, lane_tile=B, interpret=True))
    assert (want == got).all(), "contiguous-row kernel diverged from XLA"


def test_pallas_romix_tiles_the_batch():
    tile = 8
    x = _random_block(tile * 2)  # two grid steps share the V scratch
    want = np.asarray(scrypt.romix_r1(x, N))
    got = np.asarray(romix_pallas(x, n=N, lane_tile=tile, interpret=True))
    assert (want == got).all(), "per-tile scratch reuse broke a grid step"


def test_flagged_pipeline_is_bit_exact_vs_hashlib(monkeypatch):
    """End-to-end labels through the SPACEMESH_ROMIX=pallas flag equal
    hashlib.scrypt ground truth (the repo's canonical oracle)."""
    monkeypatch.setenv("SPACEMESH_ROMIX", "pallas")
    commitment = hashlib.sha256(b"romix-race-commitment").digest()
    indices = np.arange(LANE_TILE, dtype=np.uint64)  # full lane tile
    got = scrypt.scrypt_labels(commitment, indices, n=N)
    for i in (0, 1, LANE_TILE - 1):
        want = hashlib.scrypt(commitment, salt=int(i).to_bytes(8, "little"),
                              n=N, r=1, p=1, dklen=16)
        assert bytes(got[i]) == want, f"label {i} mismatch"


def test_flag_pads_when_batch_does_not_tile(monkeypatch):
    """An explicit pallas request with a non-tiling batch PADS the lanes
    up to the tile (romix_pallas_padded) instead of silently falling
    back to XLA — explicit requests never degrade (ops/autotune.py)."""
    monkeypatch.setenv("SPACEMESH_ROMIX", "pallas")
    commitment = hashlib.sha256(b"romix-fallback").digest()
    got = scrypt.scrypt_labels(commitment, np.arange(3, dtype=np.uint64),
                               n=N)
    want = hashlib.scrypt(commitment, salt=(2).to_bytes(8, "little"),
                          n=N, r=1, p=1, dklen=16)
    assert bytes(got[2]) == want


def test_bad_batch_rejected():
    with pytest.raises(ValueError, match="multiple"):
        romix_pallas(_random_block(12), n=N, lane_tile=8, interpret=True)
