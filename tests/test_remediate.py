"""Remediation engine + circuit breaker semantics (ISSUE 15).

Everything runs on injected clocks — zero sleeps: breaker transitions
(open after budget, half-open single probe, re-close, escalating
reopen cooldown, quarantine), the shared backoff rule, the registries'
metric-series lifecycle (the PR-12 ``remove_matching`` cardinality
pattern), policy budgets with quarantine escalation (no restart
storm), and breaker state surviving into flight-bundle manifests.
"""

import asyncio
import json

import pytest

from spacemesh_tpu.node import events as events_mod
from spacemesh_tpu.obs import remediate
from spacemesh_tpu.utils import metrics


class Clock:
    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


# --- backoff_delay ------------------------------------------------------


def test_backoff_delay_deterministic_capped_and_floored():
    d0 = remediate.backoff_delay(0, base_s=0.1, cap_s=2.0, seed=7)
    assert d0 == remediate.backoff_delay(0, base_s=0.1, cap_s=2.0, seed=7)
    assert 0.05 <= d0 < 0.1           # jitter in [0.5, 1.0) of base
    assert remediate.backoff_delay(0, base_s=0.1, cap_s=2.0, seed=8) != d0
    # exponential growth, capped
    d5 = remediate.backoff_delay(5, base_s=0.1, cap_s=2.0, seed=7)
    assert d5 > d0
    assert remediate.backoff_delay(50, base_s=0.1, cap_s=2.0,
                                   seed=7) <= 2.0
    # the server hint floors the wait (retrying sooner is wasted), but
    # never beyond the cap
    assert remediate.backoff_delay(0, base_s=0.1, cap_s=2.0,
                                   retry_after_s=1.5, seed=7) >= 1.5
    assert remediate.backoff_delay(0, base_s=0.1, cap_s=2.0,
                                   retry_after_s=99.0, seed=7) == 2.0


# --- CircuitBreaker -----------------------------------------------------


def _breaker(clock, **kw):
    kw.setdefault("failure_budget", 3)
    kw.setdefault("window_s", 10.0)
    kw.setdefault("cooldown_s", 2.0)
    kw.setdefault("cooldown_cap_s", 16.0)
    return remediate.CircuitBreaker("dev", time_source=clock.now, **kw)


def test_breaker_opens_after_budget_within_window():
    clock = Clock()
    br = _breaker(clock)
    for _ in range(2):
        br.record_failure()
    assert br.state == remediate.CLOSED and br.allow()
    br.record_failure()
    assert br.state == remediate.OPEN
    assert not br.allow()
    assert br.retry_in() is not None


def test_breaker_window_prunes_stale_failures():
    clock = Clock()
    br = _breaker(clock)
    br.record_failure()
    br.record_failure()
    clock.advance(11.0)  # both age out of the 10s window
    br.record_failure()
    assert br.state == remediate.CLOSED


def test_breaker_half_open_single_probe_then_close():
    clock = Clock()
    br = _breaker(clock)
    for _ in range(3):
        br.record_failure()
    retry_in = br.retry_in()
    clock.advance(retry_in - 1e-6)
    assert not br.allow()
    clock.advance(1.0)
    assert br.allow()                       # THE probe
    assert br.state == remediate.HALF_OPEN
    assert not br.allow()                   # a second caller is refused
    br.record_success()
    assert br.state == remediate.CLOSED and br.allow()
    assert br.probes == 1


def test_breaker_failed_probe_reopens_with_escalated_cooldown():
    clock = Clock()
    br = _breaker(clock)
    for _ in range(3):
        br.record_failure()
    first = br.retry_in()
    clock.advance(first)
    assert br.allow()
    br.record_failure()                     # probe failed
    assert br.state == remediate.OPEN
    second = br.retry_in()
    # the shared backoff rule escalates: attempt 1's base doubles
    assert second > first
    # the timings ARE backoff_delay — the client and breaker share it
    assert first == pytest.approx(remediate.backoff_delay(
        0, base_s=2.0, cap_s=16.0, seed=0))
    assert second == pytest.approx(remediate.backoff_delay(
        1, base_s=2.0, cap_s=16.0, seed=0))


def test_breaker_honors_retry_after_hint_for_probe_timing():
    clock = Clock()
    br = _breaker(clock)
    for i in range(3):
        br.record_failure(retry_after_s=7.5 if i == 2 else None)
    # the shedding peer said 7.5s: the half-open probe waits at least
    # that long, jitter or not
    assert br.retry_in() >= 7.5
    clock.advance(7.4)
    assert not br.allow()
    clock.advance(0.2)
    assert br.allow()


def test_breaker_quarantine_after_consecutive_opens_and_reset():
    clock = Clock()
    br = _breaker(clock, quarantine_after=2)
    for _ in range(3):
        br.record_failure()
    assert br.state == remediate.OPEN
    clock.advance(br.retry_in())
    assert br.allow()
    br.record_failure()                     # second consecutive open
    assert br.state == remediate.QUARANTINED
    clock.advance(1e9)
    assert not br.allow()                   # only reset() leaves
    br.reset()
    assert br.state == remediate.CLOSED and br.allow()


def test_breaker_transition_callback_sequence():
    clock = Clock()
    seen = []
    br = remediate.CircuitBreaker(
        "cb-seq", failure_budget=1, cooldown_s=1.0, cooldown_cap_s=1.0,
        time_source=clock.now,
        on_transition=lambda frm, to: seen.append((frm, to)))
    br.record_failure()
    clock.advance(2.0)
    br.allow()
    br.record_success()
    assert seen == [(remediate.CLOSED, remediate.OPEN),
                    (remediate.OPEN, remediate.HALF_OPEN),
                    (remediate.HALF_OPEN, remediate.CLOSED)]


# --- registries and series lifecycle ------------------------------------


def test_breaker_registry_series_removed_on_unregister():
    clock = Clock()
    br = remediate.CircuitBreaker("reg-test", failure_budget=1,
                                  time_source=clock.now)
    remediate.BREAKERS.register(br)
    try:
        key = (("component", "reg-test"),)
        assert metrics.remediation_breaker_state.sample()[key] == 0.0
        br.record_failure()
        assert metrics.remediation_breaker_state.sample()[key] == 1.0
        assert metrics.remediation_breaker_transitions.sample()[
            (("component", "reg-test"), ("to", "open"))] == 1.0
        assert "reg-test" in remediate.BREAKERS.names()
        assert remediate.BREAKERS.states()["reg-test"] == "open"
    finally:
        remediate.BREAKERS.unregister(br)
    # the PR-12 cardinality pattern: EVERY per-component series left
    assert key not in metrics.remediation_breaker_state.sample()
    assert not [k for k in
                metrics.remediation_breaker_transitions.sample()
                if ("component", "reg-test") in k]
    assert "reg-test" not in remediate.BREAKERS.names()


def test_breaker_abort_probe_releases_the_slot():
    """A probe that resolves with NO health verdict (config-class shed,
    cancelled caller) must release the slot, or the breaker wedges
    half-open and fast-fails forever (review fix)."""
    clock = Clock()
    br = _breaker(clock, failure_budget=1, cooldown_s=1.0)
    br.record_failure()
    clock.advance(2.0)
    assert br.allow()                       # probe granted
    br.abort_probe()                        # ...resolved verdict-less
    assert br.state == remediate.HALF_OPEN
    assert br.allow()                       # a NEW probe is grantable
    br.record_success()
    assert br.state == remediate.CLOSED
    # no-op outside a probe
    br.abort_probe()
    assert br.state == remediate.CLOSED and br.allow()


def test_breaker_registry_unregister_only_evicts_same_object():
    clock = Clock()
    a = remediate.CircuitBreaker("evict", time_source=clock.now)
    b = remediate.CircuitBreaker("evict", time_source=clock.now)
    remediate.BREAKERS.register(a)
    remediate.BREAKERS.register(b)          # last-wins
    try:
        remediate.BREAKERS.unregister(a)    # stale: must not evict b
        assert remediate.BREAKERS.get("evict") is b
    finally:
        remediate.BREAKERS.unregister(b)


def test_breaker_registry_displacement_silences_the_evicted():
    """Two same-named breakers (two farms in one process): the evicted
    one must stop writing the shared series, and its stale unregister
    must not remove the successor's series (review fix)."""
    clock = Clock()
    key = (("component", "displace"),)
    a = remediate.CircuitBreaker("displace", failure_budget=1,
                                 time_source=clock.now)
    b = remediate.CircuitBreaker("displace", failure_budget=1,
                                 time_source=clock.now)
    remediate.BREAKERS.register(a)
    remediate.BREAKERS.register(b)          # displaces a
    try:
        a.record_failure()                  # a opens — silently
        assert metrics.remediation_breaker_state.sample()[key] == 0.0
        remediate.BREAKERS.unregister(a)    # stale: series stay (b's)
        assert key in metrics.remediation_breaker_state.sample()
        b.record_failure()                  # the live owner writes
        assert metrics.remediation_breaker_state.sample()[key] == 1.0
    finally:
        remediate.BREAKERS.unregister(b)
    assert key not in metrics.remediation_breaker_state.sample()


def test_action_registry_equality_unregister():
    calls = []

    def hook():
        calls.append(1)

    remediate.ACTIONS.register("t-comp", "restart_component", hook)
    try:
        assert remediate.ACTIONS.get("t-comp",
                                     "restart_component") is hook
        remediate.ACTIONS.unregister("t-comp", "restart_component",
                                     lambda: None)   # wrong hook: no-op
        assert remediate.ACTIONS.get("t-comp",
                                     "restart_component") is hook
    finally:
        remediate.ACTIONS.unregister("t-comp", "restart_component", hook)
    assert remediate.ACTIONS.get("t-comp", "restart_component") is None


# --- the engine ---------------------------------------------------------


def _engine(clock, rules, **kw):
    return remediate.RemediationEngine(policy=rules,
                                       time_source=clock.now, **kw)


def test_engine_runs_hook_and_records_everything():
    clock = Clock(100.0)
    eng = _engine(clock, [remediate.RecoveryRule(
        component="farm.*", action="reset_farm_lanes", budget=3,
        window_s=60.0, cooldown_s=5.0)])
    ran = []
    remediate.ACTIONS.register("farm.x", "reset_farm_lanes",
                               lambda: ran.append(1))
    try:
        before = metrics.remediation_actions.sample().get(
            (("action", "reset_farm_lanes"), ("component", "farm.x"),
             ("outcome", "ok")), 0)
        rec = eng.handle_component("farm.x", "stalled 31s")
        assert rec["outcome"] == "ok" and rec["ran"] and ran == [1]
        assert metrics.remediation_actions.sample()[
            (("action", "reset_farm_lanes"), ("component", "farm.x"),
             ("outcome", "ok"))] == before + 1
        assert eng.history[-1]["component"] == "farm.x"
        assert eng.budgets()["farm.x"]["used"] == 1
    finally:
        remediate.ACTIONS.unregister("farm.x", "reset_farm_lanes")


def test_engine_cooldown_rate_limits_and_recovery_clears_it():
    clock = Clock()
    eng = _engine(clock, [remediate.RecoveryRule(
        component="c", action="restart_component", budget=10,
        window_s=600.0, cooldown_s=30.0)])
    assert eng.handle_component("c")["outcome"] == "no_hook"
    assert eng.handle_component("c")["outcome"] == "rate_limited"
    clock.advance(31.0)
    assert eng.handle_component("c")["outcome"] == "no_hook"
    # a recovered-then-broken component earns a fresh action sooner
    eng.note_recovered("c")
    assert eng.handle_component("c")["outcome"] == "no_hook"


def test_engine_budget_exhaustion_escalates_to_quarantine():
    """The flapping component: the action budget bounds the restart
    storm, the exhausting verdict quarantines, later verdicts no-op."""
    clock = Clock()
    eng = _engine(clock, [remediate.RecoveryRule(
        component="flappy", action="restart_component", budget=2,
        window_s=600.0, cooldown_s=0.0)])
    ran = []
    br = remediate.CircuitBreaker("flappy", time_source=clock.now)
    remediate.BREAKERS.register(br)
    remediate.ACTIONS.register("flappy", "restart_component",
                               lambda: ran.append(1))
    try:
        for _ in range(2):
            assert eng.handle_component("flappy")["outcome"] == "ok"
            clock.advance(1.0)
        rec = eng.handle_component("flappy")
        assert rec["action"] == "quarantine_component"
        assert rec["outcome"] == "escalated"
        # the registered breaker is forced into quarantine too
        assert br.state == remediate.QUARANTINED
        clock.advance(1.0)
        # no restart storm: later verdicts never reach the hook again
        assert eng.handle_component("flappy")["outcome"] == "quarantined"
        assert ran == [1, 1]
        assert eng.budgets()["flappy"]["quarantined"] is True
        assert "flappy" in eng.snapshot()["quarantined"]
    finally:
        remediate.ACTIONS.unregister("flappy", "restart_component")
        remediate.BREAKERS.unregister(br)


def test_engine_hook_error_is_recorded_never_propagates():
    clock = Clock()
    eng = _engine(clock, [remediate.RecoveryRule(
        component="bad", action="restart_component", cooldown_s=0.0)])

    def boom():
        raise RuntimeError("hook exploded")

    remediate.ACTIONS.register("bad", "restart_component", boom)
    try:
        assert eng.handle_component("bad")["outcome"] == "error"
    finally:
        remediate.ACTIONS.unregister("bad", "restart_component", boom)


def test_engine_slo_trigger_and_first_match_wins():
    clock = Clock()
    eng = _engine(clock, [
        remediate.RecoveryRule(component="farm_*", trigger="slo_breach",
                               action="shed_and_alert", cooldown_s=0.0),
        remediate.RecoveryRule(component="*", trigger="slo_breach",
                               action="restart_component",
                               cooldown_s=0.0),
    ])
    rec = eng.handle_slo("farm_queue_wait", "burn 0.4")
    assert rec["action"] == "shed_and_alert"
    rec = eng.handle_slo("layer_apply_latency")
    assert rec["action"] == "restart_component"
    # an unhealthy verdict never matches slo_breach rules
    assert eng.handle_component("farm_queue_wait") is None


def test_engine_history_is_bounded():
    clock = Clock()
    eng = _engine(clock, [remediate.RecoveryRule(
        component="*", action="shed_and_alert", budget=10_000,
        window_s=1.0, cooldown_s=0.0)], history=16)
    for i in range(50):
        eng.handle_component(f"c{i % 4}")
        clock.advance(2.0)
    assert len(eng.history) == 16


def test_engine_consumes_bus_events():
    """The production path: SloBreach/ComponentHealth bus events reach
    the policy; RemediationAction events come back out."""

    async def run():
        bus = events_mod.EventBus()
        clock = Clock()
        eng = remediate.RemediationEngine(
            bus=bus, time_source=clock.now,
            policy=[remediate.RecoveryRule(
                component="comp", action="restart_component",
                cooldown_s=0.0)])
        out = bus.subscribe(events_mod.RemediationAction, size=16)
        eng.start()
        try:
            bus.emit(events_mod.ComponentHealth(
                component="comp", healthy=False, reason="stalled"))
            ev = await asyncio.wait_for(out.next(), 5)
            assert ev.component == "comp"
            assert ev.action == "restart_component"
            assert ev.outcome == "no_hook"
        finally:
            eng.close()
            out.close()

    asyncio.run(run())


# --- flight-bundle manifests --------------------------------------------


def test_breaker_state_survives_into_flight_manifest(tmp_path):
    from spacemesh_tpu.obs import health as health_mod

    clock = Clock(50.0)
    br = remediate.CircuitBreaker("manifest-test", failure_budget=1,
                                  time_source=clock.now)
    remediate.BREAKERS.register(br)
    br.record_failure()
    eng = health_mod.HealthEngine(spool_dir=tmp_path,
                                  time_source=clock.now)
    eng.remediation = remediate.RemediationEngine(time_source=clock.now)
    try:
        path = eng.dump_flight("test")
        manifest = json.loads(
            (tmp_path / path.split("/")[-1] / "manifest.json")
            .read_text())
        doc = manifest["remediation"]["breakers"]["manifest-test"]
        assert doc["state"] == "open"
        assert doc["failure_budget"] == 1
        assert manifest["remediation"]["actions"] == []
    finally:
        eng.remediation.close()
        eng.close()
        remediate.BREAKERS.unregister(br)


def test_flight_manifest_falls_back_to_global_breakers(tmp_path):
    """A recorder dump with no engine attached still records every
    registered breaker."""
    from spacemesh_tpu.obs import flight as flight_mod

    clock = Clock()
    br = remediate.CircuitBreaker("global-fb", time_source=clock.now)
    remediate.BREAKERS.register(br)
    try:
        rec = flight_mod.FlightRecorder(tmp_path, time_source=clock.now)
        path = rec.dump("test")
        manifest = json.loads((path / "manifest.json").read_text())
        assert manifest["remediation"]["breakers"][
            "global-fb"]["state"] == "closed"
    finally:
        remediate.BREAKERS.unregister(br)
