"""Tortoise: vectorized counting, healing, pending votes, trace replay.

The margin computation is a masked mat-vec over the vote matrix; these
tests pin it against an independent scalar recount, exercise full-mode
healing past the confidence window (reference tortoise/full.go), the
pending-support resolution (round-1 advisor fix), recovery, and the
self-contained JSON trace replay (reference tortoise/tracer.go RunTrace).
"""

import random
import time

from spacemesh_tpu.consensus.tortoise import (
    EMPTY,
    FULL,
    Tortoise,
    replay_trace,
)
from spacemesh_tpu.core.types import Ballot, Opinion
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo

LPE = 4


def _cache(weight=100, epochs=6):
    cache = AtxCache()
    for e in range(epochs):
        cache.add(e, b"atx-%02d" % e + bytes(26), AtxInfo(
            node_id=b"n" * 32, weight=weight * LPE, base_height=0, height=1,
            num_units=1, vrf_nonce=0, vrf_public_key=b"n" * 32))
    return cache


def _ballot(bid, layer, opinion, node=b"n"):
    # bid lands in the signature so distinct calls yield distinct ids
    # (Ballot.id is content-derived)
    return Ballot(layer=layer, atx_id=bytes(32),
                  node_id=(node * 32)[:32], epoch_data=None,
                  ref_ballot=bytes(32), opinion=opinion, eligibilities=[],
                  signature=bid.ljust(64, b"\0"))


def _bid(i):
    return b"B%07d" % i + bytes(24)


def _blk(layer, j=0):
    return b"K%03d-%02d" % (layer, j) + bytes(25)


def scalar_margin(t, target_layer, block_id, last):
    """Independent recount straight from the BallotInfo dicts."""
    m = 0
    for bid, info in t._ballots.items():
        if not (target_layer < info.layer <= last) or info.malicious:
            continue
        if target_layer in info.abstains:
            continue
        sup = info.supports.get(target_layer, set())
        m += info.weight if block_id in sup else -info.weight
    return m


def test_vectorized_margins_match_scalar_recount():
    random.seed(7)
    t = Tortoise(_cache(), LPE, hdist=4, zdist=2, window=100)
    blocks = {}
    for layer in range(1, 12):
        blocks[layer] = [_blk(layer, j) for j in range(3)]
        for b in blocks[layer]:
            t.on_block(layer, b)
    n = 0
    for layer in range(2, 13):
        for _ in range(5):
            support = []
            abstain = []
            for lyr in range(1, layer):
                r = random.random()
                if r < 0.15:
                    abstain.append(lyr)
                else:
                    support += random.sample(blocks.get(lyr, []),
                                             random.randint(0, 2))
            op = Opinion(base=EMPTY, support=sorted(set(support)),
                         against=[], abstain=abstain)
            t.on_ballot(_ballot(_bid(n), layer, op, node=b"%02d" % n),
                        weight=random.randint(1, 50))
            n += 1
    for layer in range(1, 12):
        ids, margins = t._margins(layer, 12)
        for b, m in zip(ids, margins):
            assert int(m) == scalar_margin(t, layer, b, 12), (layer, b)


def test_supported_blocks_verify():
    t = Tortoise(_cache(weight=100), LPE, hdist=3, zdist=2, window=100)
    good = _blk(1)
    t.on_block(1, good)
    # heavy honest support from newer layers
    for i, layer in enumerate(range(2, 6)):
        op = Opinion(base=_bid(i - 1) if i else EMPTY, support=[good],
                     against=[], abstain=[])
        t.on_ballot(_ballot(_bid(i), layer, op, node=b"%02d" % i), weight=200)
        t.on_hare_output(layer, EMPTY)
    t.on_hare_output(1, good)
    t.tally_votes(6)
    assert t.verified >= 1
    assert t.is_valid(good)


def test_healing_decides_stuck_layer_by_sign():
    """A layer whose margin never clears the GLOBAL threshold (and has
    no hare output) settles once it falls past hdist+zdist, by count
    sign — provided the margin clears the LOCAL threshold (reference
    tortoise/full.go + threshold.go local/global split)."""
    t = Tortoise(_cache(weight=10_000), LPE, hdist=2, zdist=1, window=100)
    b1 = _blk(1)
    t.on_block(1, b1)
    # support above the local threshold (10000/LPE/3) but below the
    # global one (which includes the whole window's expected weight)
    lt = t._local_threshold(8)
    t.on_ballot(_ballot(_bid(0), 2, Opinion(
        base=EMPTY, support=[b1], against=[], abstain=[]), b"aa"),
        weight=lt + 5)
    t.on_ballot(_ballot(_bid(1), 3, Opinion(
        base=EMPTY, support=[b1], against=[], abstain=[]), b"bb"),
        weight=lt + 5)
    t.on_ballot(_ballot(_bid(2), 3, Opinion(
        base=EMPTY, support=[], against=[b1], abstain=[]), b"cc"),
        weight=lt)
    t.tally_votes(4)
    assert t.verified == 0  # within the confidence window: stuck
    t.tally_votes(8)        # 8 - 1 > hdist + zdist -> heal by sign
    assert t.verified >= 1
    assert t.is_valid(b1)
    assert t.mode == FULL


def test_healing_zero_margin_decided_by_weak_coin():
    """A genuinely tied layer (|margin| <= local threshold) is decided
    by the weak coin of the latest layer, so every node lands on the
    same side (reference tortoise/tortoise.go:287-306 getFullVote
    reasonCoinflip). Without a recorded coin the layer stays stuck."""
    def mk(coin):
        t = Tortoise(_cache(weight=10_000), LPE, hdist=2, zdist=1,
                     window=100)
        b1 = _blk(1)
        t.on_block(1, b1)
        # equal support and against: margin exactly zero
        t.on_ballot(_ballot(_bid(0), 2, Opinion(
            base=EMPTY, support=[b1], against=[], abstain=[]), b"aa"),
            weight=7)
        t.on_ballot(_ballot(_bid(1), 3, Opinion(
            base=EMPTY, support=[], against=[b1], abstain=[]), b"bb"),
            weight=7)
        if coin is not None:
            t.on_weak_coin(7, coin)
        t.tally_votes(8)
        return t, b1

    t, b1 = mk(None)
    assert t.verified == 0  # no coin: cannot settle the tie

    t, b1 = mk(True)
    assert t.verified >= 1
    assert t.is_valid(b1)   # coin says support

    t, b1 = mk(False)
    assert t.verified >= 1
    assert not t.is_valid(b1)  # coin says against


def test_bad_beacon_ballots_muted_until_delay():
    """Ballots with a wrong beacon vote at zero weight until
    bad_beacon_delay layers past their own layer (reference
    tortoise.go BadBeaconVoteDelayLayers): a grinding adversary can't
    swing margins inside the confidence window, but the votes DO count
    eventually (self-healing keeps working on whatever weight exists)."""
    t = Tortoise(_cache(weight=100), LPE, hdist=3, zdist=2, window=100,
                 bad_beacon_delay=4)
    good = _blk(1)
    t.on_block(1, good)
    t.on_hare_output(1, good)
    # heavy support arrives ONLY from bad-beacon ballots
    for i, layer in enumerate(range(2, 6)):
        op = Opinion(base=EMPTY, support=[good], against=[], abstain=[])
        t.on_ballot(_ballot(_bid(i), layer, op, node=b"%02d" % i),
                    weight=500, bad_beacon=True)
    t.tally_votes(6)
    # margins muted: only hare trust within hdist can hold the opinion,
    # the 2000-weight support does not cross any threshold
    blocks, margins = t._margins(1, 6)
    assert list(margins) == [0]
    # ...until the delay passes: layers 2..5 are all > 4 layers behind
    # the new tip, so the weight counts again
    t.tally_votes(10)
    blocks, margins = t._margins(1, 10)
    assert list(margins) == [2000]


def test_pending_support_resolved_when_block_arrives():
    """Ballots may vote for blocks the node hasn't fetched yet (sync
    ordering); the vote must count once the block shows up."""
    t = Tortoise(_cache(weight=100), LPE, hdist=3, zdist=2, window=100)
    late = _blk(1)
    # ballot arrives BEFORE the block it supports
    t.on_ballot(_ballot(_bid(0), 2, Opinion(
        base=EMPTY, support=[late], against=[], abstain=[]), b"aa"),
        weight=300)
    t.on_block(1, late)
    t.on_hare_output(1, late)
    ids, margins = t._margins(1, 3)
    assert ids == [late]
    assert int(margins[0]) == 300  # support counted, not against


def test_pending_support_inherits_through_base_chain():
    """A descendant basing on a ballot with a pending vote must inherit
    that vote when the block finally arrives (exception lists are deltas,
    so the support exists only via the base chain)."""
    t = Tortoise(_cache(weight=100), LPE, hdist=3, zdist=2, window=100)
    late = _blk(1)
    b0 = _ballot(_bid(0), 2, Opinion(
        base=EMPTY, support=[late], against=[], abstain=[]), b"aa")
    t.on_ballot(b0, weight=100)
    # descendant bases on b0, listing no explicit votes of its own
    t.on_ballot(_ballot(_bid(1), 3, Opinion(
        base=b0.id, support=[], against=[], abstain=[]), b"bb"), weight=70)
    # a second descendant explicitly votes AGAINST: must NOT inherit
    t.on_ballot(_ballot(_bid(2), 3, Opinion(
        base=b0.id, support=[], against=[late], abstain=[]), b"cc"),
        weight=10)
    t.on_block(1, late)
    ids, margins = t._margins(1, 4)
    assert ids == [late]
    assert int(margins[0]) == 100 + 70 - 10


def test_malfeasance_zeroes_existing_ballots():
    t = Tortoise(_cache(weight=100), LPE, hdist=3, zdist=2, window=100)
    b1 = _blk(1)
    t.on_block(1, b1)
    t.on_ballot(_ballot(_bid(0), 2, Opinion(
        base=EMPTY, support=[b1], against=[], abstain=[]), b"ev"), weight=500)
    ids, margins = t._margins(1, 3)
    assert int(margins[0]) == 500
    t.on_malfeasance(b"ev" * 16)
    ids, margins = t._margins(1, 3)
    assert int(margins[0]) == 0


def test_trace_replay_reproduces_state():
    lines = []
    t = Tortoise(_cache(weight=100), LPE, hdist=3, zdist=2, window=100,
                 tracer=lines.append)
    random.seed(3)
    blocks = {}
    for layer in range(1, 8):
        blocks[layer] = [_blk(layer, j) for j in range(2)]
        for b in blocks[layer]:
            t.on_block(layer, b)
        t.on_hare_output(layer, blocks[layer][0])
    for i, layer in enumerate(range(2, 9)):
        op = Opinion(base=EMPTY,
                     support=[blocks[lyr][0] for lyr in range(1, layer)],
                     against=[], abstain=[])
        t.on_ballot(_ballot(_bid(i), layer, op, node=b"%02d" % i), weight=120)
    t.tally_votes(9)

    r = replay_trace(lines, cache=_cache(weight=100))
    assert r.verified == t.verified
    assert r.processed == t.processed
    assert r._validity == t._validity
    assert r.mode == t.mode


def test_recover_roundtrip(tmp_path):
    """recover() rebuilds blocks/hare/validity from storage."""
    from spacemesh_tpu.consensus.eligibility import Oracle
    from spacemesh_tpu.storage import blocks as blockstore
    from spacemesh_tpu.storage import db as dbmod
    from spacemesh_tpu.storage import layers as layerstore
    from spacemesh_tpu.core.types import Block

    db = dbmod.open_state(":memory:")
    cache = _cache(weight=100)
    blk = Block(layer=2, tick_height=0, rewards=[], tx_ids=[])
    blockstore.add(db, blk)
    blockstore.set_valid(db, blk.id)
    layerstore.set_processed(db, 3)
    layerstore.set_applied(db, 2, blk.id, bytes(32))

    t = Tortoise.recover(db, cache, Oracle(cache, LPE),
                         layers_per_epoch=LPE, hdist=3, zdist=2, window=100)
    assert t.processed == 3
    assert blk.id in t._col_of
    assert t.is_valid(blk.id)
    assert t._hare.get(2) == blk.id


def test_recover_skips_ballots_at_or_below_migration_boundary(tmp_path):
    """Ballots at or below the 0004 block-id-rewrite boundary carry signed
    vote lists over pre-rewrite ids; recover must not replay them (their
    supports would all resolve as against), while later ballots load."""
    from spacemesh_tpu.consensus.eligibility import Oracle
    from spacemesh_tpu.core.types import VotingEligibility
    from spacemesh_tpu.storage import ballots as ballotstore
    from spacemesh_tpu.storage import db as dbmod
    from spacemesh_tpu.storage import layers as layerstore

    db = dbmod.open_state(":memory:")
    cache = _cache(weight=100)

    def stored_ballot(layer, tag):
        op = Opinion(base=EMPTY, support=[], against=[], abstain=[])
        return Ballot(layer=layer, atx_id=b"atx-%02d" % (layer // LPE)
                      + bytes(26), node_id=b"n" * 32, epoch_data=None,
                      ref_ballot=bytes(32), opinion=op,
                      eligibilities=[VotingEligibility(j=0, sig=bytes(80))],
                      signature=tag.ljust(64, b"\0"))

    pre, post = stored_ballot(2, b"pre"), stored_ballot(6, b"post")
    ballotstore.add(db, pre)
    ballotstore.add(db, post)
    layerstore.set_processed(db, 6)
    db.exec("INSERT OR REPLACE INTO migration_marks VALUES"
            " ('block_id_rewrite_boundary', 3)")

    t = Tortoise.recover(db, cache, Oracle(cache, LPE),
                         layers_per_epoch=LPE, hdist=3, zdist=2, window=100)
    assert post.id in t._ballots
    assert pre.id not in t._ballots


def test_tally_speed_vs_scalar_loop():
    """The mat-vec tally must beat a per-ballot Python recount by a wide
    margin on a realistic window (informational: prints the ratio; asserts
    only a conservative floor)."""
    random.seed(11)
    t = Tortoise(_cache(weight=1000), LPE, hdist=4, zdist=2, window=2000)
    layers = 60
    blocks = {}
    for layer in range(1, layers):
        blocks[layer] = [_blk(layer, j) for j in range(4)]
        for b in blocks[layer]:
            t.on_block(layer, b)
    n = 0
    for layer in range(2, layers + 1):
        for _ in range(20):
            support = [random.choice(blocks[lyr])
                       for lyr in range(max(1, layer - 30), layer)]
            op = Opinion(base=EMPTY, support=support, against=[], abstain=[])
            t.on_ballot(_ballot(_bid(n), layer, op, node=b"%04d" % n),
                        weight=random.randint(1, 9))
            n += 1

    t0 = time.perf_counter()
    for layer in range(1, layers):
        t._margins(layer, layers)
    vec_dt = time.perf_counter() - t0

    t0 = time.perf_counter()
    for layer in range(1, layers):
        for b in blocks[layer]:
            scalar_margin(t, layer, b, layers)
    scalar_dt = time.perf_counter() - t0

    ratio = scalar_dt / max(vec_dt, 1e-9)
    print(f"tally speedup: {ratio:.1f}x (vec {vec_dt*1e3:.1f}ms, "
          f"scalar {scalar_dt*1e3:.1f}ms)")
    assert ratio > 10, f"vectorized tally only {ratio:.1f}x faster"
