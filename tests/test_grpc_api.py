"""gRPC API: the PostService Register seam + query services.

The seam test mirrors the reference's post_service_test.go: a post
service dials the node's gRPC listener, Registers its identity over the
bidirectional stream, and the node drives metadata + proof generation
through it (reference api/grpcserver/post_service.go:91,
post_client.go:37-146).  Runs on a real asyncio loop — gRPC owns real
sockets and a poller thread, so no virtual clock here.
"""

import asyncio
import hashlib

import grpc
import pytest

from spacemesh_tpu.api.gen import core_pb2 as cpb
from spacemesh_tpu.api.gen import post_pb2 as ppb
from spacemesh_tpu.api.rpc import POST_REGISTER, GrpcApiServer
from spacemesh_tpu.post import initializer, verifier
from spacemesh_tpu.post.grpc_worker import GrpcWorker
from spacemesh_tpu.post.prover import ProofParams
from spacemesh_tpu.post.service import PostClient, PostService

NODE_ID = hashlib.sha256(b"grpc-test-node").digest()
COMMITMENT = hashlib.sha256(b"grpc-test-commitment").digest()
PARAMS = ProofParams(k1=64, k2=8, k3=4,
                     pow_difficulty=b"\x20" + b"\xff" * 31)


@pytest.fixture(scope="module")
def post_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("grpcpost") / NODE_ID.hex()[:16]
    initializer.initialize(
        d, node_id=NODE_ID, commitment=COMMITMENT, num_units=1,
        labels_per_unit=256, scrypt_n=2, batch_size=128)
    return d


def _service(post_dir) -> PostService:
    svc = PostService()
    svc.register(NODE_ID, PostClient(post_dir, PARAMS))
    return svc


async def _start_pair(post_dir):
    server = GrpcApiServer(app=None, listen="127.0.0.1:0",
                           post_query_interval=0.05)
    port = await server.start()
    worker = GrpcWorker(_service(post_dir), f"127.0.0.1:{port}",
                        reconnect_backoff=0.2)
    await worker.start()
    await worker.wait_connected(timeout=10)
    await server.post_service.wait_registered([NODE_ID], timeout=10)
    return server, worker


def test_register_info_proof_roundtrip(post_dir):
    async def go():
        server, worker = await _start_pair(post_dir)
        try:
            client = server.post_service.client(NODE_ID)
            info = await asyncio.to_thread(client.info)
            assert info.node_id == NODE_ID
            assert info.commitment == COMMITMENT
            assert info.num_units == 1
            assert info.labels_per_unit == 256

            challenge = hashlib.sha256(b"grpc-challenge").digest()
            proof, _meta = await asyncio.to_thread(client.proof, challenge)
            assert len(proof.indices) == PARAMS.k2
            ok = verifier.verify(verifier.VerifyItem(
                proof=proof, challenge=challenge, node_id=NODE_ID,
                commitment=COMMITMENT, scrypt_n=2, total_labels=256), PARAMS)
            assert ok, "proof over the gRPC seam failed verification"
        finally:
            await worker.stop()
            await server.stop()

    asyncio.run(go())


def test_duplicate_identity_rejected(post_dir):
    """A second Register for an already-streamed identity is refused
    (reference post_service.go setConnection errors on duplicates)."""

    async def go():
        server, worker = await _start_pair(post_dir)
        try:
            async with grpc.aio.insecure_channel(
                    f"127.0.0.1:{server.actual_port}") as channel:
                stub = channel.stream_stream(
                    POST_REGISTER,
                    request_serializer=ppb.ServiceResponse.SerializeToString,
                    response_deserializer=ppb.NodeRequest.FromString)
                call = stub()
                req = await call.read()  # metadata request
                assert req.WhichOneof("kind") == "metadata"
                await call.write(ppb.ServiceResponse(
                    metadata=ppb.MetadataResponse(meta=ppb.Metadata(
                        node_id=NODE_ID, commitment_atx_id=COMMITMENT,
                        num_units=1, labels_per_unit=256))))
                with pytest.raises(grpc.aio.AioRpcError) as e:
                    await call.read()
                assert e.value.code() == grpc.StatusCode.ALREADY_EXISTS
        finally:
            await worker.stop()
            await server.stop()

    asyncio.run(go())


def test_worker_reconnects_after_node_restart(post_dir):
    """The worker's dial loop re-Registers when the node comes back
    (the reference post-service reconnects the same way)."""

    async def go():
        server, worker = await _start_pair(post_dir)
        port = server.actual_port
        try:
            await server.stop()
            await asyncio.sleep(0.3)
            assert NODE_ID not in [*server.post_service.clients]
            server2 = GrpcApiServer(app=None, listen=f"127.0.0.1:{port}",
                                    post_query_interval=0.05)
            await server2.start()
            try:
                await server2.post_service.wait_registered([NODE_ID],
                                                           timeout=15)
                client = server2.post_service.client(NODE_ID)
                info = await asyncio.to_thread(client.info)
                assert info.node_id == NODE_ID
            finally:
                await server2.stop()
        finally:
            await worker.stop()

    asyncio.run(go())


def test_subprocess_worker_registers_via_supervisor(post_dir):
    """End-to-end over a REAL subprocess: the supervisor spawns
    `spacemesh_tpu.post serve --node-address` and the worker dials in
    (reference activation/post_supervisor.go + post service)."""
    from spacemesh_tpu.post.supervisor import PostSupervisor

    async def go():
        server = GrpcApiServer(app=None, listen="127.0.0.1:0",
                               post_query_interval=0.05)
        port = await server.start()
        sup = PostSupervisor(post_dir.parent, params=PARAMS,
                             node_address=f"127.0.0.1:{port}",
                             restart_backoff=0.2)
        try:
            await asyncio.to_thread(sup.start, 120)
            await server.post_service.wait_registered([NODE_ID], timeout=60)
            client = server.post_service.client(NODE_ID)
            info = await asyncio.to_thread(client.info)
            assert info.commitment == COMMITMENT
        finally:
            sup.stop()
            await server.stop()

    asyncio.run(go())


def test_node_smeshes_through_grpc_worker(tmp_path):
    """Full node seam e2e: smeshing with worker_grpc=True spawns the
    worker subprocess, which dials the node's PostService and Registers;
    the first ATX (epoch 0) is proven through the Register stream
    (reference node + post-service deployment shape)."""
    from spacemesh_tpu.api.rpc import GrpcPostClient
    from spacemesh_tpu.node.app import App
    from spacemesh_tpu.node.config import load
    from spacemesh_tpu.storage import atxs as atxstore

    cfg = load("standalone", overrides={
        "data_dir": str(tmp_path / "node"),
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": 1, "init_batch": 128,
                     "external_worker": True, "worker_grpc": True},
    })
    app = App(cfg)

    async def go():
        try:
            await asyncio.wait_for(app.prepare(), 300)
            assert app.grpc_api.post_service.registered() == \
                [s.node_id for s in app.signers]
            for b in app.atx_builders:
                assert isinstance(b.post_client, GrpcPostClient)
            atx = atxstore.by_node_in_epoch(
                app.state, app.signer.node_id, 0)
            assert atx is not None, "no ATX proven through the gRPC worker"
            assert atx.num_units == 1
        finally:
            await app.stop_grpc_api()
            app.close()

    asyncio.run(go())


def test_query_services_against_live_node(tmp_path):
    """Node/Mesh/GlobalState gRPC services answer over the wire."""
    from spacemesh_tpu.node.app import App
    from spacemesh_tpu.node.config import load

    cfg = load("standalone", overrides={
        "data_dir": str(tmp_path / "node"),
        "smeshing": {"start": False},
    })
    app = App(cfg)

    async def go():
        port = await app.start_grpc_api()
        try:
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                echo = ch.unary_unary(
                    "/spacemesh.v1.NodeService/Echo",
                    request_serializer=cpb.EchoRequest.SerializeToString,
                    response_deserializer=cpb.EchoResponse.FromString)
                assert (await echo(cpb.EchoRequest(msg="hi"))).msg == "hi"

                status = ch.unary_unary(
                    "/spacemesh.v1.NodeService/Status",
                    request_serializer=cpb.StatusRequest.SerializeToString,
                    response_deserializer=cpb.StatusResponse.FromString)
                st = await status(cpb.StatusRequest())
                assert st.status.is_synced

                gt = ch.unary_unary(
                    "/spacemesh.v1.MeshService/GenesisTime",
                    request_serializer=cpb.GenesisTimeRequest.SerializeToString,
                    response_deserializer=cpb.GenesisTimeResponse.FromString)
                assert (await gt(cpb.GenesisTimeRequest())).unixtime == \
                    int(cfg.genesis.time)

                gid = ch.unary_unary(
                    "/spacemesh.v1.MeshService/GenesisID",
                    request_serializer=cpb.GenesisIDRequest.SerializeToString,
                    response_deserializer=cpb.GenesisIDResponse.FromString)
                assert (await gid(cpb.GenesisIDRequest())).genesis_id == \
                    cfg.genesis.genesis_id

                acct = ch.unary_unary(
                    "/spacemesh.v1.GlobalStateService/Account",
                    request_serializer=cpb.AccountRequest.SerializeToString,
                    response_deserializer=cpb.AccountResponse.FromString)
                from spacemesh_tpu.core.types import Address
                addr = Address(b"\x00" * 24).encode()
                resp = await acct(cpb.AccountRequest(address=addr))
                assert resp.account_wrapper.state_current.balance == 0

                bad = acct(cpb.AccountRequest(address="nonsense"))
                with pytest.raises(grpc.aio.AioRpcError) as e:
                    await bad
                assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
        finally:
            await app.stop_grpc_api()
            app.close()

    asyncio.run(go())
