"""Span tracer (spacemesh_tpu/utils/tracing.py): no-op fast path, ring
bounds, contextvar causality, trace-event export validity, and the
end-to-end acceptance capture — one init + prove + verify-farm run whose
export links verify-farm requests to their batch and stamps one window
id across a prove pass's read/dispatch/retire spans."""

import asyncio
import hashlib
import json
import subprocess
import sys
import threading
import time

import pytest

from spacemesh_tpu.utils import tracing


@pytest.fixture(autouse=True)
def _tracer_off():
    """Every test starts and ends with the tracer disabled."""
    tracing.stop()
    yield
    tracing.stop()


# --- disabled fast path -----------------------------------------------


def test_disabled_span_is_the_noop_singleton():
    assert not tracing.is_enabled()
    assert tracing.span("anything") is tracing._NOP
    assert tracing.span("x", {"k": 1}, parent=7) is tracing._NOP
    # instant is a plain early return
    tracing.instant("x")
    # the singleton absorbs every protocol call
    with tracing.span("x") as sp:
        sp.set(a=1)
    assert sp is tracing._NOP and sp.id is None
    assert tracing.current_id() is None


def test_disabled_span_call_is_cheap():
    """The disabled path must stay an attribute check + singleton return
    (the acceptance criterion's '~dict-free work'): 200k calls in well
    under a second even on a loaded CI host."""
    span = tracing.span
    t0 = time.perf_counter()
    for _ in range(200_000):
        with span("hot"):
            pass
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"disabled span path too slow: {dt:.3f}s / 200k"


# --- recording + export -----------------------------------------------


def test_span_records_parenting_and_attrs():
    tracing.start(capacity=64)
    with tracing.span("outer", {"a": 1}) as outer:
        assert tracing.current_id() == outer.id
        with tracing.span("inner") as inner:
            inner.set(b=2)
        tracing.instant("mark", {"m": 3})
    assert tracing.current_id() is None
    doc = tracing.export()
    tracing.validate(doc)
    evs = {e["name"]: e for e in doc["traceEvents"] if e["ph"] != "M"}
    assert evs["outer"]["args"]["a"] == 1
    assert evs["inner"]["args"]["parent"] == evs["outer"]["args"]["id"]
    assert evs["inner"]["args"]["b"] == 2
    assert evs["mark"]["ph"] == "i"
    assert evs["mark"]["args"]["parent"] == evs["outer"]["args"]["id"]
    assert evs["outer"]["dur"] >= evs["inner"]["dur"] >= 0


def test_async_context_propagation():
    tracing.start(capacity=64)

    async def child():
        with tracing.span("child"):
            await asyncio.sleep(0)

    async def main():
        with tracing.span("root") as root:
            # both a created task and a plain await inherit the parent
            await asyncio.gather(child(), child())
            return root.id

    root_id = asyncio.run(main())
    doc = tracing.export()
    tracing.validate(doc)
    children = [e for e in doc["traceEvents"] if e["name"] == "child"]
    assert len(children) == 2
    assert all(e["args"]["parent"] == root_id for e in children)


def test_thread_parent_handoff():
    """Long-lived pool threads can't inherit contextvars — current_id()
    + the explicit parent argument is the documented handoff."""
    tracing.start(capacity=64)
    seen = {}

    def worker(parent):
        with tracing.span("pool.work", parent=parent) as sp:
            seen["id"] = sp.id

    with tracing.span("submitter") as sub:
        t = threading.Thread(target=worker, args=(tracing.current_id(),))
        t.start()
        t.join()
    doc = tracing.export()
    tracing.validate(doc)
    work = [e for e in doc["traceEvents"] if e["name"] == "pool.work"][0]
    assert work["args"]["parent"] == sub.id
    # the worker thread shows up as its own named track
    tids = {e["tid"] for e in doc["traceEvents"] if e["ph"] != "M"}
    assert len(tids) == 2


def test_ring_is_bounded_and_counts_drops():
    tracing.start(capacity=16)
    for i in range(50):
        with tracing.span(f"s{i}"):
            pass
    doc = tracing.export()
    tracing.validate(doc)
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 16
    assert doc["otherData"]["captured_spans"] == 16
    assert doc["otherData"]["dropped_spans"] == 34
    # the ring keeps the NEWEST spans
    assert {e["name"] for e in xs} == {f"s{i}" for i in range(34, 50)}


def test_restart_resets_the_capture():
    tracing.start(capacity=16)
    with tracing.span("first"):
        pass
    tracing.start(capacity=16)  # new capture window
    with tracing.span("second"):
        pass
    names = {e["name"] for e in tracing.export()["traceEvents"]
             if e["ph"] == "X"}
    assert names == {"second"}


def test_export_json_roundtrip(tmp_path):
    tracing.start(capacity=16)
    with tracing.span("a"):
        pass
    path = tmp_path / "trace.json"
    tracing.export_json(str(path))
    doc = json.loads(path.read_text())
    tracing.validate(doc)
    assert any(e["name"] == "a" for e in doc["traceEvents"])


# --- validator --------------------------------------------------------


def test_validate_rejects_malformed_docs():
    with pytest.raises(ValueError):
        tracing.validate([])
    with pytest.raises(ValueError):
        tracing.validate({"traceEvents": [{"ph": "X"}]})  # missing keys
    base = {"name": "x", "pid": 1, "tid": 1}
    with pytest.raises(ValueError):  # unknown phase
        tracing.validate({"traceEvents": [{**base, "ph": "Z", "ts": 0}]})
    with pytest.raises(ValueError):  # X without dur
        tracing.validate({"traceEvents": [{**base, "ph": "X", "ts": 0}]})
    with pytest.raises(ValueError):  # ts going backwards
        tracing.validate({"traceEvents": [
            {**base, "ph": "X", "ts": 10, "dur": 1},
            {**base, "ph": "X", "ts": 5, "dur": 1}]})
    with pytest.raises(ValueError):  # E without B
        tracing.validate({"traceEvents": [{**base, "ph": "E", "ts": 0}]})
    with pytest.raises(ValueError):  # unclosed B
        tracing.validate({"traceEvents": [{**base, "ph": "B", "ts": 0}]})
    # matched B/E is fine
    tracing.validate({"traceEvents": [
        {**base, "ph": "B", "ts": 0},
        {**base, "ph": "E", "ts": 4}]})


# --- flame summary ----------------------------------------------------


def test_summarize_self_time_and_wait_split():
    tracing.start(capacity=64)
    with tracing.span("stage.work"):
        with tracing.span("stage.read_wait"):
            time.sleep(0.01)
    summary = tracing.summarize(tracing.export())
    by_name = {r["name"]: r for r in summary["top_self_time"]}
    # the child's time is subtracted from the parent's self time
    assert by_name["stage.work"]["self_us"] <= \
        by_name["stage.work"]["total_us"] - by_name["stage.read_wait"]["total_us"] \
        + 1000
    st = summary["stages"]["stage"]
    assert st["wait_us"] > 0
    assert 0.0 <= st["wait_frac"] <= 1.0
    text = tracing.render_summary(summary)
    assert "stage.read_wait" in text and "wait %" in text


# --- SPACEMESH_TRACE boot knob ----------------------------------------


def _boot_probe(trace_value: str) -> str:
    import os

    env = dict(os.environ)
    env["SPACEMESH_TRACE"] = trace_value
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "-c",
         "from spacemesh_tpu.utils import tracing; "
         "print(tracing.is_enabled(), tracing.TRACER.capacity)"],
        env=env, capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    return out.stdout.strip()


def test_boot_env_knob_starts_capture():
    assert _boot_probe("4096") == "True 4096"
    assert _boot_probe("off").startswith("False")


# --- the acceptance capture: init + prove + verify-farm ----------------


def _tiny_post_run(tmp_path):
    from spacemesh_tpu.post import initializer
    from spacemesh_tpu.post.prover import ProofParams, Prover

    node = hashlib.sha256(b"trace-node").digest()
    commit = hashlib.sha256(b"trace-commit").digest()
    ch = hashlib.sha256(b"trace-ch").digest()
    params = ProofParams(k1=64, k2=8, k3=4,
                         pow_difficulty=bytes([32]) + bytes([255]) * 31)
    initializer.initialize(
        str(tmp_path), node_id=node, commitment=commit, num_units=1,
        labels_per_unit=512, scrypt_n=2, max_file_size=4096,
        batch_size=128)
    return Prover(str(tmp_path), params, batch_labels=256).prove(ch)


async def _farm_leg():
    from spacemesh_tpu.core.signing import EdSigner
    from spacemesh_tpu.verify.farm import Lane, SigRequest, VerificationFarm

    signer = EdSigner()
    farm = VerificationFarm()
    reqs = [SigRequest(1, signer.public_key, b"msg-%d" % i,
                       signer.sign(1, b"msg-%d" % i)) for i in range(3)]
    try:
        verdicts = await asyncio.gather(
            *(farm.submit(r, lane=Lane.GOSSIP) for r in reqs))
    finally:
        await farm.aclose()
    return verdicts


def test_capture_init_prove_farm_end_to_end(tmp_path):
    """The PR's acceptance criterion: one capture over a small init +
    prove + verify-farm run exports valid trace-event JSON in which a
    verify-farm request span links to its batch's dispatch span and a
    prove window's read/dispatch/retire spans share one window id."""
    tracing.start(capacity=16384)
    proof = _tiny_post_run(tmp_path)
    assert proof.nonce >= 0
    verdicts = asyncio.run(_farm_leg())
    assert all(verdicts)
    tracing.stop()
    doc = tracing.export()
    tracing.validate(doc)
    evs = [e for e in doc["traceEvents"] if e["ph"] in ("X", "i")]
    names = {e["name"] for e in evs}

    # every layer of the node contributed spans
    assert {"init.run", "init.dispatch", "init.fetch", "init.write",
            "prove.run", "prove.window", "prove.read_io",
            "prove.dispatch", "prove.retire", "romix.dispatch",
            "farm.request", "farm.batch"} <= names

    # farm linkage: each non-dedup request span carries its batch's id,
    # and that batch's members list carries the request's id back
    batches = {e["args"]["id"]: e for e in evs
               if e["name"] == "farm.batch"}
    linked = 0
    for e in evs:
        if e["name"] == "farm.request" and "batch" in e["args"]:
            b = batches[e["args"]["batch"]]
            assert e["args"]["id"] in b["args"]["members"]
            linked += 1
    assert linked >= 1

    # prove window id: read/dispatch/retire of one pass share it, and
    # every batch-level prove span carries one
    windows = {}
    for e in evs:
        if e["name"] in ("prove.read_wait", "prove.dispatch",
                         "prove.retire"):
            windows.setdefault(e["args"]["window"], set()).add(e["name"])
    assert windows, "no windowed prove spans captured"
    first = min(windows)
    assert windows[first] == {"prove.read_wait", "prove.dispatch",
                              "prove.retire"}

    # the prove spans parent into their window span
    wspans = {e["args"]["id"] for e in evs if e["name"] == "prove.window"}
    for e in evs:
        if e["name"] == "prove.dispatch":
            assert e["args"]["parent"] in wspans

    # writer-pool spans crossed the thread boundary with their parent
    # (the submit-side stall span, itself nested under init.fetch)
    writes = [e for e in evs if e["name"] == "init.write"]
    submit_side = {e["args"]["id"] for e in evs
                   if e["name"] in ("init.fetch", "init.write_stall")}
    assert writes and all(e["args"].get("parent") in submit_side
                          for e in writes)
