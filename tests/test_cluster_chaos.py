"""Cluster harness chaos run: timeskew + kill on one subprocess cluster.

Drives the same ``Cluster`` class the one-command harness
(`python -m spacemesh_tpu.tools.cluster`) uses; scenario provenance:
reference systest/chaos/timeskew.go:12, fail.go:31 and the watcher
pattern of systest/tests/common.go.  The partition scenario is covered
by the harness CLI and the deterministic vclock suite
(tests/test_partition.py); running all three here would double the
suite's wall clock for no new code path.
"""

import time

import pytest

from spacemesh_tpu.tools.cluster import Cluster

N = 5
SMESHERS = 2
LPE = 3
LAYER_SEC = 1.0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    c = Cluster(tmp_path_factory.mktemp("chaos"), N, smeshers=SMESHERS,
                layer_sec=LAYER_SEC, lpe=LPE, spinup=90.0,
                until_layer=7 * LPE)  # nodes must outlive every assertion
    c.start()
    try:
        c.wait_api(timeout=210)
        yield c
    finally:
        c.stop()


def test_timeskew_then_kill_then_converge(cluster):
    c = cluster
    c.wait_layer(LPE, timeout=c.spinup + LPE * LAYER_SEC + 120)

    # chaos 1: skew the last node's clock forward three layers
    skewed = c.nodes[-1]
    c.timeskew(skewed, 3 * LAYER_SEC)
    st = skewed.api("/v1/node/status")["status"]
    assert st["top_layer"] >= LPE + 2, "skewed clock must show ahead"
    c.wait_layer(2 * LPE, timeout=120)
    c.timeskew(skewed, 0.0)

    # chaos 2: SIGKILL a different observer mid-run
    victim = c.nodes[-2]
    c.kill(victim)
    assert not victim.alive()

    # the survivors (incl. the formerly-skewed node) must keep applying
    # layers and agree on state
    survivors = [n for n in c.nodes if n is not victim]
    target = 3 * LPE + 1
    c.wait_layer(target + 1, timeout=180, nodes=survivors)
    deadline = time.time() + 180
    ok = False
    while time.time() < deadline and not ok:
        try:
            ok = c.converged(target, nodes=survivors)
        except OSError:  # a node mid-restart/poll race: retry
            ok = False
        time.sleep(LAYER_SEC / 2)
    assert ok, c.state_hashes(target, nodes=survivors)


def test_survivors_exit_clean(cluster):
    c = cluster
    victim = c.nodes[-2]
    deadline = time.time() + c.spinup + 8 * LPE * LAYER_SEC + 240
    for node in c.nodes:
        if node is victim:
            continue
        while node.alive() and time.time() < deadline:
            time.sleep(1.0)
        assert node.proc.poll() == 0, \
            f"{node.name} rc={node.proc.poll()} (log: {node.log_path})"
