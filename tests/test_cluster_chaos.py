"""Cluster harness chaos run: timeskew + kill on one subprocess cluster.

SUPERSEDED for day-to-day regression coverage by the deterministic
scenario engine (ISSUE 8): the ``timeskew-kill`` sim scenario
(spacemesh_tpu/sim/scenarios.py, asserted tier-1 in
tests/test_sim_scenarios.py) ports these assertions — skewed clock
ahead and back, SIGKILL a node, survivors keep applying and agree on
state — onto seeded virtual-clock nodes where any failure replays
exactly from its seed. This subprocess version stays TIER-2 ONLY as
the real-process/real-socket integration check: it drives the same
``Cluster`` class the one-command harness
(`python -m spacemesh_tpu.tools.cluster`) uses, with wall-clock sleeps
and per-run random keys (the flake class ADVICE.md kept flagging —
acceptable in tier-2, where reruns are cheap and the point is the
subprocess plumbing, not the consensus logic).

Scenario provenance: reference systest/chaos/timeskew.go:12, fail.go:31
and the watcher pattern of systest/tests/common.go.  The partition
scenario is covered by the harness CLI and the deterministic vclock
suite (tests/test_partition.py + the sim ``partition-heal``/
``storm-256`` scenarios).
"""

import time

import pytest

from spacemesh_tpu.tools.cluster import Cluster

# tier-2: a five-subprocess cluster needs minutes of real wall clock
# (POST init + jit warmup per node), and its random seeds make it
# statistically, not deterministically, green; the seeded sim port
# (tests/test_sim_scenarios.py::test_timeskew_kill_ports_cluster_chaos_assertions)
# is the tier-1 version of this coverage
pytestmark = pytest.mark.slow

N = 5
SMESHERS = 2
LPE = 3
LAYER_SEC = 1.0


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    # until_layer sizes the healing window: after the timeskew resets,
    # the skewed node must fork-find + resync BEFORE its clean exit —
    # under full-suite load that takes a while (round-5 flake: 7*LPE
    # left it diverged at exit)
    c = Cluster(tmp_path_factory.mktemp("chaos"), N, smeshers=SMESHERS,
                layer_sec=LAYER_SEC, lpe=LPE, spinup=90.0,
                until_layer=14 * LPE)
    c.start()
    try:
        c.wait_api(timeout=210)
        yield c
    finally:
        c.stop()


def test_timeskew_then_kill_then_converge(cluster):
    c = cluster
    c.wait_layer(LPE, timeout=c.spinup + LPE * LAYER_SEC + 120)

    # chaos 1: skew the last node's clock forward three layers
    skewed = c.nodes[-1]
    c.timeskew(skewed, 3 * LAYER_SEC)
    st = skewed.api("/v1/node/status")["status"]
    assert st["top_layer"] >= LPE + 2, "skewed clock must show ahead"
    c.wait_layer(2 * LPE, timeout=120)
    c.timeskew(skewed, 0.0)

    # chaos 2: SIGKILL a different observer mid-run
    victim = c.nodes[-2]
    c.kill(victim)
    assert not victim.alive()

    # the survivors (incl. the formerly-skewed node) must keep applying
    # layers and agree on state
    survivors = [n for n in c.nodes if n is not victim]
    target = 3 * LPE + 1
    c.wait_layer(target + 1, timeout=180, nodes=survivors)
    # On a machine loaded with the rest of the suite, the survivors can
    # reach until_layer and EXIT (cleanly) while this loop is still
    # polling — at which point every API call is connection-refused
    # (the one full-suite flake of round 5). A clean exit is not a
    # failure: the final verdict then comes from the nodes' databases.
    deadline = time.time() + 180
    ok = False
    hashes: dict = {}
    while time.time() < deadline and not ok:
        if all(not n.alive() and n.proc.poll() == 0 for n in survivors):
            hashes = c.db_state_hashes(target, nodes=survivors)
            vals = set(hashes.values())
            ok = len(vals) == 1 and None not in vals
            break
        try:
            ok = c.converged(target, nodes=survivors)
        except OSError:  # a node mid-restart/poll race: retry
            ok = False
        time.sleep(LAYER_SEC / 2)
    assert ok, hashes or "no convergence while nodes were live"


def test_survivors_exit_clean(cluster):
    c = cluster
    victim = c.nodes[-2]
    deadline = time.time() + c.spinup + 15 * LPE * LAYER_SEC + 240
    for node in c.nodes:
        if node is victim:
            continue
        while node.alive() and time.time() < deadline:
            time.sleep(1.0)
        assert node.proc.poll() == 0, \
            f"{node.name} rc={node.proc.poll()} (log: {node.log_path})"
