"""Node infrastructure: config presets, layer clock, event bus."""

import asyncio
import json

import pytest

from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node import config as config_mod
from spacemesh_tpu.node import events as events_mod


def test_presets():
    main = config_mod.load("mainnet")
    assert main.layer_duration == 300.0 and main.layers_per_epoch == 4032
    assert main.post.scrypt_n == 8192 and main.post.labels_per_unit == 2**32
    fast = config_mod.load("fastnet")
    assert fast.layer_duration == 15.0 and fast.post.scrypt_n == 2
    sa = config_mod.load("standalone")
    assert sa.standalone and sa.smeshing.start
    assert main.genesis.genesis_id != b""
    assert len(main.genesis.genesis_id) == 20


def test_mainnet_preset_consensus_parameters():
    """The full mainnet profile (reference config/mainnet.go) — the
    values that are CONSENSUS-critical must be pinned, not defaulted."""
    main = config_mod.load("mainnet")
    assert main.post.min_num_units == 4            # 256 GiB minimum
    assert main.post.k1 == 26 and main.post.k2 == 37 and main.post.k3 == 1
    assert main.post.pow_difficulty.startswith("000dfb23b0979b4b")
    # nonzero min-weight floor: the dust-declared-set defense is ON
    assert main.min_active_set_weight == [(0, 1_000_000)]
    # historical hare committee downgrade (mainnet.go:70-75)
    assert main.hare.committee_size == 400
    assert main.hare.committee_upgrade == [105_720, 50]
    assert main.tortoise.hdist == 10
    assert main.tortoise.window_size == 4032


def test_testnet_preset():
    """Testnet trio completes the reference's preset set
    (config/presets/testnet.go): mainnet timing, day-long epochs, small
    units, low-but-nonzero floor."""
    tn = config_mod.load("testnet")
    assert tn.layer_duration == 300.0
    assert tn.layers_per_epoch == 288
    assert tn.post.min_num_units == 2
    assert tn.post.labels_per_unit == 1024
    assert tn.min_active_set_weight == [(0, 10_000)]
    assert tn.poet_cycle_gap == 7200.0
    # distinct genesis id from mainnet (different network)
    assert tn.genesis.genesis_id != config_mod.load("mainnet") \
        .genesis.genesis_id


def test_every_preset_loads_and_validates():
    for name in config_mod.PRESETS:
        cfg = config_mod.load(name)
        assert cfg.preset == name
        assert cfg.layers_per_epoch > 0 and cfg.layer_duration > 0
        assert cfg.p2p.transport in ("tcp", "quic")


def test_config_file_and_overrides(tmp_path):
    f = tmp_path / "c.json"
    f.write_text(json.dumps({"layer_duration": 1.5,
                             "post": {"k1": 99}}))
    cfg = config_mod.load("fastnet", file=f, overrides={"data_dir": "/x"})
    assert cfg.layer_duration == 1.5
    assert cfg.post.k1 == 99
    assert cfg.data_dir == "/x"
    assert cfg.post.scrypt_n == 2  # preset value survives partial override
    with pytest.raises(ValueError, match="unknown config key"):
        config_mod.load("fastnet", overrides={"nope": 1})


def test_genesis_id_depends_on_time_and_extra():
    a = config_mod.GenesisConfig(time=100, extra_data="x").genesis_id
    b = config_mod.GenesisConfig(time=101, extra_data="x").genesis_id
    c = config_mod.GenesisConfig(time=100, extra_data="y").genesis_id
    assert a != b and a != c


def test_clock_layers():
    ft = clock_mod.FakeTime(start=1000.0)
    c = clock_mod.LayerClock(genesis_time=1000.0, layer_duration=10.0,
                             time_source=ft)
    assert c.current_layer() == 0
    ft.advance(25)
    assert c.current_layer() == 2
    assert c.time_of(3) == 1030.0
    ft.t = 990.0
    assert c.current_layer() == 0
    assert not c.genesis_reached()


def test_clock_await_and_ticks():
    async def run():
        ft = clock_mod.FakeTime(start=1000.0)
        c = clock_mod.LayerClock(1000.0, 10.0, time_source=ft)
        seen = []

        async def consume():
            async for lyr in c.ticks():
                seen.append(int(lyr))
                if len(seen) >= 3:
                    return

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.05)
        ft.advance(10)   # layer 1
        await asyncio.sleep(0.1)
        ft.advance(20)   # layers 2,3
        await asyncio.wait_for(task, timeout=2)
        assert seen == [1, 2, 3]
    asyncio.run(run())


def test_event_bus():
    async def run():
        bus = events_mod.EventBus()
        sub = bus.subscribe(events_mod.LayerUpdate, events_mod.BeaconEvent)
        bus.emit(events_mod.LayerUpdate(layer=1, status="tick"))
        bus.emit(events_mod.AtxEvent(atx_id=b"", node_id=b"", epoch=0))  # not subscribed
        bus.emit(events_mod.BeaconEvent(epoch=2, beacon=b"\x01"))
        ev1 = await sub.next()
        ev2 = await sub.next()
        assert isinstance(ev1, events_mod.LayerUpdate)
        assert isinstance(ev2, events_mod.BeaconEvent)
        assert sub.queue.empty()
        sub.close()
        bus.emit(events_mod.BeaconEvent(epoch=3, beacon=b"\x02"))
        assert sub.queue.empty()
    asyncio.run(run())


def test_metrics_registry():
    from spacemesh_tpu.utils import metrics as m

    reg = m.Registry()
    c = reg.counter("reqs", "requests")
    c.inc(); c.inc(2, route="/v1/x")
    reg.gauge("depth").set(7)
    h = reg.histogram("lat")
    h.observe(0.001); h.observe(42)
    text = reg.expose()
    assert "reqs 1.0" in text and 'route="/v1/x"' in text
    assert "depth 7" in text
    assert "lat_count 2" in text
    assert reg.counter("reqs") is c  # idempotent registration
    import pytest as _pytest
    with _pytest.raises(TypeError):
        reg.gauge("reqs")


def test_logging_levels(capsys):
    import logging

    from spacemesh_tpu.utils import logging as slog

    slog.configure(level="INFO", levels={"hare": "DEBUG"})
    assert slog.get("hare").isEnabledFor(logging.DEBUG)
    assert not slog.get("mesh").isEnabledFor(logging.DEBUG)
    assert slog.get("mesh").isEnabledFor(logging.INFO)


def test_event_bus_overflow():
    bus = events_mod.EventBus()
    sub = bus.subscribe(events_mod.LayerUpdate, size=2)
    for i in range(5):
        bus.emit(events_mod.LayerUpdate(layer=i, status="tick"))
    assert sub.overflowed
    assert sub.queue.qsize() == 2


def test_clock_await_layer_across_jump_with_notify():
    """A big injected-time jump (chaos timeskew / virtual clock): every
    await_layer waiter wakes IMMEDIATELY on notify_time_changed() and
    observes the post-jump layer — no poll-interval latency, no missed
    wakeups (ISSUE 8 satellite)."""

    async def run():
        ft = clock_mod.FakeTime(start=1000.0)
        c = clock_mod.LayerClock(1000.0, 10.0, time_source=ft,
                                 poll_interval=30.0)
        # poll_interval is deliberately huge: only the notify can wake
        # the waiters within the test timeout
        w5 = asyncio.create_task(c.await_layer(5))
        w2 = asyncio.create_task(c.await_layer(2))
        await asyncio.sleep(0.05)
        assert not w5.done() and not w2.done()
        ft.advance(57)           # jump straight into layer 5
        c.notify_time_changed()
        assert await asyncio.wait_for(w5, 1.0) == 5
        assert await asyncio.wait_for(w2, 1.0) == 5
        # an already-begun layer returns without any waiting
        assert await asyncio.wait_for(c.await_layer(3), 1.0) == 5

    asyncio.run(run())


def test_clock_ticks_order_and_completeness_across_jump():
    """A jump spanning several layers must yield EVERY skipped layer,
    in order, exactly once — consumers (the App layer loop) depend on
    gapless tick streams for epoch bookkeeping."""

    async def run():
        ft = clock_mod.FakeTime(start=1000.0)
        c = clock_mod.LayerClock(1000.0, 10.0, time_source=ft)
        seen = []

        async def consume():
            async for lyr in c.ticks():
                seen.append(int(lyr))
                if len(seen) >= 6:
                    return

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.05)
        ft.advance(41)           # jump over layers 1..4 at once
        c.notify_time_changed()
        await asyncio.sleep(0.1)
        assert seen == [1, 2, 3, 4]
        ft.advance(8.9)          # t=1049.9: not yet layer 5 (1050)
        c.notify_time_changed()
        await asyncio.sleep(0.05)
        assert seen == [1, 2, 3, 4]
        ft.advance(11.2)         # layers 5 and 6 land together
        c.notify_time_changed()
        await asyncio.wait_for(task, timeout=2)
        assert seen == [1, 2, 3, 4, 5, 6]

    asyncio.run(run())


def test_clock_backward_jump_keeps_waiters_sane():
    """A BACKWARD jump (timeskew healing) must not fire layers early:
    waiters re-arm against the corrected time and fire at the true
    layer start."""

    async def run():
        ft = clock_mod.FakeTime(start=1000.0)
        c = clock_mod.LayerClock(1000.0, 10.0, time_source=ft)
        ft.advance(35)                       # layer 3
        assert int(c.current_layer()) == 3
        w = asyncio.create_task(c.await_layer(4))
        await asyncio.sleep(0.05)
        ft.t = 1005.0                        # heal: back to layer 0
        c.notify_time_changed()
        await asyncio.sleep(0.1)
        assert not w.done(), "waiter fired during the backward jump"
        ft.t = 1041.0                        # true layer 4 start
        c.notify_time_changed()
        assert await asyncio.wait_for(w, 1.0) == 4

    asyncio.run(run())
