"""State-DB migration 0004: Reward gained atx_id — old block blobs must
re-encode on open, with block ids (content hashes) and the tables that
point at them following. Derived data the rewrite invalidates (chained
aggregated layer hashes, signed certificates, ballot vote lists) must be
recomputed, dropped, or fenced off behind the recorded boundary layer
(ADVICE r4)."""

import io

from spacemesh_tpu.core import codec, types
from spacemesh_tpu.core.hashing import sum256
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.storage import misc as miscstore


def _legacy_block_bytes(layer, tick, rewards, tx_ids):
    w = io.BytesIO()
    types.u32.enc(w, layer)
    types.u64.enc(w, tick)
    codec.vec(codec.Codec(
        enc=lambda w_, v: (types.ADDRESS.enc(w_, v[0]),
                           types.u64.enc(w_, v[1])),
        dec=None), 1 << 12).enc(w, rewards)
    codec.vec(types.HASH32, 1 << 16).enc(w, tx_ids)
    return w.getvalue()


def test_migration_reencodes_legacy_blocks(tmp_path):
    path = tmp_path / "state.db"
    # build a pre-0004 database: schema at version 3, legacy block blob
    old = dbmod.Database(path, dbmod.STATE_MIGRATIONS[:3], name="state")
    coinbase = b"\x07" * 24
    data = _legacy_block_bytes(5, 9, [(coinbase, 3)], [b"\x21" * 32])
    old_id = sum256(data)
    old.exec("INSERT INTO blocks (id, layer, data) VALUES (?,?,?)",
             (old_id, 5, data))
    old.exec("INSERT INTO layers (id, applied_block, aggregated_hash)"
             " VALUES (?,?,?)", (5, old_id, sum256(bytes(32), old_id)))
    old.exec("INSERT INTO certificates (layer, block_id) VALUES (?,?)",
             (5, old_id))
    old.close()

    state = dbmod.open_state(path)  # runs 0004
    blocks = blockstore.in_layer(state, 5)
    assert len(blocks) == 1
    b = blocks[0]
    assert b.tick_height == 9
    assert b.rewards == [types.Reward(atx_id=bytes(32), coinbase=coinbase,
                                      weight=3)]
    assert b.id != old_id
    assert layerstore.applied_block(state, 5) == b.id
    # certificates are signed over the OLD id and cannot be re-signed:
    # the migration drops them instead of rewriting the column
    assert state.one("SELECT COUNT(*) c FROM certificates")["c"] == 0
    # the boundary mark fences pre-rewrite signed ballots off from recovery
    assert miscstore.migration_boundary(state) == 5
    # idempotent: reopening does not re-run (user_version advanced)
    state.close()
    state2 = dbmod.open_state(path)
    assert len(blockstore.in_layer(state2, 5)) == 1
    assert miscstore.migration_boundary(state2) == 5


def test_migration_recomputes_aggregated_hash_chain(tmp_path):
    """agg(L) = H(agg(L-1) || applied_block) chains over the REWRITTEN ids
    after the migration — a freshly syncing peer computing the chain from
    the new blocks must agree with the upgraded node's stored values."""
    path = tmp_path / "state.db"
    old = dbmod.Database(path, dbmod.STATE_MIGRATIONS[:3], name="state")
    ids = {}
    agg = bytes(32)
    for layer in (1, 2, 3):
        data = _legacy_block_bytes(layer, 0, [(b"\x01" * 24, layer)], [])
        ids[layer] = sum256(data)
        agg = sum256(agg, ids[layer])  # pre-migration chain (old ids)
        old.exec("INSERT INTO blocks (id, layer, data) VALUES (?,?,?)",
                 (ids[layer], layer, data))
        old.exec("INSERT INTO layers (id, applied_block, aggregated_hash)"
                 " VALUES (?,?,?)", (layer, ids[layer], agg))
    old.close()

    state = dbmod.open_state(path)
    expect = bytes(32)
    for layer in (1, 2, 3):
        new_id = layerstore.applied_block(state, layer)
        assert new_id != ids[layer]
        expect = sum256(expect, new_id)
        assert layerstore.aggregated_hash(state, layer) == expect
    assert miscstore.migration_boundary(state) == 3
    state.close()


def test_version4_database_gets_fixups_via_0005(tmp_path):
    """A database already migrated to version 4 by the previous build
    (ids rewritten, derived data left stale) must still receive the
    repairs — 0005 detects the stale aggregated-hash chain on its own
    (0004 cannot be amended: version-4 databases never re-run it)."""
    path = tmp_path / "state.db"
    old = dbmod.Database(path, dbmod.STATE_MIGRATIONS[:3], name="state")
    data = _legacy_block_bytes(7, 1, [(b"\x09" * 24, 2)], [])
    old_id = sum256(data)
    old.exec("INSERT INTO blocks (id, layer, data) VALUES (?,?,?)",
             (old_id, 7, data))
    old.exec("INSERT INTO layers (id, applied_block, aggregated_hash)"
             " VALUES (?,?,?)", (7, old_id, sum256(bytes(32), old_id)))
    old.exec("INSERT INTO certificates (layer, block_id) VALUES (?,?)",
             (7, old_id))
    old.close()
    # version 4 as the old code left it: rewrite done, fixups absent
    mid = dbmod.Database(path, dbmod.STATE_MIGRATIONS[:4], name="state")
    assert mid.one("SELECT COUNT(*) c FROM certificates")["c"] == 1
    new_id = layerstore.applied_block(mid, 7)
    assert new_id != old_id
    assert layerstore.aggregated_hash(mid, 7) == sum256(bytes(32), old_id)
    mid.close()

    state = dbmod.open_state(path)  # 0005 runs
    assert layerstore.aggregated_hash(state, 7) == sum256(bytes(32), new_id)
    assert state.one("SELECT COUNT(*) c FROM certificates")["c"] == 0
    assert miscstore.migration_boundary(state) == 7
    state.close()


def test_0005_fences_only_pre_rewrite_layers(tmp_path):
    """A version-4 node that kept RUNNING after the rewrite has valid
    post-rewrite layers, certificates, and ballots — 0005 must localize
    the boundary with the step relation and fence only at/below it
    (code-review r5: over-fencing discarded weeks of valid state)."""
    from spacemesh_tpu.core.types import Block

    path = tmp_path / "state.db"
    old = dbmod.Database(path, dbmod.STATE_MIGRATIONS[:3], name="state")
    data = _legacy_block_bytes(1, 0, [(b"\x01" * 24, 1)], [])
    old_id = sum256(data)
    old.exec("INSERT INTO blocks (id, layer, data) VALUES (?,?,?)",
             (old_id, 1, data))
    stale_agg = sum256(bytes(32), old_id)
    old.exec("INSERT INTO layers (id, applied_block, aggregated_hash)"
             " VALUES (?,?,?)", (1, old_id, stale_agg))
    old.exec("INSERT INTO certificates (layer, block_id) VALUES (?,?)",
             (1, old_id))
    old.close()
    # version-4 code rewrites layer 1's ids; the node then keeps running
    # and applies layer 2 with a NEW-format block, chaining its agg hash
    # on the (stale-prefixed) stored value — step-consistent
    mid = dbmod.Database(path, dbmod.STATE_MIGRATIONS[:4], name="state")
    new1 = layerstore.applied_block(mid, 1)
    assert new1 != old_id
    blk2 = Block(layer=2, tick_height=0, rewards=[], tx_ids=[])
    mid.exec("INSERT INTO blocks (id, layer, data) VALUES (?,?,?)",
             (blk2.id, 2, blk2.to_bytes()))
    mid.exec("INSERT INTO layers (id, applied_block, aggregated_hash)"
             " VALUES (?,?,?)", (2, blk2.id, sum256(stale_agg, blk2.id)))
    mid.exec("INSERT INTO certificates (layer, block_id) VALUES (?,?)",
             (2, blk2.id))
    mid.close()

    state = dbmod.open_state(path)  # 0005
    assert miscstore.migration_boundary(state) == 1
    # pre-rewrite cert dropped, valid post-rewrite cert KEPT
    certs = [r["layer"] for r in
             state.all("SELECT layer FROM certificates ORDER BY layer")]
    assert certs == [2]
    # full chain recomputed from genesis over rewritten ids
    assert layerstore.aggregated_hash(state, 1) == sum256(bytes(32), new1)
    assert layerstore.aggregated_hash(state, 2) \
        == sum256(sum256(bytes(32), new1), blk2.id)
    state.close()


def test_0005_is_noop_on_consistent_chain(tmp_path):
    """A database whose chain already matches (never held legacy blocks)
    keeps its certificates and gets no boundary."""
    path = tmp_path / "state.db"
    mid = dbmod.Database(path, dbmod.STATE_MIGRATIONS[:4], name="state")
    from spacemesh_tpu.core.types import Block, Certificate
    blk = Block(layer=3, tick_height=0, rewards=[], tx_ids=[])
    mid.exec("INSERT INTO blocks (id, layer, data) VALUES (?,?,?)",
             (blk.id, 3, blk.to_bytes()))
    mid.exec("INSERT INTO layers (id, applied_block, aggregated_hash)"
             " VALUES (?,?,?)", (3, blk.id, sum256(bytes(32), blk.id)))
    mid.exec("INSERT INTO certificates (layer, block_id) VALUES (?,?)",
             (3, blk.id))
    mid.close()
    state = dbmod.open_state(path)
    assert state.one("SELECT COUNT(*) c FROM certificates")["c"] == 1
    assert miscstore.migration_boundary(state) == -1
    state.close()


def test_fresh_database_has_no_boundary(tmp_path):
    state = dbmod.open_state(tmp_path / "state.db")
    assert miscstore.migration_boundary(state) == -1
    state.close()
