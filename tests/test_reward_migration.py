"""State-DB migration 0004: Reward gained atx_id — old block blobs must
re-encode on open, with block ids (content hashes) and the tables that
point at them following."""

import io

from spacemesh_tpu.core import codec, types
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import layers as layerstore


def _legacy_block_bytes(layer, tick, rewards, tx_ids):
    w = io.BytesIO()
    types.u32.enc(w, layer)
    types.u64.enc(w, tick)
    codec.vec(codec.Codec(
        enc=lambda w_, v: (types.ADDRESS.enc(w_, v[0]),
                           types.u64.enc(w_, v[1])),
        dec=None), 1 << 12).enc(w, rewards)
    codec.vec(types.HASH32, 1 << 16).enc(w, tx_ids)
    return w.getvalue()


def test_migration_reencodes_legacy_blocks(tmp_path):
    path = tmp_path / "state.db"
    # build a pre-0004 database: schema at version 3, legacy block blob
    old = dbmod.Database(path, dbmod.STATE_MIGRATIONS[:3], name="state")
    coinbase = b"\x07" * 24
    data = _legacy_block_bytes(5, 9, [(coinbase, 3)], [b"\x21" * 32])
    from spacemesh_tpu.core.hashing import sum256
    old_id = sum256(data)
    old.exec("INSERT INTO blocks (id, layer, data) VALUES (?,?,?)",
             (old_id, 5, data))
    old.exec("INSERT INTO layers (id, applied_block) VALUES (?,?)",
             (5, old_id))
    old.exec("INSERT INTO certificates (layer, block_id) VALUES (?,?)",
             (5, old_id))
    old.close()

    state = dbmod.open_state(path)  # runs 0004
    blocks = blockstore.in_layer(state, 5)
    assert len(blocks) == 1
    b = blocks[0]
    assert b.tick_height == 9
    assert b.rewards == [types.Reward(atx_id=bytes(32), coinbase=coinbase,
                                      weight=3)]
    assert b.id != old_id
    assert layerstore.applied_block(state, 5) == b.id
    assert state.one("SELECT block_id FROM certificates WHERE layer=5")[
        "block_id"] == b.id
    # idempotent: reopening does not re-run (user_version advanced)
    state.close()
    state2 = dbmod.open_state(path)
    assert len(blockstore.in_layer(state2, 5)) == 1
