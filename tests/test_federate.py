"""Fleet-wide observability federation (PR 20, obs/federate.py).

The collection plane's contracts: the strict escape-aware exposition
parser round-trips hostile label values; pipe-shipped Registry.sample()
documents and HTTP-scraped exposition text federate to identical
triples; ``proc=`` series obey strict cardinality hygiene (gone on
drop, retained+flagged on crash); ``tracing.merge_captures`` produces
ONE validate-clean timeline with per-process provenance and resolved
cross-process parent links; and the two end-to-end planes — sim shard
workers over pipes, verifyd replicas over HTTP — both land a merged
timeline with ≥1 cross-process link and zero leaked series after a
clean teardown. Plus the satellites that ride on the same machinery:
span-drop accounting surfaced as a loud profiler hint, the romix
roofline model, flight bundles' ``procs/`` subdir, and benchtrend's
``--history`` trajectory view.
"""

import asyncio
import json
import os
import subprocess
import sys
import threading

import pytest

from spacemesh_tpu.obs import flight as flight_mod
from spacemesh_tpu.obs.federate import (FEDERATION, Federation,
                                        flatten_samples, parse_exposition)
from spacemesh_tpu.sim import builtin, run_scenario
from spacemesh_tpu.sim.shard import ShardedMeshHub
from spacemesh_tpu.utils import metrics, tracing
from spacemesh_tpu.tools import benchtrend
from spacemesh_tpu.tools.profiler import (_drop_hint, romix_roofline,
                                          timeline_view)


@pytest.fixture(autouse=True)
def _clean_slate(monkeypatch):
    """Every test starts with no federated procs, no live capture and
    the default process identity (tests here mutate all three)."""
    monkeypatch.delenv("SPACEMESH_SIM_SHARDS", raising=False)
    FEDERATION.clear()
    if tracing.is_enabled():
        tracing.stop()
    yield
    FEDERATION.clear()
    if tracing.is_enabled():
        tracing.stop()
    tracing.set_process_identity(f"pid-{os.getpid()}")


# --- the strict exposition parser --------------------------------------


def test_parser_roundtrips_escaped_label_values():
    reg = metrics.Registry()
    g = reg.gauge("nasty_gauge", "hostile label values")
    hostile = 'quote " backslash \\ newline \n done'
    g.set(2.5, peer=hostile, plain="ok")
    series = parse_exposition(reg.expose())
    match = [(lb, v) for name, lb, v in series if name == "nasty_gauge"]
    assert match == [({"peer": hostile, "plain": "ok"}, 2.5)]


def test_parser_rejects_garbage_lines():
    for bad in ("not a metric", 'x{a="1} 2', 'x{a="1"} ',
                'x{=""} 1', "x 1 2 3"):
        with pytest.raises(ValueError):
            parse_exposition(bad)
    # comments and blanks are fine
    assert parse_exposition("# HELP x y\n\n") == []


def test_flatten_samples_matches_expose_histograms_included():
    """Pipe-shipped (sample) and HTTP-shipped (exposition) snapshots of
    the same registry must federate to the SAME triples — or a shard
    worker and a verifyd replica would disagree about one metric."""
    reg = metrics.Registry()
    reg.counter("c_total", "c").inc(3, kind="a")
    reg.gauge("g", "g").set(1.5)
    h = reg.histogram("h_seconds", "h", buckets=(0.1, 1.0))
    h.observe(0.05, lane="x")
    h.observe(5.0, lane="x")

    def key(triples):
        return sorted((n, tuple(sorted(lb.items())), v)
                      for n, lb, v in triples)

    assert key(flatten_samples(reg.sample())) == \
        key(parse_exposition(reg.expose()))


# --- the Federation container ------------------------------------------


def test_federation_proc_lifecycle_and_cardinality_hygiene():
    fed = Federation()
    fed.update("w1", [("up", {"x": "1"}, 1.0)], trace={"traceEvents": []})
    fed.update("w2", [("up", {}, 1.0)])
    text = fed.expose()
    series = parse_exposition(text)
    assert {lb.get("proc") for _, lb, _ in series} == {"w1", "w2"}
    # clean exit: drop removes EVERY series for that proc
    assert fed.drop("w1") is True
    assert all(lb.get("proc") != "w1"
               for _, lb, _ in parse_exposition(fed.expose()))
    assert fed.trace("w1") is None
    # crash: snapshot retained AND flagged for forensics
    fed.mark_crashed("w2")
    series = parse_exposition(fed.expose())
    assert ("federated_proc_crashed", {"proc": "w2"}, 1.0) in series
    assert any(n == "up" and lb.get("proc") == "w2"
               for n, lb, _ in series)
    # a re-update means the process is evidently alive again
    fed.update("w2", [("up", {}, 2.0)])
    assert not fed.procs()["w2"]["crashed"]
    fed.clear()
    assert fed.expose() == "" and fed.procs() == {}


def test_federation_gauges_track_live_and_crashed():
    fed = Federation()  # private instance still drives the global gauge
    fed.update("a", [])
    fed.update("b", [])
    fed.mark_crashed("b")
    sample = metrics.REGISTRY.sample()["federated_procs"][1]
    assert sample[(("state", "live"),)] == 1.0
    assert sample[(("state", "crashed"),)] == 1.0
    fed.clear()


# --- merge_captures: provenance + cross-process links -------------------


def _two_process_captures():
    """Two REAL captures taken sequentially from the one in-process
    tracer, wearing different process identities — the child's span
    links to the parent's via the parent's link token."""
    tracing.set_process_identity("parent")
    tracing.start(capacity=256, jax_bridge=False)
    with tracing.span("request", {"n": 1}, cat="test"):
        token = tracing.link_token()
    parent_doc = tracing.export()
    tracing.stop()

    tracing.set_process_identity("child", clock_domain="wall")
    tracing.start(capacity=256, jax_bridge=False)
    with tracing.span("handle", {"link": token}, cat="test"):
        pass
    with tracing.span("orphan", {"link": "ghost/12345"}, cat="test"):
        pass
    child_doc = tracing.export()
    tracing.stop()
    return parent_doc, child_doc


def test_merge_captures_resolves_links_and_stamps_provenance():
    parent_doc, child_doc = _two_process_captures()
    merged = tracing.merge_captures([parent_doc, child_doc])
    assert tracing.validate(merged) == []
    od = merged["otherData"]
    assert [p["role"] for p in od["procs"]] == ["parent", "child"]
    assert od["links"] == {"resolved": 1, "unresolved": 1}
    # the resolved child span now parents into the parent's timeline
    spans = [e for e in merged["traceEvents"] if e.get("ph") == "X"]
    handle = next(e for e in spans if e["name"] == "handle")
    request = next(e for e in spans if e["name"] == "request")
    assert handle["args"]["parent"] == request["args"]["id"]
    assert handle["pid"] != request["pid"]


def test_merged_digest_is_a_span_multiset_not_a_timestamp_hash():
    parent_doc, child_doc = _two_process_captures()
    d1 = tracing.span_multiset_digest(
        tracing.merge_captures([parent_doc, child_doc]))
    d2 = tracing.span_multiset_digest(
        tracing.merge_captures([parent_doc, child_doc]))
    assert d1 == d2
    # dropping the child changes the multiset, hence the digest
    assert d1 != tracing.span_multiset_digest(
        tracing.merge_captures([parent_doc]))


def test_federation_merged_capture_orders_procs_deterministically():
    parent_doc, child_doc = _two_process_captures()
    fed = Federation()
    fed.update("child", [], trace=child_doc)
    merged = fed.merged_capture(parent=parent_doc)
    assert [p["role"] for p in merged["otherData"]["procs"]] == \
        ["parent", "child"]
    assert fed.merged_capture() is not None
    assert Federation().merged_capture() is None


# --- satellite: drop accounting ends in a loud profiler hint ------------


def test_span_drops_surface_in_validate_and_profiler_hint(tmp_path):
    tracing.set_process_identity("droppy")
    tracing.start(capacity=4, jax_bridge=False)
    for i in range(32):
        with tracing.span(f"s{i}", cat="test"):
            pass
    doc = tracing.export()
    tracing.stop()
    assert doc["otherData"]["dropped_spans"] > 0
    warnings = tracing.validate(doc)
    assert warnings and any("dropped" in w for w in warnings)
    hint = _drop_hint(warnings)
    assert "SPACEMESH_TRACE" in hint and "trace_capacity" in hint
    assert "LOWER BOUNDS" in hint
    # the timeline view returns the warnings and exits clean
    p = tmp_path / "trace.json"
    p.write_text(json.dumps(doc))
    view = timeline_view(str(p), top=5)
    assert view["warnings"] == warnings
    assert _drop_hint([]) is None


def test_timeline_view_merges_comma_separated_captures(tmp_path):
    parent_doc, child_doc = _two_process_captures()
    pa, ch = tmp_path / "parent.json", tmp_path / "child.json"
    pa.write_text(json.dumps(parent_doc))
    ch.write_text(json.dumps(child_doc))
    view = timeline_view(f"{pa},{ch}", top=5)
    assert view["merged"] is True
    assert [p["proc"] for p in view["procs"]] == ["parent", "child"]
    assert view["cross_proc_links"]["total"] == 1
    assert "request->handle" in view["cross_proc_links"]["pairs"]


# --- satellite: the romix roofline model --------------------------------


def test_romix_roofline_traffic_and_compute_model(monkeypatch):
    monkeypatch.delenv("SPACEMESH_ROOFLINE_GBPS", raising=False)
    r = romix_roofline(8192)
    # ROMix moves V twice (fill writes, mix reads): 2 * 128 * N bytes
    assert r["bytes_per_label"] == 2 * 128 * 8192
    # 2N BlockMix passes of 2r Salsa20/8 cores: 4N at r=1
    assert r["salsa20_8_per_label"] == 4 * 8192
    assert "utilization" not in r and "achieved_gbps" not in r
    # r/p scale both linearly
    r2 = romix_roofline(8192, r=2, p=2)
    assert r2["bytes_per_label"] == 4 * r["bytes_per_label"]
    assert r2["salsa20_8_per_label"] == 4 * r["salsa20_8_per_label"]

    full = romix_roofline(8192, labels_per_sec=1000.0, gbps=50.0)
    assert full["achieved_gbps"] == pytest.approx(
        2 * 128 * 8192 * 1000.0 / 1e9, rel=1e-3)
    assert full["utilization"] == pytest.approx(
        full["achieved_gbps"] / 50.0, abs=1e-3)
    assert full["roofline_labels_per_sec"] == pytest.approx(
        50e9 / full["bytes_per_label"], rel=1e-3)
    # the peak defaults from the environment
    monkeypatch.setenv("SPACEMESH_ROOFLINE_GBPS", "10")
    assert romix_roofline(8192)["roofline_gbps"] == 10.0


# --- satellite: flight bundles grow a procs/ subdir ---------------------


def test_flight_bundle_federates_procs_and_digests_merged(tmp_path):
    parent_doc, child_doc = _two_process_captures()
    FEDERATION.update("shard-1", [("up", {}, 1.0)], trace=child_doc)
    FEDERATION.update("shard-2", [("up", {}, 1.0)])
    FEDERATION.mark_crashed("shard-2")
    rec = flight_mod.FlightRecorder(tmp_path / "spool", min_interval_s=0)
    path = rec.dump("test:procs", force=True)
    assert path is not None

    bundle = flight_mod.read_bundle(path)
    assert set(bundle["procs"]) == {"shard-1", "shard-2"}
    assert bundle["procs"]["shard-1"]["trace"] is not None
    assert not bundle["procs"]["shard-1"]["crashed"]
    assert bundle["procs"]["shard-2"]["crashed"]
    assert 'proc="shard-1"' in bundle["procs"]["shard-1"]["metrics"]

    doc = flight_mod.digest(bundle)
    assert doc["procs"]["shard-2"]["crashed"] is True
    # the summary ran over the MERGED timeline: the child's spans show
    # up under its own proc row
    roles = {p["proc"] for p in doc["proc_self_time"]}
    assert "child" in roles


# --- satellite: benchtrend --history ------------------------------------


def _bench_round(root, n, value, ratio):
    line = json.dumps({"metric": f"post_init_labels_per_sec_n{n}",
                       "value": value, "vs_baseline": ratio,
                       "bit_identical": True})
    (root / f"BENCH_r{n:02d}.json").write_text(
        json.dumps({"n": n, "tail": line + "\n"}))


def test_benchtrend_history_renders_trajectory_with_markers(tmp_path,
                                                            capsys):
    _bench_round(tmp_path, 1, 100.0, 2.0)
    _bench_round(tmp_path, 2, 104.0, 2.1)
    _bench_round(tmp_path, 3, 50.0, 1.0)   # >10% round-over-round drop
    doc = benchtrend.history(str(tmp_path), drop=0.10)
    assert doc["rounds"] == [1, 2, 3]
    rows = doc["families"]["post_init_labels_per_sec"]
    assert [r["round"] for r in rows] == [1, 2, 3]
    assert rows[0]["regressed"] == [] and rows[1]["regressed"] == []
    assert set(rows[2]["regressed"]) == {"value", "vs_baseline"}
    text = benchtrend.render_history(doc)
    assert "post_init_labels_per_sec" in text and " v" in text
    # report-only: exits 0 even with regressions in the trajectory
    assert benchtrend.main(["--history", "--root", str(tmp_path)]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["families"]


def test_benchtrend_gate_still_requires_current():
    with pytest.raises(SystemExit):
        benchtrend.main(["--root", "/nonexistent"])


# --- end-to-end: the sharded sim fabric federates over pipes ------------


@pytest.fixture(scope="module")
def sharded_runs(tmp_path_factory):
    """The SAME seeded 2-worker smoke run twice: one pass proves the
    federation plane, the pair proves merged-capture determinism. The
    federation's state is snapshotted IMMEDIATELY after each run (the
    autouse fixture clears it between tests)."""
    out = []
    for tag in ("a", "b"):
        FEDERATION.clear()
        script = builtin("smoke", light=6)
        script["shards"] = 2
        r = run_scenario(script, tmp=tmp_path_factory.mktemp(f"fed-{tag}"))
        out.append((r, FEDERATION.expose(), dict(FEDERATION.procs())))
    return out


def test_sharded_run_merges_a_validate_clean_fleet_timeline(sharded_runs):
    r, _, _ = sharded_runs[0]
    assert r.ok, [a for a in r.asserts if not a["ok"]]
    kinds = {a["kind"]: a for a in r.asserts}
    assert kinds["trace_valid"]["ok"]
    assert kinds["merged_procs"]["ok"], kinds["merged_procs"]
    assert kinds["cross_proc_links"]["ok"], kinds["cross_proc_links"]
    # proc= series were LIVE during the run (asserted in-engine, where
    # the workers still exist)
    assert kinds["proc_series_live"]["ok"], kinds["proc_series_live"]
    mt = r.stats["merged_trace"]
    assert mt["procs"] == 2
    assert mt["links"]["unresolved"] == 0
    assert mt["links"]["resolved"] >= 1
    assert mt["warnings"] == []


def test_sharded_run_leaks_zero_proc_series_after_finalize(sharded_runs):
    r, expose_text, procs = sharded_runs[0]
    assert r.ok
    # strict parse over the federation's own post-run exposition:
    # clean worker exits took every proc= series with them
    assert parse_exposition(expose_text) == []
    assert not any(p.startswith("shard-") for p in procs)


def test_sharded_merged_capture_digest_is_deterministic(sharded_runs):
    (a, _, _), (b, _, _) = sharded_runs
    assert a.stats["merged_trace"]["digest"] == \
        b.stats["merged_trace"]["digest"]
    assert a.digest == b.digest


def test_crashed_worker_snapshot_is_retained_for_forensics(
        tmp_path, monkeypatch):
    """Kill worker 0 mid-run: the typed failure carries the dead
    worker's last federated snapshot, and the federation RETAINS its
    proc= series flagged crashed (clean-exit hygiene must not eat the
    forensics)."""
    calls = {"n": 0}
    orig = ShardedMeshHub._flush_and_run

    def killer(self, need, upto, inclusive):
        calls["n"] += 1
        if calls["n"] == 5:
            self._workers[0].proc.kill()
        return orig(self, need, upto, inclusive)

    monkeypatch.setattr(ShardedMeshHub, "_flush_and_run", killer)
    script = builtin("smoke", light=6)
    script["shards"] = 2
    r = run_scenario(script, tmp=tmp_path)
    assert not r.ok
    crash = next(a for a in r.asserts if a["kind"] == "shard_worker")
    assert crash["last_metrics"] and crash["last_spans"]
    procs = FEDERATION.procs()
    crashed = {p: e for p, e in procs.items() if e["crashed"]}
    assert crashed, procs
    assert all(p.startswith("shard-") for p in crashed)
    assert "federated_proc_crashed" in FEDERATION.expose()


# --- end-to-end: verifyd replicas federate over HTTP --------------------


def _boot_replica():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "spacemesh_tpu.verifyd",
         "--listen", "127.0.0.1:0", "--workers", "2"],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, env=env)
    doc = {}

    def read():
        line = p.stdout.readline()
        if line:
            doc.update(json.loads(line))

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(90)
    if not doc:
        p.kill()
        raise RuntimeError("verifyd replica did not boot in 90s")
    return p, "http://" + doc["listening"]


def test_fleet_router_pulls_and_merges_replica_captures():
    """The real thing, no fakes: two verifyd replicas in their OWN
    processes, captures started over /debug/trace, verify traffic
    carrying trace_parent link tokens, the router pulling trace +
    /metrics into the federation, and ONE validate-clean merged
    timeline with replica provenance and resolved cross-process
    links. Unregistering a replica drops its proc= series."""
    aiohttp = pytest.importorskip("aiohttp")  # noqa: F841
    from spacemesh_tpu.verify.farm import PowRequest
    from spacemesh_tpu.verifyd.fleet import (FleetRouter,
                                             HttpReplicaEndpoint)

    replicas = [_boot_replica() for _ in range(2)]

    async def go():
        tracing.set_process_identity("fleet-parent")
        tracing.start(capacity=8192, jax_bridge=False)
        router = FleetRouter(seed=1)
        endpoints = []
        try:
            for i, (_, url) in enumerate(replicas):
                ep = HttpReplicaEndpoint(url)
                endpoints.append(ep)
                router.register_replica(f"r{i}", ep, own_endpoint=True)
            started = await router.start_captures(capacity=4096)
            assert started == {
                "r0": {"enabled": True, "capacity": 4096,
                       "role": "replica-r0"},
                "r1": {"enabled": True, "capacity": 4096,
                       "role": "replica-r1"}}
            req = PowRequest(challenge=b"\x01" * 32,
                             node_id=b"\x02" * 32,
                             difficulty=b"\xff" * 32, nonce=1)
            for name, rep in sorted(router.replicas.items()):
                await rep.endpoint.register(f"cli-{name}")
                with tracing.span("fleet.remote", {"replica": name}):
                    got = await rep.endpoint.verify(
                        [req], client=f"cli-{name}")
                assert len(got) == 1
            pulled = await router.pull_captures()
            assert set(pulled) == {"replica-r0", "replica-r1"}
            for proc, doc in pulled.items():
                assert doc["otherData"]["proc"]["role"] == proc

            merged = router.merged_capture(parent=tracing.export())
            assert tracing.validate(merged) == []
            od = merged["otherData"]
            assert [p["role"] for p in od["procs"]] == \
                ["fleet-parent", "replica-r0", "replica-r1"]
            assert od["links"]["unresolved"] == 0
            assert od["links"]["resolved"] >= 2

            # every replica's series re-exposed under proc= provenance
            series = parse_exposition(FEDERATION.expose())
            for proc in ("replica-r0", "replica-r1"):
                assert any(lb.get("proc") == proc
                           for _, lb, _ in series), proc
            # a replica that LEAVES takes its proc= series with it
            router.unregister_replica("r0")
            assert "replica-r0" not in FEDERATION.procs()
            assert all(lb.get("proc") != "replica-r0" for _, lb, _ in
                       parse_exposition(FEDERATION.expose()))
        finally:
            tracing.stop()
            for ep in endpoints:
                await ep.aclose()
            await router.aclose()

    try:
        asyncio.run(go())
    finally:
        for p, _ in replicas:
            p.terminate()
        for p, _ in replicas:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
