"""ATX v2: merged multi-identity ATXs, marriages, equivocation sets,
InvalidPrevATX and InvalidPostIndex malfeasance.

Reference: activation/wire/wire_v2.go, handler_v2.go:379 marriages,
malfeasance/handler.go:33-42 proof types. End-to-end: two identities
POST-init tiny data, one merged ATX covers both through a real poet
round, the handler validates it batched, marriage condemns both when one
equivocates.
"""

import asyncio
import dataclasses
import hashlib

import pytest

from spacemesh_tpu.consensus import activation_v2, malfeasance as mal_mod
from spacemesh_tpu.consensus.activation import commitment_of
from spacemesh_tpu.consensus.poet import PoetService
from spacemesh_tpu.core.hashing import sum256
from spacemesh_tpu.core.signing import Domain, EdSigner, EdVerifier
from spacemesh_tpu.core.types import ActivationTxV2, MarriageCert
from spacemesh_tpu.p2p.pubsub import PubSub
from spacemesh_tpu.post import initializer
from spacemesh_tpu.post.prover import ProofParams
from spacemesh_tpu.post.service import PostClient
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import misc as miscstore
from spacemesh_tpu.storage.cache import AtxCache

GEN = b"atxv2-test-genesis!!"
GOLDEN = sum256(b"golden", GEN)
PARAMS = ProofParams(k1=64, k2=8, k3=4,
                     pow_difficulty=b"\x20" + b"\xff" * 31)
LPU = 256  # labels per unit


@pytest.fixture(scope="module")
def world(tmp_path_factory):
    """Two identities with initialized POST data + a built merged ATX."""
    tmp = tmp_path_factory.mktemp("atxv2")
    primary = EdSigner(prefix=GEN)
    partner = EdSigner(prefix=GEN)
    clients = {}
    for s in (primary, partner):
        d = tmp / s.node_id.hex()[:12]
        initializer.initialize(
            d, node_id=s.node_id,
            commitment=commitment_of(s.node_id, GOLDEN),
            num_units=1, labels_per_unit=LPU, scrypt_n=2, batch_size=128)
        clients[s.node_id] = PostClient(d, PARAMS)

    db = dbmod.open_state(":memory:")
    poet = PoetService(poet_id=sum256(b"poet", GEN), ticks=64)

    atx2 = asyncio.run(activation_v2.build_merged_atx(
        primary=primary, partners=[partner], db=db, poet=poet,
        post_clients=clients, golden_atx=GOLDEN, coinbase=bytes(24),
        publish_epoch=1, execute_round=True))
    return primary, partner, db, atx2


def _handler(db):
    cache = AtxCache()
    return activation_v2.HandlerV2(
        db=db, cache=cache, verifier=EdVerifier(prefix=GEN),
        golden_atx=GOLDEN, post_params=PARAMS, labels_per_unit=LPU,
        scrypt_n=2), cache


def test_merged_atx_validates_and_stores_per_identity(world):
    primary, partner, db, atx2 = world
    handler, cache = _handler(db)
    assert handler.process(atx2)
    for s in (primary, partner):
        view = atxstore.by_node_in_epoch(db, s.node_id, 1)
        assert view is not None
        assert view.id == atx2.identity_atx_id(s.node_id)
        assert view.version == 2
        info = cache.get(2, view.id)
        assert info is not None and info.node_id == s.node_id
        assert info.weight > 0
    # marriage recorded for both
    m1 = miscstore.marriage_of(db, primary.node_id)
    m2 = miscstore.marriage_of(db, partner.node_id)
    assert m1 == m2 == atx2.id


def test_unmarried_identity_rejected(world):
    primary, partner, db, atx2 = world
    stranger = EdSigner(prefix=GEN)
    bad = dataclasses.replace(
        atx2,
        subposts=[dataclasses.replace(atx2.subposts[0]),
                  dataclasses.replace(atx2.subposts[1],
                                      node_id=stranger.node_id)],
        signature=bytes(64))
    bad = dataclasses.replace(
        bad, signature=primary.sign(Domain.ATX, bad.signed_bytes()))
    handler, _ = _handler(dbmod.open_state(":memory:"))
    # needs the poet blob; reuse original db's handler instead
    handler2, _ = _handler(db)
    assert not handler2.process(bad)


def test_forged_marriage_cert_rejected(world):
    primary, partner, db, atx2 = world
    stranger = EdSigner(prefix=GEN)
    forged_cert = MarriageCert(
        partner_id=partner.node_id,
        signature=stranger.sign(Domain.ATX,
                                MarriageCert.message(primary.node_id)))
    bad = dataclasses.replace(atx2, marriages=[forged_cert],
                              signature=bytes(64))
    bad = dataclasses.replace(
        bad, signature=primary.sign(Domain.ATX, bad.signed_bytes()))
    handler, _ = _handler(db)
    assert not handler.process(bad)


def test_process_async_parity_with_inline(world):
    """HandlerV2.process (inline) and process_async (verification farm)
    must return identical verdicts on the same envelopes — valid,
    bad-signature, and tampered-POST. The 'edit them together' comments
    in consensus/activation_v2.py point here."""
    from spacemesh_tpu.verify.farm import VerificationFarm

    primary, partner, db, atx2 = world
    bad_sig = dataclasses.replace(atx2, signature=bytes(64))
    sp0 = atx2.subposts[0]
    # out-of-range indices: deterministic reject on both paths (an
    # in-range shift could still pass the K3 spot check for one path —
    # the seeded device-path parity lives in tests/test_verify_farm.py)
    tampered = dataclasses.replace(atx2, subposts=[
        dataclasses.replace(sp0, nipost=dataclasses.replace(
            sp0.nipost, post=dataclasses.replace(
                sp0.nipost.post,
                indices=[LPU + 1 + i
                         for i in sp0.nipost.post.indices]))),
        atx2.subposts[1]], signature=bytes(64))
    tampered = dataclasses.replace(
        tampered, signature=primary.sign(Domain.ATX,
                                         tampered.signed_bytes()))
    envelopes = [atx2, bad_sig, tampered]

    async def farm_verdicts():
        farm = VerificationFarm(ed_verifier=EdVerifier(prefix=GEN),
                                post_params=PARAMS)
        h, _ = _handler(db)
        h.farm = farm
        out = [await h.process_async(e) for e in envelopes]
        await farm.aclose()
        return out

    # farm path first (full validation incl. store of the valid one);
    # the inline pass then re-derives every verdict on the same state
    got = asyncio.run(farm_verdicts())
    h2, _ = _handler(db)
    expected = [h2.process(e) for e in envelopes]
    assert got == expected == [True, False, False]


def test_marriage_condemns_whole_set(world):
    """One married identity equivocates -> the WHOLE set is malicious."""
    primary, partner, db, atx2 = world
    handler, cache = _handler(db)
    assert handler.process(atx2)

    ps = PubSub(node_name=b"test")
    mal = mal_mod.Handler(db=db, cache=cache,
                          verifier=EdVerifier(prefix=GEN), pubsub=ps)
    # the PARTNER double-signs hare messages
    from spacemesh_tpu.consensus.hare import HareMessage

    def hare_msg(values):
        m = HareMessage(layer=3, iteration=0, round=0, values=values,
                        eligibility_proof=bytes(80), eligibility_count=1,
                        atx_id=bytes(32), node_id=partner.node_id,
                        cert_msgs=[], signature=bytes(64))
        m.signature = partner.sign(Domain.HARE, m.signed_bytes())
        return m

    m1, m2 = hare_msg([sum256(b"x")]), hare_msg([sum256(b"y")])
    proof = mal_mod.MalfeasanceProof(
        domain=int(Domain.HARE), msg1=m1.signed_bytes(), sig1=m1.signature,
        msg2=m2.signed_bytes(), sig2=m2.signature, node_id=partner.node_id)
    assert mal.process(proof)
    assert miscstore.is_malicious(db, partner.node_id)
    assert miscstore.is_malicious(db, primary.node_id), \
        "married primary must fall with the equivocating partner"
    assert cache.is_malicious(primary.node_id)


@pytest.fixture(scope="module")
def v1_world(tmp_path_factory):
    """One identity with a REAL v1 ATX (own poet round + POST proof)."""
    from spacemesh_tpu.consensus.activation import (
        nipost_challenge, post_challenge, store_poet_blob)
    from spacemesh_tpu.consensus.poet import PoetBlob
    from spacemesh_tpu.core.types import (
        EMPTY32, ActivationTx, NIPost, Post, PostMetadataWire)

    tmp = tmp_path_factory.mktemp("atxv1")
    s = EdSigner(prefix=GEN)
    initializer.initialize(
        tmp / "post", node_id=s.node_id,
        commitment=commitment_of(s.node_id, GOLDEN),
        num_units=1, labels_per_unit=LPU, scrypt_n=2, batch_size=128)
    client = PostClient(tmp / "post", PARAMS)
    db = dbmod.open_state(":memory:")
    poet = PoetService(poet_id=sum256(b"poet-v1", GEN), ticks=64)
    challenge = nipost_challenge(EMPTY32, 1)

    async def run_round():
        await poet.register("1", challenge)
        return await poet.execute_round("1")

    result = asyncio.run(run_round())
    store_poet_blob(db, PoetBlob(proof=result.proof,
                                 member_count=len(result.members)))
    proof, _meta = client.proof(post_challenge(result.proof.root,
                                               challenge))
    info = client.info()
    atx = ActivationTx(
        publish_epoch=1, prev_atx=EMPTY32, pos_atx=GOLDEN,
        commitment_atx=commitment_of(s.node_id, GOLDEN),
        initial_post=None,
        nipost=NIPost(
            membership=result.membership(challenge),
            post=Post(nonce=proof.nonce, indices=proof.indices,
                      pow_nonce=proof.pow_nonce),
            post_metadata=PostMetadataWire(
                challenge=result.proof.id,
                labels_per_unit=info.labels_per_unit)),
        num_units=info.num_units, vrf_nonce=info.vrf_nonce,
        vrf_public_key=s.vrf_signer().public_key, coinbase=bytes(24),
        node_id=s.node_id, signature=bytes(64))
    atx = dataclasses.replace(
        atx, signature=s.sign(Domain.ATX, atx.signed_bytes()))
    return s, db, atx


def test_v1_process_async_parity_with_inline(v1_world):
    """activation.Handler.process (inline) vs process_async (farm):
    identical verdicts for valid, bad-signature, wrong-VRF-key, and
    tampered-POST envelopes. The 'edit them together' comment in
    consensus/activation.py points here."""
    from spacemesh_tpu.consensus import activation
    from spacemesh_tpu.verify.farm import VerificationFarm

    s, db, atx = v1_world
    bad_sig = dataclasses.replace(atx, signature=bytes(64))
    bad_vrf = dataclasses.replace(atx, vrf_public_key=bytes(32),
                                  signature=bytes(64))
    bad_vrf = dataclasses.replace(
        bad_vrf, signature=s.sign(Domain.ATX, bad_vrf.signed_bytes()))
    tampered = dataclasses.replace(
        atx, nipost=dataclasses.replace(
            atx.nipost, post=dataclasses.replace(
                atx.nipost.post,  # out of range: deterministic reject
                indices=[LPU + 1 + i for i in atx.nipost.post.indices])),
        signature=bytes(64))
    tampered = dataclasses.replace(
        tampered, signature=s.sign(Domain.ATX, tampered.signed_bytes()))
    envelopes = [atx, bad_sig, bad_vrf, tampered]

    def handler(farm):
        return activation.Handler(
            db=db, cache=AtxCache(), verifier=EdVerifier(prefix=GEN),
            golden_atx=GOLDEN, post_params=PARAMS, labels_per_unit=LPU,
            scrypt_n=2, pubsub=PubSub(), farm=farm)

    async def farm_verdicts():
        farm = VerificationFarm(ed_verifier=EdVerifier(prefix=GEN),
                                post_params=PARAMS)
        h = handler(farm)
        out = [await h.process_async(e) for e in envelopes]
        await farm.aclose()
        return out

    # farm path first (full validation incl. store of the valid one);
    # the inline pass then re-derives every verdict on the same state
    got = asyncio.run(farm_verdicts())
    expected = [handler(None).process(e) for e in envelopes]
    assert got == expected == [True, False, False, False]


def test_checkpoint_roundtrips_v2_atxs(world):
    """Checkpoint snapshot + recover must carry merged ATXs intact
    (one envelope blob, per-identity rows + ticks restored)."""
    from spacemesh_tpu.node import checkpoint

    primary, partner, db, atx2 = world
    handler, _ = _handler(db)
    handler.process(atx2)
    snap = checkpoint.generate(db)
    # the envelope appears ONCE even though two identity rows exist
    assert sum(1 for b in snap["atxs"]
               if bytes.fromhex(b) == atx2.to_bytes()) == 1

    fresh = dbmod.open_state(":memory:")
    checkpoint.recover(fresh, snap)
    for s in (primary, partner):
        view = atxstore.by_node_in_epoch(fresh, s.node_id, 1)
        assert view is not None and view.version == 2
        assert atxstore.tick_height(fresh, view.id) == \
            atxstore.tick_height(db, view.id)
    fresh.close()


def test_invalid_prev_atx_proof():
    """Two v1 ATXs claiming the same prev -> malfeasance."""
    from spacemesh_tpu.core.types import (
        ActivationTx, MerkleProof, NIPost, Post, PostMetadataWire)

    db = dbmod.open_state(":memory:")
    cache = AtxCache()
    evil = EdSigner(prefix=GEN)
    prev = sum256(b"some prev atx")

    def make_atx(epoch):
        atx = ActivationTx(
            publish_epoch=epoch, prev_atx=prev, pos_atx=GOLDEN,
            commitment_atx=None, initial_post=None,
            nipost=NIPost(membership=MerkleProof(leaf_index=0, nodes=[]),
                          post=Post(nonce=0, indices=[1], pow_nonce=0),
                          post_metadata=PostMetadataWire(
                              challenge=bytes(32), labels_per_unit=LPU)),
            num_units=1, vrf_nonce=0, vrf_public_key=evil.node_id,
            coinbase=bytes(24), node_id=evil.node_id, signature=bytes(64))
        return dataclasses.replace(
            atx, signature=evil.sign(Domain.ATX, atx.signed_bytes()))

    a1, a2 = make_atx(3), make_atx(4)  # different epochs, same prev
    proof = mal_mod.MalfeasanceProof(
        domain=int(Domain.ATX), msg1=a1.signed_bytes(), sig1=a1.signature,
        msg2=a2.signed_bytes(), sig2=a2.signature, node_id=evil.node_id)
    ps = PubSub(node_name=b"t")
    mal = mal_mod.Handler(db=db, cache=cache,
                          verifier=EdVerifier(prefix=GEN), pubsub=ps)
    assert mal.process(proof)
    assert miscstore.is_malicious(db, evil.node_id)


def test_invalid_post_index_proof(world, tmp_path):
    """An ATX carrying a non-qualifying POST index is provably bad."""
    primary, partner, db, atx2 = world
    from spacemesh_tpu.consensus.activation import (
        nipost_challenge, post_challenge)
    from spacemesh_tpu.core.types import (
        ActivationTx, NIPost, Post, PostMetadataWire)
    from spacemesh_tpu.post import verifier as pv
    from spacemesh_tpu.post.prover import Proof as PProof

    cheat = EdSigner(prefix=GEN)
    # take the real poet proof from the merged build
    poet = miscstore.poet_proof(db, atx2.subposts[0].nipost
                                .post_metadata.challenge)
    assert poet is not None

    atx = ActivationTx(
        publish_epoch=1, prev_atx=bytes(32), pos_atx=GOLDEN,
        commitment_atx=None, initial_post=None,
        nipost=NIPost(
            membership=atx2.subposts[0].nipost.membership,
            post=Post(nonce=0, indices=[0, 7, 13], pow_nonce=0),
            post_metadata=PostMetadataWire(challenge=poet.id,
                                           labels_per_unit=LPU)),
        num_units=1, vrf_nonce=0, vrf_public_key=cheat.node_id,
        coinbase=bytes(24), node_id=cheat.node_id, signature=bytes(64))
    atx = dataclasses.replace(
        atx, signature=cheat.sign(Domain.ATX, atx.signed_bytes()))

    def post_checker(a, index_pos):
        challenge = nipost_challenge(a.prev_atx, a.publish_epoch)
        params = dataclasses.replace(PARAMS, k2=1, k3=1)
        item = pv.VerifyItem(
            proof=PProof(nonce=a.nipost.post.nonce,
                         indices=[a.nipost.post.indices[index_pos]],
                         pow_nonce=a.nipost.post.pow_nonce, k2=1),
            challenge=post_challenge(poet.root, challenge),
            node_id=a.node_id,
            commitment=commitment_of(a.node_id, GOLDEN),
            scrypt_n=2, total_labels=LPU)
        return not pv.verify(item, params)

    cache = AtxCache()
    ps = PubSub(node_name=b"t")
    mal = mal_mod.Handler(db=db, cache=cache,
                          verifier=EdVerifier(prefix=GEN), pubsub=ps,
                          post_checker=post_checker)
    proof = mal_mod.proof_invalid_post(atx, 1)
    assert mal.process(proof)
    assert miscstore.is_malicious(db, cheat.node_id)
