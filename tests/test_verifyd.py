"""verifyd — verification-as-a-service (spacemesh_tpu/verifyd/).

The acceptance properties (ISSUE 13): verdicts bit-identical to inline
verification through admission + fair share + continuous batching;
typed SHED responses (never silent drops) with heavy-client-first
fairness under overload, asserted from windowed SLIs with injected
time and zero sleeps; per-client metric series bounded under client
churn; graceful drain with zero stranded futures; the speculative
batch-sizing model's race/persist/policy contracts; and the wire
protocol over real sockets (HTTP + gRPC carrying identical docs).
"""

import asyncio
import json
import threading
import time

import pytest

from spacemesh_tpu.obs import health as health_mod
from spacemesh_tpu.obs import sli as sli_mod
from spacemesh_tpu.utils import metrics, tracing
from spacemesh_tpu.verify import workload
from spacemesh_tpu.verify.farm import (
    Lane, PowRequest, SigRequest, VerificationFarm)
from spacemesh_tpu.verifyd import (
    Shed,
    VerifydClient,
    VerifydServer,
    VerifydService,
    batchtune,
    protocol,
)


@pytest.fixture(scope="module")
def wl(tmp_path_factory):
    """One small mixed workload (every kind, malformed items included)
    per module — the POST init + proofs inside are the expensive part."""
    d = tmp_path_factory.mktemp("verifyd-wl")
    return workload.build(str(d), sigs=16, vrfs=4, posts=6,
                          memberships=4, pows=8, post_challenges=2)


@pytest.fixture(scope="module")
def expected(wl):
    return wl.inline_all()


def _service(wl, **kw):
    kw.setdefault("workers", 3)
    svc = VerifydService(post_params=wl.post_params,
                         post_seed=wl.post_seed, **kw)
    svc.farm.ed_verifier = wl.ed
    svc.farm.vrf_verifier = wl.vrf
    return svc


class _Clock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def now(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _run(coro):
    return asyncio.run(coro)


# --- parity + tracing ----------------------------------------------------


def test_service_parity_and_span_linkage(wl, expected):
    """Admitted verdicts are bit-identical to inline verification, and
    a client request decomposes verifyd.request -> verifyd.drain ->
    farm.request -> farm.batch in one capture (the worker-thread hop
    re-parents explicitly)."""

    async def go():
        svc = _service(wl)
        try:
            await svc.start()
            svc.register_client("alice")
            got = await svc.verify("alice", wl.requests)
            assert got == expected
        finally:
            await svc.aclose()

    tracing.start(capacity=65536)
    try:
        _run(go())
    finally:
        tracing.stop()
    doc = tracing.export()
    tracing.validate(doc)
    spans = [e for e in doc["traceEvents"]
             if e["ph"] == "X" and "id" in e.get("args", {})]
    reqs = [e for e in spans if e["name"] == "verifyd.request"]
    drains = [e for e in spans if e["name"] == "verifyd.drain"]
    farm_reqs = [e for e in spans if e["name"] == "farm.request"]
    batches = [e for e in spans if e["name"] == "farm.batch"]
    assert reqs and drains and farm_reqs and batches
    req_ids = {e["args"]["id"] for e in reqs}
    assert any(e["args"].get("parent") in req_ids for e in drains), \
        "drain spans must parent into the request span across the hop"
    drain_ids = {e["args"]["id"] for e in drains}
    linked = [e for e in farm_reqs
              if e["args"].get("parent") in drain_ids]
    assert linked, "farm.request must chain under verifyd.drain"
    batch_ids = {e["args"]["id"] for e in batches}
    assert any(e["args"].get("batch") in batch_ids for e in linked), \
        "the request chain must link to its farm.batch"


def test_empty_and_unregistered(wl):
    async def go():
        svc = _service(wl)
        try:
            await svc.start()
            svc.register_client("a")
            assert await svc.verify("a", []) == []
            with pytest.raises(Shed) as ei:
                await svc.verify("ghost", wl.requests[:1])
            assert ei.value.reason == protocol.SHED_UNREGISTERED
        finally:
            await svc.aclose()

    _run(go())


def test_genesis_id_prefixes_the_service_verifier():
    """genesis_id is a consensus parameter: nodes sign
    genesis_id||domain||msg, so a replica verifying under a different
    prefix fails every honest signature (the --genesis-id CLI flag)."""
    from spacemesh_tpu.core.signing import Domain, EdSigner

    gid = b"e2e-genesis-id"
    signer = EdSigner(seed=b"\x07" * 32, prefix=gid)
    msg = b"prefixed"
    req = SigRequest(int(Domain.HARE), signer.public_key, msg,
                     signer.sign(Domain.HARE, msg))

    async def go(svc):
        try:
            await svc.start()
            svc.register_client("a")
            return (await svc.verify("a", [req]))[0]
        finally:
            await svc.aclose()

    assert _run(go(VerifydService(workers=1, genesis_id=gid))) is True
    assert _run(go(VerifydService(workers=1))) is False
    with pytest.raises(ValueError):
        VerifydService(farm=VerificationFarm(), genesis_id=gid)


# --- typed admission -----------------------------------------------------


def test_registry_full_typed_and_bounded(wl):
    async def go():
        svc = _service(wl, max_clients=2)
        try:
            await svc.start()
            svc.register_client("a")
            svc.register_client("b")
            with pytest.raises(Shed) as ei:
                svc.register_client("c")
            assert ei.value.reason == protocol.SHED_REGISTRY_FULL
            # re-registering an existing client is reconfig, not growth
            svc.register_client("a", weight=2.0)
            assert len(svc.clients) == 2
            # every unspecified knob KEEPS its value: a rate-only
            # update must not silently reset the fair-share weight
            svc.register_client("a", rate=9000.0)
            assert svc.clients["a"].weight == 2.0
            assert svc.clients["a"].bucket.rate == 9000.0
            assert svc.scheduler._tenants["a"].weight == 2.0
        finally:
            await svc.aclose()

    _run(go())


def test_rate_shed_typed_with_injected_refill(wl, expected):
    """Token-bucket shed carries retry_after_s; advancing the INJECTED
    clock (no sleeps) refills and re-admits."""
    clock = _Clock()

    async def go():
        svc = _service(wl, time_source=clock.now)
        try:
            await svc.start()
            # budget for exactly one 2-sig request (cost 2), no refill
            # to speak of within the test window
            svc.register_client("a", rate=0.5, burst=2.0)
            reqs = [r for r in wl.requests
                    if isinstance(r, SigRequest)][:2]
            got = await svc.verify("a", reqs)
            assert got == [wl.inline_verify(r) for r in reqs]
            with pytest.raises(Shed) as ei:
                await svc.verify("a", reqs)
            assert ei.value.reason == protocol.SHED_RATE
            assert ei.value.retry_after_s > 0
            assert ei.value.to_doc()["status"] == "SHED"
            clock.advance(ei.value.retry_after_s + 0.1)
            got = await svc.verify("a", reqs)
            assert got == [wl.inline_verify(r) for r in reqs]
        finally:
            await svc.aclose()

    _run(go())


def test_deadline_shed_predicts_miss(wl):
    clock = _Clock()

    async def go():
        svc = _service(wl, time_source=clock.now)
        try:
            await svc.start()
            svc.register_client("a")
            # white-box backlog: 1000 pending at 10 items/s -> 100 s
            svc._pending_items, svc._rate_ewma = 1000, 10.0
            try:
                with pytest.raises(Shed) as ei:
                    await svc.verify("a", wl.requests[:1],
                                     deadline_s=1.0)
            finally:
                svc._pending_items, svc._rate_ewma = 0, 0.0
            assert ei.value.reason == protocol.SHED_DEADLINE
            assert ei.value.retry_after_s == pytest.approx(100.0)
        finally:
            await svc.aclose()

    _run(go())


# --- overload: fairness, typed sheds, bounded queue, SLIs ---------------


def _gate_farm(svc):
    """Hold every farm backend dispatch behind a threading.Event so
    pending work accumulates deterministically (no timing races)."""
    gate = threading.Event()
    orig = svc.farm._run_backend

    def gated(kind, reqs):
        gate.wait(timeout=60)
        return orig(kind, reqs)

    svc.farm._run_backend = gated
    return gate


def test_overload_heavy_shed_first_bounded_slis(wl, expected):
    """Offered load far above capacity: the heavy client sheds with
    typed overload/rate reasons FIRST, the light client's BLOCK-lane
    work keeps being admitted, every admitted verdict is correct, the
    queue stays bounded, and the BLOCK-lane p99 SLO evaluates green
    from windowed SLIs on the injected clock — zero sleeps."""
    clock = _Clock()
    sig_pool = [r for r in wl.requests if isinstance(r, SigRequest)]

    async def go():
        svc = _service(wl, time_source=clock.now, max_pending_items=40,
                       workers=2, default_rate=1e9, default_burst=1e9)
        engine = health_mod.HealthEngine(
            slis=sli_mod.verifyd_slis(),
            slos=health_mod.verifyd_slos(), time_source=clock.now)
        gate = _gate_farm(svc)
        try:
            await svc.start()
            svc.register_client("heavy")
            svc.register_client("light")
            engine.tick(clock.now())

            def req(n):
                return [sig_pool[i % len(sig_pool)] for i in range(n)]

            tasks = []

            async def submit(cid, n, lane):
                try:
                    got = await svc.verify(cid, req(n), lane=lane)
                    return ("ok", got, [wl.inline_verify(r)
                                        for r in req(n)])
                except Shed as e:
                    return (e.reason, None, None)

            # heavy floods: 8 requests x 10 items against a 40-item
            # bound (fair share 20); gate holds the farm so pending
            # accumulates deterministically
            for _ in range(8):
                tasks.append(asyncio.ensure_future(
                    submit("heavy", 10, Lane.SYNC)))
                await asyncio.sleep(0)
            # light client's block-critical work lands anyway
            light_tasks = []
            for _ in range(3):
                light_tasks.append(asyncio.ensure_future(
                    submit("light", 4, Lane.BLOCK)))
                await asyncio.sleep(0)
            clock.advance(0.01)
            gate.set()
            heavy_out = await asyncio.gather(*tasks)
            light_out = await asyncio.gather(*light_tasks)
            clock.advance(1.0)
            engine.tick(clock.now())

            heavy_shed = [o for o in heavy_out if o[0] != "ok"]
            assert heavy_shed, "heavy client must shed"
            assert all(o[0] in (protocol.SHED_OVERLOAD,
                                protocol.SHED_QUEUE_FULL)
                       for o in heavy_shed), heavy_shed
            assert all(o[0] == "ok" for o in light_out), \
                "light BLOCK-lane work must be admitted"
            for outcome, got, exp in heavy_out + light_out:
                if outcome == "ok":
                    assert got == exp, "zero wrong verdicts"
            assert svc.stats["pending_peak"] <= 40, "bounded queue"
            assert svc.stats["shed"].get(protocol.SHED_OVERLOAD, 0) >= 1
            # windowed SLIs on the injected clock: BLOCK p99 exists and
            # its SLO is green (admitted block work resolved without
            # queueing behind the flood)
            report = engine.tick(clock.now())
            assert report["slis"].get("verifyd_request_block_p99") \
                is not None
            assert not report["slos"]["verifyd_block_latency"]["breached"]
        finally:
            engine.close()
            await svc.aclose()

    _run(go())


def test_quota_shed_typed(wl):
    async def go():
        svc = _service(wl, workers=2)
        gate = _gate_farm(svc)
        try:
            await svc.start()
            svc.register_client("a", max_queued=1)
            t = asyncio.ensure_future(
                svc.verify("a", wl.requests[:2]))
            await asyncio.sleep(0)
            with pytest.raises(Shed) as ei:
                await svc.verify("a", wl.requests[:2])
            assert ei.value.reason == protocol.SHED_QUOTA
            gate.set()
            await t
        finally:
            gate.set()
            await svc.aclose()

    _run(go())


# --- graceful drain ------------------------------------------------------


def test_graceful_drain_zero_stranded_futures(wl, expected):
    """aclose() drains admitted work (verdicts still delivered), then
    sheds new submits with shutting_down; nothing hangs."""

    async def go():
        svc = _service(wl, workers=2)
        gate = _gate_farm(svc)
        try:
            await svc.start()
            svc.register_client("a")
            pending = [asyncio.ensure_future(
                svc.verify("a", wl.requests[i:i + 4]))
                for i in range(0, 12, 4)]
            await asyncio.sleep(0)
            closer = asyncio.ensure_future(svc.aclose())
            await asyncio.sleep(0)
            gate.set()
            results = await asyncio.gather(*pending,
                                           return_exceptions=True)
            await closer
            for i, r in enumerate(results):
                assert not isinstance(r, BaseException), r
                assert r == expected[4 * i:4 * i + 4]
            with pytest.raises(Shed) as ei:
                await svc.verify("a", wl.requests[:1])
            assert ei.value.reason == protocol.SHED_SHUTTING_DOWN
        finally:
            gate.set()
            await svc.aclose()

    _run(go())


# --- per-client metric cardinality --------------------------------------


def test_client_churn_bounds_metric_cardinality(wl):
    """The satellite regression: a churn loop of poisoned client ids
    must leave ZERO per-client series behind (gauge, counters,
    scheduler tenant series), and the exposition stays parseable."""
    poisoned = [f'churn-{i}-"quote"\\back\nline' for i in range(24)]

    async def go():
        svc = _service(wl, max_clients=8)
        try:
            await svc.start()
            req = wl.requests[:2]
            for cid in poisoned:
                svc.register_client(cid)
                await svc.verify(cid, req)
                with pytest.raises(Shed):
                    await svc.verify("nobody", req)  # "-" series only
                svc.unregister_client(cid)
            assert len(svc.clients) == 0
        finally:
            await svc.aclose()

    _run(go())
    churned = set(poisoned)
    for inst in (metrics.verifyd_client_pending, metrics.verifyd_items,
                 metrics.verifyd_requests, metrics.verifyd_shed,
                 metrics.runtime_tenant_queued,
                 metrics.runtime_tenant_jobs,
                 metrics.runtime_quantum_seconds):
        leaked = [k for k in inst.sample()
                  if dict(k).get("client", dict(k).get("tenant"))
                  in churned]
        assert not leaked, (inst.name, leaked)
    # the poisoned ids contained every escape-relevant character; the
    # full exposition must still round-trip the text format
    text = metrics.REGISTRY.expose()
    assert "verifyd_clients" in text


# --- batchtune -----------------------------------------------------------


def test_batchtune_race_persists_and_reloads(tmp_path, monkeypatch):
    monkeypatch.setenv(batchtune.ENV_CACHE,
                       str(tmp_path / "tune.json"))
    monkeypatch.delenv(batchtune.ENV_TUNE, raising=False)
    calls = []

    def backend(kind, reqs):
        calls.append((kind, len(reqs)))
        return [True] * len(reqs)

    t1 = batchtune.BatchTuner(backend=backend, platform="cpu")
    raced = t1.ensure_raced(kinds=["membership"])
    assert "membership" in raced and calls
    assert (tmp_path / "tune.json").exists()
    doc = json.loads((tmp_path / "tune.json").read_text())
    assert "v1:cpu:membership" in doc
    # a fresh tuner (new process) loads the rows without re-racing
    calls.clear()
    t2 = batchtune.BatchTuner(backend=backend, platform="cpu")
    assert t2.ensure_raced(kinds=["membership"]) == {}
    assert not calls
    assert t2.rates("membership")
    # a corrupt cache is ignored and re-raced
    (tmp_path / "tune.json").write_text("{broken")
    t3 = batchtune.BatchTuner(backend=backend, platform="cpu")
    assert "membership" in t3.ensure_raced(kinds=["membership"])
    assert calls


def test_batchtune_race_disabled_by_env(tmp_path, monkeypatch):
    monkeypatch.setenv(batchtune.ENV_CACHE, str(tmp_path / "t.json"))
    monkeypatch.setenv(batchtune.ENV_TUNE, "off")
    calls = []
    t = batchtune.BatchTuner(
        backend=lambda k, r: calls.append(k), platform="cpu")
    assert t.ensure_raced() == {}
    assert not calls
    # static default target serves until observations arrive
    assert t.target_batch("sig") == batchtune.STATIC_TARGETS["sig"]


def test_batchtune_model_and_policy(tmp_path, monkeypatch):
    monkeypatch.setenv(batchtune.ENV_CACHE, str(tmp_path / "t.json"))
    monkeypatch.setenv(batchtune.ENV_TUNE, "off")
    clock = _Clock()
    t = batchtune.BatchTuner(platform="cpu", max_batch=256,
                             time_source=clock.now)
    # cold-discard: the FIRST observation per bucket (the compile) is
    # dropped; the second creates the row
    t.observe("sig", 32, 10.0)
    assert not t.rates("sig")
    t.observe("sig", 32, 0.001)
    t.observe("sig", 1, 1.0)   # discarded (first at bucket 1)
    t.observe("sig", 1, 0.01)  # 100/s
    rows = t.rates("sig")
    assert rows[32] == pytest.approx(32000.0)
    assert rows[1] == pytest.approx(100.0)
    assert t.target_batch("sig") == 32
    # interpolated service model
    assert t.service_s("sig", 32) == pytest.approx(0.001)
    assert t.service_s("sig", 64) == pytest.approx(0.002)
    # no arrival estimate -> dispatch now (nothing else is coming)
    assert t.dispatch_now("sig", 4, 0.0)
    # fast arrivals -> waiting for the target pays; lingering is chosen
    for i in range(6):
        t.note_arrival("sig", 1000.0 + i * 0.0001)
    assert t.arrival_rate("sig") > 1000
    assert not t.dispatch_now("sig", 4, 0.0)
    # at/above target -> always dispatch
    assert t.dispatch_now("sig", 32, 0.0)
    # slow arrivals -> waiting costs more than the gain
    t2 = batchtune.BatchTuner(platform="cpu", max_batch=256)
    t2.observe("sig", 32, 10.0)
    t2.observe("sig", 32, 0.001)
    t2.observe("sig", 1, 1.0)
    t2.observe("sig", 1, 0.01)
    t2.note_arrival("sig", 0.0)
    t2.note_arrival("sig", 100.0)  # one item per 100 s
    assert t2.dispatch_now("sig", 4, 0.0)


def test_farm_consumes_tuner_targets(wl, expected):
    """A farm with a tuner dispatches per the tuned policy and feeds
    observations back; verdicts stay bit-identical."""
    t = batchtune.BatchTuner(platform="cpu", max_batch=64)

    async def go():
        svc = _service(wl, tuner=t, max_batch=64)
        try:
            await svc.start()
            svc.register_client("a")
            got = await svc.verify("a", wl.requests)
            assert got == expected
        finally:
            await svc.aclose()

    _run(go())
    assert t.stats["observations"] + t.stats["discarded_cold"] > 0


# --- protocol ------------------------------------------------------------


def test_protocol_roundtrip_every_kind(wl):
    for req in wl.requests:
        doc = protocol.request_to_doc(req)
        back = protocol.request_from_doc(json.loads(json.dumps(doc)))
        assert protocol.request_to_doc(back) == doc
        assert back.kind == req.kind


def test_protocol_malformed_docs():
    with pytest.raises(protocol.ProtocolError, match="kind"):
        protocol.request_from_doc({"kind": "nope"})
    with pytest.raises(protocol.ProtocolError, match="public_key"):
        protocol.request_from_doc({"kind": "sig", "domain": 1,
                                   "public_key": "zz", "msg": "",
                                   "signature": ""})
    with pytest.raises(protocol.ProtocolError, match="challenge"):
        protocol.request_from_doc({"kind": "pow", "challenge": "ab",
                                   "node_id": "00" * 32,
                                   "difficulty": "00" * 32, "nonce": 1})
    with pytest.raises(protocol.ProtocolError, match="nonce"):
        protocol.request_from_doc({"kind": "pow",
                                   "challenge": "00" * 32,
                                   "node_id": "00" * 32,
                                   "difficulty": "00" * 32,
                                   "nonce": "7"})
    # JSON ints are unbounded: an out-of-u64 nonce must be a typed 400
    # at the boundary, not an OverflowError poisoning a co-batched
    # dispatch deep inside the farm
    for bad in (1 << 64, -1):
        with pytest.raises(protocol.ProtocolError, match="64-bit"):
            protocol.request_from_doc({"kind": "pow",
                                       "challenge": "00" * 32,
                                       "node_id": "00" * 32,
                                       "difficulty": "00" * 32,
                                       "nonce": bad})
        with pytest.raises(protocol.ProtocolError, match="64-bit"):
            protocol.request_from_doc({
                "kind": "post", "challenge": "00" * 32,
                "node_id": "00" * 32, "commitment": "00" * 32,
                "scrypt_n": 2, "total_labels": 64,
                "proof": {"nonce": 0, "indices": [1, 2],
                          "pow_nonce": bad, "k2": 2}})
    with pytest.raises(protocol.ProtocolError, match="lane"):
        protocol.parse_lane("express")


# --- the network surface (real sockets) ---------------------------------


def test_server_http_e2e(wl, expected):
    async def go():
        server = VerifydServer(listen="127.0.0.1:0",
                               post_params=wl.post_params,
                               post_seed=wl.post_seed, workers=3)
        server.service.farm.ed_verifier = wl.ed
        server.service.farm.vrf_verifier = wl.vrf
        try:
            port = await server.start()
            base = f"http://127.0.0.1:{port}"
            c = VerifydClient(base, "alice")
            await c.register()
            got = await c.verify(wl.requests)
            assert got == expected
            sess = await c._sess()
            # typed shed over the wire: 429 + structured body
            tiny = VerifydClient(base, "tiny", session=sess,
                                 unregister_on_close=False)
            await tiny.register(rate=0.001, burst=1)
            with pytest.raises(Shed) as ei:
                await tiny.verify(wl.requests)
            assert ei.value.reason == protocol.SHED_RATE
            async with sess.post(base + "/v1/verify", json={
                    "client": "tiny",
                    "items": [protocol.request_to_doc(r)
                              for r in wl.requests]}) as resp:
                assert resp.status == 429
                doc = await resp.json()
                assert doc["status"] == "SHED"
                assert doc["reason"] == protocol.SHED_RATE
                assert doc["retry_after_s"] > 0
            # malformed item -> 400 with a field-qualified message
            async with sess.post(base + "/v1/verify", json={
                    "client": "alice",
                    "items": [{"kind": "martian"}]}) as resp:
                assert resp.status == 400
                assert "kind" in await resp.text()
            # unregistered -> 403 typed
            async with sess.post(base + "/v1/verify", json={
                    "client": "ghost", "items": []}) as resp:
                assert resp.status == 403
                assert (await resp.json())["reason"] == \
                    protocol.SHED_UNREGISTERED
            # observability surface
            async with sess.get(base + "/readyz") as resp:
                assert resp.status == 200
                rep = await resp.json()
                assert rep["ready"] and "verifyd" in rep["components"]
            async with sess.get(base + "/metrics") as resp:
                text = await resp.text()
                assert 'verifyd_items_total' in text
            async with sess.get(base + "/v1/stats") as resp:
                st = await resp.json()
                assert st["clients"] == 2
            async with sess.get(base + "/v1/tune") as resp:
                assert "targets" in await resp.json()
            async with sess.post(base + "/v1/client/unregister",
                                 json={"client": "tiny"}) as resp:
                assert (await resp.json())["unregistered"] is True
            await c.aclose()  # unregisters alice, closes the session
        finally:
            await server.close()

    _run(go())


def test_server_grpc_same_docs(wl, expected):
    pytest.importorskip("grpc")
    from spacemesh_tpu.verifyd.client import grpc_verify

    async def go():
        server = VerifydServer(listen="127.0.0.1:0",
                               grpc_listen="127.0.0.1:0",
                               post_params=wl.post_params,
                               post_seed=wl.post_seed, workers=3)
        server.service.farm.ed_verifier = wl.ed
        server.service.farm.vrf_verifier = wl.vrf
        try:
            await server.start()
            assert server.grpc_port
            server.service.register_client("g")
            got = await grpc_verify(f"127.0.0.1:{server.grpc_port}",
                                    "g", wl.requests[:8])
            assert got == expected[:8]
            server.service.unregister_client("g")
        finally:
            await server.close()

    _run(go())


def test_server_sheds_during_shutdown(wl):
    """Admission during drain is a typed shutting_down, and close is
    idempotent."""

    async def go():
        server = VerifydServer(listen="127.0.0.1:0",
                               post_params=wl.post_params,
                               post_seed=wl.post_seed, workers=2)
        port = await server.start()
        base = f"http://127.0.0.1:{port}"
        c = VerifydClient(base, "a", unregister_on_close=False)
        await c.register()
        await server.service.aclose()  # drain the service first
        with pytest.raises(Shed) as ei:
            await c.verify(wl.requests[:1])
        assert ei.value.reason == protocol.SHED_SHUTTING_DOWN
        await c.aclose()
        await server.close()
        await server.close()  # idempotent

    _run(go())


# --- the pow farm kind ---------------------------------------------------


def test_farm_pow_kind_parity(wl, expected):
    """PowRequests through the farm match inline k2pow.verify exactly
    (valid, walked-to-miss, wrong-prefix, impossible-difficulty)."""
    pow_reqs = [(i, r) for i, r in enumerate(wl.requests)
                if isinstance(r, PowRequest)]
    assert pow_reqs

    async def go():
        svc = _service(wl)
        try:
            await svc.start()
            svc.register_client("a")
            got = await svc.verify("a", [r for _i, r in pow_reqs])
            assert got == [expected[i] for i, _r in pow_reqs]
        finally:
            await svc.aclose()

    _run(go())


# --- the sim scenario ----------------------------------------------------


def test_sim_verifyd_load_replays_byte_identical():
    from spacemesh_tpu.sim import verifyd_load
    from spacemesh_tpu.sim.scenarios import builtin

    script = builtin("verifyd-load", light=2)
    script["waves"] = 4
    script["workload"] = {"sigs": 24, "vrfs": 4, "posts": 2,
                          "memberships": 4, "pows": 6}
    script["asserts"] = [
        {"kind": "no_wrong_verdicts"},
        {"kind": "shed", "client": "heavy", "reason": "rate", "min": 1},
        {"kind": "no_shed", "client": "light-0"},
        {"kind": "sli_present", "name": "verifyd_request_p99"},
    ]
    r1 = verifyd_load.run_scenario(script)
    r2 = verifyd_load.run_scenario(script)
    assert r1.ok, r1.asserts
    assert r2.ok
    assert r1.digest == r2.digest
    assert r1.stats["hub"]["shed"] >= 1
    json.loads(r1.to_json())  # result serializes
