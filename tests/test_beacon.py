"""Beacon protocol: multi-node convergence, adversaries, fallback.

The round-2 "done" criterion (VERDICT item 3): N drivers over the in-proc
hub, one adversarial proposer and one late joiner, all converging on one
protocol-decided beacon; fallback only on explicit timeout with the
reason recorded. Mirrors reference beacon/beacon.go runProposalPhase /
runConsensusPhase + weakcoin.
"""

import asyncio

from spacemesh_tpu.consensus import beacon as beacon_mod
from spacemesh_tpu.consensus.eligibility import Oracle
from spacemesh_tpu.core.signing import EdSigner, EdVerifier
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import misc as miscstore
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo

GEN = b"beacon-test-genesis!"
EPOCH = 2
LPE = 4


def _driver(hub, cache, signer, **kw):
    ps = PubSub(node_name=signer.node_id)
    hub.join(ps)
    db = dbmod.open_state(":memory:")
    drv = beacon_mod.ProtocolDriver(
        db=db, oracle=Oracle(cache, LPE), pubsub=ps, genesis_id=GEN,
        verifier=EdVerifier(prefix=GEN),
        # deadlines are generous for loaded CI machines; the early-complete
        # rule (all active weight voted) keeps the happy path fast anyway
        proposal_duration=kw.pop("proposal_duration", 0.4),
        first_voting_round_duration=0.8, voting_round_duration=0.8,
        rounds_number=2, grace_period=0.3, theta=0.25, **kw)
    return drv, db, ps


def _cache_with(signers, weight=100):
    cache = AtxCache()
    atx_ids = {}
    for i, s in enumerate(signers):
        atx_id = b"ATX%05d" % i + bytes(24)
        atx_ids[s.node_id] = atx_id
        cache.add(EPOCH, atx_id, AtxInfo(
            node_id=s.node_id, weight=weight, base_height=0, height=1,
            num_units=1, vrf_nonce=0, vrf_public_key=s.node_id))
    return cache, atx_ids


def test_three_nodes_converge_one_beacon():
    signers = [EdSigner(prefix=GEN) for _ in range(3)]
    cache, atx_ids = _cache_with(signers)
    hub = LoopbackHub()

    async def go():
        drivers = [_driver(hub, cache, s) for s in signers]
        results = await asyncio.gather(*(
            d.run_epoch(EPOCH, s, s.vrf_signer(), atx_ids[s.node_id])
            for (d, _, _), s in zip(drivers, signers)))
        assert len(set(results)) == 1, "nodes disagree on the beacon"
        for d, db, _ in drivers:
            assert miscstore.beacon_source(db, EPOCH) == \
                miscstore.BEACON_PROTOCOL
        return results[0]

    beacon = asyncio.run(asyncio.wait_for(go(), 30))
    assert len(beacon) == beacon_mod.BEACON_SIZE


def test_adversarial_proposer_and_late_node_still_converge():
    """One adversary (node 0) spams invalid proposals under someone
    else's identity and withholds its votes; one node (node 3) starts
    LATE, missing the whole proposal phase — all honest nodes plus the
    late one still land on a single protocol beacon."""
    signers = [EdSigner(prefix=GEN) for _ in range(4)]
    cache, atx_ids = _cache_with(signers)
    hub = LoopbackHub()

    async def go():
        honest = [_driver(hub, cache, s) for s in signers[1:3]]
        late = _driver(hub, cache, signers[3])
        adv_ps = PubSub(node_name=signers[0].node_id)
        hub.join(adv_ps)

        async def adversary():
            # forged proposal: claims node 1's ATX with node 0's VRF
            forged = beacon_mod.BeaconProposal(
                epoch=EPOCH, atx_id=atx_ids[signers[1].node_id],
                node_id=signers[1].node_id,
                vrf_proof=signers[0].vrf_signer().prove(
                    beacon_mod.proposal_alpha(EPOCH)))
            for _ in range(3):
                await adv_ps.publish(beacon_mod.TOPIC_BEACON_PROPOSAL,
                                     forged.to_bytes())
                await asyncio.sleep(0.05)

        async def late_runner():
            await asyncio.sleep(0.5)  # proposal phase is over
            d, db, _ = late
            return await d.run_epoch(EPOCH, signers[3],
                                     signers[3].vrf_signer(),
                                     atx_ids[signers[3].node_id])

        results = await asyncio.gather(
            *(d.run_epoch(EPOCH, s, s.vrf_signer(), atx_ids[s.node_id])
              for (d, _, _), s in zip(honest, signers[1:3])),
            late_runner(), adversary())
        beacons = results[:3]
        assert len(set(beacons)) == 1, f"divergence: {beacons}"
        # node 1's slot must hold its OWN proposal, not the forged one:
        # the forged VRF proof cannot verify under node 1's key
        legit = beacon_mod.proposal_id(
            signers[1].vrf_signer().prove(beacon_mod.proposal_alpha(EPOCH)))
        forged_pid = beacon_mod.proposal_id(
            signers[0].vrf_signer().prove(beacon_mod.proposal_alpha(EPOCH)))
        for d, _, _ in honest:
            st = d._states.get(EPOCH)
            if st and signers[1].node_id in st.proposals:
                pid, _grade = st.proposals[signers[1].node_id]
                assert pid == legit
                assert pid != forged_pid

    asyncio.run(asyncio.wait_for(go(), 30))


def test_fallback_only_on_timeout_with_reason():
    """No proposals at all (observer with no ATX): the protocol records a
    fallback with an explicit reason instead of silently bootstrapping."""
    signer = EdSigner(prefix=GEN)
    cache = AtxCache()  # empty: nobody is active
    hub = LoopbackHub()
    reasons = []

    async def go():
        drv, db, _ = _driver(hub, cache, signer,
                             on_fallback_used=lambda e, r: reasons.append(r))
        beacon = await drv.run_epoch(EPOCH, signer, signer.vrf_signer(), None)
        assert beacon == drv._bootstrap(EPOCH)
        assert miscstore.beacon_source(db, EPOCH) == \
            miscstore.BEACON_GUESS  # locally derived, still supersedable
        assert reasons and "no proposals" in reasons[0]

    asyncio.run(asyncio.wait_for(go(), 30))


def test_protocol_beacon_not_superseded_fallback_is():
    signer = EdSigner(prefix=GEN)
    cache, atx_ids = _cache_with([signer])
    hub = LoopbackHub()

    async def go():
        drv, db, _ = _driver(hub, cache, signer)
        b1 = await drv.run_epoch(EPOCH, signer, signer.vrf_signer(),
                                 atx_ids[signer.node_id])
        drv.on_fallback(EPOCH, b"\xde\xad\xbe\xef")
        assert miscstore.get_beacon(db, EPOCH) == b1  # protocol is final
        drv.on_fallback(5, b"\x01\x02\x03\x04")
        drv.on_fallback(5, b"\x05\x06\x07\x08")       # fallback supersedes
        assert miscstore.get_beacon(db, 5) == b"\x05\x06\x07\x08"

    asyncio.run(asyncio.wait_for(go(), 30))
