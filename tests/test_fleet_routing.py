"""Consistent-hash fleet placement (ISSUE 17, verifyd/routing.py).

The two contracts everything downstream leans on: placement is a
DETERMINISTIC function of (seed, members, client ids) — pinned across
processes with different PYTHONHASHSEED salts, because a restarted
router that scatters placements scatters every client's admission
state — and membership changes move at most ceil(K/N) clients (the
bounded-load rebalance budget).
"""

import json
import math
import os
import subprocess
import sys

import pytest

from spacemesh_tpu.verifyd.routing import HashRing, Placement, ring_hash

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ROUTING = os.path.join(REPO, "spacemesh_tpu", "verifyd", "routing.py")

# loads routing.py standalone (stdlib-only module) so the subprocess
# proves hash stability without paying the package import
_SCRIPT = """
import importlib.util, json, sys
spec = importlib.util.spec_from_file_location("routing", sys.argv[1])
routing = importlib.util.module_from_spec(spec)
spec.loader.exec_module(routing)
p = routing.Placement(seed=42)
for r in ("r0", "r1", "r2"):
    p.add_replica(r)
for i in range(60):
    p.place(f"c{i:03d}")
print(json.dumps(p.assign, sort_keys=True))
"""


def _placement_in_subprocess(hashseed: str) -> dict:
    env = dict(os.environ, PYTHONHASHSEED=hashseed)
    out = subprocess.run(
        [sys.executable, "-c", _SCRIPT, ROUTING], env=env,
        capture_output=True, text=True, check=True)
    return json.loads(out.stdout)


def _placed(seed=42, replicas=("r0", "r1", "r2"), clients=60):
    p = Placement(seed=seed)
    for r in replicas:
        p.add_replica(r)
    for i in range(clients):
        p.place(f"c{i:03d}")
    return p


def test_cross_process_placement_is_identical():
    """Same seed + members + ids => same table, whatever the process
    hash salt (builtin hash() would silently break this)."""
    local = _placed().assign
    assert _placement_in_subprocess("1") == local
    assert _placement_in_subprocess("31337") == local


def test_ring_hash_never_uses_builtin_hash():
    # pinned value: any accidental switch to a salted hash shows up as
    # a different constant in SOME process
    assert ring_hash(42, "key", "c000") == ring_hash(42, "key", "c000")
    assert ring_hash(42, "key", "c000") != ring_hash(43, "key", "c000")
    assert ring_hash(0, "a", 1) != ring_hash(0, "a", 2)


def test_ring_order_is_insertion_order_independent():
    a = HashRing(["r0", "r1", "r2"], seed=7)
    b = HashRing(["r2", "r0", "r1"], seed=7)
    for key in ("alice", "bob", "c042"):
        assert list(a.walk(key)) == list(b.walk(key))
    # walk yields every member exactly once
    chain = list(a.walk("alice"))
    assert sorted(chain) == a.members() and len(chain) == 3


def test_empty_ring_raises():
    with pytest.raises(LookupError):
        HashRing(seed=1).owner("x")
    with pytest.raises(LookupError):
        Placement(seed=1).place("x")


def test_bounded_load_capacity_respected_throughout():
    p = Placement(seed=3)
    for r in ("r0", "r1", "r2"):
        p.add_replica(r)
    for i in range(90):
        p.place(f"c{i:03d}")
        k, n = len(p.assign), 3
        cap = math.ceil(k / n)
        assert max(p.loads.values()) <= cap
    assert sum(p.loads.values()) == 90


def test_add_replica_moves_at_most_ceil_k_over_n():
    p = _placed(clients=100)
    before = dict(p.assign)
    moved = p.add_replica("r3")
    assert len(moved) <= math.ceil(100 / 4)
    for cid, old, new in moved:
        assert new == "r3" and before[cid] == old != "r3"
        assert p.assign[cid] == "r3"
    # everyone else stayed put (sticky), and the books balance
    untouched = set(before) - {m[0] for m in moved}
    assert all(p.assign[c] == before[c] for c in untouched)
    assert sum(p.loads.values()) == 100
    # sticky add: survivors keep at most their PRE-add bounded load
    # (shrinking them further would blow the ceil(K/N) move budget)
    assert max(p.loads.values()) <= math.ceil(100 / 3)


def test_remove_replica_moves_only_its_clients():
    p = _placed(clients=100)
    before = dict(p.assign)
    victims = {c for c, r in before.items() if r == "r1"}
    moved = p.remove_replica("r1")
    assert {m[0] for m in moved} == victims
    assert len(moved) <= math.ceil(100 / 3) + 1  # ≤ one replica's cap
    for cid, old, new in moved:
        assert old == "r1" and new in ("r0", "r2")
    untouched = set(before) - victims
    assert all(p.assign[c] == before[c] for c in untouched)
    assert "r1" not in p.loads and sum(p.loads.values()) == 100


def test_membership_change_replay_converges():
    """Two placements replaying the same membership history agree —
    the restarted-router contract, add/remove included."""
    def build():
        p = Placement(seed=9)
        for r in ("r0", "r1"):
            p.add_replica(r)
        for i in range(40):
            p.place(f"c{i:03d}")
        p.add_replica("r2")
        p.remove_replica("r0")
        return p
    assert build().assign == build().assign


def test_reroute_avoids_and_forget_releases():
    p = _placed(clients=12)
    cid = "c003"
    old = p.assign[cid]
    new = p.reroute(cid, old)
    assert new is not None and new != old
    assert p.assign[cid] == new
    assert sum(p.loads.values()) == 12
    assert p.forget(cid) == new
    assert cid not in p.assign and sum(p.loads.values()) == 11
    # single-replica fleet: nowhere else to go
    solo = Placement(seed=1)
    solo.add_replica("only")
    solo.place("x")
    assert solo.reroute("x", "only") is None
