"""BASELINE config 5 shape: 16 smeshers in ONE node, 16 ATXs per epoch.

VERDICT round-2 item 6 "done" criterion. Tiny POST geometry stands in for
4 SU each (the kernels' per-lane commitment batching is exercised by
tests/test_parallel.py on the virtual 8-device mesh; this test proves the
NODE hosts 16 identities end to end: 16 inits, one shared poet round per
epoch, 16 proofs, 16 valid ATXs, all signers participating in hare).
"""

import asyncio

import pytest

from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.utils.vclock import VirtualClockLoop, cancel_all_tasks

LPE = 3
LAYER_SEC = 2.0  # virtual seconds (VirtualClockLoop)
N_IDS = 16


@pytest.fixture(scope="module")
def ran(tmp_path_factory):
    tmp_path = tmp_path_factory.mktemp("sixteen")
    cfg = load("standalone", overrides={
        "data_dir": str(tmp_path / "node"),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": 0.0},  # replaced with virtual time below
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": 1, "init_batch": 256,
                     "num_identities": N_IDS},
        "hare": {"committee_size": 32, "round_duration": 0.2,
                 "preround_delay": 0.5, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.2},
        "tortoise": {"hdist": 4, "window_size": 50},
    })
    loop = VirtualClockLoop()
    app = App(cfg, time_source=loop.time)

    async def go():
        await app.prepare()   # 16 inits + 16 initial proofs (epoch 0)
        app.clock = clock_mod.LayerClock(loop.time() + 1.0,
                                         cfg.layer_duration,
                                         time_source=loop.time)
        await asyncio.wait_for(app.run(until_layer=2 * LPE), 10_000)

    try:
        loop.run_until_complete(go())
        yield app
    finally:
        loop.run_until_complete(cancel_all_tasks())
        app.close()


def test_sixteen_atxs_per_epoch(ran):
    for epoch in (0, 1):
        published = [s for s in ran.signers
                     if atxstore.by_node_in_epoch(ran.state, s.node_id,
                                                  epoch) is not None]
        assert len(published) == N_IDS, (
            f"epoch {epoch}: only {len(published)}/{N_IDS} ATXs")


def test_all_identities_in_cache_with_weight(ran):
    for s in ran.signers:
        view = atxstore.by_node_in_epoch(ran.state, s.node_id, 0)
        info = ran.cache.get(1, view.id)
        assert info is not None and info.weight > 0
        assert info.vrf_public_key == s.node_id


def test_consensus_survived_sixteen_way_weight_split(ran):
    assert layerstore.last_applied(ran.state) >= LPE + 1
