"""Operator tools (bootstrapper, merge-nodes) + codec fuzzing.

Codec fuzz mirrors the reference's gofuzz seeds over SCALE codecs: random
and mutated bytes must raise DecodeError/ValueError, never crash, and
every wire type must round-trip exactly.
"""

import json
import random

from spacemesh_tpu.core import codec
from spacemesh_tpu.core.hashing import sum256
from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.tools import bootstrapper, merge_nodes


def test_bootstrapper_generates_epoch_doc(tmp_path):
    from spacemesh_tpu.storage import misc as miscstore

    db = dbmod.open_state(str(tmp_path / "state.db"))
    miscstore.set_beacon(db, 7, b"\xaa\xbb\xcc\xdd")
    db.close()

    out = tmp_path / "fallback.json"
    rc = bootstrapper.main(["--state", str(tmp_path / "state.db"),
                            "--epoch", "7", "--beacon", "--activeset",
                            "--out", str(out)])
    assert rc == 0
    docs = json.loads(out.read_text())
    assert docs[0]["epoch"] == 7
    assert docs[0]["beacon"] == "aabbccdd"  # stored beacon wins
    # the doc feeds straight into the updater
    from spacemesh_tpu.node.bootstrap import BootstrapUpdater

    got = []
    upd = BootstrapUpdater(str(out), on_beacon=lambda e, b: got.append((e, b)))
    assert upd.poll_once() == 1
    assert got == [(7, b"\xaa\xbb\xcc\xdd")]


def test_merge_nodes_moves_identities(tmp_path):
    a, b = tmp_path / "a", tmp_path / "b"
    for d, n in ((a, 2), (b, 1)):
        (d / "identities").mkdir(parents=True)
        for i in range(n):
            name = "local.key" if i == 0 else f"local_{i:02d}.key"
            (d / "identities" / name).write_text(
                EdSigner().private_bytes().hex())
        (d / "post" / f"id{d.name}").mkdir(parents=True)
        (d / "post" / f"id{d.name}" / "postdata_metadata.json").write_text("{}")

    result = merge_nodes.merge(a, b)
    assert result["total_identities"] == 3
    assert len(result["keys_merged"]) == 2
    assert result["post_dirs_merged"] == ["ida"]
    # MOVE semantics: the source must not retain usable keys/data (two
    # nodes smeshing one identity would self-equivocate) — the source
    # keys are renamed away and the post dirs moved
    assert list((a / "identities").glob("*.key")) == []
    assert len(list((a / "identities").glob("*.key.merged"))) == 2
    assert not (a / "post" / "ida").exists()
    # and existing target keys are never overwritten
    assert (b / "identities" / "local.key").exists()


def _wire_samples():
    """One valid instance per registered wire type (encode side)."""
    from spacemesh_tpu.consensus.beacon import (
        BeaconProposal, FirstVotes, FollowVotes, WeakCoinMsg)
    from spacemesh_tpu.consensus.hare import CompactHareMessage, HareMessage
    from spacemesh_tpu.core.types import (
        ActivationTxV2, MarriageCert, MerkleProof, NIPost, Post,
        PostMetadataWire, SubPostV2)

    h = sum256(b"fuzz")
    nipost = NIPost(membership=MerkleProof(leaf_index=1, nodes=[h]),
                    post=Post(nonce=3, indices=[1, 5, 9], pow_nonce=7),
                    post_metadata=PostMetadataWire(challenge=h,
                                                   labels_per_unit=64))
    return [
        HareMessage(layer=4, iteration=0, round=2, values=[h],
                    eligibility_proof=bytes(80), eligibility_count=2,
                    atx_id=h, node_id=h, cert_msgs=[b"x"],
                    signature=bytes(64)),
        CompactHareMessage(layer=4, iteration=1, round=3,
                           compact_ids=[h[:4]], root=h,
                           eligibility_proof=bytes(80),
                           eligibility_count=2, atx_id=h, node_id=h,
                           cert_msgs=[], signature=bytes(64)),
        BeaconProposal(epoch=2, atx_id=h, node_id=h, vrf_proof=bytes(80)),
        FirstVotes(epoch=2, valid=[h], late=[], atx_id=h, node_id=h,
                   signature=bytes(64)),
        FollowVotes(epoch=2, round=1, votes_for=[h], atx_id=h, node_id=h,
                    signature=bytes(64)),
        WeakCoinMsg(epoch=2, round=1, atx_id=h, node_id=h,
                    vrf_proof=bytes(80)),
        ActivationTxV2(publish_epoch=1, pos_atx=h, coinbase=bytes(24),
                       marriages=[MarriageCert(partner_id=h,
                                               signature=bytes(64))],
                       subposts=[SubPostV2(node_id=h, prev_atx=h,
                                           num_units=1, vrf_nonce=9,
                                           nipost=nipost)],
                       node_id=h, signature=bytes(64)),
    ]


def test_wire_roundtrips():
    for sample in _wire_samples():
        cls = type(sample)
        assert cls.from_bytes(sample.to_bytes()) == sample, cls.__name__


def test_fuzz_decoders_never_crash():
    """Random + truncated + bit-flipped inputs: DecodeError/ValueError
    only — a malformed gossip blob must never take the node down."""
    rng = random.Random(1234)
    samples = _wire_samples()
    classes = [type(s) for s in samples]
    blobs = [s.to_bytes() for s in samples]
    trials = 0
    for _ in range(300):
        kind = rng.randrange(3)
        if kind == 0:       # pure noise
            data = bytes(rng.getrandbits(8) for _ in range(rng.randrange(200)))
        elif kind == 1:     # truncation of a valid blob
            base = rng.choice(blobs)
            data = base[:rng.randrange(len(base))]
        else:               # bit flip in a valid blob
            base = bytearray(rng.choice(blobs))
            base[rng.randrange(len(base))] ^= 1 << rng.randrange(8)
            data = bytes(base)
        for cls in classes:
            trials += 1
            try:
                cls.from_bytes(data)
            except (codec.DecodeError, ValueError):
                pass  # the ONLY acceptable failures (OverflowError was
                #       tolerated here until codec._read learned to
                #       reject implausible lengths itself)
    assert trials > 1000
