"""The pprof-analogue debug surface (api/http.py /debug/*), the full
/metrics exposition (parsed line by line — this is the test that catches
label-escaping corruption), the span-trace capture endpoints, labeled
histograms, and the event-bus overflow instruments."""

import asyncio
import math
import re
from types import SimpleNamespace

import pytest
from aiohttp import ClientSession

from spacemesh_tpu.api.http import ApiServer
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.utils import metrics as metrics_mod
from spacemesh_tpu.utils import tracing


# --- a strict Prometheus text-format parser ---------------------------

_LINE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})? (-?(?:[0-9.eE+-]+|inf|nan))$')


def _parse_labels(s: str) -> dict:
    """Parse a label block honoring the exposition-format escapes
    (\\\\, \\", \\n). Raises on anything malformed — an unescaped quote
    or newline in a label value fails this parser the way it fails a
    real Prometheus scrape."""
    out = {}
    i = 0
    while i < len(s):
        eq = s.index("=", i)
        name = s[i:eq]
        if not re.fullmatch(r"[a-zA-Z_][a-zA-Z0-9_]*", name):
            raise ValueError(f"bad label name {name!r}")
        if s[eq + 1] != '"':
            raise ValueError("label value not quoted")
        k = eq + 2
        val = []
        while s[k] != '"':
            if s[k] == "\\":
                val.append({"\\": "\\", '"': '"', "n": "\n"}[s[k + 1]])
                k += 2
            else:
                val.append(s[k])
                k += 1
        out[name] = "".join(val)
        i = k + 1
        if i < len(s):
            if s[i] != ",":
                raise ValueError(f"junk after label value: {s[i:]!r}")
            i += 1
    return out


def parse_exposition(text: str) -> list[tuple[str, dict, float]]:
    """Parse a full exposition; every non-comment line must be a valid
    sample or the whole scrape is considered corrupt."""
    samples = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _LINE.match(line)
        if m is None:
            raise ValueError(f"unparseable sample line: {line!r}")
        name, labels, value = m.groups()
        samples.append((name, _parse_labels(labels) if labels else {},
                        float(value)))
    return samples


# --- unit: escaping + labeled histograms ------------------------------

EVIL = 'say "hi"\nback\\slash'


def test_label_escaping_counter_gauge_histogram():
    reg = metrics_mod.Registry()
    reg.counter("c").inc(peer=EVIL)
    reg.gauge("g").set(2.0, reason=EVIL)
    reg.histogram("h", buckets=(1.0, float("inf"))).observe(0.5, kind=EVIL)
    samples = parse_exposition(reg.expose())
    by_name = {}
    for name, labels, value in samples:
        by_name.setdefault(name, []).append((labels, value))
    assert by_name["c"][0][0]["peer"] == EVIL
    assert by_name["g"][0][0]["reason"] == EVIL
    for labels, _ in by_name["h_bucket"]:
        assert labels["kind"] == EVIL
    assert by_name["h_count"][0] == ({"kind": EVIL}, 1.0)


def test_histogram_per_labelset_series():
    reg = metrics_mod.Registry()
    h = reg.histogram("lat", buckets=(0.01, 1.0, float("inf")))
    h.observe(0.005, kind="sig")
    h.observe(0.5, kind="sig")
    h.observe(100.0, kind="post")
    h.observe(0.002)  # label-free series coexists
    samples = parse_exposition(reg.expose())
    sig_buckets = {lbl["le"]: v for n, lbl, v in samples
                   if n == "lat_bucket" and lbl.get("kind") == "sig"}
    post_buckets = {lbl["le"]: v for n, lbl, v in samples
                    if n == "lat_bucket" and lbl.get("kind") == "post"}
    bare = {lbl.get("le"): v for n, lbl, v in samples
            if n == "lat_bucket" and "kind" not in lbl}
    assert sig_buckets == {"0.01": 1.0, "1.0": 2.0, "+Inf": 2.0}
    assert post_buckets == {"0.01": 0.0, "1.0": 0.0, "+Inf": 1.0}
    assert bare == {"0.01": 1.0, "1.0": 1.0, "+Inf": 1.0}
    counts = {tuple(sorted(lbl.items())): v for n, lbl, v in samples
              if n == "lat_count"}
    assert counts[(("kind", "sig"),)] == 2.0
    assert counts[(("kind", "post"),)] == 1.0
    assert counts[()] == 1.0
    sums = {tuple(sorted(lbl.items())): v for n, lbl, v in samples
            if n == "lat_sum"}
    assert math.isclose(sums[(("kind", "sig"),)], 0.505)


def test_farm_dispatch_histogram_carries_kind():
    """The migrated instrument: batch timings split per request kind
    instead of blending signatures and POST proofs."""
    metrics_mod.verify_farm_dispatch_seconds.observe(0.003, kind="sig")
    metrics_mod.verify_farm_dispatch_seconds.observe(1.5, kind="post")
    text = "\n".join(metrics_mod.verify_farm_dispatch_seconds.expose())
    samples = parse_exposition(text)
    kinds = {lbl.get("kind") for _, lbl, _ in samples}
    assert {"sig", "post"} <= kinds


def test_event_bus_overflow_metrics():
    import gc

    from spacemesh_tpu.node import events as events_mod

    async def run():
        gc.collect()  # drop dead buses from earlier tests (WeakSet)
        bus = events_mod.EventBus()
        sub = bus.subscribe(events_mod.LayerUpdate, size=2)
        before = dict(metrics_mod.events_overflows._values)
        for i in range(5):
            bus.emit(events_mod.LayerUpdate(layer=i, status="tick"))
        assert sub.overflowed
        key = (("type", "LayerUpdate"),)
        dropped = (metrics_mod.events_overflows._values.get(key, 0)
                   - before.get(key, 0))
        assert dropped == 3
        # the depth gauge is recomputed at SCRAPE time (registry
        # collector hook), not written on emit: a drained queue must
        # read 0 on the next scrape instead of pinning the high-water
        # mark of the last emission forever
        metrics_mod.REGISTRY.run_collectors()
        assert metrics_mod.events_queue_depth._values.get(()) == 2
        while not sub.queue.empty():
            sub.queue.get_nowait()
        metrics_mod.REGISTRY.run_collectors()
        assert metrics_mod.events_queue_depth._values.get(()) == 0
        # emit + close the deepest subscriber: scrape recomputes, never
        # resurrects the closed queue's depth
        bus.emit(events_mod.LayerUpdate(layer=9, status="tick"))
        metrics_mod.REGISTRY.run_collectors()
        assert metrics_mod.events_queue_depth._values.get(()) == 1
        sub.close()
        metrics_mod.REGISTRY.run_collectors()
        assert metrics_mod.events_queue_depth._values.get(()) == 0

    asyncio.run(run())


# --- the live HTTP surface --------------------------------------------


@pytest.fixture()
def stub_api(tmp_path):
    """An ApiServer over a stub node: enough attributes for /metrics,
    and the /debug endpoints need none at all — so this fixture stays
    orders of magnitude lighter than a full App."""
    state = dbmod.open_state(tmp_path / "state.db")
    node = SimpleNamespace(
        clock=SimpleNamespace(current_layer=lambda: 7),
        tortoise=SimpleNamespace(verified=3, mode=0),
        state=state, server=None, syncer=None)
    api = ApiServer(node, listen="127.0.0.1:0")
    yield api
    state.close()


def _with_server(api, coro):
    async def run():
        port = await api.start()
        base = f"http://127.0.0.1:{port}"
        try:
            async with ClientSession() as s:
                return await coro(s, base)
        finally:
            await api.stop()

    return asyncio.run(run())


def test_debug_stacks_and_profile(stub_api):
    async def go(s, base):
        stacks = await (await s.get(f"{base}/debug/stacks")).text()
        prof_r = await s.get(f"{base}/debug/profile?seconds=0.1")
        prof = await prof_r.text()
        bad = (await s.get(f"{base}/debug/profile?seconds=abc")).status
        return stacks, prof_r.status, prof, bad

    stacks, prof_status, prof, bad = _with_server(stub_api, go)
    assert "--- thread" in stacks and "asyncio tasks" in stacks
    # the dump names at least this test's own frames
    assert "test_http_debug" in stacks or "pytest" in stacks
    assert prof_status == 200
    assert "cumulative" in prof and "function calls" in prof
    assert bad == 400


def test_metrics_full_exposition_parses(stub_api):
    # poison the registry with exactly the values that used to corrupt
    # the scrape: quotes, newlines and backslashes in label values
    metrics_mod.pubsub_handler_drops.inc(topic=EVIL)
    metrics_mod.verify_farm_dispatch_seconds.observe(0.01, kind="sig")

    async def go(s, base):
        r = await s.get(f"{base}/metrics")
        return r.status, await r.text()

    status, text = _with_server(stub_api, go)
    assert status == 200
    samples = parse_exposition(text)  # raises on any corrupt line
    names = {n for n, _, _ in samples}
    assert "node_current_layer" in names
    assert "verify_farm_dispatch_seconds_bucket" in names
    evil = [lbl for n, lbl, _ in samples
            if n == "pubsub_handler_drops_total" and lbl.get("topic") == EVIL]
    assert evil, "escaped label value did not round-trip the scrape"


def test_trace_capture_endpoints(stub_api):
    tracing.stop()

    async def go(s, base):
        started = await (await s.post(
            f"{base}/debug/trace/start?capacity=512")).json()
        assert started["enabled"] and started["capacity"] == 512
        with tracing.span("api.test_span", {"k": 1}):
            pass
        doc = await (await s.get(f"{base}/debug/trace/export")).json()
        stopped = await (await s.post(f"{base}/debug/trace/stop")).json()
        bad = (await s.get(
            f"{base}/debug/trace/start?capacity=zap")).status
        return doc, stopped, bad

    try:
        doc, stopped, bad = _with_server(stub_api, go)
    finally:
        tracing.stop()
    tracing.validate(doc)
    assert any(e["name"] == "api.test_span"
               for e in doc["traceEvents"])
    assert stopped["enabled"] is False
    assert stopped["spans_recorded"] >= 1
    assert bad == 400
    assert not tracing.is_enabled()


def test_admin_chaos_link_validates_bodies(stub_api):
    """POST /v1/admin/chaos/link: a real Host gains a link policy; a
    non-object JSON body (valid JSON, wrong shape) is a 400, never an
    unhandled 500; no host at all is a 409."""
    class FakeHost:
        def __init__(self):
            self.calls = []

        def chaos_link(self, **kw):
            self.calls.append(kw)

    async def go(s, base):
        r409 = await s.post(f"{base}/v1/admin/chaos/link",
                            json={"loss": 0.5})
        assert r409.status == 409, r409.status  # no transport host yet
        stub_api.node.host = FakeHost()
        r = await s.post(f"{base}/v1/admin/chaos/link",
                         json={"loss": 0.25, "delay": 0.1, "seed": 3})
        assert r.status == 200, await r.text()
        for bad in ("[1, 2]", "null", '"str"', "3"):
            rb = await s.post(f"{base}/v1/admin/chaos/link", data=bad,
                              headers={"Content-Type": "application/json"})
            assert rb.status == 400, (bad, rb.status)
        return stub_api.node.host.calls

    calls = _with_server(stub_api, go)
    assert calls == [{"loss": 0.25, "delay": 0.1, "jitter": 0.0,
                      "dup": 0.0, "seed": 3}]


def test_debug_remediation_and_readyz_breakers(stub_api):
    """/debug/remediation serves breaker states + action history +
    budgets; /readyz carries breaker states while any are registered
    (ISSUE 15)."""
    from spacemesh_tpu.obs import remediate

    clock = [0.0]
    br = remediate.CircuitBreaker("http-test", failure_budget=1,
                                  time_source=lambda: clock[0])
    eng = remediate.RemediationEngine(
        time_source=lambda: clock[0],
        policy=[remediate.RecoveryRule(component="http-test",
                                       action="restart_component",
                                       cooldown_s=0.0)])
    stub_api.node.remediation = eng
    remediate.BREAKERS.register(br)
    br.record_failure()
    eng.handle_component("http-test", "stalled")

    async def go(s, base):
        doc = await (await s.get(f"{base}/debug/remediation")).json()
        ready = await (await s.get(f"{base}/readyz")).json()
        return doc, ready

    try:
        doc, ready = _with_server(stub_api, go)
    finally:
        remediate.BREAKERS.unregister(br)
    assert doc["breakers"]["http-test"]["state"] == "open"
    assert doc["breakers"]["http-test"]["failure_budget"] == 1
    acts = [a for a in doc["actions"]
            if a["component"] == "http-test"]
    assert acts and acts[-1]["action"] == "restart_component"
    assert doc["budgets"]["http-test"]["used"] == 1
    # an open breaker is visible on readiness but is NOT unreadiness:
    # the fallback is carrying the load
    assert ready["breakers"]["http-test"] == "open"
    assert ready["ready"] is True
