"""The GSPMD data plane (ISSUE 16): one process-wide topology, persistent
layout catalog, mesh-sharded pack/verify twins bit-identical to the
single-device paths, and mesh-shape autotune winners that persist."""

import hashlib
import json

import jax
import numpy as np
import pytest

from spacemesh_tpu.ops import autotune, scrypt
from spacemesh_tpu.parallel import data_mesh, topology
from spacemesh_tpu.parallel import mesh as pmesh

N = 4


@pytest.fixture
def tuner(tmp_path, monkeypatch):
    """Fresh autotune world: private winners file, no overrides, no
    memoized decisions (racing stays OFF via conftest)."""
    path = tmp_path / "romix_autotune.json"
    monkeypatch.setenv(autotune.ENV_CACHE, str(path))
    monkeypatch.delenv(autotune.ENV_IMPL, raising=False)
    monkeypatch.delenv(autotune.ENV_CHUNK, raising=False)
    monkeypatch.delenv(autotune.ENV_MESH, raising=False)
    autotune.reset_memo()
    yield path
    autotune.reset_memo()


def _seed_mesh_winner(path, n, batch, devices, impl="xla"):
    key = autotune._key("cpu", n, scrypt.shape_bucket(batch),
                        autotune._device_cap(None))
    doc = json.loads(path.read_text()) if path.exists() else {}
    doc[key] = {"impl": impl, "chunk": None, "devices": devices,
                "labels_per_sec": 9999.0}
    path.write_text(json.dumps(doc))
    autotune.reset_memo()


# --- the topology singleton + persistent catalog --------------------------


def test_one_mesh_object_per_process():
    """Every entry point consumes the SAME Mesh/NamedSharding objects —
    the acceptance criterion that makes jit executable reuse structural
    rather than accidental."""
    t = topology.get()
    assert t is topology.get()
    lay = t.layouts()
    assert lay is t.layouts()
    assert lay.mesh is data_mesh()
    assert lay.mesh.shape == {"data": 8, "model": 1}
    # submesh catalogs are cached per count, prefix selections included
    sub = t.layouts(4)
    assert sub is t.layouts_for_devices(jax.devices()[:4])
    assert sub.mesh is data_mesh(jax.devices()[:4])
    # the sharding objects themselves are persistent (not per-call)
    assert lay.batch is t.layouts().batch
    assert lay.lane is t.layouts().lane
    assert pmesh.lane_sharding(lay.mesh) is lay.lane


def test_layouts_for_foreign_mesh_resolves_by_devices():
    lay = topology.get().layouts(2)
    resolved = topology.get().layouts_for(lay.mesh)
    assert resolved is lay


def test_replicate_is_noop_for_resident_carry():
    """The satellite fix: a carry already replicated on the mesh is
    returned as-is (same object), so donated carries stay resident
    across a pass instead of paying a device_put per batch."""
    lay = topology.get().layouts()
    carry = scrypt.vrf_carry_init()
    placed = lay.replicate(carry)
    assert lay.replicate(placed) is placed
    # and via the mesh.py entry point wrapper too
    assert pmesh.replicate(lay.mesh, placed) is placed


# --- sharded packed multi-tenant init: ragged totals ----------------------


@pytest.mark.parametrize("totals", [(1,), (7,), (7, 1039)],
                         ids=["1", "7", "7+1039"])
def test_packed_init_sharded_bit_identity(tuner, tmp_path, totals):
    """The TenantScheduler's pack dispatch routed over a 4-device mesh
    produces byte-identical label files and VRF nonces to the host
    reference at ragged totals (host pre-bucket pad + segment slicing)."""
    from spacemesh_tpu.post.data import LabelStore
    from spacemesh_tpu.runtime import TenantScheduler

    pack = 256
    _seed_mesh_winner(tuner, N, pack, devices=4)
    ids = [(f"t{i}", hashlib.sha256(b"tnode%d" % i).digest(),
            hashlib.sha256(b"tcommit%d" % i).digest(), total)
           for i, total in enumerate(totals)]
    with TenantScheduler(workers=2, pack_lanes=pack) as sched:
        handles = []
        for tid, node, commit, total in ids:
            sched.register_tenant(tid)
            handles.append((tid, commit, total, sched.submit_init(
                tid, tmp_path / tid, node_id=node, commitment=commit,
                num_units=1, labels_per_unit=total, scrypt_n=N,
                max_file_size=1 << 20)))
        for tid, commit, total, h in handles:
            meta = h.result(timeout=600)
            store = LabelStore(tmp_path / tid, meta)
            got = np.frombuffer(store.read_labels(0, total),
                                dtype=np.uint8).reshape(-1, 16)
            store.close()
            want = scrypt.scrypt_labels(
                commit, np.arange(total, dtype=np.uint64), n=N)
            assert np.array_equal(got, want), f"{tid} labels diverged"
            lo = want[:, :8].copy().view("<u8").ravel()
            hi = want[:, 8:].copy().view("<u8").ravel()
            assert meta.vrf_nonce == int(np.lexsort((lo, hi))[0]), tid
    # the routing the packer consulted really was the sharded one
    devs, _ = autotune.resolve_auto_mesh(N, scrypt.shape_bucket(pack))
    assert devs is not None and len(devs) == 4


def test_packed_init_steady_state_zero_new_compiles(tuner, tmp_path):
    """A warm process dispatches sharded packs with ZERO new compiles:
    after the first pack at a bucket, compiled_shape_count() stays flat
    for every later pack at that bucket (acceptance criterion)."""
    from spacemesh_tpu.runtime import TenantScheduler

    pack = 128
    _seed_mesh_winner(tuner, N, pack, devices=4)

    def run(tag, totals):
        with TenantScheduler(workers=2, pack_lanes=pack) as sched:
            hs = []
            for i, total in enumerate(totals):
                tid = f"{tag}{i}"
                sched.register_tenant(tid)
                hs.append(sched.submit_init(
                    tid, tmp_path / tid, node_id=hashlib.sha256(
                        b"zn%d" % i).digest(),
                    commitment=hashlib.sha256(b"zc%d" % i).digest(),
                    num_units=1, labels_per_unit=total, scrypt_n=N,
                    max_file_size=1 << 20))
            for h in hs:
                h.result(timeout=600)

    run("warm", (64, 64))           # compile the (n, bucket) executables
    warm = scrypt.compiled_shape_count()
    run("steady", (33, 95, 128))    # ragged lanes, same pack bucket
    assert scrypt.compiled_shape_count() == warm, \
        "steady-state sharded dispatch minted a new executable"


# --- sharded farm verify: ragged flat batches -----------------------------


@pytest.mark.parametrize("count", [1, 7, 1039])
def test_farm_verify_sharded_matches_single_device(tuner, count):
    """verify_many over a mesh-routed batch returns the same verdicts as
    the single-device pass at ragged spot-check totals."""
    from spacemesh_tpu.post import verifier
    from spacemesh_tpu.post.prover import Proof, ProofParams

    total_labels = 64
    p = ProofParams(k1=8, k2=1, k3=1, pow_difficulty=bytes([255] * 32))
    items = []
    for i in range(count):
        items.append(verifier.VerifyItem(
            Proof(nonce=0, indices=[i % total_labels], pow_nonce=0, k2=1),
            hashlib.sha256(b"vch%d" % i).digest(),
            hashlib.sha256(b"vnode%d" % i).digest(),
            hashlib.sha256(b"vcommit%d" % i).digest(),
            N, total_labels))
    seed = b"topology-seed".ljust(32, b"\0")

    autotune.reset_memo()
    single = verifier.verify_many(items, p, seed)
    _seed_mesh_winner(tuner, N, scrypt.shape_bucket(count), devices=4)
    sharded = verifier.verify_many(items, p, seed)
    assert sharded == single
    devs, _ = autotune.resolve_auto_mesh(N, scrypt.shape_bucket(count))
    if scrypt.shape_bucket(count) % 4 == 0:
        assert devs is not None and len(devs) == 4


# --- mesh-shape autotune winners ------------------------------------------


def _fake_rows(platform, n, combos):
    """Synthetic race: V-sharded (xla-rows) wins at 4 devices, the best
    lane-sharded row is xla at 2; single-device rows stay slow."""
    rates = {("xla-rows", 4): 4000.0, ("xla-rows", 2): 2500.0,
             ("xla", 2): 3000.0, ("xla", 4): 2900.0, ("xla", 8): 2800.0,
             ("xla-rows", 8): 2600.0}
    return [{"impl": impl, "chunk": chunk, "devices": d,
             "shape": autotune.shape_of(impl),
             "labels_per_sec": rates.get((impl, d), 100.0)}
            for impl, chunk, d in combos]


def test_mesh_shape_winner_persist_and_reread(tuner, monkeypatch):
    """race() persists a winner PER mesh shape; shape_winner() re-reads
    both from disk in a fresh memo world (the round-trip criterion)."""
    monkeypatch.setenv(autotune.ENV_AUTOTUNE, "on")
    monkeypatch.setattr(autotune, "_race_rows", _fake_rows)
    d = autotune.decide(N, 512, platform="cpu", max_devices=None)
    assert (d.impl, d.devices, d.mesh_shape) == ("xla-rows", 4, "vshard")

    # fresh process: memos dropped, everything comes off the disk file
    autotune.reset_memo()
    monkeypatch.setenv(autotune.ENV_AUTOTUNE, "off")
    lane = autotune.shape_winner(N, 512, "lane", platform="cpu",
                                 max_devices=None)
    vshard = autotune.shape_winner(N, 512, "vshard", platform="cpu",
                                   max_devices=None)
    assert (lane.impl, lane.devices, lane.mesh_shape) == ("xla", 2, "lane")
    assert (vshard.impl, vshard.devices, vshard.mesh_shape) \
        == ("xla-rows", 4, "vshard")
    # and the overall cached winner still resolves (source=cache)
    d2 = autotune.decide(N, 512, platform="cpu", max_devices=None)
    assert (d2.impl, d2.devices, d2.source) == ("xla-rows", 4, "cache")
    assert d2.mesh_shape == "vshard"


def test_legacy_winner_entries_default_their_shape(tuner):
    """Pre-shape winners files (written before ISSUE 16) resolve with
    the shape implied by their impl — no re-race, no schema bump."""
    _seed_mesh_winner(tuner, N, 512, devices=4, impl="xla-rows")
    d = autotune.decide(N, 512, platform="cpu", max_devices=None)
    assert (d.devices, d.mesh_shape) == (4, "vshard")
    assert autotune.shape_winner(N, 512, "lane", platform="cpu",
                                 max_devices=None) is None


def test_shape_winner_rejects_unknown_shape(tuner):
    with pytest.raises(ValueError):
        autotune.shape_winner(N, 512, "diagonal", platform="cpu")
