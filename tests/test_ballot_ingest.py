"""Ballot ingestion semantics around declared active sets (reference
proposals/eligibility_validator.go):

- a REF ballot's eligibility count is recomputed from its declared
  set's weight and must MATCH the declared count (validateReference);
- SECONDARY ballots reuse the ref ballot's validated count — never a
  local recomputation — and must share smesher + atx with the ref
  (validateSecondary);
- a secondary arriving before its ref fetches the ref instead of
  letting gossip delivery order decide validity (code-review r5).

Crypto is stubbed (verifier/oracle validate_slot); what's under test is
the ingestion state machine, not ed25519/ECVRF.
"""

import asyncio

import pytest

from spacemesh_tpu.consensus.activeset import active_set_hash
from spacemesh_tpu.consensus.miner import BAD_BEACON, ProposalHandler
from spacemesh_tpu.core.types import (
    Ballot,
    EpochData,
    Opinion,
    VotingEligibility,
)
from spacemesh_tpu.storage import ballots as ballotstore
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import misc as miscstore
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo

LPE = 4
BEACON = b"\x0b" * 4
NODE = b"n" * 32
ATX = b"A" * 32


class _Verifier:
    def verify(self, domain, node_id, msg, sig):
        return True


class _Oracle:
    """validate_slot honors ONLY num_slots_override (the handler must
    always pass the validated bound); num_slots mirrors the slot
    formula weight*10 // total."""

    def __init__(self, cache):
        self.cache = cache

    def trusts_declared(self, epoch):
        return True

    def num_slots(self, epoch, atx_id, total_override=None):
        info = self.cache.get(epoch, atx_id)
        total = total_override if total_override is not None \
            else self.cache.epoch_weight(epoch)
        return info.weight * 10 // total if total else 0

    def validate_slot(self, beacon, epoch, atx_id, layer, j, proof,
                      total_override=None, num_slots_override=None):
        assert num_slots_override is not None, \
            "handler must pass the validated bound"
        return j < num_slots_override


class _Tortoise:
    def __init__(self):
        self.ballots = []

    def on_ballot(self, ballot, weight, bad_beacon=False):
        self.ballots.append((ballot.id, weight, bad_beacon))


class _Store:
    def add(self, proposal):
        pass


class _Hub:
    def register(self, topic, fn):
        pass


def _setup():
    db = dbmod.open_state(":memory:")
    cache = AtxCache()
    cache.add(1, ATX, AtxInfo(node_id=NODE, weight=100, base_height=0,
                              height=1, num_units=1, vrf_nonce=0,
                              vrf_public_key=NODE))
    other = b"B" * 32
    cache.add(1, other, AtxInfo(node_id=b"o" * 32, weight=900,
                                base_height=0, height=1, num_units=1,
                                vrf_nonce=0, vrf_public_key=b"o" * 32))
    # the DECLARED set is just {ATX}: weight 200? no — weight 100, so
    # declared denominator 100 vs local 1000
    root = active_set_hash([ATX])
    miscstore.add_active_set(db, root, 1, [ATX])
    tortoise = _Tortoise()

    async def beacon_getter(epoch):
        return BEACON

    handler = ProposalHandler(
        db=db, cache=cache, oracle=_Oracle(cache), tortoise=tortoise,
        store=_Store(), verifier=_Verifier(), pubsub=_Hub(),
        layers_per_epoch=LPE, beacon_getter=beacon_getter)
    return db, cache, tortoise, handler, root


def _ballot(layer, *, epoch_data=None, ref=bytes(32), eligs=1, tag=b"x"):
    return Ballot(
        layer=layer, atx_id=ATX, node_id=NODE, epoch_data=epoch_data,
        ref_ballot=ref,
        eligibilities=[VotingEligibility(j=j, sig=bytes(80))
                       for j in range(eligs)],
        opinion=Opinion(base=bytes(32), support=[], against=[], abstain=[]),
        signature=tag.ljust(64, b"\0"))


def test_ref_ballot_count_validated_against_declared_set():
    db, cache, tortoise, handler, root = _setup()
    # declared denominator 100 -> bound = 100*10//100 = 10
    good = _ballot(4, epoch_data=EpochData(
        beacon=BEACON, active_set_root=root, eligibility_count=10))
    assert asyncio.run(handler.ingest_ballot(good)) is True
    # per-eligibility weight divides by the validated bound
    assert tortoise.ballots == [(good.id, (100 // 10) * 1, False)]

    forged = _ballot(5, epoch_data=EpochData(
        beacon=BEACON, active_set_root=root, eligibility_count=40),
        tag=b"f")
    assert asyncio.run(handler.ingest_ballot(forged)) is False
    db.close()


def test_secondary_reuses_ref_count_and_requires_same_atx():
    db, cache, tortoise, handler, root = _setup()
    ref = _ballot(4, epoch_data=EpochData(
        beacon=BEACON, active_set_root=root, eligibility_count=10))
    assert asyncio.run(handler.ingest_ballot(ref)) is True

    # secondary: bound is the REF's validated count (10), which admits
    # j up to 9 — a local recomputation (1000 denominator -> 1) would
    # reject these
    sec = _ballot(5, ref=ref.id, eligs=3, tag=b"s")
    assert asyncio.run(handler.ingest_ballot(sec)) is True
    assert tortoise.ballots[-1] == (sec.id, (100 // 10) * 3, False)

    # different atx than the ref: rejected (validateSecondary)
    cache.add(1, b"C" * 32, AtxInfo(node_id=NODE, weight=100,
                                    base_height=0, height=1, num_units=1,
                                    vrf_nonce=0, vrf_public_key=NODE))
    import dataclasses
    bad = dataclasses.replace(_ballot(6, ref=ref.id, tag=b"m"),
                              atx_id=b"C" * 32)
    assert asyncio.run(handler.ingest_ballot(bad)) is False
    db.close()


def test_secondary_fetches_missing_ref_ballot():
    db, cache, tortoise, handler, root = _setup()
    ref = _ballot(4, epoch_data=EpochData(
        beacon=BEACON, active_set_root=root, eligibility_count=10))
    calls = []

    async def fetch_ballot(ballot_id):
        calls.append(ballot_id)
        ballotstore.add(db, ref)  # what v_ballot does after validating
        return True

    handler.fetch_ballot = fetch_ballot
    sec = _ballot(5, ref=ref.id, tag=b"s")
    assert asyncio.run(handler.ingest_ballot(sec)) is True
    assert calls == [ref.id]
    db.close()


def test_secondary_without_resolvable_ref_rejected():
    db, cache, tortoise, handler, root = _setup()
    sec = _ballot(5, ref=b"R" * 32, tag=b"s")
    assert asyncio.run(handler.ingest_ballot(sec)) is False
    assert tortoise.ballots == []
    db.close()


def test_bad_beacon_ballot_ingested_but_flagged():
    db, cache, tortoise, handler, root = _setup()
    odd = _ballot(4, epoch_data=EpochData(
        beacon=b"\xee" * 4, active_set_root=root, eligibility_count=10))
    assert asyncio.run(handler.ingest_ballot(odd)) is BAD_BEACON
    assert tortoise.ballots == [(odd.id, 10, True)]
    db.close()
