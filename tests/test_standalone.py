"""The minimum end-to-end slice: a standalone node lives through epochs.

One in-proc node with its own poet and POST worker (the reference's
--standalone path, node/node.go:1293): initializes POST, publishes ATXs,
runs beacon/hare/tortoise per layer, generates + applies blocks, credits
rewards. This is SURVEY.md §7 M2 — every layer of the stack exercised with
no external network.
"""

import asyncio
import time

import pytest

from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import ballots as ballotstore
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.storage import misc as miscstore
from spacemesh_tpu.storage import transactions as txstore


LPE = 3           # layers per epoch
LAYER_SEC = 0.7


def _config(tmp_path):
    return load("standalone", overrides={
        "data_dir": str(tmp_path / "node"),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": time.time() + 3600},  # placeholder; moved later
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.06,
                 "preround_delay": 0.06, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.05},
        "tortoise": {"hdist": 4, "window_size": 50},
    })


@pytest.fixture(scope="module")
def ran(tmp_path_factory):
    """Run the standalone node through epochs 0-2 (layers 1..8)."""
    tmp_path = tmp_path_factory.mktemp("standalone")
    cfg = _config(tmp_path)
    app = App(cfg)

    async def go():
        # slow part (POST init + jit warmup) happens before the clock starts
        await app.prepare()
        app.clock = clock_mod.LayerClock(time.time() + 0.3,
                                         cfg.layer_duration)
        await asyncio.wait_for(app.run(until_layer=2 * LPE + 2), timeout=120)

    asyncio.run(go())
    return app


def test_atxs_published_across_epochs(ran):
    app = ran
    mine = [atxstore.by_node_in_epoch(app.state, app.signer.node_id, e)
            for e in range(3)]
    assert mine[0] is not None, "initial ATX (epoch 0) missing"
    assert mine[1] is not None, "epoch-1 ATX missing"
    # chain: epoch-1 ATX references the initial one (views are
    # version-independent; fetch the full v1 wire for initial-ATX fields)
    assert mine[1].prev_atx == mine[0].id
    full0 = atxstore.get(app.state, mine[0].id)
    full1 = atxstore.get(app.state, mine[1].id)
    assert full0.commitment_atx is not None
    assert full1.commitment_atx is None


def test_beacon_decided_for_epoch2(ran):
    app = ran
    assert miscstore.get_beacon(app.state, 2) is not None


def test_proposals_and_blocks_flow(ran):
    app = ran
    # from epoch 1 on the node is eligible: some layer in 3..8 has a ballot
    total_ballots = sum(len(ballotstore.in_layer(app.state, lyr))
                       for lyr in range(LPE, 2 * LPE + 3))
    assert total_ballots > 0, "no ballots were ever built"
    blocks_found = [lyr for lyr in range(LPE, 2 * LPE + 3)
                    if blockstore.in_layer(app.state, lyr)]
    assert blocks_found, "no blocks generated in epochs 1-2"


def test_layers_applied_and_rewarded(ran):
    app = ran
    assert layerstore.last_applied(app.state) >= LPE
    # rewards landed at the smesher's coinbase for each block-bearing layer
    from spacemesh_tpu.vm import sdk
    coinbase = sdk.wallet_address(app.signer.public_key).raw
    rewards = miscstore.rewards_for(app.state, coinbase)
    assert rewards, "no rewards credited"
    acct = txstore.account(app.state, coinbase)
    assert acct is not None and acct["balance"] > 0


def test_hare_outputs_recorded(ran):
    app = ran
    hare_layers = [lyr for lyr, out in app.tortoise._hare.items()]
    assert hare_layers, "hare never produced output"


def test_certificates_collected(ran):
    app = ran
    certified = [lyr for lyr in range(LPE, 2 * LPE + 3)
                 if miscstore.certified_block(app.state, lyr) is not None]
    assert certified, "no layer was certified"
