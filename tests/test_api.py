"""API surface over a live standalone node.

De-flaked (ISSUE 8 satellite, finished in ISSUE 12): the node's signer
is a FIXED seed (a random key redraws the VRF proposal-slot lottery per
run) and the tx lifecycle is awaited on CONDITIONS — poll the API until
the result lands, bounded by virtual time — instead of sleeping a fixed
number of layers and hoping the spawn got into one of them.  The ISSUE
12 pass removed the last timing cliff: the node used to stop ticking at
layer 12 while the reward wait alone could burn 15 virtual layers under
slow real IO (POST init + hare share the wall clock even on a virtual
loop), so a late-landing reward pushed the spawn past the final layer
and its result never existed.  The run now carries double the layer
headroom and every wait is a virtual-deadline condition poll."""

import asyncio
import hashlib

import pytest
from aiohttp import ClientSession

from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.vm import sdk
from spacemesh_tpu.utils.vclock import VirtualClockLoop, cancel_all_tasks

LPE = 3
LAYER_SEC = 2.0  # virtual seconds (VirtualClockLoop)


@pytest.fixture(scope="module")
def api_env(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("api")
    cfg = load("standalone", overrides={
        "data_dir": str(tmp / "node"),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": 0.0},  # rebased to virtual time below
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.2,
                 "preround_delay": 0.5, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.2},
        "tortoise": {"hdist": 4, "window_size": 50},
    })
    loop = VirtualClockLoop()
    signer = EdSigner(seed=hashlib.sha256(b"api-node").digest(),
                      prefix=cfg.genesis.genesis_id)
    app = App(cfg, signer=signer, time_source=loop.time)
    results = {}

    async def go():
        await app.prepare()
        port = await app.start_api()
        app.clock = clock_mod.LayerClock(loop.time() + 1.0, LAYER_SEC,
                                         time_source=loop.time)
        run = asyncio.create_task(app.run(until_layer=8 * LPE))
        base = f"http://127.0.0.1:{port}"
        async with ClientSession() as s:
            # let a couple of layers pass
            await asyncio.sleep(LAYER_SEC * (LPE + 1.5))
            results["status"] = await (await s.get(f"{base}/v1/node/status")).json()
            results["genesis"] = await (await s.get(f"{base}/v1/mesh/genesis")).json()
            results["atxs_e1"] = await (await s.get(f"{base}/v1/mesh/epoch/1/atxs")).json()
            results["smesher"] = await (await s.get(f"{base}/v1/smesher/status")).json()
            # wait for the first reward so the account can pay the tx
            # fee — a virtual-deadline condition poll, leaving at least
            # half the run's layers for the spawn itself to apply
            coinbase = sdk.wallet_address(app.signer.public_key)
            deadline = loop.time() + 4 * LPE * LAYER_SEC
            while True:
                acct = await (await s.get(
                    f"{base}/v1/account/{coinbase.encode()}")).json()
                if acct["balance"] > 0 or loop.time() >= deadline:
                    break
                await asyncio.sleep(LAYER_SEC / 4)
            results["acct_pre"] = acct
            spawn = sdk.spawn_wallet(app.signer)
            r = await s.post(f"{base}/v1/tx/submit",
                             json={"raw": spawn.raw.hex()})
            results["submit"] = (r.status, await r.json())
            results["bad_submit"] = (await s.post(
                f"{base}/v1/tx/submit", json={"raw": "zz"})).status
            results["tx_lookup_404"] = (await s.get(
                f"{base}/v1/tx/{'00'*32}")).status
            # condition wait: the spawn lands in whichever later layer
            # includes it — poll the API until the result exists
            # (bounded by VIRTUAL time, costs no wall clock) instead of
            # sleeping an exact layer count and hoping
            tx_id = results["submit"][1]["tx_id"]
            deadline = loop.time() + 10 * LAYER_SEC
            while loop.time() < deadline:
                tx_doc = await (await s.get(f"{base}/v1/tx/{tx_id}")).json()
                if tx_doc.get("result") is not None:
                    break
                await asyncio.sleep(LAYER_SEC / 4)
            results["tx_after"] = tx_doc
            results["layer3"] = await (await s.get(f"{base}/v1/mesh/layer/3")).json()
            results["root"] = await (await s.get(f"{base}/v1/globalstate/root")).json()
            results["debug"] = await (await s.get(f"{base}/v1/debug/state")).json()
            results["events"] = await (await s.get(
                f"{base}/v1/events?timeout=0.3")).json()
            results["stacks"] = await (await s.get(
                f"{base}/debug/stacks")).text()
            results["profile"] = await (await s.get(
                f"{base}/debug/profile?seconds=0.2")).text()
        await run
        await app.api.stop()

    try:
        loop.run_until_complete(asyncio.wait_for(go(), 10_000))
    finally:
        loop.run_until_complete(cancel_all_tasks())
    return app, results


def test_node_and_genesis(api_env):
    app, r = api_env
    assert r["status"]["status"]["top_layer"] >= 3
    assert r["genesis"]["layers_per_epoch"] == LPE
    assert r["genesis"]["genesis_id"] == app.cfg.genesis.genesis_id.hex()


def test_epoch_atxs_and_smesher(api_env):
    app, r = api_env
    assert len(r["atxs_e1"]["atxs"]) == 1
    assert r["smesher"]["smeshing"] is True
    assert r["smesher"]["node_id"] == app.signer.node_id.hex()


def test_tx_submit_and_result(api_env):
    app, r = api_env
    status, body = r["submit"]
    assert status == 200 and body["accepted"]
    assert r["bad_submit"] == 400
    assert r["tx_lookup_404"] == 404
    # the spawn applied in a later layer
    assert r["tx_after"]["result"] is not None
    assert r["tx_after"]["result"]["status"] == 0


def test_layer_and_state(api_env):
    app, r = api_env
    assert r["root"]["root"] is not None
    assert r["debug"]["last_applied"] >= 3
    assert isinstance(r["events"]["events"], list)
    assert r["acct_pre"]["balance"] > 0  # rewards had landed


def test_debug_profiling_endpoints(api_env):
    """pprof analogue (reference node/node.go:2121-2151): thread/task
    stack dumps and an on-demand CPU profile over the admin HTTP API."""
    app, r = api_env
    assert "--- thread" in r["stacks"]
    assert "asyncio tasks" in r["stacks"]
    assert "cumulative" in r["profile"]  # pstats header
