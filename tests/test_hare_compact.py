"""hare4-style compaction: compact ids, roots, full exchange fallback.

Reference hare4/hare.go:328 fetchFull + :394 reconstructProposals: hare
messages carry 4-byte proposal-id prefixes and a root; receivers rebuild
full ids from their store, or stream them from the delivering peer.

De-flaked (ISSUE 8 satellite): signers are built from FIXED seeds (a
random key redraws every VRF eligibility roll — with 3 signers sharing
a 30-seat committee an unlucky draw left a node without seats ~1/8 of
full-suite runs), and the timing-sensitive tests (hare rounds are
wall-clock slots) run on a VirtualClockLoop with ``wall=loop.time`` so
machine load cannot skip a round.
"""

import asyncio
import hashlib

from spacemesh_tpu.consensus.eligibility import Oracle
from spacemesh_tpu.consensus.hare import (
    COMMIT,
    CompactHareMessage,
    Hare,
    compact_id,
    values_root,
)
from spacemesh_tpu.core.hashing import sum256
from spacemesh_tpu.core.signing import Domain, EdSigner, EdVerifier
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.p2p.server import LoopbackNet, Server
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo
from spacemesh_tpu.utils.vclock import run_virtual

GEN = b"hare-compact-gen!!!!"


def _signers(n: int) -> list[EdSigner]:
    """Deterministic test identities: every eligibility draw replays.
    The seed salt is CHOSEN so the draws carry margin — signers 0+1
    alone hold >=26 of the 30 committee seats in the preround (the
    full-exchange test's store-less third node contributes no preround
    support) and every round's total clears the 16-seat threshold
    comfortably. A random key redraws this lottery per run and loses
    it ~1/8 of the time, which was exactly the old flake."""
    return [EdSigner(seed=hashlib.sha256(b"hare-compact-6-%d" % i).digest(),
                     prefix=GEN) for i in range(n)]
LPE = 4
LAYER = 5
EPOCH = LAYER // LPE
BEACON = b"\x07\x07\x07\x07"
COMMITTEE = 30


def _cache_with(signers, weight=100):
    cache = AtxCache()
    atx_ids = {}
    for i, s in enumerate(signers):
        atx_id = b"CATX%04d" % i + bytes(24)
        atx_ids[s.node_id] = atx_id
        cache.add(EPOCH, atx_id, AtxInfo(
            node_id=s.node_id, weight=weight, base_height=0, height=1,
            num_units=1, vrf_nonce=0, vrf_public_key=s.node_id))
    return cache, atx_ids


async def _abeacon(epoch):
    return BEACON


def _mk(hub, net, cache, atx_ids, signer, outputs, proposals,
        store: dict, wall=None):
    """store: layer -> list of full proposal ids this node knows."""
    ps = PubSub(node_name=signer.node_id)
    hub.join(ps)
    srv = Server(signer.node_id)
    net.join(srv)

    async def on_output(out):
        outputs.append((signer.node_id, tuple(out.proposals)))

    hare = Hare(
        signers=[signer], verifier=EdVerifier(prefix=GEN),
        oracle=Oracle(cache, LPE), pubsub=ps, committee_size=COMMITTEE,
        round_duration=0.15, iteration_limit=2, preround_delay=0.15,
        layers_per_epoch=LPE, beacon_of=_abeacon,
        atx_for=lambda epoch, node_id: atx_ids.get(node_id),
        proposals_for=lambda layer: list(store.get(layer, [])),
        on_output=on_output, compact=True, server=srv, wall=wall)
    return hare


def test_compact_agreement_with_shared_store():
    """All nodes know the proposals: reconstruction is store-local and
    they agree through compact messages only."""
    signers = _signers(3)
    cache, atx_ids = _cache_with(signers)
    hub, net = LoopbackHub(), LoopbackNet()
    props = sorted([sum256(b"p1"), sum256(b"p2")])
    store = {LAYER: props}
    outs = []

    async def go():
        loop = asyncio.get_running_loop()
        hares = [_mk(hub, net, cache, atx_ids, s, outs, props, store,
                     wall=loop.time)
                 for s in signers]
        await asyncio.gather(*(h.run_layer(LAYER) for h in hares))

    run_virtual(go(), timeout=300)
    values = {v for _, v in outs}
    assert len(values) == 1
    assert sorted(values.pop()) == props


def test_full_exchange_recovers_missing_proposals():
    """One node's proposal store is EMPTY: every reconstruction must go
    through the hf/1 full exchange with the delivering peer — and the
    node still reaches the same output."""
    signers = _signers(3)
    cache, atx_ids = _cache_with(signers)
    hub, net = LoopbackHub(), LoopbackNet()
    props = sorted([sum256(b"q1"), sum256(b"q2"), sum256(b"q3")])
    full_store = {LAYER: props}
    empty_store: dict = {}
    outs = []

    async def go():
        loop = asyncio.get_running_loop()
        hares = [
            _mk(hub, net, cache, atx_ids, signers[0], outs, props,
                full_store, wall=loop.time),
            _mk(hub, net, cache, atx_ids, signers[1], outs, props,
                full_store, wall=loop.time),
            _mk(hub, net, cache, atx_ids, signers[2], outs, [],
                empty_store, wall=loop.time),  # knows nothing locally
        ]
        await asyncio.gather(*(h.run_layer(LAYER) for h in hares))

    run_virtual(go(), timeout=300)
    by_node = dict(outs)
    assert by_node[signers[2].node_id] == tuple(props), \
        "store-less node failed to reconstruct via full exchange"
    assert len({v for v in by_node.values()}) == 1


def test_root_mismatch_rejected():
    """A compact message whose root doesn't match its ids is refused."""
    signers = _signers(2)
    cache, atx_ids = _cache_with(signers)
    hub, net = LoopbackHub(), LoopbackNet()
    props = [sum256(b"z1")]
    store = {LAYER: props}
    outs = []
    hare = _mk(hub, net, cache, atx_ids, signers[0], outs, props, store)
    oracle = Oracle(cache, LPE)
    attacker = signers[1]
    el = oracle.hare_eligibility(attacker.vrf_signer(), BEACON, LAYER,
                                 0 * 4 + COMMIT, EPOCH,
                                 atx_ids[attacker.node_id], COMMITTEE)
    proof, count = el
    cm = CompactHareMessage(
        layer=LAYER, iteration=0, round=COMMIT,
        compact_ids=[compact_id(props[0])],
        root=sum256(b"some other set"),  # lies about the values
        eligibility_proof=proof, eligibility_count=count,
        atx_id=atx_ids[attacker.node_id], node_id=attacker.node_id,
        cert_msgs=[], signature=bytes(64))
    cm.signature = attacker.sign(Domain.HARE, cm.signed_bytes())

    async def go():
        assert not await hare._gossip_compact(b"peer", cm.to_bytes())

    asyncio.run(go())


def test_standalone_node_runs_with_compact_hare(tmp_path):
    """A full node lives through epochs with hare.compact=True — the
    compact path is wired end to end (topic b4, hf/1 on the server).
    Runs on a VirtualClockLoop with a fixed signer: the old wall-clock
    version (0.7 s layers, random key) missed hare rounds under
    full-suite load ~1/8 of the time."""
    from spacemesh_tpu.node import clock as clock_mod
    from spacemesh_tpu.node.app import App
    from spacemesh_tpu.node.config import load
    from spacemesh_tpu.storage import layers as layerstore
    from spacemesh_tpu.utils.vclock import VirtualClockLoop, \
        cancel_all_tasks

    cfg = load("standalone", overrides={
        "data_dir": str(tmp_path / "node"),
        "layer_duration": 0.7, "layers_per_epoch": 3, "slots_per_layer": 2,
        "genesis": {"time": 1_700_000_450.0},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.06,
                 "preround_delay": 0.06, "iteration_limit": 2,
                 "compact": True},
        "beacon": {"proposal_duration": 0.05},
        "tortoise": {"hdist": 4, "window_size": 50},
    })
    loop = VirtualClockLoop()
    signer = EdSigner(
        seed=hashlib.sha256(b"hare-compact-standalone").digest(),
        prefix=cfg.genesis.genesis_id)
    app = App(cfg, signer=signer, time_source=loop.time)

    async def go():
        await app.prepare()
        app.clock = clock_mod.LayerClock(loop.time() + 0.3,
                                         cfg.layer_duration,
                                         time_source=loop.time)
        await app.run(until_layer=7)

    try:
        loop.run_until_complete(asyncio.wait_for(go(), 10_000))
        assert layerstore.last_applied(app.state) >= 6
        from spacemesh_tpu.storage import blocks as blockstore

        assert any(blockstore.ids_in_layer(app.state, lyr)
                   for lyr in range(3, 8)), "no blocks under compact hare"
    finally:
        try:
            loop.run_until_complete(cancel_all_tasks())
        finally:
            loop.close()
            app.close()


def test_compact_equivocation_proof_validates():
    """Two conflicting COMPACT messages must yield a malfeasance proof
    that the handler accepts (signatures cover the compact encoding)."""
    from spacemesh_tpu.consensus import malfeasance as mal_mod
    from spacemesh_tpu.storage import db as dbmod
    from spacemesh_tpu.storage import misc as miscstore

    signers = _signers(2)
    cache, atx_ids = _cache_with(signers)
    hub, net = LoopbackHub(), LoopbackNet()
    p1, p2 = sum256(b"e1"), sum256(b"e2")
    store = {LAYER: [p1, p2]}
    equivs = []
    hare = _mk(hub, net, cache, atx_ids, signers[0], [], [p1, p2], store)
    hare.on_equivocation = equivs.append
    evil = signers[1]
    oracle = Oracle(cache, LPE)

    def compact_msg(vals):
        el = oracle.hare_eligibility(evil.vrf_signer(), BEACON, LAYER,
                                     0, EPOCH, atx_ids[evil.node_id],
                                     COMMITTEE)
        proof, count = el
        vals = sorted(vals)
        cm = CompactHareMessage(
            layer=LAYER, iteration=0, round=0,
            compact_ids=[compact_id(v) for v in vals],
            root=values_root(vals), eligibility_proof=proof,
            eligibility_count=count, atx_id=atx_ids[evil.node_id],
            node_id=evil.node_id, cert_msgs=[], signature=bytes(64))
        cm.signature = evil.sign(Domain.HARE, cm.signed_bytes())
        return cm

    async def go():
        from spacemesh_tpu.consensus.hare import HareSession

        session = HareSession(hare, LAYER, [])
        hare.sessions[LAYER] = session
        assert await hare._gossip_compact(b"x", compact_msg([p1]).to_bytes())
        assert await hare._gossip_compact(b"x", compact_msg([p2]).to_bytes())

    asyncio.run(go())
    assert equivs, "compact equivocation went unreported"
    eq = equivs[0]
    proof = mal_mod.proof_from_hare(eq.node_id, eq.msg1, eq.sig1,
                                    eq.msg2, eq.sig2)
    db = dbmod.open_state(":memory:")
    handler = mal_mod.Handler(db=db, cache=cache,
                              verifier=EdVerifier(prefix=GEN),
                              pubsub=PubSub(node_name=b"t"))
    assert handler.process(proof), \
        "compact-mode equivocation proof rejected by malfeasance handler"
    assert miscstore.is_malicious(db, evil.node_id)
    db.close()


def test_compact_is_smaller_on_the_wire():
    vals = [sum256(b"v%d" % i) for i in range(50)]
    full_len = sum(len(v) for v in vals)
    compact_len = sum(len(compact_id(v)) for v in vals) + 32  # + root
    assert compact_len < full_len // 4
    assert values_root(sorted(vals)) == values_root(sorted(vals))
