"""TCP transport: sockets, handshake, gossip relay, req/resp, peer drop.

Exercises p2p/transport.Host directly over real loopback sockets — the
layer the round-1 build lacked entirely. Mirrors the behaviors the
reference gets from libp2p: network-cookie handshake rejection
(p2p/handshake), flood gossip with dedup + relay, drop-on-validation-
reject (pubsub.go:168), peer exchange discovery.
"""

import asyncio

import pytest

from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.p2p.pubsub import PubSub
from spacemesh_tpu.p2p.server import RequestError, Server
from spacemesh_tpu.p2p.transport import Host

GEN = b"g" * 20

# identities are real ed25519 keys now (the handshake PROVES them);
# deterministic per node letter so restarts reuse the same id
_SIGNERS: dict[bytes, EdSigner] = {}


def _signer(node_byte: bytes) -> EdSigner:
    if node_byte not in _SIGNERS:
        _SIGNERS[node_byte] = EdSigner(seed=node_byte * 32, prefix=GEN)
    return _SIGNERS[node_byte]


def _mk(node_byte: bytes, genesis: bytes = GEN, **kw):
    signer = _signer(node_byte)
    host = Host(signer=signer, genesis_id=genesis,
                listen="127.0.0.1:0", **kw)
    ps = PubSub(node_name=signer.node_id)
    srv = Server(signer.node_id)
    host.join_pubsub(ps)
    host.join(srv)
    return host, ps, srv


async def _wait(pred, timeout=5.0, tick=0.02):
    async def loop():
        while not pred():
            await asyncio.sleep(tick)
    await asyncio.wait_for(loop(), timeout)


def test_gossip_and_relay_line_topology():
    """A-B-C line: A's publish floods through B to C; dedup holds."""

    async def go():
        a, psa, _ = _mk(b"a")
        b, psb, _ = _mk(b"b")
        c, psc, _ = _mk(b"c", min_peers=1)  # C must not dial A via PX
        got_b, got_c = [], []

        async def hb(peer, data):
            got_b.append(data)
            return True

        async def hc(peer, data):
            got_c.append(data)
            return True

        psb.register("t1", hb)
        psc.register("t1", hc)
        await a.start()
        await b.start()
        await c.start()
        # connect A-B and B-C only
        await a._dial(b.address)
        await c._dial(b.address)
        await _wait(lambda: len(a.nodes) >= 1 and len(c.nodes) >= 1)

        await psa.publish("t1", b"hello-mesh")
        await _wait(lambda: got_c)
        assert got_b == [b"hello-mesh"]
        assert got_c == [b"hello-mesh"]
        # republish: B/C have seen the id; no duplicate delivery
        await psa.publish("t1", b"hello-mesh")
        await asyncio.sleep(0.3)
        assert got_b == [b"hello-mesh"]
        assert got_c == [b"hello-mesh"]
        for h in (a, b, c):
            await h.stop()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_genesis_cookie_rejects_wrong_network():
    async def go():
        a, _, _ = _mk(b"a")
        b, _, _ = _mk(b"b", genesis=b"x" * 20)
        await a.start()
        await b.start()
        await a._dial(b.address)
        await asyncio.sleep(0.5)
        assert len(a.nodes) == 0
        assert len(b.nodes) == 0
        await a.stop()
        await b.stop()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_request_response_and_unknown_protocol():
    async def go():
        a, _, sa = _mk(b"a")
        b, _, sb = _mk(b"b")

        async def echo(peer, data):
            return b"echo:" + data

        sb.register("ec/1", echo)
        await a.start()
        await b.start()
        await a._dial(b.address)
        await _wait(lambda: len(a.nodes) >= 1)
        peer = list(a.nodes)[0]
        resp = await sa.request(peer, "ec/1", b"ping")
        assert resp == b"echo:ping"
        with pytest.raises(RequestError):
            await sa.request(peer, "nope/1", b"x")
        await a.stop()
        await b.stop()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_drop_peer_on_repeated_validation_reject():
    async def go():
        a, psa, _ = _mk(b"a", reject_limit=3)
        b, psb, _ = _mk(b"b")

        async def reject(peer, data):
            return False

        psa.register("bad", reject)
        await a.start()
        await b.start()
        await b._dial(a.address)
        await _wait(lambda: len(b.nodes) >= 1)
        for _ in range(5):
            await psb.publish("bad", b"junk-%d" % _)
        await _wait(lambda: len(a.nodes) == 0)
        # A banned B: an immediate redial is refused
        await b._dial(a.address)
        await asyncio.sleep(0.3)
        assert len(a.nodes) == 0
        await a.stop()
        await b.stop()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_reconnects_to_restarted_peer():
    """A crashed peer that comes back on the SAME address is redialed by
    the maintainer loop (known-addr redial; reference reconnect/bootstrap
    retry behavior)."""

    async def go():
        a, psa, _ = _mk(b"a", min_peers=2)
        b, psb, _ = _mk(b"b")
        got = []

        async def hb(peer, data):
            got.append(data)
            return True

        psb.register("t9", hb)
        await a.start()
        addr_b = await b.start()
        a._known[(addr_b[0], addr_b[1])] = 0.0  # seed the known-addr table
        await _wait(lambda: len(a.nodes) >= 1)

        # B "crashes"
        await b.stop()
        await _wait(lambda: len(a.nodes) == 0, timeout=10)

        # ...and restarts on the same port
        b2, psb2, _ = _mk(b"b")
        psb2.register("t9", hb)
        b2.listen = f"{addr_b[0]}:{addr_b[1]}"
        await b2.start()
        # A's maintainer redials the known address
        await _wait(lambda: len(a.nodes) >= 1, timeout=15)
        await psa.publish("t9", b"hello-again")
        await _wait(lambda: got)
        assert got == [b"hello-again"]
        await a.stop()
        await b2.stop()

    asyncio.run(asyncio.wait_for(go(), 40))


def test_peer_exchange_discovers_third_node():
    """C bootstraps only to B but learns A's address and dials it."""

    async def go():
        a, _, _ = _mk(b"a")
        b, _, _ = _mk(b"b")
        await a.start()
        await b.start()
        await a._dial(b.address)
        await _wait(lambda: len(b.nodes) >= 1)

        c, _, _ = _mk(b"c")
        c.bootstrap = [f"{b.address[0]}:{b.address[1]}"]
        await c.start()
        c._known[(b.address[0], b.address[1])] = 0.0
        await _wait(lambda: len(c.nodes) >= 2, timeout=10)
        assert {conn.node_id for conn in c.nodes.values()} == {
            _signer(b"a").node_id, _signer(b"b").node_id}
        for h in (a, b, c):
            await h.stop()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_impersonation_rejected():
    """A peer claiming another node's id is dropped: the channel-binding
    signature can't be produced without the victim's key (VERDICT r2
    item 3 done-criterion; reference p2p/host.go:306-309 key-bound ids)."""

    async def go():
        victim, _, _ = _mk(b"v")
        target, _, _ = _mk(b"t")
        evil, _, _ = _mk(b"e")
        await victim.start()
        await target.start()
        await evil.start()
        # evil CLAIMS the victim's identity in its HELLO, but its
        # binding signature is made with its own key
        evil.node_id = victim.node_id
        await evil._dial(target.address)
        await asyncio.sleep(0.5)
        assert len(target.nodes) == 0, "forged identity accepted"
        assert len(evil.nodes) == 0
        for h in (victim, target, evil):
            await h.stop()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_wire_traffic_is_encrypted():
    """No plaintext identity/topic bytes on the wire (noise channel)."""

    async def go():
        a, psa, _ = _mk(b"a")
        b, psb, _ = _mk(b"b")
        seen = bytearray()

        async def sniff(reader, writer):
            up_r, up_w = await asyncio.open_connection(*b.address)

            async def pump(r, w):
                try:
                    while True:
                        chunk = await r.read(4096)
                        if not chunk:
                            break
                        seen.extend(chunk)
                        w.write(chunk)
                        await w.drain()
                except (OSError, ConnectionError):
                    pass

            await asyncio.gather(pump(reader, up_w), pump(up_r, writer))

        mitm = await asyncio.start_server(sniff, "127.0.0.1", 0)
        mitm_addr = mitm.sockets[0].getsockname()[:2]

        got = []

        async def hb(peer, data):
            got.append(data)
            return True

        psb.register("sekrit-topic", hb)
        await a.start()
        await b.start()
        await a._dial(mitm_addr)
        await _wait(lambda: len(a.nodes) >= 1)
        await psa.publish("sekrit-topic", b"attack-at-dawn")
        await _wait(lambda: got)
        assert got == [b"attack-at-dawn"]
        blob = bytes(seen)
        assert b"attack-at-dawn" not in blob
        assert b"sekrit-topic" not in blob
        assert a.node_id not in blob  # identity is inside the ciphertext
        mitm.close()
        await a.stop()
        await b.stop()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_fallback_aead_mac_is_length_framed():
    """The no-`cryptography` AEAD must not authenticate distinct
    (aad, ct) splits of the same byte string: the tag input frames the
    aad with a length prefix, so shifting a byte across the aad/ct
    boundary invalidates the tag (it previously verified, decrypting
    to garbage that the MAC was supposed to gate)."""
    from spacemesh_tpu.p2p import noise

    if noise._HAVE_CRYPTOGRAPHY:
        pytest.skip("real ChaCha20-Poly1305 in use; fallback not built")
    aead = noise.ChaCha20Poly1305(b"k" * 32)
    nonce = bytes(12)
    aad = b"header"
    blob = aead.encrypt(nonce, b"payload-bytes", aad)
    ct, tag = blob[:-aead.TAG], blob[-aead.TAG:]
    assert aead.decrypt(nonce, blob, aad) == b"payload-bytes"
    # move the first ciphertext byte into the aad: same concatenation,
    # different split — must NOT authenticate
    with pytest.raises(ValueError):
        aead.decrypt(nonce, ct[1:] + tag, aad + ct[:1])
    # and vice versa: last aad byte moved into the ciphertext
    with pytest.raises(ValueError):
        aead.decrypt(nonce, aad[-1:] + ct + tag, aad[:-1])


# --- chaos hooks under concurrency (ISSUE 8 satellite) ----------------


def test_chaos_block_under_concurrent_dials():
    """chaos_block must hold while BOTH sides dial simultaneously and
    repeatedly: no connection forms in either direction while the block
    stands, and chaos_clear restores dialing."""

    async def go():
        a, _, _ = _mk(b"a", min_peers=0)
        b, _, _ = _mk(b"b", min_peers=0)
        await a.start()
        await b.start()
        a.chaos_block(node_ids=[b.node_id], addrs=[b.address])
        # a storm of simultaneous dials from both sides
        await asyncio.gather(*(
            [a._dial(b.address) for _ in range(5)]
            + [b._dial(a.address) for _ in range(5)]))
        await asyncio.sleep(0.5)
        assert b.node_id not in a.nodes, "blocked peer connected"
        assert a.node_id not in b.nodes, \
            "accept side ignored the chaos block"
        # clear + re-dial (again concurrently) -> exactly one connection
        a.chaos_clear()
        await asyncio.gather(*(
            [a._dial(b.address) for _ in range(3)]
            + [b._dial(a.address) for _ in range(3)]))
        await _wait(lambda: b.node_id in a.nodes
                    and a.node_id in b.nodes)
        assert not a.nodes[b.node_id].closed.is_set()
        await a.stop()
        await b.stop()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_chaos_block_severs_live_connection_midstream():
    """Blocking an already-connected peer drops the live connection;
    the dial maintainer must not silently resurrect it while blocked."""

    async def go():
        a, psa, _ = _mk(b"a")
        b, psb, _ = _mk(b"b")
        await a.start()
        await b.start()
        await a._dial(b.address)
        await _wait(lambda: b.node_id in a.nodes)
        a.chaos_block(node_ids=[b.node_id],
                      addrs=[b.address])
        await _wait(lambda: b.node_id not in a.nodes)
        # give the maintainers a couple of cycles to try to reconnect
        await asyncio.sleep(1.2)
        assert b.node_id not in a.nodes
        await a.stop()
        await b.stop()

    asyncio.run(asyncio.wait_for(go(), 30))


def test_chaos_link_loss_delay_and_dup():
    """chaos_link degrades gossip relays: full loss drops them, delay
    defers them, duplication is absorbed by the receiver's dedup."""

    async def go():
        a, psa, _ = _mk(b"a")
        b, psb, _ = _mk(b"b")
        got = []

        async def hb(peer, data):
            got.append(data)
            return True

        psb.register("t1", hb)
        await a.start()
        await b.start()
        await a._dial(b.address)
        await _wait(lambda: b.node_id in a.nodes)

        a.chaos_link(loss=1.0)
        await psa.publish("t1", b"lost")
        await asyncio.sleep(0.4)
        assert got == [], "full loss still delivered"

        a.chaos_clear()
        await psa.publish("t1", b"clean")
        await _wait(lambda: b"clean" in got)

        a.chaos_link(dup=1.0)
        dup_before = b.stats["gossip_dup"]
        await psa.publish("t1", b"doubled")
        await _wait(lambda: b"doubled" in got)
        await _wait(lambda: b.stats["gossip_dup"] > dup_before)
        assert got.count(b"doubled") == 1, "dedup must absorb the copy"

        a.chaos_link(delay=0.5)
        await psa.publish("t1", b"late")
        await asyncio.sleep(0.15)
        assert b"late" not in got, "delayed frame arrived early"
        await _wait(lambda: b"late" in got, timeout=3.0)
        await a.stop()
        await b.stop()

    asyncio.run(asyncio.wait_for(go(), 30))
