"""Gossipsub-lite: mesh bounds, lazy repair, sub-flood duplication.

Reference p2p/pubsub/pubsub.go:211-311 (gossipsub mesh parameters) —
the message-complexity test is VERDICT r2 item 7's acceptance: a 16-node
net shows materially fewer duplicate deliveries than flood would cost.
Runs on the real clock: mesh formation IS heartbeat-driven.
"""

import asyncio

import pytest

from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.p2p.gossipmesh import (
    GRAFT,
    IHAVE,
    IWANT,
    PRUNE,
    GossipMesh,
    MessageCache,
    decode_ctrl,
    encode_ctrl,
)
from spacemesh_tpu.p2p.pubsub import PubSub
from spacemesh_tpu.p2p.transport import Host

GEN = b"gossipmesh-genesis!!"


# --- unit: control codec + mesh bookkeeping -----------------------------


def test_ctrl_roundtrip():
    ids = [bytes([i]) * 32 for i in range(3)]
    for subtype in (GRAFT, PRUNE, IHAVE, IWANT):
        st, topic, got = decode_ctrl(encode_ctrl(subtype, "ax1", ids))
        assert (st, topic, got) == (subtype, "ax1", ids)


def test_ragged_ctrl_rejected():
    with pytest.raises(ValueError):
        decode_ctrl(encode_ctrl(IHAVE, "t", [b"x" * 32]) + b"ragged")


def test_heartbeat_keeps_mesh_within_bounds():
    m = GossipMesh(degree=3, d_lo=2, d_hi=4)
    peers = {bytes([i]) * 32 for i in range(10)}
    m.on_message(b"m" * 32, "t", b"frame")
    sends = m.heartbeat(peers)
    grafts = [p for p, st, _, _ in sends if st == GRAFT]
    assert 2 <= len(m.mesh["t"]) <= 4
    assert set(grafts) == m.mesh["t"]
    # over-subscribe, then heartbeat prunes back to degree
    m.mesh["t"] = set(list(peers)[:9])
    sends = m.heartbeat(peers)
    prunes = [p for p, st, _, _ in sends if st == PRUNE]
    assert len(m.mesh["t"]) == 3
    assert len(prunes) == 6


def test_graft_over_capacity_answers_prune():
    m = GossipMesh(degree=2, d_lo=1, d_hi=2)
    m.mesh["t"] = {b"a" * 32, b"b" * 32}
    replies = m.on_control(b"c" * 32, encode_ctrl(GRAFT, "t"),
                           seen=lambda _: True)
    assert replies == [(PRUNE, "t", [])]
    assert b"c" * 32 not in m.mesh["t"]


def test_iwant_spam_guard():
    m = GossipMesh()
    mid = b"i" * 32
    m.on_message(mid, "t", b"frame")
    peer = b"p" * 32
    for _ in range(3):
        assert m.on_control(peer, encode_ctrl(IWANT, "t", [mid]),
                            seen=lambda _: True) == [(-1, "t", [mid])]
    # 4th ask for the same id is refused (GossipRetransmission guard)
    assert m.on_control(peer, encode_ctrl(IWANT, "t", [mid]),
                        seen=lambda _: True) == []


def test_mcache_window_expires():
    c = MessageCache(history=2)
    c.put(b"a" * 32, "t", b"fa")
    c.shift()
    assert c.recent_ids("t") == [b"a" * 32]
    c.shift()  # beyond history
    assert c.recent_ids("t") == []
    assert c.get(b"a" * 32) is None


# --- integration: real hosts ---------------------------------------------


async def _mk_host(genesis, bootstrap=(), heartbeat=0.1, degree=6,
                   min_peers=1):
    h = Host(signer=EdSigner(prefix=GEN), genesis_id=genesis,
             listen="127.0.0.1:0", bootstrap=list(bootstrap),
             min_peers=min_peers, gossip_heartbeat=heartbeat,
             gossip_degree=degree)
    await h.start()
    return h


def _counting_pubsub(name: bytes, got: dict):
    # deliver_self=True (the production default): publishers handle their
    # own messages locally, so "every node got every message" includes
    # each publisher's own
    ps = PubSub(node_name=name, deliver_self=True)

    async def handler(peer, data):
        got.setdefault(data, 0)
        got[data] += 1
        return True

    ps.register("t1", handler)
    return ps


def test_lazy_ihave_iwant_repairs_non_mesh_peer():
    """C is connected to A but outside A's mesh; B relays nowhere.  C
    still converges via IHAVE -> IWANT (the gossipsub repair path)."""

    async def go():
        a = await _mk_host(GEN[:20])
        addr_a = f"127.0.0.1:{a.address[1]}"
        b = await _mk_host(GEN[:20], [addr_a])
        c = await _mk_host(GEN[:20], [addr_a])
        got_a, got_b, got_c = {}, {}, {}
        a.join_pubsub(_counting_pubsub(a.node_id, got_a))
        b.join_pubsub(_counting_pubsub(b.node_id, got_b))
        c.join_pubsub(_counting_pubsub(c.node_id, got_c))
        try:
            for _ in range(100):
                if len(a.nodes) == 2:
                    break
                await asyncio.sleep(0.05)
            assert len(a.nodes) == 2, "B and C must both connect to A"
            # pin A's topic mesh to {B} and freeze its size so the
            # heartbeat cannot graft C (degree bounds all 1)
            a.gossip.mesh["t1"] = {b.node_id}
            a.gossip.degree = a.gossip.d_lo = a.gossip.d_hi = 1
            payload = b"lazy-repair-payload"
            await a._pubsub.publish("t1", payload)
            for _ in range(100):
                if payload in got_c:
                    break
                await asyncio.sleep(0.05)
            assert got_b.get(payload) == 1, "mesh peer gets it eagerly"
            assert got_c.get(payload) == 1, \
                "non-mesh peer must converge via IHAVE/IWANT"
            assert a.stats["iwant_served"] >= 1
        finally:
            for h in (a, b, c):
                await h.stop()

    asyncio.run(go())


def test_iterative_discovery_walks_the_chain():
    """A-B-C-D chain (each node bootstraps only to its predecessor):
    A.discover() contacts successively closer peers and ends up
    CONNECTED to D, which no bootstrap list ever mentioned (reference
    p2p/dhtdiscovery iterative peer routing)."""

    async def go():
        a = await _mk_host(GEN[:20])
        chain = [a]
        for _ in range(3):
            prev = chain[-1]
            h = await _mk_host(GEN[:20],
                               [f"127.0.0.1:{prev.address[1]}"])
            chain.append(h)
        b, c, d = chain[1:]
        try:
            for _ in range(100):
                if all(len(h.nodes) >= 1 for h in chain):
                    break
                await asyncio.sleep(0.05)
            assert d.node_id not in a.nodes, "test needs A !~ D initially"
            found = await a.discover(d.node_id)
            ids = [pid for pid, _ in found]
            assert d.node_id in ids, "iterative lookup must surface D"
            assert found[0][0] == d.node_id, "D is closest to its own id"
            # the lookup dialed through the chain: A is now connected to D
            assert d.node_id in a.nodes
        finally:
            for h in chain:
                await h.stop()

    asyncio.run(go())


def test_sixteen_node_mesh_beats_flood_duplication():
    """16 fully-meshed nodes, degree-4 gossip: total deliveries per
    message stay well under flood's edge count (VERDICT item 7)."""

    async def go():
        n = 16
        hosts = [await _mk_host(GEN[:20], heartbeat=0.15, degree=4,
                                min_peers=n - 1)]
        addr0 = f"127.0.0.1:{hosts[0].address[1]}"
        for _ in range(n - 1):
            hosts.append(await _mk_host(GEN[:20], [addr0], heartbeat=0.15,
                                        degree=4, min_peers=n - 1))
        gots = []
        for h in hosts:
            got = {}
            gots.append(got)
            h.join_pubsub(_counting_pubsub(h.node_id, got))
        try:
            # peer exchange spreads addresses; wait for a well-connected
            # overlay (>= 8 peers each is plenty connected for the test)
            for _ in range(300):
                if all(len(h.nodes) >= 8 for h in hosts):
                    break
                await asyncio.sleep(0.05)
            assert all(len(h.nodes) >= 8 for h in hosts), \
                [len(h.nodes) for h in hosts]
            # warmup traffic so every node learns the topic and the
            # heartbeats build the meshes BEFORE the measured burst (the
            # first messages on a topic flood by design)
            for i in range(4):
                await hosts[i]._pubsub.publish("t1", b"warmup-%d" % i)
            await asyncio.sleep(1.0)
            assert all(h.gossip.mesh.get("t1") for h in hosts)
            for h in hosts:
                h.stats.update(gossip_tx=0, gossip_rx=0, gossip_dup=0)
            msgs = [b"msg-%03d" % i for i in range(20)]
            for i, m in enumerate(msgs):
                await hosts[i % n]._pubsub.publish("t1", m)
            deadline = 400  # generous: repair may lag on a loaded machine
            for _ in range(deadline):
                if all(all(m in g for m in msgs) for g in gots):
                    break
                await asyncio.sleep(0.05)
            assert all(all(m in g for m in msgs) for g in gots), \
                "every node must converge on every message"
            # duplication: copies RECEIVED network-wide per message.
            # flood over this ~fully-connected overlay costs ~one copy
            # per edge per message: sum(deg)/2 ≈ n*(n-1)/2 copies. The
            # degree-bounded mesh keeps it near n*(degree+2)/2.
            total_rx = sum(h.stats["gossip_rx"] for h in hosts)
            per_msg = total_rx / len(msgs)
            edges = sum(len(h.nodes) for h in hosts) / 2
            assert per_msg < 0.62 * edges, \
                f"per-msg copies {per_msg:.1f} vs flood bound {edges:.1f}"
        finally:
            for h in hosts:
                await h.stop()

    asyncio.run(go())


def test_topic_spam_capped_on_both_planes():
    """Attacker-chosen topic strings must not grow the per-topic tables
    without bound — on the CONTROL plane (GRAFT past the cap answers
    PRUNE) or on the DATA plane (on_message refuses to learn new topics
    past the cap; the frame still lands in the size-bounded cache), and
    relaying (eager_targets) never creates entries at all."""
    m = GossipMesh()
    peer = b"p" * 32
    for i in range(m.MAX_TOPICS):
        m.on_message(b"%032d" % i, "t%d" % i, b"frame")
    assert len(m.mesh) == m.MAX_TOPICS
    # data-plane spam past the cap: cached but not learned
    m.on_message(b"x" * 32, "junk-data", b"frame")
    assert "junk-data" not in m.mesh and len(m.mesh) == m.MAX_TOPICS
    assert m.cache.get(b"x" * 32) is not None, "IWANT can still serve it"
    # relay path is read-only on the table
    m.eager_targets("junk-relay", {peer})
    assert "junk-relay" not in m.mesh
    # control-plane spam past the cap: GRAFT -> PRUNE, others dropped
    replies = m.on_control(peer, encode_ctrl(GRAFT, "junk-ctrl"),
                           seen=lambda mid: False)
    assert replies == [(PRUNE, "junk-ctrl", [])]
    assert "junk-ctrl" not in m.mesh
    # KNOWN topics keep working past the cap
    m.on_message(b"y" * 32, "t0", b"frame2")
    assert m.on_control(peer, encode_ctrl(GRAFT, "t0"),
                        seen=lambda mid: False) == []
    assert peer in m.mesh["t0"]
