"""Peersync clock-drift detection (reference timesync/peersync)."""

import asyncio

from spacemesh_tpu.node.peersync import PeerSync
from spacemesh_tpu.p2p.fetch import Fetch
from spacemesh_tpu.p2p.server import LoopbackNet, Server


def _pair(offset_b: float):
    """Two connected servers; B's wall clock runs ``offset_b`` ahead."""
    net = LoopbackNet()
    a = Server(b"a" * 32)
    b = Server(b"b" * 32)
    net.join(a)
    net.join(b)
    base = [1000.0]

    def wall_a():
        return base[0]

    def wall_b():
        return base[0] + offset_b

    # min_peers=1: the pair has a single peer (production default is a
    # 3-response quorum)
    ps_a = PeerSync(a, Fetch(a), wall=wall_a, max_drift=5.0, min_peers=1)
    PeerSync(b, Fetch(b), wall=wall_b, max_drift=5.0, min_peers=1)
    return ps_a


def test_no_drift_measures_near_zero():
    ps = _pair(offset_b=0.0)
    offset = asyncio.run(ps.check())
    assert offset is not None
    assert abs(offset) < 0.5


def test_skewed_peer_detected():
    ps = _pair(offset_b=42.0)
    offset = asyncio.run(ps.check())
    assert offset is not None
    assert 41.0 < offset < 43.0


def test_drift_callback_fires():
    drifts = []
    ps = _pair(offset_b=42.0)
    ps.on_drift = drifts.append
    ps.interval = 0.01

    async def go():
        task = asyncio.ensure_future(ps.run())
        await asyncio.sleep(0.05)
        ps.stop()
        task.cancel()

    asyncio.run(go())
    assert drifts and 41.0 < drifts[0] < 43.0


def test_unreachable_peers_yield_no_verdict():
    net = LoopbackNet()
    a = Server(b"a" * 32)
    net.join(a)  # alone: no peers to sample
    ps = PeerSync(a, Fetch(a), max_drift=5.0)
    assert asyncio.run(ps.check()) is None
