"""Hare protocol/committee upgrade mid-run (VERDICT r3 item 6).

Reference semantics: hare4/hare.go:52 CommitteeUpgrade (committee size
switches at a configured layer) and node/node.go:915-943 (hare3 serves
layers below the hare4 enable layer, hare4 takes over from it). Here the
equivalents are Hare.committee_for (committee_upgrade=[layer, size]) and
Hare.compact_for (compact_enable_layer): both flip at a layer boundary,
network-wide, from config. The test runs a two-smesher network across
BOTH flips and checks no layer is lost around the boundary and the nodes
keep converging.
"""

import asyncio
import hashlib

import pytest

from spacemesh_tpu.core.signing import EdSigner
from spacemesh_tpu.node import clock as clock_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.p2p.pubsub import LoopbackHub, PubSub
from spacemesh_tpu.p2p.server import LoopbackNet
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.utils.vclock import VirtualClockLoop, cancel_all_tasks

LPE = 3
LAYER_SEC = 2.0
GENESIS_PLACEHOLDER = 1_700_000_900.0
FLIP_LAYER = 2 * LPE + 1   # both upgrades take effect here, mid-epoch
UNTIL = 4 * LPE + 1        # two full epochs past the flip: eligibility is
                           # a per-slot VRF draw, so the post-flip window
                           # must span enough slots that "some layer got a
                           # block" is not one die roll (ADVICE r5)

# Fixed smesher identities: with random per-run keys the VRF proposal-slot
# and hare-committee draws in the post-flip window are a fresh gamble every
# run (the flake ADVICE r5 calls out). These seeds produced blocks on both
# sides of the flip across repeated runs with this exact config.
SEED_A = hashlib.sha256(b"hare-upgrade-smesher-a").digest()
SEED_B = hashlib.sha256(b"hare-upgrade-smesher-b").digest()


def _config(tmp_path, name):
    return load("standalone", overrides={
        "data_dir": str(tmp_path / name),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": GENESIS_PLACEHOLDER},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": True, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.2,
                 "preround_delay": 0.5, "iteration_limit": 2,
                 "committee_upgrade": [FLIP_LAYER, 12],
                 "compact_enable_layer": FLIP_LAYER},
        "beacon": {"proposal_duration": 0.2},
        "tortoise": {"hdist": 4, "window_size": 50},
    })


@pytest.fixture(scope="module")
def upgraded_network(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("hare_upgrade")
    loop = VirtualClockLoop()
    hub = LoopbackHub()
    net = LoopbackNet()

    def make(name, seed):
        cfg = _config(tmp, name)
        signer = EdSigner(seed=seed, prefix=cfg.genesis.genesis_id)
        ps = PubSub(node_name=signer.node_id)
        hub.join(ps)
        app = App(cfg, signer=signer, pubsub=ps, time_source=loop.time)
        app.connect_network(net)
        return app

    a, b = make("a", SEED_A), make("b", SEED_B)

    async def go():
        await asyncio.gather(a.prepare(), b.prepare())
        genesis = loop.time() + 1.0
        for app in (a, b):
            app.clock = clock_mod.LayerClock(genesis, LAYER_SEC,
                                             time_source=loop.time)
        await asyncio.gather(a.run(until_layer=UNTIL),
                             b.run(until_layer=UNTIL))

    try:
        loop.run_until_complete(asyncio.wait_for(go(), 10_000))
    finally:
        loop.run_until_complete(cancel_all_tasks())
    return a, b


def test_flip_is_configured_at_the_boundary(upgraded_network):
    a, _ = upgraded_network
    assert a.hare.committee_for(FLIP_LAYER - 1) == 20
    assert a.hare.committee_for(FLIP_LAYER) == 12
    assert not a.hare.compact_for(FLIP_LAYER - 1)
    assert a.hare.compact_for(FLIP_LAYER)


def test_no_layer_lost_across_the_flip(upgraded_network):
    """Every layer in a window straddling the flip must have been
    applied — the upgrade must not stall hare or the mesh."""
    a, b = upgraded_network
    for app in (a, b):
        for layer in range(FLIP_LAYER - 2, FLIP_LAYER + 2):
            assert layerstore.applied_block(app.state, layer) is not None, \
                f"layer {layer} lost across the upgrade"


def test_consensus_on_both_sides_of_the_flip(upgraded_network):
    """Blocks keep converging between the nodes before AND after the
    switch, and both sides actually produced blocks (the flip did not
    silently degrade every post-flip layer to empty)."""
    a, b = upgraded_network
    pre = [lyr for lyr in range(LPE, FLIP_LAYER)
           if blockstore.ids_in_layer(a.state, lyr)]
    post = [lyr for lyr in range(FLIP_LAYER, UNTIL + 1)
            if blockstore.ids_in_layer(a.state, lyr)]
    assert pre, "no pre-flip blocks"
    assert post, "no post-flip blocks"
    for lyr in pre + post:
        assert blockstore.ids_in_layer(a.state, lyr) \
            == blockstore.ids_in_layer(b.state, lyr), \
            f"layer {lyr}: nodes disagree on blocks"
