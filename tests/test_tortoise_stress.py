"""Mainnet-shape tortoise stress (VERDICT r3 item 4).

Reference yardstick: tortoise/tortoise_test.go BenchmarkTallyVotes
(the reference keeps ~12k LoC of graph state; this design must hold the
same shape in a dense matrix without quadratic tally time or unbounded
RSS). Shape here: ~50 ballots/layer, 3 blocks/layer, 10k ATXs/epoch,
1000 layers with a 600-layer window so eviction cycles several times.

Quick mode (default, CI): 300 layers. Full mainnet shape:
SPACEMESH_STRESS_FULL=1 — numbers recorded in docs/TORTOISE_STRESS.md.
"""

import os
import resource
import time

import numpy as np

from spacemesh_tpu.consensus.tortoise import Tortoise
from spacemesh_tpu.core.types import Opinion
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo

FULL = os.environ.get("SPACEMESH_STRESS_FULL") == "1"
LAYERS = 1000 if FULL else 300
WINDOW = 600 if FULL else 150
BALLOTS = 50
BLOCKS = 3
LPE = 100
ATXS_PER_EPOCH = 10_000 if FULL else 2_000


def _rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024


def _bid(layer, i):
    return b"B%07d%03d" % (layer, i) + bytes(18)


def _ballot_id(layer, i):
    return b"L%07d%03d" % (layer, i) + bytes(18)


def _run(layers=LAYERS, window=WINDOW, on_layer=None):
    cache = AtxCache()
    for epoch in range((layers // LPE) + 2):
        for i in range(ATXS_PER_EPOCH):
            nid = b"N%05d" % i + bytes(26)
            cache.add(epoch, b"A%05d%04d" % (i, epoch) + bytes(22),
                      AtxInfo(node_id=nid, weight=100, base_height=0,
                              height=1, num_units=1, vrf_nonce=0,
                              vrf_public_key=nid))
    t = Tortoise(cache, LPE, hdist=10, zdist=8, window=window)
    rng = np.random.default_rng(7)
    prev_ballot = None
    tally_times = []
    for layer in range(1, layers + 1):
        hare_block = _bid(layer, 0)
        for i in range(BLOCKS):
            t.on_block(layer, _bid(layer, i))
        t.on_hare_output(layer, hare_block)
        t.on_weak_coin(layer, bool(rng.integers(2)))
        base = prev_ballot if prev_ballot else b""
        support = [_bid(layer - 1, 0)] if layer > 1 else []
        for i in range(BALLOTS):
            bid = _ballot_id(layer, i)
            op = Opinion(base=base if base else bytes(32),
                         support=list(support), against=[], abstain=[])
            t._ingest(bid, layer, b"N%05d" % (i % ATXS_PER_EPOCH)
                      + bytes(26), op, weight=100)
        prev_ballot = _ballot_id(layer, 0)
        t0 = time.perf_counter()
        t.tally_votes(layer)
        tally_times.append(time.perf_counter() - t0)
        t.updates()  # drain, as the mesh does
        if on_layer:
            on_layer(t, layer)
    return t, tally_times


def test_stress_tally_time_and_rss():
    rss_samples = []

    def sample(t, layer):
        if layer % 50 == 0:
            rss_samples.append((layer, _rss_mb(), t._rows, t._cols,
                               len(t._ballots)))

    t0 = time.perf_counter()
    t, times = _run(on_layer=sample)
    total = time.perf_counter() - t0

    # frontier keeps up: everything but the hdist tail is verified
    assert t.verified >= LAYERS - t.hdist - 1, t.verified

    # steady-state tally time per layer stays flat: the mean of the last
    # quarter must not exceed 4x the mean of the second quarter (a
    # quadratic tally fails this immediately) and stays under an absolute
    # per-layer budget
    q = len(times) // 4
    early = sum(times[q:2 * q]) / q
    late = sum(times[-q:]) / q
    assert late < early * 4 + 0.05, (early, late)
    assert late < 0.25, f"steady-state tally {late * 1000:.1f}ms/layer"

    # the window bounds live state: ballots/blocks in memory never exceed
    # window * per-layer rate (+ the eviction-hysteresis chunk and the
    # pre-eviction ramp)
    slack = WINDOW + max(WINDOW // 10, 16) + t.hdist + 2
    assert len(t._ballots) <= slack * BALLOTS
    assert t._cols <= slack * BLOCKS
    # aux maps are evicted too (hare outputs, validity, coins)
    assert len(t._hare) <= slack
    assert len(t._validity) <= slack * BLOCKS
    assert len(t._coin) <= slack

    if os.environ.get("SPACEMESH_STRESS_REPORT"):
        import json
        print(json.dumps({
            "layers": LAYERS, "window": WINDOW, "ballots_per_layer": BALLOTS,
            "blocks_per_layer": BLOCKS, "atxs_per_epoch": ATXS_PER_EPOCH,
            "total_s": round(total, 2),
            "tally_ms_mean": round(sum(times) / len(times) * 1000, 3),
            "tally_ms_p99": round(sorted(times)[int(len(times) * .99)] * 1000,
                                  3),
            "rss_mb_final": round(_rss_mb(), 1),
            "rss_samples": [(x, round(m, 1), r, c, nb)
                            for x, m, r, c, nb in rss_samples],
        }))


def test_window_slide_eviction_keeps_consistency():
    """After the window slides, evicted layers stay decided (validity was
    drained via updates) and the matrix only holds in-window state."""
    t, _ = _run(layers=2 * WINDOW, window=WINDOW)
    low = t.verified - t.window - max(t.window // 10, 16)  # hysteresis
    assert min(t._ballots_by_layer) >= low
    assert min(t._blocks) >= low
    assert all(int(t._col_layer[c]) >= low for c in range(t._cols))
    # still live: new layers keep verifying after several slides
    assert t.verified >= 2 * WINDOW - t.hdist - 1


def test_dirty_retally_crossing_eviction_edge():
    """Late evidence (malfeasance) marks layers at the eviction edge
    dirty; the re-tally must clamp to retained state, not crash, and the
    frontier must recover."""
    t, _ = _run(layers=WINDOW + 60, window=WINDOW)
    before = t.verified
    # condemn an identity whose ballots span every layer incl. evicted
    t.on_malfeasance(b"N%05d" % 1 + bytes(26))
    assert t._dirty is not None and t._dirty <= before - t.window + 1
    t.tally_votes(WINDOW + 60)
    assert t.verified >= before - t.hdist  # frontier recovers
    # the zeroed weight is visible in the retained matrix
    rows = t._node_rows.get(b"N%05d" % 1 + bytes(26), [])
    assert rows and all(t._weights[r] == 0 for r in rows)


def test_late_ballot_below_eviction_edge_is_safe():
    """A ballot arriving for a layer already evicted must not corrupt
    state or un-verify the frontier."""
    t, _ = _run(layers=WINDOW + 60, window=WINDOW)
    before = t.verified
    low = before - t.window
    old_layer = max(low - 5, 1)
    op = Opinion(base=bytes(32), support=[], against=[], abstain=[])
    t._ingest(b"LATE" + bytes(28), old_layer, b"N%05d" % 2 + bytes(26),
              op, weight=100)
    t.tally_votes(WINDOW + 60)
    assert t.verified >= before - t.hdist
