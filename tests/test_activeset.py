"""Active-set generation + min-weight gating (VERDICT r3 item 8).

Reference: miner/active_set_generator.go (grading + three-path
generation), miner/minweight/minweight.go (epoch table),
proposals/util/util.go:29-39 (slot formula with the min-weight
denominator)."""

import pytest

from spacemesh_tpu.consensus import activeset
from spacemesh_tpu.consensus.activeset import (
    GRADE_ACCEPTABLE,
    GRADE_EVIL,
    GRADE_GOOD,
    ActiveSetGenerator,
    active_set_hash,
    grade_atx,
    num_eligible_slots,
    select_min_weight,
)
from spacemesh_tpu.core import types
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import misc as miscstore
from spacemesh_tpu.storage.cache import AtxCache, AtxInfo

LPE = 4
LAYER_DUR = 10.0
DELAY = 5.0


def _atx(i, epoch=0, units=2):
    node = b"N%07d" % i + bytes(24)
    return types.ActivationTx(
        publish_epoch=epoch, prev_atx=bytes(32), pos_atx=bytes(32),
        commitment_atx=None, initial_post=None,
        nipost=types.NIPost(
            membership=types.MerkleProof(leaf_index=0, nodes=[]),
            post=types.Post(nonce=0, indices=[1], pow_nonce=0),
            post_metadata=types.PostMetadataWire(challenge=bytes(32),
                                                 labels_per_unit=64)),
        num_units=units, vrf_nonce=7, vrf_public_key=bytes(32),
        coinbase=bytes(24), node_id=node,
        signature=bytes(64))


def test_select_min_weight_table():
    table = [(0, 100), (4, 1000), (8, 5000)]
    assert select_min_weight(0, table) == 100
    assert select_min_weight(3, table) == 100
    assert select_min_weight(4, table) == 1000
    assert select_min_weight(9, table) == 5000
    assert select_min_weight(5, []) == 0
    with pytest.raises(ValueError):
        select_min_weight(1, [(4, 10), (0, 5)])


def test_num_eligible_slots_minweight_gates_dust():
    # young network: total weight 10, committee 50/layer, 4 layers/epoch.
    # ungated, a weight-1 identity harvests 20 slots...
    assert num_eligible_slots(1, 0, 10, 50, 4) == 20
    # ...the mainnet-scale min-weight floor collapses that to the
    # reference's single-slot floor (proposals/util/util.go:36-38)
    assert num_eligible_slots(1, 10_000, 10, 50, 4) == 1
    # and a real miner is proportional against the floor, not the dust net
    assert num_eligible_slots(5_000, 10_000, 10, 50, 4) == 100
    assert num_eligible_slots(5_000, 0, 0, 50, 4) == 0


def test_grade_atx_boundaries():
    s = 1000.0
    # good: atx < s-4d, no proof before s
    assert grade_atx(s, DELAY, 979.9, None) == GRADE_GOOD
    assert grade_atx(s, DELAY, 979.9, 1000.0) == GRADE_GOOD
    # proof strictly before s demotes: acceptable if proof >= s-d
    assert grade_atx(s, DELAY, 979.9, 996.0) == GRADE_ACCEPTABLE
    # proof before s-d: evil
    assert grade_atx(s, DELAY, 979.9, 990.0) == GRADE_EVIL
    # received in (s-4d, s-3d): at best acceptable
    assert grade_atx(s, DELAY, 982.0, None) == GRADE_ACCEPTABLE
    assert grade_atx(s, DELAY, 982.0, 990.0) == GRADE_EVIL
    # received after s-3d: evil
    assert grade_atx(s, DELAY, 986.0, None) == GRADE_EVIL


def _setup(n_good=3, n_late=1, target=1):
    state = dbmod.open_state()
    local = dbmod.open_local()
    cache = AtxCache()
    epoch_start = target * LPE * LAYER_DUR  # genesis_time = 0
    ids = []
    for i in range(n_good + n_late):
        atx = _atx(i, epoch=target - 1)
        received = epoch_start - 4 * DELAY - 1 if i < n_good \
            else epoch_start - 1
        atxstore.add(state, atx, received=int(received))
        cache.add(target, atx.id, AtxInfo(
            node_id=atx.node_id, weight=10, base_height=0, height=1,
            num_units=atx.num_units, vrf_nonce=0,
            vrf_public_key=atx.node_id))
        ids.append(atx.id)
    gen = ActiveSetGenerator(
        state, local, cache, layers_per_epoch=LPE, layer_duration=LAYER_DUR,
        genesis_time=0.0, network_delay=DELAY, good_atx_percent=50)
    return state, local, cache, gen, ids


def test_generate_from_grades_and_persistence():
    state, local, cache, gen, ids = _setup(n_good=3, n_late=1)
    set_id, weight, got = gen.generate(current_layer=3, target_epoch=1)
    assert sorted(got) == sorted(ids[:3])   # late ATX graded out
    assert weight == 30
    assert set_id == active_set_hash(got)
    # persisted: a fresh generator over the same local db returns it
    # without touching grading again
    gen2 = ActiveSetGenerator(
        state, local, AtxCache(), layers_per_epoch=LPE,
        layer_duration=LAYER_DUR, genesis_time=0.0, network_delay=DELAY)
    assert gen2.generate(3, 1) == (set_id, weight, got)


def test_generate_gate_fails_when_too_few_good():
    # 1 good / 4 total = 25% < 50% gate, and no block yet -> LookupError
    state, local, cache, gen, ids = _setup(n_good=1, n_late=3)
    with pytest.raises(LookupError):
        gen.generate(current_layer=3, target_epoch=1)


def test_fallback_wins_over_grading():
    state, local, cache, gen, ids = _setup(n_good=3, n_late=1)
    gen.update_fallback(1, [ids[0], ids[3]])
    set_id, weight, got = gen.generate(3, 1)
    assert sorted(got) == sorted([ids[0], ids[3]])
    assert weight == 20
    # first update wins (generator.go:86-90)
    gen.update_fallback(1, [ids[1]])
    assert gen._fallback[1] == [ids[0], ids[3]]


def test_malfeasance_proof_receipt_grades_out():
    state, local, cache, gen, ids = _setup(n_good=3, n_late=0)
    # condemn the second identity well before epoch start
    view = atxstore.view(state, ids[1])
    from spacemesh_tpu.core.types import MalfeasanceProof
    miscstore.set_malicious(
        state, view.node_id,
        MalfeasanceProof(domain=1, msg1=b"a", sig1=bytes(64), msg2=b"b",
                         sig2=bytes(64), node_id=view.node_id), received=1)
    set_id, weight, got = gen.generate(3, 1)  # 2/3 good clears the gate
    assert sorted(got) == sorted([ids[0], ids[2]])


def test_from_first_block_path():
    from spacemesh_tpu.storage import ballots as ballotstore
    from spacemesh_tpu.storage import blocks as blockstore
    from spacemesh_tpu.storage import layers as layerstore

    state, local, cache, gen, ids = _setup(n_good=1, n_late=3)  # gate fails
    # a ref ballot built on ids[0] declaring a stored active set
    stored = sorted(ids[:3])
    root = active_set_hash(stored)
    miscstore.add_active_set(state, root, 1, stored)
    ballot = types.Ballot(
        layer=LPE, atx_id=ids[0],
        epoch_data=types.EpochData(beacon=b"\x01" * 4, active_set_root=root,
                                   eligibility_count=1),
        ref_ballot=types.EMPTY32, eligibilities=[],
        opinion=types.Opinion(base=types.EMPTY32, support=[], against=[],
                              abstain=[]),
        node_id=b"N%07d" % 0 + bytes(24), signature=bytes(64))
    ballotstore.add(state, ballot)
    block = types.Block(
        layer=LPE, tick_height=1,
        rewards=[types.Reward(atx_id=ids[0], coinbase=bytes(24), weight=1)],
        tx_ids=[])
    blockstore.add(state, block)
    layerstore.set_applied(state, LPE, block.id, bytes(32))
    set_id, weight, got = gen.generate(current_layer=LPE + 1, target_epoch=1)
    assert sorted(got) == stored
    assert weight == 30


def test_declared_set_denominator_overrides_local_view():
    """A validator whose local ATX view carries MORE weight than the
    ballot's declared active set must still size slot counts against the
    declared set (ADVICE r4) — divergent ATX views must not make nodes
    disagree on ballot validity when the set resolves."""
    from spacemesh_tpu.consensus.activeset import (active_set_hash,
                                                   declared_set_weight)
    from spacemesh_tpu.consensus.eligibility import Oracle
    from spacemesh_tpu.storage import db as dbmod
    from spacemesh_tpu.storage import misc as miscstore

    db = dbmod.open_state(":memory:")
    cache = AtxCache()

    def info(w):
        return AtxInfo(node_id=b"n" * 32, weight=w, base_height=0,
                       height=1, num_units=1, vrf_nonce=0,
                       vrf_public_key=b"n" * 32)

    a, b, c = b"A" * 32, b"B" * 32, b"C" * 32
    cache.add(1, a, info(100))
    cache.add(1, b, info(100))
    cache.add(1, c, info(800))  # local-only ATX the ballot did not declare

    declared = sorted([a, b])
    root = active_set_hash(declared)
    miscstore.add_active_set(db, root, 1, declared)
    assert declared_set_weight(db, cache, 1, root) == 200

    # declared denominators require a nonzero consensus floor (the
    # dust-set defense); 50 < any honest total here, so it never binds
    oracle = Oracle(cache, LPE, slots_per_layer=10,
                    min_weight_table=[(0, 50)])
    # local denominator 1000 vs declared 200: 5x more slots
    assert oracle.num_slots(1, a) == 100 * 10 * LPE // 1000
    assert oracle.num_slots(1, a, 200) == 100 * 10 * LPE // 200

    # unknown root or unresolvable member -> None (caller falls back)
    assert declared_set_weight(db, cache, 1, b"x" * 32) is None
    root2 = active_set_hash(sorted([a, b"Z" * 32]))
    miscstore.add_active_set(db, root2, 1, sorted([a, b"Z" * 32]))
    assert declared_set_weight(db, cache, 1, root2) is None
    db.close()


def test_handler_fetches_unresolved_declared_set():
    """A ballot declaring an active set the validator has not stored
    triggers a fetch by root; once stored, the declared denominator is
    used (code-review r5: without the fetch, validators silently fall
    back to local weight and disagree with the builder)."""
    import asyncio

    from spacemesh_tpu.consensus.activeset import active_set_hash
    from spacemesh_tpu.consensus.eligibility import Oracle
    from spacemesh_tpu.consensus.miner import ProposalHandler
    from spacemesh_tpu.storage import db as dbmod
    from spacemesh_tpu.storage import misc as miscstore

    db = dbmod.open_state(":memory:")
    cache = AtxCache()
    a = b"A" * 32
    cache.add(1, a, AtxInfo(node_id=b"n" * 32, weight=100, base_height=0,
                            height=1, num_units=1, vrf_nonce=0,
                            vrf_public_key=b"n" * 32))
    root = active_set_hash([a])

    class _Hub:
        def register(self, topic, fn):
            pass

    handler = ProposalHandler(
        db=db, cache=cache,
        oracle=Oracle(cache, LPE, min_weight_table=[(0, 10)]),
        tortoise=None, store=None, verifier=None, pubsub=_Hub(),
        layers_per_epoch=LPE, beacon_getter=None)
    calls = []

    async def fake_fetch(r):
        calls.append(r)
        miscstore.add_active_set(db, r, -1, [a])  # what v_active_set does
        return True

    handler.fetch_active_set = fake_fetch
    ed = types.EpochData(beacon=b"\x01" * 4, active_set_root=root,
                         eligibility_count=1)
    total = asyncio.run(handler._declared_set_weight(1, ed))
    assert calls == [root]
    assert total == 100
    # second resolution hits the store, no re-fetch
    assert asyncio.run(handler._declared_set_weight(1, ed)) == 100
    assert calls == [root]
    db.close()


def test_dust_declared_set_cannot_shrink_denominator():
    """Security (code-review r5): an attacker declaring a dust active
    set (only their own ATX) must not collect the epoch's whole slot
    allotment. Two defenses: without a consensus min-weight floor the
    declared total is IGNORED (local weight used); with a floor, the
    floor caps the amplification via max(floor, declared)."""
    from spacemesh_tpu.consensus.eligibility import Oracle

    cache = AtxCache()
    attacker = b"E" * 32
    cache.add(1, attacker, AtxInfo(node_id=b"e" * 32, weight=10,
                                   base_height=0, height=1, num_units=1,
                                   vrf_nonce=0, vrf_public_key=b"e" * 32))
    for i in range(9):  # honest weight dwarfs the attacker
        cache.add(1, bytes([i]) * 32,
                  AtxInfo(node_id=bytes([i]) * 32, weight=1000,
                          base_height=0, height=1, num_units=1,
                          vrf_nonce=0, vrf_public_key=bytes([i]) * 32))

    # no floor configured: the declared dust total is not trusted
    o_nofloor = Oracle(cache, LPE, slots_per_layer=10)
    assert not o_nofloor.trusts_declared(1)
    assert o_nofloor.num_slots(1, attacker, 10) \
        == o_nofloor.num_slots(1, attacker)

    # floor configured: denominator = max(5000, 10), not 10
    o_floor = Oracle(cache, LPE, slots_per_layer=10,
                     min_weight_table=[(0, 5000)])
    assert o_floor.trusts_declared(1)
    slots = o_floor.num_slots(1, attacker, 10)
    assert slots == max(10 * 10 * LPE // 5000, 1)
    assert slots < 10 * LPE  # nowhere near the epoch allotment
