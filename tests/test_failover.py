"""verifyd failover client + shed-retry policy (ISSUE 15).

The FailoverVerifier's routing contract on an injected clock — remote
while healthy, breaker-guarded local fallback on typed sheds/transport
errors/deadline misses, half-open probe honoring ``retry_after_s``,
failback on recovery — and the cookbook client's bounded
``retry_after_s``-honoring backoff (the sleeps asserted against the
shared ``backoff_delay`` rule, zero real sleeping).  Verdict
bit-identity remote-vs-local at workload scale is the verifyd-outage
sim scenario's job (tests/test_sim_scenarios.py).
"""

import asyncio

import pytest

from spacemesh_tpu.obs import remediate
from spacemesh_tpu.utils import metrics
from spacemesh_tpu.verify.farm import Lane
from spacemesh_tpu.verifyd.client import RetryPolicy, VerifydClient
from spacemesh_tpu.verifyd.failover import FailoverVerifier
from spacemesh_tpu.verifyd.service import Shed


class Clock:
    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


class FakeReq:
    kind = "sig"

    def __init__(self, i: int):
        self.i = i


class FakeRemote:
    """Scriptable remote endpoint: verdict = (i % 2 == 0)."""

    def __init__(self):
        self.calls = 0
        self.registers = 0
        self.fail_with = None       # exception instance to raise

    async def register(self):
        self.registers += 1

    async def verify(self, reqs, *, lane="gossip", deadline_s=None):
        self.calls += 1
        if self.fail_with is not None:
            raise self.fail_with
        return [r.i % 2 == 0 for r in reqs]


class FakeFarm:
    """Local twin computing the SAME verdicts (the farm contract)."""

    def __init__(self):
        self.submits = 0

    async def submit(self, req, lane=Lane.GOSSIP) -> bool:
        self.submits += 1
        return req.i % 2 == 0


def _fv(clock, **br_kw):
    br_kw.setdefault("failure_budget", 2)
    br_kw.setdefault("cooldown_s", 4.0)
    br_kw.setdefault("cooldown_cap_s", 8.0)
    remote, farm = FakeRemote(), FakeFarm()
    breaker = remediate.CircuitBreaker(
        "verifyd.remote", time_source=clock.now, window_s=60.0, **br_kw)
    fv = FailoverVerifier(remote=remote, farm=farm, breaker=breaker,
                          time_source=clock.now)
    return fv, remote, farm


REQS = [FakeReq(i) for i in range(4)]
WANT = [True, False, True, False]


def test_remote_path_serves_and_registers_once():
    async def run():
        clock = Clock()
        fv, remote, farm = _fv(clock)
        assert await fv.verify_batch(REQS, Lane.BLOCK) == WANT
        assert await fv.submit(FakeReq(2)) is True
        assert remote.calls == 2 and remote.registers == 1
        assert farm.submits == 0
        assert fv.stats["remote_ok"] == 2

    asyncio.run(run())


def test_transport_error_falls_back_same_call_then_breaker_opens():
    async def run():
        clock = Clock()
        fv, remote, farm = _fv(clock)
        remote.fail_with = ConnectionError("down")
        # budget 2: both failing calls STILL answer (local), then open
        for _ in range(2):
            assert await fv.verify_batch(REQS) == WANT
        assert fv.breaker.state == remediate.OPEN
        assert remote.calls == 2 and farm.submits == 8
        # open: straight to local, the dead service is not re-paid
        for _ in range(5):
            assert await fv.verify_batch(REQS) == WANT
        assert remote.calls == 2
        assert fv.stats["local_fastfail"] == 5

    asyncio.run(run())


def test_typed_shed_trips_and_retry_after_floors_the_probe():
    async def run():
        clock = Clock()
        fv, remote, farm = _fv(clock, failure_budget=1,
                               cooldown_s=1.0, cooldown_cap_s=60.0)
        remote.fail_with = Shed("overload", "busy", retry_after_s=30.0)
        assert await fv.verify_batch(REQS) == WANT   # local answer
        assert fv.breaker.state == remediate.OPEN
        # the shed's hint drives the half-open probe timing
        assert fv.breaker.retry_in() >= 30.0
        clock.advance(29.0)
        assert await fv.verify_batch(REQS) == WANT
        assert remote.calls == 1                     # still open
        clock.advance(2.0)
        remote.fail_with = None
        assert await fv.verify_batch(REQS) == WANT   # the probe
        assert remote.calls == 2
        assert fv.breaker.state == remediate.CLOSED
        assert fv.stats["failbacks"] == 1
        # failed back: remote serves again
        assert await fv.verify_batch(REQS) == WANT
        assert remote.calls == 3

    asyncio.run(run())


def test_non_tripping_shed_serves_locally_and_reregisters():
    async def run():
        clock = Clock()
        fv, remote, farm = _fv(clock)
        assert await fv.verify_batch(REQS) == WANT
        remote.fail_with = Shed("unregistered", "who?")
        assert await fv.verify_batch(REQS) == WANT   # local, no trip
        assert fv.breaker.state == remediate.CLOSED
        remote.fail_with = None
        assert await fv.verify_batch(REQS) == WANT
        assert remote.registers == 2                 # re-registered

    asyncio.run(run())


def test_non_tripping_shed_during_probe_does_not_wedge_breaker():
    """The review-confirmed leak: a half-open probe answered with a
    config-class shed must RELEASE the probe slot — a verifyd restart
    that wiped its client registry must not strand the node on the
    local farm forever."""

    async def run():
        clock = Clock()
        fv, remote, farm = _fv(clock, failure_budget=1, cooldown_s=1.0,
                               cooldown_cap_s=2.0)
        remote.fail_with = ConnectionError("down")
        assert await fv.verify_batch(REQS) == WANT
        assert fv.breaker.state == remediate.OPEN
        clock.advance(2.5)
        # the service is back but restarted: the probe gets a
        # registry-wipe shed, not a verdict
        remote.fail_with = Shed("unregistered", "registry wiped")
        assert await fv.verify_batch(REQS) == WANT   # local answer
        # NOT wedged: the very next call may probe again, re-registers,
        # succeeds, and traffic fails back to remote
        remote.fail_with = None
        before = remote.calls
        assert await fv.verify_batch(REQS) == WANT
        assert remote.calls == before + 1
        assert fv.breaker.state == remediate.CLOSED
        assert await fv.verify_batch(REQS) == WANT
        assert remote.calls == before + 2

    asyncio.run(run())


def test_cancelled_probe_releases_the_slot():
    async def run():
        clock = Clock()
        fv, remote, farm = _fv(clock, failure_budget=1, cooldown_s=1.0,
                               cooldown_cap_s=2.0)
        remote.fail_with = ConnectionError("down")
        await fv.verify_batch(REQS)
        clock.advance(2.5)
        remote.fail_with = None
        hang = asyncio.Event()

        async def hung_verify(reqs, *, lane="gossip", deadline_s=None):
            hang.set()
            await asyncio.sleep(3600)

        remote.verify = hung_verify
        task = asyncio.ensure_future(fv.verify_batch(REQS))
        await hang.wait()                   # the probe is in flight
        task.cancel()
        with pytest.raises(asyncio.CancelledError):
            await task
        # the slot came back: a later caller can probe
        assert fv.breaker.state == remediate.HALF_OPEN
        assert fv.breaker.allow()

    asyncio.run(run())


def test_deadline_miss_trips_breaker():
    async def run():
        clock = Clock()
        remote, farm = FakeRemote(), FakeFarm()

        async def slow_verify(reqs, *, lane="gossip", deadline_s=None):
            await asyncio.sleep(30)

        remote.verify = slow_verify
        fv = FailoverVerifier(
            remote=remote, farm=farm, deadline_s=0.05,
            breaker=remediate.CircuitBreaker(
                "verifyd.remote", failure_budget=1,
                time_source=clock.now),
            time_source=clock.now)
        assert await fv.verify_batch(REQS) == WANT
        assert fv.breaker.state == remediate.OPEN
        assert fv.stats["remote_failed"] == 1

    asyncio.run(run())


def test_start_aclose_registry_and_metrics_lifecycle():
    async def run():
        clock = Clock()
        fv, remote, farm = _fv(clock)
        fv.start()
        assert "verifyd.remote" in remediate.BREAKERS.names()
        key = (("component", "verifyd.remote"),)
        assert key in metrics.remediation_breaker_state.sample()
        await fv.verify_batch(REQS, Lane.BLOCK)
        assert metrics.failover_requests.sample()[
            (("lane", "block"), ("path", "remote"))] >= 1
        await fv.aclose()
        assert "verifyd.remote" not in remediate.BREAKERS.names()
        assert key not in metrics.remediation_breaker_state.sample()
        assert fv.state_doc()["breaker"]["state"] == "closed"

    asyncio.run(run())


# --- the cookbook client's shed-retry policy ----------------------------


class _ScriptedClient(VerifydClient):
    """verify() driven by a script of outcomes instead of sockets."""

    def __init__(self, outcomes, **kw):
        sleeps = []
        kw.setdefault("sleep", self._fake_sleep)
        super().__init__("http://x", "c", **kw)
        self._outcomes = list(outcomes)
        self.sleeps = sleeps
        self.attempts = 0

    async def _fake_sleep(self, s):
        self.sleeps.append(s)

    async def _verify_once(self, reqs, *, lane, deadline_s):
        self.attempts += 1
        out = self._outcomes.pop(0)
        if isinstance(out, Exception):
            raise out
        return out


def test_client_honors_retry_after_with_shared_backoff():
    async def run():
        policy = RetryPolicy(max_attempts=3, base_s=0.05, cap_s=2.0,
                             seed=11)
        c = _ScriptedClient(
            [Shed("rate", "over budget", retry_after_s=0.3),
             Shed("queue_full", "deep", retry_after_s=0.8),
             [True, False]],
            retry=policy)
        assert await c.verify(["r"]) == [True, False]
        assert c.attempts == 3
        # the waits ARE the shared rule, floored at the server's hint
        assert c.sleeps == [
            remediate.backoff_delay(0, base_s=0.05, cap_s=2.0,
                                    retry_after_s=0.3, seed=11),
            remediate.backoff_delay(1, base_s=0.05, cap_s=2.0,
                                    retry_after_s=0.8, seed=11),
        ]
        assert c.sleeps[0] >= 0.3 and c.sleeps[1] >= 0.8

    asyncio.run(run())


def test_client_attempt_budget_exhausts_and_reraises():
    async def run():
        c = _ScriptedClient(
            [Shed("rate", "x", retry_after_s=0.1)] * 5,
            retry=RetryPolicy(max_attempts=3))
        with pytest.raises(Shed) as ei:
            await c.verify(["r"])
        assert ei.value.reason == "rate"
        assert c.attempts == 3 and len(c.sleeps) == 2

    asyncio.run(run())


def test_client_gives_up_immediately_when_hint_exceeds_patience():
    """A retry_after beyond cap_s means the condition won't clear
    within this client's patience: re-raise NOW, sleep never."""

    async def run():
        c = _ScriptedClient(
            [Shed("rate", "tiny bucket", retry_after_s=3600.0)],
            retry=RetryPolicy(max_attempts=5, cap_s=2.0))
        with pytest.raises(Shed):
            await c.verify(["r"])
        assert c.attempts == 1 and c.sleeps == []

    asyncio.run(run())


def test_client_non_retryable_sheds_and_opt_out():
    async def run():
        # lifecycle sheds never retry, whatever the budget
        c = _ScriptedClient([Shed("shutting_down", "bye",
                                  retry_after_s=0.1)],
                            retry=RetryPolicy(max_attempts=5))
        with pytest.raises(Shed):
            await c.verify(["r"])
        assert c.attempts == 1
        # retry=None is the raw one-shot client
        c2 = _ScriptedClient([Shed("rate", "x", retry_after_s=0.01)],
                             retry=None)
        with pytest.raises(Shed):
            await c2.verify(["r"])
        assert c2.attempts == 1 and c2.sleeps == []

    asyncio.run(run())
