"""Streaming init pipeline: smoke, crash consistency, stop latency,
writer-pool durability ordering.

The crash-consistency tests are the contract behind interval metadata
saves (docs/POST_PIPELINE.md): kill the pipeline at various points, and a
resume from whatever metadata survived must converge to a byte-identical
label set and the same VRF nonce as an uninterrupted init — because the
persisted cursor never runs ahead of durably-written labels and the VRF
min-merge is idempotent over recomputed batches.
"""

import hashlib
import threading
import time

import numpy as np
import pytest

from spacemesh_tpu.ops import scrypt
from spacemesh_tpu.post import initializer
from spacemesh_tpu.post.data import LabelStore, PostMetadata
from spacemesh_tpu.utils import metrics

NODE = hashlib.sha256(b"pipe-node").digest()
COMMIT = hashlib.sha256(b"pipe-commitment").digest()

TOTAL = 1024
BATCH = 256
N = 2


def _init_kwargs(**over):
    kw = dict(node_id=NODE, commitment=COMMIT, num_units=1,
              labels_per_unit=TOTAL, scrypt_n=N, max_file_size=1 << 20,
              batch_size=BATCH)
    kw.update(over)
    return kw


def _disk_labels(d, count):
    meta = PostMetadata.load(d)
    store = LabelStore(d, meta)
    return store.read_labels(0, count)


@pytest.fixture(scope="module")
def reference(tmp_path_factory):
    """Uninterrupted init: the ground truth for crash/resume equivalence."""
    d = tmp_path_factory.mktemp("pipe-ref")
    meta, res = initializer.initialize(d, **_init_kwargs())
    return d, meta, res


def test_pipeline_smoke(reference):
    d, meta, res = reference
    assert meta.labels_written == TOTAL
    assert res.labels_per_s > 0
    assert res.stats is not None and res.stats.batches == TOTAL // BATCH
    got = np.frombuffer(_disk_labels(d, TOTAL), dtype=np.uint8)
    want = scrypt.scrypt_labels(COMMIT, np.arange(TOTAL, dtype=np.uint64),
                                n=N)
    assert np.array_equal(got.reshape(-1, 16), want)
    # VRF nonce: first occurrence of the LE-u128 minimum, like np.lexsort
    lo = want[:, :8].copy().view("<u8").ravel()
    hi = want[:, 8:].copy().view("<u8").ravel()
    k = int(np.lexsort((lo, hi))[0])
    assert meta.vrf_nonce == k
    assert bytes.fromhex(meta.vrf_nonce_value) == bytes(want[k])


def test_pipeline_exports_metrics(reference):
    text = metrics.REGISTRY.expose()
    assert "post_pipeline_batches_dispatched_total" in text
    assert "post_pipeline_stage_seconds_total" in text
    assert "post_pipeline_meta_saves_total" in text


class _Crash(RuntimeError):
    pass


@pytest.mark.parametrize("crash_after", [0, 1, 2])
def test_crash_resume_bit_identical(tmp_path, reference, crash_after):
    """Kill the run after N flushed batches (no orderly shutdown, no final
    metadata save); the resume must produce bit-identical labels and the
    same VRF nonce as the uninterrupted reference."""
    _, ref_meta, _ = reference
    calls = []

    def die(done, total):
        calls.append(done)
        if len(calls) > crash_after:
            raise _Crash

    with pytest.raises(_Crash):
        initializer.initialize(
            tmp_path, **_init_kwargs(progress=die),
            meta_interval_s=0.0, meta_interval_labels=1)

    # durability ordering: whatever cursor survived must be backed by
    # readable bytes on disk
    try:
        meta = PostMetadata.load(tmp_path)
    except FileNotFoundError:
        meta = None
    if meta is not None and meta.labels_written > 0:
        assert meta.labels_written < TOTAL
        got = _disk_labels(tmp_path, meta.labels_written)
        want = scrypt.scrypt_labels(
            COMMIT, np.arange(meta.labels_written, dtype=np.uint64), n=N)
        assert got == want.tobytes()

    meta2, _ = initializer.initialize(tmp_path, **_init_kwargs())
    assert meta2.labels_written == TOTAL
    assert meta2.vrf_nonce == ref_meta.vrf_nonce
    assert meta2.vrf_nonce_value == ref_meta.vrf_nonce_value
    assert _disk_labels(tmp_path, TOTAL) == _disk_labels(
        reference[0], TOTAL)


def test_crash_in_writer_surfaces_and_resumes(tmp_path, reference):
    """A failing disk write must fail the run (not hang it), leave a
    conservative cursor, and still resume cleanly."""
    _, ref_meta, _ = reference
    real = LabelStore.write_labels
    hits = []

    def flaky(self, start, labels):
        hits.append(start)
        if len(hits) > 2:
            raise IOError("disk full (injected)")
        real(self, start, labels)

    from unittest import mock
    with mock.patch.object(LabelStore, "write_labels", flaky):
        with pytest.raises(RuntimeError, match="writer failed"):
            initializer.initialize(
                tmp_path, **_init_kwargs(),
                meta_interval_s=0.0, meta_interval_labels=1)

    meta2, _ = initializer.initialize(tmp_path, **_init_kwargs())
    assert meta2.labels_written == TOTAL
    assert meta2.vrf_nonce == ref_meta.vrf_nonce
    assert _disk_labels(tmp_path, TOTAL) == _disk_labels(
        reference[0], TOTAL)


def test_stop_before_dispatch_persists_cursor(tmp_path):
    """stop() must take effect before the next batch is dispatched, and
    the discarded-pending path must still persist the flushed cursor."""
    meta = PostMetadata(node_id=NODE.hex(), commitment=COMMIT.hex(),
                        scrypt_n=N, num_units=1, labels_per_unit=TOTAL,
                        max_file_size=1 << 20)
    dispatched = []
    init = initializer.Initializer(
        tmp_path, meta, batch_size=BATCH, inflight=3,  # pin: assertions
        # below assume the window fills before the run drains
        progress=lambda done, total: (dispatched.append(done),
                                      init.stop()))
    init.run()
    assert init.status == initializer.Status.STOPPED
    # stop fired on the first flushed batch: later batches may already be
    # in flight, but nothing further was dispatched after the stop check
    assert dispatched == [BATCH]
    on_disk = PostMetadata.load(tmp_path)
    assert on_disk.labels_written == BATCH
    got = _disk_labels(tmp_path, BATCH)
    want = scrypt.scrypt_labels(COMMIT, np.arange(BATCH, dtype=np.uint64),
                                n=N)
    assert got == want.tobytes()


def test_writer_durable_cursor_is_contiguous(tmp_path):
    """durable() only advances over contiguous completed writes, even when
    pool threads complete out of order."""
    meta = PostMetadata(node_id=NODE.hex(), commitment=COMMIT.hex(),
                        scrypt_n=N, num_units=1, labels_per_unit=TOTAL,
                        max_file_size=1 << 20)
    store = LabelStore(tmp_path, meta)
    gate = threading.Event()
    real = LabelStore.write_labels

    def gated(self, start, labels):
        if start == 0:
            assert gate.wait(10)
        real(self, start, labels)

    from unittest import mock
    with mock.patch.object(LabelStore, "write_labels", gated):
        w = store.start_writer(threads=2, queue_depth=4)
        try:
            w.submit(0, bytes(BATCH * 16))
            w.submit(BATCH, bytes(BATCH * 16))
            deadline = time.monotonic() + 10
            while w.bytes_written < BATCH * 16:
                assert time.monotonic() < deadline
                time.sleep(0.01)
            # second interval done, first still gated: cursor must hold
            assert w.durable() == 0
            gate.set()
            w.drain()
            assert w.durable() == 2 * BATCH
        finally:
            gate.set()
            w.close(drain=False)


def test_interval_metadata_saves_happen_midrun(tmp_path):
    """With a tiny interval, resume metadata is rewritten during the run,
    not only at the end — and any mid-run cursor respects the durability
    rule (it can trail the dispatch frontier, never lead the disk)."""
    seen = []

    def peek(done, total):
        if done == TOTAL:  # retiring the last batch: earlier interval
            # saves must already be on disk, final save has not happened
            m = PostMetadata.load(tmp_path)
            seen.append(m.labels_written)
            assert m.labels_written < TOTAL
            if m.labels_written:
                assert _disk_labels(tmp_path, m.labels_written)

    meta, res = initializer.initialize(
        tmp_path, **_init_kwargs(progress=peek),
        meta_interval_s=0.0, meta_interval_labels=1)
    assert seen, "progress callback never fired for the last batch"
    assert res.stats is not None and res.stats.meta_saves >= 2
    assert meta.labels_written == TOTAL


def test_profiler_pipeline_hook(capsys):
    """tools/profiler --pipeline: per-stage timings of a real streaming
    init, runnable without a full profile (tier-1 smoke for the hook;
    the CLI-level twin lives in test_tools_cli.py)."""
    import json

    from spacemesh_tpu.tools import profiler

    doc = profiler.pipeline_benchmark(2, 512, 256, probe=False)
    json.dumps(doc)  # must be JSON-serializable
    assert doc["labels_per_sec"] > 0
    assert set(doc["stages"]) >= {"dispatch_s", "fetch_s",
                                  "write_stall_s", "write_s"}
    assert doc["stages"]["batches"] == 2
    assert doc["bottleneck"] in ("dispatch_s", "fetch_s", "write_stall_s")
