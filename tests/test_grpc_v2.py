"""v2alpha1 gRPC services: pagination contracts + live streams against a
running node (VERDICT r3 item 3; reference api/grpcserver/v2alpha1/*)."""

import asyncio

import grpc
import pytest

from spacemesh_tpu.api.gen import v2alpha1_pb2 as v2
from spacemesh_tpu.core import types
from spacemesh_tpu.node import events as events_mod
from spacemesh_tpu.node.app import App
from spacemesh_tpu.node.config import load
from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import layers as layerstore
from spacemesh_tpu.storage import misc as miscstore
from spacemesh_tpu.storage.cache import AtxInfo


def _atx(i, epoch=0, units=2):
    node = b"V%07d" % i + bytes(24)
    return types.ActivationTx(
        publish_epoch=epoch, prev_atx=bytes(32), pos_atx=bytes(32),
        commitment_atx=None, initial_post=None,
        nipost=types.NIPost(
            membership=types.MerkleProof(leaf_index=0, nodes=[]),
            post=types.Post(nonce=0, indices=[1], pow_nonce=0),
            post_metadata=types.PostMetadataWire(challenge=bytes(32),
                                                 labels_per_unit=64)),
        num_units=units, vrf_nonce=7, vrf_public_key=bytes(32),
        coinbase=b"\x0c" * 24, node_id=node,
        signature=bytes(64))


@pytest.fixture
def app(tmp_path):
    cfg = load("standalone", overrides={
        "data_dir": str(tmp_path / "node"),
        "smeshing": {"start": False},
    })
    a = App(cfg)
    # seed: 7 ATXs in epoch 0, rewards over layers 1-3, applied layers,
    # one malfeasant identity, one transaction
    for i in range(7):
        atx = _atx(i)
        atxstore.add(a.state, atx, tick_height=3, received=i)
        a.cache.add(1, atx.id, AtxInfo(
            node_id=atx.node_id, weight=6, base_height=0, height=3,
            num_units=2, vrf_nonce=0, vrf_public_key=atx.node_id))
    for layer in (1, 2, 3):
        miscstore.add_reward(a.state, b"\x0c" * 24, layer, 50, 40)
        layerstore.set_applied(a.state, layer, b"\x0b" * 32, b"\x0d" * 32)
        layerstore.set_processed(a.state, layer)
    miscstore.add_reward(a.state, b"\x0e" * 24, 2, 7, 5)
    bad = b"V%07d" % 0 + bytes(24)
    miscstore.set_malicious(a.state, bad, types.MalfeasanceProof(
        domain=3, msg1=b"a", sig1=bytes(64), msg2=b"b", sig2=bytes(64),
        node_id=bad), received=9)
    yield a
    a.close()


def _unary(ch, path, req_cls, resp_cls):
    return ch.unary_unary(path, request_serializer=req_cls.SerializeToString,
                          response_deserializer=resp_cls.FromString)


def _stream(ch, path, req_cls, resp_cls):
    return ch.unary_stream(path,
                           request_serializer=req_cls.SerializeToString,
                           response_deserializer=resp_cls.FromString)


def test_v2alpha1_list_services(app):
    async def go():
        port = await app.start_grpc_api()
        try:
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                atx_list = _unary(
                    ch, "/spacemesh.v2alpha1.ActivationService/List",
                    v2.ActivationRequest, v2.ActivationList)
                # pagination contract
                with pytest.raises(grpc.aio.AioRpcError) as e:
                    await atx_list(v2.ActivationRequest(limit=0))
                assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                with pytest.raises(grpc.aio.AioRpcError) as e:
                    await atx_list(v2.ActivationRequest(limit=101))
                assert e.value.code() == grpc.StatusCode.INVALID_ARGUMENT
                # paginated walk: 3 + 3 + 1
                got = []
                for off in (0, 3, 6):
                    page = await atx_list(v2.ActivationRequest(
                        limit=3, offset=off))
                    got.extend(page.activations)
                assert len(got) == 7
                assert len({a.id for a in got}) == 7
                assert got[0].weight == 6 and got[0].num_units == 2
                # filter by smesher
                one = await atx_list(v2.ActivationRequest(
                    limit=10, smesher_id=got[2].smesher_id))
                assert [a.id for a in one.activations] == [got[2].id]

                count = _unary(
                    ch,
                    "/spacemesh.v2alpha1.ActivationService/ActivationsCount",
                    v2.ActivationsCountRequest, v2.ActivationsCountResponse)
                assert (await count(
                    v2.ActivationsCountRequest(epoch=0))).count == 7

                rewards = _unary(ch, "/spacemesh.v2alpha1.RewardService/List",
                                 v2.RewardRequest, v2.RewardList)
                rl = await rewards(v2.RewardRequest(limit=100,
                                                    coinbase=b"\x0c" * 24))
                assert [r.layer for r in rl.rewards] == [1, 2, 3]
                assert rl.rewards[0].total == 50
                rl2 = await rewards(v2.RewardRequest(limit=100,
                                                     start_layer=2))
                assert len(rl2.rewards) == 3  # layers 2,2(other cb),3

                layers = _unary(ch, "/spacemesh.v2alpha1.LayerService/List",
                                v2.LayerRequest, v2.LayerList)
                ll = await layers(v2.LayerRequest(limit=100, start_layer=1))
                assert [x.number for x in ll.layers] == [1, 2, 3]
                assert ll.layers[0].applied_block == b"\x0b" * 32

                mal = _unary(
                    ch, "/spacemesh.v2alpha1.MalfeasanceService/List",
                    v2.MalfeasanceRequest, v2.MalfeasanceList)
                ml = await mal(v2.MalfeasanceRequest(limit=10))
                assert len(ml.proofs) == 1
                assert ml.proofs[0].domain == "hare_equivocation"

                info = _unary(ch, "/spacemesh.v2alpha1.NetworkService/Info",
                              v2.NetworkInfoRequest, v2.NetworkInfoResponse)
                ni = await info(v2.NetworkInfoRequest())
                assert ni.layers_per_epoch == app.cfg.layers_per_epoch
                assert ni.genesis_id == app.cfg.genesis.genesis_id
                assert ni.hrp == "sm"

                status = _unary(ch, "/spacemesh.v2alpha1.NodeService/Status",
                                v2.NodeStatusRequest, v2.NodeStatusResponse)
                st = await status(v2.NodeStatusRequest())
                assert st.status == v2.NodeStatusResponse.SYNC_STATUS_SYNCED
                assert st.processed_layer == 3

                accounts = _unary(
                    ch, "/spacemesh.v2alpha1.AccountService/List",
                    v2.AccountRequest, v2.AccountList)
                with pytest.raises(grpc.aio.AioRpcError):
                    await accounts(v2.AccountRequest(limit=0))
                al = await accounts(v2.AccountRequest(
                    limit=10, addresses=[b"\x01" * 24]))
                assert al.accounts[0].current.balance == 0

                txs = _unary(
                    ch, "/spacemesh.v2alpha1.TransactionService/List",
                    v2.TransactionRequest, v2.TransactionList)
                tl = await txs(v2.TransactionRequest(limit=10))
                assert len(tl.transactions) == 0  # none seeded
        finally:
            await app.stop_grpc_api()

    asyncio.run(go())


def test_v2alpha1_streams_follow_live_events(app):
    async def go():
        port = await app.start_grpc_api()
        try:
            async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as ch:
                atx_stream = _stream(
                    ch, "/spacemesh.v2alpha1.ActivationStreamService/Stream",
                    v2.ActivationStreamRequest, v2.Activation)
                call = atx_stream(v2.ActivationStreamRequest(watch=True))
                it = call.__aiter__()
                stored = [await asyncio.wait_for(it.__anext__(), 5)
                          for _ in range(7)]
                assert len({a.id for a in stored}) == 7
                # live: store an 8th ATX, emit the event the stream follows
                atx = _atx(7)
                atxstore.add(app.state, atx, tick_height=3, received=99)
                app.cache.add(1, atx.id, AtxInfo(
                    node_id=atx.node_id, weight=6, base_height=0, height=3,
                    num_units=2, vrf_nonce=0, vrf_public_key=atx.node_id))
                app.events.emit(events_mod.AtxEvent(
                    atx_id=atx.id, node_id=atx.node_id, epoch=1))
                live = await asyncio.wait_for(it.__anext__(), 5)
                assert live.id == atx.id
                call.cancel()

                layer_stream = _stream(
                    ch, "/spacemesh.v2alpha1.LayerStreamService/Stream",
                    v2.LayerStreamRequest, v2.Layer)
                call = layer_stream(v2.LayerStreamRequest(start_layer=1,
                                                          watch=True))
                it = call.__aiter__()
                for want in (1, 2, 3):
                    got = await asyncio.wait_for(it.__anext__(), 5)
                    assert got.number == want
                layerstore.set_applied(app.state, 4, b"\x0f" * 32,
                                       b"\x0d" * 32)
                app.events.emit(events_mod.LayerUpdate(layer=4,
                                                       status="applied"))
                got = await asyncio.wait_for(it.__anext__(), 5)
                assert got.number == 4 and got.applied_block == b"\x0f" * 32
                call.cancel()

                reward_stream = _stream(
                    ch, "/spacemesh.v2alpha1.RewardStreamService/Stream",
                    v2.RewardStreamRequest, v2.Reward)
                call = reward_stream(v2.RewardStreamRequest(
                    coinbase=b"\x0c" * 24, watch=True))
                it = call.__aiter__()
                for want in (1, 2, 3):
                    got = await asyncio.wait_for(it.__anext__(), 5)
                    assert got.layer == want
                miscstore.add_reward(app.state, b"\x0c" * 24, 4, 50, 40)
                app.events.emit(events_mod.LayerUpdate(layer=4,
                                                       status="applied"))
                got = await asyncio.wait_for(it.__anext__(), 5)
                assert got.layer == 4
                call.cancel()

                mal_stream = _stream(
                    ch, "/spacemesh.v2alpha1.MalfeasanceStreamService/Stream",
                    v2.MalfeasanceStreamRequest, v2.MalfeasanceProof)
                call = mal_stream(v2.MalfeasanceStreamRequest(watch=True))
                it = call.__aiter__()
                first = await asyncio.wait_for(it.__anext__(), 5)
                assert first.domain == "hare_equivocation"
                evil = b"V%07d" % 5 + bytes(24)
                miscstore.set_malicious(app.state, evil,
                                        types.MalfeasanceProof(
                                            domain=1, msg1=b"x",
                                            sig1=bytes(64), msg2=b"y",
                                            sig2=bytes(64), node_id=evil),
                                        received=10)
                app.events.emit(events_mod.Malfeasance(node_id=evil))
                got = await asyncio.wait_for(it.__anext__(), 5)
                assert got.smesher_id == evil
                assert got.domain == "multiple_atxs"
                call.cancel()

                from spacemesh_tpu.storage import transactions as txstore
                tx1 = types.Transaction(raw=b"tx-one")
                txstore.add_tx(app.state, tx1, principal=b"\x0a" * 24,
                               nonce=1)
                tx_stream = _stream(
                    ch, "/spacemesh.v2alpha1.TransactionStreamService/Stream",
                    v2.TransactionStreamRequest, v2.TransactionV2)
                call = tx_stream(v2.TransactionStreamRequest(watch=True))
                it = call.__aiter__()
                got = await asyncio.wait_for(it.__anext__(), 5)
                assert got.id == tx1.id and got.raw == b"tx-one"
                tx2 = types.Transaction(raw=b"tx-two")
                txstore.add_tx(app.state, tx2, principal=b"\x0a" * 24,
                               nonce=2)
                app.events.emit(events_mod.TxEvent(tx_id=tx2.id, valid=True))
                got = await asyncio.wait_for(it.__anext__(), 5)
                assert got.id == tx2.id and got.nonce == 2
                call.cancel()
                # streams release their event-bus subscriptions on cancel
                await asyncio.sleep(0.2)
                assert not any(app.events._subs.values())
        finally:
            await app.stop_grpc_api()

    asyncio.run(go())
