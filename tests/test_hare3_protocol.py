"""Graded hare protocol core: adversarial timing + grading scenarios.

Deterministic, clock-free: each case drives the pure machine round by
round and injects messages at exact arrival rounds, the way the
reference's hare3/protocol_test.go drives its protocol struct.  Scenario
provenance is cited per test.
"""

import hashlib

from spacemesh_tpu.consensus.hare3 import (
    COMMIT,
    GRADE1,
    GRADE2,
    GRADE3,
    GRADE4,
    GRADE5,
    HARDLOCK,
    NOTIFY,
    PREROUND,
    PROPOSE,
    SOFTLOCK,
    WAIT1,
    WAIT2,
    Input,
    IterRound,
    Protocol,
    values_ref,
)


def pid(i: int) -> bytes:
    return hashlib.sha256(b"prop%d" % i).digest()


def nid(i: int) -> bytes:
    return hashlib.sha256(b"node%d" % i).digest()


def vrf(i: int) -> bytes:
    return hashlib.sha256(b"vrf%d" % i).digest()


def msg(sender, ir, *, values=None, reference=None, count=1, v=None,
        mhash=None):
    payload = (b"".join(sorted(values)) if values is not None
               else reference or b"")
    return Input(
        sender=sender, ir=ir, eligibility_count=count,
        vrf=v if v is not None else hashlib.sha256(sender).digest(),
        msg_hash=mhash or hashlib.sha256(
            sender + bytes([ir.iter, ir.round]) + payload).digest(),
        values=values, reference=reference)


class Driver:
    """Advance a Protocol while injecting messages at chosen rounds."""

    def __init__(self, threshold=3):
        self.p = Protocol(threshold)
        self.outputs = []

    def now(self) -> IterRound:
        return self.p.current

    def tick(self):
        out = self.p.next()
        self.outputs.append(out)
        return out

    def tick_to(self, it, rnd):
        """Advance until current == (it, rnd); returns last output."""
        out = None
        guard = 0
        while self.p.current != IterRound(it, rnd):
            out = self.tick()
            guard += 1
            assert guard < 64, "round never reached"
        return out

    def deliver(self, m: Input):
        return self.p.on_input(m)


def run_happy_iteration(d: Driver, senders=4, props=3):
    """All messages on time, threshold=3 of 4 single-seat senders."""
    values = [pid(i) for i in range(props)]
    d.p.on_initial(values)
    # preround: everyone sends, delivered during preround/softlock
    out = d.tick()  # emits our preround message
    assert out.message is not None and out.message.ir.round == PREROUND
    for i in range(senders):
        d.deliver(msg(nid(i), IterRound(0, PREROUND), values=values))
    d.tick_to(0, PROPOSE)
    out = d.tick()  # propose emission (leader-eligible driver would send)
    assert sorted(out.message.values) == sorted(values)
    # leader's propose arrives on time (within 1 round of propose)
    d.deliver(msg(nid(0), IterRound(0, PROPOSE), values=values))
    d.tick_to(0, COMMIT)
    out = d.tick()
    ref = values_ref(values)
    assert out.message is not None and out.message.reference == ref
    for i in range(senders):
        d.deliver(msg(nid(i), IterRound(0, COMMIT), reference=ref))
    out = d.tick()  # notify round
    assert out.message is not None and out.message.reference == ref
    for i in range(senders):
        d.deliver(msg(nid(i), IterRound(0, NOTIFY), reference=ref))
    return values, ref


def test_happy_path_result_next_hardlock():
    """Full clean iteration -> result at the next hardlock
    (reference protocol_test.go sanity run)."""
    d = Driver(threshold=3)
    values, ref = run_happy_iteration(d)
    out = d.tick()  # hardlock of iteration 1
    assert out.result is not None
    assert sorted(out.result) == sorted(values)
    assert d.p.result == ref
    # protocol participates one more iteration, then terminates
    d.tick_to(2, HARDLOCK)
    out = d.tick()  # executes hardlock of iteration 2
    assert out.terminated


def test_weak_coin_is_smallest_preround_vrf_lsb():
    """Coin = LSB of the smallest preround VRF, emitted after softlock
    (reference protocol.go:263-267, coin from preround messages)."""
    d = Driver(threshold=2)
    d.p.on_initial([pid(0)])
    d.tick()
    lo = bytes(31) + b"\x01"   # smallest, LSB 1
    hi = b"\xff" * 32
    d.deliver(msg(nid(0), IterRound(0, PREROUND), values=[pid(0)], v=hi))
    d.deliver(msg(nid(1), IterRound(0, PREROUND), values=[pid(0)], v=lo))
    out = d.tick()  # softlock -> coin comes out
    assert out.coin is True


def test_late_preround_gets_lower_grade():
    """A preround message arriving 3 rounds late reaches grade3 only: it
    counts for the commit-round g3 subset check but NOT for the propose
    union at grade4 (reference execution: propose uses grade4,
    condition (f) uses grade3)."""
    d = Driver(threshold=1)
    d.p.on_initial([])
    d.tick()
    # on-time preround for p0 arrives during softlock (delay 1)
    d.deliver(msg(nid(0), IterRound(0, PREROUND), values=[pid(0)]))
    d.tick()              # executes softlock -> current is propose
    # late preround for p1 arrives in PROPOSE round: delay 2 -> grade4 still
    d.deliver(msg(nid(1), IterRound(0, PREROUND), values=[pid(1)]))
    out = d.tick()        # propose emission reads grade4 tallies
    assert pid(0) in out.message.values and pid(1) in out.message.values
    # a third preround arriving in wait1: delay 3 -> grade3, misses propose
    d.deliver(msg(nid(2), IterRound(0, PREROUND), values=[pid(2)]))
    g4 = d.p.gossip.threshold_gossip(IterRound(0, PREROUND), GRADE4)
    g3 = d.p.gossip.threshold_gossip(IterRound(0, PREROUND), GRADE3)
    assert pid(2) not in g4
    assert pid(2) in g3


def test_late_leader_demoted_to_grade1_not_committed():
    """Gradecast 3(a): a propose arriving 2 rounds late gets grade1;
    commit condition (e) requires grade2, so nobody commits to it
    (reference protocol.go:391-407 + condition (e) at :205-233)."""
    d = Driver(threshold=3)
    values = [pid(0)]
    d.p.on_initial(values)
    d.tick()
    for i in range(4):
        d.deliver(msg(nid(i), IterRound(0, PREROUND), values=values))
    d.tick_to(0, WAIT1)
    # leader's propose surfaces in wait1: delay(propose)=1 -> still grade2
    d.deliver(msg(nid(0), IterRound(0, PROPOSE), values=values))
    d.tick()  # -> wait2
    # a second would-be leader surfaces in wait2: delay 2 -> grade1
    d.deliver(msg(nid(1), IterRound(0, PROPOSE), values=values,
                  v=bytes(32)))  # best VRF — would win were it graded 2
    gsets = d.p.gossip.gradecast(IterRound(0, PROPOSE))
    grades = {g.smallest: g.grade for g in gsets}
    assert grades[bytes(32)] == GRADE1
    d.tick()  # -> commit round current
    out = d.tick()
    # commit happened (on-time leader's set), proving grade1 was skipped
    assert out.message is not None
    assert out.message.reference == values_ref(values)


def test_too_late_leader_excluded_entirely():
    """A propose arriving >2 rounds after the propose round gets no grade
    at all (reference gradecast: both branches bounded by delay <= 2)."""
    d = Driver(threshold=3)
    values = [pid(0)]
    d.p.on_initial(values)
    d.tick()
    for i in range(4):
        d.deliver(msg(nid(i), IterRound(0, PREROUND), values=values))
    d.tick_to(0, COMMIT)
    d.deliver(msg(nid(0), IterRound(0, PROPOSE), values=values))  # delay 3
    assert d.p.gossip.gradecast(IterRound(0, PROPOSE)) == []
    out = d.tick()
    assert out.message is None  # nothing valid to commit to


def test_equivocating_leader_grade_boundaries():
    """Gradecast 2(b)/3(b): a conflicting propose surfacing at delay 3
    demotes the leader to grade1; at delay 4 the leader keeps grade2
    (reference protocol.go:391-407)."""
    for conflict_round, expected_grade in ((WAIT2, None), (COMMIT, GRADE1),
                                           (NOTIFY, GRADE2)):
        d = Driver(threshold=3)
        d.p.on_initial([pid(0)])
        d.tick()
        for i in range(4):
            d.deliver(msg(nid(i), IterRound(0, PREROUND), values=[pid(0)]))
        d.tick_to(0, PROPOSE)
        d.deliver(msg(nid(0), IterRound(0, PROPOSE), values=[pid(0)],
                      mhash=b"a" * 32))
        d.tick_to(0, conflict_round)
        _, eq = d.deliver(msg(nid(0), IterRound(0, PROPOSE),
                              values=[pid(1)], mhash=b"b" * 32))
        assert eq is not None, "conflict must surface an equivocation proof"
        gsets = d.p.gossip.gradecast(IterRound(0, PROPOSE))
        if expected_grade is None:
            # conflict at delay 2: leader fails both (a)-conditions
            assert gsets == []
        else:
            assert len(gsets) == 1
            assert gsets[0].grade == expected_grade
            assert gsets[0].values == [pid(0)]


def test_threshgossip_needs_one_honest_vote():
    """Protocol 3: total >= threshold AND >= 1 non-equivocating vote.
    An equivocator's weight counts toward the total but cannot carry a
    value alone (reference thresholdGossip valid>0)."""
    d = Driver(threshold=2)
    d.p.on_initial([])
    d.tick()
    # equivocator with weight 2 backs p0 twice (conflicting messages)
    d.deliver(msg(nid(0), IterRound(0, PREROUND), values=[pid(0)],
                  count=2, mhash=b"x" * 32))
    d.deliver(msg(nid(0), IterRound(0, PREROUND), values=[pid(0)],
                  count=2, mhash=b"y" * 32))
    assert d.p.gossip.threshold_gossip(IterRound(0, PREROUND), GRADE5) == []
    # one honest single-seat vote joins: total 4 (2+2... the kept copy) —
    # now the value passes because valid > 0
    d.deliver(msg(nid(1), IterRound(0, PREROUND), values=[pid(0)], count=1))
    assert d.p.gossip.threshold_gossip(
        IterRound(0, PREROUND), GRADE5) == [pid(0)]


def test_equivocation_detected_and_relayed_once():
    """Graded-gossip case 3: conflicting message -> relay + proof; exact
    duplicate -> no relay (reference protocol.go:349-376)."""
    d = Driver(threshold=2)
    d.p.on_initial([])
    d.tick()
    m1 = msg(nid(0), IterRound(0, PREROUND), values=[pid(0)], mhash=b"m" * 32)
    relay, eq = d.deliver(m1)
    assert relay and eq is None
    relay, eq = d.deliver(m1)               # duplicate
    assert not relay and eq is None
    m2 = msg(nid(0), IterRound(0, PREROUND), values=[pid(1)], mhash=b"n" * 32)
    relay, eq = d.deliver(m2)               # conflict
    assert relay and eq is not None
    assert eq.sender == nid(0)


def test_hardlock_from_prev_commit_threshold():
    """A grade4 commit threshold from iteration i-1 hard-locks iteration i
    (reference execution hardlock: thresholdProposals(commit, grade4))."""
    d = Driver(threshold=3)
    values = [pid(0)]
    ref = values_ref(values)
    d.p.on_initial(values)
    d.tick()
    for i in range(4):
        d.deliver(msg(nid(i), IterRound(0, PREROUND), values=values))
    d.tick_to(0, PROPOSE)
    d.deliver(msg(nid(0), IterRound(0, PROPOSE), values=values))
    d.tick_to(0, COMMIT)
    for i in range(4):
        d.deliver(msg(nid(i), IterRound(0, COMMIT), reference=ref))
    # NO notify threshold: notify messages lost
    d.tick_to(1, SOFTLOCK)   # past hardlock of iter 1
    assert d.p.hard_locked
    assert d.p.locked == ref
    # iteration 1 commit proposes/commits the locked reference
    d.tick_to(1, COMMIT)
    out = d.tick()
    assert out.message is not None and out.message.reference == ref


def test_commit_respects_softlock_condition_h():
    """If iteration i-1 reached a grade3 commit threshold for ref A, the
    soft lock forbids committing to a different proposal B in iteration i
    (reference execution condition (h))."""
    d = Driver(threshold=2)
    a, b = [pid(0)], [pid(1)]
    ref_a = values_ref(a)
    d.p.on_initial(a)
    d.tick()
    for i in range(3):
        d.deliver(msg(nid(i), IterRound(0, PREROUND),
                      values=[pid(0), pid(1)]))
    d.tick_to(0, PROPOSE)
    d.deliver(msg(nid(0), IterRound(0, PROPOSE), values=a))  # leader: A
    d.tick_to(0, COMMIT)
    # commits for A arrive with grade4 (within 2 of commit round)
    for i in range(2):
        d.deliver(msg(nid(i), IterRound(0, COMMIT), reference=ref_a))
    d.tick_to(1, PROPOSE)
    assert d.p.locked == ref_a  # soft- or hard-locked on A
    # iteration 1: leader proposes B on time
    d.deliver(msg(nid(2), IterRound(1, PROPOSE), values=b))
    d.tick_to(1, COMMIT)
    out = d.tick()
    # condition (h): locked ref != B -> no commit to B. Either we commit
    # to A (hardlock path) or emit nothing.
    if out.message is not None:
        assert out.message.reference == ref_a
