"""Three separate OS processes form a real TCP network (+ chaos kill).

The round-2 "done" criterion for the transport: the multinode scenario —
smesher A, observers B and C — over real sockets between real processes,
not in-proc loopback. B is SIGKILLed mid-run (chaos, reference
systest/chaos/fail.go); A and C must still converge on ATXs, blocks, and
state roots, read from their state databases after clean exit.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest

from spacemesh_tpu.storage import atxs as atxstore
from spacemesh_tpu.storage import blocks as blockstore
from spacemesh_tpu.storage import db as dbmod
from spacemesh_tpu.storage import layers as layerstore

LPE = 3
LAYER_SEC = 1.0
UNTIL = 8
PREPARE_BUDGET = 50  # seconds for the smesher's POST init + jit warmup

# tier-2: three real OS-process nodes ride wall-clock layer timing —
# minutes per run and flaky on loaded machines; tier-1 skips it
pytestmark = pytest.mark.slow

REPO = Path(__file__).resolve().parent.parent


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _write_config(tmp, name, genesis_time, smesh) -> Path:
    cfg = {
        "data_dir": str(tmp / name),
        "layer_duration": LAYER_SEC,
        "layers_per_epoch": LPE,
        "slots_per_layer": 2,
        "genesis": {"time": genesis_time},
        "post": {"labels_per_unit": 256, "scrypt_n": 2, "k1": 64, "k2": 8,
                 "k3": 4, "min_num_units": 1,
                 "pow_difficulty": "20" + "ff" * 31},
        "smeshing": {"start": smesh, "num_units": 1, "init_batch": 128},
        "hare": {"committee_size": 20, "round_duration": 0.1,
                 "preround_delay": 0.35, "iteration_limit": 2},
        "beacon": {"proposal_duration": 0.1},
        "tortoise": {"hdist": 4, "window_size": 50},
    }
    path = tmp / f"{name}.json"
    path.write_text(json.dumps(cfg))
    return path


def _spawn(cfg_path, listen_port, bootnodes, log_path):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [sys.executable, "-u", "-m", "spacemesh_tpu.node",
           "--preset", "standalone", "--config", str(cfg_path),
           "--listen", f"127.0.0.1:{listen_port}",
           "--until-layer", str(UNTIL)]
    for bn in bootnodes:
        cmd += ["--bootnode", bn]
    log = open(log_path, "w")
    return subprocess.Popen(cmd, stdout=log, stderr=subprocess.STDOUT,
                            env=env, cwd=str(REPO)), log


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("procnet")
    genesis = float(int(time.time()) + PREPARE_BUDGET)
    pa, pb, pc = _free_port(), _free_port(), _free_port()
    boot = [f"127.0.0.1:{pa}"]

    procs, logs = {}, {}
    for name, port, bootnodes, smesh in (
            ("a", pa, [], True),
            ("b", pb, boot, False),
            ("c", pc, boot, False)):
        cfg = _write_config(tmp, name, genesis, smesh)
        procs[name], logs[name] = _spawn(cfg, port, bootnodes,
                                         tmp / f"{name}.log")

    # chaos: SIGKILL B in the middle of epoch 1
    kill_at = genesis + LAYER_SEC * (LPE + 1.5)
    time.sleep(max(kill_at - time.time(), 0))
    procs["b"].send_signal(signal.SIGKILL)

    deadline = genesis + LAYER_SEC * UNTIL + 90
    rcs = {}
    try:
        for name in ("a", "c"):
            rcs[name] = procs[name].wait(timeout=max(
                deadline - time.time(), 5))
    finally:
        for name, proc in procs.items():
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
        for log in logs.values():
            log.close()

    tail = {n: (tmp / f"{n}.log").read_text()[-2000:] for n in ("a", "c")}
    assert rcs.get("a") == 0, f"node A failed:\n{tail['a']}"
    assert rcs.get("c") == 0, f"node C failed:\n{tail['c']}"
    return tmp


def test_processes_exit_clean_and_converge(cluster):
    tmp = cluster
    sa = dbmod.open_state(tmp / "a" / "state.db")
    sc = dbmod.open_state(tmp / "c" / "state.db")
    try:
        # A's ATXs propagated over real sockets
        atx_rows = atxstore.all_rows(sa)
        assert len(atx_rows) >= 2, "A should publish ATXs for epochs 0+1"
        for row in atx_rows:
            assert atxstore.get(sc, row["id"]) is not None, (
                f"C missing ATX {row['id'].hex()[:12]}")

        # block convergence on every layer that has blocks, excluding the
        # last two: the syncer intentionally defers recent layers whose
        # certificates may still be propagating, and both nodes exit at
        # until_layer — those tip layers can legitimately lag
        layers_with_blocks = [
            lyr for lyr in range(LPE, UNTIL - 1)
            if blockstore.ids_in_layer(sa, lyr)]
        assert layers_with_blocks, "A generated no blocks"
        for lyr in layers_with_blocks:
            ids_a = blockstore.ids_in_layer(sa, lyr)
            ids_c = blockstore.ids_in_layer(sc, lyr)
            assert ids_a == ids_c, f"layer {lyr}: A and C disagree"

        # state root convergence at the last layer both applied
        lyr = min(layerstore.last_applied(sa), layerstore.last_applied(sc))
        assert lyr >= LPE
        assert layerstore.state_hash(sa, lyr) == \
            layerstore.state_hash(sc, lyr), f"state divergence at {lyr}"
    finally:
        sa.close()
        sc.close()


def test_killed_node_left_artifacts_but_not_needed(cluster):
    """B died mid-epoch-1; its DB exists (was syncing) and the survivors
    finished anyway — the chaos didn't stall the network."""
    tmp = cluster
    assert (tmp / "b" / "state.db").exists()
